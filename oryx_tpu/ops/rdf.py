"""Random decision forest — TPU-native histogram trainer + array forest.

Re-design of the reference's RDF compute path (app/oryx-app-mllib
.../batch/mllib/rdf/RDFUpdate.java:143-165 invoking MLlib
RandomForest.trainClassifier/trainRegressor, and the serving-side tree
walk in app/oryx-app-common .../rdf/tree/DecisionTree.java:50-64). The
reference leans on MLlib's pointer-based trees; here the whole forest is
a handful of dense arrays so both training and inference are single
compiled XLA programs:

- **Implicit-heap layout.** Every tree is padded to 2^(max_depth+1)-1
  slots; node i's children are 2i+1 (left, the reference's '-' branch)
  and 2i+2 (right, '+'). Routing an example is a fixed-trip-count gather
  loop — no pointers, no recursion, vectorized over trees x examples.

- **Binned features.** Numeric predictors are quantile-binned to at most
  `max-split-candidates` bins (the same role the parameter plays in
  MLlib); categorical predictors use their value encodings as bins. A
  split is stored as a goes-left bitmask over bins, which represents
  numeric threshold splits (prefix masks) and categorical subset splits
  (arbitrary masks) uniformly — the reference's NumericDecision /
  CategoricalDecision pair (.../rdf/decision/) collapses into one array.

- **Level-by-level histogram growth.** Each depth level is one scatter-add
  building [nodes, predictors, bins, stats] label histograms, a cumulative
  sum over (score-ordered) bins, and an argmax over candidate splits by
  impurity gain (entropy/gini in nats, variance for regression) — the
  classic histogram-forest formulation that maps onto the VPU instead of
  MLlib's per-partition binned aggregation. Categorical subset search
  orders categories by per-bin target score (Breiman's sorted-category
  trick; exact for binary/regression, principled heuristic for
  multiclass, like MLlib's ordered-category mode).

- **Bootstrap as weights.** Each tree carries a multinomial count-weight
  vector over the shared binned matrix, so trees differ only in a [T,N]
  weight array and "auto" per-node feature subsets (sqrt(P) for
  classification, P/3 for regression, MLlib's defaults) drawn inside the
  compiled program. Tree growth is vmapped over the tree axis; with a
  mesh the tree axis shards over "data" (trees are embarrassingly
  parallel, the idiomatic forest sharding).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.common.rng import RandomManager

MAX_BINS_CAP = 256


# ---------------------------------------------------------------------------
# node-ID strings (wire parity with the reference's TreePath IDs:
# root "r", '-' = left child, '+' = right child; RDFUpdate.java:423,480-481)
# ---------------------------------------------------------------------------

def heap_to_node_id(index: int) -> str:
    """Heap slot -> reference-style path ID ("r", "r-", "r+-", ...)."""
    path = []
    i = index
    while i > 0:
        parent = (i - 1) // 2
        path.append("-" if i == 2 * parent + 1 else "+")
        i = parent
    return "r" + "".join(reversed(path))


def node_id_to_heap(node_id: str) -> int:
    """Reference-style path ID -> heap slot."""
    if not node_id or node_id[0] != "r":
        raise ValueError(f"bad node ID: {node_id!r}")
    i = 0
    for c in node_id[1:]:
        if c == "-":
            i = 2 * i + 1
        elif c == "+":
            i = 2 * i + 2
        else:
            raise ValueError(f"bad node ID: {node_id!r}")
    return i


# ---------------------------------------------------------------------------
# binning
# ---------------------------------------------------------------------------

@dataclass
class BinnedData:
    """Quantile-binned dataset + the edges needed to bin future inputs.

    edges[p] has n_bins[p]-1 sorted cut points for numeric predictor p
    (bin b covers x <= edges[b], last bin is the overflow; NaN bins to the
    last bin); categorical predictors bin by value encoding directly.
    """

    binned: np.ndarray  # [N, P] int32
    edges: list[np.ndarray | None]  # per predictor; None for categorical
    n_bins: np.ndarray  # [P] int32
    is_categorical: np.ndarray  # [P] bool


def bin_column(
    values: np.ndarray, edges: np.ndarray | None, n_bins: int
) -> np.ndarray:
    """Bin one predictor column; NaN and unseen categories go to the last
    bin (searchsorted sends NaN past every edge)."""
    if edges is None:  # categorical: values are already encodings
        v = np.nan_to_num(values, nan=n_bins - 1).astype(np.int64)
        return np.clip(v, 0, n_bins - 1).astype(np.int32)
    return np.searchsorted(edges, values, side="left").astype(np.int32)


def bin_dataset(
    x: np.ndarray,
    is_categorical: np.ndarray,
    category_counts: np.ndarray,
    max_split_candidates: int,
) -> BinnedData:
    """Quantile-bin numeric columns of x [N,P] (categoricals pass through
    as encodings). max_split_candidates caps bins per predictor, like its
    namesake in RDFUpdate.java:121-151."""
    n, p = x.shape
    max_bins = min(int(max_split_candidates), MAX_BINS_CAP)
    binned = np.empty((n, p), dtype=np.int32)
    edges: list[np.ndarray | None] = []
    n_bins = np.empty(p, dtype=np.int32)
    for j in range(p):
        col = x[:, j]
        if is_categorical[j]:
            nb = max(int(category_counts[j]), 1)
            edges.append(None)
            n_bins[j] = nb
            binned[:, j] = bin_column(col, None, nb)
        else:
            finite = col[np.isfinite(col)]
            if len(finite) == 0:
                e = np.empty(0, dtype=np.float32)
            else:
                qs = np.quantile(finite, np.linspace(0, 1, max_bins + 1)[1:-1])
                e = np.unique(qs.astype(np.float32))
            edges.append(e)
            n_bins[j] = len(e) + 1
            binned[:, j] = bin_column(col, e, len(e) + 1)
    return BinnedData(binned, edges, n_bins, np.asarray(is_categorical, dtype=bool))


# ---------------------------------------------------------------------------
# forest container
# ---------------------------------------------------------------------------

@dataclass
class Forest:
    """Dense array forest; T trees x M=2^(max_depth+1)-1 heap slots.

    feature[t,m] is the split predictor (-1 = terminal/absent);
    split_left[t,m,b] says bin b of that predictor goes left. For
    classification class_counts[t,m,:] holds per-class training counts at
    every node (terminal prediction = normalized counts, the reference's
    CategoricalPrediction); for regression leaf_stats[t,m] = (count, sum)
    (NumericPrediction's running mean).
    """

    feature: np.ndarray  # [T, M] int32
    split_left: np.ndarray  # [T, M, B] bool
    class_counts: np.ndarray | None  # [T, M, C] f64, classification
    leaf_stats: np.ndarray | None  # [T, M, 2] f64 (count, sum), regression
    feature_importances: np.ndarray  # [P] f64, max-normalized
    max_depth: int

    @property
    def num_trees(self) -> int:
        return self.feature.shape[0]

    @property
    def is_classification(self) -> bool:
        return self.class_counts is not None

    def weights(self) -> np.ndarray:
        """Uniform tree weights (the reference forest votes uniformly for
        MLlib models; DecisionForest.java weights)."""
        return np.full(self.num_trees, 1.0 / self.num_trees)


# ---------------------------------------------------------------------------
# growth (jit core)
# ---------------------------------------------------------------------------

def _impurity(counts, kind: str):
    """Impurity from class-count vectors [..., C]; nats for entropy."""
    n = counts.sum(axis=-1)
    p = counts / jnp.maximum(n, 1.0)[..., None]
    if kind == "gini":
        return 1.0 - jnp.sum(p * p, axis=-1)
    # entropy
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0), axis=-1)


@partial(
    jax.jit,
    static_argnames=("max_depth", "n_bins_max", "n_classes", "impurity", "mtry"),
)
def _grow_one_tree(
    binned,  # [N, P] int32
    y,  # [N] int32 (classification) or f32 (regression)
    weight,  # [N] f32 bootstrap multinomial counts
    n_bins,  # [P] int32
    is_cat,  # [P] bool
    key,  # PRNG key for per-node feature subsets
    *,
    max_depth: int,
    n_bins_max: int,
    n_classes: int,  # 0 => regression
    impurity: str,
    mtry: int,
):
    n, p = binned.shape
    b = n_bins_max
    m = 2 ** (max_depth + 1) - 1
    classification = n_classes > 0
    c = n_classes if classification else 3  # regression stats: (w, wy, wy2)

    feature = jnp.full((m,), -1, dtype=jnp.int32)
    split_left = jnp.zeros((m, b), dtype=bool)
    node_counts = jnp.zeros((m, c), dtype=jnp.float32)
    importance = jnp.zeros((p,), dtype=jnp.float32)

    if classification:
        stat_cols = jax.nn.one_hot(y, c, dtype=jnp.float32)  # [N, C]
    else:
        stat_cols = jnp.stack([jnp.ones_like(y), y, y * y], axis=1)

    cols = jnp.arange(p, dtype=jnp.int32)[None, :]  # [1, P]
    valid_bin = jnp.arange(b)[None, :] < n_bins[:, None]  # [P, B]
    # split position j is valid only below the last in-use bin
    valid_pos = jnp.arange(b)[None, :] < (n_bins[:, None] - 1)  # [P, B]

    node = jnp.zeros(n, dtype=jnp.int32)
    keys = jax.random.split(key, max_depth)

    # One compiled level body via lax.scan over depth, every level padded
    # to the LAST level's node count: the per-level tensors are tiny next
    # to the N-point scatters (which don't depend on the node axis), and a
    # single level body compiles ~max_depth times faster than the old
    # per-depth unroll whose every level had a different shape (measured
    # 18 s of the 25 s cold forest build was XLA compile).
    n_pad = 2 ** (max_depth - 1) if max_depth > 0 else 1
    level_starts = jnp.asarray([2**d - 1 for d in range(max_depth)], dtype=jnp.int32)
    n_levels = jnp.asarray([2**d for d in range(max_depth)], dtype=jnp.int32)

    def level_body(carry, xs):
        node, feature, split_left, node_counts, importance = carry
        level_start, n_level, lkey = xs
        local = node - level_start
        active = (local >= 0) & (local < n_level)
        w = jnp.where(active, weight, 0.0)
        loc = jnp.clip(local, 0, n_pad - 1)
        row_valid = jnp.arange(n_pad, dtype=jnp.int32) < n_level

        # label histogram: [n_pad, P, B, C]; one scatter-add per stat
        # column (C is tiny) keeps the scatter rank simple
        hist = jnp.zeros((n_pad, p, b, c), dtype=jnp.float32)
        for s in range(c):
            hist = hist.at[loc[:, None], cols, binned, s].add(
                w[:, None] * stat_cols[:, s][:, None]
            )

        total = hist.sum(axis=2)  # [n_pad, P, C]
        node_n = total[:, 0].sum(axis=-1)  # [n_pad]

        # order bins: numeric keep natural order; categorical sort by the
        # per-bin target score (sorted-category subset trick)
        if classification:
            bin_n = hist.sum(axis=3)  # [n_pad, P, B]
            maj = jnp.argmax(total.sum(axis=1), axis=-1)  # [n_pad]
            maj_n = jnp.take_along_axis(hist, maj[:, None, None, None], axis=3)
            score = maj_n[..., 0] / jnp.maximum(bin_n, 1.0)
        else:
            bin_n = hist[..., 0]
            score = hist[..., 1] / jnp.maximum(bin_n, 1.0)  # mean y
        # empty/padded bins sort last
        score = jnp.where((bin_n > 0) & valid_bin[None], score, jnp.inf)
        cat_order = jnp.argsort(score, axis=2)  # [n_pad, P, B]
        nat_order = jnp.broadcast_to(jnp.arange(b), cat_order.shape)
        order = jnp.where(is_cat[None, :, None], cat_order, nat_order)

        ordered = jnp.take_along_axis(hist, order[..., None], axis=2)
        left = jnp.cumsum(ordered, axis=2)  # [n_pad, P, B, C]
        right = left[:, :, -1:, :] - left

        if classification:
            nl = left.sum(axis=3)
            nr = right.sum(axis=3)
            h_parent = _impurity(total, impurity)  # [n_pad, P]
            h_l = _impurity(left, impurity)
            h_r = _impurity(right, impurity)
        else:
            nl, nr = left[..., 0], right[..., 0]

            def var(s):
                mean = s[..., 1] / jnp.maximum(s[..., 0], 1.0)
                return jnp.maximum(
                    s[..., 2] / jnp.maximum(s[..., 0], 1.0) - mean * mean, 0.0
                )

            h_parent = var(total)
            h_l, h_r = var(left), var(right)

        nn = jnp.maximum(node_n, 1.0)[:, None, None]
        gain = h_parent[..., None] - (nl / nn) * h_l - (nr / nn) * h_r
        ok = (nl > 0) & (nr > 0) & valid_pos[None]
        # per-node "auto" feature subset: keep mtry features with the
        # smallest uniform draws (MLlib featureSubsetStrategy="auto")
        if mtry < p:
            u = jax.random.uniform(lkey, (n_pad, p))
            ranks = jnp.argsort(jnp.argsort(u, axis=1), axis=1)
            ok = ok & (ranks < mtry)[:, :, None]
        gain = jnp.where(ok, gain, -jnp.inf)

        flat = gain.reshape(n_pad, p * b)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        best_p = (best // b).astype(jnp.int32)
        best_j = (best % b).astype(jnp.int32)
        should = (
            (best_gain > 0.0)
            & (node_n >= 2.0)
            & jnp.isfinite(best_gain)
            & row_valid
        )

        # goes-left mask over original bins: rank of bin in the chosen
        # predictor's order <= best_j
        inv_order = jnp.argsort(order, axis=2)  # [n_pad, P, B]
        inv_best = jnp.take_along_axis(
            inv_order, best_p[:, None, None], axis=1
        )[:, 0, :]  # [n_pad, B]
        left_mask = inv_best <= best_j[:, None]  # [n_pad, B]

        # padded rows (row >= n_level) would land on the NEXT level's
        # slots — write back the gathered current values there instead
        slots = level_start + jnp.arange(n_pad, dtype=jnp.int32)
        feature = feature.at[slots].set(
            jnp.where(row_valid, jnp.where(should, best_p, -1), feature[slots])
        )
        split_left = split_left.at[slots].set(
            jnp.where(
                row_valid[:, None], left_mask & should[:, None], split_left[slots]
            )
        )
        # every predictor's histogram sums to the same node totals, so the
        # mean over the predictor axis is the per-node stat exactly
        node_counts = node_counts.at[slots].set(
            jnp.where(row_valid[:, None], total.mean(axis=1), node_counts[slots])
        )
        importance = importance.at[best_p].add(jnp.where(should, node_n, 0.0))

        # route: split nodes push actives down, others freeze (terminal)
        ex_bin = jnp.take_along_axis(binned, best_p[loc][:, None], axis=1)[:, 0]
        goes_left = left_mask[loc, ex_bin]
        child = 2 * node + 1 + (1 - goes_left.astype(jnp.int32))
        node = jnp.where(active & should[loc], child, node)
        return (node, feature, split_left, node_counts, importance), None

    (node, feature, split_left, node_counts, importance), _ = jax.lax.scan(
        level_body,
        (node, feature, split_left, node_counts, importance),
        (level_starts, n_levels, keys),
    )

    # leaf-level stats for every node examples ended on
    final_counts = jnp.zeros((m, c), dtype=jnp.float32)
    for s in range(c):
        final_counts = final_counts.at[node, s].add(weight * stat_cols[:, s])
    # internal nodes also get their totals (prediction fallback parity with
    # the reference, where every PMML node records counts)
    node_counts = jnp.where(
        final_counts.sum(axis=1, keepdims=True) > 0, final_counts, node_counts
    )
    return feature, split_left, node_counts, importance


def resolve_mtry(
    strategy: str | int | None,
    p: int,
    classification: bool,
    num_trees: int | None = None,
) -> int:
    """featureSubsetStrategy -> per-node feature count, MLlib semantics
    (the reference's RDFUpdate.java:143-165 passes the same strategy
    names to RandomForest): "auto" = "all" for a single tree, else
    sqrt(P) for classification / ceil(P/3) for regression; "all",
    "sqrt", "log2", "onethird" = ceil(P/3), or an explicit integer.
    num_trees=None (unknown) treats the forest as multi-tree."""
    onethird = max(1, -(-p // 3))  # ceil(p/3), matching MLlib
    if strategy is None or strategy == "auto":
        # MLlib: a single tree has no inter-tree decorrelation to buy
        # with feature subsampling, so "auto" degrades to "all"
        if num_trees == 1:
            return p
        return max(1, int(math.sqrt(p))) if classification else onethird
    if isinstance(strategy, int) or str(strategy).lstrip("-").isdigit():
        v = int(strategy)
        if not 1 <= v <= p:
            raise ValueError(f"feature-subset {v} outside [1, {p}]")
        return v
    named = {
        "all": p,
        "sqrt": max(1, int(math.sqrt(p))),
        "log2": max(1, int(math.log2(p))),
        "onethird": onethird,
    }
    if strategy not in named:
        raise ValueError(f"unknown feature-subset strategy {strategy!r}")
    return named[strategy]


def grow_forest(
    data: BinnedData,
    y: np.ndarray,
    *,
    num_trees: int,
    max_depth: int,
    impurity: str,
    n_classes: int,
    feature_subset: str | int | None = "auto",
    mesh=None,
) -> Forest:
    """Train the forest: multinomial bootstrap weights per tree, vmapped
    single-program growth; tree axis shards over the mesh "data" axis."""
    n, p = data.binned.shape
    rng = RandomManager.get_random()
    weights = rng.multinomial(n, np.full(n, 1.0 / n), size=num_trees).astype(
        np.float32
    )  # [T, N]
    keys = jax.random.split(
        jax.random.PRNGKey(int(rng.integers(2**31 - 1))), num_trees
    )
    classification = n_classes > 0
    mtry = resolve_mtry(feature_subset, p, classification, num_trees=num_trees)
    if classification:
        yy = np.nan_to_num(y, nan=0.0).astype(np.int32)
    else:
        yy = np.asarray(y, dtype=np.float32)

    grow = jax.vmap(
        partial(
            _grow_one_tree,
            max_depth=max_depth,
            n_bins_max=int(data.n_bins.max()),
            n_classes=n_classes,
            impurity=impurity,
            mtry=mtry,
        ),
        in_axes=(None, None, 0, None, None, 0),
    )

    binned_j = jnp.asarray(data.binned)
    y_j = jnp.asarray(yy)
    nb = jnp.asarray(data.n_bins)
    ic = jnp.asarray(data.is_categorical)
    w_j = jnp.asarray(weights)
    keys = jnp.asarray(keys)
    if mesh is not None:
        from oryx_tpu.parallel.mesh import DATA_AXIS, data_sharding, replicated

        # trees are embarrassingly parallel: shard the tree axis when it
        # divides the mesh (padding would add phantom trees to the vote)
        if num_trees % mesh.shape[DATA_AXIS] == 0:
            w_j = jax.device_put(w_j, data_sharding(mesh, w_j.ndim))
            keys = jax.device_put(keys, data_sharding(mesh, keys.ndim))
            binned_j = jax.device_put(binned_j, replicated(mesh))
            y_j = jax.device_put(y_j, replicated(mesh))

    feature, split_left, counts, importance = jax.device_get(
        grow(binned_j, y_j, w_j, nb, ic, jnp.asarray(keys))
    )

    imp = importance.sum(axis=0).astype(np.float64)
    imp = imp / imp.max() if imp.max() > 0 else imp
    if classification:
        return Forest(
            feature=np.asarray(feature),
            split_left=np.asarray(split_left),
            class_counts=np.asarray(counts, dtype=np.float64),
            leaf_stats=None,
            feature_importances=imp,
            max_depth=max_depth,
        )
    stats = np.asarray(counts, dtype=np.float64)  # [T, M, 3] (w, wy, wy2)
    return Forest(
        feature=np.asarray(feature),
        split_left=np.asarray(split_left),
        class_counts=None,
        leaf_stats=np.stack([stats[..., 0], stats[..., 1]], axis=-1),
        feature_importances=imp,
        max_depth=max_depth,
    )


# ---------------------------------------------------------------------------
# inference
# ---------------------------------------------------------------------------

def route_binned(
    feature: np.ndarray, split_left: np.ndarray, binned: np.ndarray, max_depth: int
) -> np.ndarray:
    """Host routing: binned examples [Ne,P] -> terminal heap slot per tree
    [T,Ne] (numpy; the serving/speed tiers route small batches per request
    against mutable leaf stats, reference DecisionTree.findTerminal)."""
    t = feature.shape[0]
    ne = binned.shape[0]
    node = np.zeros((t, ne), dtype=np.int64)
    tree_ix = np.arange(t)[:, None]
    for _ in range(max_depth):
        f = feature[tree_ix, node]  # [T, Ne]
        internal = f >= 0
        fb = binned[np.arange(ne)[None, :], np.clip(f, 0, None)]  # [T, Ne]
        goes_left = split_left[tree_ix, node, fb]
        child = 2 * node + 1 + (1 - goes_left.astype(np.int64))
        node = np.where(internal, child, node)
    return node


@partial(jax.jit, static_argnames=("max_depth",))
def route_binned_jit(feature, split_left, binned, *, max_depth: int):
    """Device routing, same semantics as route_binned; one fused gather
    loop over depth, batched over trees x examples."""
    t = feature.shape[0]
    ne = binned.shape[0]
    node = jnp.zeros((t, ne), dtype=jnp.int32)
    tree_ix = jnp.arange(t)[:, None]
    ex_ix = jnp.arange(ne)[None, :]

    def body(_, node):
        f = feature[tree_ix, node]
        internal = f >= 0
        fb = binned[ex_ix, jnp.clip(f, 0, None)]
        goes_left = split_left[tree_ix, node, fb]
        child = 2 * node + 1 + (1 - goes_left.astype(jnp.int32))
        return jnp.where(internal, child, node)

    return jax.lax.fori_loop(0, max_depth, body, node)


def predict_class_probs(forest: Forest, binned: np.ndarray) -> np.ndarray:
    """[Ne, C] probabilities: uniform-weight vote of per-leaf normalized
    class counts (reference WeightedPrediction.voteOnFeature over
    CategoricalPredictions)."""
    leaves = route_binned(forest.feature, forest.split_left, binned, forest.max_depth)
    counts = forest.class_counts[np.arange(forest.num_trees)[:, None], leaves]
    probs = counts / np.maximum(counts.sum(axis=-1, keepdims=True), 1e-12)
    return probs.mean(axis=0)


def predict_regression(forest: Forest, binned: np.ndarray) -> np.ndarray:
    """[Ne] regression prediction: uniform-weight mean of leaf means."""
    leaves = route_binned(forest.feature, forest.split_left, binned, forest.max_depth)
    stats = forest.leaf_stats[np.arange(forest.num_trees)[:, None], leaves]
    means = stats[..., 1] / np.maximum(stats[..., 0], 1e-12)
    return means.mean(axis=0)
