"""Shared ALS app plumbing: input parsing, config view, update payloads.

Input lines are CSV or JSON arrays `user,item[,strength[,timestamp]]`
(reference MLFunctions.PARSE_FN semantics): empty strength = 1, "delete"
semantics = empty-string strength on DELETE paths encoded as NaN.
Update-topic payloads are JSON arrays: ["X", id, [vector], [knownItems]] and
["Y", id, [vector]] (reference ALSUpdate.publishAdditionalModelData /
ALSSpeedModelManager.buildUpdates payload shapes).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from oryx_tpu.bus.api import KeyMessage
from oryx_tpu.common.config import Config
from oryx_tpu.common.text import parse_input_line


@dataclass
class ALSConfig:
    implicit: bool
    log_strength: bool
    epsilon: float
    decay_factor: float
    zero_threshold: float
    no_known_items: bool
    features: object
    lam: object
    alpha: object
    iterations: int
    sample_rate: float
    approx_recall: float
    compute_dtype: str
    checkpoint_interval: int
    candidate_partitions: int
    lsh_max_bits_differing: int | None

    @staticmethod
    def from_config(config: Config) -> "ALSConfig":
        g = lambda k, d=None: config.get(f"oryx.als.{k}", d)
        return ALSConfig(
            implicit=bool(g("implicit", True)),
            log_strength=bool(g("logStrength", False)),
            epsilon=float(g("epsilon", 1.0)),
            decay_factor=float(g("decay.factor", 1.0)),
            zero_threshold=float(g("decay.zero-threshold", 0.0)),
            no_known_items=bool(g("no-known-items", False)),
            features=g("hyperparams.features", 10),
            lam=g("hyperparams.lambda", 0.001),
            alpha=g("hyperparams.alpha", 1.0),
            iterations=int(g("hyperparams.iterations", 10)),
            sample_rate=float(g("sample-rate", 1.0)),
            approx_recall=_valid_recall(float(g("approx-recall", 1.0))),
            compute_dtype=_valid_compute_dtype(str(g("compute-dtype", "float32"))),
            checkpoint_interval=int(g("checkpoint-interval", 0)),
            # LSH knobs (the CPU-parity approximate path): 0 = auto
            # partition count from cores; null = auto Hamming radius
            candidate_partitions=_valid_nonneg(
                "candidate-partitions", int(g("candidate-partitions", 0))
            ),
            lsh_max_bits_differing=_valid_lsh_bits(g("lsh-max-bits-differing", None)),
        )


def _valid_nonneg(key: str, value: int) -> int:
    """Fail at config load, not on the first /recommend request."""
    if value < 0:
        raise ValueError(f"oryx.als.{key} must be >= 0, got {value}")
    return value


def _valid_lsh_bits(raw) -> int | None:
    if raw is None:
        return None
    return _valid_nonneg("lsh-max-bits-differing", int(raw))


def _valid_recall(value: float) -> float:
    """Fail at config load, not on the first /recommend request."""
    if not (0.0 < value <= 1.0):
        raise ValueError(
            f"oryx.als.approx-recall must be in (0, 1], got {value!r}"
        )
    return value


def _valid_compute_dtype(value: str) -> str:
    """Fail at config load, not mid-generation inside the jitted trainer."""
    if value not in ("float32", "bfloat16"):
        raise ValueError(
            f"oryx.als.compute-dtype must be 'float32' or 'bfloat16', got {value!r}"
        )
    return value


def _native_loader():
    try:
        from oryx_tpu.bus.native import NativeAppender

        return NativeAppender.load()
    except (FileNotFoundError, OSError, AttributeError):
        return None


def valid_event_line(line: str) -> bool:
    """True when parse_events would accept this line — the cheap
    deserialize check behind the layers' validate_record hook. Kept in
    lockstep with the per-line rules in parse_events below so quarantine
    decisions can never disagree with what the build would actually
    ingest (pinned by tests/test_chaos.py)."""
    try:
        tok = parse_input_line(line)
        if len(tok) < 2 or not tok[0] or not tok[1]:
            return False
        if len(tok) > 2 and tok[2] != "":
            float(tok[2])
        if len(tok) > 3 and tok[3] != "":
            int(float(tok[3]))
    except (ValueError, IndexError, TypeError, OverflowError):
        # OverflowError: int(float("1e400")) — an exception escaping this
        # hook would bypass the layers' quarantine sweep entirely
        return False
    return True


def valid_event_lines(lines) -> list[bool]:
    """Batch valid_event_line: ONE native parse call covers the common
    all-canonical-CSV window, and only the lines the C parser flags pay
    the per-line Python check — native ok=False means "not verbatim
    C-parseable" (JSON-array lines land there too), NOT invalid, so
    those are re-checked rather than rejected. A line-count mismatch
    (blank messages, embedded newlines) falls the whole batch back to
    Python, mirroring parse_events' own fallback discipline. Keeps the
    quarantine sweep off the per-record Python path the native loader
    exists to avoid. Deliberate cost: the sweep is one extra native
    parse per window on top of the build's own parse_events call —
    threading one parse's results through both would couple the
    validate hook to parse internals for a C call that is cheap by
    construction."""
    lines = list(lines)
    native = _native_loader()
    if native is not None and lines:
        try:
            ok = native.parse_interactions(
                ("\n".join(lines)).encode("utf-8")
            )[4]
        except Exception:
            ok = None
        if ok is not None and len(ok) == len(lines):
            return [bool(o) or valid_event_line(l) for o, l in zip(ok, lines)]
    return [valid_event_line(l) for l in lines]


def parse_events(data) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """KeyMessages -> (users, items, values, timestamps) arrays. Bad lines
    are skipped. Empty/absent strength = 1.0; empty-string with a 'delete'
    convention arrives as NaN from the /pref DELETE path.

    Hot path: when the native data loader (native/oryxbus) is built and
    every line is plain CSV with canonical-integer ids, the whole batch
    parses in C with no Python object per record (users/items come back as
    int64 arrays; aggregate_interactions factorizes those without string
    round-trips). Any line the loader can't take verbatim falls the whole
    batch back to the Python parser, so semantics never fork."""
    native = _native_loader()
    if native is not None:
        lines = [
            km.message if isinstance(km, KeyMessage) else str(km) for km in data
        ]
        if lines:
            u, i, v, t, ok = native.parse_interactions(
                ("\n".join(lines)).encode("utf-8")
            )
            # row count must match the message count exactly (catches blank
            # messages and embedded newlines) and every row must be clean
            if len(ok) == len(lines) and bool(ok.all()):
                return u, i, v, t

    users, items, vals, tss = [], [], [], []
    for km in data:
        line = km.message if isinstance(km, KeyMessage) else str(km)
        try:
            tok = parse_input_line(line)
            if len(tok) < 2 or not tok[0] or not tok[1]:
                continue
            u, i = tok[0], tok[1]
            v = 1.0
            if len(tok) > 2 and tok[2] != "":
                v = float(tok[2])
            elif len(tok) > 2 and tok[2] == "":
                v = float("nan")  # delete marker
            ts = int(float(tok[3])) if len(tok) > 3 and tok[3] != "" else 0
        except (ValueError, IndexError):
            continue
        users.append(u)
        items.append(i)
        vals.append(v)
        tss.append(ts)
    return (
        np.asarray(users, dtype=object),
        np.asarray(items, dtype=object),
        np.asarray(vals, dtype=np.float64),
        np.asarray(tss, dtype=np.int64),
    )


# UP-message codec: the generic builders/parser moved to
# oryx_tpu/apps/updates.py (the app-SPI split — the seq app shares them
# with kind "E"); these ALS-named wrappers keep every existing call site
# and the byte-parity pin (tests/test_als_state.py) working unchanged.
from oryx_tpu.apps.updates import (  # noqa: F401 - re-exported API
    batch_update_messages,
    parse_update_message,
    vector_update_message,
)


def x_update_message(user_id: str, vector, known_items) -> tuple[str, str]:
    return vector_update_message("X", user_id, vector, known=known_items)


def y_update_message(item_id: str, vector) -> tuple[str, str]:
    return vector_update_message("Y", item_id, vector)
