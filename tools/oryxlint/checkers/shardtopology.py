"""Shard-topology vocabulary checker (rule ``shard-topology``).

PR 11 wired the shard count through five surfaces — the serving view
(``oryx.serving.api.sync.shard-count``), the fleet overlay
(``oryx.fleet.shards``), the train mesh (``oryx.batch.train.shards``),
the ``/healthz`` ``shards`` field the front's prober reads into
``ReplicaInfo.shards`` (mis-sharded replicas get ejected), and the
bench ``shard_devices`` honesty field. Each of those was hand-checked
in review; a new shard-bearing surface that wires only some of them
ships a replica the front cannot vet, or a bench claim nobody can
audit.

The rule pins the vocabulary both ways:

- every **known** shard surface must still be present at its expected
  site (config key read somewhere + declared; healthz emits ``shards``
  next to its shard-count read; ``ReplicaInfo`` declares ``shards``;
  the front parses the probe body's ``shards``; the supervisor overlay
  carries the sync key; bench.py carries ``shard_devices``) — a
  half-unwired removal is as broken as a half-wired addition;
- every shard-shaped config key read anywhere (``*.shards`` /
  ``*.shard-count``) must be one of the known keys — a NEW shard
  surface fails loudly here until it is added to ``KNOWN_SHARD_KEYS``
  *and* wired through the same vocabulary.

Site checks apply only when their file exists (fixture trees exercise
single surfaces); the key-vocabulary check applies to any tree.
"""

from __future__ import annotations

import re

from tools.oryxlint.core import Checker, Finding, Project

# every config key that carries a shard count, with the wiring it rides
KNOWN_SHARD_KEYS = (
    "oryx.serving.api.sync.shard-count",
    "oryx.fleet.shards",
    "oryx.batch.train.shards",
)

# a Config accessor read of a shard-shaped key
SHARD_KEY_READ = re.compile(
    r"\.(?:get|get_string|get_int|get_float|get_bool|get_list|get_config|has)"
    r"\(\s*[bru]?[\"'](oryx\.[A-Za-z0-9_.\-]*(?:\.shards|shard-count))[\"']"
)

HEALTHZ_FILE = "oryx_tpu/serving/resources/common.py"
FRONT_FILE = "oryx_tpu/fleet/front.py"
SUPERVISOR_FILE = "oryx_tpu/fleet/supervisor.py"


class ShardTopologyChecker(Checker):
    name = "shardtopology"
    rules = {
        "shard-topology": (
            "a shard-count surface is half-wired: a new shard config key "
            "outside the known vocabulary, or a known surface (healthz "
            "shards field, ReplicaInfo.shards, front probe parse, "
            "supervisor overlay, bench shard_devices) has gone missing"
        ),
    }
    severities = {"shard-topology": "error"}
    fix_hints = {
        "shard-topology": (
            "wire the surface end to end — config key, /healthz shards, "
            "ReplicaInfo.shards + front probe, supervisor overlay, bench "
            "shard_devices — and register the key in "
            "checkers/shardtopology.py KNOWN_SHARD_KEYS"
        ),
    }

    def check(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        mods = {m.relpath: m for m in project.modules}
        texts = {m.relpath: m.text for m in project.modules}

        # 1) no shard-shaped key outside the known vocabulary
        reads: dict[str, tuple[str, int]] = {}
        for rel, text in sorted(texts.items()):
            if not rel.startswith("oryx_tpu"):
                continue
            for m in SHARD_KEY_READ.finditer(text):
                line = text.count("\n", 0, m.start()) + 1
                reads.setdefault(m.group(1), (rel, line))
                if m.group(1) not in KNOWN_SHARD_KEYS:
                    findings.append(Finding(
                        rel, line, "shard-topology",
                        f"{m.group(1)}: shard-bearing config key outside "
                        "the known vocabulary — a new shard surface must "
                        "wire /healthz shards, ReplicaInfo.shards, the "
                        "supervisor overlay, and bench shard_devices, then "
                        "register in KNOWN_SHARD_KEYS",
                    ))

        # 2) known keys must still be read somewhere (only when the tree
        # has any shard vocabulary at all — a fixture tree with zero
        # shard reads is not a regressed fleet)
        if reads:
            for key in KNOWN_SHARD_KEYS:
                if key not in reads:
                    findings.append(Finding(
                        "oryx_tpu", 1, "shard-topology",
                        f"{key}: known shard surface no longer read by any "
                        "Config accessor — the fleet/serving/train shard "
                        "wiring lost a leg",
                    ))

        # 3) per-site wiring, checked when the site file exists
        hz = mods.get(HEALTHZ_FILE)
        if hz is not None and "shard-count" in hz.text:
            if '"shards"' not in hz.text:
                findings.append(Finding(
                    HEALTHZ_FILE, 1, "shard-topology",
                    "reads the sync shard-count but never emits the "
                    '/healthz "shards" field — the front cannot vet this '
                    "replica's topology (mis-sharded replicas route)",
                ))
        front = mods.get(FRONT_FILE)
        if front is not None:
            if not _class_has_attr(front, "ReplicaInfo", "shards"):
                findings.append(Finding(
                    FRONT_FILE, 1, "shard-topology",
                    "ReplicaInfo no longer carries `shards` — the probe "
                    "cannot record replica topology, so shard-topology "
                    "ejection is dead",
                ))
            if '"shards"' not in front.text:
                findings.append(Finding(
                    FRONT_FILE, 1, "shard-topology",
                    'the front never parses the probe body\'s "shards" '
                    "field — ReplicaInfo.shards can never be populated",
                ))
        sup = mods.get(SUPERVISOR_FILE)
        if sup is not None and "oryx.fleet.shards" in sup.text:
            if "oryx.serving.api.sync.shard-count" not in sup.text:
                findings.append(Finding(
                    SUPERVISOR_FILE, 1, "shard-topology",
                    "reads oryx.fleet.shards but never overlays "
                    "oryx.serving.api.sync.shard-count onto replicas — "
                    "the fleet knob would be a silent no-op on every child",
                ))
        bench = project.root / "bench.py"
        if bench.exists() and reads:
            if '"shard_devices"' not in bench.read_text(encoding="utf-8"):
                findings.append(Finding(
                    "bench.py", 1, "shard-topology",
                    "shard vocabulary in the tree but bench.py lost the "
                    "shard_devices honesty field — shard-scaling claims "
                    "become unauditable",
                ))
        return findings


def _class_has_attr(mod, cls_name: str, attr: str) -> bool:
    import ast

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            for sub in ast.walk(node):
                if isinstance(sub, ast.AnnAssign):
                    t = sub.target
                    if isinstance(t, ast.Name) and t.id == attr:
                        return True
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and t.attr == attr
                    ):
                        return True
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Name) and t.id == attr:
                            return True
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and t.attr == attr
                        ):
                            return True
    return False
