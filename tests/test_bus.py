"""Tests for the message bus: in-process + file-log brokers, producer,
blocking consumer iterator, offsets, replay semantics.

Mirrors the reference's kafka-util test approach (real broker in-process,
produce/consume round-trips) from SURVEY.md §4.
"""

import threading
import time

import pytest

from oryx_tpu.bus.api import ConsumeDataIterator, KeyMessage, TopicProducer
from oryx_tpu.bus.broker import get_broker, partition_for, topics
from oryx_tpu.bus.filelog import FileLogBroker, encode_record
from oryx_tpu.bus.inproc import InProcBroker


@pytest.fixture(autouse=True)
def _fresh_registry():
    InProcBroker.reset_all()
    yield
    InProcBroker.reset_all()


@pytest.fixture(params=["mem", "file"])
def broker(request, tmp_path):
    if request.param == "mem":
        return get_broker("mem://test")
    return FileLogBroker(str(tmp_path / "bus"))


def test_topic_admin(broker):
    assert not broker.topic_exists("T")
    broker.create_topic("T", partitions=3)
    assert broker.topic_exists("T")
    assert broker.num_partitions("T") == 3
    with pytest.raises(ValueError):
        broker.create_topic("T")
    broker.delete_topic("T")
    assert not broker.topic_exists("T")


def test_send_read_roundtrip(broker):
    broker.create_topic("T", partitions=2)
    broker.send("T", "k1", "hello")
    broker.send("T", None, "nokey")
    broker.send("T", "k2", 'complex "msg" €')
    total = sum(broker.end_offsets("T"))
    assert total == 3
    seen = []
    for p in range(2):
        seen.extend(broker.read("T", p, 0, 100))
    msgs = {m for _, _, m in seen}
    assert msgs == {"hello", "nokey", 'complex "msg" €'}
    keys = {k for _, k, _ in seen}
    assert None in keys and "k1" in keys


def test_partitioning_stable(broker):
    broker.create_topic("T", partitions=4)
    p1 = partition_for("user-42", 4)
    assert partition_for("user-42", 4) == p1
    broker.send("T", "user-42", "a")
    broker.send("T", "user-42", "b")
    recs = broker.read("T", p1, 0, 10)
    assert [m for _, _, m in recs] == ["a", "b"]


def test_max_message_size(broker):
    broker.create_topic("S", partitions=1, max_message_bytes=10)
    with pytest.raises(ValueError):
        broker.send("S", None, "x" * 100)


def test_offsets_store(broker):
    broker.create_topic("T", partitions=2)
    broker.commit_offsets("g1", "T", {0: 5, 1: 7})
    broker.commit_offsets("g1", "T", {1: 9})
    assert broker.get_offsets("g1", "T") == {0: 5, 1: 9}
    assert broker.get_offsets("g2", "T") == {}


def test_consumer_earliest_replays_all(broker):
    broker.create_topic("U", partitions=1)
    prod = TopicProducer(broker, "U")
    for i in range(5):
        prod.send("UP", f"m{i}")
    it = ConsumeDataIterator(broker, "U", start="earliest")
    got = [next(it) for _ in range(5)]
    assert got == [KeyMessage("UP", f"m{i}") for i in range(5)]
    it.close()


def test_consumer_latest_skips_history(broker):
    broker.create_topic("U", partitions=1)
    broker.send("U", None, "old")
    it = ConsumeDataIterator(broker, "U", start="latest")
    broker.send("U", None, "new")
    assert next(it).message == "new"
    it.close()


def test_consumer_committed_resume(broker):
    broker.create_topic("U", partitions=1)
    for i in range(4):
        broker.send("U", None, f"m{i}")
    it = ConsumeDataIterator(broker, "U", group="g", start="earliest")
    next(it), next(it)
    it.commit()
    it.close()
    it2 = ConsumeDataIterator(broker, "U", group="g", start="committed")
    assert next(it2).message == "m2"
    it2.close()


def test_consumer_blocking_and_wakeup(broker):
    broker.create_topic("U", partitions=1)
    it = ConsumeDataIterator(broker, "U", start="latest")
    got = []

    def consume():
        for km in it:
            got.append(km.message)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.05)
    broker.send("U", None, "wake")
    deadline = time.time() + 5
    while not got and time.time() < deadline:
        time.sleep(0.01)
    assert got == ["wake"]
    it.close()  # wakeup: iteration must end promptly
    t.join(5)
    assert not t.is_alive()


def test_poll_available_microbatch(broker):
    broker.create_topic("I", partitions=2)
    it = ConsumeDataIterator(broker, "I", start="latest")
    assert it.poll_available() == []
    for i in range(6):
        broker.send("I", f"k{i}", f"m{i}")
    batch = it.poll_available()
    assert sorted(m.message for m in batch) == [f"m{i}" for i in range(6)]
    assert it.poll_available() == []


def test_poll_available_bounded_window(broker):
    """up_to bounds the drain per partition (exclusive) — pod members use
    the leader's end-offset snapshot so every member's generation window
    holds the same records (layers/batch.py _pod_window)."""
    broker.create_topic("W", partitions=1)
    it = ConsumeDataIterator(broker, "W", start="earliest")
    for i in range(8):
        broker.send("W", None, f"m{i}")
    ends = it.end_offsets()
    assert ends == {0: 8}
    # window agreed at offset 5: exactly m0..m4, nothing more
    got = it.poll_available(up_to={0: 5})
    assert [m.message for m in got] == [f"m{i}" for i in range(5)]
    assert it.poll_available(up_to={0: 5}) == []
    # a partition missing from the window yields nothing (conservative)
    assert it.poll_available(up_to={}) == []
    # the rest arrives once the window advances
    got2 = it.poll_available(up_to={0: 8})
    assert [m.message for m in got2] == [f"m{i}" for i in range(5, 8)]
    # unbounded drain still works afterwards
    broker.send("W", None, "m8")
    assert [m.message for m in it.poll_available()] == ["m8"]


def test_topic_admin_helpers(tmp_path):
    uri = f"file://{tmp_path}/bus2"
    topics.maybe_create(uri, "A", partitions=2)
    topics.maybe_create(uri, "A", partitions=2)  # idempotent
    assert topics.exists(uri, "A")
    topics.delete(uri, "A")
    assert not topics.exists(uri, "A")


def test_filelog_multiprocess_view(tmp_path):
    """Two broker instances over the same dir see each other's writes —
    the cross-process contract batch/speed/serving rely on."""
    a = FileLogBroker(str(tmp_path / "shared"))
    b = FileLogBroker(str(tmp_path / "shared"))
    a.create_topic("T", partitions=1)
    a.send("T", "k", "from-a")
    recs = b.read("T", 0, 0, 10)
    assert [m for _, _, m in recs] == ["from-a"]
    b.send("T", "k", "from-b")
    assert [m for _, _, m in a.read("T", 0, 0, 10)] == ["from-a", "from-b"]


def test_filelog_torn_trailing_write(tmp_path):
    """A torn (partial) trailing record must not break the index; the full
    record is picked up once completed."""
    br = FileLogBroker(str(tmp_path / "bus"))
    br.create_topic("T", partitions=1)
    br.send("T", None, "complete")
    log = tmp_path / "bus" / "T" / "p0.log"
    full = encode_record("k", "later-completed")
    with open(log, "ab") as f:
        f.write(full[: len(full) - 3])  # torn
    assert [m for _, _, m in br.read("T", 0, 0, 10)] == ["complete"]
    with open(log, "ab") as f:
        f.write(full[len(full) - 3 :])
    fresh = FileLogBroker(str(tmp_path / "bus"))
    assert [m for _, _, m in fresh.read("T", 0, 0, 10)] == ["complete", "later-completed"]


def test_native_appender_if_built(tmp_path):
    try:
        from oryx_tpu.bus.native import NativeAppender

        nat = NativeAppender.load()
    except (FileNotFoundError, OSError):
        pytest.skip("native oryxbus not built")
    log = tmp_path / "n.log"
    nat.append(str(log), "key1", "native message")
    nat.append(str(log), None, "second")
    positions, scanned = nat.scan(str(log), 0)
    assert len(positions) == 2 and scanned == log.stat().st_size
    # records written natively are readable by the Python broker path
    br = FileLogBroker(str(tmp_path / "busdir"))
    br.create_topic("T", partitions=1)
    br.send("T", "nk", "via broker")
    assert [(k, m) for _, k, m in br.read("T", 0, 0, 10)] == [("nk", "via broker")]


def test_send_batch(broker):
    broker.create_topic("B", partitions=2)
    broker.send_batch("B", [(f"k{i}", f"m{i}") for i in range(20)])
    total = sum(broker.end_offsets("B"))
    assert total == 20
    msgs = set()
    for p in range(2):
        msgs |= {m for _, _, m in broker.read("B", p, 0, 100)}
    assert msgs == {f"m{i}" for i in range(20)}


def test_native_autobuild(tmp_path):
    """A fresh checkout (no .so) compiles the native library on first load
    when a toolchain is present — run in a subprocess so the per-process
    build/instance caches start cold, with the .so renamed away."""
    import shutil
    import subprocess
    import sys as _sys
    from pathlib import Path

    if shutil.which("g++") is None:
        pytest.skip("no g++")
    so = Path(__file__).resolve().parent.parent / "native" / "oryxbus" / "liboryxbus.so"
    moved = tmp_path / "stash.so"
    if so.exists():
        shutil.move(str(so), str(moved))
    try:
        code = (
            "import sys; sys.path.insert(0, {root!r}); "
            "from oryx_tpu.bus.native import NativeAppender; "
            "n = NativeAppender.load(); "
            "u, i, v, t, ok = n.parse_interactions(b'3,4,1.5,99'); "
            "assert list(u) == [3] and list(i) == [4] and ok.all(); "
            "print('AUTOBUILD_OK')"
        ).format(root=str(so.parent.parent.parent))
        proc = subprocess.run(
            [_sys.executable, "-c", code], capture_output=True, text=True, timeout=180
        )
        assert proc.returncode == 0, proc.stderr[-800:]
        assert "AUTOBUILD_OK" in proc.stdout
        assert so.exists()
    finally:
        if not so.exists() and moved.exists():
            shutil.move(str(moved), str(so))
