"""Flight recorder: a bounded on-disk ring of lifecycle events plus a
snapshot bundler — the fleet's black box.

The in-memory observability built so far (tracing ring, perfstats
dispatch ring, /metrics) dies with its process: when a replica is
SIGKILLed mid update-storm, or an accel bench stage times out and the
driver kills it, the evidence evaporates at exactly the moment it is
needed (the still-unexplained ``_bench_http_body``/``_bench_train_body``
failures of BENCH_TPU_WINDOW_r05 are a bare ``error:`` string because
nothing survived the kill). This module keeps the last seconds of
STRUCTURED lifecycle evidence on disk, where a supervisor — or the bench
driver, or an operator — can harvest it from the corpse:

- ``FlightRecorder.record(kind=..., **fields)`` appends one JSONL event
  to a bounded segment ring under the flight dir (``oryx.monitoring.
  flight.dir``): ejections/readmissions, shed episodes, host-fallback
  dispatches, wedge transitions, generation adoptions, fault injections,
  health up→degraded flips, bench stage phases. Every ``kind`` is
  registered in ``EVENT_KINDS`` (the oryxlint ``flight-events`` rule
  holds call sites and the docs catalog to it) and every event is
  stamped with pid, wall time, and the fleet replica id — the same id
  the front's ejection log and ``oryx_fleet_*`` labels carry, so a
  harvested corpse's events join the surviving front's trace of the
  incident.
- ``snapshot()`` bundles the recent event ring, finished tracing spans,
  the perfstats dispatch ring, a /metrics text snapshot, and the config
  fingerprint into ONE artifact file — triggered by ``GET
  /debug/flight``, automatically on a healthz up→degraded transition,
  and by bench stages on failure.
- ``harvest()`` packs a DEAD process's on-disk ring (the supervisor
  calls it on a replica corpse before restarting it; the bench driver
  calls it on a SIGKILLed stage) — crash-loop last words.

Recording is cheap (one locked JSONL append on rare lifecycle events;
``episode_s`` rate-limits bursty kinds like sheds) and ON by default:
like perfstats, the cost a switch would save is near zero, and a black
box that must be enabled before the crash records nothing.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time

log = logging.getLogger(__name__)

# Event-kind catalog. The oryxlint `flight-events` consistency rule pins
# every `record(kind="...")` call site to this dict AND every entry here
# to a row in docs/observability.md's flight-recorder event catalog, so
# the event schema cannot drift silently (the config-key / metric-docs
# pattern applied to the black box).
EVENT_KINDS: dict[str, str] = {
    "process-start": "a serving/fleet process configured its recorder",
    "ejection": "the fleet front ejected a replica from routing",
    "readmission": "the fleet front readmitted a replica",
    "shed-episode": "serving shed load (rate-limited episode marker)",
    "fallback": "device->host fallback scoring dispatches",
    "wedge": "a layer's wedge watchdog tripped or cleared",
    "generation": "a published model generation was adopted for serving",
    "fault-injection": "the deterministic fault harness fired",
    "health-degraded": "GET /healthz flipped up->degraded",
    "replica-death": "the fleet supervisor observed a replica corpse",
    "snapshot": "a flight snapshot bundle was written",
    "bench-stage": "a bench stage/phase lifecycle marker",
    "quality-alarm": (
        "live model quality degraded: the quality SLO's fast burn rate "
        "crossed the alarm threshold while windowed live recall sat "
        "below the floor"
    ),
    "drift-alarm": (
        "live input/prediction drift against the served generation's "
        "training profile crossed the alarm threshold"
    ),
    "compile-storm": (
        "XLA recompile rate crossed the configured threshold within the "
        "rolling window — a shape-signature churn (generation swap, "
        "k-bucket spread) is stealing device time"
    ),
    "profile-capture": (
        "a latency fast-burn triggered an automatic bounded profile "
        "window (perfstats summary + phase budget) into the ring"
    ),
    "canary-start": (
        "the fleet controller split a traffic cohort to the canary "
        "replica for a newly published generation"
    ),
    "canary-hold": (
        "a canary rollout is waiting for enough shadow-rescored samples "
        "to judge the new generation (episode-limited heartbeat)"
    ),
    "canary-promote": (
        "the canary generation passed its quality/latency/recall gate "
        "and was approved fleet-wide (hold replicas adopted it)"
    ),
    "canary-rollback": (
        "the canary generation was rolled back to its predecessor — a "
        "pointer swap from the pinned artifact cache — with the burn/"
        "recall evidence that forced it"
    ),
    "autoscale": (
        "the fleet controller changed capacity: up spawned and joined a "
        "replica, down drained one, stopped it, and removed its ring "
        "keys"
    ),
    "crash-loop": (
        "the fleet supervisor gave up restarting crash-looping replicas "
        "(max fast fails reached); the affected replicas surface as "
        "state=gave_up on /fleet/status"
    ),
}

_SEGMENT_PREFIX = "events-"
_DEFAULT_SEGMENT_BYTES = 262144
_DEFAULT_SEGMENTS = 4
_SNAPSHOTS_KEPT = 8


def _strip_scheme(path: str) -> str:
    return path[5:] if path.startswith("file:") else path


class FlightRecorder:
    """Bounded on-disk JSONL event ring + snapshot bundler.

    Segment files ``events-<n>.jsonl`` roll at ``segment_bytes``; only
    the newest ``segments`` are kept, so the ring is bounded in bytes no
    matter how long the process lives. Appends happen under one lock
    (events are rare lifecycle moments, never the request hot path)."""

    def __init__(self):
        self.dir: str | None = None
        self.enabled = True
        self.replica_id: str | None = None
        self.segment_bytes = _DEFAULT_SEGMENT_BYTES
        self.segments = _DEFAULT_SEGMENTS
        self.config_fingerprint: str | None = None
        self._lock = threading.Lock()
        self._seg_index = 0        # guarded-by: _lock
        self._seg_written = 0      # guarded-by: _lock (bytes in current segment)
        self._scanned = False      # guarded-by: _lock (resume index found)
        self._last_episode: dict[str, float] = {}  # guarded-by: _lock

    # -- configuration -----------------------------------------------------

    def configure(self, config) -> None:
        """Adopt the oryx.monitoring.flight.* keys (each layer runtime
        calls this at construction; last writer wins, the one-config-
        per-process convention). Also captures the config fingerprint the
        snapshot bundle carries — a crash artifact must say which config
        the corpse was running."""
        self.enabled = config.get_bool("oryx.monitoring.flight.enabled", True)
        raw_dir = config.get_string(
            "oryx.monitoring.flight.dir", "file:/tmp/oryx_tpu/flight"
        )
        new_dir = _strip_scheme(raw_dir) if raw_dir else None
        if new_dir != self.dir:
            # a different dir is a different ring: episode rate-limit
            # state from the old ring must not suppress the new ring's
            # first events (an episode marker the new ring never saw)
            with self._lock:
                self._last_episode.clear()
        self.dir = new_dir
        self.segment_bytes = max(
            4096,
            config.get_int(
                "oryx.monitoring.flight.segment-bytes", _DEFAULT_SEGMENT_BYTES
            ),
        )
        self.segments = max(
            2, config.get_int("oryx.monitoring.flight.segments", _DEFAULT_SEGMENTS)
        )
        self.replica_id = config.get_string("oryx.fleet.replica.id", None)
        try:
            self.config_fingerprint = hashlib.sha256(
                config.serialize().encode("utf-8")
            ).hexdigest()[:16]
        except Exception:  # noqa: BLE001 - a fingerprint never blocks startup
            self.config_fingerprint = None
        with self._lock:
            self._scanned = False  # re-resolve the resume segment for the new dir

    # -- recording ---------------------------------------------------------

    def record(self, *, kind: str, episode_s: float | None = None, **fields) -> bool:
        """Append one event; returns True when written. ``kind`` must be a
        literal from EVENT_KINDS (machine-checked by oryxlint).
        ``episode_s`` rate-limits bursty kinds: within that many seconds
        of the previous same-kind event the call is a no-op dict probe —
        the idiom for shed storms, where the EPISODE is the story and a
        per-request event would just churn the ring (and do disk I/O
        under the shed decision's lock)."""
        if not self.enabled or not self.dir:
            return False
        now = time.time()
        with self._lock:
            if episode_s is not None:
                last = self._last_episode.get(kind, 0.0)
                if now - last < episode_s:
                    return False
                self._last_episode[kind] = now
            event = {"ts_ms": int(now * 1000), "kind": kind, "pid": os.getpid()}
            if self.replica_id:
                event["replica"] = self.replica_id
            event.update(fields)
            try:
                self._append_locked(json.dumps(event, default=str) + "\n")
            except OSError:
                return False  # a full/missing disk must never break the caller
        return True

    def _append_locked(self, line: str) -> None:  # oryxlint: holds=_lock
        os.makedirs(self.dir, exist_ok=True)
        if not self._scanned:
            self._resume_locked()
        if self._seg_written >= self.segment_bytes:
            self._seg_index += 1
            self._seg_written = 0
            stale = f"{_SEGMENT_PREFIX}{self._seg_index - self.segments}.jsonl"
            try:
                os.unlink(os.path.join(self.dir, stale))
            except OSError:
                pass
        path = os.path.join(
            self.dir, f"{_SEGMENT_PREFIX}{self._seg_index}.jsonl"
        )
        data = line.encode("utf-8")
        with open(path, "ab") as f:
            f.write(data)
        self._seg_written += len(data)

    def _resume_locked(self) -> None:  # oryxlint: holds=_lock
        """Continue the newest existing segment (restarted process, or a
        sibling writer in the same dir) instead of clobbering index 0. A
        torn tail (the previous writer died mid-append) is repaired with
        one newline so the next event starts on its own line — the torn
        fragment becomes a skipped bad line, not a corrupter of the next
        good one."""
        newest, size = 0, 0
        for name in os.listdir(self.dir):
            if name.startswith(_SEGMENT_PREFIX) and name.endswith(".jsonl"):
                try:
                    idx = int(name[len(_SEGMENT_PREFIX):-6])
                except ValueError:
                    continue
                if idx >= newest:
                    newest = idx
                    try:
                        size = os.path.getsize(os.path.join(self.dir, name))
                    except OSError:
                        size = 0
        if size > 0:
            path = os.path.join(self.dir, f"{_SEGMENT_PREFIX}{newest}.jsonl")
            try:
                with open(path, "rb+") as f:
                    f.seek(-1, os.SEEK_END)
                    if f.read(1) != b"\n":
                        f.write(b"\n")
                        size += 1
            except OSError:
                pass
        self._seg_index, self._seg_written = newest, size
        self._scanned = True

    # -- reading -----------------------------------------------------------

    def events(self, limit: int = 0) -> list[dict]:
        d = self.dir
        return read_events(d, limit=limit) if d else []

    # -- snapshot bundling -------------------------------------------------

    def snapshot(self, trigger: str, extra: dict | None = None) -> tuple[dict, str | None]:
        """Bundle the black box into one artifact: recent flight events,
        finished tracing spans (span forest), the perfstats dispatch
        ring, a /metrics text snapshot, and the config fingerprint.
        Returns (bundle, path-on-disk); the path is None when no flight
        dir is configured (the bundle is still returned for HTTP
        callers)."""
        from oryx_tpu.common.metrics import get_registry
        from oryx_tpu.common.perfstats import get_perfstats
        from oryx_tpu.common.tracing import get_tracer, span_forest

        tr = get_tracer()
        bundle: dict = {
            "trigger": trigger,
            "ts_ms": int(time.time() * 1000),
            "pid": os.getpid(),
            "replica": self.replica_id,
            "config_fingerprint": self.config_fingerprint,
            "events": self.events(limit=512),
            "traces": span_forest(tr.snapshot()) if tr.enabled else [],
            "dispatch_ring": [
                {
                    "kind": r.kind,
                    "wall_s": round(r.wall_s, 6),
                    "flops": r.flops,
                    "bytes_moved": r.bytes_moved,
                    "rows": r.rows,
                    "occupancy": round(r.occupancy, 4),
                    "trace_id": r.trace_id or "",
                    "score_mode": r.score_mode or "",
                }
                for r in get_perfstats().records_since(0.0)[-256:]
            ],
            "metrics": get_registry().render_prometheus(),
        }
        if extra:
            bundle.update(extra)
        path = None
        if self.dir and self.enabled:
            try:
                snap_dir = os.path.join(self.dir, "snapshots")
                os.makedirs(snap_dir, exist_ok=True)
                path = os.path.join(
                    snap_dir, f"flight-{trigger}-{bundle['ts_ms']}.json"
                )
                tmp = path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(bundle, f)
                os.replace(tmp, path)
                _prune_snapshots(snap_dir)
            except OSError:
                path = None
        self.record(kind="snapshot", trigger=trigger, path=path or "")
        return bundle, path

    def snapshot_async(self, trigger: str, event: dict | None = None) -> None:
        """Fire-and-forget snapshot on a daemon thread — the healthz
        up→degraded trigger runs on an event loop, which must not pay
        the bundle's file writes and metrics render inline. ``event``
        ({"kind": ..., fields}) is recorded FIRST on the same thread, so
        the triggering lifecycle event also stays off the caller's loop
        (a degrading disk is a common cause of degradation — the record
        that documents it must not block the loop on that same disk)."""

        def _snap() -> None:  # oryxlint: offloop (one-shot snapshot thread)
            try:
                if event is not None:
                    self.record(**event)
                self.snapshot(trigger)
            except Exception:  # noqa: BLE001 - the black box never raises out
                log.exception("flight snapshot (%s) failed", trigger)

        threading.Thread(
            target=_snap, name="oryx-flight-snapshot", daemon=True
        ).start()


def read_events(flight_dir: str, limit: int = 0) -> list[dict]:
    """Parse the segment ring under ``flight_dir`` oldest-first (bad lines
    skipped — a torn tail write must not hide the rest of the ring)."""
    flight_dir = _strip_scheme(flight_dir)
    segs: list[tuple[int, str]] = []
    try:
        for name in os.listdir(flight_dir):
            if name.startswith(_SEGMENT_PREFIX) and name.endswith(".jsonl"):
                try:
                    segs.append((int(name[len(_SEGMENT_PREFIX):-6]), name))
                except ValueError:
                    continue
    except OSError:
        return []
    out: list[dict] = []
    for _, name in sorted(segs):
        try:
            with open(os.path.join(flight_dir, name), encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(ev, dict):
                        out.append(ev)
        except OSError:
            continue
    return out[-limit:] if limit > 0 else out


def harvest(flight_dir: str, **meta) -> str | None:
    """Pack a (possibly dead) process's on-disk event ring into one
    harvest artifact under ``<flight_dir>/harvest/`` — the supervisor's
    crash-loop-last-words path and the bench driver's timeout path. Works
    on a corpse: reads only the segment files the dead process left.
    Returns the artifact path, or None when the dir never existed (the
    process died before recording anything)."""
    flight_dir = _strip_scheme(flight_dir)
    if not os.path.isdir(flight_dir):
        return None
    events = read_events(flight_dir)
    artifact = {
        "harvested_ms": int(time.time() * 1000),
        "harvested_by_pid": os.getpid(),
        "flight_dir": flight_dir,
        "events": events,
        **meta,
    }
    try:
        hdir = os.path.join(flight_dir, "harvest")
        os.makedirs(hdir, exist_ok=True)
        path = os.path.join(hdir, f"harvest-{artifact['harvested_ms']}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(artifact, f)
        os.replace(tmp, path)
        _prune_snapshots(hdir)
        return path
    except OSError:
        log.exception("flight harvest of %s failed", flight_dir)
        return None


def _prune_snapshots(snap_dir: str, kept: int = _SNAPSHOTS_KEPT) -> None:
    """Keep the newest `kept` artifacts — the snapshot/harvest dirs must
    stay bounded like the ring they bundle."""
    try:
        files = sorted(
            n for n in os.listdir(snap_dir) if n.endswith(".json")
        )
    except OSError:
        return
    for name in files[:-kept] if len(files) > kept else []:
        try:
            os.unlink(os.path.join(snap_dir, name))
        except OSError:
            pass


# -- process-global recorder ------------------------------------------------

_default = FlightRecorder()


def get_flightrec() -> FlightRecorder:
    return _default


def configure_flightrec(config) -> FlightRecorder:
    _default.configure(config)
    return _default
