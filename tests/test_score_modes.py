"""Serving score modes (oryx.serving.api.score-mode = exact|quantized|
approx): candidate-set parity at the kernel layer, quantized delta-sync
discipline, per-mode perfstats labeling, and the acceptance path — both
non-exact modes serving end-to-end over HTTP (batcher -> frontend ->
fleet front) with recall@10 against exact holding the quality gate's
floor."""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402


def _recall(got_ids, exact_ids) -> float:
    return len(set(got_ids) & set(exact_ids)) / max(1, len(exact_ids))


# ---------------------------------------------------------------------------
# kernel layer: the three modes' candidate sets
# ---------------------------------------------------------------------------

def test_score_mode_candidate_sets_parity():
    """Exact equality where the math is exact: the exact mode against the
    XLA reference, the quantized Pallas kernel against the quantized XLA
    reference (identical quantized scores), and — on CPU, where
    approx_max_k computes exactly — the approx mode against exact."""
    from oryx_tpu.ops.als import (
        topk_dot_batch,
        topk_dot_batch_approx,
        topk_dot_batch_quant_xla,
        topk_dot_batch_xla,
    )
    from oryx_tpu.ops.pallas_topk import topk_dot_batch_pallas
    from oryx_tpu.ops.transfer import QuantizedMatrix, quantize_rows_int8

    rng = np.random.default_rng(5)
    y = rng.standard_normal((3000, 24)).astype(np.float32)
    xs = rng.standard_normal((12, 24)).astype(np.float32)
    xs_j, y_j = jnp.asarray(xs), jnp.asarray(y)

    v_e, i_e = topk_dot_batch_xla(xs_j, y_j, k=10)
    # exact mode through the dispatcher (CPU -> XLA path)
    v_d, i_d = topk_dot_batch(xs_j, y_j, k=10)
    assert np.array_equal(np.asarray(i_d), np.asarray(i_e))

    # quantized: dispatcher (QuantizedMatrix -> quant XLA) and the Pallas
    # quantized kernel agree index-for-index — same quantized scores
    q, s = quantize_rows_int8(y)
    qm = QuantizedMatrix(jnp.asarray(q), jnp.asarray(s))
    v_q, i_q = topk_dot_batch(xs_j, qm, k=10)
    v_qx, i_qx = topk_dot_batch_quant_xla(
        xs_j, jnp.asarray(q), jnp.asarray(s), k=10
    )
    assert np.array_equal(np.asarray(i_q), np.asarray(i_qx))
    v_qp, i_qp = topk_dot_batch_pallas(
        xs_j, jnp.asarray(q), scales=jnp.asarray(s), k=10,
        block_b=8, block_i=512, interpret=True,
    )
    assert np.array_equal(np.asarray(i_qp), np.asarray(i_qx))
    np.testing.assert_allclose(np.asarray(v_qp), np.asarray(v_qx), atol=1e-4)

    # quantized candidates recover the exact top-k after the serve
    # path's exact rescore contract (here: overlap is already near-total)
    rec = np.mean([
        _recall(list(map(int, a)), list(map(int, b)))
        for a, b in zip(np.asarray(i_q), np.asarray(i_e))
    ])
    assert rec >= 0.9, rec

    # approx on CPU computes exactly
    v_a, i_a = topk_dot_batch_approx(xs_j, y_j, k=10, recall=0.95)
    assert np.array_equal(np.asarray(i_a), np.asarray(i_e))


def test_quantized_scatter_requantizes_dirty_rows_only():
    """PR 3's delta contract under quantization: a scatter re-quantizes
    ONLY the dirty rows — untouched int8 rows and scales are bit-identical
    to the previous view's."""
    from oryx_tpu.ops.transfer import (
        QuantizedMatrix, quantized_device_put, scatter_rows,
    )

    rng = np.random.default_rng(7)
    y = rng.standard_normal((256, 8)).astype(np.float32)
    qm = quantized_device_put(y)
    dirty = np.array([3, 77, 200], dtype=np.int32)
    new_rows = 5.0 * rng.standard_normal((3, 8)).astype(np.float32)
    qm2 = scatter_rows(qm, dirty, new_rows)
    assert isinstance(qm2, QuantizedMatrix)
    q_old, q_new = np.asarray(qm.q), np.asarray(qm2.q)
    s_old, s_new = np.asarray(qm.scale), np.asarray(qm2.scale)
    clean = np.setdiff1d(np.arange(256), dirty)
    assert np.array_equal(q_old[clean], q_new[clean])
    assert np.array_equal(s_old[clean], s_new[clean])
    # dirty rows dequantize back to the new values within the scale step
    deq = q_new[dirty].astype(np.float32) * s_new[dirty][:, None]
    np.testing.assert_allclose(deq, new_rows, atol=np.abs(new_rows).max() / 100)


# ---------------------------------------------------------------------------
# perfstats: per-dispatch score-mode labels
# ---------------------------------------------------------------------------

def test_batcher_labels_dispatch_records_with_score_mode():
    from oryx_tpu.common.metrics import get_registry
    from oryx_tpu.common.perfstats import get_perfstats
    from oryx_tpu.ops.transfer import quantized_device_put
    from oryx_tpu.serving.batcher import TopKBatcher

    rng = np.random.default_rng(9)
    y = rng.standard_normal((4096, 8)).astype(np.float32)
    qm = quantized_device_put(y)
    ps = get_perfstats()
    c = get_registry().counter("oryx_score_mode_dispatches_total")
    before = c.value(score_mode="quantized")
    t0 = time.monotonic()
    b = TopKBatcher(max_batch=8)
    try:
        vals, idx = b.submit(
            np.ones(8, dtype=np.float32), 5, qm,
            host_mat=y, score_mode="quantized",
        )
        assert len(idx) == 5
    finally:
        b.close()
    assert c.value(score_mode="quantized") == before + 1
    recs = [
        r for r in ps.records_since(t0)
        if r.kind == "serving" and r.score_mode == "quantized"
    ]
    assert recs, "dispatch record missing its score_mode label"
    # the mode also rides into /debug/profile slice args
    assert recs[0].chrome_event(1)["args"]["score_mode"] == "quantized"


# ---------------------------------------------------------------------------
# serving model: quantized views + delta resync
# ---------------------------------------------------------------------------

def test_quantized_model_serves_and_delta_resyncs():
    from oryx_tpu.apps.als.serving import ALSServingModel, SyncConfig
    from oryx_tpu.apps.als.state import ALSState
    from oryx_tpu.ops.transfer import QuantizedMatrix

    rng = np.random.default_rng(13)
    n, f = 400, 12
    state = ALSState(f, implicit=True)
    state.y.bulk_set([f"i{j}" for j in range(n)],
                     rng.standard_normal((n, f)).astype(np.float32))
    model = ALSServingModel(state, score_mode="quantized", sync=SyncConfig())
    try:
        xu = rng.standard_normal(f).astype(np.float32)
        got = [i for i, _ in model.top_n(xu, 5)]
        assert isinstance(model._device_view[0], QuantizedMatrix)
        mat, ids, _v = state.y.snapshot()
        exact = [
            ids[int(j)]
            for j in np.argsort(-(np.asarray(mat) @ xu), kind="stable")[:5]
        ]
        # int8 selection + exact f32 rescore: top-5 matches exact here
        assert _recall(got, exact) >= 0.8
        # cosine path: the quantized unit view shares the int8 rows
        got_cos = model.top_n(xu, 5, cosine=True)
        assert len(got_cos) == 5

        # delta: dirty a few rows, wait for the background resync, and
        # require the served answers to track the new factors
        for j in (1, 7, 42):
            state.y.set(f"i{j}", (10.0 + j) * np.ones(f, dtype=np.float32))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            model.top_n(xu, 5)  # queries observe drift and kick resync
            dv = model._device_view
            if dv is not None and dv[2] == state.y.get_version():
                break
            time.sleep(0.05)
        dv = model._device_view
        assert dv[2] == state.y.get_version(), "resync never caught up"
        assert isinstance(dv[0], QuantizedMatrix)
        assert model.last_resync and model.last_resync["kind"] == "delta"
        # the cosine view keeps SHARING the device view's int8 rows
        # across deltas (its half of the sync is scale-only) — two full
        # int8 matrices must never go resident
        uv = model._unit_view
        if uv is not None and uv[2] == dv[2]:
            assert uv[0].q is dv[0].q
        got2 = [i for i, _ in model.top_n(np.ones(f, dtype=np.float32), 3)]
        assert "i42" in got2  # the updated all-positive row must surface
    finally:
        model.close()


# ---------------------------------------------------------------------------
# acceptance: quantized + approx end-to-end over HTTP and the fleet front
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["quantized", "approx"])
def test_score_mode_serves_end_to_end_http_and_fleet_front(mode):
    from oryx_tpu.apps.als.serving import ALSServingModelManager
    from oryx_tpu.bus.broker import get_broker, topics
    from oryx_tpu.bus.inproc import InProcBroker
    from oryx_tpu.common.artifact import ModelArtifact
    from oryx_tpu.common.config import load_config
    from oryx_tpu.fleet.front import FleetFront
    from oryx_tpu.serving.server import ServingLayer

    InProcBroker.reset_all()
    rng = np.random.default_rng(17)
    n, f = 1500, 16
    bus = f"mem://mode-{mode}"
    cfg = load_config(overlay={
        "oryx.id": f"mode-{mode}",
        "oryx.input-topic.broker": bus,
        "oryx.update-topic.broker": bus,
        "oryx.serving.api.port": 0,
        "oryx.serving.api.read-only": True,
        "oryx.serving.init-topics": True,
        "oryx.serving.api.score-mode": mode,
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.als",
        ],
        "oryx.als.hyperparams.features": f,
    })
    topics.maybe_create(bus, "OryxUpdate", partitions=1)
    topics.maybe_create(bus, "OryxInput", partitions=1)
    x_mat = rng.standard_normal((8, f)).astype(np.float32)
    y_mat = rng.standard_normal((n, f)).astype(np.float32)
    art = ModelArtifact(app="als", tensors={"X": x_mat, "Y": y_mat})
    art.set_extension("features", str(f))
    art.set_extension("implicit", "true")
    art.set_extension("XIDs", [f"u{j}" for j in range(8)])
    art.set_extension("YIDs", [f"i{j}" for j in range(n)])
    get_broker(bus).send("OryxUpdate", "MODEL", art.to_string())

    manager = ALSServingModelManager(cfg)
    assert manager.score_mode == mode
    serving = ServingLayer(cfg, model_manager=manager)
    serving.start()
    front = None
    try:
        base = f"http://127.0.0.1:{serving.port}"
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(f"{base}/ready", timeout=5) as r:
                    if r.status == 200:
                        break
            except Exception:
                pass
            time.sleep(0.1)
        assert manager.model is not None and manager.model.score_mode == mode

        def exact_top10(uj: int) -> list[str]:
            scores = y_mat @ x_mat[uj]
            return [
                f"i{int(j)}"
                for j in np.argsort(-scores, kind="stable")[:10]
            ]

        # direct HTTP (batcher -> frontend)
        recalls = []
        for uj in range(8):
            with urllib.request.urlopen(
                f"{base}/recommend/u{uj}?howMany=10", timeout=30
            ) as r:
                assert r.status == 200
                got = [p[0] for p in json.loads(r.read())]
            recalls.append(_recall(got, exact_top10(uj)))
        assert np.mean(recalls) >= 0.95, (mode, recalls)

        # through the fleet front: the same request routed by the L7 tier
        front = FleetFront(
            load_config(
                overlay={"oryx.fleet.front.probe-interval-sec": 0.2}
            ),
            backends=[("r0", "127.0.0.1", serving.port)],
            port=0,
        )
        front.start()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{front.port}/recommend/u0?howMany=10",
            timeout=30,
        ) as r:
            assert r.status == 200
            got = [p[0] for p in json.loads(r.read())]
        assert _recall(got, exact_top10(0)) >= 0.9
    finally:
        if front is not None:
            front.close()
        serving.close()
