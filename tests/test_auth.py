"""DIGEST/Basic auth for the serving layer (reference: ServingLayer's
DIGEST InMemoryRealm protecting every endpoint)."""

import hashlib
import urllib.error
import urllib.request

import pytest

from oryx_tpu.apps.example.serving import ExampleServingModelManager
from oryx_tpu.bus.broker import topics
from oryx_tpu.bus.inproc import InProcBroker
from oryx_tpu.common.config import load_config
from oryx_tpu.serving.auth import (
    BasicAuthenticator,
    DigestAuthenticator,
    _parse_auth_params,
    make_authenticator,
)
from oryx_tpu.serving.server import ServingLayer


def _md5(s: str) -> str:
    return hashlib.md5(s.encode()).hexdigest()


def _digest_response(user, password, realm, method, uri, nonce, nc="00000001", cnonce="abc"):
    ha1 = _md5(f"{user}:{realm}:{password}")
    ha2 = _md5(f"{method}:{uri}")
    resp = _md5(f"{ha1}:{nonce}:{nc}:{cnonce}:auth:{ha2}")
    return (
        f'Digest username="{user}", realm="{realm}", nonce="{nonce}", '
        f'uri="{uri}", qop=auth, nc={nc}, cnonce="{cnonce}", response="{resp}"'
    )


def test_parse_auth_params_quoted_and_bare():
    p = _parse_auth_params('username="bob", qop=auth, nc=00000001, uri="/a,b"')
    assert p == {"username": "bob", "qop": "auth", "nc": "00000001", "uri": "/a,b"}


def test_digest_roundtrip():
    a = DigestAuthenticator("oryx", "pass")
    challenge = a.check("GET", "/ready", None)
    assert isinstance(challenge, str) and challenge.startswith("Digest ")
    nonce = _parse_auth_params(challenge[len("Digest "):])["nonce"]
    hdr = _digest_response("oryx", "pass", "Oryx", "GET", "/ready", nonce)
    assert a.check("GET", "/ready", hdr) is True
    # wrong password fails
    bad = _digest_response("oryx", "nope", "Oryx", "GET", "/ready", nonce)
    assert a.check("GET", "/ready", bad) is not True
    # replay against a different uri fails
    assert a.check("GET", "/other", hdr) is not True
    # wrong method fails
    assert a.check("POST", "/ready", hdr) is not True


def test_digest_stale_nonce_rechallenges():
    a = DigestAuthenticator("u", "p")
    forged_nonce = "123.000:deadbeef"
    hdr = _digest_response("u", "p", "Oryx", "GET", "/x", forged_nonce)
    verdict = a.check("GET", "/x", hdr)
    assert verdict is not True  # bad mac -> plain challenge


def test_basic_authenticator():
    a = BasicAuthenticator("u", "p")
    import base64

    good = "Basic " + base64.b64encode(b"u:p").decode()
    assert a.check("GET", "/", good) is True
    assert a.check("GET", "/", "Basic bm9wZTpub3Bl") is not True
    assert a.check("GET", "/", None) == 'Basic realm="Oryx"'


def test_make_authenticator_selection():
    base = {
        "oryx.serving.api.user-name": "u",
        "oryx.serving.api.password": "p",
    }
    assert isinstance(make_authenticator(load_config(overlay=base)), DigestAuthenticator)
    assert isinstance(
        make_authenticator(
            load_config(overlay={**base, "oryx.serving.api.auth-scheme": "basic"})
        ),
        BasicAuthenticator,
    )
    assert make_authenticator(load_config(overlay={})) is None
    with pytest.raises(ValueError):
        make_authenticator(
            load_config(overlay={**base, "oryx.serving.api.auth-scheme": "kerberos"})
        )


def test_serving_layer_digest_end_to_end(tmp_path):
    """urllib's stock digest handler must be able to talk to the server —
    proof the challenge/response wire format is standard."""
    InProcBroker.reset_all()
    cfg = load_config(
        overlay={
            "oryx.id": "auth-test",
            "oryx.input-topic.broker": "mem://auth",
            "oryx.update-topic.broker": "mem://auth",
            "oryx.serving.api.port": 0,
            "oryx.serving.api.read-only": True,
            "oryx.serving.api.user-name": "oryx",
            "oryx.serving.api.password": "secret",
            "oryx.serving.application-resources": [
                "oryx_tpu.serving.resources.common",
                "oryx_tpu.serving.resources.example",
            ],
        }
    )
    topics.maybe_create("mem://auth", "OryxUpdate", partitions=1)
    serving = ServingLayer(cfg, model_manager=ExampleServingModelManager(cfg))
    serving.start()
    try:
        base = f"http://127.0.0.1:{serving.port}"
        # no credentials -> 401 with a Digest challenge
        try:
            urllib.request.urlopen(f"{base}/ready", timeout=10)
            raise AssertionError("expected 401")
        except urllib.error.HTTPError as e:
            assert e.code == 401
            assert e.headers.get("WWW-Authenticate", "").startswith("Digest ")
        # stock digest client succeeds
        mgr = urllib.request.HTTPPasswordMgrWithDefaultRealm()
        mgr.add_password(None, base, "oryx", "secret")
        opener = urllib.request.build_opener(
            urllib.request.HTTPDigestAuthHandler(mgr)
        )
        with opener.open(f"{base}/ready", timeout=10) as resp:
            assert resp.status == 200
        # wrong password still locked out
        mgr2 = urllib.request.HTTPPasswordMgrWithDefaultRealm()
        mgr2.add_password(None, base, "oryx", "wrong")
        opener2 = urllib.request.build_opener(
            urllib.request.HTTPDigestAuthHandler(mgr2)
        )
        try:
            opener2.open(f"{base}/ready", timeout=10)
            raise AssertionError("expected auth failure")
        except (urllib.error.HTTPError, ValueError):
            pass  # urllib raises ValueError on repeated digest 401s
    finally:
        serving.close()
        InProcBroker.reset_all()


def test_digest_401_drains_body_on_keepalive(tmp_path):
    """A body-carrying POST that gets a 401 challenge must leave the
    keep-alive connection in sync for the authenticated retry — the normal
    digest-client flow (401 -> retry on the same socket)."""
    import http.client

    InProcBroker.reset_all()
    cfg = load_config(
        overlay={
            "oryx.id": "auth-ka",
            "oryx.input-topic.broker": "mem://authka",
            "oryx.update-topic.broker": "mem://authka",
            "oryx.serving.api.port": 0,
            "oryx.serving.api.user-name": "oryx",
            "oryx.serving.api.password": "secret",
            "oryx.serving.application-resources": [
                "oryx_tpu.serving.resources.common",
                "oryx_tpu.serving.resources.example",
            ],
        }
    )
    topics.maybe_create("mem://authka", "OryxInput", partitions=1)
    topics.maybe_create("mem://authka", "OryxUpdate", partitions=1)
    serving = ServingLayer(cfg, model_manager=ExampleServingModelManager(cfg))
    serving.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", serving.port, timeout=10)
        payload = b"a b c\n" * 100
        conn.request("POST", "/ingest", body=payload)
        r = conn.getresponse()
        assert r.status == 401
        challenge = r.headers["WWW-Authenticate"]
        r.read()
        nonce = _parse_auth_params(challenge[len("Digest "):])["nonce"]
        hdr = _digest_response("oryx", "secret", "Oryx", "POST", "/ingest", nonce)
        # SAME connection: if the 401 path left the body unread, this
        # request line would be parsed out of the stale body bytes
        conn.request("POST", "/ingest", body=payload, headers={"Authorization": hdr})
        r2 = conn.getresponse()
        assert r2.status == 200, r2.read()
        r2.read()
        conn.close()
    finally:
        serving.close()
        InProcBroker.reset_all()
