"""JAX purity / donation checker (rules ``jit-side-effect``,
``donation-reuse``).

Functions traced by ``jax.jit`` / ``pjit`` / Pallas run ONCE at trace
time; Python side effects inside them (prints, metrics increments,
``time.*`` reads, host RNG, mutation of closed-over containers) execute
at compile time, not per call — silently wrong, and invisible until
someone wonders why a counter stopped moving. Buffer donation has the
dual hazard: an array passed at a ``donate_argnums`` position is
invalidated by the call, and any later use of that name reads a deleted
buffer (PR 3's hand-enforced "never donate the serving view" rule).

Jitted functions are discovered from decorators (``@jax.jit``,
``@partial(jax.jit, ...)``), wrapper assignments
(``f_jit = jax.jit(f, ...)``, ``f_jit = partial(jax.jit, ...)(f)``) and
Pallas kernels (first argument of ``pl.pallas_call``). Donated argument
positions ride the same discovery, so a call to a donated wrapper
invalidates the names it consumed for the rest of the function — unless
the call's own statement rebinds them (``y = f_donated(..., y, ...)``,
the supported carry idiom).
"""

from __future__ import annotations

import ast

from tools.oryxlint.callgraph import ProjectIndex, shared_index
from tools.oryxlint.core import Checker, Finding, Project, SourceModule

MUTATOR_METHODS = frozenset({
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear",
})

LOG_METHODS = frozenset({"debug", "info", "warning", "error", "exception", "critical"})

METRICS_MODULE = "oryx_tpu/common/metrics.py"
# method names unambiguous enough to treat as metrics calls when every
# project definer lives in common/metrics.py. "set" is deliberately
# absent: jitted code uses the `.at[idx].set(...)` idiom everywhere, and
# other project classes define set too — a rename there would flip the
# all-definers-in-metrics test and mass-flag functional updates.
METRIC_METHODS = frozenset({"inc", "dec", "observe"})


def _is_jit_dotted(dotted: str | None) -> bool:
    return dotted is not None and (
        dotted == "jax.jit" or dotted == "jit" or dotted.endswith(".pjit")
        or dotted == "pjit"
    )


def _is_partial_dotted(dotted: str | None) -> bool:
    return dotted in ("functools.partial", "partial")


def _donate_positions(call: ast.Call) -> tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
            return tuple(out)
    return ()


class JaxPurityChecker(Checker):
    name = "jaxpurity"
    rules = {
        "jit-side-effect": (
            "Python side effect (print/log/metrics/time/host-RNG/"
            "closed-over mutation) inside a jax.jit/pjit/Pallas-traced "
            "function — it runs at trace time, not per call"
        ),
        "donation-reuse": (
            "a buffer passed at a donate_argnums position is used again "
            "after the donating call invalidated it"
        ),
    }
    fix_hints = {
        "jit-side-effect": (
            "hoist the side effect out of the traced function (record "
            "after the call, or thread values out as outputs)"
        ),
        "donation-reuse": (
            "rebind the name from the donating call (the carry idiom) or "
            "stop donating on this path"
        ),
    }

    def check(self, project: Project) -> list[Finding]:
        idx = shared_index(project)
        findings: list[Finding] = []
        jitted, donated = self._discover(idx)
        for mod, fn in jitted:
            self._check_purity(idx, mod, fn, findings)
        for fi in idx.functions:
            self._check_donation(idx, fi, donated, findings)
        return findings

    # -- discovery -----------------------------------------------------------

    def _discover(self, idx: ProjectIndex):
        """(jitted function defs, donated-callable registry). The registry
        maps (module relpath, local name) -> donated arg positions."""
        jitted: list[tuple[SourceModule, ast.AST]] = []
        # (module relpath, local name) -> ((arg position, condition-kwarg
        # or None for unconditional), ...)
        donated: dict[tuple[str, str], tuple[tuple[int, str | None], ...]] = {}

        def jit_call_info(mod, call):
            """(is_jit_wrapper, donate_positions) of a Call expression."""
            d = idx.dotted_name(mod, call.func)
            if _is_jit_dotted(d):
                return True, _donate_positions(call)
            # partial(jax.jit, ...): the partial itself carries the kwargs
            if (
                _is_partial_dotted(d)
                and call.args
                and _is_jit_dotted(idx.dotted_name(mod, call.args[0]))
            ):
                return True, _donate_positions(call)
            return False, ()

        for fi in idx.functions:
            mod = fi.module
            for dec in getattr(fi.node, "decorator_list", []):
                if _is_jit_dotted(idx.dotted_name(mod, dec)):
                    jitted.append((mod, fi.node))
                    break
                if isinstance(dec, ast.Call):
                    is_jit, pos = jit_call_info(mod, dec)
                    # @partial(jax.jit, ...) decorates the def directly
                    wraps_def = is_jit and (
                        _is_partial_dotted(idx.dotted_name(mod, dec.func))
                        or _is_jit_dotted(idx.dotted_name(mod, dec.func))
                    )
                    if wraps_def:
                        jitted.append((mod, fi.node))
                        if pos:
                            donated[(mod.relpath, fi.node.name)] = tuple(
                                (i, None) for i in pos
                            )
                        break

        for mod in idx.project.modules:
            for node in ast.walk(mod.tree):
                # X = jax.jit(f, ...) and X = partial(jax.jit, ...)(f)
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                    call = node.value
                    inner = None
                    is_jit, pos = jit_call_info(mod, call)
                    if is_jit and call.args and isinstance(call.args[0], ast.Name):
                        maybe_fn = idx.top_level.get(
                            (mod.relpath, call.args[0].id)
                        )
                        if maybe_fn is not None and not _is_jit_dotted(
                            idx.dotted_name(mod, call.args[0])
                        ):
                            inner = maybe_fn
                    elif isinstance(call.func, ast.Call):
                        outer_jit, pos = jit_call_info(mod, call.func)
                        if outer_jit and call.args and isinstance(
                            call.args[0], ast.Name
                        ):
                            inner = idx.top_level.get(
                                (mod.relpath, call.args[0].id)
                            )
                    if inner is not None:
                        jitted.append((mod, inner.node))
                        if pos:
                            for t in node.targets:
                                if isinstance(t, ast.Name):
                                    donated[(mod.relpath, t.id)] = tuple(
                                        (i, None) for i in pos
                                    )
                # pl.pallas_call(kernel, ...): the kernel is traced
                if isinstance(node, ast.Call):
                    d = idx.dotted_name(mod, node.func)
                    if d is not None and (
                        d.endswith(".pallas_call") or d == "pallas_call"
                    ):
                        if node.args and isinstance(node.args[0], ast.Name):
                            k = idx.top_level.get((mod.relpath, node.args[0].id))
                            if k is not None:
                                jitted.append((mod, k.node))
        # hand-written wrappers declaring a donation contract by
        # annotation (`donates=<pos> [when <kwarg>]`) join the registry —
        # e.g. ops/transfer.scatter_rows, whose donate=True form consumes
        # the serving-view buffer exactly like donate_argnums would
        for fi in idx.functions:
            ann = fi.module.fn_donates(fi.node)
            if ann is not None and fi.cls is None and fi.parent is None:
                key = (fi.module.relpath, fi.name)
                donated[key] = donated.get(key, ()) + (ann,)
        # dedupe by node identity
        seen: set[int] = set()
        uniq = []
        for mod, fn in jitted:
            if id(fn) not in seen:
                seen.add(id(fn))
                uniq.append((mod, fn))
        return uniq, donated

    # -- purity --------------------------------------------------------------

    def _check_purity(self, idx, mod, fn, findings: list[Finding]) -> None:
        local: set[str] = {a.arg for a in fn.args.args}
        local.update(a.arg for a in fn.args.kwonlyargs)
        local.update(a.arg for a in fn.args.posonlyargs)
        if fn.args.vararg:
            local.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            local.add(fn.args.kwarg.arg)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                local.add(node.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local.add(node.name)

        def flag(line, what):
            findings.append(Finding(
                mod.relpath, line, "jit-side-effect",
                f"{what} inside jitted function {fn.name!r} "
                f"({mod.relpath}:{fn.lineno}): it executes at trace time, "
                "not per call — hoist it out of the traced function",
            ))

        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Name) and f.id == "print":
                    flag(node.lineno, "print()")
                    continue
                dotted = idx.dotted_name(mod, f)
                if dotted is not None:
                    if dotted.startswith("time."):
                        flag(node.lineno, f"{dotted}() wall-clock read")
                        continue
                    if dotted.startswith(("numpy.random.", "random.")):
                        flag(
                            node.lineno,
                            f"{dotted}() host RNG (use an explicit "
                            "jax.random key)",
                        )
                        continue
                if isinstance(f, ast.Attribute):
                    recv = f.value
                    if f.attr in LOG_METHODS and isinstance(recv, ast.Name) and (
                        "log" in recv.id.lower()
                    ):
                        flag(node.lineno, f"logging call .{f.attr}()")
                        continue
                    if f.attr in METRIC_METHODS:
                        definers = idx.methods_by_name.get(f.attr, [])
                        if definers and all(
                            d.module.relpath == METRICS_MODULE for d in definers
                        ):
                            flag(node.lineno, f"metrics call .{f.attr}()")
                            continue
                    if (
                        f.attr in MUTATOR_METHODS
                        and isinstance(recv, ast.Name)
                        and recv.id not in local
                    ):
                        flag(
                            node.lineno,
                            f"mutation of closed-over {recv.id!r} "
                            f"(.{f.attr}())",
                        )
                        continue
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and t.value.id not in local
                    ):
                        flag(
                            node.lineno,
                            f"item assignment into closed-over "
                            f"{t.value.id!r}",
                        )

    # -- donation -------------------------------------------------------------

    def _check_donation(self, idx, fi, donated, findings: list[Finding]) -> None:
        mod = fi.module
        if not donated:
            return
        # name -> sorted store line numbers (rebinds revive a donated name)
        stores: dict[str, list[int]] = {}
        loads: dict[str, list[int]] = {}
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Name):
                d = stores if isinstance(node.ctx, ast.Store) else loads
                d.setdefault(node.id, []).append(node.lineno)
        for call in ast.walk(fi.node):
            if not isinstance(call, ast.Call):
                continue
            fname = None
            if isinstance(call.func, ast.Name):
                fname = call.func.id
            if fname is None:
                continue
            pos = donated.get((mod.relpath, fname))
            if pos is None:
                # imported donated wrapper
                imp = idx.imports.get(mod.relpath, {}).get(fname)
                if imp is not None and imp[0] == "sym":
                    rel = imp[1].replace(".", "/") + ".py"
                    pos = donated.get((rel, imp[2]))
            if not pos:
                continue
            for i, cond in pos:
                if cond is not None and not any(
                    kw.arg == cond
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                    for kw in call.keywords
                ):
                    continue  # conditional donation not taken at this site
                if i >= len(call.args):
                    continue
                arg = call.args[i]
                if not isinstance(arg, ast.Name):
                    continue
                line = call.lineno
                later_stores = [l for l in stores.get(arg.id, []) if l >= line]
                for use in sorted(loads.get(arg.id, [])):
                    if use <= line:
                        continue
                    if any(line <= s <= use for s in later_stores):
                        break  # rebound before (or at) the use: revived
                    findings.append(Finding(
                        mod.relpath, use, "donation-reuse",
                        f"{arg.id!r} was donated to {fname}() at line "
                        f"{line} (donate_argnums position {i}) and is "
                        "used again here — the donated buffer is "
                        "invalidated by the call",
                    ))
                    break
