"""ALS batch tier: the full TPU model rebuild per generation.

Replaces the reference's Spark-MLlib pipeline (app/oryx-app-mllib
.../als/ALSUpdate.java): parse events, aggregate with decay/delete
semantics, train pjit ALS, evaluate (implicit: mean per-user AUC; explicit:
negative RMSE), publish a *skeleton* artifact (hyperparams + expected ID
lists, no tensors — factor matrices are streamed row-by-row as UP messages
through publish_additional_model_data, the reference's
EnqueueFeatureVecsFn pattern at ALSUpdate.java:286-318), and split
train/test by time instead of randomly (ALSUpdate.java:325-342).
"""

from __future__ import annotations

import logging
import pathlib
import threading
import time
from typing import Any, Sequence

import numpy as np

from oryx_tpu.bus.api import KeyMessage, TopicProducer
from oryx_tpu.common.artifact import ModelArtifact
from oryx_tpu.common.config import Config
from oryx_tpu.common.metrics import get_registry
from oryx_tpu.common.tracing import get_tracer
from oryx_tpu.ml.evaluate import auc_mean_per_user, rmse
from oryx_tpu.ml.update import MLUpdate
from oryx_tpu.ops.als import (
    AggregateState,
    agg_state_fingerprint,
    aggregate_interactions,
    align_factors,
    train_als,
    train_als_warm,
)
from oryx_tpu.apps.als.common import (
    ALSConfig,
    parse_events,
    batch_update_messages,
    valid_event_line,
    valid_event_lines,
)

log = logging.getLogger(__name__)


class ALSUpdate(MLUpdate):
    def __init__(self, config: Config, mesh=None):
        super().__init__(config)
        self.als = ALSConfig.from_config(config)
        if mesh is None:
            from oryx_tpu.parallel.distributed import mesh_from_config

            mesh = mesh_from_config(config)
        self.mesh = mesh
        # incremental generations: persistent aggregate snapshot + warm
        # starts (docs/operations.md "Incremental generations & warm start")
        self.data_dir = config.get_string("oryx.batch.storage.data-dir", None)
        self.warm_start = config.get_bool("oryx.batch.train.warm-start", True)
        self.train_tol = config.get_float("oryx.batch.train.tol", 0.02)
        self.train_min_iterations = config.get_int(
            "oryx.batch.train.min-iterations", 2
        )
        self.train_check_every = config.get_int("oryx.batch.train.check-every", 2)
        # pod-scale factor sharding: > 1 runs the bucketed scan under
        # pjit with the item-factor table row-sharded over a model-axis
        # mesh of that many devices (ops/als.py train_als shard_mesh)
        self.train_shards = config.get_int("oryx.batch.train.shards", 1)
        self.max_drift_fraction = config.get_float(
            "oryx.batch.storage.incremental.max-drift-fraction", 0.5
        )
        self.snapshots_kept = config.get_int(
            "oryx.batch.storage.incremental.snapshots-kept", 2
        )
        self._agg_state: AggregateState | None = None  # in-memory, authoritative
        self._agg_pending = None  # (users, items, vals, tss) holdout to fold next gen
        # fold staged by the in-flight generation; adopted (and the staged
        # snapshot promoted) only in finalize_generation, after the batch
        # layer has persisted + committed the window — otherwise a crash
        # between snapshot and persist would re-deliver the window into a
        # state that already contains it (double-counted strengths)
        self._staged_state: AggregateState | None = None
        self._staged_pending = None
        self._staged_ts: int | None = None
        self._agg_through_ts: int | None = None  # newest generation folded
        self._prev_item_ids = None  # last generation's Y alignment table
        self._prev_y: np.ndarray | None = None
        # the batch process's train-scan dispatches feed the live perf
        # accounting (oryx_device_mfu{kind="train"} and friends) — adopt
        # the configured window/peak and register the families so a
        # co-resident serving /metrics page carries them from start
        from oryx_tpu.common.perfstats import configure_perfstats

        configure_perfstats(config)
        reg = get_registry()
        self._m_agg_rows = reg.gauge(
            "oryx_batch_aggregate_rows",
            "Entries in the persistent batch aggregate state (0 until the "
            "first incremental generation)",
        )
        self._m_warm_iters = reg.gauge(
            "oryx_batch_warm_iterations",
            "ALS sweeps actually run by the last batch generation "
            "(convergence early stop; equals the configured iteration "
            "count on cold starts)",
        )

    # ---- incremental generations ---------------------------------------

    @property
    def _with_days(self) -> bool:
        return self.als.implicit and self.als.decay_factor < 1.0

    def validate_record(self, km) -> bool:
        """Deserialize check for the batch layer's quarantine sweep: a
        line parse_events would reject diverts to the dead-letter store
        instead of entering persisted history (where every from-scratch
        rebuild would re-read it forever)."""
        return valid_event_line(km.message)

    def validate_records(self, records):
        """Batch sweep: one native parse per window (see
        valid_event_lines) instead of a Python parse per record."""
        return valid_event_lines(km.message for km in records)

    @property
    def _fingerprint(self) -> str:
        return agg_state_fingerprint(
            implicit=self.als.implicit, with_days=self._with_days
        )

    def _parse_to_str(self, data):
        """parse_events with id arrays normalized to unicode — pending
        holdout buffers round-trip through npz, which cannot hold object
        arrays without pickling."""
        users, items, vals, tss = parse_events(data)
        return (
            np.asarray(users, dtype=str),
            np.asarray(items, dtype=str),
            vals,
            tss,
        )

    def _load_snapshot(self):
        """Persisted (state, pending) for the current schema, or None when
        missing/mismatched/stale. Stale = a persisted generation newer
        than the snapshot's through_ts: that window was never folded
        (crash between persist and snapshot), so the state lies."""
        from oryx_tpu.layers.datastore import (
            latest_generation_ts,
            load_aggregate_snapshot,
        )

        if not self.data_dir:
            return None
        loaded = load_aggregate_snapshot(self.data_dir, self._fingerprint)
        if loaded is None:
            return None
        through_ts, arrays = loaded
        newest = latest_generation_ts(self.data_dir)
        if newest is not None and newest > through_ts:
            log.info(
                "aggregate snapshot through %d is older than persisted "
                "generation %d; full rebuild", through_ts, newest,
            )
            return None
        try:
            state = AggregateState.from_arrays(arrays)
            pending = (
                np.asarray(arrays["pending_users"], dtype=str),
                np.asarray(arrays["pending_items"], dtype=str),
                np.asarray(arrays["pending_vals"], dtype=np.float64),
                np.asarray(arrays["pending_tss"], dtype=np.int64),
            )
        except KeyError:
            return None
        return state, pending

    def _snapshot_arrays(self, state: AggregateState, pending) -> dict:
        arrays = state.to_arrays()
        users, items, vals, tss = pending
        arrays["pending_users"] = (
            users if users.size else np.zeros(0, "<U1")
        )
        arrays["pending_items"] = (
            items if items.size else np.zeros(0, "<U1")
        )
        arrays["pending_vals"] = vals.astype(np.float64)
        arrays["pending_tss"] = tss.astype(np.int64)
        return arrays

    def _persist_snapshot(self, timestamp_ms: int, state, pending) -> None:
        from oryx_tpu.layers.datastore import save_aggregate_snapshot

        if not self.data_dir:
            return
        save_aggregate_snapshot(
            self.data_dir, timestamp_ms, self._fingerprint,
            self._snapshot_arrays(state, pending), keep=self.snapshots_kept,
            staged=True,
        )

    def incremental_update(
        self,
        timestamp_ms: int,
        new_data,
        model_dir: str,
        update_producer: TopicProducer,
    ) -> bool:
        """One O(window) generation: merge the new window into the
        persisted aggregate state, warm-start training from the previous
        generation's factors, evaluate on the window's temporal holdout,
        publish, and snapshot — overlapping the snapshot write with the
        device training scan. Returns False (→ full rebuild) when the
        snapshot is missing/stale/mismatched, when the window drifts past
        max-drift-fraction of the state, or when a hyperparameter search
        is configured (candidates > 1 needs the full path's scoring)."""
        if self.candidates > 1:
            return False
        if (
            self._agg_state is not None
            and self._agg_state.fingerprint == self._fingerprint
            and self._memory_state_fresh()
        ):
            state_pending = (self._agg_state, self._agg_pending)
        else:
            state_pending = self._load_snapshot()
        if state_pending is None:
            return False
        state, pending = state_pending
        tr = get_tracer()
        t_merge = time.monotonic()
        train_msgs, test_msgs = self.split_train_test(list(new_data))
        users, items, vals, tss = self._parse_to_str(train_msgs)
        self._window_tss = tss  # event-rate input of the quality profile
        if pending is not None and len(pending[2]):
            # the previous generation's holdout is persisted history the
            # from-scratch path would train on: fold it in now
            users = np.concatenate([pending[0], users])
            items = np.concatenate([pending[1], items])
            vals = np.concatenate([pending[2], vals])
            tss = np.concatenate([pending[3], tss])
        window = AggregateState.from_window(
            users, items, vals, tss,
            implicit=self.als.implicit, with_days=self._with_days,
        )
        if state.entries == 0 and window.entries == 0:
            log.info("no data at generation %d; skipping model build", timestamp_ms)
            return True
        if (
            state.entries
            and window.entries > self.max_drift_fraction * state.entries
        ):
            log.info(
                "window touches %d aggregate rows (> %.0f%% of %d): drift "
                "past oryx.batch.storage.incremental.max-drift-fraction; "
                "full rebuild", window.entries,
                100 * self.max_drift_fraction, state.entries,
            )
            self._agg_state = None  # re-anchor from history
            return False
        merged = state.merge(window)
        agg = merged.materialize(
            decay_factor=self.als.decay_factor,
            zero_threshold=self.als.zero_threshold,
            now_ms=int(time.time() * 1000),
            log_strength=self.als.log_strength,
            epsilon=self.als.epsilon,
        )
        tr.record_interval(
            "batch.merge", t_merge, window_rows=window.entries,
            aggregate_rows=merged.entries,
        )
        if len(agg.values) == 0 or agg.n_users == 0 or agg.n_items == 0:
            # everything deleted/thresholded away: nothing to train, but
            # the fold itself must survive
            log.info("generation %d: empty aggregate after merge", timestamp_ms)
            self._set_state(merged, self._parse_to_str(test_msgs), timestamp_ms)
            return True

        hyperparams = {
            "features": self.als.features,
            "lambda": self.als.lam,
            "alpha": self.als.alpha,
        }
        features = int(hyperparams["features"])
        t_warm = time.monotonic()
        resume_y = None
        if self.warm_start:
            if self._prev_y is None:
                self._load_prev_factors(model_dir)
            resume_y = align_factors(
                self._prev_item_ids, self._prev_y, agg.item_ids, features,
            )
        tr.record_interval(
            "batch.warmstart", t_warm,
            resumed_rows=0 if resume_y is None else len(agg.item_ids),
        )
        # snapshot write overlaps the training scan: the device is busy
        # for the whole solve, the npz write is pure host I/O
        pending_next = self._parse_to_str(test_msgs)
        snap_err: list[BaseException] = []

        def _snapshot():
            try:
                self._persist_snapshot(timestamp_ms, merged, pending_next)
            except BaseException as e:  # noqa: BLE001 - surfaced after join
                snap_err.append(e)

        snap_thread = threading.Thread(
            target=_snapshot, name="oryx-agg-snapshot", daemon=True
        )
        snap_thread.start()
        try:
            # shards (when configured and applicable) replace the auto
            # mesh: the sharded BUCKETED scan is the one that composes
            # with the donated carry and warm starts below
            shard_mesh = self._shard_mesh()
            model, sweeps = train_als_warm(
                agg,
                features=features,
                lam=float(hyperparams["lambda"]),
                alpha=float(hyperparams["alpha"]),
                iterations=self.als.iterations,
                implicit=self.als.implicit,
                mesh=None if shard_mesh is not None else self._build_mesh(),
                compute_dtype=self.als.compute_dtype,
                resume_y=resume_y,
                tol=self.train_tol if resume_y is not None else 0.0,
                min_iterations=self.train_min_iterations,
                check_every=self.train_check_every,
                shard_mesh=shard_mesh,
            )
        finally:
            snap_thread.join()
        if snap_err:
            raise snap_err[0]
        self._m_warm_iters.set(sweeps)
        self._m_agg_rows.set(merged.entries)
        art = self._artifact_from_model(model, hyperparams, agg)

        score = self.evaluate(art, train_msgs, test_msgs) if test_msgs else float("nan")
        log.info(
            "incremental generation %d: %d aggregate rows, %d/%d sweeps "
            "(warm=%s), eval %s", timestamp_ms, merged.entries, sweeps,
            self.als.iterations, resume_y is not None, score,
        )
        self._set_state(merged, pending_next, timestamp_ms, persisted=True)
        if (
            self.threshold is not None
            and np.isfinite(score)
            and score < float(self.threshold)
        ):
            log.warning(
                "incremental eval %.6f below threshold %s; not publishing "
                "model", score, self.threshold,
            )
            return True

        from pathlib import Path

        from oryx_tpu.common.ioutil import delete_recursively, mkdirs, strip_scheme

        root = Path(strip_scheme(model_dir))
        staged = art.write(mkdirs(root / ".incremental") / str(timestamp_ms))
        self.note_eval(score)  # the stamp carries this generation's AUC
        self.promote_and_publish(staged, root, timestamp_ms, update_producer)
        delete_recursively(root / ".incremental")
        self._prev_item_ids = list(model.item_ids)
        self._prev_y = model.y
        return True

    def _memory_state_fresh(self) -> bool:
        """The in-memory state must pass the SAME newest-persisted-
        generation check as a loaded snapshot: a generation whose build
        raised AFTER its window was polled still gets that window
        persisted and committed by the batch layer — trusting the
        in-memory state blindly would drop those events from every
        future aggregate."""
        from oryx_tpu.layers.datastore import latest_generation_ts

        if not self.data_dir or self._agg_through_ts is None:
            return False
        newest = latest_generation_ts(self.data_dir)
        return newest is None or newest <= self._agg_through_ts

    def _load_prev_factors(self, model_dir: str) -> None:
        """Restart path: resume warm starts from the newest published
        model artifact's Y (the in-memory copy dies with the process)."""
        from oryx_tpu.common.ioutil import list_generation_dirs

        try:
            gens = list_generation_dirs(model_dir)
            if not gens:
                return
            art = ModelArtifact.read(gens[-1])
            y = art.tensors.get("Y")
            ids = art.get_extension_list("YIDs")
            if y is not None and ids and len(ids) == len(y):
                self._prev_item_ids = ids
                self._prev_y = np.asarray(y, dtype=np.float32)
        except Exception:  # noqa: BLE001 - warm start is best-effort
            log.warning("could not load previous factors for warm start",
                        exc_info=True)

    def _set_state(self, state, pending, timestamp_ms: int, persisted=False) -> None:
        """Stage the folded state. Both the in-memory adoption and the
        durable snapshot become visible in finalize_generation, once the
        window itself is persisted and committed."""
        self._staged_state = state
        self._staged_pending = pending
        self._staged_ts = timestamp_ms
        if not persisted:
            self._persist_snapshot(timestamp_ms, state, pending)

    def finalize_generation(self, timestamp_ms: int) -> None:
        from oryx_tpu.layers.datastore import finalize_aggregate_snapshot

        if self._staged_ts != timestamp_ms or self._staged_state is None:
            return
        self._agg_state = self._staged_state
        self._agg_pending = self._staged_pending
        self._agg_through_ts = timestamp_ms
        self._staged_state = self._staged_pending = None
        self._staged_ts = None
        if self.data_dir:
            try:
                finalize_aggregate_snapshot(
                    self.data_dir, timestamp_ms, keep=self.snapshots_kept
                )
            except Exception:  # noqa: BLE001 - next generation rebuilds
                log.exception("aggregate snapshot finalize failed")

    def after_full_build(self, timestamp_ms, train, test, model) -> None:
        """Re-anchor the incremental state after a from-scratch build: one
        extra linear pass over the already-materialized train/test splits,
        so the NEXT generation runs O(window) again. model is None when
        the build was withheld by the eval threshold — the aggregates
        still re-anchor (the window is persisted either way); only the
        warm-start factors are skipped."""
        try:
            users, items, vals, tss = self._parse_to_str(train)
            state = AggregateState.from_window(
                users, items, vals, tss,
                implicit=self.als.implicit, with_days=self._with_days,
            )
            pending = self._parse_to_str(test)
            self._set_state(state, pending, timestamp_ms)
            self._m_agg_rows.set(state.entries)
            # cold builds run the full configured sweep count; without
            # this a fallback generation would keep showing the previous
            # warm generation's low figure
            self._m_warm_iters.set(self.als.iterations)
            if model is not None:
                try:
                    self._prev_item_ids = model.get_extension_list("YIDs")
                    self._prev_y = model.tensors.get("Y")
                except Exception:  # noqa: BLE001 - warm start is best-effort
                    self._prev_item_ids = self._prev_y = None
        except Exception:  # noqa: BLE001 - snapshotting must never fail a
            # published generation; next generation just rebuilds again
            log.exception("aggregate snapshot rebuild failed; next "
                          "generation will run a full rebuild")

    def hyperparam_ranges(self) -> dict[str, Any]:
        return {
            "features": self.als.features,
            "lambda": self.als.lam,
            "alpha": self.als.alpha,
        }

    def split_train_test(self, data: Sequence[KeyMessage]):
        """Temporal split: newest test-fraction of events held out
        (ALSUpdate.java:325-342 sorts by timestamp) — the shared
        split_by_time helper (ml/update.py), falling back to the random
        split when no line carries a usable timestamp."""
        from oryx_tpu.ml.update import split_by_time

        return split_by_time(
            data, self.test_fraction, super().split_train_test
        )

    def _shard_mesh(self):
        """Model-axis mesh for pjit-sharded bucketed training, or None.

        Precedence: a candidate sub-mesh (partitioned parallel search)
        and an explicit TENSOR-PARALLEL training mesh (model axis > 1 —
        the operator already chose a factor layout) always win; otherwise
        ``oryx.batch.train.shards > 1`` REPLACES the auto data-parallel
        mesh for the build — the sharded bucketed scan is the path that
        keeps the bucketed-width savings, the donated Y carry, and warm
        starts while the factor table is row-sharded, which the plain
        mesh trainer has none of. The shard count clamps to the devices
        that exist — a 2-shard config on a 1-chip host trains unsharded
        instead of failing the build."""
        if self.train_shards <= 1:
            return None
        from oryx_tpu.parallel.submesh import current_candidate_mesh

        if current_candidate_mesh() is not None:
            return None
        from oryx_tpu.parallel.mesh import MODEL_AXIS, model_mesh

        mesh = self.training_mesh()
        if (
            mesh is not None
            and MODEL_AXIS in mesh.shape
            and mesh.shape[MODEL_AXIS] > 1
        ):
            return None
        import jax

        n = min(self.train_shards, len(jax.devices()))
        if n <= 1:
            return None
        return model_mesh(n)

    def _aggregate(self, data: Sequence[KeyMessage]):
        users, items, vals, tss = parse_events(data)
        if len(vals) == 0:
            raise ValueError("no parseable interactions")
        self._window_tss = tss  # event-rate input of the quality profile
        return aggregate_interactions(
            users, items, vals, tss,
            implicit=self.als.implicit,
            decay_factor=self.als.decay_factor,
            zero_threshold=self.als.zero_threshold,
            now_ms=int(time.time() * 1000),
            log_strength=self.als.log_strength,
            epsilon=self.als.epsilon,
        )

    def build_model(self, train: Sequence[KeyMessage], hyperparams: dict[str, Any]) -> ModelArtifact:
        agg = self._aggregate(train)
        shard_mesh = self._shard_mesh()
        kwargs = dict(
            features=int(hyperparams["features"]),
            lam=float(hyperparams["lambda"]),
            alpha=float(hyperparams["alpha"]),
            iterations=self.als.iterations,
            implicit=self.als.implicit,
            # shards (when configured and applicable) replace the auto
            # mesh so the build takes the row-sharded BUCKETED scan
            mesh=None if shard_mesh is not None else self._build_mesh(),
            compute_dtype=self.als.compute_dtype,
        )
        model_dir = self.config.get_string("oryx.batch.storage.model-dir", None)
        if self.als.checkpoint_interval > 0 and model_dir:
            # long builds survive preemption: resume from the last
            # checkpointed sweep instead of restarting the generation.
            # One subdir per hyperparam combo — candidates may build in
            # parallel (oryx.ml.eval.parallelism) and must not share a
            # checkpoint file
            import hashlib
            import json as _json

            from oryx_tpu.common.ioutil import strip_scheme
            from oryx_tpu.ops.als import train_als_checkpointed

            combo = hashlib.sha1(
                _json.dumps(hyperparams, sort_keys=True, default=str).encode()
            ).hexdigest()[:12]
            m = train_als_checkpointed(
                agg,
                pathlib.Path(strip_scheme(model_dir)) / ".als-checkpoint" / combo,
                self.als.checkpoint_interval,
                shard_mesh=shard_mesh,
                **kwargs,
            )
        else:
            m = train_als(agg, shard_mesh=shard_mesh, **kwargs)
        return self._artifact_from_model(m, hyperparams, agg)

    def _artifact_from_model(self, m, hyperparams, agg) -> ModelArtifact:
        """Model arrays + aggregate -> the publishable skeleton artifact
        (shared by the from-scratch candidate builds and the incremental
        warm-start path)."""
        art = ModelArtifact(
            "als",
            extensions={
                "features": str(int(hyperparams["features"])),
                "lambda": str(float(hyperparams["lambda"])),
                "alpha": str(float(hyperparams["alpha"])),
                "implicit": str(self.als.implicit).lower(),
                "logStrength": str(self.als.log_strength).lower(),
            },
            tensors={"X": m.x, "Y": m.y},
        )
        art.set_extension("XIDs", m.user_ids)
        art.set_extension("YIDs", m.item_ids)
        self._attach_quality_profile(art, m, agg)
        # knownItems per user ride with the X rows at publish time.
        # Vectorized grouping: a per-pair Python dict loop costs ~20s at
        # the 25M-interaction benchmark scale (measured 3x slower than
        # this sort-and-slice form)
        if not self.als.no_known_items and len(agg.users):
            item_arr = np.asarray(agg.item_ids, dtype=object)
            order = np.argsort(agg.users, kind="stable")
            us = agg.users[order]
            its = item_arr[agg.items[order]]
            cut = np.nonzero(np.r_[True, us[1:] != us[:-1]])[0]
            ends = np.r_[cut[1:], len(us)]
            art.content["knownItems"] = {
                agg.user_ids[us[c]]: its[c:e].tolist()
                for c, e in zip(cut, ends)
            }
        return art

    def _attach_quality_profile(self, art: ModelArtifact, m, agg) -> None:
        """Stamp the generation's training profile (item-popularity
        sketch, event rate, new-item fraction, predicted-score
        distribution) into the artifact so the serving/speed tiers can
        measure drift against what this model actually trained on. Never
        fails a build — a generation without a profile just reads NaN
        drift."""
        try:
            from oryx_tpu.common.qualitystats import build_training_profile

            counts = np.bincount(
                agg.items, minlength=agg.n_items
            ).astype(np.float64)
            scores = None
            x, y = np.asarray(m.x), np.asarray(m.y)
            if len(x) and len(y):
                # the LIVE side of prediction drift is the mean of served
                # top-k scores (an extreme order statistic), so the
                # baseline must be the SAME statistic — mean top-10 score
                # of sampled training users over the full catalog — or a
                # perfectly healthy model reads as drifted forever
                rng = np.random.default_rng(7)
                us = rng.integers(0, len(x), 32)
                k = min(10, len(y))
                full = x[us] @ y.T  # (32, n_items), a few GFLOP at 1M rows
                part = -np.partition(-full, k - 1, axis=1)[:, :k]
                scores = part.mean(axis=1)
            profile = build_training_profile(
                agg.item_ids, counts,
                timestamps_ms=getattr(self, "_window_tss", None),
                prev_item_ids=self._prev_item_ids,
                scores=scores,
            )
            art.set_extension("qualityProfile", profile.to_json())
        except Exception:  # noqa: BLE001 - the profile must never fail a build
            log.warning("quality profile build failed", exc_info=True)

    def eval_metric_name(self) -> str:
        # implicit feedback evaluates mean per-user AUC; explicit a
        # negated RMSE (bigger is better either way)
        return "auc" if self.als.implicit else "neg_rmse"

    def evaluate(self, model: ModelArtifact, train, test) -> float:
        users, items, vals, _ = parse_events(test)
        if len(vals) == 0:
            return float("nan")
        xids = model.get_extension_list("XIDs")
        yids = model.get_extension_list("YIDs")
        umap = {u: j for j, u in enumerate(xids)}
        imap = {i: j for j, i in enumerate(yids)}
        keep = [
            (umap[u], imap[i], v)
            for u, i, v in zip(users, items, vals)
            if u in umap and i in imap and not np.isnan(v)
        ]
        if not keep:
            return float("nan")
        tu = np.asarray([a for a, _, _ in keep])
        ti = np.asarray([b for _, b, _ in keep])
        tv = np.asarray([c for _, _, c in keep])
        x, y = model.tensors["X"], model.tensors["Y"]
        if self.als.implicit:
            known = {
                umap[u]: {imap[i] for i in its if i in imap}
                for u, its in model.content.get("knownItems", {}).items()
                if u in umap
            }
            return auc_mean_per_user(x, y, tu, ti, known)
        return -rmse(x, y, tu, ti, tv)

    def publish_model(self, model: ModelArtifact, model_path: str, producer: TopicProducer) -> None:
        """Publish a tensor-free skeleton; factor rows stream separately
        (the reference's skeleton-PMML-with-extensions pattern). An
        oversized skeleton ships its bytes as bus chunks ahead of the
        MODEL-REF so other hosts resolve it with no shared mount."""
        from oryx_tpu.common.artifact import publish_model_ref

        skeleton = ModelArtifact("als", dict(model.extensions), {})
        serialized = skeleton.to_string()
        if len(serialized.encode("utf-8")) <= self.max_message_size:
            producer.send("MODEL", serialized)
        else:
            publish_model_ref(
                producer, serialized, model_path, self.max_message_size,
                transfer=self.artifact_transfer,
            )
        # freshness stamp (SPI contract: every publish_model override ends
        # with this) — before the PR 10 SPI split, ALS generations were
        # invisible to oryx_model_generation / update-to-serve freshness
        self.send_publish_stamp(model_path, producer)

    def publish_additional_model_data(
        self, model: ModelArtifact, model_path: str, producer: TopicProducer
    ) -> None:
        """Stream every Y row then every X row as UP messages
        (ALSUpdate.java:286-318: Y first so user solves see item vectors)."""
        xids = model.get_extension_list("XIDs")
        yids = model.get_extension_list("YIDs")
        x, y = model.tensors["X"], model.tensors["Y"]
        known = model.content.get("knownItems", {})

        def chunks(kind, ids, mat, known_of=None):
            # batched message building (one C-encoder pass per chunk), in
            # bounded chunks so a million-row flood never materializes one
            # multi-hundred-MB JSON blob
            step = 8192
            dropped = 0
            for lo in range(0, len(ids), step):
                part = ids[lo : lo + step]
                block = mat[lo : lo + len(part)]
                finite = np.isfinite(block).all(axis=1)
                if not finite.all():  # builder contract: NaN is not JSON
                    dropped += int((~finite).sum())
                    rows = np.nonzero(finite)[0]
                    part = [part[j] for j in rows]
                    block = block[rows]
                yield from batch_update_messages(
                    kind, part, block,
                    known_lists=(
                        [known_of.get(i, []) for i in part]
                        if known_of is not None else None
                    ),
                )
            if dropped:
                log.warning("dropped %d non-finite %s factor rows at publish", dropped, kind)

        producer.send_batch(chunks("Y", yids, y))
        producer.send_batch(chunks("X", xids, x, known))
        log.info("published %d Y and %d X factor rows", len(yids), len(xids))
