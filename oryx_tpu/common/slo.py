"""Config-declared SLOs computed from the metrics the system already has.

ROADMAP item 5's canary gate needs a promotion signal: "is the fleet
burning its error budget faster than the objective allows, right now?"
That is a burn rate — the ratio of the observed bad fraction over a
window to the budgeted bad fraction (1 - objective) — evaluated over a
FAST window (pages/gates react in minutes) and a SLOW window (sustained
burn distinguishes a blip from an incident), the standard
multi-window-burn-rate alerting shape. This module derives both from
counters/histograms that already exist (no new instrumentation on any
hot path):

- ``serving-availability``: non-5xx fraction of
  ``oryx_serving_requests_total`` (a deliberate shed IS a client-visible
  503 — the SLO counts it, which is exactly why an induced shed storm
  moves the burn rate and recovery returns it to ~0).
- ``serving-latency``: fraction of ``oryx_serving_request_seconds``
  observations at/under ``oryx.monitoring.slo.latency.threshold-sec``.
- ``front-availability``: fraction of
  ``oryx_fleet_front_requests_total`` answered by a replica
  (``replica="none"`` means the client saw the front's own 503).
- ``quality``: fraction of shadow-rescored responses
  (``common/qualitystats.py``) whose measured recall held the
  ``oryx.monitoring.slo.quality.recall-floor`` — the live model-quality
  objective a degraded generation burns.

Exported as ``oryx_slo_burn_rate{slo,window}`` and
``oryx_slo_error_budget_remaining{slo}``. A burn rate of 1.0 means
spending the budget exactly as fast as the objective allows; the classic
page thresholds are ~14 (fast window) and ~6 (slow window). Sampling is
scrape-driven: each gauge read snapshots the cumulative totals into a
bounded time-indexed ring and differences against the sample nearest the
window start — no background thread, and the cost is two counter-series
sums per scrape.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable

from oryx_tpu.common.metrics import get_registry

# Minimum spacing between stored samples: the three gauge reads of one
# scrape (fast burn, slow burn, budget) share a single sample.
_MIN_SAMPLE_GAP_S = 0.05


class SloTracker:
    """One objective's burn-rate state: a bounded ring of (t, total, bad)
    cumulative samples and the window math over it."""

    def __init__(
        self,
        slo: str,
        objective: float,
        source: Callable[[], tuple[float, float]],
        fast_s: float,
        slow_s: float,
    ):
        self.slo = slo
        self.objective = objective
        self.source = source  # () -> (total, bad), cumulative
        self.fast_s = fast_s
        self.slow_s = slow_s
        self._lock = threading.Lock()
        self._samples: deque[tuple[float, float, float]] = deque()  # guarded-by: _lock
        # last source-read failure, surfaced on /fleet/status so broken
        # SLO math (a renamed counter, a raising callback) can't hide
        # behind a silently-flat burn rate
        self.last_error: str | None = None

    def reconfigure(
        self, objective: float, fast_s: float, slow_s: float
    ) -> None:
        self.objective = objective
        self.fast_s = fast_s
        self.slow_s = slow_s

    def _sample(self) -> None:
        now = time.monotonic()
        with self._lock:
            if self._samples and now - self._samples[-1][0] < _MIN_SAMPLE_GAP_S:
                return
            try:
                total, bad = self.source()
            except Exception as e:  # noqa: BLE001 - a scrape never fails on SLO math
                # ...but it must never fail SILENTLY either: count it and
                # keep the last error readable (/fleet/status slo_errors)
                self.last_error = f"{type(e).__name__}: {e}"
                _sample_errors().inc(slo=self.slo)
                return
            self._samples.append((now, float(total), float(bad)))
            horizon = now - self.slow_s * 1.25 - 60.0
            while len(self._samples) > 2 and self._samples[1][0] < horizon:
                self._samples.popleft()

    def _bad_fraction(self, window_s: float) -> float:
        """Bad fraction of the requests that LANDED in the window (0.0
        when none did — an idle window is not an outage)."""
        now = time.monotonic()
        cutoff = now - window_s
        with self._lock:
            if not self._samples:
                return 0.0
            newest = self._samples[-1]
            base = self._samples[0]
            for s in self._samples:
                if s[0] <= cutoff:
                    base = s
                else:
                    break
        d_total = newest[1] - base[1]
        d_bad = newest[2] - base[2]
        if d_total <= 0:
            return 0.0
        return max(0.0, min(1.0, d_bad / d_total))

    def burn_rate(self, window_s: float) -> float:
        self._sample()
        budget = 1.0 - self.objective
        if budget <= 0:
            return 0.0
        return self._bad_fraction(window_s) / budget

    def budget_remaining(self) -> float:
        """Fraction of the slow window's error budget still unspent
        (negative = overspent — the alerting-friendly rendering)."""
        self._sample()
        budget = 1.0 - self.objective
        if budget <= 0:
            return 1.0
        return 1.0 - self._bad_fraction(self.slow_s) / budget


def _sample_errors():
    """The (lazily registered) sample-error counter: one series per SLO
    whose source read raised during a scrape."""
    return get_registry().counter(
        "oryx_slo_sample_errors_total",
        "SLO source reads that raised during burn-rate sampling, by SLO "
        "— a nonzero rate means that SLO's burn math is running on stale "
        "samples (see /fleet/status slo_errors for the last error)",
        labeled=True,
    )


def sample_errors() -> dict[str, str]:
    """slo -> last source-read error string, for every tracker that has
    one (the /fleet/status surface of the error counter)."""
    with _trackers_lock:
        return {
            name: t.last_error
            for name, t in _trackers.items()
            if t.last_error
        }


# -- sources over the existing metric families ------------------------------


def _serving_availability() -> tuple[float, float]:
    c = get_registry().counter("oryx_serving_requests_total")
    total = bad = 0.0
    for key, v in c.series().items():
        total += v
        if dict(key).get("status", "").startswith("5"):
            bad += v
    return total, bad


def _serving_latency(threshold_s: float) -> Callable[[], tuple[float, float]]:
    def read() -> tuple[float, float]:
        h = get_registry().histogram("oryx_serving_request_seconds")
        below, total = h.totals_below(threshold_s)
        return float(total), float(total - below)

    return read


def _front_availability() -> tuple[float, float]:
    c = get_registry().counter("oryx_fleet_front_requests_total")
    total = bad = 0.0
    for key, v in c.series().items():
        total += v
        if dict(key).get("replica") == "none":
            bad += v
    return total, bad


# -- registration -----------------------------------------------------------

_trackers: dict[str, SloTracker] = {}  # guarded-by: _trackers_lock
_trackers_lock = threading.Lock()


def tracker(slo: str) -> SloTracker | None:
    with _trackers_lock:
        return _trackers.get(slo)


def current_burn(slo: str, fast: bool = True) -> float | None:
    """The named SLO's current fast- (or slow-) window burn rate, or None
    when the tracker is not registered in this process. Trackers are
    scrape-driven and sample-gated, so this is cheap enough for gated
    hot-path probes (perfattr's burn-triggered profile capture)."""
    t = tracker(slo)
    if t is None:
        return None
    return t.burn_rate(t.fast_s if fast else t.slow_s)


def burn_snapshot() -> dict[str, dict[str, float]]:
    """slo -> {"fast": burn, "slow": burn} for every tracker registered
    in this process — the /healthz ``slo_burn`` section the fleet front's
    prober copies into /fleet/status, and the evidence block the canary
    gate's promote/rollback flight events carry. Same sample-gated math
    as the oryx_slo_burn_rate gauges, so a scrape and a probe in the
    same instant read one sample."""
    with _trackers_lock:
        items = list(_trackers.items())
    return {
        name: {
            "fast": round(t.burn_rate(t.fast_s), 4),
            "slow": round(t.burn_rate(t.slow_s), 4),
        }
        for name, t in items
    }


def _ensure(
    slo: str,
    objective: float,
    source: Callable[[], tuple[float, float]],
    fast_s: float,
    slow_s: float,
) -> SloTracker:
    reg = get_registry()
    g_burn = reg.gauge(
        "oryx_slo_burn_rate",
        "Error-budget burn rate of a config-declared SLO over its fast/"
        "slow window: observed bad fraction over (1 - objective); 1.0 = "
        "spending the budget exactly at the objective's rate",
        labeled=True,
    )
    g_budget = reg.gauge(
        "oryx_slo_error_budget_remaining",
        "Fraction of the slow window's error budget still unspent for a "
        "config-declared SLO (negative = overspent)",
        labeled=True,
    )
    with _trackers_lock:
        t = _trackers.get(slo)
        if t is None:
            t = SloTracker(slo, objective, source, fast_s, slow_s)
            _trackers[slo] = t
        else:
            t.source = source
            t.reconfigure(objective, fast_s, slow_s)
    # re-binding the same closures over the singleton tracker is harmless
    # and keeps the series alive across registry.clear() in tests
    g_burn.set_function(lambda: t.burn_rate(t.fast_s), slo=slo, window="fast")
    g_burn.set_function(lambda: t.burn_rate(t.slow_s), slo=slo, window="slow")
    g_budget.set_function(lambda: t.budget_remaining(), slo=slo)
    return t


def _windows(config) -> tuple[float, float]:
    fast = config.get_float("oryx.monitoring.slo.fast-window-sec", 300.0)
    slow = config.get_float("oryx.monitoring.slo.slow-window-sec", 3600.0)
    return max(0.001, fast), max(0.001, slow)


def ensure_serving_slos(config) -> None:
    """Register the serving layer's availability + latency SLOs from the
    oryx.monitoring.slo.* keys (called by ServingApp at construction)."""
    if not config.get_bool("oryx.monitoring.slo.enabled", True):
        return
    fast_s, slow_s = _windows(config)
    _ensure(
        "serving-availability",
        config.get_float("oryx.monitoring.slo.availability.objective", 0.999),
        _serving_availability,
        fast_s, slow_s,
    )
    threshold = config.get_float(
        "oryx.monitoring.slo.latency.threshold-sec", 0.25
    )
    _ensure(
        "serving-latency",
        config.get_float("oryx.monitoring.slo.latency.objective", 0.99),
        _serving_latency(threshold),
        fast_s, slow_s,
    )


def _quality_source() -> tuple[float, float]:
    """(shadow samples, samples below the recall floor) — cumulative
    totals the live quality sampler (common/qualitystats.py) counts."""
    reg = get_registry()
    total = sum(reg.counter("oryx_quality_samples_total").series().values())
    bad = sum(
        reg.counter("oryx_quality_bad_samples_total").series().values()
    )
    return total, bad


def ensure_quality_slo(config) -> None:
    """Register the live model-quality SLO (called by the quality
    sampler's configure when shadow sampling is on): a shadow sample is
    bad when its measured recall fell below the configured floor, so the
    burn rate answers "is the served model's live quality degrading
    faster than the objective allows" — the canary gate's quality leg."""
    if not config.get_bool("oryx.monitoring.slo.enabled", True):
        return
    fast_s, slow_s = _windows(config)
    _ensure(
        "quality",
        config.get_float("oryx.monitoring.slo.quality.objective", 0.95),
        _quality_source,
        fast_s, slow_s,
    )


def ensure_front_slos(config) -> None:
    """Register the fleet front's availability SLO (called by FleetFront
    at construction): a request is bad when no replica answered it."""
    if not config.get_bool("oryx.monitoring.slo.enabled", True):
        return
    fast_s, slow_s = _windows(config)
    _ensure(
        "front-availability",
        config.get_float("oryx.monitoring.slo.availability.objective", 0.999),
        _front_availability,
        fast_s, slow_s,
    )
