"""Device-placement checker (rule ``device-placement``).

Two placement invariants PR 11's review pass enforced by hand, now
machine-checked:

1. **Committed placement for long-lived stores.** A bare
   ``jax.device_put(x)`` — no explicit device/sharding argument — leaves
   the buffer *uncommitted*: it follows the current default device, and
   the first computation touching it silently migrates the whole array
   to device 0 (``jax.default_device`` context blocks do NOT commit).
   For a transient temp that is at worst a perf wobble; for a value
   stored into long-lived serving state (an attribute on a serving
   view, a ``ShardedMatrix`` field) it re-creates the multi-chip OOM
   sharding exists to prevent. The rule: an uncommitted ``device_put``
   result must not flow — through local assignments or confidently
   resolved helper returns — into an attribute store or a
   ``ShardedMatrix(...)`` construction.

2. **mesh / shard_mesh exclusivity at train call sites.** ``train_als``
   raises loudly at runtime when both are passed (the PR 11 hardening);
   the rule catches it before runtime at any ``train_als``-family call
   site where both keywords are *definitely constructed* values (a
   ``Call`` expression, or a local assigned from one). Wrapper
   forwarding — both values are bare parameters of the enclosing
   function, exclusivity being the outer caller's obligation — is
   exempt and checked at the outer site instead.
"""

from __future__ import annotations

import ast

from tools.oryxlint.callgraph import FunctionInfo, ProjectIndex, shared_index
from tools.oryxlint.core import Checker, Finding, Project

LONG_LIVED_CTORS = frozenset({"ShardedMatrix"})
TRAIN_FAMILY_PREFIX = "train_als"


def _is_put_name(dotted: str | None, bare: str | None) -> bool:
    if dotted is not None and (
        dotted == "jax.device_put" or dotted.endswith(".device_put")
    ):
        return True
    return bare == "device_put"


def _committed(call: ast.Call) -> bool:
    """An explicit placement argument commits the buffers."""
    if len(call.args) >= 2:
        return True
    return any(kw.arg in ("device", "sharding") for kw in call.keywords)


class PlacementChecker(Checker):
    name = "placement"
    rules = {
        "device-placement": (
            "an uncommitted device_put result (no explicit device/"
            "sharding arg) flows into long-lived state, or mesh and "
            "shard_mesh both reach the same train_als-family call site"
        ),
    }
    severities = {"device-placement": "error"}
    fix_hints = {
        "device-placement": (
            "pass the device/sharding explicitly to device_put (a "
            "default_device context does not commit), or drop one of "
            "mesh/shard_mesh at the train call"
        ),
    }

    def check(self, project: Project) -> list[Finding]:
        idx = shared_index(project)
        findings: list[Finding] = []
        returns_uncommitted = self._summarize_returns(idx)
        for fi in idx.functions:
            self._check_stores(idx, fi, returns_uncommitted, findings)
            self._check_train_calls(idx, fi, findings)
        return findings

    # -- rule 1: uncommitted puts into long-lived stores ----------------------

    def _uncommitted_call(
        self, idx: ProjectIndex, fi: FunctionInfo, node: ast.AST,
        returns_uncommitted: set[int],
    ) -> bool:
        if not isinstance(node, ast.Call):
            return False
        dotted = idx.dotted_name(fi.module, node.func)
        bare = node.func.id if isinstance(node.func, ast.Name) else None
        if _is_put_name(dotted, bare):
            return not _committed(node)
        # a confidently-resolved helper that returns an uncommitted put
        for tgt in idx.resolve_call(fi, node):
            if id(tgt) in returns_uncommitted:
                return True
        return False

    def _summarize_returns(self, idx: ProjectIndex) -> set[int]:
        """ids of FunctionInfos that return an uncommitted device_put
        result (directly, or via a local). One fixed-point round over
        direct returns, then one propagation round through call chains."""
        out: set[int] = set()
        for _ in range(3):  # direct + two levels of helper chaining
            changed = False
            for fi in idx.functions:
                if id(fi) in out:
                    continue
                tainted = self._local_tainted(idx, fi, out)
                for node in ast.walk(fi.node):
                    if isinstance(node, ast.Return) and node.value is not None:
                        v = node.value
                        if self._uncommitted_call(idx, fi, v, out) or (
                            isinstance(v, ast.Name) and v.id in tainted
                        ):
                            out.add(id(fi))
                            changed = True
                            break
            if not changed:
                break
        return out

    def _local_tainted(
        self, idx: ProjectIndex, fi: FunctionInfo, returns_uncommitted: set[int]
    ) -> set[str]:
        """Local names bound to uncommitted put results (fixed-point over
        plain Name-to-Name copies)."""
        tainted: set[str] = set()
        for _ in range(3):
            changed = False
            for node in ast.walk(fi.node):
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                ):
                    continue
                name = node.targets[0].id
                if name in tainted:
                    continue
                v = node.value
                if self._uncommitted_call(idx, fi, v, returns_uncommitted) or (
                    isinstance(v, ast.Name) and v.id in tainted
                ):
                    tainted.add(name)
                    changed = True
            if not changed:
                break
        return tainted

    def _check_stores(
        self, idx: ProjectIndex, fi: FunctionInfo,
        returns_uncommitted: set[int], findings: list[Finding],
    ) -> None:
        mod = fi.module
        tainted = self._local_tainted(idx, fi, returns_uncommitted)

        def is_uncommitted_value(v: ast.AST) -> bool:
            return self._uncommitted_call(idx, fi, v, returns_uncommitted) or (
                isinstance(v, ast.Name) and v.id in tainted
            )

        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and is_uncommitted_value(
                        node.value
                    ):
                        findings.append(Finding(
                            mod.relpath, node.lineno, "device-placement",
                            "uncommitted device_put result stored into "
                            f"long-lived attribute {ast.unparse(t)}: without "
                            "an explicit device/sharding argument the first "
                            "computation silently migrates the buffer to the "
                            "default device (a default_device context does "
                            "not commit)",
                        ))
            elif isinstance(node, ast.Call):
                fname = node.func.id if isinstance(node.func, ast.Name) else (
                    node.func.attr if isinstance(node.func, ast.Attribute)
                    else None
                )
                if fname in LONG_LIVED_CTORS:
                    for a in node.args:
                        vals = a.elts if isinstance(a, (ast.List, ast.Tuple)) \
                            else [a]
                        for v in vals:
                            if is_uncommitted_value(v):
                                findings.append(Finding(
                                    mod.relpath, node.lineno,
                                    "device-placement",
                                    "uncommitted device_put result becomes "
                                    f"a {fname} shard: every shard must be "
                                    "committed to its own device or the "
                                    "whole matrix migrates to device 0 on "
                                    "first use",
                                ))

    # -- rule 2: mesh + shard_mesh at the same train call ---------------------

    def _check_train_calls(
        self, idx: ProjectIndex, fi: FunctionInfo, findings: list[Finding]
    ) -> None:
        mod = fi.module
        params = {
            a.arg for a in (
                list(fi.node.args.args) + list(fi.node.args.kwonlyargs)
                + list(fi.node.args.posonlyargs)
            )
        }
        # local names assigned from constructed (Call) values
        constructed: set[str] = set()
        for node in ast.walk(fi.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                constructed.add(node.targets[0].id)

        def definitely_set(v: ast.AST) -> bool:
            if isinstance(v, ast.Constant):  # None / literals
                return False
            if isinstance(v, ast.IfExp) and (
                (isinstance(v.body, ast.Constant) and v.body.value is None)
                or (
                    isinstance(v.orelse, ast.Constant)
                    and v.orelse.value is None
                )
            ):
                # the conditional-exclusivity idiom:
                # `mesh=None if shard_mesh is not None else build()`
                return False
            if isinstance(v, ast.Call):
                return True
            if isinstance(v, ast.Name):
                # bare parameter forwarding: the wrapper inherits the
                # exclusivity obligation; checked at the outer site
                return v.id in constructed and v.id not in params
            return True  # attribute reads etc.: assume live

        for call in ast.walk(fi.node):
            if not isinstance(call, ast.Call):
                continue
            fname = call.func.id if isinstance(call.func, ast.Name) else (
                call.func.attr if isinstance(call.func, ast.Attribute)
                else None
            )
            if fname is None or not fname.startswith(TRAIN_FAMILY_PREFIX):
                continue
            kw = {k.arg: k.value for k in call.keywords if k.arg}
            if "mesh" in kw and "shard_mesh" in kw and (
                definitely_set(kw["mesh"]) and definitely_set(kw["shard_mesh"])
            ):
                findings.append(Finding(
                    mod.relpath, call.lineno, "device-placement",
                    f"mesh and shard_mesh both reach {fname}() here: the "
                    "layouts are mutually exclusive (train_als raises at "
                    "runtime; pick the tensor-parallel mesh OR the "
                    "row-sharded model mesh)",
                ))
