"""Resources every app shares: /ready, /healthz, /ingest, /metrics,
/debug/traces.

Mirrors the reference's Ready.java:33-46 (GET/HEAD 200-or-503 on model
load fraction) and Ingest.java (bulk lines -> input topic, gzip-aware via
the server's request decoding), plus the observability endpoints the
reference never had: Prometheus /metrics, a /healthz liveness probe
(distinct from /ready readiness), and the /debug/traces span lens
(common/tracing.py).
"""

from __future__ import annotations

import json
import time

from oryx_tpu.common.metrics import get_registry
from oryx_tpu.common.tracing import chrome_trace, get_tracer, span_forest
from oryx_tpu.serving.app import OryxServingException, RawResponse, Request, ServingApp


def _ingest_text(req: Request) -> str:
    """Body text for /ingest: plain text (frontends already undo
    Content-Encoding: gzip), or every file part of a multipart/form-data
    upload — parity with the reference's AbstractOryxResource
    maybeBuffer/maybeDecompress upload handling, which accepts browser
    form posts of (optionally gzipped) data files."""
    ctype = req.headers.get("content-type", "")
    if not ctype.lower().startswith("multipart/form-data"):
        return req.body_text()
    import gzip
    from email import policy
    from email.parser import BytesParser

    # reuse the stdlib MIME parser by re-wrapping the body with its header
    raw = (f"Content-Type: {ctype}\r\n\r\n").encode("latin-1") + req.body
    msg = BytesParser(policy=policy.default).parsebytes(raw)
    parts = []
    for part in msg.iter_parts():
        name = (part.get_filename() or "").lower()
        if not name:
            # ordinary form fields (hidden tokens, submit values) are not
            # data: only FILE parts ingest, like the reference's FileItem
            # handling
            continue
        payload = part.get_payload(decode=True)
        if payload is None:
            continue
        if name.endswith(".gz") or payload[:2] == b"\x1f\x8b":
            import zlib

            try:
                payload = gzip.decompress(payload)
            except (OSError, EOFError, zlib.error):
                # OSError: bad magic; EOFError: truncated; zlib.error:
                # corrupt deflate stream
                raise OryxServingException(400, f"bad gzip upload: {name}")
        parts.append(payload.decode("utf-8", errors="replace"))
    if not parts:
        raise OryxServingException(400, "no file parts in multipart upload")
    return "\n".join(parts)


def send_input_lines(
    app: ServingApp, text: str, what: str = "data points", required: bool = True
) -> int:
    """Bulk lines -> input topic; 400 when nothing usable was given (unless
    required=False — the wordcount /add treats an empty flush as a no-op).
    The one implementation behind /ingest, /add, and /train."""
    n = 0
    for line in text.splitlines():
        line = line.strip()
        if line:
            app.send_input(line)
            n += 1
    if n == 0 and required:
        raise OryxServingException(400, f"no {what} given")
    return n


def register(app: ServingApp) -> None:
    @app.route("GET", "/ready", nonblocking=True)
    def ready(a: ServingApp, req: Request):
        a.get_serving_model()  # raises 503 if not ready
        return 200, {"ready": True}

    @app.route("HEAD", "/ready", nonblocking=True)
    def ready_head(a: ServingApp, req: Request):
        a.get_serving_model()
        return 200, None

    @app.route("GET", "/healthz", nonblocking=True)
    def healthz(a: ServingApp, req: Request):
        """Health probe reporting uptime, event-loop fan-out, and the
        generation id of the model being served (from the update topic's
        publish stamps). GET doubles as the DEGRADED-readiness surface:
        503 + reasons when the served model is past its staleness bound
        (oryx.serving.api.max-staleness-sec), top-k scoring has failed
        over to the host path, or a co-resident layer's wedge watchdog
        tripped — conditions a log line can't route to a load balancer.
        HEAD stays pure liveness (200 whenever the frontend dispatches),
        so probes choose their semantics by method."""
        from oryx_tpu.common.freshness import model_freshness

        degraded = a.degraded_reasons()
        body = {
            "status": "degraded" if degraded else "up",
            "degraded": degraded,
            "uptime_seconds": round(time.monotonic() - a.started_at, 3),
            "loops": a.loop_count,
            "model_generation": model_freshness().generation,
        }
        # fleet surface: name this process (the front's ejection log and
        # oryx_fleet_replica_* labels come straight from here) and carry
        # the per-replica freshness/perf numbers the front aggregates
        if a.replica_id:
            body["replica"] = a.replica_id
        if a.listen_port:
            body["port"] = a.listen_port
        shard_count = a.config.get_int("oryx.serving.api.sync.shard-count", 1)
        if shard_count > 1:
            # shard topology surface: the fleet front compares this
            # against its expected shards-per-replica and treats a
            # mis-sharded replica (restarted with stale config, about to
            # overrun one chip's HBM) as degraded. oryxlint's
            # shard-topology rule pins this field to the shard-count
            # read above — removing either leg alone fails tier-1.
            body["shards"] = shard_count
        age = a.staleness_age()
        if age is not None:
            body["staleness_seconds"] = round(age, 3)
        if a.update_lag_fn is not None:
            try:
                body["update_lag"] = int(a.update_lag_fn())
            except Exception:  # noqa: BLE001 - a probe never 500s on lag
                pass
        try:
            import math

            from oryx_tpu.common.perfstats import get_perfstats

            mfu = get_perfstats().mfu("serving")
            if not math.isnan(mfu):
                body["mfu"] = round(mfu, 6)
            # rolling-window dispatch occupancy: the fleet autoscaler's
            # scale-down evidence (sustained low occupancy = padding
            # headroom mostly waste), probed per replica off /healthz
            occ, n_disp = get_perfstats().window_occupancy("serving")
            if occ is not None:
                body["occupancy"] = {
                    "mean": round(occ, 4), "dispatches": n_disp,
                }
        except Exception:  # noqa: BLE001 - perf accounting is optional
            pass
        try:
            from oryx_tpu.common.qualitystats import get_qualitystats

            # live quality scorecard: windowed shadow-rescore recall,
            # sample/drop accounting, the served generation's stamped
            # eval metrics, and drift vs its training profile — the
            # fleet front's prober copies this into /fleet/status
            body["quality"] = get_qualitystats().healthz_section()
        except Exception:  # noqa: BLE001 - a probe never 500s on quality
            pass
        try:
            from oryx_tpu.common import slo

            # SLO source reads that raised in THIS process (slo -> last
            # error): federated per replica into /fleet/status so broken
            # burn math is visible fleet-wide, not just on the front
            errs = slo.sample_errors()
            if errs:
                body["slo_errors"] = errs
            # per-SLO fast/slow burn rates: the canary gate's promotion
            # evidence, read per replica by the fleet controller so a
            # canary's burn is judged against ITS traffic, not the
            # fleet-merged /metrics view
            burn = slo.burn_snapshot()
            if burn:
                body["slo_burn"] = burn
        except Exception:  # noqa: BLE001 - a probe never 500s on slo state
            pass
        try:
            from oryx_tpu.common.modelgate import get_model_gate

            # staged-adoption state (mode, watermark, held generation,
            # adoption history): how the controller sees whether a
            # canary adopted the new generation and a hold replica is
            # still pinning the incumbent
            gate = get_model_gate()
            if gate.active:
                body["model_gate"] = gate.healthz_section()
        except Exception:  # noqa: BLE001 - a probe never 500s on gate state
            pass
        try:
            from oryx_tpu.common.perfattr import get_perfattr

            # live latency budget: per-phase p50/p99/share over the
            # rolling window plus ranked idle-gap causes — the fleet
            # front's prober copies this into /fleet/status, and `oryx
            # perf` renders the same shape from /metrics
            body["latency_budget"] = get_perfattr().healthz_section()
        except Exception:  # noqa: BLE001 - a probe never 500s on perfattr
            pass
        # up->degraded edge: the first degraded probe snapshots the
        # flight recorder's black box off-thread (app.py note_health_state)
        a.note_health_state(bool(degraded), degraded)
        return (503 if degraded else 200), body

    @app.route("HEAD", "/healthz", nonblocking=True)
    def healthz_head(a: ServingApp, req: Request):
        return 200, None

    @app.route("POST", "/ingest")
    def ingest(a: ServingApp, req: Request):
        n = send_input_lines(a, _ingest_text(req), "ingest body")
        return 200, {"ingested": n}

    # model-gate control plane (fleet/control.py drives these; an
    # operator can too — docs/operations.md "Canary rollout & rollback").
    # Deliberately exempt from the app's read-only mode: they mutate
    # which already-published model serves, never application data.
    @app.route("POST", "/control/model/approve")
    def model_approve(a: ServingApp, req: Request):
        """Raise the gate's approved watermark to the given generation; a
        held generation at/under it is adopted before the response
        returns. 409 while the gate is off."""
        from oryx_tpu.common.modelgate import ModelGateError, get_model_gate

        try:
            doc = json.loads(req.body_text() or "{}")
            generation = int(doc["generation"])
        except (json.JSONDecodeError, KeyError, TypeError, ValueError):
            raise OryxServingException(
                400, 'body must be JSON {"generation": <int>}'
            )
        try:
            return 200, get_model_gate().approve(generation)
        except ModelGateError as e:
            raise OryxServingException(409, str(e))

    @app.route("POST", "/control/model/rollback")
    def model_rollback(a: ServingApp, req: Request):
        """Re-apply the previously adopted generation (pointer swap from
        the pinned relay cache) and veto the current one. 409 while the
        gate is off or holds no previous generation."""
        from oryx_tpu.common.modelgate import ModelGateError, get_model_gate

        try:
            doc = json.loads(req.body_text() or "{}")
        except json.JSONDecodeError:
            doc = {}
        reason = doc.get("reason") if isinstance(doc, dict) else None
        try:
            return 200, get_model_gate().rollback(
                reason=str(reason) if reason else None
            )
        except ModelGateError as e:
            raise OryxServingException(409, str(e))

    # NOT nonblocking: serializing a full ring (thousands of spans) on an
    # event loop would stall that loop's other connections
    @app.route("GET", "/debug/traces")
    def debug_traces(a: ServingApp, req: Request):
        """Recent finished spans from the process ring buffer as a span
        forest (default) or Chrome trace-event JSON (?format=chrome —
        opens directly in Perfetto, alongside maybe_profile TPU traces).
        ?limit=N keeps only the newest N spans. Empty until
        oryx.monitoring.tracing.enabled = true."""
        tr = get_tracer()
        spans = tr.snapshot()
        try:
            limit = int(req.q1("limit", "0") or 0)
        except ValueError:
            raise OryxServingException(400, "bad limit")
        if limit > 0:
            spans = spans[-limit:]
        if req.q1("format") == "chrome":
            body = json.dumps(chrome_trace(spans), default=str)
        else:
            body = json.dumps(
                {
                    "enabled": tr.enabled,
                    "capacity": tr.capacity,
                    "spans": len(spans),
                    "traces": span_forest(spans),
                },
                default=str,
            )
        return RawResponse(200, body.encode("utf-8"), "application/json")

    # NOT nonblocking: bundling renders the whole metrics page and writes
    # the artifact to disk — worker-thread work, never an event loop's
    @app.route("GET", "/debug/flight")
    def debug_flight(a: ServingApp, req: Request):
        """On-demand flight-recorder snapshot (common/flightrec.py): the
        recent lifecycle-event ring, finished tracing spans, the
        perfstats dispatch ring, a /metrics snapshot, and the config
        fingerprint as ONE downloadable artifact — the same bundle a
        healthz up→degraded transition writes automatically and the
        fleet supervisor harvests from a corpse. 403 when the recorder
        is disabled (oryx.monitoring.flight.enabled = false)."""
        from oryx_tpu.common.flightrec import get_flightrec

        rec = get_flightrec()
        if not rec.enabled:
            raise OryxServingException(
                403, "flight recorder disabled (oryx.monitoring.flight.enabled)"
            )
        bundle, path = rec.snapshot("debug-endpoint")
        if path:
            req.response_headers.append((
                "Content-Disposition",
                f'attachment; filename="{path.rsplit("/", 1)[-1]}"',
            ))
        return RawResponse(
            200, json.dumps(bundle, default=str).encode("utf-8"),
            "application/json",
        )

    # NOT nonblocking: the handler sleeps for the capture window — that
    # must park a worker thread, never an event loop
    @app.route("GET", "/debug/profile")
    def debug_profile(a: ServingApp, req: Request):
        """On-demand performance capture: blocks for ?seconds=N (clamped
        to oryx.monitoring.profile.max-seconds) recording every device
        dispatch's cost (common/perfstats.py) — plus finished tracing
        spans, and a jax.profiler device trace into
        oryx.monitoring.profile.dir when configured — and returns the
        window as a downloadable Perfetto-loadable Chrome trace-event
        artifact with an `oryx` summary block (per-kind FLOPs, bytes,
        occupancy, window MFU). 403 until
        oryx.monitoring.profile.enabled = true; 409 while another capture
        holds the (process-global) jax profiler."""
        from oryx_tpu.common.perfstats import get_perfstats

        ps = get_perfstats()
        if not ps.profile_enabled:
            raise OryxServingException(
                403, "profiling disabled (set oryx.monitoring.profile.enabled)"
            )
        try:
            seconds = float(req.q1("seconds", "1") or 1.0)
        except ValueError:
            raise OryxServingException(400, "bad seconds")
        seconds = max(0.0, min(seconds, ps.profile_max_seconds))
        try:
            artifact = ps.capture_profile(seconds)
        except RuntimeError as e:
            raise OryxServingException(409, str(e))
        req.response_headers.append((
            "Content-Disposition",
            f'attachment; filename="oryx-profile-{int(time.time())}.json"',
        ))
        return RawResponse(
            200, json.dumps(artifact).encode("utf-8"), "application/json"
        )

    if app.config.get_bool("oryx.monitoring.metrics", True):

        from oryx_tpu.serving.batcher import TopKBatcher

        # live callback gauges: scrapes read the batcher's counters (incl.
        # the wedged-device failover state) without per-scrape mutation
        TopKBatcher.shared().register_gauges()

        @app.route("GET", "/metrics")
        def metrics(a: ServingApp, req: Request):
            """Prometheus text exposition; a scraper that negotiates
            `Accept: application/openmetrics-text` gets the OpenMetrics
            dialect instead, which is the ONLY format exemplars
            (metric→trace joins, docs/observability.md) may legally ride
            — emitting them into classic text would fail legacy
            parsers on the whole scrape."""
            wants_om = "application/openmetrics-text" in req.headers.get(
                "accept", ""
            )
            text = get_registry().render_prometheus(openmetrics=wants_om)
            ctype = (
                "application/openmetrics-text; version=1.0.0; charset=utf-8"
                if wants_om else "text/plain; version=0.0.4"
            )
            return RawResponse(200, text.encode("utf-8"), ctype)

    @app.route("GET", "/console")
    def console(a: ServingApp, req: Request):
        """Human status page (the reference serves an HTML console per app,
        e.g. .../als/Console.java): model state, app-specific sections
        registered via app.console_sections, and the route table."""
        import html as _html

        model = a.model_manager.get_model()
        frac = model.fraction_loaded() if model is not None else 0.0
        manager = _html.escape(type(a.model_manager).__name__)
        ctx = a.context_path  # links must stay inside the mount

        def table(pairs) -> str:
            return "<table>" + "".join(
                f"<tr><td>{_html.escape(str(k))}</td>"
                f"<td>{_html.escape(str(v))}</td></tr>"
                for k, v in pairs
            ) + "</table>"

        sections = []
        for title, fn in a.console_sections:
            try:
                pairs = fn(a)
            except OryxServingException:
                pairs = [("status", "model not yet available")]
            except Exception as e:  # noqa: BLE001 - console must render
                pairs = [("error", f"{type(e).__name__}: {e}")]
            sections.append(f"<h2>{_html.escape(title)}</h2>{table(pairs)}")

        rows = "".join(
            f"<tr><td>{_html.escape(r.method)}</td>"
            f"<td><code>{_html.escape(r.pattern.pattern)}</code></td></tr>"
            for r in sorted(a.routes, key=lambda r: (r.pattern.pattern, r.method))
        )
        html = (
            "<!doctype html><html><head><title>Oryx TPU Serving</title>"
            "<style>body{font-family:sans-serif;margin:2em}table{border-collapse:"
            "collapse}td,th{border:1px solid #ccc;padding:4px 8px}</style></head>"
            f"<body><h1>Oryx TPU serving console</h1>"
            f"<p>Model manager: <b>{manager}</b></p>"
            f"<p>Model loaded: <b>{frac:.0%}</b>"
            f"{' (serving)' if frac >= a.min_fraction else ' (warming up)'}</p>"
            f"<p><a href='{ctx}/metrics'>metrics</a> &middot; "
            f"<a href='{ctx}/ready'>ready</a></p>"
            f"{''.join(sections)}"
            f"<h2>Endpoints</h2><table><tr><th>method</th><th>path</th></tr>"
            f"{rows}</table></body></html>"
        )
        return RawResponse(200, html.encode("utf-8"), "text/html; charset=utf-8")
