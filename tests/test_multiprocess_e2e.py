"""Three-OS-process lambda deployment over a file:// broker — the real
deployment topology (the reference runs batch/speed/serving as separate
JVMs wired only by Kafka; AbstractLambdaIT boots real services the same
way). Includes a serving-process kill -9 + restart asserting model recovery
via earliest-replay of the update topic (ModelManagerListener.java:118-132).
"""

import json
import pathlib
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from oryx_tpu.bus.broker import get_broker
from oryx_tpu.common.ioutil import choose_free_port

REPO = str(pathlib.Path(__file__).resolve().parent.parent)


def _http(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _spawn(cmd_flags):
    return subprocess.Popen(
        [sys.executable, "-m", "oryx_tpu.cli", *cmd_flags],
        cwd=REPO,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        start_new_session=True,
    )


def _dead(proc, name):
    if proc.poll() is not None:
        raise AssertionError(
            f"{name} process died rc={proc.returncode}: "
            + proc.stderr.read().decode()[-2000:]
        )


@pytest.mark.slow
def test_three_process_lambda_with_serving_crash_recovery(tmp_path):
    bus = f"file://{tmp_path}/bus"
    port = choose_free_port()
    sets = [
        "oryx.id=mp",
        f"oryx.input-topic.broker={bus}",
        f"oryx.update-topic.broker={bus}",
        f"oryx.batch.storage.data-dir={tmp_path}/data",
        f"oryx.batch.storage.model-dir={tmp_path}/model",
        f"oryx.serving.api.port={port}",
        "oryx.batch.streaming.generation-interval-sec=2",
        "oryx.speed.streaming.generation-interval-sec=1",
        "oryx.batch.update-class=oryx_tpu.apps.als.batch.ALSUpdate",
        "oryx.speed.model-manager-class=oryx_tpu.apps.als.speed.ALSSpeedModelManager",
        "oryx.serving.model-manager-class=oryx_tpu.apps.als.serving.ALSServingModelManager",
        'oryx.serving.application-resources='
        '["oryx_tpu.serving.resources.common","oryx_tpu.serving.resources.als"]',
        "oryx.als.hyperparams.features=4",
        "oryx.als.hyperparams.iterations=4",
        "oryx.ml.eval.test-fraction=0.1",
        "oryx.speed.min-model-load-fraction=0.8",
        "oryx.serving.min-model-load-fraction=0.8",
    ]
    flags = [x for s in sets for x in ("--set", s)]

    setup = subprocess.run(
        [sys.executable, "-m", "oryx_tpu.cli", "setup", *flags],
        cwd=REPO, capture_output=True, timeout=60,
    )
    assert setup.returncode == 0, setup.stderr.decode()

    broker = get_broker(bus)
    procs: dict[str, subprocess.Popen] = {}
    try:
        # ---- 1. batch + speed + serving as real processes ----
        procs["batch"] = _spawn(["batch", *flags])
        procs["speed"] = _spawn(["speed", *flags])
        procs["serving"] = _spawn(["serving", *flags])

        # wait until the batch consumer group pinned its start position —
        # input sent before that would be after its "latest" start point
        deadline = time.time() + 60
        while time.time() < deadline:
            _dead(procs["batch"], "batch")
            if broker.get_offsets("OryxGroup-mp-batch", "OryxInput"):
                break
            time.sleep(0.3)
        else:
            raise AssertionError("batch layer never pinned start offsets")

        # ---- 2. feed interactions through the input topic ----
        rng = np.random.default_rng(1)
        lines = []
        for u in range(30):
            for i in rng.choice(20, 5, replace=False):
                lines.append(f"u{u},i{i},1,{1000 + int(i)}")
        pump = subprocess.run(
            [sys.executable, "-m", "oryx_tpu.cli", "input", *flags],
            cwd=REPO, input="\n".join(lines).encode(),
            capture_output=True, timeout=60,
        )
        assert pump.returncode == 0, pump.stderr.decode()

        # ---- 3. serving becomes ready from the batch-published model ----
        base = f"http://127.0.0.1:{port}"
        deadline = time.time() + 90
        status = None
        while time.time() < deadline:
            for name in ("batch", "speed", "serving"):
                _dead(procs[name], name)
            try:
                status, _ = _http(f"{base}/ready")
                if status == 200:
                    break
            except Exception:
                pass
            time.sleep(0.5)
        assert status == 200, "serving never became ready"

        status, body = _http(f"{base}/recommend/u5?howMany=3")
        assert status == 200, body
        first_recs = json.loads(body)
        assert len(first_recs) == 3

        # ---- 4. speed layer folds a brand-new user in ----
        status, _ = _http_post(f"{base}/pref/brandnew/i3", b"5.0")
        assert status == 200
        status, _ = _http_post(f"{base}/pref/brandnew/i7", b"5.0")
        assert status == 200
        deadline = time.time() + 60
        got = None
        while time.time() < deadline:
            _dead(procs["speed"], "speed")
            status, body = _http(f"{base}/recommend/brandnew?howMany=3")
            if status == 200:
                got = json.loads(body)
                break
            time.sleep(0.5)
        assert got is not None, "speed fold-in never reached serving"

        # ---- 5. kill -9 serving mid-stream; restart; model recovers ----
        proc = procs.pop("serving")
        import os

        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
        procs["serving"] = _spawn(["serving", *flags])
        deadline = time.time() + 90
        status = None
        while time.time() < deadline:
            _dead(procs["serving"], "serving")
            try:
                status, _ = _http(f"{base}/ready")
                if status == 200:
                    break
            except Exception:
                pass
            time.sleep(0.5)
        assert status == 200, "restarted serving never recovered the model"
        # recovered model answers queries again, incl. the folded-in user
        status, body = _http(f"{base}/recommend/u5?howMany=3")
        assert status == 200 and len(json.loads(body)) == 3
        status, body = _http(f"{base}/recommend/brandnew?howMany=3")
        assert status == 200, "earliest-replay lost the speed-layer update"
    finally:
        import os

        for name, proc in procs.items():
            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
        for name, proc in procs.items():
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                os.killpg(proc.pid, signal.SIGKILL)
                proc.wait(timeout=5)


def _http_post(url, body, timeout=10):
    req = urllib.request.Request(url, method="POST", data=body)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_batch_build_killed_and_resumed(tmp_path):
    """A batch build hard-killed mid-training (SIGKILL-equivalent process
    exit between checkpoint writes) resumes from the last checkpointed
    sweep in a fresh process — config-driven, through ALSUpdate."""
    import os

    import numpy as np

    worker = """
import sys, os, logging
logging.basicConfig(level=logging.INFO, stream=sys.stderr)
sys.path.insert(0, sys.argv[3])
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
from oryx_tpu.common.config import load_config
from oryx_tpu.common.rng import RandomManager
from oryx_tpu.apps.als.batch import ALSUpdate
from oryx_tpu.bus.api import KeyMessage
td = sys.argv[1]
cfg = load_config(overlay={
    "oryx.batch.storage.model-dir": td + "/models",
    "oryx.als.hyperparams.features": 8,
    "oryx.als.hyperparams.iterations": 6,
    "oryx.als.checkpoint-interval": 2,
    "oryx.ml.eval.test-fraction": 0.0,
})
RandomManager.use_test_seed(77)
rng = np.random.default_rng(1)
lines = [KeyMessage(None, f"u{u},i{i},1,{j}") for j, (u, i) in enumerate(
    zip(rng.integers(0, 200, 8000), rng.integers(0, 150, 8000)))]
upd = ALSUpdate(cfg, mesh=None)
if sys.argv[2] == "abort":
    import oryx_tpu.ops.als as als
    orig = als.train_als
    calls = {"n": 0}
    def wrapped(*a, **k):
        m = orig(*a, **k)
        calls["n"] += 1
        if calls["n"] == 2:
            os._exit(9)  # die between chunk 2's compute and its checkpoint
        return m
    als.train_als = wrapped
art = upd.build_model(lines, {"features": 8, "lambda": 0.001, "alpha": 1.0})
print("BUILD_OK", art.tensors["X"].shape, flush=True)
"""
    root = str(REPO)
    from oryx_tpu.common.executil import cpu_subprocess_env

    env = cpu_subprocess_env()
    p1 = subprocess.run(
        [sys.executable, "-c", worker, str(tmp_path), "abort", root],
        env=env, capture_output=True, text=True, timeout=150,
    )
    assert p1.returncode == 9, (p1.returncode, p1.stderr[-500:])
    ck = tmp_path / "models" / ".als-checkpoint"
    cks = list(ck.rglob("als-train.ckpt.npz"))
    assert cks, "no checkpoint left behind by the killed build"
    with np.load(cks[0]) as z:
        assert int(z["done"]) == 2

    p2 = subprocess.run(
        [sys.executable, "-c", worker, str(tmp_path), "run", root],
        env=env, capture_output=True, text=True, timeout=150,
    )
    assert p2.returncode == 0 and "BUILD_OK" in p2.stdout, p2.stderr[-500:]
    assert "resuming ALS build from checkpoint: 2/6" in p2.stderr, p2.stderr[-500:]
    assert not list(ck.rglob("als-train.ckpt.npz"))  # consumed on success
