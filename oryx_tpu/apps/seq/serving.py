"""Seq serving tier: GRU session encoder + top-k over item embeddings.

The request path is the ALS shape on purpose: encode the session's item
history into a hidden state (the "user vector"), then score the whole
catalog with ONE matmul + top-k through the shared micro-batcher
(serving/batcher.py) — so coalesced dispatch, shedding, host fallback,
and perfstats MFU all apply unchanged. The device view is a
capacity-padded bf16 matrix kept in step with the live FactorStore by
dirty-row deltas (PR 3's delta_since + scatter_rows): a speed-layer UP
storm re-uploads only the touched rows, and growth within the headroom
scatters into reserved padding rows without changing the batcher's
compiled dispatch shape.
"""

from __future__ import annotations

import logging
import threading
from concurrent.futures import Future

import numpy as np

import jax.numpy as jnp

from oryx_tpu.api import AbstractServingModelManager, ServingModel
from oryx_tpu.common.config import Config
from oryx_tpu.serving.app import chain_future, configure_post_pool, post_pool
from oryx_tpu.serving.batcher import TopKBatcher
from oryx_tpu.apps.seq.common import SeqConfig
from oryx_tpu.apps.seq.state import SeqState, apply_seq_update
from oryx_tpu.ops.seq import encode_sessions

log = logging.getLogger(__name__)


class SeqServingModel(ServingModel):
    def __init__(self, state: SeqState, sync=None):
        from oryx_tpu.apps.als.serving import SyncConfig

        self.state = state
        self.sync = sync or SyncConfig()
        self._sync_lock = threading.Lock()
        # (device E [capacity,d] bf16, ids [n], version, host f32 mirror)
        # swapped as ONE tuple — readers take the snapshot lock-free
        self._device_view: tuple | None = None

    def fraction_loaded(self) -> float:
        return self.state.fraction_loaded()

    def served_version(self) -> int | None:
        view = self._device_view
        return None if view is None else view[2]

    # -- device view (FactorStore delta sync) ------------------------------

    def _view(self) -> tuple:
        view = self._device_view
        if view is not None and view[2] == self.state.items.get_version():
            return view
        with self._sync_lock:
            view = self._device_view
            if view is not None and view[2] == self.state.items.get_version():
                return view
            if view is not None and self._try_apply_delta(view):
                return self._device_view
            return self._build_view_full()

    def _try_apply_delta(self, view: tuple) -> bool:
        """Catch the device view up by dirty-row scatter. Call under
        _sync_lock. Returns False when only a full rebuild can serve
        (drift overflow, growth past capacity, arena compaction after a
        model swap). NOT donated: in-flight coalesced dispatches still
        score the old buffer — the functional scatter IS the double
        buffer (ops/transfer.py scatter_rows contract)."""
        from oryx_tpu.ops.transfer import (
            ShardedMatrix, scatter_rows, scatter_transfer_bytes,
        )
        from oryx_tpu.serving.viewsync import (
            extend_view_ids, note_sync_bytes, set_shard_rows,
            sharded_delta_bytes, view_sync_metrics,
        )
        import time as _time

        t0 = _time.monotonic()
        y_dev, ids, _version, host_mat = view
        n_old = len(ids)
        capacity = int(host_mat.shape[0])
        delta = self.state.items.delta_since(
            view[2],
            max_rows=max(1, int(self.sync.max_delta_fraction * max(n_old, 1))),
        )
        if delta is None or delta.n > capacity:
            return False
        if delta.rows.size == 0:
            return True
        ids = extend_view_ids(ids, delta)
        if ids is None:
            return False
        host_mat[delta.rows] = delta.mat
        # a ShardedMatrix view routes each dirty row into its OWNING
        # shard only (ops/transfer.py scatter_rows)
        y_new = scatter_rows(y_dev, delta.rows, delta.mat)
        self._device_view = (y_new, ids, delta.version, host_mat)

        metrics = view_sync_metrics()
        bytes_of_d = lambda d: scatter_transfer_bytes(d, 2, self.state.dim)
        if isinstance(y_dev, ShardedMatrix):
            n_bytes, by_shard = sharded_delta_bytes(
                y_dev.plan, delta.rows, bytes_of_d
            )
            if delta.n > n_old:
                set_shard_rows(metrics[4], y_dev.plan, delta.n)
        else:
            n_bytes, by_shard = bytes_of_d(delta.rows.size), None
        note_sync_bytes(metrics[0], n_bytes, by_shard)
        metrics[1].observe(_time.monotonic() - t0)
        metrics[2].inc(kind="delta")
        return True

    def _build_view_full(self) -> tuple:
        """Initial load / delta-overflow fallback: one capacity-padded
        bf16 upload. Call under _sync_lock."""
        from oryx_tpu.ops.transfer import (
            device_put_maybe_chunked, row_capacity, sharded_device_put,
        )
        from oryx_tpu.serving.viewsync import (
            note_sync_bytes, set_shard_rows, view_sync_metrics,
        )
        import time as _time

        t0 = _time.monotonic()
        mat, ids, version = self.state.items.snapshot()
        mat = np.asarray(mat, dtype=np.float32)
        n = len(ids)
        cap = row_capacity(n, self.sync.capacity_headroom)
        if cap > n:
            host = np.zeros((cap, self.state.dim), dtype=np.float32)
            host[:n] = mat
        else:
            host = mat
        by_shard = None
        if self.sync.shard_count > 1:
            # the seq item-embedding matrix shards exactly like the ALS
            # item factors: same plan, same owning-shard delta routing,
            # same cross-shard merge on the serve path
            y_dev = sharded_device_put(
                host, self.sync.shard_count, dtype=jnp.bfloat16
            )
            set_shard_rows(view_sync_metrics()[4], y_dev.plan, n)
            by_shard = {
                s: y_dev.plan.size(s) * self.state.dim * 2
                for s in range(y_dev.plan.n_shards)
            }
        else:
            y_dev = device_put_maybe_chunked(host, dtype=jnp.bfloat16)
        view = (y_dev, ids, version, host)
        self._device_view = view
        metrics = view_sync_metrics()
        note_sync_bytes(metrics[0], cap * self.state.dim * 2, by_shard)
        metrics[1].observe(_time.monotonic() - t0)
        metrics[2].inc(kind="full")
        return view

    # -- queries -----------------------------------------------------------

    def encode(self, context_items: list[str]) -> np.ndarray | None:
        """Session item history (oldest -> newest) -> hidden state, or
        None when no context item is known to the model."""
        if not context_items or self.state.params is None:
            return None
        ctx = context_items[-self.state.window:]
        vecs, have = self.state.items.get_many(ctx)
        if not have.any():
            return None
        # left-pad to the fixed window so the jitted encoder compiles ONE
        # (1, window, d) program for every context length (an unpadded
        # call would compile per distinct session length on the hot path)
        w = self.state.window
        mat = np.zeros((1, w, self.state.dim), dtype=np.float32)
        mask = np.zeros((1, w), dtype=np.float32)
        mat[0, w - len(ctx):] = vecs
        mask[0, w - len(ctx):] = have.astype(np.float32)
        return encode_sessions(self.state.params, mat, mask)[0]

    def next_items_async(
        self,
        context_items: list[str],
        how_many: int,
        exclude: set[str] = frozenset(),
    ) -> Future:
        """Top next items for a session context, excluding the session's
        own history — a Future so the deferred endpoint holds no worker
        thread while the coalesced device dispatch is in flight."""
        out: Future = Future()
        try:
            h = self.encode(context_items)
        except BaseException as e:  # noqa: BLE001 - carried to caller
            out.set_exception(e)
            return out
        if h is None:
            out.set_result(None)  # no known context item: 404 at the route
            return out
        y_dev, ids, _version, host_mat = self._view()
        n = len(ids)
        if n == 0:
            out.set_result([])
            return out
        from oryx_tpu.common.tracing import current_span

        span = current_span()
        trace_id = span.trace_id if span is not None else None
        k = min(n, how_many + len(exclude) + 8)
        fut = TopKBatcher.shared().submit_nowait(
            h, k, y_dev, host_mat=host_mat, valid_rows=n,
        )

        def _post(result):
            from oryx_tpu.serving.batcher import host_topk

            vals, idx = np.asarray(result[0]), np.asarray(result[1])
            keep = idx < n  # capacity-padding rows never reach callers
            if not keep.all():
                vals, idx = vals[keep], idx[keep]
                # pads score 0.0 and displace real NEGATIVE-scoring rows:
                # when the kept set can no longer fill the request after
                # exclusions, rescore exactly on the host (the ALS pad
                # backstop, apps/als/serving.py _post)
                if len(idx) < min(n, how_many + len(exclude)):
                    vals, idx = host_topk(
                        np.asarray(h, dtype=np.float32), k, host_mat[:n], False, None
                    )
                    vals, idx = np.asarray(vals), np.asarray(idx)
            # exact f32 re-rank against the row-aligned host mirror (the
            # device scan selects in bf16)
            rows = host_mat[idx]
            vals = rows @ np.asarray(h, dtype=np.float32)
            order = np.argsort(-vals, kind="stable")
            pairs = []
            for j in order:
                ident = ids[int(idx[j])]
                if ident in exclude:
                    continue
                pairs.append([ident, float(vals[j])])
                if len(pairs) == how_many:
                    break
            if pairs:
                # live recall: offer the served page to the shadow
                # rescore sampler (post-pool thread, never the batcher
                # dispatcher; the exact reference is the row-aligned
                # host mirror, read by reference on the drain thread)
                from oryx_tpu.common.qualitystats import get_qualitystats

                get_qualitystats().maybe_sample(
                    np.asarray(h, dtype=np.float32), pairs,
                    how_many=how_many, exclude=exclude,
                    score_mode="exact", trace_id=trace_id,
                    snapshot_fn=lambda: (host_mat, ids, n),
                )
            return pairs

        return chain_future(fut, _post, executor=post_pool())

    def next_items(
        self,
        context_items: list[str],
        how_many: int,
        exclude: set[str] = frozenset(),
    ):
        return self.next_items_async(context_items, how_many, exclude).result()


class SeqServingModelManager(AbstractServingModelManager):
    def __init__(self, config: Config):
        super().__init__(config)
        from oryx_tpu.apps.als.serving import SyncConfig

        self.seq = SeqConfig.from_config(config)
        self.sync = SyncConfig.from_config(config)
        self.model: SeqServingModel | None = None
        configure_post_pool(
            config.get_int("oryx.serving.api.post-workers", 8)
        )

    def get_model(self) -> SeqServingModel | None:
        return self.model

    def consume_key_message(self, key: str | None, message: str) -> None:
        prev = self.model.state if self.model is not None else None
        state = apply_seq_update(prev, key, message)
        if state is not None and state is not prev:
            self.model = SeqServingModel(state, sync=self.sync)
