"""Wordcount speed tier.

Mirrors ExampleSpeedModelManager (app/example .../speed/
ExampleSpeedModelManager.java): MODEL replaces the local map, UP is
ignored, and each micro-batch emits "word,newCount" CSV updates that add
the batch's distinct-co-occurrence counts to the current model's.
"""

from __future__ import annotations

import json
import threading

from oryx_tpu.api import AbstractSpeedModelManager
from oryx_tpu.apps.example.batch import count_distinct_other_words


class ExampleSpeedModelManager(AbstractSpeedModelManager):
    def __init__(self, config=None):
        self._words: dict[str, int] = {}
        self._lock = threading.Lock()

    def consume_key_message(self, key: str | None, message: str) -> None:
        if key == "MODEL":
            model = json.loads(message)
            with self._lock:
                self._words.clear()
                self._words.update(model)
        elif key == "UP":
            pass  # hearing our own updates
        else:
            raise ValueError(f"bad key: {key}")

    def build_updates(self, new_data):
        counts = count_distinct_other_words(km.message for km in new_data)
        out = []
        with self._lock:
            for word, count in counts.items():
                new_count = count + self._words.get(word, 0)
                self._words[word] = new_count
                out.append(("UP", f"{word},{new_count}"))
        return out
