"""Concurrency primitives: context-managed read/write locks and rate-limited
checks.

Mirrors the reference's AutoReadWriteLock/AutoLock try-with-resources
discipline (framework/oryx-common .../lang/AutoReadWriteLock.java) and
RateLimitCheck (hot-path log throttling, used at
ALSSpeedModelManager.java:64,96-98). Serving models use the read/write lock
to guard factor-store mutation against concurrent request scans.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager


class AutoReadWriteLock:
    """Writer-preference read/write lock with `with lock.read():` /
    `with lock.write():` usage."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    @contextmanager
    def read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1
        try:
            yield self
        finally:
            with self._cond:
                self._readers -= 1
                if self._readers == 0:
                    self._cond.notify_all()

    @contextmanager
    def write(self):
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
                self._writer = True
            finally:
                # Decrement on all exits: an exception while waiting must not
                # leave readers blocked on a phantom waiting writer.
                self._writers_waiting -= 1
                if not self._writer:
                    self._cond.notify_all()
        try:
            yield self
        finally:
            with self._cond:
                self._writer = False
                self._cond.notify_all()


class RateLimitCheck:
    """True at most once per period; callers gate log statements on it."""

    def __init__(self, period_sec: float = 60.0):
        self.period = period_sec
        self._next = 0.0
        self._lock = threading.Lock()

    def test(self) -> bool:
        now = time.monotonic()
        with self._lock:
            if now >= self._next:
                self._next = now + self.period
                return True
            return False
