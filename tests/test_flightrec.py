"""Flight recorder (ISSUE 14): bounded on-disk event ring, episode
rate-limiting, snapshot bundling, corpse harvesting, the supervisor's
death-time harvest, the /debug/flight endpoint, and the healthz
up→degraded automatic snapshot."""

from __future__ import annotations

import json
import os
import time

from oryx_tpu.common.config import load_config
from oryx_tpu.common.flightrec import (
    EVENT_KINDS,
    FlightRecorder,
    configure_flightrec,
    harvest,
    read_events,
)


def _rec(tmp_path, **overlay) -> FlightRecorder:
    rec = FlightRecorder()
    rec.configure(load_config(overlay={
        "oryx.monitoring.flight.dir": str(tmp_path / "flight"),
        **overlay,
    }))
    return rec


def test_record_and_read_round_trip(tmp_path):
    rec = _rec(tmp_path)
    assert rec.record(kind="generation", generation=7, lag_s=0.5)
    assert rec.record(kind="wedge", layer="speed", state="wedged")
    events = rec.events()
    assert [e["kind"] for e in events] == ["generation", "wedge"]
    assert events[0]["generation"] == 7
    assert events[0]["pid"] == os.getpid()
    assert events[0]["ts_ms"] > 0


def test_replica_id_stamps_every_event(tmp_path):
    rec = _rec(tmp_path, **{"oryx.fleet.replica.id": "r3"})
    rec.record(kind="generation", generation=1)
    assert rec.events()[0]["replica"] == "r3"


def test_ring_is_bounded_and_rotates(tmp_path):
    rec = _rec(tmp_path, **{
        "oryx.monitoring.flight.segment-bytes": 4096,  # clamp floor
        "oryx.monitoring.flight.segments": 2,
    })
    for i in range(400):
        rec.record(kind="generation", generation=i)
    flight = tmp_path / "flight"
    segs = [p for p in flight.iterdir() if p.name.startswith("events-")]
    assert len(segs) <= 2
    assert sum(p.stat().st_size for p in segs) <= 2 * 4096 + 512
    events = rec.events()
    gens = [e["generation"] for e in events]
    assert gens[-1] == 399           # newest survives
    assert 0 not in gens             # oldest rotated out
    assert gens == sorted(gens)      # oldest-first read order


def test_episode_rate_limit_coalesces_bursts(tmp_path):
    rec = _rec(tmp_path)
    assert rec.record(kind="shed-episode", episode_s=60.0, queue_depth=1)
    for _ in range(10):  # the storm: no further disk writes
        assert not rec.record(kind="shed-episode", episode_s=60.0, queue_depth=2)
    assert len([e for e in rec.events() if e["kind"] == "shed-episode"]) == 1


def test_disabled_recorder_writes_nothing(tmp_path):
    rec = _rec(tmp_path, **{"oryx.monitoring.flight.enabled": False})
    assert not rec.record(kind="generation", generation=1)
    assert not (tmp_path / "flight").exists()


def test_restart_resumes_newest_segment(tmp_path):
    """A restarted process (or co-resident sibling) continues the ring
    instead of clobbering segment 0."""
    a = _rec(tmp_path)
    a.record(kind="generation", generation=1)
    b = _rec(tmp_path)  # fresh recorder, same dir
    b.record(kind="generation", generation=2)
    assert [e["generation"] for e in read_events(str(tmp_path / "flight"))] == [1, 2]


def test_read_events_skips_torn_lines(tmp_path):
    """A writer that died mid-append leaves a torn tail; the NEXT
    process's resume repairs it, and reads skip the bad fragment instead
    of losing the ring."""
    rec = _rec(tmp_path)
    rec.record(kind="generation", generation=1)
    seg = next((tmp_path / "flight").glob("events-*.jsonl"))
    with open(seg, "a", encoding="utf-8") as f:
        f.write('{"kind": "torn')  # the crash, mid-append, no newline
    rec2 = _rec(tmp_path)  # restarted process resumes + repairs
    rec2.record(kind="generation", generation=2)
    assert [e["generation"] for e in rec2.events()] == [1, 2]


def test_snapshot_bundles_the_black_box(tmp_path):
    rec = _rec(tmp_path)
    rec.record(kind="health-degraded", reasons=["model-stale"])
    bundle, path = rec.snapshot("unit-test", extra={"note": "x"})
    assert path is not None and os.path.exists(path)
    on_disk = json.load(open(path, encoding="utf-8"))
    for doc in (bundle, on_disk):
        assert doc["trigger"] == "unit-test"
        assert doc["note"] == "x"
        assert doc["config_fingerprint"]
        assert any(e["kind"] == "health-degraded" for e in doc["events"])
        # the metrics snapshot is the live registry's text exposition
        assert "oryx_" in doc["metrics"]
    # the snapshot itself is a recorded lifecycle event
    assert rec.events()[-1]["kind"] == "snapshot"


def test_snapshot_dir_stays_bounded(tmp_path):
    rec = _rec(tmp_path)
    for i in range(12):
        rec.snapshot(f"t{i}")
    snaps = list((tmp_path / "flight" / "snapshots").glob("*.json"))
    assert len(snaps) <= 8


def test_harvest_packs_a_corpse_ring(tmp_path):
    rec = _rec(tmp_path)
    rec.record(kind="generation", generation=9)
    del rec  # the "corpse": only its files remain
    path = harvest(str(tmp_path / "flight"), replica="r0", returncode=-9)
    assert path is not None
    doc = json.load(open(path, encoding="utf-8"))
    assert doc["replica"] == "r0" and doc["returncode"] == -9
    assert any(e["kind"] == "generation" for e in doc["events"])


def test_harvest_of_missing_dir_returns_none(tmp_path):
    assert harvest(str(tmp_path / "never-existed")) is None


def test_every_cataloged_kind_is_a_string():
    for kind, doc in EVENT_KINDS.items():
        assert isinstance(kind, str) and isinstance(doc, str)


# -- supervisor harvest -------------------------------------------------------


class _Dead:
    returncode = -9

    def poll(self):
        return -9


def test_supervisor_harvests_corpse_flight_dir(tmp_path):
    from oryx_tpu.fleet.supervisor import FleetSupervisor

    cfg = load_config(overlay={
        "oryx.fleet.replicas": 1,
        "oryx.fleet.base-port": 9400,
        "oryx.fleet.data-dir": str(tmp_path / "fleet"),
    })
    sup = FleetSupervisor(cfg)
    # the replica overlay names a per-replica flight dir
    flight_dir = sup.overlays[0]["oryx.monitoring.flight.dir"]
    assert str(tmp_path / "fleet") in str(flight_dir)
    # simulate the corpse's ring: events the dead child already wrote
    child = FlightRecorder()
    child.configure(load_config(overlay={
        "oryx.monitoring.flight.dir": str(flight_dir),
        "oryx.fleet.replica.id": "r0",
    }))
    child.record(kind="generation", generation=42)
    sup._spawn = lambda i: _Dead()  # type: ignore[assignment]
    sup.procs[0] = _Dead()
    sup._spawned_at[0] = time.monotonic()
    sup.poll()
    assert len(sup.harvested) == 1
    doc = json.load(open(sup.harvested[0], encoding="utf-8"))
    assert doc["replica"] == "r0" and doc["returncode"] == -9
    assert any(
        e["kind"] == "generation" and e.get("replica") == "r0"
        for e in doc["events"]
    )
    # the stub respawn "dies" instantly too: its death is a NEW death and
    # harvests once more — but a corpse waiting out the restart backoff
    # is never re-harvested by every further poll tick
    sup.poll()
    sup.poll()
    sup.poll()
    assert len(sup.harvested) == 2


def test_supervisor_harvests_even_with_restarts_off(tmp_path):
    """The crash-loop-last-words path must not depend on the restart
    policy: a kill that sticks (restart=false, the chaos shape) still
    harvests."""
    from oryx_tpu.fleet.supervisor import FleetSupervisor

    cfg = load_config(overlay={
        "oryx.fleet.replicas": 1,
        "oryx.fleet.base-port": 9401,
        "oryx.fleet.data-dir": str(tmp_path / "fleet"),
        "oryx.fleet.supervisor.restart": False,
    })
    sup = FleetSupervisor(cfg)
    child = FlightRecorder()
    child.configure(load_config(overlay={
        "oryx.monitoring.flight.dir": str(sup.overlays[0]["oryx.monitoring.flight.dir"]),
    }))
    child.record(kind="process-start", role="serving", port=9401)
    spawns: list[int] = []
    sup._spawn = lambda i: spawns.append(i) or _Dead()  # type: ignore[assignment]
    sup.procs[0] = _Dead()
    sup._spawned_at[0] = time.monotonic()
    sup.poll()
    assert len(sup.harvested) == 1
    assert spawns == []  # harvested, NOT restarted
    assert not sup.crash_looping


# -- serving integration ------------------------------------------------------


class _NoModelManager:
    def __init__(self, config=None):
        self.config = config

    def consume(self, it):
        pass

    def get_model(self):
        return None


def _app(tmp_path, **overlay):
    from oryx_tpu.serving.app import ServingApp

    cfg = load_config(overlay={
        "oryx.monitoring.flight.dir": str(tmp_path / "flight"),
        **overlay,
    })
    return ServingApp(cfg, _NoModelManager(cfg), None)


def _dispatch(app, method, path, query=None):
    from oryx_tpu.serving.app import Request

    req = Request(
        method=method, path=path, params={}, query=query or {},
        body=b"", headers={},
    )
    return app.dispatch(req)


def test_serving_app_records_process_start(tmp_path):
    _app(tmp_path)
    events = read_events(str(tmp_path / "flight"))
    assert any(
        e["kind"] == "process-start" and e.get("role") == "serving"
        for e in events
    )


def test_debug_flight_endpoint_serves_the_bundle(tmp_path):
    app = _app(tmp_path)
    status, body, ctype = _dispatch(app, "GET", "/debug/flight")
    assert status == 200 and ctype == "application/json"
    doc = json.loads(body)
    assert doc["trigger"] == "debug-endpoint"
    assert any(e["kind"] == "process-start" for e in doc["events"])
    assert "oryx_serving_requests" in doc["metrics"]


def test_debug_flight_403_when_disabled(tmp_path):
    app = _app(tmp_path, **{"oryx.monitoring.flight.enabled": False})
    status, body, _ = _dispatch(app, "GET", "/debug/flight")
    assert status == 403


def test_healthz_degraded_transition_snapshots_once(tmp_path):
    app = _app(tmp_path)
    app.note_health_state(False, [])
    app.note_health_state(True, ["model-stale@r1:8101"])   # the EDGE
    app.note_health_state(True, ["model-stale@r1:8101"])   # steady state: no-op
    deadline = time.time() + 10
    snap_dir = tmp_path / "flight" / "snapshots"
    while time.time() < deadline:
        if snap_dir.exists() and list(snap_dir.glob("flight-healthz-degraded-*.json")):
            break
        time.sleep(0.05)
    snaps = list(snap_dir.glob("flight-healthz-degraded-*.json"))
    assert len(snaps) == 1, "exactly one snapshot per up->degraded edge"
    events = read_events(str(tmp_path / "flight"))
    degraded = [e for e in events if e["kind"] == "health-degraded"]
    assert len(degraded) == 1
    assert degraded[0]["reasons"] == ["model-stale@r1:8101"]
    # recovery re-arms the edge: the NEXT degradation snapshots again
    app.note_health_state(False, [])
    app.note_health_state(True, ["device-down"])
    deadline = time.time() + 10
    while time.time() < deadline:
        if len(list(snap_dir.glob("flight-healthz-degraded-*.json"))) >= 2:
            break
        time.sleep(0.05)
    assert len(list(snap_dir.glob("flight-healthz-degraded-*.json"))) == 2


def test_configure_flightrec_is_the_servingapp_path(tmp_path):
    """configure_flightrec redirects the process singleton — the
    ServingApp constructor path the fleet children take."""
    rec = configure_flightrec(load_config(overlay={
        "oryx.monitoring.flight.dir": str(tmp_path / "f2"),
    }))
    rec.record(kind="process-start", role="test")
    assert read_events(str(tmp_path / "f2"))
