"""k-means application-tier tests: batch build + eval strategies, speed
centroid shifts, serving assignment + live updates, and the REST surface
over a real HTTP server (the KMeansUpdateIT / speed/serving IT pattern)."""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from oryx_tpu.apps.kmeans import (
    KMeansServingModelManager,
    KMeansSpeedModelManager,
    KMeansUpdate,
)
from oryx_tpu.bus.api import KeyMessage
from oryx_tpu.bus.broker import get_broker, topics
from oryx_tpu.bus.inproc import InProcBroker
from oryx_tpu.common.config import load_config
from oryx_tpu.common.ioutil import choose_free_port
from oryx_tpu.serving.server import ServingLayer


@pytest.fixture(autouse=True)
def _fresh_registry():
    InProcBroker.reset_all()
    yield
    InProcBroker.reset_all()


def _cfg(port=0):
    return load_config(overlay={
        "oryx.id": "kmt",
        "oryx.input-topic.broker": "mem://kmt",
        "oryx.update-topic.broker": "mem://kmt",
        "oryx.serving.api.port": port,
        "oryx.serving.model-manager-class":
            "oryx_tpu.apps.kmeans.serving.KMeansServingModelManager",
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.clustering",
        ],
        "oryx.input-schema.num-features": 2,
        "oryx.input-schema.numeric-features": ["0", "1"],
        "oryx.kmeans.hyperparams.k": 2,
        "oryx.kmeans.iterations": 10,
        "oryx.ml.eval.test-fraction": 0.2,
    })


def _blob_lines(seed=0):
    rng = np.random.default_rng(seed)
    lines = []
    for c in ((0.0, 0.0), (10.0, 10.0)):
        for _ in range(40):
            x = rng.normal(c[0], 0.2)
            y = rng.normal(c[1], 0.2)
            lines.append(f"{x:.4f},{y:.4f}")
    return [KeyMessage(None, ln) for ln in lines]


def test_batch_build_and_eval_strategies():
    data = _blob_lines()
    for strategy in ("SILHOUETTE", "DAVIES_BOULDIN", "DUNN", "SSE"):
        upd = KMeansUpdate(_cfg().overlay(
            {"oryx.kmeans.evaluation-strategy": strategy}))
        art = upd.build_model(data, {"k": 2})
        assert art.tensors["centers"].shape == (2, 2)
        assert sorted(art.content["counts"]) == [40, 40]
        ev = upd.evaluate(art, data, [])
        assert np.isfinite(ev)
        if strategy == "SILHOUETTE":
            assert ev > 0.8  # well-separated blobs
        if strategy in ("DAVIES_BOULDIN", "SSE"):
            assert ev < 0  # negated lower-is-better


def test_speed_manager_shifts_centroids():
    cfg = _cfg()
    upd = KMeansUpdate(cfg)
    art = upd.build_model(_blob_lines(), {"k": 2})
    mgr = KMeansSpeedModelManager(cfg)
    assert mgr.build_updates([KeyMessage(None, "0,0")]) == []  # no model yet
    mgr.consume_key_message("MODEL", art.to_string())
    # a window of points near one blob, displaced toward (2,2)
    window = [KeyMessage(None, "2.0,2.0")] * 10
    ups = mgr.build_updates(window)
    assert len(ups) == 1
    key, msg = ups[0]
    assert key == "UP"
    cid, center, count = json.loads(msg)[0], json.loads(msg)[1], json.loads(msg)[2]
    assert count == 50  # 40 original + 10 new
    # centroid moved from ~(0,0) toward (2,2) by 10/50
    assert 0.3 < center[0] < 0.6
    # UP messages are ignored on re-consume (hearing our own updates)
    mgr.consume_key_message("UP", msg)


def test_serving_model_applies_updates():
    cfg = _cfg()
    art = KMeansUpdate(cfg).build_model(_blob_lines(), {"k": 2})
    mgr = KMeansServingModelManager(cfg)
    mgr.consume_key_message("UP", json.dumps([0, [1.0, 1.0], 5]))  # pre-model: noop
    mgr.consume_key_message("MODEL", art.to_string())
    model = mgr.get_model()
    cid0, d0 = model.closest_cluster(model.vectorize("0.1,0.1"))
    cid1, d1 = model.closest_cluster(model.vectorize("9.9,10.1"))
    assert cid0 != cid1 and d0 < 1 and d1 < 1
    # live centroid replacement
    mgr.consume_key_message(
        "UP", json.dumps([cid0, [5.0, 5.0], 99]))
    _, d_after = model.closest_cluster(model.vectorize("5.0,5.0"))
    assert d_after < 1e-6
    assert model.counts[cid0] == 99


def _http(method, url, body=None):
    req = urllib.request.Request(
        url, method=method, data=body, headers={"Accept": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_clustering_rest_surface():
    port = choose_free_port()
    cfg = _cfg(port)
    topics.maybe_create("mem://kmt", cfg.get_string("oryx.input-topic.message.topic"), 1)
    topics.maybe_create("mem://kmt", cfg.get_string("oryx.update-topic.message.topic"), 1)
    broker = get_broker("mem://kmt")
    art = KMeansUpdate(cfg).build_model(_blob_lines(), {"k": 2})
    broker.send(cfg.get_string("oryx.update-topic.message.topic"), "MODEL", art.to_string())

    with ServingLayer(cfg) as layer:
        base = f"http://127.0.0.1:{port}"
        for _ in range(100):
            try:
                if _http("GET", f"{base}/ready")[0] == 200:
                    break
            except Exception:
                pass
            time.sleep(0.1)
        s, one = _http("GET", f"{base}/assign/0.1,0.2")
        assert s == 200
        s, other = _http("GET", f"{base}/assign/9.9,10.0")
        assert s == 200 and json.loads(one) != json.loads(other)
        s, body = _http("POST", f"{base}/assign", b"0.0,0.0\n10.0,10.0\n")
        assert s == 200 and len(json.loads(body)) == 2
        s, body = _http("GET", f"{base}/distanceToNearest/0.0,0.0")
        assert s == 200 and float(json.loads(body)) < 1.0
        s, body = _http("GET", f"{base}/assign/not-a-number,1")
        assert s == 400
        s, body = _http("GET", f"{base}/assign/1")  # wrong arity
        assert s == 400
        s, _ = _http("POST", f"{base}/add/3.0,4.0")
        assert s == 200
        in_topic = cfg.get_string("oryx.input-topic.message.topic")
        recs = broker.read(in_topic, 0, 0, 10)
        assert any(m == "3.0,4.0" for _, _, m in recs)


def test_clustering_console_section():
    port = choose_free_port()
    cfg = _cfg(port)
    topics.maybe_create("mem://kmt", cfg.get_string("oryx.input-topic.message.topic"), 1)
    topics.maybe_create("mem://kmt", cfg.get_string("oryx.update-topic.message.topic"), 1)
    broker = get_broker("mem://kmt")
    art = KMeansUpdate(cfg).build_model(_blob_lines(), {"k": 2})
    broker.send(cfg.get_string("oryx.update-topic.message.topic"), "MODEL", art.to_string())
    with ServingLayer(cfg):
        base = f"http://127.0.0.1:{port}"
        for _ in range(100):
            try:
                if _http("GET", f"{base}/ready")[0] == 200:
                    break
            except Exception:
                pass
            time.sleep(0.1)
        s, html = _http("GET", f"{base}/console")
        assert s == 200
        assert "Clustering model" in html and "clusters" in html
