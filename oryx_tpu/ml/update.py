"""The batch-training harness: hyperparam candidates -> build -> eval ->
publish the winner.

TPU-native MLUpdate (reference framework/oryx-ml .../ml/MLUpdate.java:60-378).
Per generation it: splits train/test (random by default, overridable — ALS
splits by time), chooses hyperparameter combos, builds + evaluates each
candidate (sequential by default: on a TPU the device is the scarce
resource, concurrent builds just contend — eval parallelism is for CPU-side
eval), applies the acceptance threshold, atomically renames the winner into
model_dir/<timestamp>, and publishes it to the update topic inline
("MODEL") or as a path reference ("MODEL-REF") when it exceeds the topic's
max message size (MLUpdate.java:212-231), then streams any oversized extras
via publish_additional_model_data (e.g. ALS factor rows).
"""

from __future__ import annotations

import logging
from abc import abstractmethod
from pathlib import Path
from typing import Any, Sequence

import numpy as np

from oryx_tpu.api import BatchLayerUpdate
from oryx_tpu.bus.api import KeyMessage, TopicProducer
from oryx_tpu.common.artifact import ModelArtifact
from oryx_tpu.common.config import Config
from oryx_tpu.common.executil import collect_in_parallel
from oryx_tpu.common.ioutil import atomic_rename, delete_recursively, mkdirs, strip_scheme
from oryx_tpu.common.metrics import get_registry
from oryx_tpu.common.rng import RandomManager
from oryx_tpu.ml.hyperparams import choose_combos

log = logging.getLogger(__name__)


def split_by_time(
    data: Sequence[KeyMessage],
    test_fraction: float,
    fallback,
    ts_token: int = 3,
) -> tuple[Sequence[KeyMessage], Sequence[KeyMessage]]:
    """Temporal holdout split shared by the timestamped apps (ALS event
    lines and seq session lines both carry the timestamp as CSV token
    ``ts_token``): the newest ``test_fraction`` of records is held out
    (the reference's ALSUpdate.java:325-342 sort-by-time split).
    Timestamps are read per line in place — unparseable lines get -1 and
    stay in train, so indices always align with ``data``. When no line
    carries a usable timestamp (or all are equal), ``fallback(data)``
    decides (usually the random split)."""
    if test_fraction <= 0 or len(data) == 0:
        return data, []
    from oryx_tpu.common.text import parse_input_line

    ts = np.full(len(data), -1, dtype=np.int64)
    for j, km in enumerate(data):
        try:
            tok = parse_input_line(km.message)
            if len(tok) > ts_token and tok[ts_token] != "":
                ts[j] = int(float(tok[ts_token]))
        except (ValueError, IndexError, OverflowError):
            pass
    valid = ts[ts >= 0]
    if len(valid) == 0 or np.all(valid == valid[0]):
        return fallback(data)
    order = np.argsort(ts, kind="stable")
    n_test = int(len(data) * test_fraction)
    if n_test == 0:
        return data, []
    test_set = set(order[-n_test:].tolist())
    train = [d for j, d in enumerate(data) if j not in test_set]
    test = [d for j, d in enumerate(data) if j in test_set]
    return train, test


class MLUpdate(BatchLayerUpdate):
    def __init__(self, config: Config):
        self.config = config
        self.test_fraction = config.get_float("oryx.ml.eval.test-fraction", 0.1)
        self.candidates = config.get_int("oryx.ml.eval.candidates", 1)
        self.search = config.get_string("oryx.ml.eval.hyperparam-search", "random")
        self.eval_parallelism = config.get_int("oryx.ml.eval.parallelism", 1)
        self.threshold = config.get("oryx.ml.eval.threshold", None)
        self.max_message_size = config.get_int("oryx.update-topic.message.max-size", 1 << 24)
        # bus-chunked MODEL-REF artifact bytes (cross-host resolution with
        # no shared mount); off restores the reference's bare-path publish
        self.artifact_transfer = config.get_bool(
            "oryx.update-topic.artifact-transfer", True
        )
        from oryx_tpu.parallel.distributed import DistributedConfig

        # multi-PROCESS pods parallelize the candidate search by process
        # GROUP (run_update): the pod splits into contiguous host groups,
        # each group trains a disjoint candidate subset on its own slice
        # of the mesh, and scores are gathered pod-wide afterwards — the
        # cluster-parallel search of the reference (MLUpdate.java:253-258)
        # without ever interleaving two candidates' collectives on one
        # device.
        self._pod = DistributedConfig.from_config(config).enabled
        # incremental generations: apps that maintain a persistent
        # aggregate snapshot (incremental_update) make generation N cost
        # O(new window); the pod path stays on the lockstep full rebuild
        # (every member must see identical inputs, and per-member
        # snapshots could diverge after partial failures).
        self.incremental = config.get_bool(
            "oryx.batch.storage.incremental.enabled", True
        )
        self._m_incremental = get_registry().counter(
            "oryx_batch_incremental_total",
            "Batch model builds by kind: delta = merged into the persisted "
            "aggregate snapshot, full = from-scratch over all history",
            labeled=True,
        )

    # ---- hooks an app implements -----------------------------------------

    @abstractmethod
    def build_model(self, train: Sequence[KeyMessage], hyperparams: dict[str, Any]) -> ModelArtifact:
        """Train one candidate on the train split."""

    @abstractmethod
    def evaluate(
        self,
        model: ModelArtifact,
        train: Sequence[KeyMessage],
        test: Sequence[KeyMessage],
    ) -> float:
        """Bigger-is-better eval of a candidate on held-out data; NaN = bad."""

    def hyperparam_ranges(self) -> dict[str, Any]:
        """Config-valued hyperparameter ranges (name -> scalar/list/dict)."""
        return {}

    def eval_metric_name(self) -> str:
        """Name of the number ``evaluate`` returns (e.g. "auc",
        "hit_rate_at_10") — the label the generation's quality scorecard
        carries through the publish stamp into
        ``oryx_generation_quality{metric}`` on every consuming tier."""
        return "score"

    def note_eval(self, score: float | None) -> None:
        """Remember the winning candidate's eval score so the publish
        stamp that follows can carry the generation's scorecard. Every
        publish path (candidate search, app incremental_update
        overrides) calls this just before promote_and_publish; a
        non-finite score clears the card instead of stamping a lie."""
        if score is not None and np.isfinite(score):
            self._last_eval = {self.eval_metric_name(): float(score)}
        else:
            self._last_eval = None

    def split_train_test(
        self, data: Sequence[KeyMessage]
    ) -> tuple[Sequence[KeyMessage], Sequence[KeyMessage]]:
        """Random holdout by test-fraction (MLUpdate.java:370-376); apps
        with temporal data override to split by time."""
        if self.test_fraction <= 0 or len(data) == 0:
            return data, []
        rng = RandomManager.get_random()
        mask = rng.random(len(data)) < self.test_fraction
        train = [d for d, m in zip(data, mask) if not m]
        test = [d for d, m in zip(data, mask) if m]
        return train, test

    def publish_additional_model_data(
        self,
        model: ModelArtifact,
        model_path: str,
        producer: TopicProducer,
    ) -> None:
        """Hook for streaming data too large for the artifact message (ALS
        streams every factor row here, MLUpdate.java:233-236)."""

    def incremental_update(
        self,
        timestamp_ms: int,
        new_data: Sequence[KeyMessage],
        model_dir: str,
        update_producer: TopicProducer,
    ) -> bool:
        """App hook: attempt an O(new-window) incremental generation
        against a persisted aggregate snapshot (see apps/als/batch.py).
        Return True when the generation was fully handled — model built
        and published, or legitimately withheld (threshold) — with the
        window folded into the snapshot. Return False to fall back to the
        from-scratch path over materialized history (snapshot missing,
        schema-mismatched, stale, or window drift past the configured
        fraction)."""
        return False

    def after_full_build(
        self,
        timestamp_ms: int,
        train: Sequence[KeyMessage],
        test: Sequence[KeyMessage],
        model: ModelArtifact | None,
    ) -> None:
        """App hook, called after a from-scratch build: rebuild and stage
        the aggregate snapshot so the NEXT generation can run
        incrementally again (the delta-vs-full discipline: every full
        rebuild re-anchors the incremental state). model is None when the
        eval threshold withheld publication — aggregates re-anchor
        regardless, since the window is persisted regardless."""

    def training_mesh(self):
        """The mesh candidate builds run on (apps that shard training set
        self.mesh in __init__); None trains single-device."""
        return getattr(self, "mesh", None)

    def _build_mesh(self):
        """The mesh for the CURRENT candidate build: the thread's assigned
        sub-mesh during a partitioned parallel search, else the full
        training mesh. App build_model implementations resolve their
        trainer's mesh through this."""
        from oryx_tpu.parallel.submesh import current_candidate_mesh

        m = current_candidate_mesh()
        return m if m is not None else self.training_mesh()

    # ---- the harness -----------------------------------------------------

    def run_update(
        self,
        timestamp_ms: int,
        new_data: Sequence[KeyMessage],
        past_data: Sequence[KeyMessage],
        model_dir: str,
        update_producer: TopicProducer,
    ) -> None:
        if self.incremental and not self._pod:
            # the incremental path never touches past_data: when the app's
            # aggregate snapshot is valid, generation cost is O(window)
            if self.incremental_update(
                timestamp_ms, new_data, model_dir, update_producer
            ):
                self._m_incremental.inc(kind="delta")
                return
        data = list(past_data) + list(new_data)
        if not data:
            log.info("no data at generation %d; skipping model build", timestamp_ms)
            return
        self._m_incremental.inc(kind="full")
        if self._pod:
            # every pod member must draw the SAME random split, the same
            # hyperparam combos, and the same factor-init keys, or the
            # lockstep collective training diverges. The generation
            # timestamp is already pod-agreed (BatchLayer._pod_window),
            # so it seeds one shared deterministic stream per generation.
            RandomManager.use_test_seed(timestamp_ms & 0x7FFFFFFF)
        train, test = self.split_train_test(data)
        if not train:
            train, test = data, []
        combos = choose_combos(self.hyperparam_ranges(), self.candidates, self.search)

        root = Path(strip_scheme(model_dir))
        cand_root = mkdirs(root / ".candidates" / str(timestamp_ms))

        # parallel search runs one candidate per DISJOINT sub-mesh (the
        # TPU-native MLUpdate.java:253-258 — concurrent threads over one
        # mesh would only contend): slice the mesh along its data axis,
        # clamp the thread count to the number of sub-meshes, and hand
        # each RUNNING build a mesh from a free pool (assignment by task
        # index would let two in-flight candidates share devices whenever
        # candidates outnumber sub-meshes)
        mesh_pool = None
        parallelism = min(self.eval_parallelism, len(combos))
        pod_groups = None
        multiproc = False
        if self._pod:
            import jax

            multiproc = jax.process_count() > 1
        if parallelism > 1 and multiproc:
            from oryx_tpu.parallel.submesh import pod_group_submesh

            mesh = self.training_mesh()
            pod_groups = (
                pod_group_submesh(mesh, parallelism) if mesh is not None else None
            )
            if pod_groups is None:
                # un-partitionable pod (a data row spanning processes, or
                # no mesh): candidates must then run serially in the same
                # order on every member — two candidates' collectives
                # interleaved on shared devices wedge the group
                log.warning(
                    "pod mesh not partitionable by process group; "
                    "running candidate search serially"
                )
                parallelism = 1
        if parallelism > 1 and pod_groups is None:
            mesh = self.training_mesh()
            if mesh is not None:
                import queue

                from oryx_tpu.parallel.submesh import partition_mesh

                subs = partition_mesh(mesh, parallelism)
                parallelism = min(parallelism, len(subs))
                if parallelism > 1:
                    mesh_pool = queue.Queue()
                    for m in subs:
                        mesh_pool.put(m)
                    log.info(
                        "parallel candidate search: %d sub-meshes of %s "
                        "devices", len(subs),
                        [m.devices.size for m in subs],
                    )

        def build_and_eval(i: int) -> tuple[float, Path | None]:
            if multiproc:
                # per-candidate deterministic seed, order-independent: a
                # pod member building only its group's candidate subset
                # must draw the same keys the serial lockstep search
                # would, or group-parallel and serial searches diverge
                RandomManager.use_test_seed(
                    self._pod_candidate_seed(timestamp_ms, i)
                )
            sub = mesh_pool.get() if mesh_pool is not None else None
            try:
                return self._build_one(i, combos, train, test, cand_root, sub)
            finally:
                if sub is not None:
                    mesh_pool.put(sub)

        if pod_groups is not None:
            scores, has_model, paths = self._pod_group_search(
                timestamp_ms, train, test, combos, cand_root, pod_groups
            )
        else:
            results = collect_in_parallel(len(combos), build_and_eval, parallelism)
            scores = [s for s, _ in results]
            paths = [p for _, p in results]
            has_model = [p is not None for p in paths]

        best_i, best_score = -1, float("-inf")
        for i, (score, ok) in enumerate(zip(scores, has_model)):
            if not ok:
                continue
            if np.isnan(score):
                # no test data / failed eval: candidate is acceptable only
                # if nothing scored beats it (mirror of the reference's
                # NaN-tolerant pickBest)
                if best_i < 0:
                    best_i = i
            elif score > best_score:
                best_i, best_score = i, score
        if best_i < 0:
            delete_recursively(cand_root)
            raise RuntimeError("no model candidate built successfully")

        if (
            self.threshold is not None
            and np.isfinite(best_score)  # only gate actually-evaluated models:
            # a NaN-pick leaves best_score=-inf, which must not block publication
            and best_score < float(self.threshold)
        ):
            log.warning(
                "best eval %.6f below threshold %s; not publishing model",
                best_score, self.threshold,
            )
            delete_recursively(cand_root)
            # still re-anchor the aggregate snapshot: the window persists
            # either way, and skipping this would leave the snapshot
            # permanently stale — every later generation would repeat the
            # O(history) full rebuild until eval crossed the threshold
            if self.incremental and not self._pod:
                self.after_full_build(timestamp_ms, train, test, None)
            return

        if pod_groups is not None:
            # the winner lives on its builder group's disks only; every
            # process must end this generation with the same final_dir
            # content (exactly as the serial lockstep search guarantees)
            paths[best_i] = self._fetch_winner(
                best_i, paths[best_i], cand_root, pod_groups
            )

        # the winner's eval rides the publish stamp as the generation's
        # quality scorecard (best_score is -inf on a NaN-tolerant pick)
        self.note_eval(best_score if np.isfinite(best_score) else None)
        model = self.promote_and_publish(
            paths[best_i], root, timestamp_ms, update_producer
        )
        delete_recursively(root / ".candidates")
        if self.incremental and not self._pod:
            self.after_full_build(timestamp_ms, train, test, model)

    def promote_and_publish(
        self,
        staged_dir: Path,
        model_root: Path,
        timestamp_ms: int,
        update_producer: TopicProducer,
    ) -> ModelArtifact:
        """Atomically promote a built candidate dir to
        model_root/<timestamp> and publish it (MODEL/MODEL-REF + extras)
        — the one publish tail shared by the candidate-search and
        incremental paths."""
        final_dir = model_root / str(timestamp_ms)
        delete_recursively(final_dir)
        # bounded retry (common/retry.py): the built candidate is complete
        # on disk, so only the cheap promote rename replays on a transient
        # filesystem error — losing a finished multi-hour build to one
        # EIO here would be the worst trade in the system
        from oryx_tpu.common.retry import retry_call

        retry_call("datastore.rename", atomic_rename, staged_dir, final_dir)
        model = ModelArtifact.read(final_dir)
        self.publish_model(model, str(final_dir), update_producer)
        self.publish_additional_model_data(model, str(final_dir), update_producer)
        return model

    def _build_one(
        self,
        i: int,
        combos: list[dict[str, Any]],
        train: Sequence[KeyMessage],
        test: Sequence[KeyMessage],
        cand_root: Path,
        sub,
    ) -> tuple[float, Path | None]:
        """Build, write, and evaluate candidate i (on sub-mesh `sub` when
        given) — the single copy of the candidate build-and-score contract
        that the serial, thread-parallel, and pod-group searches all use."""
        from contextlib import nullcontext

        from oryx_tpu.parallel.submesh import candidate_mesh

        ctx = candidate_mesh(sub) if sub is not None else nullcontext()
        try:
            with ctx:
                model = self.build_model(train, combos[i])
                cand_dir = model.write(cand_root / str(i))
                score = (
                    self.evaluate(model, train, test) if test else float("nan")
                )
            log.info("candidate %d %s -> eval %s", i, combos[i], score)
            return score, cand_dir
        except Exception:
            log.exception("candidate %d failed", i)
            return float("nan"), None

    @staticmethod
    def _pod_candidate_seed(timestamp_ms: int, i: int) -> int:
        """Deterministic per-(generation, candidate) RNG seed: every pod
        member derives the same seed for candidate i no matter which
        candidates it builds, or in what order."""
        return (timestamp_ms ^ ((i + 1) * 0x9E3779B9)) & 0x7FFFFFFF

    def _pod_group_search(
        self,
        timestamp_ms: int,
        train: Sequence[KeyMessage],
        test: Sequence[KeyMessage],
        combos: list[dict[str, Any]],
        cand_root: Path,
        pod_groups,
    ) -> tuple[list[float], list[bool], list[Path | None]]:
        """The multi-host parallel candidate search (reference
        MLUpdate.java:253-258 fans candidates out over the Spark cluster).
        Process groups build disjoint candidate subsets concurrently, each
        on its own slice of the pod mesh; afterwards every process gathers
        all scores and adopts each candidate's GROUP-LEADER row, so every
        member picks the winner from identical numbers."""
        import jax

        from oryx_tpu.parallel.distributed import host_allgather

        my_group, groups, sub = pod_groups
        n_groups = len(groups)
        n = len(combos)
        mine = [i for i in range(n) if i % n_groups == my_group]
        log.info(
            "pod parallel candidate search: %d groups over %d processes; "
            "group %d (processes %s, %d-device sub-mesh) builds candidates %s",
            n_groups, jax.process_count(), my_group, groups[my_group],
            sub.devices.size, mine,
        )
        scores = np.full(n, np.nan)
        built = np.zeros(n, dtype=np.int64)
        paths: list[Path | None] = [None] * n
        for i in mine:
            RandomManager.use_test_seed(self._pod_candidate_seed(timestamp_ms, i))
            scores[i], paths[i] = self._build_one(
                i, combos, train, test, cand_root, sub
            )
            built[i] = 1 if paths[i] is not None else 0
        all_scores = host_allgather(scores)
        all_built = host_allgather(built)
        final_scores, final_built = [], []
        for i in range(n):
            leader = groups[i % n_groups][0]
            final_scores.append(float(all_scores[leader, i]))
            final_built.append(bool(all_built[leader, i]))
        return final_scores, final_built, paths

    def _fetch_winner(
        self, best_i: int, local_path: Path | None, cand_root: Path, pod_groups
    ) -> Path:
        """Collective: ship the winning candidate's artifact bytes from its
        builder group's leader to every process that did not build it (no
        shared filesystem — same reason MODEL-REF rides the ArtifactRelay).
        All pod members must call this together."""
        import jax

        from oryx_tpu.parallel.distributed import host_broadcast_bytes

        _, groups, _ = pod_groups
        src = groups[best_i % len(groups)][0]
        payload = None
        if jax.process_index() == src:
            payload = ModelArtifact.read(local_path).to_string().encode("utf-8")
        blob = host_broadcast_bytes(payload, src)
        if local_path is not None:
            return Path(local_path)
        return ModelArtifact.from_string(blob.decode("utf-8")).write(
            cand_root / str(best_i)
        )

    def publish_model(
        self, model: ModelArtifact, model_path: str, producer: TopicProducer
    ) -> None:
        """Inline when small enough, else a path reference
        (MLUpdate.java:212-231) — preceded by the bus-chunked artifact
        bytes so consumers on other hosts can resolve it without a shared
        filesystem (common/artifact.py ArtifactRelay; the reference leans
        on a shared Hadoop FileSystem instead, AppPMMLUtils.java:261-275)."""
        from oryx_tpu.common.artifact import publish_model_ref

        serialized = model.to_string()
        if len(serialized.encode("utf-8")) <= self.max_message_size:
            producer.send("MODEL", serialized)
        else:
            publish_model_ref(
                producer, serialized, model_path, self.max_message_size,
                transfer=self.artifact_transfer,
            )
        self.send_publish_stamp(model_path, producer)

    def send_publish_stamp(
        self, model_path: str, producer: TopicProducer
    ) -> None:
        """Publish-time freshness stamp, sent AFTER the model message
        (app-visible record order is unchanged; consumers claim the stamp
        for the model that just loaded): feeds
        oryx_update_to_serve_seconds / oryx_model_staleness_seconds on
        every consuming tier and carries the generation's trace context.
        An SPI contract point: apps overriding publish_model (the ALS/seq
        skeleton pattern) call this at the end of their override so every
        packaged app's generations stay observable the same way."""
        from oryx_tpu.common.freshness import publish_stamp

        try:
            generation = int(Path(model_path).name)
        except (TypeError, ValueError):
            generation = None
        producer.send(
            "TRACE",
            publish_stamp(
                generation=generation,
                quality=getattr(self, "_last_eval", None),
            ),
        )
