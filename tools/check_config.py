#!/usr/bin/env python
"""Static config-key consistency check — thin wrapper (DEPRECATED entry
point; the logic now lives in the oryxlint ``config-keys`` rule,
tools/oryxlint/checkers/consistency.py, and runs with the rest of the
static-analysis suite via ``python -m tools.oryxlint``).

Kept as a CLI because operators and older docs invoke it directly. The
collector functions (``code_config_keys``, ``reference_config``) are
defined here and stay monkeypatchable as before — ``main`` reads them
through this module's globals. ``ACCESSOR``/``STRICT_BLOCKS`` are
read-only re-exports of the rule's constants (rebinding them here does
not change the rule's behavior).

Contract (unchanged): every ``oryx.*`` key the code reads through a
``Config`` accessor must be declared in ``common/reference.conf``, and
every key declared under a strict robustness block (faults / retry /
quarantine / shed) must be read somewhere — a dead recovery knob
misleads operators. Keys composed with f-string interpolation cannot be
resolved statically and are skipped on purpose.

Exit status 0 = consistent; 1 = drift (each problem printed on stderr).
"""

from __future__ import annotations

import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "oryx_tpu"
REFERENCE = PACKAGE / "common" / "reference.conf"

if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from tools.oryxlint.checkers import consistency as _rule  # noqa: E402

# re-exported for callers/tests that reach into this module
ACCESSOR = _rule.ACCESSOR
STRICT_BLOCKS = _rule.STRICT_BLOCKS


def code_config_keys() -> dict[str, str]:
    """key -> first file reading it, for every literal oryx.* accessor."""
    return {
        key: where
        for key, (where, _line) in _rule.code_config_keys(PACKAGE, ROOT).items()
    }


def reference_config():
    return _rule.reference_config(REFERENCE)


def main() -> int:
    if not REFERENCE.exists():
        print(f"missing {REFERENCE.relative_to(ROOT)}", file=sys.stderr)
        return 1
    code = code_config_keys()
    problems = _rule.config_problems(code, reference_config())
    for p in problems:
        print(p, file=sys.stderr)
    if not problems:
        print(f"ok: {len(code)} config keys all declared in reference.conf")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
