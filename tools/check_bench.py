#!/usr/bin/env python
"""Bench ratchet: fail when a fresh bench run regresses a locked metric.

The ROADMAP's performance claims (kernel MFU, serving qps, latency) were
previously enforced by a human reading two JSON artifacts side by side.
This tool makes the claim a ratchet: ``BASELINE_RATCHET.json`` locks, per
metric, the best honestly-measured value, the direction that counts as
progress (``up`` for qps/MFU, ``down`` for latency), a noise tolerance,
and the platform the number was measured on. A run that slips past
tolerance in the wrong direction — or that silently stops emitting a
ratcheted metric at all — exits non-zero with a per-metric table.

    python tools/check_bench.py --current BENCH_rNN.json
    python bench.py | python tools/check_bench.py --current -
    python tools/check_bench.py --run          # runs bench.py itself

Metrics locked for a different platform than the current run's are
reported as skipped, not failed: a CPU fallback run must not trip the TPU
ratchet (and cannot satisfy it either — the TPU claim stays unproven
until the next TPU window re-measures it).

Ratcheting UP the baseline is a deliberate git edit of
BASELINE_RATCHET.json riding the PR that earned the number — never
automatic, so a lucky run can't quietly raise the bar for everyone.
``tools/check_metrics.py`` statically verifies every ratcheted metric
name still exists in bench.py's output vocabulary.

A row may carry ``"pending": true``: the baseline was set AHEAD of its
first banked measurement (a PR that rebuilt the thing being measured and
re-declared the bar, e.g. the gen-2 fused kernel retightening
pallas_speedup before a TPU window could run it). Pending rows render
loudly in the table but never fail the check — the committed ratchet
must keep accepting the previously banked artifacts. The PR that banks
the first artifact measuring a pending row REMOVES the flag (and
corrects the baseline to the measured number), at which point the row
enforces like any other.

A pending row also records ``"pending_since": <round>`` — the bench
round at which the bar was declared. ``stale_pending_problems`` (run by
this CLI and by the oryxlint ``bench-ratchet`` rule) fails a pending row
once a banked artifact of the row's platform from that round or later
MEASURES the metric: the flag has outlived its purpose, and keeping it
would let the bar float unenforced forever.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(ROOT, "BASELINE_RATCHET.json")


def load_baseline(path: str) -> list[dict]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    metrics = doc.get("metrics")
    if not isinstance(metrics, list):
        raise SystemExit(f"{path}: expected a top-level 'metrics' list")
    for m in metrics:
        for field in ("name", "baseline", "direction"):
            if field not in m:
                raise SystemExit(f"{path}: metric entry missing '{field}': {m}")
        if m["direction"] not in ("up", "down"):
            raise SystemExit(
                f"{path}: direction must be 'up' or 'down': {m['name']}"
            )
    return metrics


def extract_current(raw: str) -> dict:
    """The run's metric dict from bench-style output: prefer the full
    `"detail": true` line, else the last parseable JSON object line (the
    compact final), else a whole-document JSON object (a saved artifact,
    possibly the {final, detail} shape banked by tools/bank_window.py)."""
    try:
        doc = json.loads(raw)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict):
        # a saved artifact: either the metric dict itself, or the banked
        # {final, detail} wrapper — detail carries the full vocabulary
        if isinstance(doc.get("detail"), dict):
            return doc["detail"]
        return doc
    detail = final = None
    for line in raw.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(row, dict):
            continue
        if row.get("detail") is True:
            detail = row
        final = row
    if detail is not None:
        return detail
    if final is not None:
        # a banked window artifact: one JSON object wrapping final/detail
        if "detail" in final and isinstance(final.get("detail"), dict):
            return final["detail"]
        return final
    raise SystemExit("no parseable JSON metrics found in the current input")


def banked_artifacts(root: str = ROOT) -> list[tuple[int, str, dict]]:
    """(round, platform, metric dict) for every banked bench artifact:
    ``BENCH_TPU_WINDOW_r{N}.json`` and ``BENCH_r{N}.json``. Unparseable
    files are skipped — a stale-pending verdict must rest on artifacts
    that actually decode."""
    import glob
    import re

    out: list[tuple[int, str, dict]] = []
    for path in sorted(
        glob.glob(os.path.join(root, "BENCH_TPU_WINDOW_r*.json"))
        + glob.glob(os.path.join(root, "BENCH_r*.json"))
    ):
        m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict):
            continue
        # banked artifact shapes: window artifacts wrap {final, detail}
        # (detail carries the full vocabulary; final alone still counts —
        # bank_window.py can bank a detail-less capture), and the driver's
        # round artifacts nest the same dicts under "parsed"
        parsed = doc.get("parsed") if isinstance(doc.get("parsed"), dict) else {}
        current: dict = {}
        for part in (
            doc, parsed,
            doc.get("final"), doc.get("detail"),
            parsed.get("final"), parsed.get("detail"),
        ):
            if isinstance(part, dict):
                current.update({
                    k: v for k, v in part.items() if not isinstance(v, dict)
                })
        platform = current.get("platform")
        if isinstance(platform, str):
            out.append((int(m.group(1)), platform, current))
    return out


def stale_pending_problems(
    metrics: list[dict], root: str = ROOT
) -> list[str]:
    """Pending rows whose flag has outlived a banked artifact of the right
    platform: an artifact from the row's declaration round or later
    measures the metric, so the PR that banked it should have removed the
    flag and locked the measured number. Rows without ``pending_since``
    are held to the strict reading (any measuring artifact counts)."""
    problems: list[str] = []
    artifacts = None
    for m in metrics:
        if not m.get("pending") or not m.get("name"):
            # nameless rows are already reported by the vocabulary check;
            # crashing here would turn one malformed row into a traceback
            continue
        if artifacts is None:
            artifacts = banked_artifacts(root)
        try:
            since = int(m.get("pending_since", 0))
        except (TypeError, ValueError):
            since = 0  # unparseable: strict reading, any artifact counts
        for rnd, platform, current in artifacts:
            if rnd < since:
                continue
            if m.get("platform") and platform != m["platform"]:
                continue
            if current.get(m["name"]) is None:
                continue
            problems.append(
                f"{m['name']}: pending (since round {since or '?'}) but the "
                f"banked round-{rnd} {platform} artifact measures it "
                f"({current.get(m['name'])!r}) — remove the pending flag "
                "and lock the measured baseline"
            )
            break
    return problems


def check(
    metrics: list[dict], current: dict
) -> tuple[list[tuple], int, int]:
    """Returns (table rows, n_failed, n_checked). Row: (name, baseline,
    got, direction, tolerance, verdict)."""
    platform = current.get("platform")
    rows: list[tuple] = []
    failed = checked = 0
    for m in metrics:
        name, base, direction = m["name"], m["baseline"], m["direction"]
        tol = float(m.get("tolerance", 0.0))
        want_platform = m.get("platform")
        if want_platform and platform and want_platform != platform:
            rows.append((name, base, "-", direction, tol,
                         f"SKIP (locked for {want_platform}, run is {platform})"))
            continue
        if m.get("pending"):
            # baseline declared ahead of its first banked measurement:
            # report, never fail — the flag is removed by the PR that
            # banks an artifact measuring it
            got = current.get(name)
            rows.append((
                name, base, got if got is not None else "-", direction, tol,
                "PENDING (baseline ahead of first banked measurement; "
                "remove the flag when one lands)",
            ))
            continue
        checked += 1
        got = current.get(name)
        if got is None:
            failed += 1
            rows.append((name, base, "MISSING", direction, tol,
                         "FAIL (metric absent from the run)"))
            continue
        try:
            got_f = float(got)
        except (TypeError, ValueError):
            failed += 1
            rows.append((name, base, repr(got), direction, tol,
                         "FAIL (not numeric)"))
            continue
        if direction == "up":
            floor = base * (1.0 - tol)
            ok = got_f >= floor
            bound = f">= {floor:g}"
        else:
            ceil = base * (1.0 + tol)
            ok = got_f <= ceil
            bound = f"<= {ceil:g}"
        if not ok:
            failed += 1
        rows.append((
            name, base, got_f, direction, tol,
            "ok" if ok else f"FAIL (want {bound})",
        ))
    return rows, failed, checked


def render_table(rows: list[tuple]) -> str:
    headers = ("metric", "baseline", "current", "dir", "tol", "verdict")
    table = [headers] + [
        tuple(str(c) for c in row) for row in rows
    ]
    widths = [max(len(r[i]) for r in table) for i in range(len(headers))]
    lines = []
    for i, row in enumerate(table):
        lines.append("  ".join(c.ljust(widths[j]) for j, c in enumerate(row)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--baseline", default=DEFAULT_BASELINE,
        help="ratchet file (default: repo BASELINE_RATCHET.json)",
    )
    ap.add_argument(
        "--current", default=None,
        help="bench output to check: a JSON artifact path, or '-' for stdin",
    )
    ap.add_argument(
        "--run", action="store_true",
        help="run `python bench.py` fresh and check its output",
    )
    args = ap.parse_args(argv)

    metrics = load_baseline(args.baseline)
    if args.run:
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "bench.py")],
            capture_output=True, text=True,
        )
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            print(f"bench.py exited {proc.returncode}", file=sys.stderr)
            return 2
        raw = proc.stdout
    elif args.current == "-":
        raw = sys.stdin.read()
    elif args.current:
        with open(args.current, encoding="utf-8") as f:
            raw = f.read()
    else:
        ap.error("one of --current or --run is required")
        return 2  # unreachable; argparse exits

    current = extract_current(raw)
    # banked artifacts live next to the ratchet file: a tmp-dir baseline
    # (tests, ad-hoc experiments) is judged against its own directory,
    # never against this repo's banked windows
    stale = stale_pending_problems(
        metrics, root=os.path.dirname(os.path.abspath(args.baseline)) or ROOT
    )
    rows, failed, checked = check(metrics, current)
    print(render_table(rows))
    if stale:
        for p in stale:
            print(p, file=sys.stderr)
        print(
            f"\nRATCHET FAILED: {len(stale)} pending row(s) outlived a "
            "banked artifact that measures them", file=sys.stderr,
        )
        return 1
    if checked == 0:
        print(
            "\nno ratcheted metric applies to this run's platform "
            f"({current.get('platform')!r}) — nothing enforced",
            file=sys.stderr,
        )
        return 0
    if failed:
        print(
            f"\nRATCHET FAILED: {failed} of {checked} applicable metric(s) "
            "regressed past tolerance or went missing", file=sys.stderr,
        )
        return 1
    print(f"\nratchet ok: {checked} applicable metric(s) within tolerance")
    return 0


if __name__ == "__main__":
    sys.exit(main())
