"""Tests for the ML harness: hyperparam ranges/search, eval metrics, and the
MLUpdate generation loop with a mock update (the MockMLUpdate pattern from
the reference's SimpleMLUpdateIT — SURVEY.md §4)."""


import numpy as np
import pytest

from oryx_tpu.bus.api import KeyMessage, TopicProducer
from oryx_tpu.bus.broker import get_broker
from oryx_tpu.bus.inproc import InProcBroker
from oryx_tpu.common.artifact import ModelArtifact
from oryx_tpu.common.config import load_config
from oryx_tpu.ml import (
    ContinuousRange,
    DiscreteRange,
    Unordered,
    choose_combos,
    from_config_value,
    grid_search,
    random_search,
)
from oryx_tpu.ml.evaluate import accuracy, auc_mean_per_user, rmse
from oryx_tpu.ml.update import MLUpdate


@pytest.fixture(autouse=True)
def _fresh_registry():
    InProcBroker.reset_all()
    yield
    InProcBroker.reset_all()


# ---- hyperparams ----------------------------------------------------------

def test_from_config_value_forms():
    assert isinstance(from_config_value(5), Unordered)
    assert isinstance(from_config_value([1, 2]), Unordered)
    assert isinstance(from_config_value({"min": 1, "max": 10}), DiscreteRange)
    assert isinstance(from_config_value({"min": 0.1, "max": 1.0}), ContinuousRange)


def test_discrete_range_trials():
    r = DiscreteRange(1, 10)
    vals = r.trial_values(4)
    assert vals[0] == 1 and vals[-1] == 10 and len(vals) == 4
    assert DiscreteRange(3, 3).trial_values(5) == [3]


def test_continuous_log_detection():
    assert ContinuousRange(0.001, 10.0).log is True
    assert ContinuousRange(1.0, 2.0).log is False
    vals = ContinuousRange(0.001, 10.0).trial_values(5)
    # log-spaced: ratios roughly constant
    ratios = [vals[i + 1] / vals[i] for i in range(4)]
    assert max(ratios) / min(ratios) < 1.1


def test_grid_search_budget():
    combos = grid_search(
        {"a": from_config_value([1, 2, 3]), "b": from_config_value({"min": 0.0, "max": 1.0})},
        9,
    )
    assert len(combos) == 9  # 3 x 3
    assert all(set(c) == {"a", "b"} for c in combos)


def test_random_search_deterministic_under_seed():
    ranges = {"lam": {"min": 0.001, "max": 1.0}}
    from oryx_tpu.common.rng import RandomManager

    RandomManager.use_test_seed(5)
    a = random_search({k: from_config_value(v) for k, v in ranges.items()}, 4)
    RandomManager.use_test_seed(5)
    b = random_search({k: from_config_value(v) for k, v in ranges.items()}, 4)
    assert a == b and len(a) == 4


def test_choose_combos_single_candidate_is_default_point():
    combos = choose_combos({"f": [8, 16], "lam": 0.1}, 1)
    assert combos == [{"f": 8, "lam": 0.1}]


# ---- evaluate -------------------------------------------------------------

def test_rmse_zero_for_perfect():
    x = np.eye(3)
    y = np.eye(3)
    u = np.array([0, 1])
    i = np.array([0, 1])
    v = np.array([1.0, 1.0])
    assert rmse(x, y, u, i, v) == pytest.approx(0.0)


def test_auc_separates_good_from_random():
    rng = np.random.default_rng(0)
    k = 8
    x = rng.normal(size=(30, k))
    y = rng.normal(size=(50, k))
    # test positives = the items each user truly scores highest
    scores = x @ y.T
    test_u, test_i = [], []
    for u in range(30):
        top = np.argsort(scores[u])[-3:]
        test_u += [u] * 3
        test_i += list(top)
    good = auc_mean_per_user(x, y, np.array(test_u), np.array(test_i))
    bad = auc_mean_per_user(x, rng.normal(size=(50, k)), np.array(test_u), np.array(test_i))
    assert good > 0.95
    assert abs(bad - 0.5) < 0.15


def test_accuracy():
    assert accuracy(np.array([1, 2, 3]), np.array([1, 9, 3])) == pytest.approx(2 / 3)


# ---- MLUpdate harness -----------------------------------------------------

class _MockUpdate(MLUpdate):
    """Builds a trivial 'model' whose quality is its hyperparam value."""

    def __init__(self, config):
        super().__init__(config)
        self.built = []

    def hyperparam_ranges(self):
        return {"q": [0.1, 0.9, 0.5]}

    def build_model(self, train, hyperparams):
        self.built.append(hyperparams["q"])
        return ModelArtifact("mock", extensions={"q": str(hyperparams["q"])},
                             content={"n_train": len(train)})

    def evaluate(self, model, train, test):
        return float(model.get_extension("q"))


def _run_harness(tmp_path, overlay):
    cfg = load_config(overlay=overlay)
    broker = get_broker("mem://ml")
    broker.create_topic("U", partitions=1)
    producer = TopicProducer(broker, "U")
    upd = _MockUpdate(cfg)
    data = [KeyMessage(None, f"line{i}") for i in range(100)]
    upd.run_update(1234567890123, data, [], str(tmp_path / "models"), producer)
    return upd, broker


def test_harness_picks_best_candidate_and_publishes(tmp_path):
    upd, broker = _run_harness(
        tmp_path,
        {"oryx.ml.eval.candidates": 3, "oryx.ml.eval.hyperparam-search": "grid"},
    )
    assert sorted(upd.built) == [0.1, 0.5, 0.9]
    # winner (q=0.9) atomically in model_dir/<ts>
    final = tmp_path / "models" / "1234567890123"
    assert final.is_dir()
    model = ModelArtifact.read(final)
    assert model.get_extension("q") == "0.9"
    # no candidate litter left behind
    assert not (tmp_path / "models" / ".candidates").exists()
    # published inline as MODEL, followed by its framework publish stamp
    # (key TRACE — intercepted by _dispatch_update, app handlers never see it)
    recs = broker.read("U", 0, 0, 10)
    assert [k for _, k, _ in recs] == ["MODEL", "TRACE"]
    assert ModelArtifact.from_string(recs[0][2]).get_extension("q") == "0.9"
    import json as _json

    assert _json.loads(recs[1][2])["published_ms"] > 0


def test_harness_threshold_rejects_bad_model(tmp_path):
    upd, broker = _run_harness(
        tmp_path,
        {"oryx.ml.eval.candidates": 3, "oryx.ml.eval.threshold": 0.95,
         "oryx.ml.eval.hyperparam-search": "grid"},
    )
    assert broker.read("U", 0, 0, 10) == []
    assert not (tmp_path / "models" / "1234567890123").exists()


def test_harness_train_test_split_sizes(tmp_path):
    """Binomial-style statistical assertion on the split, like
    SimpleMLUpdateIT (reference :77-95)."""
    cfg = load_config(overlay={"oryx.ml.eval.test-fraction": 0.2})

    class _SplitProbe(_MockUpdate):
        def build_model(self, train, hp):
            self.n_train = len(train)
            return super().build_model(train, hp)

        def evaluate(self, model, train, test):
            self.n_test = len(test)
            return 1.0

    upd = _SplitProbe(cfg)
    broker = get_broker("mem://ml2")
    broker.create_topic("U", partitions=1)
    data = [KeyMessage(None, f"l{i}") for i in range(1000)]
    upd.run_update(1, data, [], str(tmp_path / "m"), TopicProducer(broker, "U"))
    n_test = upd.n_test
    # mean 200, sd ~12.6; 5 sd window
    assert 137 < n_test < 263, n_test


def test_harness_model_ref_when_oversized(tmp_path):
    cfg = load_config(overlay={"oryx.update-topic.message.max-size": 64})

    class _BigModel(_MockUpdate):
        def build_model(self, train, hp):
            return ModelArtifact("mock", content={"blob": "z" * 500})

        def evaluate(self, model, train, test):
            return 1.0

    broker = get_broker("mem://ml3")
    broker.create_topic("U", partitions=1)
    upd = _BigModel(cfg)
    upd.run_update(7, [KeyMessage(None, "x")], [], str(tmp_path / "m"), TopicProducer(broker, "U"))
    recs = broker.read("U", 0, 0, 1000)
    # a 64-byte cap cannot carry even one chunk envelope: the publish
    # falls back to the bare reference instead of overrunning the topic
    assert recs[0][1] == "MODEL-REF"
    assert ModelArtifact.read(recs[0][2]).content["blob"] == "z" * 500


def test_harness_failed_candidate_tolerated(tmp_path):
    cfg = load_config(overlay={"oryx.ml.eval.candidates": 3,
                               "oryx.ml.eval.hyperparam-search": "grid"})

    class _Flaky(_MockUpdate):
        def build_model(self, train, hp):
            if hp["q"] == 0.9:
                raise RuntimeError("boom")
            return super().build_model(train, hp)

    broker = get_broker("mem://ml4")
    broker.create_topic("U", partitions=1)
    upd = _Flaky(cfg)
    upd.run_update(9, [KeyMessage(None, "x")] * 200, [], str(tmp_path / "m"),
                   TopicProducer(broker, "U"))
    recs = broker.read("U", 0, 0, 10)
    # best surviving candidate (q=0.5) won
    assert ModelArtifact.from_string(recs[0][2]).get_extension("q") == "0.5"
