"""PMML import: read models published by the reference into artifacts.

The reference publishes every model as PMML 4.3 (PMMLUtils, framework/
oryx-common .../pmml/PMMLUtils.java:45-135): ALS as a skeleton whose
Extensions carry hyperparams + factor-file paths (ALSUpdate.java:429-472),
k-means as a ClusteringModel with per-cluster center arrays and sizes
(KMeansUpdate.java:178-215), and random forests as a MiningModel holding a
Segmentation of TreeModels whose nodes use SimplePredicate GREATER_THAN /
SimpleSetPredicate IS_NOT_IN splits with per-node scores, record counts and
score distributions (RDFUpdate.java:379-538). This module parses those
documents so a deployment can migrate to this framework without
retraining: k-means imports into the native artifact (tensors.centers +
content.counts), ALS into an extensions-only skeleton, and forests into a
host-side PredicateForest evaluator (prediction parity; new training runs
produce the native vectorized forest instead).
"""

from __future__ import annotations

import threading
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field

import numpy as np

from oryx_tpu.common.artifact import ModelArtifact


def _local(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def _children(el, name: str):
    return [c for c in el if _local(c.tag) == name]


def _find(el, name: str):
    for c in el:
        if _local(c.tag) == name:
            return c
    return None


def _iter_all(el, name: str):
    for c in el.iter():
        if _local(c.tag) == name:
            yield c


def pmml_to_artifact(xml: str) -> ModelArtifact:
    """Parse a reference-published PMML document into a ModelArtifact.
    Raises ValueError for documents with no recognizable model."""
    root = ET.fromstring(xml)
    if _local(root.tag) != "PMML":
        raise ValueError(f"not a PMML document: root <{_local(root.tag)}>")

    extensions: dict = {}
    for ext in _children(root, "Extension"):
        name = ext.get("name")
        if name is None:
            continue
        value = ext.get("value")
        if value is not None:
            extensions[name] = value
        else:
            # the reference stores id lists as whitespace-separated content
            extensions[name] = (ext.text or "").split()

    clustering = _find(root, "ClusteringModel")
    if clustering is not None:
        return _clustering_to_artifact(clustering, extensions)

    mining = _find(root, "MiningModel")
    if mining is None:
        tree = _find(root, "TreeModel")
        if tree is not None:
            trees, weights = [_tree_to_dict(tree)], [1.0]
            fn = tree.get("functionName", "classification")
            return _forest_artifact(trees, weights, fn, extensions)
        if extensions:
            # ALS publishes a model-less skeleton: extensions carry
            # everything (factor paths, hyperparams, id lists)
            return ModelArtifact("als", extensions=extensions)
        raise ValueError("PMML document contains no supported model")
    return _mining_to_artifact(mining, extensions)


def _clustering_to_artifact(el, extensions) -> ModelArtifact:
    centers, counts, ids = [], [], []
    for cl in _children(el, "Cluster"):
        arr = _find(cl, "Array")
        if arr is None:
            continue
        centers.append([float(v) for v in (arr.text or "").split()])
        counts.append(int(float(cl.get("size", 0) or 0)))
        ids.append(cl.get("id", str(len(ids))))
    if not centers:
        raise ValueError("ClusteringModel has no clusters")
    art = ModelArtifact(
        "kmeans",
        extensions=extensions,
        tensors={"centers": np.asarray(centers, dtype=np.float32)},
    )
    art.content["counts"] = counts
    art.content["clusterIDs"] = ids
    return art


def _predicate_to_dict(el) -> dict | None:
    name = _local(el.tag)
    if name == "True":
        return {"op": "true"}
    if name == "False":
        return {"op": "false"}
    if name == "SimplePredicate":
        return {
            "op": el.get("operator"),
            "field": el.get("field"),
            "value": el.get("value"),
        }
    if name == "SimpleSetPredicate":
        arr = _find(el, "Array")
        values = _parse_string_array(arr)
        return {
            "op": el.get("booleanOperator"),
            "field": el.get("field"),
            "values": values,
        }
    return None


def _parse_string_array(arr) -> list[str]:
    """PMML string arrays quote values containing spaces; the reference's
    categorical sets are plain tokens, so token-split with quote stripping
    covers both."""
    if arr is None or not arr.text:
        return []
    import re

    return [
        t[1:-1] if t.startswith('"') and t.endswith('"') else t
        for t in re.findall(r'"[^"]*"|\S+', arr.text)
    ]


def _tree_to_dict(tree_el) -> dict:
    root = _find(tree_el, "Node")
    if root is None:
        raise ValueError("TreeModel has no root Node")
    return _node_to_dict(root)


def _node_to_dict(el) -> dict:
    node: dict = {"id": el.get("id")}
    if el.get("score") is not None:
        node["score"] = el.get("score")
    if el.get("recordCount") is not None:
        node["recordCount"] = float(el.get("recordCount"))
    dist = [
        {"value": sd.get("value"), "recordCount": float(sd.get("recordCount", 0))}
        for sd in _children(el, "ScoreDistribution")
    ]
    if dist:
        node["distribution"] = dist
    children = []
    for child in _children(el, "Node"):
        pred = None
        for c in child:
            tag = _local(c.tag)
            if tag in ("ScoreDistribution", "Node", "Extension"):
                continue
            pred = _predicate_to_dict(c)
            if pred is None:
                # fabricating an always-true split here would silently
                # misroute every datum — fail the import instead
                raise ValueError(f"unsupported PMML predicate element: <{tag}>")
            break
        if pred is None:
            raise ValueError(f"PMML Node {child.get('id')!r} has no predicate")
        children.append({"predicate": pred, "node": _node_to_dict(child)})
    if children:
        node["children"] = children
    return node


def _mining_to_artifact(el, extensions) -> ModelArtifact:
    seg = _find(el, "Segmentation")
    if seg is None:
        raise ValueError("MiningModel has no Segmentation")
    trees, weights = [], []
    for s in _children(seg, "Segment"):
        tm = _find(s, "TreeModel")
        if tm is None:
            continue
        trees.append(_tree_to_dict(tm))
        weights.append(float(s.get("weight", 1.0)))
    if not trees:
        raise ValueError("Segmentation has no TreeModels")
    return _forest_artifact(trees, weights, el.get("functionName", "classification"), extensions)


def _forest_artifact(trees, weights, function_name, extensions) -> ModelArtifact:
    art = ModelArtifact("rdf-pmml", extensions=extensions)
    art.content["trees"] = trees
    art.content["weights"] = weights
    art.content["functionName"] = function_name
    return art


# ---------------------------------------------------------------------------
# host evaluator for imported predicate forests
# ---------------------------------------------------------------------------


@dataclass
class PredicateForest:
    """Evaluates an imported reference forest on host: per datum walk each
    tree by predicate (the reference's DecisionTree.findTerminal,
    app/oryx-app-common .../rdf/tree/DecisionTree.java:38-63), then combine
    votes weighted by tree weight (DecisionForest.predict semantics:
    weighted majority vote for classification, weighted average for
    regression)."""

    trees: list[dict]
    weights: list[float]
    is_classification: bool = True
    # guards node-dict mutation (UP folding from the bus listener thread)
    # against concurrent predict traversals from HTTP request threads —
    # the native RDFModel keeps the same discipline with its own lock
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    @classmethod
    def from_artifact(cls, art: ModelArtifact) -> "PredicateForest":
        if art.app != "rdf-pmml":
            raise ValueError(f"not an imported PMML forest: app={art.app}")
        return cls(
            trees=art.content["trees"],
            weights=[float(w) for w in art.content["weights"]],
            is_classification=art.content.get("functionName") != "regression",
        )

    def _matches(self, pred: dict, features: dict) -> bool:
        op = pred.get("op")
        if op == "true":
            return True
        if op == "false":
            return False
        value = features.get(pred.get("field"))
        if value is None:
            return False
        if op == "greaterThan":
            return float(value) > float(pred["value"])
        if op == "greaterOrEqual":
            return float(value) >= float(pred["value"])
        if op == "lessThan":
            return float(value) < float(pred["value"])
        if op == "lessOrEqual":
            return float(value) <= float(pred["value"])
        if op == "equal":
            return str(value) == pred["value"]
        if op == "isIn":
            return str(value) in pred["values"]
        if op == "isNotIn":
            return str(value) not in pred["values"]
        raise ValueError(f"unsupported PMML predicate operator: {op}")

    def _terminal(self, tree: dict, features: dict) -> dict:
        node = tree
        while "children" in node:
            for child in node["children"]:
                if self._matches(child["predicate"], features):
                    node = child["node"]
                    break
            else:
                # nothing matched (e.g. a missing feature fails both the
                # positive predicate and its complement): descend into the
                # last child — the reference's negative/default branch
                # (RDFUpdate.java writes positive first, negative second) —
                # so every datum still reaches a leaf
                node = node["children"][-1]["node"]
        return node

    def terminal_ids(self, features: dict) -> list[str]:
        """Terminal node id per tree — the speed tier's routing pass
        (RDFSpeedModelManager groups micro-batch targets by (tree, node))."""
        with self._lock:
            return [self._terminal(t, features).get("id") for t in self.trees]

    def _find_node(self, tree_idx: int, node_id: str) -> dict | None:
        stack = [self.trees[tree_idx]]
        while stack:
            node = stack.pop()
            if node.get("id") == node_id:
                return node
            for child in node.get("children", ()):
                stack.append(child["node"])
        return None

    def update_classification_leaf(self, tree_idx: int, node_id: str, counts: dict) -> None:
        """Fold speed-layer [treeID, nodeID, counts] updates into the node's
        score distribution (RDFServingModelManager.java:57-84 — PMML node
        ids are the reference's own +/- path strings, so live updates keep
        working against an imported forest)."""
        with self._lock:
            node = self._find_node(tree_idx, node_id)
            if node is None:
                return
            dist = node.setdefault("distribution", [])
            by_value = {d["value"]: d for d in dist}
            for value, count in counts.items():
                entry = by_value.get(str(value))
                if entry is None:
                    dist.append({"value": str(value), "recordCount": float(count)})
                else:
                    entry["recordCount"] += float(count)

    def update_regression_leaf(self, tree_idx: int, node_id: str, mean: float, count: int) -> None:
        """Running-mean fold of a (mean, count) summary into the node score
        (NumericPrediction.update semantics)."""
        with self._lock:
            node = self._find_node(tree_idx, node_id)
            if node is None:
                return
            old_count = float(node.get("recordCount", 0.0))
            old_score = float(node.get("score", 0.0) or 0.0)
            total = old_count + count
            if total <= 0:
                return
            node["score"] = str((old_score * old_count + mean * count) / total)
            node["recordCount"] = total

    def predict(self, features: dict):
        """Classification: (label, distribution dict). Regression: float."""
        with self._lock:
            return self._predict_locked(features)

    def _predict_locked(self, features: dict):
        if self.is_classification:
            votes: dict[str, float] = {}
            for tree, w in zip(self.trees, self.weights):
                leaf = self._terminal(tree, features)
                dist = leaf.get("distribution")
                if dist:
                    total = sum(d["recordCount"] for d in dist) or 1.0
                    for d in dist:
                        votes[d["value"]] = votes.get(d["value"], 0.0) + w * (
                            d["recordCount"] / total
                        )
                elif leaf.get("score") is not None:
                    votes[leaf["score"]] = votes.get(leaf["score"], 0.0) + w
            if not votes:
                raise ValueError("no tree produced a prediction")
            total = sum(votes.values())
            dist = {k: v / total for k, v in votes.items()}
            return max(dist.items(), key=lambda kv: kv[1])[0], dist
        num = den = 0.0
        for tree, w in zip(self.trees, self.weights):
            leaf = self._terminal(tree, features)
            if leaf.get("score") is not None:
                num += w * float(leaf["score"])
                den += w
        if den == 0.0:
            raise ValueError("no tree produced a prediction")
        return num / den
