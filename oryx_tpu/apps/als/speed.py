"""ALS speed tier: micro-batch fold-in deltas.

Mirrors ALSSpeedModelManager (app/oryx-app .../speed/als/
ALSSpeedModelManager.java:68-221): consume MODEL/MODEL-REF (new or retained
state keyed on the features hyperparam) and UP X/Y vector writes; per
micro-batch, aggregate interactions with the batch tier's dup semantics and
compute fold-in deltas for BOTH the user and item vectors of every
interaction against the cached X^T.X / Y^T.Y solvers — emitted as UP
messages. Skips everything until the model is min-model-load-fraction
loaded. The fold-in solves run as one vmapped batch on device rather than a
parallelStream over interactions.
"""

from __future__ import annotations

import logging

import numpy as np

from oryx_tpu.api import AbstractSpeedModelManager
from oryx_tpu.common.config import Config
from oryx_tpu.common.locks import RateLimitCheck
from oryx_tpu.ops.als import aggregate_interactions, fold_in_batch, fold_in_batch_explicit
from oryx_tpu.apps.als.common import (
    ALSConfig,
    parse_events,
    x_update_message,
    y_update_message,
)
from oryx_tpu.apps.als.state import ALSState, apply_update_message

log = logging.getLogger(__name__)


class ALSSpeedModelManager(AbstractSpeedModelManager):
    def __init__(self, config: Config):
        self.config = config
        self.als = ALSConfig.from_config(config)
        self.min_fraction = config.get_float("oryx.speed.min-model-load-fraction", 0.8)
        self.state: ALSState | None = None
        self._not_ready_log = RateLimitCheck(60.0)

    # -- update-topic consumption ------------------------------------------

    def consume_key_message(self, key: str | None, message: str) -> None:
        self.state = apply_update_message(
            self.state, key, message, with_known_items=False
        )

    # -- micro-batch -> updates --------------------------------------------

    def build_updates(self, new_data):
        st = self.state
        if st is None or st.fraction_loaded() < self.min_fraction:
            if self._not_ready_log.test():
                log.info("speed model not yet loaded; skipping micro-batch")
            return []
        users, items, vals, tss = parse_events(new_data)
        if len(vals) == 0:
            return []
        # same strength transform the batch model was trained with — folding
        # raw strengths into a log1p-trained model would overweight them
        agg = aggregate_interactions(
            users, items, vals, tss,
            implicit=st.implicit,
            zero_threshold=self.als.zero_threshold,
            log_strength=self.als.log_strength,
            epsilon=self.als.epsilon,
        )
        if len(agg.values) == 0:
            return []

        # gather current vectors; zeros mark absent (new) entities
        k = st.features
        xu = np.zeros((len(agg.values), k), dtype=np.float32)
        yi = np.zeros((len(agg.values), k), dtype=np.float32)
        have_y = np.zeros(len(agg.values), dtype=bool)
        for j in range(len(agg.values)):
            u_vec = st.x.get(agg.user_ids[agg.users[j]])
            i_vec = st.y.get(agg.item_ids[agg.items[j]])
            if u_vec is not None:
                xu[j] = u_vec
            if i_vec is not None:
                yi[j] = i_vec
                have_y[j] = True

        out: list[tuple[str, str]] = []
        fold = fold_in_batch if st.implicit else fold_in_batch_explicit
        vals32 = agg.values.astype(np.float32)

        # user-side deltas need Y'Y; item-side need X'X — both one vmapped
        # solve over the whole micro-batch
        chol_y = st.yty.get()
        if chol_y is not None and have_y.any():
            new_xu = np.asarray(fold(chol_y, vals32, xu, yi))
            for j in np.nonzero(have_y)[0]:
                uid = agg.user_ids[agg.users[j]]
                iid = agg.item_ids[agg.items[j]]
                if np.all(np.isfinite(new_xu[j])):
                    out.append(x_update_message(uid, new_xu[j], [iid]))
        chol_x = st.xtx.get()
        have_x = np.any(xu != 0.0, axis=1)
        if chol_x is not None and have_x.any():
            new_yi = np.asarray(fold(chol_x, vals32, yi, xu))
            for j in np.nonzero(have_x)[0]:
                iid = agg.item_ids[agg.items[j]]
                if np.all(np.isfinite(new_yi[j])):
                    out.append(y_update_message(iid, new_yi[j]))
        return out
