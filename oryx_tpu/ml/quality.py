"""Shared build-and-evaluate harnesses: the bench's training stages and
the nightly quality gates (tests/test_quality_gate.py) run the SAME
code, so a silent quality regression in any trainer fails both.

- ALS: the bf16 singularity guard (ops/als.py _half_step jitter retry)
  cannot silently regress between bench runs. Measures what
  BASELINE.json's north star asks for: end-to-end build wall-clock at a
  given interaction scale plus held-out mean-per-user AUC — with NaN
  factor rows surfaced as a first-class diagnostic.
- RDF: planted-rule synthetic at covertype shape with a held-out
  accuracy floor (reference eval: RDFUpdate.java:179-205).
- k-means: planted Gaussian blobs; SSE against the true generating
  centers plus silhouette (reference eval strategies:
  KMeansUpdate.java:137-173 and the four metric classes).
- Serving recall gate: the quantized (int8 + exact rescore) and approx
  (partial-reduce) score modes are measured for recall@k against the
  exact top-k on a standing synthetic corpus; either mode below
  MIN_SCORE_MODE_RECALL fails the QUALITY bench — speed can never
  silently buy wrong answers.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

# The recall@k floor quantized/approx serving must hold against exact
# top-k (the serve path's acceptance bar; enforced by the tier-1 gate in
# tests/test_quality_gate.py and the nightly QUALITY artifact).
MIN_SCORE_MODE_RECALL = 0.95


@dataclass
class BuildReport:
    build_s: float
    agg_s: float
    auc: float
    nan_rows: int
    interactions: int
    timings: dict = field(default_factory=dict)


def build_and_evaluate(
    n_users: int,
    n_items: int,
    nnz: int,
    features: int = 50,
    iterations: int = 10,
    lam: float = 0.01,
    alpha: float = 1.0,
    compute_dtype: str = "bfloat16",
    seed: int = 7,
    holdout_p: float = 0.02,
    sample_users: int = 2000,
) -> BuildReport:
    """Synthesize (oryx_tpu/ml/synth.py), train, and evaluate one ALS
    build. compute_dtype="bfloat16" is the MXU-native default — quality-
    neutral on this generator (AUC 0.947 bf16 vs 0.939 f32 at 1M scale),
    and the held-out AUC keeps that claim measured on every run."""
    from oryx_tpu.ml.evaluate import auc_mean_per_user
    from oryx_tpu.ml.synth import synthesize_interactions
    from oryx_tpu.ops.als import aggregate_interactions, train_als

    # offset the eval stream from the data stream: same-seed generators
    # share the underlying bitstream, which would correlate the holdout
    # mask with the generator's user-activity draws
    rng = np.random.default_rng(seed + 1_000_003)
    users, items, values = synthesize_interactions(
        n_users, n_items, nnz, seed=seed
    )
    test_mask = rng.random(nnz) < holdout_p
    tr = ~test_mask

    t0 = time.perf_counter()
    data = aggregate_interactions(users[tr], items[tr], values[tr], implicit=True)
    agg_s = time.perf_counter() - t0
    timings: dict = {}
    model = train_als(
        data,
        features=features,
        lam=lam,
        alpha=alpha,
        iterations=iterations,
        implicit=True,
        compute_dtype=compute_dtype,
        timings=timings,
    )
    build_s = time.perf_counter() - t0

    x_np = np.asarray(model.x, dtype=np.float32)
    y_np = np.asarray(model.y, dtype=np.float32)
    nan_rows = int(
        np.isnan(x_np).any(axis=1).sum() + np.isnan(y_np).any(axis=1).sum()
    )

    # AUC on a user sample (a full per-user python loop would dominate
    # the wall-clock; 2000 users gives a +/-0.005 CI on the mean)
    uid_to_row = {u: j for j, u in enumerate(model.user_ids)}
    iid_to_row = {i: j for j, i in enumerate(model.item_ids)}
    tu_all, ti_all = users[test_mask], items[test_mask]
    known: dict[int, set[int]] = {}
    tu, ti = [], []
    sample = set(
        rng.choice(
            np.unique(tu_all),
            size=min(sample_users, len(np.unique(tu_all))),
            replace=False,
        ).tolist()
    )
    for u, i in zip(tu_all, ti_all):
        if u not in sample:
            continue
        ur, ir = uid_to_row.get(str(u)), iid_to_row.get(str(i))
        if ur is None or ir is None:
            continue
        tu.append(ur)
        ti.append(ir)
    # known (training) items for the sampled users, excluded as negatives
    smp = np.isin(users, np.fromiter(sample, dtype=np.int64)) & tr
    for u, i in zip(users[smp], items[smp]):
        ur, ir = uid_to_row.get(str(u)), iid_to_row.get(str(i))
        if ur is not None and ir is not None:
            known.setdefault(ur, set()).add(ir)
    auc = auc_mean_per_user(
        model.x,
        model.y,
        np.asarray(tu, dtype=np.int64),
        np.asarray(ti, dtype=np.int64),
        known,
    )
    return BuildReport(
        build_s=build_s,
        agg_s=agg_s,
        auc=float(auc),
        nan_rows=nan_rows,
        interactions=nnz,
        timings=timings,
    )


@dataclass
class RecallReport:
    """Measured recall@k of the approximate serving score modes against
    exact top-k on the standing corpus. green = both modes at/above the
    floor."""

    recall_quantized: float
    recall_approx: float
    k: int
    n_queries: int
    n_items: int
    features: int
    min_recall: float
    approx_recall_target: float
    eval_s: float

    @property
    def green(self) -> bool:
        return (
            self.recall_quantized >= self.min_recall
            and self.recall_approx >= self.min_recall
        )


def mean_recall_at_k(got_idx: np.ndarray, exact_idx: np.ndarray, k: int) -> float:
    """Mean per-query |top-k ∩ exact top-k| / k — the ONE recall
    definition the gate and the bench's measured-recall fields share, so
    the numbers they report can never drift in meaning."""
    return float(
        np.mean([
            len(set(map(int, g[:k])) & set(map(int, e[:k]))) / k
            for g, e in zip(got_idx, exact_idx)
        ])
    )


def evaluate_score_mode_recall(
    n_items: int = 100_000,
    features: int = 50,
    k: int = 10,
    n_queries: int = 256,
    seed: int = 23,
    approx_recall_target: float = 0.95,
    min_recall: float = MIN_SCORE_MODE_RECALL,
    overfetch: int | None = None,
) -> RecallReport:
    """Measure recall@k of the quantized and approx serving modes against
    the exact top-k on a standing synthetic corpus (deterministic seed —
    the same corpus every run, so the number is a gate, not a dice roll).

    Each mode is evaluated the way serving actually runs it
    (apps/als/serving.py): the device kernel selects an over-fetched
    candidate set, the candidates are re-ranked EXACTLY in f32, and the
    top-k of that re-rank is what a client sees. So this measures the
    mode's end answer, not the raw kernel's. The overfetch defaults to
    k + 8 — the rescore set a NO-EXCLUSION request actually gets back
    from the batcher (it slices the dispatch's k-bucket down to the
    request's own k = how_many + |exclude| + 8 before the rescore), so
    the gate is never more forgiving than production's weakest case.

    On CPU hosts jax.lax.approx_max_k computes exactly, so the approx row
    gates the plumbing there and the real recall target on TPU.
    """
    import jax.numpy as jnp

    from oryx_tpu.ops.als import (
        topk_dot_batch_approx, topk_dot_batch_quant_xla, topk_dot_batch_xla,
    )
    from oryx_tpu.ops.transfer import quantize_rows_int8

    t0 = time.perf_counter()
    rng = np.random.default_rng(seed)
    # factor-model-shaped corpus: low-rank structure plus noise, like a
    # trained Y — pure iid gaussians under-stress quantization (scores
    # concentrate), planted structure gives realistic near-ties
    basis = rng.standard_normal((max(8, features // 4), features))
    y = (
        rng.standard_normal((n_items, basis.shape[0])) @ basis
        + 0.5 * rng.standard_normal((n_items, features))
    ).astype(np.float32)
    xs = rng.standard_normal((n_queries, features)).astype(np.float32)

    # the serving over-fetch: exactly the candidate set a no-exclusion
    # request's exact rescore sees (serving requests k = how_many + 8;
    # the batcher returns that many rows of its k-bucket dispatch)
    if overfetch is None:
        overfetch = min(n_items, k + 8)

    xs_j, y_j = jnp.asarray(xs), jnp.asarray(y)
    _, exact_idx = topk_dot_batch_xla(xs_j, y_j, k=k)
    exact_idx = np.asarray(exact_idx)

    def rescored_topk(cand_idx: np.ndarray) -> np.ndarray:
        """Exact f32 re-rank of each query's candidate rows (the serve
        path's _rerank_exact), then top-k."""
        out = np.empty((n_queries, k), dtype=np.int64)
        for qi in range(n_queries):
            rows = cand_idx[qi]
            scores = y[rows] @ xs[qi]
            order = np.argsort(-scores, kind="stable")[:k]
            out[qi] = rows[order]
        return out

    # quantized: int8 + per-row scale selection, exact rescore
    q, scale = quantize_rows_int8(y)
    _, q_idx = topk_dot_batch_quant_xla(
        xs_j, jnp.asarray(q), jnp.asarray(scale), k=overfetch
    )
    recall_q = mean_recall_at_k(rescored_topk(np.asarray(q_idx)), exact_idx, k)

    # approx: the REAL partial-reduce serving kernel (ops/als.py) at the
    # recall target, exact rescore of whatever it returns
    _, a_idx = topk_dot_batch_approx(
        xs_j, y_j, k=min(overfetch, n_items), recall=approx_recall_target
    )
    recall_a = mean_recall_at_k(rescored_topk(np.asarray(a_idx)), exact_idx, k)

    return RecallReport(
        recall_quantized=recall_q,
        recall_approx=recall_a,
        k=k,
        n_queries=n_queries,
        n_items=n_items,
        features=features,
        min_recall=min_recall,
        approx_recall_target=approx_recall_target,
        eval_s=time.perf_counter() - t0,
    )


@dataclass
class SeqReport:
    """Planted-transition next-item gate: sessions walk a hidden
    successor structure, the GRU must recover it. green = hit-rate@k on
    held-out final transitions at/above the floor."""

    build_s: float
    window_s: float          # sessionize+window ingest wall-clock
    hit_rate: float          # hit-rate@k on held-out next items
    k: int
    examples: int            # training examples after windowing
    n_items: int
    n_sessions: int
    epochs_run: int

    @property
    def chance(self) -> float:
        return self.k / max(1, self.n_items)


def synthesize_sessions(
    n_items: int,
    n_sessions: int,
    session_len: int,
    seed: int = 11,
    follow_p: float = 0.85,
) -> list[np.ndarray]:
    """Planted-successor sessions: each item i has a hidden successor
    succ(i) = (i*7 + 3) mod V (a permutation when gcd(7, V) = 1); a
    session walks succ with probability follow_p, else jumps uniformly.
    A healthy next-item model must put succ(current) high; chance is
    k/V. Returns one int64 item-row array per session."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_sessions):
        it = int(rng.integers(0, n_items))
        seq = [it]
        for _ in range(session_len - 1):
            if rng.random() < follow_p:
                it = (it * 7 + 3) % n_items
            else:
                it = int(rng.integers(0, n_items))
            seq.append(it)
        out.append(np.asarray(seq, dtype=np.int64))
    return out


def build_and_evaluate_seq(
    n_items: int = 2000,
    n_sessions: int = 3000,
    session_len: int = 10,
    dim: int = 32,
    window: int = 8,
    epochs: int = 12,
    lr: float = 0.5,
    k: int = 10,
    holdout_sessions: float = 0.2,
    seed: int = 11,
) -> SeqReport:
    """Synthesize planted-transition sessions, window them (the SAME
    windowing the app's ingest uses, apps/seq/common.py), train the GRU
    (ops/seq.py) and measure hit-rate@k on each held-out session's FINAL
    transition — the serving question ("what comes next?") asked about
    the future, exactly the batch tier's temporal holdout shape."""
    import jax

    from oryx_tpu.apps.seq.common import windowed_examples
    from oryx_tpu.ops.seq import next_item_hit_rate, train_gru

    sessions = synthesize_sessions(n_items, n_sessions, session_len, seed=seed)
    rng = np.random.default_rng(seed + 1_000_003)
    eval_mask = rng.random(len(sessions)) < holdout_sessions
    item_ids = [str(i) for i in range(n_items)]
    item_to_row = {s: i for i, s in enumerate(item_ids)}

    t0 = time.perf_counter()
    train_sessions = {
        f"s{j}": [str(i) for i in (s[:-1] if eval_mask[j] else s)]
        for j, s in enumerate(sessions)
    }
    contexts, mask, targets = windowed_examples(
        train_sessions, item_to_row, window
    )
    window_s = time.perf_counter() - t0

    t1 = time.perf_counter()
    model, epochs_run = train_gru(
        contexts, mask, targets,
        n_items=n_items, dim=dim, item_ids=item_ids,
        epochs=epochs, lr=lr,
        seed_key=jax.random.PRNGKey(seed),
    )
    build_s = time.perf_counter() - t1

    # held-out final transitions: context = the session minus its last
    # event, target = the last event (padded by the app's own helper)
    from oryx_tpu.apps.seq.common import pad_examples

    ev_rows = [j for j in range(len(sessions)) if eval_mask[j]]
    ctx, cmask, tgt = pad_examples(
        [sessions[j][:-1][-window:] for j in ev_rows],
        [int(sessions[j][-1]) for j in ev_rows],
        window,
    )
    hit = next_item_hit_rate(model.e, model.params, ctx, cmask, tgt, k=k)
    return SeqReport(
        build_s=build_s,
        window_s=window_s,
        hit_rate=float(hit),
        k=k,
        examples=int(targets.shape[0]),
        n_items=n_items,
        n_sessions=n_sessions,
        epochs_run=epochs_run,
    )


@dataclass
class RDFReport:
    build_s: float
    accuracy: float
    examples: int
    trees: int
    noise_rate: float
    n_classes: int

    @property
    def accuracy_ceiling(self) -> float:
        """Achievable held-out accuracy: flipped labels agree with the
        rule by chance 1/n_classes of the time. Lives here, next to the
        label-flip code it must match."""
        return 1.0 - self.noise_rate * (1.0 - 1.0 / self.n_classes)


def build_and_evaluate_rdf(
    n_examples: int = 581_012,
    n_features: int = 54,
    n_classes: int = 7,
    num_trees: int = 20,
    max_depth: int = 10,
    noise_rate: float = 0.1,
    holdout_p: float = 0.1,
    seed: int = 13,
    feature_subset: str | int = 14,
) -> RDFReport:
    """Planted-rule synthetic at UCI-covertype shape (581k x 54, 7
    classes — BASELINE.json config #3): the label is a deterministic
    rule over a handful of feature thresholds with `noise_rate` labels
    flipped, so the achievable held-out accuracy is ~(1 - noise_rate)
    and a healthy forest must land near it. Defaults mirror the
    reference's covertype example config (oryx.rdf.num-trees etc.).

    The rule mixes axis-aligned thresholds (what trees split on) across
    several features with unequal class difficulty — deep enough that a
    stump can't ace it, learnable enough that a regressed trainer
    (broken histogram splits, bad bootstrap, mis-grown depth) falls far
    below the floor.

    feature_subset defaults to 14 (~P/4), not "auto" (sqrt(54)=7): the
    planted rule spans 4 of 54 features, and sqrt-sized per-node subsets
    rarely offer a relevant feature near the root. Round-5 sweep at 100k
    examples: auto 0.894, 14 0.8986, 27 0.8985, depth 12 and 20 trees
    and 64 bins each neutral-or-worse — the subset size is the one knob
    that moved the number.
    """
    from oryx_tpu.ops.rdf import bin_dataset, grow_forest, predict_class_probs

    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n_examples, n_features)).astype(np.float32)
    # planted rule over 4 axis-aligned thresholds — exactly representable
    # by depth>=4 trees, so the held-out ceiling is (1 - noise) plus the
    # chance agreement of flipped labels, and any shortfall measures the
    # TRAINER (histogram splits, bootstrap, subset sampling), not an
    # inexpressible concept
    r1 = (x[:, 0] > 0).astype(np.int64)
    r2 = (x[:, 7] > 0.5).astype(np.int64)
    r3 = (x[:, 21] > -0.5).astype(np.int64)
    r4 = (x[:, 40] > 0.3).astype(np.int64)
    y_true = (r1 * 4 + r2 * 2 + r3 + r4) % n_classes
    flip = rng.random(n_examples) < noise_rate
    y = np.where(
        flip, rng.integers(0, n_classes, n_examples), y_true
    ).astype(np.int32)

    test = rng.random(n_examples) < holdout_p
    tr = ~test

    t0 = time.perf_counter()
    binned = bin_dataset(
        x[tr],
        is_categorical=np.zeros(n_features, dtype=bool),
        category_counts=np.zeros(n_features, dtype=np.int32),
        max_split_candidates=32,
    )
    forest = grow_forest(
        binned, y[tr], num_trees=num_trees, max_depth=max_depth,
        impurity="entropy", n_classes=n_classes,
        feature_subset=feature_subset,
    )
    build_s = time.perf_counter() - t0

    # bin the held-out rows with the TRAINING edges (ops/rdf.py
    # bin_column — the same path serving uses, apps/rdf/common.py)
    from oryx_tpu.ops.rdf import bin_column

    xt = x[test]
    test_binned = np.empty_like(xt, dtype=np.int32)
    for j in range(n_features):
        test_binned[:, j] = bin_column(
            xt[:, j], binned.edges[j], int(binned.n_bins[j])
        )
    probs = predict_class_probs(forest, test_binned)
    acc = float((np.argmax(probs, axis=1) == y[test]).mean())
    return RDFReport(
        build_s=build_s,
        accuracy=acc,
        examples=n_examples,
        trees=num_trees,
        noise_rate=noise_rate,
        n_classes=n_classes,
    )


@dataclass
class KMeansReport:
    build_s: float
    sse_ratio: float  # model SSE / planted-centers SSE (1.0 = perfect)
    silhouette: float
    points: int
    k: int


def build_and_evaluate_kmeans(
    n_points: int = 1_000_000,
    dims: int = 20,
    k: int = 50,
    iterations: int = 10,
    spread: float = 5.0,
    seed: int = 19,
) -> KMeansReport:
    """Planted Gaussian blobs: k true centers at `spread` separation,
    unit-variance clusters. A healthy k-means|| + Lloyd's run recovers
    near the generating structure: SSE within a small factor of the
    planted-centers SSE, positive silhouette. A regressed init (bad
    k-means|| weighting) or broken Lloyd's update inflates SSE or
    collapses clusters and fails the floors."""
    from oryx_tpu.ops.kmeans import (
        silhouette_coefficient,
        sum_squared_error,
        train_kmeans,
    )

    rng = np.random.default_rng(seed)
    centers_true = (rng.standard_normal((k, dims)) * spread).astype(np.float32)
    pts = (
        centers_true[rng.integers(0, k, n_points)]
        + rng.standard_normal((n_points, dims))
    ).astype(np.float32)

    t0 = time.perf_counter()
    model = train_kmeans(pts, k=k, iterations=iterations)
    build_s = time.perf_counter() - t0

    sse_model = sum_squared_error(pts, model.centers)
    sse_true = sum_squared_error(pts, centers_true)
    sil = silhouette_coefficient(pts, model.centers)
    return KMeansReport(
        build_s=build_s,
        sse_ratio=float(sse_model / sse_true),
        silhouette=float(sil),
        points=n_points,
        k=k,
    )
