"""Shared helpers for the per-app end-to-end lambda-slice suites."""

import urllib.error
import urllib.request


def http_request(method, url, body=None, accept="application/json"):
    req = urllib.request.Request(
        url, method=method, data=body, headers={"Accept": accept}
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()
