"""Linear-system solves with singularity detection.

The reference factors Y^T.Y once (RRQR with a rank check) and reuses the
factorization for many right-hand sides (LinearSystemSolver.getSolver,
framework/oryx-common .../math/LinearSystemSolver.java:38-80; Solver.java:
31-48), raising on singular systems. TPU-native equivalent: Cholesky of the
(symmetric PSD) Gram matrix, cached as its factor; solves are batched
triangular solves that vmap cleanly. Singularity is flagged by NaNs in the
factor or an extreme diagonal condition estimate — checked on host at
factorization time, mirroring the reference's apparent-rank test.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


class SingularMatrixError(Exception):
    """Raised when the system is singular/ill-conditioned
    (reference SingularMatrixSolverException)."""


_MAX_COND = 1e10


@jax.jit
def _cholesky(a):
    return jnp.linalg.cholesky(a.astype(jnp.float32))


@jax.jit
def _chol_solve(chol, b):
    b = b.astype(jnp.float32)
    y = jax.scipy.linalg.solve_triangular(chol, b, lower=True)
    return jax.scipy.linalg.solve_triangular(chol.T, y, lower=False)


@dataclass(frozen=True)
class Solver:
    """A factored SPD system; solve() accepts one RHS vector or a batch."""

    chol: jax.Array

    def solve(self, b):
        x = _chol_solve(self.chol, jnp.asarray(b).T).T
        return x

    def solve_f(self, b) -> np.ndarray:
        return np.asarray(self.solve(b), dtype=np.float32)


def make_solver(packed_or_full) -> Solver:
    """Factor an SPD matrix (e.g. Y^T.Y). Accepts the full [K,K] matrix or
    the packed lower-triangular row-major form the reference passes around
    (LinearSystemSolver.java:38-56)."""
    a = np.asarray(packed_or_full, dtype=np.float32)
    if a.ndim == 1:
        # packed lower triangle -> full symmetric
        n = int((np.sqrt(8 * a.size + 1) - 1) / 2)
        if n * (n + 1) // 2 != a.size:
            raise ValueError(f"not a packed triangular size: {a.size}")
        full = np.zeros((n, n), dtype=np.float32)
        full[np.tril_indices(n)] = a
        full = full + np.tril(full, -1).T
        a = full
    chol = _cholesky(jnp.asarray(a))
    chol_np = np.asarray(chol)
    if not np.all(np.isfinite(chol_np)):
        raise SingularMatrixError("Cholesky failed: matrix not positive definite")
    d = np.abs(np.diag(chol_np))
    if d.min() <= 0 or (d.max() / max(d.min(), 1e-30)) ** 2 > _MAX_COND:
        raise SingularMatrixError(
            f"ill-conditioned system (cond~{(d.max() / max(d.min(), 1e-30)) ** 2:.2e})"
        )
    return Solver(chol)


@jax.jit
def batched_spd_solve(a, b):
    """Solve a_i x_i = b_i for a batch of small SPD systems [N,K,K],[N,K].
    The per-user normal-equation solve at the heart of ALS; vmapped
    Cholesky keeps it on-device with static shapes."""
    chol = jnp.linalg.cholesky(a.astype(jnp.float32))
    y = jax.scipy.linalg.solve_triangular(chol, b.astype(jnp.float32)[..., None], lower=True)
    x = jax.scipy.linalg.solve_triangular(
        jnp.swapaxes(chol, -1, -2), y, lower=False
    )
    return x[..., 0]
