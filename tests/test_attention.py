"""Ring sequence-parallel attention vs the exact single-device reference,
on the virtual 8-device CPU mesh (conftest)."""

from __future__ import annotations

import jax
import numpy as np
import pytest

from oryx_tpu.ops.attention import attention, ring_attention
from oryx_tpu.parallel.mesh import MeshSpec, make_mesh


def _mesh(n):
    return make_mesh(MeshSpec(data=n, model=1), jax.devices()[:n])


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_shards", [2, 4, 8])
def test_ring_matches_exact_2d(causal, n_shards):
    rng = np.random.default_rng(0)
    s, d = 64, 16
    q = rng.standard_normal((s, d)).astype(np.float32)
    k = rng.standard_normal((s, d)).astype(np.float32)
    v = rng.standard_normal((s, d)).astype(np.float32)
    ref = attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, _mesh(n_shards), causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_exact_batched_heads(causal):
    rng = np.random.default_rng(1)
    b, h, s, d = 2, 3, 32, 8
    q = rng.standard_normal((b, h, s, d)).astype(np.float32)
    k = rng.standard_normal((b, h, s, d)).astype(np.float32)
    v = rng.standard_normal((b, h, s, d)).astype(np.float32)
    ref = attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, _mesh(4), causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_output_keeps_sequence_sharding():
    rng = np.random.default_rng(2)
    s, d = 32, 8
    q = rng.standard_normal((s, d)).astype(np.float32)
    mesh = _mesh(4)
    out = ring_attention(q, q, q, mesh)
    # output stays sharded over the data axis (no implicit gather)
    assert len(out.sharding.device_set) == 4


def test_rejects_indivisible_sequence():
    q = np.zeros((30, 8), dtype=np.float32)
    with pytest.raises(ValueError):
        ring_attention(q, q, q, _mesh(4))


def test_causal_first_token_attends_only_itself():
    rng = np.random.default_rng(3)
    s, d = 16, 4
    q = rng.standard_normal((s, d)).astype(np.float32)
    k = rng.standard_normal((s, d)).astype(np.float32)
    v = rng.standard_normal((s, d)).astype(np.float32)
    out = ring_attention(q, k, v, _mesh(2), causal=True)
    np.testing.assert_allclose(np.asarray(out)[0], v[0], atol=1e-5)
