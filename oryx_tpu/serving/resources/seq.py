"""Seq REST endpoint surface — the session next-item recommender.

  GET /recommend-next/{itemID}/{itemID}/...   next items for a session
      whose history is the given item path (oldest -> newest);
      ?howMany= caps the count; the session's own items are excluded.
  POST /event                                 raw session-event lines
      (user,session,item,ts) -> input topic, the app-named alias of
      /ingest (clustering's /add, classreg's /train).
"""

from __future__ import annotations

from oryx_tpu.serving.app import (
    OryxServingException, Request, ServingApp, deferred_map,
)
from oryx_tpu.serving.resources.common import send_input_lines


def _how_many(req: Request, default: int = 10) -> int:
    try:
        how_many = int(req.q1("howMany", str(default)))
    except ValueError as e:
        raise OryxServingException(400, f"bad howMany: {e}") from None
    if how_many <= 0:
        raise OryxServingException(400, "howMany must be positive")
    return how_many


def register(app: ServingApp) -> None:
    # NOT nonblocking: the plan path can rebuild the device view after a
    # model update (full E upload under the sync lock) — too heavy for
    # inline event-loop dispatch; the worker-pool hop stays.
    @app.route("GET", "/recommend-next/{items:rest}")
    def recommend_next(a: ServingApp, req: Request):
        model = a.get_serving_model()
        items = [i for i in req.params["items"].split("/") if i]
        if not items:
            raise OryxServingException(400, "no session items given")
        how_many = _how_many(req)
        fut = model.next_items_async(items, how_many, exclude=set(items))

        def _render(pairs):
            if pairs is None:
                raise OryxServingException(
                    404, "no known item in the session context"
                )
            return pairs

        return deferred_map(fut, _render)

    @app.route("POST", "/event")
    def post_event(a: ServingApp, req: Request):
        n = send_input_lines(a, req.body_text(), "session events")
        return 200, {"ingested": n}

    def _console_rows(a: ServingApp):
        model = a.get_serving_model()
        st = model.state
        return [
            ("Seq model items", len(st.items)),
            ("dim", st.dim),
            ("window", st.window),
            ("served view version", model.served_version()),
        ]

    app.console_sections.append(("Seq next-item model", _console_rows))
