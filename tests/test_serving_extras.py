"""Serving runtime extras: gzip response encoding, the HTML console, and
TLS termination (parity with the reference's Tomcat connector features:
compression, per-app console, keystore TLS)."""

from __future__ import annotations

import gzip
import http.client
import json
import shutil
import socket
import ssl
import subprocess
import time
import urllib.request

import pytest

from oryx_tpu.common.config import load_config
from oryx_tpu.bus.broker import get_broker
from oryx_tpu.serving.server import ServingLayer


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _config(bus: str, port: int, **extra):
    overlay = {
        "oryx.input-topic.broker": bus,
        "oryx.update-topic.broker": bus,
        "oryx.serving.api.port": port,
        "oryx.serving.model-manager-class": "oryx_tpu.apps.example.serving.ExampleServingModelManager",
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.example",
        ],
    }
    overlay.update(extra)
    return load_config(overlay=overlay)


def _setup_bus(bus: str):
    broker = get_broker(bus)
    for t in ("OryxInput", "OryxUpdate"):
        if not broker.topic_exists(t):
            broker.create_topic(t, 1)
    broker.send("OryxUpdate", "MODEL", json.dumps({"big": 1, "word": 2}))
    return broker


def _wait_ready(port: int, scheme="http", context=None):
    for _ in range(100):
        try:
            req = urllib.request.Request(f"{scheme}://127.0.0.1:{port}/ready")
            with urllib.request.urlopen(req, timeout=2, context=context) as r:
                if r.status == 200:
                    return
        except Exception:
            pass
        time.sleep(0.1)
    raise TimeoutError("serving layer never became ready")


class _StubManager:
    """Model-manager stub for tests that only exercise startup/routing."""

    def __init__(self, config=None):
        self.config = config

    def consume(self, it):
        pass

    def get_model(self):
        return None


def test_gzip_response_and_console():
    port = _free_port()
    _setup_bus("mem://extras1")
    # fat model so /distinct exceeds the 1KB compression floor
    get_broker("mem://extras1").send(
        "OryxUpdate", "MODEL", json.dumps({f"word{i}": i for i in range(400)})
    )
    with ServingLayer(_config("mem://extras1", port)) as sl:
        _wait_ready(sl.port)
        conn = http.client.HTTPConnection("127.0.0.1", sl.port, timeout=5)
        conn.request("GET", "/distinct", headers={"Accept-Encoding": "gzip"})
        resp = conn.getresponse()
        body = resp.read()
        assert resp.getheader("Content-Encoding") == "gzip"
        data = json.loads(gzip.decompress(body))
        assert data["word399"] == 399

        # small responses are sent uncompressed
        conn.request("GET", "/ready", headers={"Accept-Encoding": "gzip"})
        resp = conn.getresponse()
        assert resp.getheader("Content-Encoding") is None
        resp.read()

        # console renders HTML with the route table + load state
        conn.request("GET", "/console")
        resp = conn.getresponse()
        html = resp.read().decode()
        assert resp.status == 200
        assert resp.getheader("Content-Type").startswith("text/html")
        assert "/distinct" in html and "Model loaded" in html
        conn.close()


@pytest.mark.skipif(shutil.which("openssl") is None, reason="openssl not available")
def test_tls_termination(tmp_path):
    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(cert), "-days", "1",
            "-subj", "/CN=localhost",
        ],
        check=True,
        capture_output=True,
    )
    port = _free_port()
    _setup_bus("mem://extras2")
    cfg = _config(
        "mem://extras2",
        port,
        **{
            "oryx.serving.api.ssl-cert-file": str(cert),
            "oryx.serving.api.ssl-key-file": str(key),
        },
    )
    with ServingLayer(cfg) as sl:
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        _wait_ready(sl.port, scheme="https", context=ctx)
        with urllib.request.urlopen(
            f"https://127.0.0.1:{sl.port}/distinct", timeout=5, context=ctx
        ) as r:
            assert r.status == 200
            assert json.loads(r.read())["word"] == 2


def test_ingest_multipart_upload(tmp_path):
    """/ingest accepts multipart/form-data file uploads, including gzipped
    parts (reference AbstractOryxResource upload handling)."""
    import gzip

    from oryx_tpu.api import ServingModelManager
    from oryx_tpu.bus.broker import get_broker, topics
    from oryx_tpu.bus.inproc import InProcBroker
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.app import Request, ServingApp

    InProcBroker.reset_all()
    topics.maybe_create("mem://mp", "OryxInput", partitions=1)

    class Manager(ServingModelManager):
        def __init__(self, config):
            self.config = config

        def consume(self, it):
            pass

        def get_model(self):
            return None

    cfg = load_config(overlay={
        "oryx.id": "mp",
        "oryx.input-topic.broker": "mem://mp",
        "oryx.update-topic.broker": "mem://mp",
        "oryx.serving.application-resources": ["oryx_tpu.serving.resources.common"],
    })
    from oryx_tpu.bus.api import TopicProducer

    app = ServingApp(cfg, Manager(cfg), TopicProducer(get_broker("mem://mp"), "OryxInput"))

    boundary = "XbOuNdArYx"
    plain = b"u1,i1,1\nu2,i2,1"
    gzipped = gzip.compress(b"u3,i3,1")
    body = (
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="f1"; filename="a.csv"\r\n'
        "Content-Type: text/csv\r\n\r\n"
    ).encode() + plain + (
        f"\r\n--{boundary}\r\n"
        'Content-Disposition: form-data; name="f2"; filename="b.csv.gz"\r\n'
        "Content-Type: application/octet-stream\r\n\r\n"
    ).encode() + gzipped + f"\r\n--{boundary}--\r\n".encode()

    import json

    status, resp, _ = app.dispatch(Request(
        "POST", "/ingest", {}, {}, body,
        {"accept": "application/json",
         "content-type": f"multipart/form-data; boundary={boundary}"},
    ))
    assert status == 200, resp
    assert json.loads(resp)["ingested"] == 3
    recs = get_broker("mem://mp").read("OryxInput", 0, 0, 10)
    assert {m for _, _, m in recs} == {"u1,i1,1", "u2,i2,1", "u3,i3,1"}
    # a plain form field (no filename) must NOT become a data record,
    # and a truncated gzip part is a 400, not a 500
    body2 = (
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="token"\r\n\r\n'
        "notdata\r\n"
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="f"; filename="c.csv"\r\n\r\n'
        "u4,i4,1\r\n"
        f"--{boundary}--\r\n"
    ).encode()
    status, resp, _ = app.dispatch(Request(
        "POST", "/ingest", {}, {}, body2,
        {"accept": "application/json",
         "content-type": f"multipart/form-data; boundary={boundary}"},
    ))
    assert status == 200 and json.loads(resp)["ingested"] == 1
    trunc = (
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="f"; filename="d.csv.gz"\r\n\r\n'
    ).encode() + gzip.compress(b"u5,i5,1")[:-4] + f"\r\n--{boundary}--\r\n".encode()
    status, _, _ = app.dispatch(Request(
        "POST", "/ingest", {}, {}, trunc,
        {"accept": "application/json",
         "content-type": f"multipart/form-data; boundary={boundary}"},
    ))
    assert status == 400

    # garbage multipart -> 400
    status, _, _ = app.dispatch(Request(
        "POST", "/ingest", {}, {}, b"--x--",
        {"accept": "application/json",
         "content-type": "multipart/form-data; boundary=x"},
    ))
    assert status == 400
    InProcBroker.reset_all()


def test_tls_binds_explicit_secure_port(tmp_path):
    """With ssl-cert-file set AND an explicit secure-port, TLS binds the
    secure port; with secure-port unset (null default) the regular port is
    kept — a packaged 443 default must never clobber it."""
    from oryx_tpu.common.config import load_config
    from oryx_tpu.common.ioutil import choose_free_port

    cfg = load_config(overlay={"oryx.id": "sp"})
    assert cfg.get("oryx.serving.api.secure-port", None) in (None, "")
    sp = choose_free_port()
    cfg2 = load_config(overlay={"oryx.serving.api.secure-port": sp})
    assert int(cfg2.get("oryx.serving.api.secure-port")) == sp


def test_serving_creates_missing_topics_unless_no_init():
    """Fail-fast default (the reference serving layer never creates
    topics): a missing topic errors at startup; init-topics=true opts in
    to auto-creation; no-init-topics=true forbids it even then."""
    from oryx_tpu.api import ServingModelManager
    from oryx_tpu.bus.broker import get_broker
    from oryx_tpu.bus.inproc import InProcBroker
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.server import ServingLayer

    InProcBroker.reset_all()

    class Manager(ServingModelManager):
        def __init__(self, config):
            self.config = config

        def consume(self, it):
            pass

        def get_model(self):
            return None

    base = {
        "oryx.id": "ni",
        "oryx.input-topic.broker": "mem://ni",
        "oryx.update-topic.broker": "mem://ni",
        "oryx.serving.api.port": 0,
        "oryx.serving.application-resources": ["oryx_tpu.serving.resources.common"],
    }
    import pytest as _pytest

    # default: fail fast on the missing topic, like the reference
    cfg0 = load_config(overlay=base)
    sl0 = ServingLayer(cfg0, model_manager=Manager(cfg0))
    with _pytest.raises(RuntimeError, match="topic does not exist"):
        sl0.start()

    InProcBroker.reset_all()
    cfg = load_config(overlay={**base, "oryx.serving.init-topics": True})
    sl = ServingLayer(cfg, model_manager=Manager(cfg))
    sl.start()  # explicit opt-in: both topics get made
    assert get_broker("mem://ni").topic_exists("OryxUpdate")
    assert get_broker("mem://ni").topic_exists("OryxInput")
    sl.close()

    InProcBroker.reset_all()
    cfg2 = load_config(
        overlay={
            **base,
            "oryx.serving.init-topics": True,
            "oryx.serving.no-init-topics": True,
        }
    )
    sl2 = ServingLayer(cfg2, model_manager=Manager(cfg2))
    with _pytest.raises(RuntimeError, match="topic does not exist"):
        sl2.start()
    InProcBroker.reset_all()


def test_nonblocking_fast_segments():
    """Routes marked nonblocking make their first segment eligible for
    inline event-loop dispatch; one blocking sibling poisons the segment."""
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.app import ServingApp

    cfg = load_config(
        overlay={
            "oryx.id": "fast",
            "oryx.serving.application-resources": [
                "oryx_tpu.serving.resources.common",
            ],
        }
    )
    app = ServingApp(cfg, _StubManager(cfg), None)
    assert app.is_fast("/ready")          # marked nonblocking
    assert not app.is_fast("/ingest")     # blocking POST
    assert not app.is_fast("/nonexistent")

    @app.route("GET", "/fastpath/{x}", nonblocking=True)
    def fast(a, req):
        return 200, {"x": req.params["x"]}

    assert app.is_fast("/fastpath/abc")

    @app.route("POST", "/fastpath/{x}")  # blocking sibling poisons it
    def slow(a, req):
        return 200, None

    assert not app.is_fast("/fastpath/abc")

    # a blocking param-first route matches ANY path: fast dispatch off
    assert app.is_fast("/ready")
    @app.route("GET", "/{anything}")
    def wildcard(a, req):
        return 200, None

    assert not app.is_fast("/ready")


def test_fast_segments_respect_context_path():
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.app import ServingApp

    cfg = load_config(
        overlay={
            "oryx.id": "ctx",
            "oryx.serving.api.context-path": "/api",
            "oryx.serving.application-resources": [
                "oryx_tpu.serving.resources.common",
            ],
        }
    )
    app = ServingApp(cfg, _StubManager(cfg), None)
    # the wire path includes the context prefix; is_fast must strip it
    # the same way _dispatch does
    assert app.is_fast("/api/ready")
    assert not app.is_fast("/ready")      # outside the context: 404 path
    assert not app.is_fast("/api/ingest")


def test_multipartition_update_topic_warns(caplog):
    """Chunked MODEL-REF transfer assumes single-partition publish order;
    a multi-partition update topic must be called out loudly at startup
    (round-4 advice: the REF can overtake its chunks across partitions)."""
    import logging

    from oryx_tpu.bus.broker import topics

    bus = "mem://multipart-upd"
    topics.maybe_create(bus, "OryxInput", partitions=1)
    topics.maybe_create(bus, "OryxUpdate", partitions=3)
    cfg = _config(bus, _free_port())
    with caplog.at_level(logging.WARNING, logger="oryx_tpu.serving.server"):
        with ServingLayer(cfg) as sl:
            _wait_ready(sl.port)
    assert any(
        "3 partitions" in r.message and "single-partition" in r.message
        for r in caplog.records
    ), [r.message for r in caplog.records][:10]

    # the single-partition default stays silent
    bus2 = "mem://singlepart-upd"
    topics.maybe_create(bus2, "OryxInput", partitions=1)
    topics.maybe_create(bus2, "OryxUpdate", partitions=1)
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="oryx_tpu.serving.server"):
        with ServingLayer(_config(bus2, _free_port())) as sl:
            pass
    assert not any("partitions" in r.message for r in caplog.records)
