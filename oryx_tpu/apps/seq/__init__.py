"""Streaming session next-item recommender — the fourth packaged app.

Schema: CSV lines ``user,session,item,ts``. The batch tier windows
session event streams into fixed-length next-item examples (tf.data's
pipeline-of-windows pattern) and trains a compact GRU (ops/seq.py); the
speed tier folds new/extended sessions into the item-embedding state as
UP row deltas; serving answers ``GET /recommend-next/...`` over the
item-embedding matrix through the shared top-k micro-batcher.
"""
