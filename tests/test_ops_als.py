"""Tests for the math/ops tier: vector primitives, solver, ALS training,
fold-in, scoring — incl. an SPMD run on the virtual 8-device mesh.

Statistical/behavioral assertions in the style of the reference's math and
ALS tests (LinearSystemSolverTest, ALSUtilsTest, ALSUpdateIT — SURVEY.md §4).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oryx_tpu.ops import (
    SingularMatrixError,
    cosine_similarity,
    dot,
    gram,
    make_solver,
    norm,
    random_unit_vectors,
)
from oryx_tpu.ops.als import (
    aggregate_interactions,
    build_padded_lists,
    compute_target_qui,
    compute_updated_xu,
    fold_in_batch,
    topk_dot,
    train_als,
)
from oryx_tpu.parallel import make_mesh, MeshSpec


# ---- vector ---------------------------------------------------------------

def test_vector_primitives():
    x = jnp.array([1.0, 2.0, 3.0])
    y = jnp.array([4.0, 5.0, 6.0])
    assert float(dot(x, y)) == pytest.approx(32.0)
    assert float(norm(x)) == pytest.approx(np.sqrt(14.0))
    assert float(cosine_similarity(x, x)) == pytest.approx(1.0, abs=1e-6)
    assert float(cosine_similarity(x, -x)) == pytest.approx(-1.0, abs=1e-6)


def test_gram_matches_numpy():
    x = np.random.default_rng(0).normal(size=(50, 7)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(gram(jnp.asarray(x))), x.T @ x, rtol=1e-4)


def test_random_unit_vectors():
    v = np.asarray(random_unit_vectors(10, 5))
    np.testing.assert_allclose(np.linalg.norm(v, axis=1), 1.0, rtol=1e-5)


# ---- solver ---------------------------------------------------------------

def test_solver_spd_roundtrip():
    rng = np.random.default_rng(1)
    m = rng.normal(size=(6, 6))
    a = m.T @ m + 0.1 * np.eye(6)
    s = make_solver(a)
    b = rng.normal(size=6)
    np.testing.assert_allclose(s.solve_f(b), np.linalg.solve(a, b), rtol=1e-3, atol=1e-4)


def test_solver_packed_triangular_input():
    a = np.array([[4.0, 1.0], [1.0, 3.0]])
    packed = np.array([4.0, 1.0, 3.0])  # row-major lower triangle
    s = make_solver(packed)
    b = np.array([1.0, 2.0])
    np.testing.assert_allclose(s.solve_f(b), np.linalg.solve(a, b), rtol=1e-4)


def test_solver_rejects_singular():
    with pytest.raises(SingularMatrixError):
        make_solver(np.zeros((4, 4)))
    with pytest.raises(SingularMatrixError):
        make_solver(np.ones((3, 3)))  # rank-1


# ---- input prep -----------------------------------------------------------

def test_aggregate_implicit_sums_and_nan_delete():
    users = np.array(["u1", "u1", "u2", "u1"])
    items = np.array(["i1", "i1", "i2", "i2"])
    vals = np.array([1.0, 2.0, 5.0, np.nan])
    d = aggregate_interactions(users, items, vals, implicit=True)
    got = {(d.user_ids[u], d.item_ids[i]): v for u, i, v in zip(d.users, d.items, d.values)}
    assert got == {("u1", "i1"): pytest.approx(3.0), ("u2", "i2"): pytest.approx(5.0)}
    # (u1,i2) killed by the NaN delete marker


def test_aggregate_explicit_last_wins():
    users = np.array(["u1", "u1", "u1"])
    items = np.array(["i1", "i1", "i1"])
    vals = np.array([5.0, 1.0, 3.0])
    ts = np.array([100, 300, 200])
    d = aggregate_interactions(users, items, vals, ts, implicit=False)
    assert len(d.values) == 1 and d.values[0] == pytest.approx(1.0)  # ts=300 wins


def test_aggregate_decay_and_zero_threshold():
    day = 86_400_000
    users = np.array(["u", "u"])
    items = np.array(["a", "b"])
    vals = np.array([1.0, 1.0])
    ts = np.array([0, 10 * day])  # first is 10 days older
    d = aggregate_interactions(
        users, items, vals, ts, implicit=True,
        decay_factor=0.5, zero_threshold=0.01, now_ms=10 * day,
    )
    got = {d.item_ids[i]: v for i, v in zip(d.items, d.values)}
    assert got["b"] == pytest.approx(1.0)
    assert "a" not in got or got["a"] < 0.01  # decayed below threshold -> dropped


def test_padded_lists_shapes_and_cap():
    entity = np.array([0, 0, 0, 2, 2], dtype=np.int32)
    other = np.array([1, 2, 3, 4, 5], dtype=np.int32)
    vals = np.array([0.5, 3.0, 1.0, 2.0, 1.0], dtype=np.float32)
    idx, val, mask = build_padded_lists(entity, other, vals, n_entities=3, cap=2)
    assert idx.shape == (3, 2)
    # entity 0 keeps its 2 largest-|value| interactions (3.0 and 1.0)
    kept = set(val[0][mask[0] > 0].tolist())
    assert kept == {3.0, 1.0}
    assert mask[1].sum() == 0  # entity 1 had nothing


# ---- training -------------------------------------------------------------

def _synthetic_implicit(n_u=24, n_i=16, k=4, seed=0):
    """Block-structured interactions: users and items in 4 groups; a user
    interacts mostly within their group."""
    rng = np.random.default_rng(seed)
    users, items, vals = [], [], []
    for u in range(n_u):
        g = u % 4
        for i in range(n_i):
            if i % 4 == g and rng.random() < 0.9:
                users.append(f"u{u}"); items.append(f"i{i}"); vals.append(1.0 + rng.random())
            elif rng.random() < 0.05:
                users.append(f"u{u}"); items.append(f"i{i}"); vals.append(0.5)
    return aggregate_interactions(
        np.array(users), np.array(items), np.array(vals, dtype=np.float64), implicit=True
    )


def test_train_als_implicit_recovers_structure():
    data = _synthetic_implicit()
    m = train_als(data, features=4, lam=0.01, alpha=10.0, iterations=8, implicit=True)
    assert m.x.shape == (data.n_users, 4) and m.y.shape == (data.n_items, 4)
    scores = m.x @ m.y.T
    # in-group items should outscore out-of-group items on average
    in_group, out_group = [], []
    for u in range(data.n_users):
        ug = int(data.user_ids[u][1:]) % 4
        for i in range(data.n_items):
            ig = int(data.item_ids[i][1:]) % 4
            (in_group if ig == ug else out_group).append(scores[u, i])
    assert np.mean(in_group) > np.mean(out_group) + 0.2


def test_train_als_explicit_fits_ratings():
    rng = np.random.default_rng(3)
    xs = rng.normal(size=(20, 3))
    ys = rng.normal(size=(15, 3))
    users, items, vals = [], [], []
    for u in range(20):
        for i in rng.choice(15, size=10, replace=False):
            users.append(f"u{u:02d}"); items.append(f"i{i:02d}")
            vals.append(float(xs[u] @ ys[i]))
    data = aggregate_interactions(
        np.array(users), np.array(items), np.array(vals), implicit=False
    )
    m = train_als(data, features=3, lam=0.005, alpha=1.0, iterations=12, implicit=False)
    # reconstruct observed ratings
    umap = {u: j for j, u in enumerate(data.user_ids)}
    imap = {i: j for j, i in enumerate(data.item_ids)}
    errs = [
        (m.x[umap[u]] @ m.y[imap[i]] - v) ** 2
        for u, i, v in zip(users, items, vals)
    ]
    rmse = np.sqrt(np.mean(errs))
    assert rmse < 0.35, rmse


def test_train_als_on_8_device_mesh():
    """SPMD path: same data, sharded over the virtual 8-device mesh; result
    must be close to the single-device run (same seed)."""
    data = _synthetic_implicit()
    mesh = make_mesh(MeshSpec(data=8, model=1))
    from oryx_tpu.common.rng import RandomManager

    RandomManager.use_test_seed(7)
    k1 = RandomManager.get_key()
    m1 = train_als(data, features=4, lam=0.01, alpha=10.0, iterations=4,
                   implicit=True, seed_key=k1)
    RandomManager.use_test_seed(7)
    k2 = RandomManager.get_key()
    m2 = train_als(data, features=4, lam=0.01, alpha=10.0, iterations=4,
                   implicit=True, mesh=mesh, seed_key=k2)
    s1 = m1.x @ m1.y.T
    s2 = m2.x @ m2.y.T
    np.testing.assert_allclose(s1, s2, rtol=0.3, atol=0.15)


# ---- fold-in --------------------------------------------------------------

def test_target_qui_semantics():
    # positive value moves target from current toward 1
    t = float(compute_target_qui(1.0, 0.0, implicit=True))
    assert t == pytest.approx(0.5)  # 0 + (1/2)*1
    # already >= 1: no change (NaN)
    assert np.isnan(float(compute_target_qui(1.0, 1.5, implicit=True)))
    # negative value moves toward 0
    t = float(compute_target_qui(-1.0, 1.0, implicit=True))
    assert t == pytest.approx(0.5)
    # explicit passes through
    assert float(compute_target_qui(3.5, 0.2, implicit=False)) == pytest.approx(3.5)


def test_fold_in_moves_prediction_toward_target():
    rng = np.random.default_rng(5)
    y = rng.normal(size=(30, 6)).astype(np.float32)
    yty = y.T @ y + 0.01 * np.eye(6, dtype=np.float32)
    chol = np.linalg.cholesky(yty).astype(np.float32)
    xu = rng.normal(size=6).astype(np.float32) * 0.1
    yi = y[3]
    before = float(xu @ yi)
    new_xu = np.asarray(compute_updated_xu(
        jnp.asarray(chol), jnp.float32(2.0), jnp.asarray(xu), jnp.asarray(yi),
        implicit=True,
    ))
    after = float(new_xu @ yi)
    assert after > before  # positive interaction raises predicted strength
    assert after <= 1.05   # toward (not past) 1


def test_fold_in_batch_shapes():
    rng = np.random.default_rng(6)
    y = rng.normal(size=(10, 4)).astype(np.float32)
    chol = np.linalg.cholesky(y.T @ y + 0.1 * np.eye(4)).astype(np.float32)
    xs = rng.normal(size=(5, 4)).astype(np.float32)
    yis = y[:5]
    vals = np.ones(5, dtype=np.float32)
    out = np.asarray(fold_in_batch(jnp.asarray(chol), jnp.asarray(vals),
                                   jnp.asarray(xs), jnp.asarray(yis)))
    assert out.shape == (5, 4)
    assert np.all(np.isfinite(out))


# ---- scoring --------------------------------------------------------------

def test_topk_dot_with_exclusion():
    y = jnp.asarray(np.diag([5.0, 4.0, 3.0, 2.0, 1.0]).astype(np.float32))
    xu = jnp.ones(5, dtype=jnp.float32)
    vals, idx = topk_dot(xu, y, k=3)
    assert idx.tolist() == [0, 1, 2]
    excl = jnp.asarray([True, False, False, False, False])
    vals, idx = topk_dot(xu, y, k=3, exclude_mask=excl)
    assert idx.tolist() == [1, 2, 3]


def test_bucketed_half_step_matches_flat():
    """The bucketed solver partitions the same padded lists by row width;
    its scattered result must equal the flat solver's row for row."""
    import jax.numpy as jnp

    from oryx_tpu.ops.als import (
        _half_step,
        _half_step_buckets,
        _row_pad,
        build_bucketed_lists,
        build_padded_lists,
        gram,
    )

    rng = np.random.default_rng(1)
    n_u, n_i, nnz = 3000, 1500, 120_000
    iw = 1.0 / np.power(np.arange(1, n_i + 1), 0.9)
    iw /= iw.sum()
    uw = rng.lognormal(0, 1.4, n_u)
    uw /= uw.sum()
    data = aggregate_interactions(
        rng.choice(n_u, size=nnz, p=uw),
        rng.choice(n_i, size=nnz, p=iw),
        rng.random(nnz) + 0.1,
        implicit=True,
    )
    k = 8
    y = jnp.asarray(rng.standard_normal((data.n_items, k)), dtype=jnp.float32)
    idx, val, mask = build_padded_lists(data.users, data.items, data.values, data.n_users)
    npad = -(-data.n_users // 64) * 64
    idx, val, mask = (_row_pad(a, npad) for a in (idx, val, mask))
    flat = _half_step(
        y, gram(y), jnp.asarray(idx), jnp.asarray(val), jnp.asarray(mask),
        jnp.float32(0.01), jnp.float32(1.0), True, 64,
    )
    buckets, blocks = build_bucketed_lists(
        data.users, data.items, data.values, data.n_users, min_rows=64
    )
    assert len(buckets) >= 2, "skewed data should produce multiple width buckets"
    bucketed = _half_step_buckets(
        y, gram(y),
        tuple(tuple(jnp.asarray(a) for a in b) for b in buckets),
        jnp.float32(0.01), jnp.float32(1.0), True, tuple(blocks), data.n_users,
    )
    np.testing.assert_allclose(
        np.asarray(flat)[: data.n_users], np.asarray(bucketed), rtol=3e-4, atol=2e-5
    )


def test_bucketed_truncation_keeps_largest_values():
    """Rows beyond the cap keep their largest-|value| interactions — the
    same policy as the flat builder."""
    from oryx_tpu.ops.als import build_bucketed_lists

    n_other = 40
    entity = np.zeros(n_other, dtype=np.int64)
    other = np.arange(n_other, dtype=np.int64)
    values = np.arange(1, n_other + 1, dtype=np.float64)  # biggest = other 39
    buckets, _ = build_bucketed_lists(entity, other, values, 1, cap=16, min_rows=1)
    (rows, idx, val, mask), = buckets
    kept = set(idx[0][mask[0] > 0].tolist())
    assert kept == set(range(n_other - 16, n_other))


def test_bfloat16_compute_dtype_quality():
    """bf16 einsum inputs (f32 accumulation) must not degrade ranking
    quality: on planted-genre data both dtypes separate in-genre items."""
    import jax
    import jax.numpy as jnp

    from oryx_tpu.ops.als import train_als

    rng = np.random.default_rng(5)
    n_u, n_i, nnz, G = 600, 400, 40_000, 8
    ug = rng.integers(0, G, n_u)
    ig = rng.integers(0, G, n_i)
    users = rng.integers(0, n_u, nnz)
    items = rng.integers(0, n_i, nnz)
    ing = rng.random(nnz) < 0.85
    for g in range(G):
        rows = np.nonzero(ing & (ug[users] == g))[0]
        pool = np.nonzero(ig == g)[0]
        if rows.size and pool.size:
            items[rows] = rng.choice(pool, size=rows.size)
    data = aggregate_interactions(users, items, rng.random(nnz) + 0.5, implicit=True)

    def genre_score(dt):
        m = train_als(
            data, features=16, iterations=5, implicit=True,
            seed_key=jax.random.PRNGKey(0), compute_dtype=dt,
        )
        # mean margin: in-genre items should outscore out-genre ones
        iid_genre = np.array([ig[int(i)] for i in m.item_ids])
        margins = []
        for j, u in enumerate(m.user_ids[:100]):
            s = m.y @ m.x[j]
            g = ug[int(u)]
            margins.append(s[iid_genre == g].mean() - s[iid_genre != g].mean())
        return float(np.mean(margins))

    f32 = genre_score("float32")
    bf16 = genre_score("bfloat16")
    assert f32 > 0.05 and bf16 > 0.05  # both models learned the structure
    assert bf16 > 0.8 * f32  # bf16 within tolerance of full precision


def test_checkpointed_training_resume_equals_uninterrupted(tmp_path):
    """Kill-and-resume must produce EXACTLY the uninterrupted model: the
    per-sweep carry is fully determined by Y, which is what the
    checkpoint stores."""
    import jax

    from oryx_tpu.ops.als import train_als, train_als_checkpointed

    rng = np.random.default_rng(3)
    data = aggregate_interactions(
        rng.integers(0, 300, 20_000).astype(str),
        rng.integers(0, 200, 20_000).astype(str),
        rng.random(20_000) + 0.1,
        implicit=True,
    )
    key = jax.random.PRNGKey(11)
    base = train_als(data, features=8, iterations=6, implicit=True, seed_key=key)

    # run the checkpointed variant but ABORT after the first chunk by
    # training only 2 of 6 sweeps, leaving the checkpoint behind
    ck = tmp_path / "ck"
    partial = train_als_checkpointed(
        data, ck, checkpoint_every=2, features=8, iterations=2,
        implicit=True, seed_key=key,
    )
    # simulate the abort: write the mid-build checkpoint a crash would
    # have left (the wrapper removes it on success, so recreate it)
    import json as _json

    fingerprint = _json.dumps(
        {
            "n_users": data.n_users, "n_items": data.n_items,
            "nnz": int(len(data.values)), "features": 8, "lam": 0.001,
            "alpha": 1.0, "implicit": True, "compute_dtype": "float32",
            "iterations": 6,
        },
        sort_keys=True,
    )
    np.savez(ck / "als-train.ckpt.npz.tmp", y=partial.y, done=2, fingerprint=fingerprint)
    import os

    os.replace(ck / "als-train.ckpt.npz.tmp.npz", ck / "als-train.ckpt.npz")

    resumed = train_als_checkpointed(
        data, ck, checkpoint_every=2, features=8, iterations=6,
        implicit=True, seed_key=key,
    )
    np.testing.assert_allclose(resumed.x, base.x, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(resumed.y, base.y, rtol=1e-5, atol=1e-6)
    assert not (ck / "als-train.ckpt.npz").exists()  # removed on success


def test_checkpointed_training_ignores_mismatched_checkpoint(tmp_path):
    """A checkpoint from different data/config restarts cleanly."""
    from oryx_tpu.ops.als import train_als, train_als_checkpointed

    import jax

    rng = np.random.default_rng(4)
    data = aggregate_interactions(
        rng.integers(0, 100, 5_000).astype(str),
        rng.integers(0, 80, 5_000).astype(str),
        rng.random(5_000) + 0.1,
        implicit=True,
    )
    ck = tmp_path / "ck"
    ck.mkdir()
    (ck / "als-train.ckpt.npz").write_bytes(b"torn garbage")
    key = jax.random.PRNGKey(2)
    m = train_als_checkpointed(
        data, ck, checkpoint_every=2, features=4, iterations=4,
        implicit=True, seed_key=key,
    )
    base = train_als(data, features=4, iterations=4, implicit=True, seed_key=key)
    np.testing.assert_allclose(m.x, base.x, rtol=1e-5, atol=1e-6)


def test_singular_systems_never_nan():
    """Rank-deficient normal equations (explicit, lam=0, users with fewer
    interactions than features) must never leak NaN into the factors: the
    _half_step singularity guard retries with trace-scaled jitter and zeroes
    anything still unsolvable (the reference Solver.java refuses
    ill-conditioned systems; here one NaN row would poison gram() and with
    it the entire next half-sweep)."""
    from oryx_tpu.ops.als import aggregate_interactions

    rng = np.random.default_rng(11)
    # 40 users x 30 items, every user rates exactly ONE item -> each user
    # system is rank-1 with lam=0
    users = np.arange(40, dtype=np.int64)
    items = rng.integers(0, 30, size=40).astype(np.int64)
    values = rng.uniform(1, 5, size=40)
    data = aggregate_interactions(users, items, values, implicit=False)
    m = train_als(
        data, features=8, lam=0.0, alpha=1.0, iterations=4, implicit=False
    )
    assert np.isfinite(m.x).all(), "NaN leaked into user factors"
    assert np.isfinite(m.y).all(), "NaN leaked into item factors"
    # and the model still scores: predictions are finite everywhere
    assert np.isfinite(m.x @ m.y.T).all()


def test_train_timings_breakdown_matches_normal_path():
    """timings= uses AOT lower/compile; the factors must match the normal
    jit path (same HLO, independently compiled) and the breakdown must be
    populated."""
    data = _synthetic_implicit()
    t: dict = {}
    m1 = train_als(data, features=4, lam=0.01, alpha=10.0, iterations=3,
                   implicit=True, seed_key=jax.random.PRNGKey(5))
    m2 = train_als(data, features=4, lam=0.01, alpha=10.0, iterations=3,
                   implicit=True, seed_key=jax.random.PRNGKey(5), timings=t)
    # two independent compilations of the same HLO: allow last-ulp drift
    # on backends with nondeterministic autotuning
    np.testing.assert_allclose(m1.x, m2.x, rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(m1.y, m2.y, rtol=1e-6, atol=1e-7)
    assert set(t) == {"lists_s", "compile_s", "train_s", "train_flops"}
    assert t["train_flops"] > 0
    assert all(v >= 0 for v in t.values())


def test_topk_chunked_matches_unchunked():
    """Chunked scoring (bounded per-dispatch shapes for models whose
    one-shot compile is too large) must agree with the single-dispatch
    kernel exactly: same values, same GLOBAL indices, ragged last chunk
    and chunks smaller than k included."""
    import jax.numpy as jnp
    import numpy as np

    from oryx_tpu.ops.als import topk_dot_batch, topk_dot_batch_chunked

    rng = np.random.default_rng(3)
    xs = jnp.asarray(rng.standard_normal((7, 16)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((1000, 16)).astype(np.float32))
    ve, ie = topk_dot_batch(xs, y, k=10)

    for sizes in [(400, 400, 200), (512, 488), (999, 1), (5, 995)]:
        chunks, at = [], 0
        for n in sizes:
            chunks.append(y[at : at + n])
            at += n
        vc, ic = topk_dot_batch_chunked(xs, chunks, k=10)
        np.testing.assert_allclose(np.asarray(vc), np.asarray(ve), rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(ic), np.asarray(ie))
