"""Message-log bus: the framework's Kafka-equivalent data plane.

The reference wires its three layers together exclusively through two Kafka
topics plus ZooKeeper offset storage (framework/kafka-util: KafkaUtils.java,
ConsumeDataIterator.java). Here the same contract — partitioned append-only
topics, consumer-group offsets, replay from earliest, blocking iteration —
is provided by pluggable brokers behind one URI scheme:

    mem://<name>    in-process broker (tests; the LocalKafkaBroker analogue)
    file://<dir>    durable log segments on a shared filesystem, safe for
                    multi-process producers/consumers (native C++ appender
                    when built, pure-Python fallback otherwise)
"""

from oryx_tpu.bus.api import KeyMessage, TopicProducer, ConsumeDataIterator
from oryx_tpu.bus.broker import Broker, get_broker, topics as topic_admin
