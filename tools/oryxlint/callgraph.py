"""Shared resolution helpers: imports, classes, methods, call edges.

Checkers need the same project-level questions answered — "what does
this call resolve to", "what type is this receiver", "which functions
does this class define" — so the index is built once per lint run and
shared. Resolution is deliberately *confident-only*: an edge is followed
when the target is unambiguous (module-local function, ``self`` method,
import-resolved symbol, annotation-typed receiver, or a method name
defined by exactly one project class). Anything else returns no
candidates rather than guessing — a project linter that guesses wrong
trains people to ignore it.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tools.oryxlint.core import Project, SourceModule

# method names too generic for the unique-definition fallback: many
# stdlib/third-party objects define them, so "only one project class has
# it" proves nothing about the receiver
COMMON_METHOD_NAMES = frozenset({
    "get", "set", "put", "add", "pop", "run", "read", "write", "close",
    "open", "send", "recv", "start", "stop", "join", "wait", "notify",
    "items", "keys", "values", "update", "clear", "copy", "append",
    "extend", "insert", "remove", "submit", "result", "acquire",
    "release", "next", "flush", "seek", "tell", "encode", "decode",
    "split", "strip", "match", "search", "format", "count", "index",
    "sort", "reverse", "load", "save", "check", "render", "observe",
    "inc", "dec", "snapshot", "commit", "request", "connect", "shutdown",
    # numpy/jax array reducers: `arr.sum()` must never resolve to a
    # project method that happens to share the name (Histogram.sum)
    "sum", "mean", "min", "max", "all", "any", "reshape", "astype",
})


@dataclass
class FunctionInfo:
    module: SourceModule
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    name: str
    cls: str | None  # enclosing class name, if a method
    parent: str | None  # qualname of the enclosing function, if nested
    qualname: str = ""
    is_async: bool = False
    offloop: bool = False
    holds: tuple[str, ...] = ()
    nonblocking_route: bool = False

    @property
    def where(self) -> str:
        return f"{self.module.relpath}:{self.node.lineno}"


@dataclass
class ClassInfo:
    name: str
    module: SourceModule
    node: ast.ClassDef
    bases: list[str] = field(default_factory=list)
    methods: dict[str, FunctionInfo] = field(default_factory=dict)
    # self.<attr> -> project class name, inferred from annotated-param
    # copies and direct constructions in method bodies
    attr_types: dict[str, str] = field(default_factory=dict)
    # self.<alias> -> self.<lock>: threading.Condition(self.<lock>)
    # assignments make `with self.<alias>` hold <lock>
    lock_aliases: dict[str, str] = field(default_factory=dict)


def _module_dotted(relpath: str) -> str:
    return relpath[:-3].replace("/", ".") if relpath.endswith(".py") else relpath


def _base_name(expr: ast.AST) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


class ProjectIndex:
    """Symbol index over a loaded Project."""

    def __init__(self, project: Project):
        self.project = project
        self.functions: list[FunctionInfo] = []
        self.top_level: dict[tuple[str, str], FunctionInfo] = {}
        self.nested: dict[tuple[str, str, str], FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self._ambiguous_classes: set[str] = set()
        self.methods_by_name: dict[str, list[FunctionInfo]] = {}
        # module relpath -> local name -> ("mod", dotted) | ("sym", dotted, symbol)
        self.imports: dict[str, dict[str, tuple]] = {}
        self._dotted_to_rel = {
            _module_dotted(m.relpath): m.relpath for m in project.modules
        }
        # (module relpath, local name) -> FunctionInfo for names bound by
        # partial(...) wrapper assignments (incl. partial(partial(f, a), b)
        # double-wrapping) — resolution follows the alias to the wrapped fn
        self.partial_aliases: dict[tuple[str, str], FunctionInfo] = {}
        # (module relpath, local name) -> how many positional args the
        # partial chain pre-bound (dataflow offsets call-site positionals
        # by this before mapping them to callee parameters)
        self.partial_bound: dict[tuple[str, str], int] = {}
        self._partial_conflicts: set[tuple[str, str]] = set()
        # resolution-rate accounting, surfaced by `oryxlint --stats`:
        # lambda call sites are counted separately because they are today
        # silently unresolved (a lambda body is its own edge, not a def)
        self.stats = {"call_sites": 0, "resolved": 0, "lambda_sites": 0}
        for mod in project.modules:
            self._index_module(mod)
        for ci in self.classes.values():
            self._infer_attr_types(ci)
        for mod in project.modules:
            self._index_partials(mod)

    # -- indexing ------------------------------------------------------------

    def _index_module(self, mod: SourceModule) -> None:
        imports: dict[str, tuple] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    imports[a.asname or a.name.split(".")[0]] = ("mod", a.name)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    imports[a.asname or a.name] = ("sym", node.module, a.name)
        self.imports[mod.relpath] = imports
        self._index_body(mod, mod.tree.body, cls=None, parent=None, prefix="")

    def _index_body(self, mod, body, cls, parent, prefix) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{node.name}"
                fi = FunctionInfo(
                    module=mod, node=node, name=node.name, cls=cls,
                    parent=parent, qualname=qual,
                    is_async=isinstance(node, ast.AsyncFunctionDef),
                    offloop=mod.fn_offloop(node), holds=mod.fn_holds(node),
                    nonblocking_route=_is_nonblocking_route(node),
                )
                self.functions.append(fi)
                if cls is None and parent is None:
                    self.top_level[(mod.relpath, node.name)] = fi
                if parent is not None:
                    self.nested[(mod.relpath, parent, node.name)] = fi
                if cls is not None and parent is None:
                    ci = self.classes.get(cls)
                    if ci is not None and ci.module is mod:
                        ci.methods[node.name] = fi
                        self.methods_by_name.setdefault(node.name, []).append(fi)
                self._index_body(
                    mod, node.body, cls=cls, parent=qual, prefix=f"{qual}.",
                )
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(
                    name=node.name, module=mod, node=node,
                    bases=[b for b in map(_base_name, node.bases) if b],
                )
                key = node.name
                if key in self.classes:
                    # duplicate bare name: name-based RESOLUTION becomes
                    # ambiguous (conservative, no guessing), but the class
                    # itself stays indexed under a synthetic key so the
                    # lock-discipline checker still enforces its
                    # guarded-by annotations — shadowing must never
                    # silently drop coverage
                    self._ambiguous_classes.add(node.name)
                    key = f"{node.name}@{mod.relpath}:{node.lineno}"
                self.classes[key] = ci
                self._index_body(
                    mod, node.body, cls=key, parent=None,
                    prefix=f"{node.name}.",
                )

    def _infer_attr_types(self, ci: ClassInfo) -> None:
        """self.<attr> types from __init__-style assignments: a parameter
        annotated with a project class, or a direct construction."""
        for fi in ci.methods.values():
            ann: dict[str, str] = {}
            for a in list(fi.node.args.args) + list(fi.node.args.kwonlyargs):
                t = _base_name(a.annotation) if a.annotation else None
                if t and t in self.classes:
                    ann[a.arg] = t
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                    continue
                t = node.targets[0]
                if not (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    continue
                v = node.value
                if isinstance(v, ast.Name) and v.id in ann:
                    ci.attr_types.setdefault(t.attr, ann[v.id])
                elif (
                    isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Name)
                    and v.func.id in self.classes
                    and v.func.id not in self._ambiguous_classes
                ):
                    ci.attr_types.setdefault(t.attr, v.func.id)
                elif (
                    isinstance(v, ast.Call)
                    and self.dotted_name(fi.module, v.func)
                    == "threading.Condition"
                    and v.args
                    and isinstance(v.args[0], ast.Attribute)
                    and isinstance(v.args[0].value, ast.Name)
                    and v.args[0].value.id == "self"
                ):
                    ci.lock_aliases[t.attr] = v.args[0].attr

    def _unwrap_partial(
        self, mod: SourceModule, expr: ast.AST
    ) -> tuple[ast.Name, int] | None:
        """(Name at the bottom of a ``partial(...)`` chain, number of
        positional args the chain pre-binds): ``partial(f, a)`` and
        ``partial(partial(f, a), b)`` both unwrap to ``f`` (binding 1
        and 2 positionals). Returns None for anything that is not a
        partial chain over a plain name. Pre-bound positionals apply
        outermost-last, so the counts simply add."""
        depth = 0
        bound = 0
        while isinstance(expr, ast.Call) and depth < 8:
            d = self.dotted_name(mod, expr.func)
            if d not in ("functools.partial", "partial") or not expr.args:
                return None
            bound += len(expr.args) - 1
            inner = expr.args[0]
            if isinstance(inner, ast.Name):
                return inner, bound
            expr = inner
            depth += 1
        return None

    def _index_partials(self, mod: SourceModule) -> None:
        """``g = partial(f, ...)`` wrapper assignments (anywhere in the
        module, module level or function-local) alias ``g`` to ``f`` for
        call resolution. Conflicts — ``g`` is already a def, or two
        assignments wrap different functions — drop the alias instead of
        guessing."""
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                continue
            unwrapped = self._unwrap_partial(mod, node.value)
            if unwrapped is None:
                continue
            inner, bound = unwrapped
            tgt = self.top_level.get((mod.relpath, inner.id))
            if tgt is None:
                imp = self.imports.get(mod.relpath, {}).get(inner.id)
                if imp is not None and imp[0] == "sym":
                    rel = self._dotted_to_rel.get(imp[1])
                    if rel is not None:
                        tgt = self.top_level.get((rel, imp[2]))
            if tgt is None:
                continue
            key = (mod.relpath, node.targets[0].id)
            if key in self.top_level or key in self._partial_conflicts:
                continue  # shadows a real def / known-conflicting name
            if key in self.partial_aliases and (
                self.partial_aliases[key] is not tgt
                or self.partial_bound.get(key) != bound
            ):
                del self.partial_aliases[key]  # conflicting rebinds
                self.partial_bound.pop(key, None)
                self._partial_conflicts.add(key)
                continue
            self.partial_aliases[key] = tgt
            self.partial_bound[key] = bound

    # -- resolution ------------------------------------------------------------

    def dotted_name(self, mod: SourceModule, expr: ast.AST) -> str | None:
        """Fully-qualified dotted name of a Name/Attribute expression via
        the module's imports: ``sleep`` (from time import sleep) and
        ``time.sleep`` both resolve to ``"time.sleep"``."""
        parts: list[str] = []
        node = expr
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        imp = self.imports.get(mod.relpath, {}).get(node.id)
        if imp is None:
            return None
        if imp[0] == "mod":
            head = imp[1]
        else:
            head = f"{imp[1]}.{imp[2]}"
        return ".".join([head] + list(reversed(parts)))

    def class_of(self, fi: FunctionInfo, expr: ast.AST) -> str | None:
        """Project class name of a receiver expression, or None."""
        if isinstance(expr, ast.Name):
            if expr.id == "self" and fi.cls:
                return fi.cls
            # annotated parameter of this function
            for a in list(fi.node.args.args) + list(fi.node.args.kwonlyargs):
                if a.arg == expr.id and a.annotation is not None:
                    t = _base_name(a.annotation)
                    if t in self.classes and t not in self._ambiguous_classes:
                        return t
            return None
        if isinstance(expr, ast.Attribute):
            base = self.class_of(fi, expr.value)
            if base is None:
                return None
            for cls in self._mro(base):
                t = self.classes[cls].attr_types.get(expr.attr)
                if t is not None:
                    return t
            # @property with a project-class return annotation: the
            # receiver of `obj.prop.method()` resolves through the
            # property's declared type
            prop = self.method_on(base, expr.attr)
            if prop is not None and _is_property(prop.node):
                ret = getattr(prop.node, "returns", None)
                t = _base_name(ret) if ret is not None else None
                if t in self.classes and t not in self._ambiguous_classes:
                    return t
            return None
        if isinstance(expr, ast.Call):
            # ClassName(...) or Class.shared()-style constructor
            if isinstance(expr.func, ast.Name) and expr.func.id in self.classes:
                return expr.func.id
        return None

    def _mro(self, cls: str) -> list[str]:
        out, queue = [], [cls]
        while queue:
            c = queue.pop(0)
            if c in out or c not in self.classes:
                continue
            out.append(c)
            queue.extend(self.classes[c].bases)
        return out

    def method_on(self, cls: str, name: str) -> FunctionInfo | None:
        for c in self._mro(cls):
            fi = self.classes[c].methods.get(name)
            if fi is not None:
                return fi
        return None

    def call_positional_offset(self, mod: SourceModule, call: ast.Call) -> int:
        """Positional-argument offset of a call site: calls through a
        partial alias start binding at the first UNBOUND callee
        parameter, not at position 0."""
        if isinstance(call.func, ast.Name):
            return self.partial_bound.get((mod.relpath, call.func.id), 0)
        return 0

    def resolve_call(self, fi: FunctionInfo, call: ast.Call) -> list[FunctionInfo]:
        """Confident candidate targets of a call made inside ``fi``.
        Updates the --stats resolution-rate counters as a side effect."""
        self.stats["call_sites"] += 1
        if isinstance(call.func, ast.Lambda):
            # an immediately-invoked lambda: its body is its own edge,
            # not a def — unresolved, but counted so --stats keeps the
            # blind spot visible
            self.stats["lambda_sites"] += 1
            return []
        out = self._resolve_call(fi, call)
        if out:
            self.stats["resolved"] += 1
        return out

    def _resolve_call(self, fi: FunctionInfo, call: ast.Call) -> list[FunctionInfo]:
        func = call.func
        mod = fi.module
        if isinstance(func, ast.Name):
            # nested sibling (a closure defined in this or an enclosing fn)
            parent = fi.qualname
            while parent:
                hit = self.nested.get((mod.relpath, parent, func.id))
                if hit is not None:
                    return [hit]
                parent = parent.rsplit(".", 1)[0] if "." in parent else ""
            hit = self.top_level.get((mod.relpath, func.id))
            if hit is not None:
                return [hit]
            imp = self.imports.get(mod.relpath, {}).get(func.id)
            if imp is not None and imp[0] == "sym":
                rel = self._dotted_to_rel.get(imp[1])
                if rel is not None:
                    tgt = self.top_level.get((rel, imp[2]))
                    if tgt is not None:
                        return [tgt]
                    # symbol may be a class: follow into __init__
                    ci = self.classes.get(imp[2])
                    if ci is not None and imp[2] not in self._ambiguous_classes:
                        init = ci.methods.get("__init__")
                        return [init] if init is not None else []
            if func.id in self.classes and func.id not in self._ambiguous_classes:
                ci = self.classes[func.id]
                if ci.module is mod:
                    init = ci.methods.get("__init__")
                    return [init] if init is not None else []
            alias = self.partial_aliases.get((mod.relpath, func.id))
            if alias is not None:
                return [alias]
            return []
        if isinstance(func, ast.Attribute):
            # module.function via imports
            dotted = self.dotted_name(mod, func)
            if dotted is not None:
                head, _, tail = dotted.rpartition(".")
                rel = self._dotted_to_rel.get(head)
                if rel is not None:
                    tgt = self.top_level.get((rel, tail))
                    if tgt is not None:
                        return [tgt]
            cls = self.class_of(fi, func.value)
            if cls is not None:
                tgt = self.method_on(cls, func.attr)
                return [tgt] if tgt is not None else []
            # unique-definition fallback: exactly one project class defines
            # this method name, and the name is specific enough to trust
            if (
                func.attr not in COMMON_METHOD_NAMES
                and len(func.attr) >= 3
                and not func.attr.startswith("__")
            ):
                cands = self.methods_by_name.get(func.attr, [])
                if len(cands) == 1:
                    return list(cands)
            return []
        return []


def shared_index(project: Project) -> ProjectIndex:
    """One ProjectIndex per loaded Project: six checkers asking the same
    symbol questions must not re-index the whole tree six times (the
    --changed pre-commit path pays index cost on every commit). The
    index is read-only after construction apart from the --stats
    counters, which are cumulative by design."""
    idx = getattr(project, "_shared_index", None)
    if idx is None:
        idx = ProjectIndex(project)
        project._shared_index = idx
    return idx


def _is_property(node) -> bool:
    for dec in getattr(node, "decorator_list", []):
        if isinstance(dec, ast.Name) and dec.id == "property":
            return True
    return False


def _is_nonblocking_route(node) -> bool:
    """True for handlers registered with route(..., nonblocking=True) —
    the async frontend dispatches these inline on the event loop."""
    for dec in getattr(node, "decorator_list", []):
        if not isinstance(dec, ast.Call):
            continue
        name = dec.func.attr if isinstance(dec.func, ast.Attribute) else (
            dec.func.id if isinstance(dec.func, ast.Name) else None
        )
        if name != "route":
            continue
        for kw in dec.keywords:
            if kw.arg == "nonblocking" and isinstance(kw.value, ast.Constant):
                if kw.value.value is True:
                    return True
    return False


def body_calls(node) -> list[ast.Call]:
    """Call nodes at this function's own level — nested function/lambda
    bodies are excluded (they run when *called*, which resolve_call models
    as its own edge)."""
    out: list[ast.Call] = []
    stack = list(getattr(node, "body", []))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(n, ast.Call):
            out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out
