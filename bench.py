"""Headline benchmark: ALS serving /recommend throughput.

Mirrors the reference's load harness (app/oryx-app-serving/src/test/java/
.../als/LoadBenchmark.java + LoadTestALSModelFactory: synthetic 50-feature
x 1M-item model, measure requests/sec of top-10 recommend). Reference best
case from docs/docs/performance.html: 437 qps at 50 features x 1M items
WITH LSH (sampleRate 0.3, 32-core Xeon); vs_baseline = measured qps / 437.

Each request is exact top-10 over ALL 1M items (no LSH approximation): the
serving tier micro-batches concurrent requests into one [B,K]x[K,I] bf16
matmul + lax.top_k on device. Timing includes the device->host result
transfer each round. The comparison is conservative: exact retrieval vs
the reference's approximate (LSH 0.3) best case.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# Serving micro-batch window (concurrent requests per dispatch). 4096 is
# the measured throughput knee: larger windows add latency linearly with no
# qps gain, smaller ones leave the device idle between host round-trips.
# Round latency at 4096 is ~90ms — inside the reference's own published
# worst-case (134ms at 250 features x 20M items, BASELINE.md).
BATCH = 4096


def main() -> None:
    import jax
    import jax.numpy as jnp

    from oryx_tpu.ops.als import topk_dot_batch

    n_items, features, k = 1_000_000, 50, 10
    rng = np.random.default_rng(42)
    y = jnp.asarray(
        rng.standard_normal((n_items, features), dtype=np.float32), dtype=jnp.bfloat16
    )
    users = jnp.asarray(
        rng.standard_normal((BATCH, features), dtype=np.float32), dtype=jnp.bfloat16
    )
    y, users = jax.block_until_ready((y, users))

    jax.block_until_ready(topk_dot_batch(users, y, k=k))  # compile
    # double-buffered serve loop: dispatch round N+1 while round N's result
    # streams back to the host (hides host-link latency, as a real server
    # overlapping response rendering with device compute would)
    n, t0, pending, rounds = 0, time.perf_counter(), None, 0
    while True:
        vals, idx = topk_dot_batch(users, y, k=k)
        idx.copy_to_host_async()
        rounds += 1
        if pending is not None:
            np.asarray(pending)  # materialize like a response render
            n += BATCH
        pending = idx
        dt = time.perf_counter() - t0
        if dt > 5.0 and rounds >= 20:
            break
    np.asarray(pending)
    n += BATCH
    dt = time.perf_counter() - t0
    qps = n / dt
    print(
        f"recommend top-{k}, {n_items} items x {features} features, exact, "
        f"micro-batch {BATCH}: {n} reqs in {dt:.2f}s on "
        f"{jax.devices()[0].platform}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "als_recommend_throughput_1M_items_50f",
                "value": round(qps, 1),
                "unit": "qps",
                "vs_baseline": round(qps / 437.0, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
