"""Shared device-view sync helpers (the app-SPI split, PR 10).

Any app serving a FactorStore-backed device matrix keeps it in step
with the live store by dirty-row delta (PR 3's `delta_since` +
`ops/transfer.scatter_rows`). The pieces that are identical across apps
live here — the dirty-delta id-list extension and the process-wide sync
metric families — so the ALS and seq serving models report into ONE
`oryx_device_sync_*` vocabulary and a fix to either helper reaches both.
(The view-tuple state machines themselves stay per-app: ALS carries
unit/LSH/quantized views the seq model has no use for.)
"""

from __future__ import annotations

import logging
import threading

from oryx_tpu.common.metrics import MICROBATCH_BUCKETS, get_registry

log = logging.getLogger(__name__)

_SYNC_METRICS = None
_SYNC_METRICS_LOCK = threading.Lock()


def view_sync_metrics():
    """(bytes counter, seconds histogram, resync counter, lsh histogram) —
    process-wide, lazily registered so importing this module never touches
    the registry."""
    global _SYNC_METRICS
    if _SYNC_METRICS is None:
        with _SYNC_METRICS_LOCK:
            if _SYNC_METRICS is None:
                reg = get_registry()
                _SYNC_METRICS = (
                    reg.counter(
                        "oryx_device_sync_bytes",
                        "host->device bytes moved keeping serving views in "
                        "sync (delta scatters move dirty rows; full "
                        "resyncs move the whole matrix)",
                    ),
                    reg.histogram(
                        "oryx_device_sync_seconds",
                        "wall-clock per serving view resync (delta or full)",
                        buckets=MICROBATCH_BUCKETS,
                    ),
                    reg.counter(
                        "oryx_view_resync_total",
                        "serving view resyncs by kind (delta = dirty-row "
                        "scatter; full = snapshot rebuild, including the "
                        "initial load)",
                        labeled=True,
                    ),
                    reg.histogram(
                        "oryx_lsh_rebuild_seconds",
                        "wall-clock per full LSH partition-index rebuild "
                        "(delta reassignments ride oryx_device_sync_seconds)",
                        buckets=MICROBATCH_BUCKETS,
                    ),
                )
    return _SYNC_METRICS


def extend_view_ids(ids: list, delta) -> list | None:
    """Extend a view's id list with the delta's appended rows, in row
    order. Every index in [len(ids), delta.n) was dirty-logged by the
    write that created it, so the delta must carry its id; None (with a
    warning — the caller falls back to a full resync) if that invariant
    ever breaks."""
    if delta.n <= len(ids):
        return ids
    by_row = dict(zip((int(r) for r in delta.rows), delta.ids))
    try:
        return ids + [by_row[r] for r in range(len(ids), delta.n)]
    except KeyError:  # pragma: no cover - log invariant broken
        log.warning("delta missing ids for appended rows; full resync")
        return None
