"""oryxlint: per-rule positive/negative fixtures + the tier-1 whole-tree
gate (zero unsuppressed findings on the current tree).

Each checker is proven in both directions: a small fixture snippet that
MUST produce the finding, and the adjacent compliant form that must not.
The whole-tree run is the ratchet — new code that blocks an event loop,
touches guarded state without its lock, side-effects inside a jitted
function, or drifts config/metric/ratchet vocabulary fails tier-1.
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys
import textwrap

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from tools.oryxlint.core import Project, run_lint  # noqa: E402
from tools.oryxlint.callgraph import ProjectIndex  # noqa: E402
from tools.oryxlint.checkers.eventloop import EventLoopChecker  # noqa: E402
from tools.oryxlint.checkers.jaxpurity import JaxPurityChecker  # noqa: E402
from tools.oryxlint.checkers.lockdiscipline import LockDisciplineChecker  # noqa: E402
from tools.oryxlint.checkers.lockorder import (  # noqa: E402
    LockOrderChecker, load_canonical_order,
)
from tools.oryxlint.checkers.paramflow import ParamFlowChecker  # noqa: E402
from tools.oryxlint.checkers.placement import PlacementChecker  # noqa: E402
from tools.oryxlint.checkers.shardtopology import ShardTopologyChecker  # noqa: E402


def _lint_fixture(tmp_path, source: str, checkers) -> tuple[list, list]:
    pkg = tmp_path / "oryx_tpu"
    pkg.mkdir(exist_ok=True)
    (pkg / "mod.py").write_text(textwrap.dedent(source), encoding="utf-8")
    return run_lint(tmp_path, checkers=checkers)


def _rules(findings) -> list[str]:
    return [f.rule for f in findings]


# -- event-loop blocking-call detector ---------------------------------------


def test_blocking_call_in_async_def_caught(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        import time

        async def handler():
            time.sleep(1)
    """, [EventLoopChecker()])
    assert _rules(active) == ["blocking-call-on-loop"]
    assert "time.sleep" in active[0].message


def test_blocking_call_reached_transitively(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        import subprocess

        def helper():
            subprocess.run(["true"])

        async def handler():
            helper()
    """, [EventLoopChecker()])
    assert _rules(active) == ["blocking-call-on-loop"]
    assert "handler -> helper" in active[0].message


def test_nonblocking_route_handler_is_a_root(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        def register(app):
            @app.route("GET", "/x", nonblocking=True)
            def handler(a, req):
                a.input_producer.send("k", "line")

            @app.route("POST", "/y")
            def worker_handler(a, req):
                a.input_producer.send("k", "line")  # worker pool: legal
    """, [EventLoopChecker()])
    assert len(active) == 1
    assert active[0].rule == "blocking-call-on-loop"
    assert "producer" in active[0].message


def test_offloop_annotation_honored(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        import time

        def sampler():  # oryxlint: offloop (dedicated thread)
            time.sleep(2)

        async def handler():
            sampler()
    """, [EventLoopChecker()])
    assert active == []


# -- lock discipline ----------------------------------------------------------


_LOCK_FIXTURE = """
    import threading


    class Shared:
        def __init__(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition(self._lock)
            self.n = 0  # guarded-by: _lock
            self.view = None  # guarded-by: _lock (writes)

        def locked_write(self):
            with self._lock:
                self.n += 1

        def cond_alias_write(self):
            with self._cond:
                self.n += 1

        def lockfree_snapshot_read(self):
            return self.view

        def contract(self):  # oryxlint: holds=_lock
            return self.n
"""


def test_with_lock_and_alias_and_writes_qualifier_pass(tmp_path):
    active, _ = _lint_fixture(tmp_path, _LOCK_FIXTURE, [LockDisciplineChecker()])
    assert active == []


def test_guarded_by_violation_caught(tmp_path):
    active, _ = _lint_fixture(tmp_path, _LOCK_FIXTURE + """
        def racy(self):
            self.n += 1

    Shared.racy = racy
    """, [LockDisciplineChecker()])
    # note: module-level function attached post-hoc is outside the class —
    # the in-class violation form is what we assert on below
    active2, _ = _lint_fixture(tmp_path, _LOCK_FIXTURE.replace(
        "def contract(self):  # oryxlint: holds=_lock",
        "def racy(self):\n            self.n += 1\n\n        def contract(self):  # oryxlint: holds=_lock",
    ), [LockDisciplineChecker()])
    assert _rules(active2) == ["guarded-by"]
    assert "self.n" in active2[0].message


def test_closure_does_not_inherit_held_lock(tmp_path):
    active, _ = _lint_fixture(tmp_path, _LOCK_FIXTURE.replace(
        "def contract(self):  # oryxlint: holds=_lock",
        "def leak(self):\n"
        "            with self._lock:\n"
        "                return lambda: self.n\n\n"
        "        def contract(self):  # oryxlint: holds=_lock",
    ), [LockDisciplineChecker()])
    assert _rules(active) == ["guarded-by"]


def test_writes_qualifier_still_checks_stores(tmp_path):
    active, _ = _lint_fixture(tmp_path, _LOCK_FIXTURE.replace(
        "def contract(self):  # oryxlint: holds=_lock",
        "def unlocked_swap(self):\n            self.view = ()\n\n"
        "        def contract(self):  # oryxlint: holds=_lock",
    ), [LockDisciplineChecker()])
    assert _rules(active) == ["guarded-by"]
    assert "self.view" in active[0].message


# -- jax purity / donation ----------------------------------------------------


def test_jit_side_effect_caught(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        import jax

        @jax.jit
        def impure(x):
            print("tracing")
            return x
    """, [JaxPurityChecker()])
    assert _rules(active) == ["jit-side-effect"]


def test_jit_closed_over_mutation_and_rng_caught(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        import numpy as np
        import jax

        hits = []

        @jax.jit
        def impure(x):
            hits.append(1)
            return x + np.random.rand()
    """, [JaxPurityChecker()])
    assert sorted(_rules(active)) == ["jit-side-effect", "jit-side-effect"]


def test_pure_jit_and_pallas_kernel_pass(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        from functools import partial

        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnames=("k",))
        def pure(x, k):
            local = []
            local.append(k)  # local mutation is fine
            return jnp.sum(x) + len(local)

        def _kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2

        def build(pl):
            return pl.pallas_call(_kernel)
    """, [JaxPurityChecker()])
    assert active == []


def test_donation_reuse_caught_and_rebind_allowed(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        from functools import partial

        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def donated(buf, row):
            return buf + row

        def bug(a, b):
            out = donated(a, b)
            return out + a

        def carry_ok(a, b):
            a = donated(a, b)
            return a + b
    """, [JaxPurityChecker()])
    assert _rules(active) == ["donation-reuse"]
    assert "'a'" in active[0].message


def test_donates_annotation_conditional_wrapper(tmp_path):
    """`donates=0 when donate` (the scatter_rows contract): reuse after a
    donate=True call is flagged; the non-donating form is free."""
    active, _ = _lint_fixture(tmp_path, """
        def scatter(buf, rows, *, donate=False):  # oryxlint: donates=0 when donate
            return buf

        def serving_path_bug(view, rows):
            out = scatter(view, rows, donate=True)
            return out, view  # in-flight dispatches read a deleted buffer

        def double_buffer_ok(view, rows):
            out = scatter(view, rows)
            return out, view
    """, [JaxPurityChecker()])
    assert _rules(active) == ["donation-reuse"]
    assert "'view'" in active[0].message


def test_donated_wrapper_assignment_form_detected(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        from functools import partial

        import jax

        def _train(x, y, carry):
            return carry + x + y

        train_donated = partial(jax.jit, donate_argnums=(2,))(_train)

        def bug(x, y, c):
            out = train_donated(x, y, c)
            return out + c
    """, [JaxPurityChecker()])
    assert _rules(active) == ["donation-reuse"]


# -- suppression syntax -------------------------------------------------------


def test_suppression_comment_honored(tmp_path):
    active, suppressed = _lint_fixture(tmp_path, """
        import time

        async def handler():
            time.sleep(1)  # oryxlint: disable=blocking-call-on-loop
    """, [EventLoopChecker()])
    assert active == []
    assert _rules(suppressed) == ["blocking-call-on-loop"]


def test_unknown_rule_suppression_rejected(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        x = 1  # oryxlint: disable=no-such-rule
    """, [EventLoopChecker()])
    assert _rules(active) == ["unknown-rule"]
    assert "no-such-rule" in active[0].message


def test_unknown_rule_finding_is_not_suppressible(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        x = 1  # oryxlint: disable=unknown-rule,bogus-rule
    """, [EventLoopChecker()])
    assert "unknown-rule" in _rules(active)


# -- consistency rules through oryxlint ---------------------------------------


def test_config_rule_catches_undeclared_key(tmp_path):
    from tools.oryxlint.checkers import consistency

    ref_dir = tmp_path / "oryx_tpu" / "common"
    ref_dir.mkdir(parents=True)
    (ref_dir / "reference.conf").write_text(
        "oryx { id = \"x\" }\n", encoding="utf-8"
    )
    (tmp_path / "oryx_tpu" / "mod.py").write_text(
        'v = config.get_int("oryx.not.declared", 1)\n', encoding="utf-8"
    )
    findings = consistency.config_findings(tmp_path)
    assert ["config-keys"] == [f.rule for f in findings]
    assert "oryx.not.declared" in findings[0].message


def test_metric_rule_catches_undocumented_name(tmp_path):
    from tools.oryxlint.checkers import consistency

    (tmp_path / "oryx_tpu").mkdir()
    (tmp_path / "docs").mkdir()
    (tmp_path / "oryx_tpu" / "mod.py").write_text(
        'NAME = "oryx_undocumented_total"\n', encoding="utf-8"
    )
    (tmp_path / "docs" / "observability.md").write_text(
        "| `oryx_ghost_metric` | gone |\nscore_mode\n", encoding="utf-8"
    )
    (tmp_path / "bench.py").write_text(
        '"qps_quantized" "approx_recall_at_10" "quantized_recall_at_10" '
        '"lsh_measured_recall_at_10"\n', encoding="utf-8"
    )
    findings = consistency.metric_findings(tmp_path)
    msgs = " | ".join(f.message for f in findings)
    assert "oryx_undocumented_total" in msgs  # code -> docs direction
    assert "oryx_ghost_metric" in msgs        # docs -> code reverse rule


# -- dataflow: param-dropped (the PR 11 dropped-shard_mesh class) -------------


def test_param_dropped_catches_resume_path_drop(tmp_path):
    """The ancestor bug: a checkpointed train path accepts the sharding
    config but forwards it only on the fresh path — the resume path
    silently trains unsharded."""
    active, _ = _lint_fixture(tmp_path, """
        def train_chunk(y, shard_mesh=None):
            return compute(y, shard_mesh)

        def train_checkpointed(data, config):
            shards = config.get_int("oryx.batch.train.shards", 1)
            if data.resume:
                y = load_ckpt()
                return train_chunk(y)  # drops shards on the resume path
            return train_chunk(data.y0, shard_mesh=shards)
    """, [ParamFlowChecker()])
    assert _rules(active) == ["param-dropped"]
    assert "oryx.batch.train.shards" in active[0].message
    assert "dropped on the path returning" in active[0].message


def test_param_dropped_interprocedural_callee_drop(tmp_path):
    """Handing the value to a wrapper does not launder it: the engine
    recurses into the callee's parameter with the same every-path rule."""
    active, _ = _lint_fixture(tmp_path, """
        def inner(y, shard_mesh=None):
            if y is None:
                return base(y)
            return base(y, shard_mesh)

        def outer(config, y):
            sm = config.get_int("oryx.batch.train.shards", 1)
            return inner(y, shard_mesh=sm)
    """, [ParamFlowChecker()])
    assert _rules(active) == ["param-dropped"]
    assert "inner" in active[0].message
    assert "does not reach a sink on every path" in active[0].message


def test_param_dropped_through_partial_rebind_offsets_positionals(tmp_path):
    """A call through a `partial(...)` alias binds positionals starting
    at the first UNBOUND callee parameter: `g = partial(train, data)`
    then `g(n)` reaches train's SECOND parameter — whose resume path
    drops it (flagged); the compliant callee stays clean."""
    active, _ = _lint_fixture(tmp_path, """
        from functools import partial

        def train(data, shards=1):
            if data is None:
                return fit(data)
            return fit(data, shards)

        def run(config, data):
            g = partial(train, data)
            n = config.get_int("oryx.batch.train.shards", 1)
            return g(n)

        def train_ok(data, shards=1):
            return fit(data, shards)

        def run_ok(config, data):
            h = partial(train_ok, data)
            n = config.get_int("oryx.batch.train.shards", 1)
            return h(n)
    """, [ParamFlowChecker()])
    assert _rules(active) == ["param-dropped"]
    assert "'shards'" in active[0].message and "train" in active[0].message


def test_param_dropped_compliant_forms_pass(tmp_path):
    """Guard-on-the-value returns, attribute stores, full threading, and
    the `# oryxlint: sink` terminal-read annotation are all clean."""
    active, _ = _lint_fixture(tmp_path, """
        class Layer:
            def adopt(self, config):
                n = config.get_int("oryx.batch.train.shards", 1)
                self.shards = n

        def guarded(data, config):
            shards = config.get_int("oryx.batch.train.shards", 1)
            if shards <= 1:
                return plain(data)
            return sharded(data, shards)

        def terminal(config):
            n = config.get_int("oryx.batch.train.shards", 1)  # oryxlint: sink
            return 0
    """, [ParamFlowChecker()])
    assert active == []


def test_param_dropped_never_consumed_flagged_at_read(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        def dead_read(config):
            n = config.get_int("oryx.fleet.replica.count", 2)
            return 0
    """, [ParamFlowChecker()])
    assert _rules(active) == ["param-dropped"]
    assert "never reaches a sink" in active[0].message


# -- dataflow: device-placement (the PR 11 uncommitted-device_put class) ------


def test_device_placement_uncommitted_store_caught(tmp_path):
    """The ancestor bug: shards staged under a default_device context
    only — uncommitted buffers silently migrate to device 0 on first
    use, recreating the multi-chip OOM sharding exists to prevent."""
    active, _ = _lint_fixture(tmp_path, """
        import jax

        class ShardedView:
            def __init__(self, host, dev):
                with jax.default_device(dev):
                    staged = jax.device_put(host)  # no explicit device
                self.view = staged

        class CommittedView:
            def __init__(self, host, dev):
                self.view = jax.device_put(host, dev)
    """, [PlacementChecker()])
    assert _rules(active) == ["device-placement"]
    assert "uncommitted" in active[0].message
    assert "self.view" in active[0].message


def test_device_placement_tracks_through_helper_returns(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        import jax

        def stage(host):
            return jax.device_put(host)

        class View:
            def __init__(self, host):
                self.y = stage(host)
    """, [PlacementChecker()])
    assert _rules(active) == ["device-placement"]


def test_device_placement_mesh_shard_mesh_pair_caught(tmp_path):
    """Both layouts constructed and passed to one train call: the loud
    runtime raise PR 11 added, now caught before runtime. Wrapper
    forwarding and the conditional-exclusivity idiom stay clean."""
    active, _ = _lint_fixture(tmp_path, """
        def pair_bug(data, make_mesh, make_shard):
            mesh = make_mesh(2)
            sm = make_shard(2)
            return train_als(data, mesh=mesh, shard_mesh=sm)

        def wrapper_ok(data, mesh=None, shard_mesh=None):
            return train_als(data, mesh=mesh, shard_mesh=shard_mesh)

        def conditional_ok(data, make_mesh, shard_mesh=None):
            return train_als_warm(
                data,
                mesh=None if shard_mesh is not None else make_mesh(),
                shard_mesh=shard_mesh,
            )
    """, [PlacementChecker()])
    assert _rules(active) == ["device-placement"]
    assert "mutually exclusive" in active[0].message


# -- dataflow: lock-order (the PR 11 convention-only multi-lock class) --------


_INVERTED_LOCKS = """
    import threading

    class Batcher:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def forward(self):
            with self._a:
                with self._b:
                    return 1

        def inverted(self):
            with self._b:
                with self._a:
                    return 2
"""


def test_lock_order_inverted_pair_caught(tmp_path):
    active, _ = _lint_fixture(tmp_path, _INVERTED_LOCKS, [LockOrderChecker()])
    assert _rules(active) == ["lock-order"]
    assert "inverted lock pair" in active[0].message
    assert "deadlock" in active[0].message


def test_lock_order_consistent_nesting_passes(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        import threading

        class Batcher:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:
                        return 1

            def also_forward(self):
                with self._a:
                    return self._under_a()

            def _under_a(self):  # oryxlint: holds=_a
                with self._b:
                    return 2
    """, [LockOrderChecker()])
    assert active == []


def test_lock_order_transitive_edge_through_call(tmp_path):
    """The acquisition graph crosses function boundaries: holding A and
    calling a helper that takes B in the opposite order elsewhere is the
    same deadlock, invisible to any single-function review."""
    active, _ = _lint_fixture(tmp_path, """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def outer(self):
                with self._a:
                    return self.helper_b()

            def helper_b(self):
                with self._b:
                    return 1

            def other_thread(self):
                with self._b:
                    with self._a:
                        return 2
    """, [LockOrderChecker()])
    assert _rules(active) == ["lock-order"]


def test_lock_order_canonical_order_violation(tmp_path):
    """An edge going backwards against lockorder.toml fails even before
    the inverse edge lands — the second half of a deadlock should never
    get written."""
    order = tmp_path / "lockorder.toml"
    order.write_text(
        'order = [\n  "Batcher._a",\n  "Batcher._b",\n]\n', encoding="utf-8"
    )
    active, _ = _lint_fixture(tmp_path, """
        import threading

        class Batcher:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def backwards(self):
                with self._b:
                    with self._a:
                        return 1
    """, [LockOrderChecker(order_file=order)])
    assert _rules(active) == ["lock-order"]
    assert "canonical order" in active[0].message


def test_committed_lockorder_toml_is_nonempty_and_ordered():
    """The committed canonical order exists and ends leaf-ward: shared
    observability locks (the metrics registry) come after the domain
    locks that call into them."""
    order = load_canonical_order()
    assert "MetricsRegistry._lock" in order
    assert order.index("MetricsRegistry._lock") == len(order) - 1
    for domain in ("ALSServingModel._sync_lock", "TopKBatcher._lock"):
        assert order.index(domain) < order.index("MetricsRegistry._lock")


# -- dataflow: shard-topology (the PR 11 half-wired-surface class) ------------


def test_shard_topology_new_key_flagged(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        def build(config):
            n = config.get_int("oryx.pod.shards", 1)
            return n
    """, [ShardTopologyChecker()])
    assert any(
        f.rule == "shard-topology" and "oryx.pod.shards" in f.message
        for f in active
    )


def test_shard_topology_half_wired_healthz_flagged(tmp_path):
    """The healthz resource reads the shard count but never emits the
    `shards` field — the front can no longer vet replica topology."""
    res = tmp_path / "oryx_tpu" / "serving" / "resources"
    res.mkdir(parents=True)
    (res / "common.py").write_text(textwrap.dedent("""
        def healthz(a):
            n = a.config.get_int("oryx.serving.api.sync.shard-count", 1)
            body = {"ok": True}
            return encode(body, n)
    """), encoding="utf-8")
    active, _ = run_lint(tmp_path, checkers=[ShardTopologyChecker()])
    assert any(
        f.rule == "shard-topology" and '"shards"' in f.message
        for f in active
    )


def test_shard_topology_fully_wired_fixture_passes(tmp_path):
    res = tmp_path / "oryx_tpu" / "serving" / "resources"
    res.mkdir(parents=True)
    (res / "common.py").write_text(textwrap.dedent("""
        def healthz(a):
            n = a.config.get_int("oryx.serving.api.sync.shard-count", 1)
            return {"ok": True, "shards": n}
    """), encoding="utf-8")
    fleet = tmp_path / "oryx_tpu" / "fleet"
    fleet.mkdir(parents=True)
    (fleet / "supervisor.py").write_text(textwrap.dedent("""
        def overlays(config):
            shards = config.get_int("oryx.fleet.shards", 1)
            return {"oryx.serving.api.sync.shard-count": shards}
    """), encoding="utf-8")
    (fleet / "front.py").write_text(textwrap.dedent("""
        class ReplicaInfo:
            def __init__(self):
                self.shards = None

        def probe(r, body):
            r.shards = body.get("shards")
    """), encoding="utf-8")
    (tmp_path / "oryx_tpu" / "batch.py").write_text(
        'def b(config):\n'
        '    n = config.get_int("oryx.batch.train.shards", 1)\n'
        '    return n\n',
        encoding="utf-8",
    )
    (tmp_path / "bench.py").write_text(
        'FIELDS = ["shard_devices"]\n', encoding="utf-8"
    )
    active, _ = run_lint(tmp_path, checkers=[ShardTopologyChecker()])
    assert active == []


# -- callgraph edge cases (PR 12 satellites) ----------------------------------


def _index(tmp_path, source: str) -> ProjectIndex:
    pkg = tmp_path / "oryx_tpu"
    pkg.mkdir(exist_ok=True)
    (pkg / "mod.py").write_text(textwrap.dedent(source), encoding="utf-8")
    return ProjectIndex(Project.load(tmp_path))


def test_callgraph_double_partial_resolves(tmp_path):
    idx = _index(tmp_path, """
        from functools import partial

        def base(a, b, c):
            return a

        once = partial(base, 1)
        twice = partial(partial(base, 1), 2)

        def caller():
            return twice(3) + once(2, 3)
    """)
    caller = idx.top_level[("oryx_tpu/mod.py", "caller")]
    import ast as _ast

    calls = [n for n in _ast.walk(caller.node) if isinstance(n, _ast.Call)]
    resolved = {t.name for c in calls for t in idx.resolve_call(caller, c)}
    assert resolved == {"base"}
    assert len(idx.partial_aliases) == 2


def test_callgraph_property_typed_receiver_resolves(tmp_path):
    """`self.store.refresh_view()` resolves through the @property's
    return annotation even when two classes define the method name (the
    unique-definition fallback cannot apply)."""
    idx = _index(tmp_path, """
        class Store:
            def refresh_view(self):
                return 1

        class Decoy:
            def refresh_view(self):
                return 2

        class Owner:
            def __init__(self, s: Store):
                self._s = s

            @property
            def store(self) -> Store:
                return self._s

            def go(self):
                return self.store.refresh_view()
    """)
    go = idx.classes["Owner"].methods["go"]
    import ast as _ast

    calls = [n for n in _ast.walk(go.node) if isinstance(n, _ast.Call)]
    targets = [t for c in calls for t in idx.resolve_call(go, c)]
    assert [t.cls for t in targets] == ["Store"]


def test_callgraph_lambda_call_sites_counted(tmp_path):
    idx = _index(tmp_path, """
        def g():
            return (lambda x: x)(3)
    """)
    g = idx.top_level[("oryx_tpu/mod.py", "g")]
    import ast as _ast

    for c in [n for n in _ast.walk(g.node) if isinstance(n, _ast.Call)]:
        idx.resolve_call(g, c)
    assert idx.stats["lambda_sites"] == 1
    assert idx.stats["call_sites"] >= 1


def test_cli_stats_prints_resolution_rate():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.oryxlint", "--stats"],
        cwd=ROOT, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "resolved" in proc.stdout and "lambda call site" in proc.stdout


# -- check_bench stale-pending ------------------------------------------------


def _bank(tmp_path, name: str, payload: dict) -> None:
    (tmp_path / name).write_text(json.dumps(payload), encoding="utf-8")


def test_stale_pending_fails_once_banked_artifact_measures_it(tmp_path):
    from tools import check_bench

    rows = [{
        "name": "qps_quantized", "platform": "tpu", "baseline": 1.0,
        "direction": "up", "pending": True, "pending_since": 8,
    }]
    # artifact OLDER than the declaration: flag is legitimate
    _bank(tmp_path, "BENCH_TPU_WINDOW_r05.json",
          {"final": {"platform": "tpu", "qps_quantized": 5.0}})
    assert check_bench.stale_pending_problems(rows, root=str(tmp_path)) == []
    # artifact from the declaring round or later measuring it: stale
    _bank(tmp_path, "BENCH_TPU_WINDOW_r09.json",
          {"final": {"platform": "tpu", "qps_quantized": 5.0}})
    problems = check_bench.stale_pending_problems(rows, root=str(tmp_path))
    assert len(problems) == 1 and "remove the pending flag" in problems[0]


def test_stale_pending_reads_parsed_shape_round_artifacts(tmp_path):
    """Driver round artifacts (BENCH_r{N}.json) nest their metrics under
    a `parsed` key — the scan must see them, or a CPU pending row could
    float forever."""
    from tools import check_bench

    rows = [{
        "name": "some_cpu_metric", "platform": "cpu", "baseline": 1.0,
        "direction": "up", "pending": True, "pending_since": 8,
    }]
    _bank(tmp_path, "BENCH_r09.json", {
        "n": 9, "rc": 0,
        "parsed": {"platform": "cpu", "some_cpu_metric": 2.5},
    })
    problems = check_bench.stale_pending_problems(rows, root=str(tmp_path))
    assert len(problems) == 1 and "round-9 cpu artifact" in problems[0]


def test_stale_pending_tolerates_malformed_rows(tmp_path):
    """A nameless pending row (already reported by the vocabulary check)
    or an unparseable pending_since must degrade, not traceback."""
    from tools import check_bench

    _bank(tmp_path, "BENCH_TPU_WINDOW_r09.json",
          {"final": {"platform": "tpu", "x": 1.0}})
    rows = [
        {"pending": True},  # nameless
        {"name": "x", "platform": "tpu", "baseline": 1.0, "direction": "up",
         "pending": True, "pending_since": "not-a-round"},
    ]
    problems = check_bench.stale_pending_problems(rows, root=str(tmp_path))
    # nameless row skipped; bad since falls back to the strict reading
    assert len(problems) == 1 and problems[0].startswith("x:")


def test_pending_survives_artifacts_that_do_not_measure_it(tmp_path):
    from tools import check_bench

    rows = [{
        "name": "qps_quantized", "platform": "tpu", "baseline": 1.0,
        "direction": "up", "pending": True, "pending_since": 8,
    }]
    # right platform, metric absent
    _bank(tmp_path, "BENCH_TPU_WINDOW_r09.json", {"final": {"platform": "tpu"}})
    # wrong platform, metric present
    _bank(tmp_path, "BENCH_r10.json",
          {"final": {"platform": "cpu", "qps_quantized": 5.0}})
    assert check_bench.stale_pending_problems(rows, root=str(tmp_path)) == []


def test_stale_pending_recognizes_pr11_shard_rows(tmp_path):
    """PR 11 committed `shard_topk_scaling_2shard` and `train_mfu` as
    pending+pending_since:11 — the staleness gate must trip each the
    moment a banked TPU artifact from round >= 11 measures it, and
    tolerate artifacts that are older or do not measure it."""
    from tools import check_bench

    rows = [
        m for m in check_bench.load_baseline(str(ROOT / "BASELINE_RATCHET.json"))
        if m.get("name") in ("shard_topk_scaling_2shard", "train_mfu")
    ]
    assert len(rows) == 2, "the PR 11 pending rows are gone from the ratchet"
    for m in rows:
        assert m.get("pending") and m.get("pending_since") == 11
        assert m.get("platform") == "tpu"

    # tolerate: a TPU artifact OLDER than the declaring round measures it
    _bank(tmp_path, "BENCH_TPU_WINDOW_r05.json", {
        "final": {"platform": "tpu", "shard_topk_scaling_2shard": 1.7,
                  "train_mfu": 0.02},
    })
    assert check_bench.stale_pending_problems(rows, root=str(tmp_path)) == []
    # tolerate: a round-11 TPU artifact that does NOT measure them
    _bank(tmp_path, "BENCH_TPU_WINDOW_r11.json", {
        "final": {"platform": "tpu", "kernel_mfu": 0.01},
    })
    assert check_bench.stale_pending_problems(rows, root=str(tmp_path)) == []
    # trip: the same round-11 artifact now banks both measurements
    _bank(tmp_path, "BENCH_TPU_WINDOW_r11.json", {
        "final": {"platform": "tpu", "shard_topk_scaling_2shard": 1.8,
                  "train_mfu": 0.015},
    })
    problems = check_bench.stale_pending_problems(rows, root=str(tmp_path))
    assert len(problems) == 2
    assert all("remove the pending flag" in p for p in problems)


def test_committed_ratchet_has_no_stale_pending_rows():
    from tools import check_bench

    metrics = check_bench.load_baseline(str(ROOT / "BASELINE_RATCHET.json"))
    assert check_bench.stale_pending_problems(metrics, root=str(ROOT)) == []
    for m in metrics:
        if m.get("pending"):
            assert "pending_since" in m, (
                f"{m['name']}: pending rows must record the declaring round"
            )


# -- CLI ----------------------------------------------------------------------


def test_cli_json_and_changed_modes():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.oryxlint", "--json"],
        cwd=ROOT, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["findings"] == []
    assert "blocking-call-on-loop" in doc["rules"]

    proc = subprocess.run(
        [sys.executable, "-m", "tools.oryxlint", "--list-rules"],
        cwd=ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    for rule in ("guarded-by", "jit-side-effect", "donation-reuse",
                 "config-keys", "metric-docs", "bench-ratchet",
                 "param-dropped", "device-placement", "lock-order",
                 "shard-topology"):
        assert rule in proc.stdout


def test_json_findings_carry_severity_and_fix_hint(tmp_path):
    """The stable --json per-finding schema: path/line/rule/severity/
    fix_hint/message (tools/precommit.sh groups on these fields)."""
    active, _ = _lint_fixture(tmp_path, """
        def dead_read(config):
            n = config.get_int("oryx.fleet.replica.count", 2)
            return 0
    """, [ParamFlowChecker()])
    assert len(active) == 1
    d = active[0].as_dict()
    assert set(d) == {"path", "line", "rule", "severity", "fix_hint", "message"}
    assert d["rule"] == "param-dropped"
    assert d["severity"] == "error"
    assert "sink" in d["fix_hint"]


def test_precommit_script_clean_exit():
    """tools/precommit.sh consumes the --json schema and exits 0 on a
    clean (or unchanged) tree, with ruff optional."""
    proc = subprocess.run(
        ["sh", str(ROOT / "tools" / "precommit.sh")],
        cwd=ROOT, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "precommit:" in proc.stdout


# -- flight-events vocabulary rule (ISSUE 14) --------------------------------


def _flight_fixture(tmp_path, source: str, extra_doc_rows: str = ""):
    """Fixture tree for the flight-events rule: a module + a docs catalog
    that (by default) documents every registered kind."""
    import textwrap as _tw

    from oryx_tpu.common.flightrec import EVENT_KINDS
    from tools.oryxlint.checkers.consistency import flight_findings

    pkg = tmp_path / "oryx_tpu"
    pkg.mkdir(exist_ok=True)
    (pkg / "mod.py").write_text(_tw.dedent(source), encoding="utf-8")
    docs = tmp_path / "docs"
    docs.mkdir(exist_ok=True)
    rows = "\n".join(f"| `{k}` | x | x |" for k in sorted(EVENT_KINDS))
    (docs / "observability.md").write_text(
        "# Observability\n\n### Flight-recorder event catalog\n\n"
        "| Kind | Recorded by | Meaning |\n|---|---|---|\n"
        + rows + "\n" + extra_doc_rows + "\n\n## Next section\n",
        encoding="utf-8",
    )
    project = Project.load(tmp_path)
    return flight_findings(tmp_path, project)


def test_flight_unregistered_kind_at_call_site_caught(tmp_path):
    findings = _flight_fixture(tmp_path, """
        from oryx_tpu.common.flightrec import get_flightrec

        def f():
            get_flightrec().record(kind="ejectoin", replica="r0")
    """)
    assert [f.rule for f in findings] == ["flight-events"]
    assert "'ejectoin'" in findings[0].message
    assert findings[0].path == "oryx_tpu/mod.py"


def test_flight_registered_kind_passes(tmp_path):
    findings = _flight_fixture(tmp_path, """
        from oryx_tpu.common.flightrec import get_flightrec

        def f():
            get_flightrec().record(kind="ejection", replica="r0", port=1)
    """)
    assert findings == []


def test_flight_non_literal_kind_skipped(tmp_path):
    # confident-only, like the dataflow checkers: a kind that arrives
    # through a variable is not flagged
    findings = _flight_fixture(tmp_path, """
        from oryx_tpu.common.flightrec import get_flightrec

        def f(kind):
            get_flightrec().record(kind=kind)
    """)
    assert findings == []


def test_flight_doc_row_without_registered_kind_caught(tmp_path):
    findings = _flight_fixture(
        tmp_path, "x = 1\n", extra_doc_rows="| `ghost-kind` | x | x |"
    )
    assert len(findings) == 1
    assert "ghost-kind" in findings[0].message
    assert findings[0].path == "docs/observability.md"


def test_flight_missing_doc_row_caught(tmp_path):
    import textwrap as _tw

    from tools.oryxlint.checkers.consistency import flight_findings

    pkg = tmp_path / "oryx_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text("x = 1\n", encoding="utf-8")
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(_tw.dedent("""
        ### Flight-recorder event catalog

        | Kind | x |
        |---|---|
        | `ejection` | x |
    """), encoding="utf-8")
    findings = flight_findings(tmp_path, Project.load(tmp_path))
    # every registered kind except `ejection` lacks a docs row
    from oryx_tpu.common.flightrec import EVENT_KINDS

    assert len(findings) == len(EVENT_KINDS) - 1
    assert all(f.rule == "flight-events" for f in findings)


def test_flight_catalog_and_docs_agree_on_the_real_tree():
    """Both directions on the committed tree: every registered kind has a
    docs row and vice versa (the whole-tree gate would catch this too —
    this pins the section parser itself against doc refactors)."""
    from oryx_tpu.common.flightrec import EVENT_KINDS
    from tools.oryxlint.checkers.consistency import flight_doc_kinds

    assert flight_doc_kinds(ROOT / "docs" / "observability.md") == set(
        EVENT_KINDS
    )


# -- the tier-1 whole-tree gate ----------------------------------------------


def test_whole_tree_is_clean():
    """`python -m tools.oryxlint` on the tree: zero unsuppressed findings.

    This is the ratchet the new checkers hold: event-loop discipline,
    guarded-by lock discipline, jit purity/donation, and the
    config/metric/ratchet consistency contracts, all at once. Suppressed
    findings are allowed (each carries an in-source justification), but
    every suppression must name a real rule (unknown-rule is active)."""
    active, suppressed = run_lint(ROOT)
    rendered = "\n".join(f.render() for f in active)
    assert active == [], f"oryxlint findings on the tree:\n{rendered}"
    # the tree currently carries a known, justified suppression budget;
    # growing it should be a conscious review decision, not drift
    assert len(suppressed) <= 8, [f.render() for f in suppressed]


def test_production_annotations_are_load_bearing():
    """The annotation seeding is real, not decorative: the threaded core
    declares guarded attributes, holds-contracts, and offloop proofs the
    checkers actually consume."""
    project = Project.load(ROOT)
    by_path = {m.relpath: m for m in project.modules}
    guarded_files = [
        "oryx_tpu/common/metrics.py",
        "oryx_tpu/common/perfstats.py",
        "oryx_tpu/common/tracing.py",
        "oryx_tpu/serving/batcher.py",
        "oryx_tpu/fleet/front.py",
        "oryx_tpu/fleet/supervisor.py",
        "oryx_tpu/apps/als/serving.py",
    ]
    for rel in guarded_files:
        assert by_path[rel].guarded_lines, f"{rel}: no guarded-by seeds"
    assert by_path["oryx_tpu/serving/server.py"].offloop_lines, (
        "the lag-sampler offloop proof (PR 7 bug class) is gone"
    )
    assert by_path["oryx_tpu/apps/als/serving.py"].holds_lines, (
        "the 'call under _sync_lock' contracts lost their holds= form"
    )
