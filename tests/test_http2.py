"""HTTP/2 frontend: negotiation and stream semantics (round-2 verdict #6).

The reference's Tomcat connector upgrades to h2 (ServingLayer.java:229
addUpgradeProtocol(new Http2Protocol())); the asyncio frontend implements
RFC 7540 + 7541 from scratch (serving/http2.py, serving/hpack.py).

Fidelity comes from TWO client sides: curl/nghttp2 (a real, independent
h2 stack — prior knowledge, h2c upgrade, POST bodies, and ALPN over TLS)
and a raw-socket client driving interleaved streams to prove actual
multiplexing onto the deferred dispatch path.
"""

from __future__ import annotations

import json
import shutil
import socket
import ssl
import struct
import subprocess

import pytest

from tests.test_aserver import _config, _setup_bus, _wait_ready
from oryx_tpu.serving.server import ServingLayer

curl = shutil.which("curl")
needs_curl = pytest.mark.skipif(curl is None, reason="curl not available")


def _curl(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [curl, "-s", "-i", "--max-time", "20", *args],
        capture_output=True,
        text=True,
        timeout=30,
    )


@needs_curl
def test_prior_knowledge_negotiation():
    bus = "mem://h2pk"
    _setup_bus(bus)
    with ServingLayer(_config(bus, "async")) as sl:
        _wait_ready(sl.port)
        r = _curl(
            "--http2-prior-knowledge",
            f"http://127.0.0.1:{sl.port}/distinct",
        )
        assert r.returncode == 0, r.stderr
        assert r.stdout.startswith("HTTP/2 200"), r.stdout[:200]
        body = r.stdout.replace("\r\n", "\n").rsplit("\n\n", 1)[-1]
        assert json.loads(body.strip())["word"] == 2

        # a second, fresh curl against the same server (this curl
        # 7.88.1 has the known h2 connection-REUSE client bug — reuse
        # and true multiplexing are proven by the raw-socket tests
        # below instead)
        r = _curl(
            "--http2-prior-knowledge",
            f"http://127.0.0.1:{sl.port}/ready",
        )
        assert r.stdout.startswith("HTTP/2 200")


@needs_curl
def test_h2c_upgrade():
    """curl --http2 on cleartext sends Upgrade: h2c; the response must
    come back 101 + HTTP/2 on stream 1."""
    bus = "mem://h2up"
    _setup_bus(bus)
    with ServingLayer(_config(bus, "async")) as sl:
        _wait_ready(sl.port)
        r = _curl("--http2", f"http://127.0.0.1:{sl.port}/distinct")
        assert "101 Switching Protocols" in r.stdout, r.stdout[:300]
        assert "HTTP/2 200" in r.stdout
        body = r.stdout.replace("\r\n", "\n").rsplit("\n\n", 1)[-1]
        assert json.loads(body.strip())["word"] == 2


@needs_curl
def test_h2_post_body_and_404():
    bus = "mem://h2post"
    _setup_bus(bus)
    with ServingLayer(_config(bus, "async")) as sl:
        _wait_ready(sl.port)
        r = _curl(
            "--http2-prior-knowledge",
            "-X", "POST", "--data-binary", "hello h2 ingest",
            f"http://127.0.0.1:{sl.port}/add/w",
        )
        assert r.stdout.startswith("HTTP/2 2"), r.stdout[:200]
        r404 = _curl(
            "--http2-prior-knowledge",
            f"http://127.0.0.1:{sl.port}/no-such-endpoint",
        )
        assert r404.stdout.startswith("HTTP/2 404")


@needs_curl
def test_alpn_h2_over_tls(tmp_path):
    if shutil.which("openssl") is None:
        pytest.skip("openssl not available")
    cert, key = tmp_path / "c.pem", tmp_path / "k.pem"
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(cert), "-days", "1",
            "-subj", "/CN=localhost",
        ],
        check=True,
        capture_output=True,
    )
    bus = "mem://h2tls"
    _setup_bus(bus)
    cfg = _config(
        bus, "async",
        **{
            "oryx.serving.api.ssl-cert-file": str(cert),
            "oryx.serving.api.ssl-key-file": str(key),
        },
    )
    with ServingLayer(cfg) as sl:
        # TLS handshake readiness: poll with a plain connect
        import time

        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", sl.port), 2):
                    break
            except OSError:
                time.sleep(0.1)
        # ALPN check straight from the ssl module: the server must offer h2
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        ctx.set_alpn_protocols(["h2", "http/1.1"])
        with socket.create_connection(("127.0.0.1", sl.port), 5) as raw:
            with ctx.wrap_socket(raw, server_hostname="localhost") as tls:
                assert tls.selected_alpn_protocol() == "h2"
        r = _curl(
            "--http2", "-k", f"https://127.0.0.1:{sl.port}/distinct"
        )
        assert r.stdout.startswith("HTTP/2 200"), r.stdout[:200]


def _read_frame(sock_file):
    head = sock_file.read(9)
    assert len(head) == 9
    length = int.from_bytes(head[:3], "big")
    ftype, flags = head[3], head[4]
    sid = int.from_bytes(head[5:9], "big") & 0x7FFFFFFF
    return ftype, flags, sid, sock_file.read(length)


def _frame(ftype, flags, sid, payload=b""):
    return (
        struct.pack(">I", len(payload))[1:]
        + bytes([ftype, flags])
        + struct.pack(">I", sid)
        + payload
    )


def test_multiplexed_streams_raw():
    """Two GETs opened back-to-back before reading any response: both
    must complete on one connection — the h2 layer dispatches each
    stream as its own task on the shared deferred path."""
    from oryx_tpu.serving.hpack import Decoder, encode

    bus = "mem://h2mux"
    _setup_bus(bus)
    with ServingLayer(_config(bus, "async")) as sl:
        _wait_ready(sl.port)
        with socket.create_connection(("127.0.0.1", sl.port), 10) as s:
            s.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n")
            s.sendall(_frame(0x4, 0, 0))  # empty SETTINGS
            f = s.makefile("rb")

            def req(sid, path):
                block = encode(
                    [
                        (b":method", b"GET"),
                        (b":scheme", b"http"),
                        (b":path", path.encode()),
                        (b":authority", b"localhost"),
                    ]
                )
                # END_STREAM | END_HEADERS
                s.sendall(_frame(0x1, 0x1 | 0x4, sid, block))

            req(1, "/distinct")
            req(3, "/ready")

            dec = Decoder()
            got: dict[int, dict] = {}
            bodies: dict[int, bytes] = {}
            ended: set[int] = set()
            while len(ended) < 2:
                ftype, flags, sid, payload = _read_frame(f)
                if ftype == 0x4 and not flags & 0x1:
                    s.sendall(_frame(0x4, 0x1, 0))  # ack server SETTINGS
                elif ftype == 0x1:
                    got[sid] = dict(dec.decode(payload))
                    if flags & 0x1:
                        ended.add(sid)
                elif ftype == 0x0:
                    bodies[sid] = bodies.get(sid, b"") + payload
                    if flags & 0x1:
                        ended.add(sid)
            assert got[1][b":status"] == b"200"
            assert got[3][b":status"] == b"200"
            assert json.loads(bodies[1])["word"] == 2
            # GOAWAY for a clean close
            s.sendall(_frame(0x7, 0, 0, struct.pack(">II", 0, 0)))


def test_rst_stream_cancels_cleanly():
    """A reset stream must not poison the connection: a follow-up request
    on the same connection still completes."""
    from oryx_tpu.serving.hpack import Decoder, encode

    bus = "mem://h2rst"
    _setup_bus(bus)
    with ServingLayer(_config(bus, "async")) as sl:
        _wait_ready(sl.port)
        with socket.create_connection(("127.0.0.1", sl.port), 10) as s:
            s.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n")
            s.sendall(_frame(0x4, 0, 0))
            f = s.makefile("rb")
            block = encode(
                [
                    (b":method", b"GET"),
                    (b":scheme", b"http"),
                    (b":path", b"/distinct"),
                    (b":authority", b"localhost"),
                ]
            )
            s.sendall(_frame(0x1, 0x5, 1, block))
            s.sendall(_frame(0x3, 0, 1, struct.pack(">I", 0x8)))  # RST CANCEL
            s.sendall(_frame(0x1, 0x5, 3, block))
            dec = Decoder()
            status3 = None
            while status3 is None:
                ftype, flags, sid, payload = _read_frame(f)
                if ftype == 0x4 and not flags & 0x1:
                    s.sendall(_frame(0x4, 0x1, 0))
                elif ftype == 0x1:
                    hdrs = dict(dec.decode(payload))
                    if sid == 3:
                        status3 = hdrs[b":status"]
                elif ftype == 0x0 and sid == 1:
                    pass  # stream 1 may have raced its response out
            assert status3 == b"200"


@needs_curl
def test_digest_auth_over_h2():
    """DIGEST auth rides HTTP/2 unchanged: 401 + WWW-Authenticate
    challenge on an anonymous stream, then curl's own digest client
    succeeds over prior-knowledge h2."""
    from oryx_tpu.apps.example.serving import ExampleServingModelManager
    from oryx_tpu.bus.broker import topics
    from oryx_tpu.common.config import load_config

    bus = "mem://h2auth"
    _setup_bus(bus)
    cfg = load_config(
        overlay={
            "oryx.id": "h2auth",
            "oryx.input-topic.broker": bus,
            "oryx.update-topic.broker": bus,
            "oryx.serving.api.port": 0,
            "oryx.serving.api.read-only": True,
            "oryx.serving.api.user-name": "oryx",
            "oryx.serving.api.password": "secret",
            "oryx.serving.application-resources": [
                "oryx_tpu.serving.resources.common",
                "oryx_tpu.serving.resources.example",
            ],
        }
    )
    topics.maybe_create(bus, "OryxUpdate", partitions=1)
    with ServingLayer(cfg, model_manager=ExampleServingModelManager(cfg)) as sl:
        # anonymous: 401 with a Digest challenge, over h2
        r = _curl(
            "--http2-prior-knowledge", f"http://127.0.0.1:{sl.port}/ready"
        )
        assert r.stdout.startswith("HTTP/2 401"), r.stdout[:200]
        assert "www-authenticate: Digest" in r.stdout, r.stdout[:400]
        # Manual digest handshake across two fresh h2 connections (this
        # curl's --digest retry trips its h2 connection-reuse bug; the
        # server's nonces are stateless HMACs, so cross-connection use is
        # exactly what the design supports).
        import re

        from tests.test_auth import _digest_response

        nonce = re.search(r'nonce="([^"]+)"', r.stdout).group(1)
        opaque = re.search(r'opaque="([^"]+)"', r.stdout).group(1)
        hdr = _digest_response("oryx", "secret", "Oryx", "GET", "/ready", nonce)
        r2 = _curl(
            "--http2-prior-knowledge",
            "-H", f"Authorization: {hdr}, opaque=\"{opaque}\"",
            f"http://127.0.0.1:{sl.port}/ready",
        )
        assert r2.stdout.startswith("HTTP/2 200"), r2.stdout[:400]
        # wrong password stays 401 over h2
        bad = _digest_response("oryx", "wrong", "Oryx", "GET", "/ready", nonce)
        r3 = _curl(
            "--http2-prior-knowledge",
            "-H", f"Authorization: {bad}",
            f"http://127.0.0.1:{sl.port}/ready",
        )
        assert r3.stdout.startswith("HTTP/2 401"), r3.stdout[:200]


def test_h2c_malformed_settings_rejected_before_101():
    """RFC 7540 §3.2.1: a malformed HTTP2-Settings header (length not a
    multiple of 6 after base64url decode) is a malformed REQUEST — the
    server must answer 400 over HTTP/1.1 and never send 101 (round-4
    advice: it used to 101 first and then fail the h2 layer with
    FRAME_SIZE_ERROR)."""
    import base64

    bus = "mem://h2badsettings"
    _setup_bus(bus)
    with ServingLayer(_config(bus, "async")) as sl:
        _wait_ready(sl.port)
        bad = base64.urlsafe_b64encode(b"12345").rstrip(b"=")  # 5 % 6 != 0
        with socket.create_connection(("127.0.0.1", sl.port), 10) as s:
            s.settimeout(10)
            s.sendall(
                b"GET /distinct HTTP/1.1\r\nHost: x\r\n"
                b"Connection: Upgrade, HTTP2-Settings\r\n"
                b"Upgrade: h2c\r\nHTTP2-Settings: " + bad + b"\r\n\r\n"
            )
            f = s.makefile("rb")
            status = f.readline()
            assert b"400" in status and b"101" not in status, status
        # not-even-base64 is rejected the same way
        with socket.create_connection(("127.0.0.1", sl.port), 10) as s:
            s.settimeout(10)
            s.sendall(
                b"GET /distinct HTTP/1.1\r\nHost: x\r\n"
                b"Connection: Upgrade, HTTP2-Settings\r\n"
                b"Upgrade: h2c\r\nHTTP2-Settings: !!!not-b64!!!\r\n\r\n"
            )
            status = s.makefile("rb").readline()
            assert b"400" in status, status


def test_decode_h2c_settings_strict_base64url():
    """decode_h2c_settings must reject anything outside the base64url
    alphabet (ADVICE.md round 5): urlsafe_b64decode silently DISCARDED
    invalid characters, so garbage whose surviving length happened to be
    a multiple of 6 bytes decoded to nonsense and was accepted — and
    standard-alphabet '+'/'/' input is valid base64 but not the base64url
    encoding RFC 7540 §3.2.1 requires."""
    import base64
    import struct

    from oryx_tpu.serving.http2 import decode_h2c_settings

    one_setting = struct.pack(">HI", 0x4, 65535)
    good = base64.urlsafe_b64encode(one_setting).decode().rstrip("=")
    assert decode_h2c_settings(good) == one_setting
    assert decode_h2c_settings("") == b""  # empty SETTINGS is legal

    # invalid characters interleaved with an otherwise-valid payload:
    # the old decoder dropped them and accepted the remainder
    assert decode_h2c_settings("!" + good) is None
    assert decode_h2c_settings(good[:4] + "\n" + good[4:]) is None
    # standard-alphabet base64 of the same bytes (only when it actually
    # differs from base64url): must be rejected as non-base64url
    payload = struct.pack(">HI", 0x4, 0x3EFBFBFF)  # encodes with '+/'
    std = base64.b64encode(payload).decode().rstrip("=")
    assert ("+" in std) or ("/" in std)
    assert decode_h2c_settings(std) is None
    # misplaced padding
    assert decode_h2c_settings("AA=A") is None


def test_h2c_upgrade_applies_http2_settings_header():
    """RFC 7540 §3.2.1: the HTTP2-Settings upgrade header IS the client's
    initial SETTINGS. A client advertising INITIAL_WINDOW_SIZE=8 must not
    be overrun by the stream-1 response: the server may send at most 8
    DATA bytes until the client grants more window (round-3 advice)."""
    import base64

    bus = "mem://h2upsettings"
    _setup_bus(bus)
    with ServingLayer(_config(bus, "async")) as sl:
        _wait_ready(sl.port)
        settings_payload = struct.pack(">HI", 0x4, 8)  # INITIAL_WINDOW_SIZE=8
        h2s = base64.urlsafe_b64encode(settings_payload).rstrip(b"=")
        with socket.create_connection(("127.0.0.1", sl.port), 10) as s:
            s.settimeout(10)
            s.sendall(
                b"GET /distinct HTTP/1.1\r\nHost: x\r\n"
                b"Connection: Upgrade, HTTP2-Settings\r\n"
                b"Upgrade: h2c\r\nHTTP2-Settings: " + h2s + b"\r\n\r\n"
            )
            f = s.makefile("rb")
            status = f.readline()
            assert b"101" in status, status
            while f.readline() not in (b"\r\n", b"\n", b""):
                pass
            # client connection preface after the 101 (RFC 7540 §3.2)
            s.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n")
            s.sendall(_frame(0x4, 0, 0))  # empty SETTINGS frame
            from oryx_tpu.serving.hpack import Decoder

            dec = Decoder()
            body = b""
            ended = False
            granted = False
            status_hdrs = None
            while not ended:
                ftype, flags, sid, payload = _read_frame(f)
                if ftype == 0x4 and not flags & 0x1:
                    s.sendall(_frame(0x4, 0x1, 0))  # ack server SETTINGS
                elif ftype == 0x1 and sid == 1:
                    status_hdrs = dict(dec.decode(payload))
                elif ftype == 0x0 and sid == 1:
                    body += payload
                    if flags & 0x1:
                        ended = True
                    elif not granted:
                        # the pre-grant DATA must respect the 8-byte
                        # window from the upgrade header
                        assert len(body) <= 8, (
                            f"server overran the advertised window: "
                            f"{len(body)} bytes before any WINDOW_UPDATE"
                        )
                        if len(body) == 8:
                            granted = True
                            s.sendall(
                                _frame(0x8, 0, 1, struct.pack(">I", 4096))
                            )
            assert status_hdrs is not None and status_hdrs[b":status"] == b"200"
            assert len(body) > 8, body  # response really was bigger
            assert json.loads(body)["word"] == 2
            s.sendall(_frame(0x7, 0, 0, struct.pack(">II", 0, 0)))


def test_continuation_stall_times_out(monkeypatch):
    """A client that sends HEADERS without END_HEADERS then stalls must
    be disconnected after the idle read deadline, not pin the connection
    forever (round-3 advice)."""
    import time as _time

    from oryx_tpu.serving import http2 as h2mod

    monkeypatch.setattr(h2mod, "IDLE_READ_TIMEOUT", 1.0)
    from oryx_tpu.serving.hpack import encode

    bus = "mem://h2stall"
    _setup_bus(bus)
    with ServingLayer(_config(bus, "async")) as sl:
        _wait_ready(sl.port)
        with socket.create_connection(("127.0.0.1", sl.port), 10) as s:
            s.settimeout(10)
            s.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n")
            s.sendall(_frame(0x4, 0, 0))
            block = encode(
                [
                    (b":method", b"GET"),
                    (b":scheme", b"http"),
                    (b":path", b"/ready"),
                    (b":authority", b"x"),
                ]
            )
            # HEADERS with END_STREAM but WITHOUT END_HEADERS: the server
            # now waits for CONTINUATION frames that never come
            s.sendall(_frame(0x1, 0x1, 1, block))
            t0 = _time.time()
            f = s.makefile("rb")
            # drain whatever the server sends; EOF must arrive well within
            # the (patched) deadline + slack, not hang past 10s
            while True:
                head = f.read(9)
                if len(head) < 9:
                    break
                length = int.from_bytes(head[:3], "big")
                f.read(length)
            assert _time.time() - t0 < 8.0
