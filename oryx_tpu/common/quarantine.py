"""Poison-record quarantine: a replayable dead-letter store.

A record that deterministically breaks its consumer must not be allowed
to rewind-loop a tier forever (the failure mode PR 4's
``oryx_speed_failures_total`` made visible, and what tf.data's input
hardening solves for malformed records, arxiv 2101.12127). Once bounded
retries are exhausted, the layer diverts the offending records HERE —
append-only JSONL files under ``oryx.monitoring.quarantine.dir`` — and
moves the stream forward. Nothing is lost: every diverted record carries
its key, message, reason, and timestamp, and ``load_quarantined`` /
``tools/chaos.py replay-quarantine`` turn a dead-letter file back into
records that can be re-ingested (e.g. POSTed to /ingest) after the bug
that poisoned them is fixed.

Layout: ``<dir>/<layer>/dl-<epoch_ms>-<pid>.jsonl`` — one file per divert
so concurrent layers never interleave, written tmp-then-rename so a crash
mid-divert can never leave a half-readable dead letter.
"""

from __future__ import annotations

import json
import logging
import os
import time
from pathlib import Path
from typing import Iterable, Sequence

from oryx_tpu.bus.api import KeyMessage
from oryx_tpu.common.ioutil import mkdirs, strip_scheme

log = logging.getLogger(__name__)

_m_quarantined = None


def _metric():
    global _m_quarantined
    if _m_quarantined is None:
        from oryx_tpu.common.metrics import get_registry

        _m_quarantined = get_registry().counter(
            "oryx_quarantined_records_total",
            "Records diverted to the dead-letter store by layer; replay "
            "them from oryx.monitoring.quarantine.dir once the poison "
            "cause is fixed",
            labeled=True,
        )
    return _m_quarantined


def ensure_metrics() -> None:
    """Register oryx_quarantined_records_total now (empty) so scrapes see
    the family from process start — a dead-letter alert needs the zero
    baseline, not a series that appears only after the first poison."""
    _metric()


class Quarantine:
    """Dead-letter writer for one layer ('speed', 'batch', ...)."""

    def __init__(self, root: str, layer: str):
        self.root = Path(strip_scheme(root))
        self.layer = layer
        self._seq = 0
        _metric()  # scrape-visible from layer construction, not first divert

    def divert(
        self, records: Sequence[KeyMessage], reason: str
    ) -> Path | None:
        """Persist the poison records and count them; returns the
        dead-letter path (None for an empty divert). Raises only on an
        unwritable quarantine dir — the caller decides whether losing the
        dead letter is worse than stalling (layers treat it as fatal for
        the window and keep rewinding: quarantine must never silently
        drop data)."""
        if not records:
            return None
        d = mkdirs(self.root / self.layer)
        now_ms = int(time.time() * 1000)
        self._seq += 1
        path = d / f"dl-{now_ms}-{os.getpid()}-{self._seq}.jsonl"
        tmp = d / (path.name + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            for km in records:
                f.write(json.dumps({
                    "key": km.key,
                    "message": km.message,
                    "reason": reason,
                    "quarantined_ms": now_ms,
                }, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _metric().inc(len(records), layer=self.layer)
        log.error(
            "quarantined %d record(s) to %s (%s) — replayable via "
            "tools/chaos.py replay-quarantine", len(records), path, reason,
        )
        return path


def quarantine_files(root: str, layer: str | None = None) -> list[Path]:
    """Dead-letter files under the quarantine root, oldest first."""
    base = Path(strip_scheme(root))
    if layer is not None:
        base = base / layer
    if not base.is_dir():
        return []
    return sorted(p for p in base.rglob("dl-*.jsonl") if p.is_file())


def load_quarantined(path: str | Path) -> list[KeyMessage]:
    """One dead-letter file back into records (replay input)."""
    out: list[KeyMessage] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            out.append(KeyMessage(d.get("key"), d["message"]))
    return out


def iter_quarantined(root: str, layer: str | None = None) -> Iterable[KeyMessage]:
    for path in quarantine_files(root, layer):
        yield from load_quarantined(path)
