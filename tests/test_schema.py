"""InputSchema / CategoricalValueEncodings tests (reference:
InputSchemaTest, CategoricalValueEncodingsTest patterns)."""

import numpy as np
import pytest

from oryx_tpu.apps.schema import (
    CategoricalValueEncodings,
    InputSchema,
    encode_matrix,
)
from oryx_tpu.common.config import load_config


def _schema(**overlay):
    base = {
        "oryx.input-schema.feature-names": ["id", "a", "b", "c", "label"],
        "oryx.input-schema.id-features": ["id"],
        "oryx.input-schema.ignored-features": ["c"],
        "oryx.input-schema.categorical-features": ["b", "label"],
        "oryx.input-schema.target-feature": "label",
    }
    base.update(overlay)
    return InputSchema(load_config(overlay=base))


def test_roles_and_predictor_maps():
    s = _schema()
    assert s.num_features == 5
    assert s.num_predictors == 2  # a, b (id/c/label excluded)
    assert s.is_id("id") and not s.is_active("id")
    assert s.is_numeric("a") and s.is_categorical("b")
    assert s.is_target("label") and s.is_classification()
    assert s.feature_to_predictor_index(1) == 0
    assert s.feature_to_predictor_index(2) == 1
    assert s.predictor_to_feature_index(1) == 2
    with pytest.raises(KeyError):
        s.feature_to_predictor_index(0)  # id is not a predictor


def test_generated_names_and_numeric_complement():
    s = InputSchema(load_config(overlay={
        "oryx.input-schema.num-features": 3,
        "oryx.input-schema.numeric-features": ["0", "2"],
    }))
    assert s.feature_names == ["0", "1", "2"]
    assert s.is_categorical("1")  # complement of numeric
    assert s.num_predictors == 3
    assert not s.has_target()


def test_schema_validation_errors():
    with pytest.raises(ValueError):
        InputSchema(load_config(overlay={
            "oryx.input-schema.feature-names": ["a", "a"],
            "oryx.input-schema.numeric-features": ["a"],
        }))
    with pytest.raises(ValueError):
        _schema(**{"oryx.input-schema.target-feature": "id"})  # not active


def test_encodings_deterministic_and_roundtrip():
    enc = CategoricalValueEncodings({2: ["z", "y", "z", "x"]})
    assert enc.get_values(2) == ["x", "y", "z"]  # sorted, deduped
    assert enc.encode(2, "y") == 1
    assert enc.decode(2, 0) == "x"
    assert enc.get_value_count(2) == 3
    rt = CategoricalValueEncodings.from_content(enc.to_content())
    assert rt.get_encoding_map(2) == enc.get_encoding_map(2)


def test_encode_matrix():
    s = _schema()
    rows = [
        ["u1", "1.5", "red", "junk", "yes"],
        ["u2", "", "blue", "junk", "no"],
        ["u3", "2.5", "green", "junk", ""],
    ]
    enc = CategoricalValueEncodings.from_data(s, rows)
    x, t = encode_matrix(s, enc, rows)
    assert x.shape == (3, 2)
    assert x[0, 0] == 1.5 and np.isnan(x[1, 0])
    # categorical codes: blue=0, green=1, red=2
    assert x[0, 1] == 2.0 and x[1, 1] == 0.0 and x[2, 1] == 1.0
    # target: no=0, yes=1; missing -> NaN
    assert t[0] == 1.0 and t[1] == 0.0 and np.isnan(t[2])
