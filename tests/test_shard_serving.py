"""Sharded serving views (PR 11): owning-shard delta sync, per-shard
sync-byte accounting, and sharded-vs-unsharded answer identity through
the real ALS/seq serving models."""

from __future__ import annotations

import time

import numpy as np

import jax.numpy as jnp

from oryx_tpu.apps.als.serving import ALSServingModel, SyncConfig
from oryx_tpu.apps.als.state import ALSState
from oryx_tpu.common.metrics import get_registry
from oryx_tpu.ops.transfer import ShardedMatrix, scatter_transfer_bytes


def _als_model(n=64, k=8, seed=2, **kw):
    rng = np.random.default_rng(seed)
    st = ALSState(k, implicit=True)
    st.y.bulk_set(
        [f"i{j}" for j in range(n)],
        rng.standard_normal((n, k)).astype(np.float32),
    )
    st.x.bulk_set(["u0"], rng.standard_normal((1, k)).astype(np.float32))
    st.set_expected(["u0"], [f"i{j}" for j in range(n)])
    return st, ALSServingModel(st, **kw)


def _wait_synced(model, timeout=10.0):
    q = np.ones(model.state.features, dtype=np.float32)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if (model.served_version() or -1) >= model.state.y.get_version():
            return True
        model.top_n(q, 3)
        time.sleep(0.01)
    return False


def _shard_bytes(reg, n_shards):
    c = reg.counter("oryx_device_sync_bytes")
    return {s: c.value(shard=f"s{s}") for s in range(n_shards)}


def test_sharded_view_full_build_splits_bytes_evenly():
    reg = get_registry()
    before = _shard_bytes(reg, 2)
    st, model = _als_model(sync=SyncConfig(shard_count=2))
    try:
        q = np.ones(8, dtype=np.float32)
        model.top_n(q, 5)
        y_dev = model._device_view[0]
        assert isinstance(y_dev, ShardedMatrix)
        assert y_dev.n_shards == 2
        after = _shard_bytes(reg, 2)
        moved = {s: after[s] - before[s] for s in after}
        cap = int(model._device_view[3].shape[0])
        full = cap * 8 * 2  # bf16 capacity matrix
        # the full build lands ~1/S of the matrix on each shard
        assert moved[0] + moved[1] == full
        assert abs(moved[0] - moved[1]) <= full / 4
        # per-shard valid-row ownership is published
        g = reg.gauge("oryx_shard_rows")
        assert g.value(shard="s0") + g.value(shard="s1") == 64
    finally:
        model.close()


def test_sharded_delta_moves_only_owning_shard_fraction():
    """The PR 3 storm assertion one level up: a dirty-row delta touching
    ONE shard moves that shard's bucket-padded scatter only — about 1/S
    of what the same delta would cost as a full-matrix sync, and nothing
    at all on the other shard."""
    reg = get_registry()
    # a real-sized store: the minimum 64-row scatter bucket must be small
    # next to each shard's slice for the 1/S claim to be observable
    st, model = _als_model(n=1000, sync=SyncConfig(shard_count=2))
    try:
        q = np.ones(8, dtype=np.float32)
        model.top_n(q, 5)
        cap = int(model._device_view[3].shape[0])
        plan = model._device_view[0].plan
        before = _shard_bytes(reg, 2)
        # dirty exactly one row owned by shard 0 (global row 0)
        st.y.set("i0", (q * 50).astype(np.float32))
        assert _wait_synced(model)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and model.last_resync["kind"] != "delta":
            time.sleep(0.01)
        lr = model.last_resync
        assert lr["kind"] == "delta"
        after = _shard_bytes(reg, 2)
        moved = {s: after[s] - before[s] for s in after}
        one_bucket = scatter_transfer_bytes(1, 2, 8)
        assert moved[0] == one_bucket
        assert moved[1] == 0.0  # the other shard's device saw NOTHING
        assert lr["shard_bytes"] == {0: one_bucket}
        # the update is served, from the shard that owns it
        assert model.top_n(q, 5)[0][0] == "i0"
        # untouched shard buffer was shared, not re-uploaded
        full_matrix = cap * 8 * 2
        assert moved[0] < full_matrix / 2
        assert plan.owner(0) == 0
    finally:
        model.close()


def test_sharded_answers_identical_to_unsharded():
    st1, unsharded = _als_model(n=100, seed=5)
    st2, sharded = _als_model(n=100, seed=5, sync=SyncConfig(shard_count=4))
    try:
        rng = np.random.default_rng(9)
        for _ in range(5):
            q = rng.standard_normal(8).astype(np.float32)
            a = unsharded.top_n(q, 10)
            b = sharded.top_n(q, 10)
            assert [p[0] for p in a] == [p[0] for p in b]
            np.testing.assert_allclose(
                [p[1] for p in a], [p[1] for p in b], rtol=1e-6
            )
            # cosine rides the sharded unit view
            a = unsharded.top_n(q, 10, cosine=True)
            b = sharded.top_n(q, 10, cosine=True)
            assert [p[0] for p in a] == [p[0] for p in b]
    finally:
        unsharded.close()
        sharded.close()


def test_sharded_quantized_delta_requantizes_shard_locally():
    st, model = _als_model(
        n=40, sync=SyncConfig(shard_count=2), score_mode="quantized"
    )
    try:
        q = np.ones(8, dtype=np.float32)
        model.top_n(q, 5)
        model.top_n(q, 5, cosine=True)  # materialize the unit view
        y_dev = model._device_view[0]
        assert isinstance(y_dev, ShardedMatrix)
        from oryx_tpu.ops.transfer import QuantizedMatrix

        assert all(isinstance(s, QuantizedMatrix) for s in y_dev.shards)
        shard1_q_before = np.asarray(y_dev.shards[1].q)
        # dirty one row in shard 0
        st.y.set("i1", (q * 30).astype(np.float32))
        assert _wait_synced(model)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and model.last_resync["kind"] != "delta":
            time.sleep(0.01)
        assert model.last_resync["kind"] == "delta"
        y_new = model._device_view[0]
        # shard 1 untouched: SAME object, identical int8 bits
        assert y_new.shards[1] is y_dev.shards[1]
        np.testing.assert_array_equal(
            np.asarray(y_new.shards[1].q), shard1_q_before
        )
        # unit view keeps sharing the device view's int8 rows per shard
        uv = model._unit_view
        assert uv is not None and uv[2] == model._device_view[2]
        assert uv[0].shards[0].q is y_new.shards[0].q
        assert model.top_n(q, 5)[0][0] == "i1"
    finally:
        model.close()


def test_als_update_shard_mesh_reachable_through_config(tmp_path):
    """Review regression (PR 11): oryx.batch.train.shards must actually
    reach the trainer — on a multi-device host mesh_from_config
    auto-builds a data-parallel mesh, and the original guard made the
    knob a silent no-op exactly there. The shards knob replaces the auto
    mesh; an explicit tensor-parallel mesh and an active candidate
    sub-mesh still win."""
    import jax

    from oryx_tpu.apps.als.batch import ALSUpdate
    from oryx_tpu.common.config import load_config
    from oryx_tpu.parallel.mesh import MODEL_AXIS, MeshSpec, make_mesh
    from oryx_tpu.parallel.submesh import candidate_mesh

    cfg = load_config(overlay={
        "oryx.id": "shardwire",
        "oryx.batch.storage.model-dir": str(tmp_path / "m"),
        "oryx.batch.train.shards": 2,
        "oryx.als.hyperparams.features": 4,
    })
    upd = ALSUpdate(cfg)
    sm = upd._shard_mesh()
    assert sm is not None and sm.shape[MODEL_AXIS] == 2
    # an explicit tensor-parallel training mesh wins over the knob
    tp_mesh = make_mesh(MeshSpec(data=4, model=2), jax.devices()[:8])
    upd_tp = ALSUpdate(cfg, mesh=tp_mesh)
    assert upd_tp._shard_mesh() is None
    # a partitioned candidate search's sub-mesh wins too
    with candidate_mesh(tp_mesh):
        assert upd._shard_mesh() is None
    # shards <= 1: never a mesh
    cfg1 = load_config(overlay={
        "oryx.id": "shardwire1",
        "oryx.batch.storage.model-dir": str(tmp_path / "m1"),
        "oryx.als.hyperparams.features": 4,
    })
    assert ALSUpdate(cfg1)._shard_mesh() is None


def test_seq_sharded_view_builds_and_deltas():
    from oryx_tpu.apps.seq.serving import SeqServingModel
    from oryx_tpu.apps.seq.state import SeqState

    rng = np.random.default_rng(3)
    n, d = 50, 8
    st = SeqState(dim=d, window=8)
    st.params = {
        "Wx": rng.standard_normal((d, 3 * d)).astype(np.float32) * 0.1,
        "Wh": rng.standard_normal((d, 3 * d)).astype(np.float32) * 0.1,
        "b": np.zeros(3 * d, dtype=np.float32),
    }
    st.items.bulk_set(
        [f"i{j}" for j in range(n)],
        rng.standard_normal((n, d)).astype(np.float32),
    )
    model = SeqServingModel(st, sync=SyncConfig(shard_count=2))
    out = model.next_items(["i1", "i2"], 5)
    assert out and len(out) == 5
    assert isinstance(model._device_view[0], ShardedMatrix)
    # growth + update route through the owning shard
    st.items.set("i3", rng.standard_normal(d).astype(np.float32))
    out2 = model.next_items(["i1", "i2"], 5)
    assert out2 and len(out2) == 5
