"""Sequence-parallel attention over the device mesh: ring and all-to-all.

The reference has no sequence models (SURVEY.md §5 "long-context ...
absent"), but long-context support is a first-class capability of this
framework. Two schedules, both sharding the sequence over the mesh "data"
axis:

- ``ring_attention``: each device keeps its Q shard resident and streams
  K/V shards around the ring with lax.ppermute (neighbor exchanges over
  ICI, never a full all-gather), folding blocks in with online-softmax
  (flash-attention) rescaling — the full [S, S] score matrix never
  exists and K/V memory per chip stays S/n. Best when S is the scarce
  resource and head count is small.
- ``ulysses_attention`` (DeepSpeed-Ulysses style): one all-to-all swaps
  the sharded axis from sequence to heads (each device then holds H/n
  full-sequence heads), attention runs locally and exactly, and a second
  all-to-all swaps back. Two collectives total instead of n ring steps —
  cheaper when H >= n and per-head attention fits on a chip.

Single-device ``attention`` is the exact reference implementation both
are tested against; all support causal masking (the ring variant masks by
global chunk position via where-masking so every device still executes
the same program).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from oryx_tpu.parallel.mesh import DATA_AXIS, shard_map_compat

_NEG_INF = -1e30


def attention(q, k, v, *, causal: bool = False):
    """Exact softmax attention. q,k,v: [..., S, D] -> [..., S, D]."""
    d = q.shape[-1]
    s = jnp.einsum("...qd,...kd->...qk", q, k) / jnp.sqrt(jnp.asarray(d, q.dtype))
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p, v.astype(jnp.float32)).astype(q.dtype)


def _block_fold(q, k, v, m_prev, l_prev, o_prev, bias):
    """Fold one K/V block into the running online-softmax state.
    q: [Sq, D], k/v: [Sk, D]; m/l: [Sq], o: [Sq, D]; bias: [Sq, Sk]."""
    d = q.shape[-1]
    s = (q @ k.T).astype(jnp.float32) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = s + bias
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    scale = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[:, None])
    l_new = l_prev * scale + jnp.sum(p, axis=-1)
    o_new = o_prev * scale[:, None] + p @ v.astype(jnp.float32)
    return m_new, l_new, o_new


def _ring_attention_local(q, k, v, *, causal: bool, axis_name: str, n_shards: int):
    """Per-device body under shard_map. q,k,v: local [Sq, D] shards."""
    my = jax.lax.axis_index(axis_name)
    sq = q.shape[0]

    def step(i, carry):
        m, l, o, k_cur, v_cur = carry
        src = (my - i) % n_shards  # which global chunk this K/V block is
        if causal:
            # global causal mask between my Q chunk and the src K chunk:
            # src > my -> fully masked; src == my -> triangular; else open
            tri = jnp.tril(jnp.ones((sq, k_cur.shape[0]), dtype=bool))
            open_ = jnp.ones((sq, k_cur.shape[0]), dtype=bool)
            mask = jnp.where(src == my, tri, jnp.where(src < my, open_, ~open_))
            bias = jnp.where(mask, 0.0, _NEG_INF).astype(jnp.float32)
        else:
            bias = jnp.zeros((sq, k_cur.shape[0]), dtype=jnp.float32)
        m, l, o = _block_fold(q, k_cur, v_cur, m, l, o, bias)
        perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m, l, o, k_nxt, v_nxt

    m0 = jnp.full((sq,), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((sq,), dtype=jnp.float32)
    o0 = jnp.zeros((sq, q.shape[1]), dtype=jnp.float32)
    m, l, o, _, _ = jax.lax.fori_loop(0, n_shards, step, (m0, l0, o0, k, v))
    return (o / jnp.maximum(l, 1e-30)[:, None]).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, causal: bool = False):
    """Sequence-parallel attention: [..., S, D] arrays with S sharded over
    the mesh data axis. Leading dims (batch, heads) are vmapped on every
    device. Returns [..., S, D] with the same sharding as q."""
    n_shards = mesh.shape[DATA_AXIS]
    if q.shape[-2] % n_shards or k.shape[-2] % n_shards:
        raise ValueError(
            f"sequence length {q.shape[-2]} must be divisible by the {n_shards}-way "
            f"'{DATA_AXIS}' axis"
        )
    spec = P(*([None] * (q.ndim - 2)), DATA_AXIS, None)
    body = partial(
        _ring_attention_local, causal=causal, axis_name=DATA_AXIS, n_shards=n_shards
    )
    for _ in range(q.ndim - 2):
        body = jax.vmap(body)
    fn = jax.jit(
        shard_map_compat(
            body,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
    )
    sharding = NamedSharding(mesh, spec)
    return fn(
        jax.device_put(q, sharding),
        jax.device_put(k, sharding),
        jax.device_put(v, sharding),
    )


def _ulysses_local(q, k, v, *, causal: bool, axis_name: str):
    """Per-device body under shard_map. q,k,v: [..., H, Sq_local, D]."""
    h_ax, s_ax = q.ndim - 3, q.ndim - 2
    # sequence-sharded -> head-sharded: [..., H, S/n, D] -> [..., H/n, S, D]
    swap = lambda x: jax.lax.all_to_all(
        x, axis_name, split_axis=h_ax, concat_axis=s_ax, tiled=True
    )
    o = attention(swap(q), swap(k), swap(v), causal=causal)
    # head-sharded -> sequence-sharded
    return jax.lax.all_to_all(
        o, axis_name, split_axis=s_ax, concat_axis=h_ax, tiled=True
    )


def ulysses_attention(q, k, v, mesh: Mesh, *, causal: bool = False):
    """All-to-all sequence-parallel attention: [..., H, S, D] arrays with S
    sharded over the mesh data axis and H divisible by the axis size. Two
    all-to-alls re-shard sequence->heads and back; attention itself runs
    locally and EXACTLY per head. Returns [..., H, S, D] sharded like q."""
    n_shards = mesh.shape[DATA_AXIS]
    if q.ndim < 3:
        raise ValueError("ulysses_attention needs [..., H, S, D] inputs")
    # validate q AND k (cross-attention may use a different S_k; GQA-style
    # mismatched head counts are not supported by the all-to-all re-shard)
    if k.shape[-3] != q.shape[-3]:
        raise ValueError(
            f"k head count {k.shape[-3]} must equal q's {q.shape[-3]}"
        )
    for name, arr in (("q", q), ("k", k)):
        h, s = arr.shape[-3], arr.shape[-2]
        if h % n_shards:
            raise ValueError(
                f"{name} head count {h} must be divisible by the {n_shards}-way "
                f"'{DATA_AXIS}' axis (use ring_attention when heads are scarce)"
            )
        if s % n_shards:
            raise ValueError(
                f"{name} sequence length {s} must be divisible by the {n_shards}-way "
                f"'{DATA_AXIS}' axis"
            )
    spec = P(*([None] * (q.ndim - 2)), DATA_AXIS, None)
    fn = jax.jit(
        shard_map_compat(
            partial(_ulysses_local, causal=causal, axis_name=DATA_AXIS),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
    )
    sharding = NamedSharding(mesh, spec)
    return fn(
        jax.device_put(q, sharding),
        jax.device_put(k, sharding),
        jax.device_put(v, sharding),
    )
