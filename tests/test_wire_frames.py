"""Kafka wire-format robustness: truncated/partial record batches.

The satellite contract (ISSUE 5): a frame cut mid-batch must fail that
ONE consume with a clear, offset-bearing error — never desync the stream
by guessing at record boundaries, and never be confused with the
legitimately-tolerated *trailing partial* batch a broker returns at the
end of a fetch response.
"""

import pytest

from oryx_tpu.bus.kafkawire import (
    Reader,
    WireDecodeError,
    decode_record_batches,
    encode_record_batch,
)


def _batch(n=3, base_ts=1000):
    return encode_record_batch(
        [(f"k{i}".encode(), f"value-{i}".encode()) for i in range(n)], base_ts
    )


def test_roundtrip_baseline():
    out = decode_record_batches(_batch())
    assert [(o, k) for o, k, _ in out] == [(0, b"k0"), (1, b"k1"), (2, b"k2")]


def test_trailing_partial_batch_is_tolerated():
    """A batch cut by the fetch-size boundary (outer length promises more
    bytes than remain) is silently dropped: the next fetch re-reads from
    the same offset, so nothing is lost and nothing errors."""
    good, partial = _batch(2), _batch(3)
    data = good + partial[: len(partial) // 2]
    out = decode_record_batches(data)
    assert len(out) == 2  # the complete batch only


def test_mid_frame_cut_inside_complete_batch_raises_clear_error():
    """The regression: a batch whose length prefix is intact but whose
    record bytes were cut (tail zero-filled by a torn write) must raise
    WireDecodeError with offset context, not a bare EOFError or silent
    garbage records."""
    raw = bytearray(_batch(3))
    # zero the last third of the records section; outer framing intact
    cut = len(raw) - len(raw) // 3
    for i in range(cut, len(raw)):
        raw[i] = 0
    with pytest.raises(WireDecodeError, match="base offset 0"):
        decode_record_batches(bytes(raw))


def test_corrupt_batch_after_good_batch_names_its_offset():
    good = _batch(2, base_ts=1)
    bad = bytearray(_batch(2, base_ts=2))
    # second batch starts at absolute offset 0 too (encode_record_batch
    # writes baseOffset 0); corrupt ITS records region
    for i in range(len(bad) - 8, len(bad)):
        bad[i] = 0xFF
    with pytest.raises(WireDecodeError):
        decode_record_batches(good + bytes(bad))


def test_record_length_beyond_payload_rejected():
    raw = bytearray(_batch(1))
    # inflate the record-count field so the decoder expects a second
    # record that does not exist
    # layout: baseOffset(8) len(4) leaderEpoch(4) magic(1) crc(4)
    #         attrs(2) lastOffsetDelta(4) ts(8+8) pid(8) epoch(2) seq(4)
    #         recordCount(4)
    count_at = 8 + 4 + 4 + 1 + 4 + 2 + 4 + 16 + 8 + 2 + 4
    raw[count_at:count_at + 4] = (99).to_bytes(4, "big")
    with pytest.raises(WireDecodeError):
        decode_record_batches(bytes(raw))


def test_corrupt_gzip_payload_maps_to_wire_decode_error():
    """Regression (review): a claimed-complete batch whose COMPRESSED
    payload is corrupt must raise WireDecodeError like any other corrupt
    frame — gzip.BadGzipFile is an OSError, and letting it escape would
    make the consume retry replay deterministically-bad bytes."""
    raw = bytearray(_batch(2))
    # set attributes codec bits to gzip(1); the payload is NOT gzip
    attrs_at = 8 + 4 + 4 + 1 + 4  # baseOffset len leaderEpoch magic crc
    raw[attrs_at:attrs_at + 2] = (1).to_bytes(2, "big")
    with pytest.raises(WireDecodeError, match="base offset 0"):
        decode_record_batches(bytes(raw))


def test_truncated_gzip_stream_maps_to_wire_decode_error():
    import gzip

    payload = gzip.compress(b"x" * 256)[: 40]  # truncated mid-stream
    raw = bytearray(_batch(1))
    attrs_at = 8 + 4 + 4 + 1 + 4
    raw[attrs_at:attrs_at + 2] = (1).to_bytes(2, "big")
    # splice the truncated gzip bytes in as the records payload
    head = bytes(raw[: attrs_at + 2 + 4 + 16 + 8 + 2 + 4 + 4])
    body = head[12:] + payload  # after baseOffset+len framing
    framed = raw[:8] + len(body).to_bytes(4, "big") + body
    with pytest.raises(WireDecodeError):
        decode_record_batches(bytes(framed))


def test_unbounded_varint_rejected():
    r = Reader(b"\xff" * 16)
    with pytest.raises(WireDecodeError, match="varint"):
        r.varint()


def test_consume_fails_once_then_stream_recovers():
    """Layer-level contract: a broker read that hits a corrupt frame
    fails THAT consume with the decode error (no retry — deterministic),
    and the next read against healthy bytes proceeds normally."""
    from oryx_tpu.bus.api import ConsumeDataIterator

    class FlakyBroker:
        def __init__(self):
            self.reads = 0

        def num_partitions(self, topic):
            return 1

        def end_offsets(self, topic):
            return [0]

        def get_offsets(self, group, topic):
            return {}

        def commit_offsets(self, group, topic, offsets):
            pass

        def read(self, topic, p, off, n):
            self.reads += 1
            if self.reads == 1:
                raise WireDecodeError("corrupt record batch at base offset 5")
            return [(off, None, "fine")] if off == 0 else []

    broker = FlakyBroker()
    it = ConsumeDataIterator(broker, "t", start="earliest")
    with pytest.raises(WireDecodeError):
        it.poll_available()
    got = it.poll_available()
    assert [km.message for km in got] == ["fine"]
    it.close()
