from oryx_tpu.apps.kmeans.batch import KMeansUpdate
from oryx_tpu.apps.kmeans.speed import KMeansSpeedModelManager
from oryx_tpu.apps.kmeans.serving import KMeansServingModel, KMeansServingModelManager

__all__ = [
    "KMeansUpdate",
    "KMeansSpeedModelManager",
    "KMeansServingModel",
    "KMeansServingModelManager",
]
