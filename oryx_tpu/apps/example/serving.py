"""Wordcount serving tier.

Mirrors ExampleServingModel(Manager) (app/example .../serving/): MODEL
replaces the word map wholesale; UP "word,count" sets one entry; the
model serves reads for the /distinct endpoints.
"""

from __future__ import annotations

import json
import threading

from oryx_tpu.api import AbstractServingModelManager, ServingModel
from oryx_tpu.common.config import Config


class ExampleServingModel(ServingModel):
    def __init__(self):
        self._words: dict[str, int] = {}
        self._lock = threading.Lock()

    def fraction_loaded(self) -> float:
        return 1.0

    def get_words(self) -> dict[str, int]:
        with self._lock:
            return dict(self._words)

    def get_count(self, word: str) -> int | None:
        with self._lock:
            return self._words.get(word)

    def replace(self, words: dict[str, int]) -> None:
        with self._lock:
            self._words.clear()
            self._words.update(words)

    def set_count(self, word: str, count: int) -> None:
        with self._lock:
            self._words[word] = count


class ExampleServingModelManager(AbstractServingModelManager):
    def __init__(self, config: Config):
        super().__init__(config)
        self.model = ExampleServingModel()

    def get_model(self) -> ExampleServingModel:
        return self.model

    def consume_key_message(self, key: str | None, message: str) -> None:
        if key == "MODEL":
            self.model.replace(json.loads(message))
        elif key == "UP":
            # rsplit: the word itself may contain commas
            word, count = message.rsplit(",", 1)
            self.model.set_count(word, int(count))
        else:
            raise ValueError(f"bad key: {key}")
