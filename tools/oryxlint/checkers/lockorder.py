"""Lock-ordering checker (rule ``lock-order``).

The multi-lock code paths (batcher dispatch vs. its shared class lock,
supervisor op-lock around per-replica process state, front router lock
vs. prober state) enforce their acquisition order only by convention —
the deadlock shape is two functions taking the same two locks in
opposite orders, which no single-function review can see.

The checker builds the global lock-acquisition graph:

- lock identities: instance attributes assigned ``threading.Lock()`` /
  ``RLock()`` (named ``Class._attr``), module-level lock globals
  (``module._NAME``), and ``threading.Condition`` aliases normalized to
  their underlying lock;
- edges: inside a ``with <lock>:`` block, every further lock acquired —
  lexically nested ``with``, or transitively inside a confidently
  resolved callee (callgraph resolution, bounded depth) — adds
  ``held -> acquired``. ``oryxlint: holds=<lock>`` contracts seed the
  held set for functions whose callers lock around them.

Findings:

- an **inverted pair**: edges ``A -> B`` and ``B -> A`` both observed
  (the statically visible deadlock), reported with both sites;
- a **canonical-order violation**: ``tools/oryxlint/lockorder.toml``
  commits the project-wide acquisition order; an observed edge that
  goes backwards against it fails even before the inverse edge lands —
  the second half of the deadlock should never get written.

Locks not named in lockorder.toml are only subject to the inversion
check, so a new lock does not demand a toml entry until it participates
in nesting.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from tools.oryxlint.callgraph import FunctionInfo, ProjectIndex, shared_index
from tools.oryxlint.core import Checker, Finding, Project

MAX_DEPTH = 6
LOCK_CTORS = ("threading.Lock", "threading.RLock")
ORDER_FILE = Path(__file__).resolve().parent.parent / "lockorder.toml"
_ORDER_RE = re.compile(r'"([^"]+)"')


def load_canonical_order(path: Path = ORDER_FILE) -> list[str]:
    """The committed acquisition order: the ``order = [...]`` string list
    of lockorder.toml (hand-parsed — the schema is one key, and the
    container python predates tomllib)."""
    if not path.exists():
        return []
    text = path.read_text(encoding="utf-8")
    m = re.search(r"order\s*=\s*\[(.*?)\]", text, re.S)
    if m is None:
        return []
    return _ORDER_RE.findall(m.group(1))


class _Edge:
    __slots__ = ("src", "dst", "path", "line", "via")

    def __init__(self, src, dst, path, line, via):
        self.src = src
        self.dst = dst
        self.path = path
        self.line = line
        self.via = via  # qualname chain for the report


class LockOrderChecker(Checker):
    name = "lockorder"
    rules = {
        "lock-order": (
            "two locks are acquired in opposite orders somewhere in the "
            "tree, or an acquisition edge violates the canonical order "
            "committed in tools/oryxlint/lockorder.toml"
        ),
    }
    severities = {"lock-order": "error"}
    fix_hints = {
        "lock-order": (
            "acquire locks in the lockorder.toml order everywhere "
            "(release and re-acquire if the code path needs the reverse)"
        ),
    }

    def __init__(self, order_file: Path | None = None):
        self.order_file = order_file or ORDER_FILE

    def check(self, project: Project) -> list[Finding]:
        idx = shared_index(project)
        self._module_locks = self._collect_module_locks(idx)
        self._class_locks = self._collect_class_locks(idx)
        edges = self._collect_edges(idx)
        return self._verdicts(edges)

    # -- lock identity --------------------------------------------------------

    def _collect_module_locks(self, idx: ProjectIndex) -> dict[tuple[str, str], str]:
        """(relpath, global name) -> lock id for module-level lock
        globals and class-level shared locks."""
        out: dict[tuple[str, str], str] = {}
        for mod in idx.project.modules:
            stem = mod.relpath.rsplit("/", 1)[-1].removesuffix(".py")
            for node in ast.walk(mod.tree):
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                ):
                    continue
                d = idx.dotted_name(mod, node.value.func)
                if d in LOCK_CTORS:
                    name = node.targets[0].id
                    out[(mod.relpath, name)] = f"{stem}.{name}"
        return out

    def _collect_class_locks(self, idx: ProjectIndex) -> dict[str, set[str]]:
        """class key -> instance lock attr names (self.x = Lock()/RLock(),
        plus class-level shared locks)."""
        out: dict[str, set[str]] = {}
        for key, ci in idx.classes.items():
            attrs: set[str] = set()
            for node in ast.walk(ci.node):
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)
                ):
                    continue
                d = idx.dotted_name(ci.module, node.value.func)
                if d not in LOCK_CTORS:
                    continue
                t = node.targets[0]
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    attrs.add(t.attr)
                elif isinstance(t, ast.Name):
                    attrs.add(t.id)  # class-level shared lock
            if attrs:
                out[key] = attrs
        return out

    def _lock_id(self, idx: ProjectIndex, fi: FunctionInfo, expr: ast.AST) -> str | None:
        """Lock identity of a `with <expr>:` context, or None."""
        mod = fi.module
        if isinstance(expr, ast.Name):
            hit = self._module_locks.get((mod.relpath, expr.id))
            if hit is not None:
                return hit
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            base, attr = expr.value.id, expr.attr
            if base == "self" and fi.cls is not None:
                for cls in idx._mro(fi.cls):
                    ci = idx.classes[cls]
                    attr_n = ci.lock_aliases.get(attr, attr)
                    if attr_n in self._class_locks.get(cls, ()):  # normalized
                        return f"{ci.name}.{attr_n}"
            elif base in idx.classes and base not in idx._ambiguous_classes:
                if attr in self._class_locks.get(base, ()):
                    return f"{base}.{attr}"
        return None

    def _contract_ids(self, fi: FunctionInfo, idx: ProjectIndex) -> list[str]:
        out = []
        for lock in fi.holds:
            if fi.cls is not None:
                for cls in idx._mro(fi.cls):
                    ci = idx.classes[cls]
                    n = ci.lock_aliases.get(lock, lock)
                    if n in self._class_locks.get(cls, ()):
                        out.append(f"{ci.name}.{n}")
                        break
        return out

    # -- edge collection ------------------------------------------------------

    def _collect_edges(self, idx: ProjectIndex) -> list[_Edge]:
        edges: list[_Edge] = []
        for fi in idx.functions:
            held = tuple(self._contract_ids(fi, idx))
            self._walk_body(
                idx, fi, list(fi.node.body), held, [fi.qualname], edges,
                set(), 0,
            )
        return edges

    def _walk_body(self, idx, fi, body, held, via, edges, visited, depth) -> None:
        for node in body:
            self._walk_node(idx, fi, node, held, via, edges, visited, depth)

    def _walk_node(self, idx, fi, node, held, via, edges, visited, depth) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # a closure runs later, not under these locks
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly = list(held)
            for item in node.items:
                lid = self._lock_id(idx, fi, item.context_expr)
                if lid is not None:
                    for h in newly:
                        if h != lid:
                            edges.append(_Edge(
                                h, lid, fi.module.relpath,
                                item.context_expr.lineno, " -> ".join(via),
                            ))
                    newly = newly + [lid]
            self._walk_body(
                idx, fi, list(node.body), tuple(newly), via, edges, visited,
                depth,
            )
            return
        if isinstance(node, ast.Call) and held and depth < MAX_DEPTH:
            for tgt in idx.resolve_call(fi, node):
                key = (id(tgt), held)
                if key in visited:
                    continue
                visited.add(key)
                # the held set carries into the callee unchanged; its own
                # holds= contract locks coincide with ours by definition
                # (same-lock edges are filtered at the acquisition site)
                self._walk_body(
                    idx, tgt, list(tgt.node.body), held,
                    via + [tgt.qualname], edges, visited, depth + 1,
                )
        for child in ast.iter_child_nodes(node):
            self._walk_node(idx, fi, child, held, via, edges, visited, depth)

    # -- verdicts -------------------------------------------------------------

    def _verdicts(self, edges: list[_Edge]) -> list[Finding]:
        findings: list[Finding] = []
        by_pair: dict[tuple[str, str], _Edge] = {}
        for e in edges:
            by_pair.setdefault((e.src, e.dst), e)
        reported: set[frozenset] = set()
        for (a, b), e in sorted(by_pair.items()):
            inv = by_pair.get((b, a))
            if inv is not None and frozenset((a, b)) not in reported:
                reported.add(frozenset((a, b)))
                findings.append(Finding(
                    e.path, e.line, "lock-order",
                    f"inverted lock pair: {a} -> {b} here (via {e.via}) "
                    f"but {b} -> {a} at {inv.path}:{inv.line} (via "
                    f"{inv.via}) — two threads on these paths deadlock",
                ))
        order = load_canonical_order(self.order_file)
        rank = {name: i for i, name in enumerate(order)}
        for (a, b), e in sorted(by_pair.items()):
            if a in rank and b in rank and rank[a] > rank[b] and (
                frozenset((a, b)) not in reported
            ):
                reported.add(frozenset((a, b)))
                findings.append(Finding(
                    e.path, e.line, "lock-order",
                    f"acquisition {a} -> {b} (via {e.via}) violates the "
                    f"canonical order in tools/oryxlint/lockorder.toml "
                    f"({b} before {a}) — this is half of a deadlock; "
                    "reorder, or update the canonical order everywhere",
                ))
        return findings
