"""Shared ALS build-and-evaluate harness: the bench's training stage and
the nightly 25M quality gate (tests/test_quality_gate.py) run the SAME
code, so the bf16 singularity guard (ops/als.py _half_step jitter retry)
cannot silently regress between bench runs.

Measures what BASELINE.json's north star asks for: end-to-end build
wall-clock at a given interaction scale plus held-out mean-per-user AUC
— with NaN factor rows surfaced as a first-class diagnostic (NaN scores
compare False everywhere, which would silently zero the AUC instead of
failing it).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np


@dataclass
class BuildReport:
    build_s: float
    agg_s: float
    auc: float
    nan_rows: int
    interactions: int
    timings: dict = field(default_factory=dict)


def build_and_evaluate(
    n_users: int,
    n_items: int,
    nnz: int,
    features: int = 50,
    iterations: int = 10,
    lam: float = 0.01,
    alpha: float = 1.0,
    compute_dtype: str = "bfloat16",
    seed: int = 7,
    holdout_p: float = 0.02,
    sample_users: int = 2000,
) -> BuildReport:
    """Synthesize (oryx_tpu/ml/synth.py), train, and evaluate one ALS
    build. compute_dtype="bfloat16" is the MXU-native default — quality-
    neutral on this generator (AUC 0.947 bf16 vs 0.939 f32 at 1M scale),
    and the held-out AUC keeps that claim measured on every run."""
    from oryx_tpu.ml.evaluate import auc_mean_per_user
    from oryx_tpu.ml.synth import synthesize_interactions
    from oryx_tpu.ops.als import aggregate_interactions, train_als

    # offset the eval stream from the data stream: same-seed generators
    # share the underlying bitstream, which would correlate the holdout
    # mask with the generator's user-activity draws
    rng = np.random.default_rng(seed + 1_000_003)
    users, items, values = synthesize_interactions(
        n_users, n_items, nnz, seed=seed
    )
    test_mask = rng.random(nnz) < holdout_p
    tr = ~test_mask

    t0 = time.perf_counter()
    data = aggregate_interactions(users[tr], items[tr], values[tr], implicit=True)
    agg_s = time.perf_counter() - t0
    timings: dict = {}
    model = train_als(
        data,
        features=features,
        lam=lam,
        alpha=alpha,
        iterations=iterations,
        implicit=True,
        compute_dtype=compute_dtype,
        timings=timings,
    )
    build_s = time.perf_counter() - t0

    x_np = np.asarray(model.x, dtype=np.float32)
    y_np = np.asarray(model.y, dtype=np.float32)
    nan_rows = int(
        np.isnan(x_np).any(axis=1).sum() + np.isnan(y_np).any(axis=1).sum()
    )

    # AUC on a user sample (a full per-user python loop would dominate
    # the wall-clock; 2000 users gives a +/-0.005 CI on the mean)
    uid_to_row = {u: j for j, u in enumerate(model.user_ids)}
    iid_to_row = {i: j for j, i in enumerate(model.item_ids)}
    tu_all, ti_all = users[test_mask], items[test_mask]
    known: dict[int, set[int]] = {}
    tu, ti = [], []
    sample = set(
        rng.choice(
            np.unique(tu_all),
            size=min(sample_users, len(np.unique(tu_all))),
            replace=False,
        ).tolist()
    )
    for u, i in zip(tu_all, ti_all):
        if u not in sample:
            continue
        ur, ir = uid_to_row.get(str(u)), iid_to_row.get(str(i))
        if ur is None or ir is None:
            continue
        tu.append(ur)
        ti.append(ir)
    # known (training) items for the sampled users, excluded as negatives
    smp = np.isin(users, np.fromiter(sample, dtype=np.int64)) & tr
    for u, i in zip(users[smp], items[smp]):
        ur, ir = uid_to_row.get(str(u)), iid_to_row.get(str(i))
        if ur is not None and ir is not None:
            known.setdefault(ur, set()).add(ir)
    auc = auc_mean_per_user(
        model.x,
        model.y,
        np.asarray(tu, dtype=np.int64),
        np.asarray(ti, dtype=np.int64),
        known,
    )
    return BuildReport(
        build_s=build_s,
        agg_s=agg_s,
        auc=float(auc),
        nan_rows=nan_rows,
        interactions=nnz,
        timings=timings,
    )
