"""tools/check_metrics.py wired as a tier-1 gate: metric docs can't drift."""

from __future__ import annotations

import importlib.util
import pathlib


def _load_tool():
    root = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "check_metrics", root / "tools" / "check_metrics.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_every_metric_name_documented_and_valid(capsys):
    tool = _load_tool()
    rc = tool.main()
    out = capsys.readouterr()
    assert rc == 0, f"metric/docs drift:\n{out.err}"


def test_checker_catches_undocumented_and_stale_names(monkeypatch):
    """The checker itself must actually fail on drift in both directions."""
    tool = _load_tool()

    real_code = tool.code_metric_names

    def with_extra():
        names = real_code()
        names["oryx_totally_new_metric"] = "somewhere.py"
        return names

    monkeypatch.setattr(tool, "code_metric_names", with_extra)
    assert tool.main() == 1  # registered but undocumented

    monkeypatch.setattr(tool, "code_metric_names", real_code)
    real_doc = tool.doc_metric_names
    monkeypatch.setattr(
        tool, "doc_metric_names", lambda: real_doc() | {"oryx_ghost_metric"}
    )
    assert tool.main() == 1  # documented but gone from code


def test_checker_rejects_invalid_names(monkeypatch):
    tool = _load_tool()
    real_code = tool.code_metric_names

    def with_bad():
        names = real_code()
        names["oryx_BadName"] = "somewhere.py"
        return names

    monkeypatch.setattr(tool, "code_metric_names", with_bad)
    assert tool.main() == 1
