"""Fused streaming dot+top-k Pallas kernel vs the XLA reference, run in
the Pallas interpreter on CPU (the kernel itself targets TPU; the driver's
bench exercises it on real hardware)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from oryx_tpu.ops.als import topk_dot_batch, topk_dot_batch_xla
from oryx_tpu.ops.pallas_topk import topk_dot_batch_pallas


def _check(b, n_items, feats, k, block_b=8, block_i=256, seed=0):
    rng = np.random.default_rng(seed)
    xs = jnp.asarray(rng.normal(size=(b, feats)), dtype=jnp.float32)
    y = jnp.asarray(rng.normal(size=(n_items, feats)), dtype=jnp.float32)
    v_ref, i_ref = topk_dot_batch_xla(xs, y, k=k)
    v, i = topk_dot_batch_pallas(
        xs, y, k=k, block_b=block_b, block_i=block_i, interpret=True
    )
    np.testing.assert_allclose(np.asarray(v), np.asarray(v_ref), atol=1e-4)
    assert np.array_equal(np.asarray(i), np.asarray(i_ref))


def test_matches_xla_basic():
    _check(b=16, n_items=1000, feats=50, k=10)


def test_uneven_batch_and_items():
    # B not a multiple of block_b, I not a multiple of block_i: padding rows
    # must never appear in results
    _check(b=13, n_items=777, feats=33, k=5)


def test_k_equals_one_and_larger_k():
    _check(b=4, n_items=300, feats=8, k=1)
    _check(b=4, n_items=300, feats=8, k=16)
    # 32 is the serving micro-batcher's bucket for default /recommend
    # overfetch (k=18 -> 32) — the fused-kernel dispatch bound
    _check(b=4, n_items=300, feats=8, k=32)


def test_single_item_block():
    # items fit in one block: the running top-k is init + one merge
    _check(b=8, n_items=100, feats=16, k=10, block_i=256)


def test_fewer_items_than_k_padding_is_neg_inf():
    rng = np.random.default_rng(3)
    xs = jnp.asarray(rng.normal(size=(4, 16)), dtype=jnp.float32)
    y = jnp.asarray(rng.normal(size=(6, 16)), dtype=jnp.float32)
    # XLA's top_k rejects k > n_items outright; the kernel degrades
    # gracefully: real items first, then -inf slots
    v, i = topk_dot_batch_pallas(xs, y, k=10, block_b=8, block_i=256, interpret=True)
    scores = np.asarray(xs, dtype=np.float64) @ np.asarray(y, dtype=np.float64).T
    order = np.argsort(-scores, axis=1)
    np.testing.assert_allclose(
        np.asarray(v)[:, :6],
        np.take_along_axis(scores, order, axis=1)[:, :6],
        atol=1e-4,
    )
    assert np.array_equal(np.asarray(i)[:, :6], order[:, :6])
    assert np.all(np.isneginf(np.asarray(v)[:, 6:]))


def test_bfloat16_inputs():
    rng = np.random.default_rng(7)
    xs = jnp.asarray(rng.normal(size=(8, 50)), dtype=jnp.bfloat16)
    y = jnp.asarray(rng.normal(size=(512, 50)), dtype=jnp.bfloat16)
    v, i = topk_dot_batch_pallas(xs, y, k=4, block_b=8, block_i=256, interpret=True)
    v_ref, i_ref = topk_dot_batch_xla(xs, y, k=4)
    # bf16 rounding differs between the two matmuls; compare scores loosely
    # and require the top-1 to agree
    np.testing.assert_allclose(np.asarray(v), np.asarray(v_ref), atol=0.05, rtol=0.05)
    assert np.array_equal(np.asarray(i)[:, 0], np.asarray(i_ref)[:, 0])


def test_k_over_lane_limit_rejected():
    xs = jnp.zeros((4, 8), dtype=jnp.float32)
    y = jnp.zeros((300, 8), dtype=jnp.float32)
    with pytest.raises(ValueError):
        topk_dot_batch_pallas(xs, y, k=200, interpret=True)


def test_dispatcher_uses_xla_off_tpu():
    # On CPU the dispatcher must route to XLA (pallas requires TPU unless
    # interpret=True) and produce the standard result
    rng = np.random.default_rng(11)
    xs = jnp.asarray(rng.normal(size=(4, 8)), dtype=jnp.float32)
    y = jnp.asarray(rng.normal(size=(100, 8)), dtype=jnp.float32)
    v, i = topk_dot_batch(xs, y, k=3)
    v_ref, i_ref = topk_dot_batch_xla(xs, y, k=3)
    assert np.array_equal(np.asarray(i), np.asarray(i_ref))


def test_b1_single_request():
    # B=1: the un-coalesced dispatch shape (an idle server's immediate
    # dispatch) — batch padding must not leak into the one real row
    _check(b=1, n_items=900, feats=50, k=10)


def test_k_not_divisor_of_lane_width():
    # k that divides neither the 128-lane tile nor any bucket boundary:
    # the kernel keeps a full sorted 128-slot state and the wrapper slices
    _check(b=6, n_items=700, feats=20, k=18)
    _check(b=6, n_items=700, feats=20, k=97)


def test_duplicate_scores_stable_tie_break():
    # duplicated rows produce exactly equal scores; the bitonic network's
    # (value desc, index asc) total order must match lax.top_k's stable
    # lowest-index-first tie-break bit-for-bit
    rng = np.random.default_rng(21)
    base = rng.normal(size=(60, 16)).astype(np.float32)
    y = jnp.asarray(np.repeat(base, 5, axis=0))  # every score appears 5x
    xs = jnp.asarray(rng.normal(size=(7, 16)), dtype=jnp.float32)
    v_ref, i_ref = topk_dot_batch_xla(xs, y, k=25)
    v, i = topk_dot_batch_pallas(xs, y, k=25, block_b=8, block_i=128, interpret=True)
    assert np.array_equal(np.asarray(i), np.asarray(i_ref))
    np.testing.assert_allclose(np.asarray(v), np.asarray(v_ref), atol=1e-4)


def test_property_random_shapes_match_xla():
    # randomized sweep over awkward shapes: non-multiple-of-128 item
    # tails, batches off the block grid, k off every boundary — exact
    # index agreement with the XLA reference in interpret mode
    rng = np.random.default_rng(33)
    for trial in range(5):
        b = int(rng.integers(1, 20))
        n_items = int(rng.integers(150, 2500))
        feats = int(rng.integers(4, 70))
        k = int(rng.integers(1, min(128, n_items) + 1))
        block_i = int(rng.choice([128, 256, 512]))
        _check(
            b=b, n_items=n_items, feats=feats, k=k,
            block_b=8, block_i=block_i, seed=100 + trial,
        )


def test_quantized_kernel_parity_interpret():
    # the quantized (int8 + per-row scale) kernel against the quantized
    # XLA reference: identical quantized scores -> identical indices
    from oryx_tpu.ops.als import topk_dot_batch_quant_xla
    from oryx_tpu.ops.transfer import quantize_rows_int8

    rng = np.random.default_rng(44)
    y = rng.normal(size=(1111, 30)).astype(np.float32)
    xs = jnp.asarray(rng.normal(size=(9, 30)), dtype=jnp.float32)
    q, s = quantize_rows_int8(y)
    v, i = topk_dot_batch_pallas(
        xs, jnp.asarray(q), scales=jnp.asarray(s), k=12,
        block_b=8, block_i=256, interpret=True,
    )
    v_ref, i_ref = topk_dot_batch_quant_xla(
        xs, jnp.asarray(q), jnp.asarray(s), k=12
    )
    assert np.array_equal(np.asarray(i), np.asarray(i_ref))
    np.testing.assert_allclose(np.asarray(v), np.asarray(v_ref), atol=1e-4)


def test_tuned_block_table_and_env_override(monkeypatch):
    from oryx_tpu.ops import pallas_topk as pt

    # int8 streams twice the rows per byte: its tuned block_i must be at
    # least bf16's at the same feature pad
    monkeypatch.setattr(pt, "_BLOCK_TABLE", {})
    bb_bf16, bi_bf16 = pt.tuned_blocks(128, 2)
    bb_i8, bi_i8 = pt.tuned_blocks(128, 1)
    assert bi_i8 >= bi_bf16 >= 256
    assert (128, 2) in pt._BLOCK_TABLE  # compile-time cached
    # env override wins for fresh entries
    monkeypatch.setattr(pt, "_BLOCK_TABLE", {})
    monkeypatch.setenv("ORYX_PALLAS_BLOCKS", "64,1024")
    assert pt.tuned_blocks(128, 2) == (64, 1024)
