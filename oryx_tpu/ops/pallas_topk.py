"""Fused streaming dot+top-k Pallas TPU kernel for the serving hot path.

`topk_dot_batch` in ops/als.py is the whole serving request path (the
reference's ALSServingModel.topN LSH fan-out, app/oryx-app-serving
.../als/model/ALSServingModel.java:264-279, collapsed into one matmul +
top-k). Its XLA form materializes the [B, I] score matrix in HBM — at
reference scale (B=1024 requests x I=20M items) that is an 80 GB write +
read per dispatch, dwarfing the matmul itself. This kernel streams item
blocks HBM->VMEM, scores each block on the MXU, and folds it into a
running per-row top-k held in VMEM scratch, so Y is read exactly once and
the score matrix never exists.

Layout: grid (B-blocks, I-blocks) with the item dimension innermost; the
running top-k scratch is (re)initialized at item-block 0 and written to the
output block on every step (the final step's write wins). k is padded to
the 128-lane tile internally and sliced by the wrapper.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128  # TPU lane tile; also the padded top-k slot width


def _topk_kernel(xs_ref, y_ref, vals_ref, idx_ref, run_vals, run_idx, *, k, block_i, n_items):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        run_vals[:] = jnp.full_like(run_vals, -jnp.inf)
        run_idx[:] = jnp.zeros_like(run_idx)

    # [Bb, K] x [K, Ib] on the MXU, f32 accumulation
    scores = jnp.dot(xs_ref[:], y_ref[:].T, preferred_element_type=jnp.float32)
    col = i * block_i + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    scores = jnp.where(col < n_items, scores, -jnp.inf)  # mask tail padding

    cand_vals = jnp.concatenate([run_vals[:], scores], axis=1)
    cand_idx = jnp.concatenate([run_idx[:], col], axis=1)
    pos = jax.lax.broadcasted_iota(jnp.int32, cand_vals.shape, 1)

    slot = jax.lax.broadcasted_iota(jnp.int32, run_vals.shape, 1)
    new_vals = jnp.full_like(run_vals, -jnp.inf)
    new_idx = jnp.zeros_like(run_idx)
    # k selection rounds (k is small and static — unrolled): extract the
    # row max, record it into slot t, then mask it out of the candidates
    for t in range(k):
        m = jnp.max(cand_vals, axis=1)
        am = jnp.argmax(cand_vals, axis=1)
        hit = pos == am[:, None]
        sel_idx = jnp.sum(jnp.where(hit, cand_idx, 0), axis=1)
        new_vals = jnp.where(slot == t, m[:, None], new_vals)
        new_idx = jnp.where(slot == t, sel_idx[:, None], new_idx)
        cand_vals = jnp.where(hit, -jnp.inf, cand_vals)

    run_vals[:] = new_vals
    run_idx[:] = new_idx
    vals_ref[:] = new_vals
    idx_ref[:] = new_idx


def _pad_to(x, size, axis, value=0.0):
    pad = size - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


@partial(jax.jit, static_argnames=("k", "block_b", "block_i", "interpret"))
def topk_dot_batch_pallas(
    xs,
    y,
    *,
    k: int,
    block_b: int = 128,
    block_i: int = 4096,
    interpret: bool = False,
):
    """Top-k of xs @ y.T per row without materializing the score matrix.

    xs: [B, K] queries; y: [I, K] item factors; returns ([B, k] f32 scores,
    [B, k] int32 indices), identical ordering to jax.lax.top_k. k <= 128.
    interpret=True runs the kernel in the Pallas interpreter (CPU tests).

    block_i=4096 keeps the f32 working set (double-buffered Y block +
    score block + the two merge candidate arrays) inside the 16 MB scoped
    VMEM limit on v5e; 8192 overflows it. Measured on v5e at 4096 x 1M x
    50f bf16 k=10: 94 ms vs 187 ms for the XLA matmul+top_k (1.98x).
    """
    if k > _LANE:
        raise ValueError(f"k must be <= {_LANE}, got {k}")
    n_b, n_feat = xs.shape
    n_items = y.shape[0]

    block_b = min(block_b, max(8, n_b))
    block_i = min(block_i, max(_LANE, -(-n_items // _LANE) * _LANE))
    # pad features to the lane tile (zeros leave dot products unchanged),
    # batch to the block size, items to the item block
    feat_pad = max(_LANE, -(-n_feat // _LANE) * _LANE)
    xs_p = _pad_to(_pad_to(xs, feat_pad, 1), -(-n_b // block_b) * block_b, 0)
    y_p = _pad_to(_pad_to(y, feat_pad, 1), -(-n_items // block_i) * block_i, 0)
    nb = xs_p.shape[0] // block_b
    ni = y_p.shape[0] // block_i

    kernel = partial(_topk_kernel, k=k, block_i=block_i, n_items=n_items)
    vals, idx = pl.pallas_call(
        kernel,
        grid=(nb, ni),
        in_specs=[
            pl.BlockSpec((block_b, feat_pad), lambda b, i: (b, 0)),
            pl.BlockSpec((block_i, feat_pad), lambda b, i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_b, _LANE), lambda b, i: (b, 0)),
            pl.BlockSpec((block_b, _LANE), lambda b, i: (b, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((xs_p.shape[0], _LANE), jnp.float32),
            jax.ShapeDtypeStruct((xs_p.shape[0], _LANE), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_b, _LANE), jnp.float32),
            pltpu.VMEM((block_b, _LANE), jnp.int32),
        ],
        interpret=interpret,
    )(xs_p, y_p)
    return vals[:n_b, :k], idx[:n_b, :k]
