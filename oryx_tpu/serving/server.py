"""Serving layer runtime: embedded threaded HTTP server + model listener.

Mirrors the reference ServingLayer + ModelManagerListener (framework/
oryx-lambda-serving .../ServingLayer.java:58-339, ModelManagerListener.java:
59-235): on start it reflectively loads the user's ServingModelManager,
spawns an update-topic listener thread replaying from earliest (so the
in-memory model rebuilds), creates an input-topic producer unless read-only,
and serves the app's routes on a thread-pooled HTTP server with optional
basic auth and gzip request bodies.
"""

from __future__ import annotations

import gzip
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from oryx_tpu.api import ServingModelManager
from oryx_tpu.bus.api import ConsumeDataIterator, TopicProducer
from oryx_tpu.bus.broker import get_broker
from oryx_tpu.common.classutil import load_instance_of
from oryx_tpu.common.config import Config
from oryx_tpu.common.perfattr import PhaseLedger, get_perfattr
from oryx_tpu.common.tracing import (
    format_traceparent,
    get_tracer,
    parse_traceparent,
)
from oryx_tpu.serving.app import Request, ServingApp
from oryx_tpu.serving.auth import Authenticator, make_authenticator

log = logging.getLogger(__name__)


class ServingLayer:
    def __init__(self, config: Config, model_manager: ServingModelManager | None = None):
        self.config = config
        self.port = config.get_int("oryx.serving.api.port", 8080)
        self.read_only = config.get_bool("oryx.serving.api.read-only", False)
        self.group = f"OryxGroup-{config.get_string('oryx.id', None) or 'serving'}-serving"
        self.update_uri = config.get_string("oryx.update-topic.broker")
        self.update_topic = config.get_string("oryx.update-topic.message.topic")
        self.input_uri = config.get_string("oryx.input-topic.broker")
        self.input_topic = config.get_string("oryx.input-topic.message.topic")

        if model_manager is not None:
            self.model_manager = model_manager
        else:
            cls_name = config.get_string("oryx.serving.model-manager-class")
            if not cls_name:
                raise ValueError("no oryx.serving.model-manager-class configured")
            self.model_manager = load_instance_of(cls_name, ServingModelManager, config)

        self._update_consumer: ConsumeDataIterator | None = None
        self._listener: threading.Thread | None = None
        self._httpd: ThreadingHTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        self._aio_server = None
        self.app: ServingApp | None = None

    def start(self) -> None:
        # Fail fast on missing topics: the reference serving layer never
        # creates topics (its no-init-topics flag only gates the test-only
        # ModelManagerListener path, ServingLayer.java:283) — a typo'd topic
        # name must error at startup, not silently serve an empty topic.
        # oryx.serving.init-topics = true opts in to auto-creation for
        # single-binary/dev deployments (a deliberate deviation, logged
        # loudly); no-init-topics = true additionally forbids it outright.
        no_init = self.config.get_bool("oryx.serving.no-init-topics", False)
        init_topics = (
            self.config.get_bool("oryx.serving.init-topics", False)
            and not no_init
        )

        def ensure(uri: str, topic: str, which: str) -> None:
            if get_broker(uri).topic_exists(topic):
                return
            if not init_topics:
                hint = (
                    "topic creation is forbidden by oryx.serving."
                    "no-init-topics = true; create it out of band"
                    if no_init
                    else "create it first (`python -m oryx_tpu.cli setup`) "
                    "or set oryx.serving.init-topics = true to let the "
                    "serving layer create it"
                )
                raise RuntimeError(f"topic does not exist: {topic} ({hint})")
            log.warning(
                "AUTO-CREATING missing %s topic %s on %s "
                "(oryx.serving.init-topics = true; the reference serving "
                "layer would fail fast here)", which, topic, uri,
            )
            partitions = self.config.get_int(
                f"oryx.{which}-topic.message.partitions", 1
            )
            # maybe_create: replicas racing on the same broker must both
            # win; honor the configured message cap (MODEL publishes are
            # sized against it)
            from oryx_tpu.bus.broker import topics

            topics.maybe_create(
                uri, topic, partitions,
                max_message_bytes=self.config.get_int(
                    f"oryx.{which}-topic.message.max-size", 1 << 24
                ),
            )

        ensure(self.update_uri, self.update_topic, "update")
        update_broker = get_broker(self.update_uri)
        try:
            n_parts = update_broker.num_partitions(self.update_topic)
        except Exception:
            n_parts = 1
        if n_parts > 1:
            # chunked MODEL-REF artifact transfer assumes the publish
            # order of one partition (MODEL-CHUNK x N, then MODEL-REF);
            # across partitions the REF can overtake its chunks and rely
            # on the relay's parked re-dispatch instead of fast delivery
            log.warning(
                "update topic %s has %d partitions; model updates assume "
                "single-partition ordering (the reference's convention) — "
                "chunked MODEL-REF delivery may be delayed",
                self.update_topic, n_parts,
            )

        input_producer = None
        if not self.read_only:
            ensure(self.input_uri, self.input_topic, "input")
            input_producer = TopicProducer(get_broker(self.input_uri), self.input_topic)

        # The app MUST exist before the model listener replays a single
        # message: its constructor configures the config-level planes the
        # listener's dispatch path consults — the model gate above all (a
        # canary replica whose incumbent replays while the gate is still
        # "off" adopts it OUTSIDE the gate's history, and the eventual
        # rollback finds nothing to swap back to), plus the artifact
        # relay's distribution mode, flight recorder, SLOs, and quality
        # sampler.
        self.app = ServingApp(self.config, self.model_manager, input_producer)

        # model listener: replay update topic from earliest forever
        # (ModelManagerListener.java:118-149)
        self._update_consumer = ConsumeDataIterator(
            update_broker, self.update_topic, group=f"{self.group}-updates", start="earliest"
        )

        def listen():
            try:
                self.model_manager.consume(self._update_consumer)
            except Exception:
                log.exception("serving model listener died")

        self._listener = threading.Thread(
            target=listen, name="oryx-serving-model-listener", daemon=True
        )
        self._listener.start()
        # /healthz reports this consumer's update-topic backlog so a
        # fleet front can see a replica falling behind model distribution.
        # Sampled on a dedicated thread, never on the probe: lag() does
        # synchronous broker I/O (Kafka ListOffsets round trips, filelog
        # stats), and /healthz dispatches inline on the serving event
        # loop — a slow bus must degrade the lag NUMBER, not stall every
        # in-flight /recommend behind a blocked probe (which would then
        # get the replica ejected by the very front asking after it).
        self._lag_sample: int | None = None
        self._lag_stop = threading.Event()

        # the offloop proof: .lag() is broker I/O (the PR 7 bug class —
        # blocking calls on the probe path), legal here only because this
        # closure runs on the dedicated sampler thread below
        def sample_lag() -> None:  # oryxlint: offloop (lag sampler thread)
            while not self._lag_stop.is_set():
                try:
                    self._lag_sample = self._update_consumer.lag()
                except Exception:  # noqa: BLE001 - lag is best-effort
                    self._lag_sample = None
                self._lag_stop.wait(2.0)

        self._lag_thread = threading.Thread(
            target=sample_lag, name="oryx-serving-update-lag", daemon=True
        )
        self._lag_thread.start()
        self.app.update_lag_fn = lambda: self._lag_sample
        # saturation shedding knobs for the process-wide top-k batcher
        # (oryx.serving.api.shed.*): past max-queue, submits 503 with
        # Retry-After instead of queueing without bound
        from oryx_tpu.serving.batcher import TopKBatcher

        TopKBatcher.shared().configure(self.config)
        auth = make_authenticator(self.config)
        frontend = self.config.get_string("oryx.serving.api.server", "async")
        cert = self.config.get_string("oryx.serving.api.ssl-cert-file", None)
        key = self.config.get_string("oryx.serving.api.ssl-key-file", None)
        ctx = None
        if cert:
            # TLS termination in-process (the reference's Tomcat keystore
            # connector, ServingLayer.java:58-339 — PEM instead of JKS);
            # like the reference, TLS binds on secure-port when one is
            # configured
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(cert, key or None)
            if frontend == "async":
                try:
                    # advertise h2 via ALPN (the reference's Tomcat
                    # connector does the same, ServingLayer.java:229); a
                    # client that negotiates h2 sends the connection
                    # preface, which the async frontend detects. The
                    # threaded frontend can't speak h2, so advertising it
                    # there would break every h2-capable TLS client.
                    ctx.set_alpn_protocols(["h2", "http/1.1"])
                except NotImplementedError:  # pragma: no cover - old ssl
                    pass
            # bind the secure connector on secure-port only when one is
            # EXPLICITLY configured (default null): a packaged default
            # would silently clobber `port` for every TLS deployment.
            # DIVERGENCE from the reference (ServingLayer.java:215), which
            # binds secure-port (default 443) whenever a keystore is
            # configured — see docs/parity.md; warn so reference configs
            # relying on that default notice the changed bind port.
            secure = self.config.get("oryx.serving.api.secure-port", None)
            if secure:
                self.port = int(secure)
            else:
                log.warning(
                    "TLS enabled without oryx.serving.api.secure-port: "
                    "binding the secure connector on port %d (the reference "
                    "would bind secure-port's default 443 here)", self.port,
                )

        if frontend == "async":
            from oryx_tpu.serving.aserver import AsyncHTTPServer

            # event-loop fan-out: 0 = auto (one loop per CPU core). All
            # loops share THIS app/model/batcher — the in-process
            # alternative to `processes`, which duplicates model state
            # per replica.
            loops = self.config.get_int("oryx.serving.api.loops", 0)
            if loops <= 0:
                import os

                loops = os.cpu_count() or 1
            self._aio_server = AsyncHTTPServer(
                self.app,
                auth,
                self.port,
                ssl_context=ctx,
                workers=self.config.get_int("oryx.serving.api.workers", 128),
                reuse_port=self.config.get_int("oryx.serving.api.processes", 1) > 1,
                loops=loops,
            )
            self._aio_server.start()
            self.port = self._aio_server.port
        else:
            handler = _make_handler(self.app, auth)
            if self.config.get_int("oryx.serving.api.processes", 1) > 1:
                # replica mode shares the port across processes
                import socket

                class _ReusePortServer(ThreadingHTTPServer):
                    def server_bind(self):
                        self.socket.setsockopt(
                            socket.SOL_SOCKET, socket.SO_REUSEPORT, 1
                        )
                        super().server_bind()

                self._httpd = _ReusePortServer(("0.0.0.0", self.port), handler)
            else:
                self._httpd = ThreadingHTTPServer(("0.0.0.0", self.port), handler)
            if ctx is not None:
                # defer the handshake to the per-connection handler thread —
                # with the default handshake-on-accept, one client that opens
                # a socket and never speaks TLS would block the accept loop
                self._httpd.socket = ctx.wrap_socket(
                    self._httpd.socket, server_side=True, do_handshake_on_connect=False
                )
            self.port = self._httpd.server_address[1]
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever, name="oryx-serving-http", daemon=True
            )
            self._http_thread.start()
        # the bound port is now concrete (ephemeral binds resolved):
        # /healthz and degraded reasons can name it
        self.app.listen_port = self.port
        if self._aio_server is not None:
            log.info(
                "serving layer listening on :%d (async, %d event loops)",
                self.port, len(self._aio_server._loopstates),
            )
        else:
            log.info("serving layer listening on :%d (%s)", self.port, frontend)

    def await_termination(self) -> None:
        if self._aio_server:
            self._aio_server.join()
        if self._http_thread:
            self._http_thread.join()

    def close(self) -> None:
        if getattr(self, "_lag_stop", None) is not None:
            self._lag_stop.set()
        if self._aio_server:
            self._aio_server.close()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._update_consumer:
            self._update_consumer.close()
        self.model_manager.close()
        if self._listener:
            self._listener.join(timeout=10)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()


def _make_handler(app: ServingApp, auth: Authenticator | None):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        timeout = 30  # bounds slow/stalled clients (incl. deferred TLS handshakes)

        def log_message(self, fmt, *args):  # route to logging, not stderr
            log.debug("http: " + fmt, *args)

        def _handle(self, method: str) -> None:
            # phase ledger from the first byte we act on: parse covers the
            # body drain + URL split + gzip decode (the auth exchange is
            # stamped separately below)
            ledger = PhaseLedger()
            t_parse0 = time.monotonic()
            parse_s = 0.0
            # drain the body FIRST, even for requests that will 401 —
            # leaving unread bytes on a keep-alive socket desyncs the next
            # request on the connection (digest clients always see a 401
            # on their first exchange, so this path is routine, not rare)
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            parse_s += time.monotonic() - t_parse0
            if auth is not None:
                # DIGEST by default (reference InMemoryRealm parity); the
                # check returns a fresh challenge on any failure/staleness
                t_auth = time.monotonic()
                verdict = auth.check(
                    method, self.path, self.headers.get("Authorization")
                )
                ledger.add("auth", time.monotonic() - t_auth, start=t_auth)
                if verdict is not True:
                    payload = b'{"status":401,"error":"unauthorized"}'
                    self.send_response(401)
                    self.send_header("WWW-Authenticate", verdict)
                    self.send_header("Content-Length", str(len(payload)))
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(payload)
                    return
            t_parse1 = time.monotonic()
            split = urlsplit(self.path)
            if self.headers.get("Content-Encoding", "").lower() == "gzip" and body:
                import zlib

                try:
                    body = gzip.decompress(body)
                except (OSError, EOFError, zlib.error):
                    # truncated/corrupt gzip must 400, not kill the
                    # handler mid-connection (same contract as aserver)
                    payload = b"bad gzip body"
                    self.send_response(400)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    if method != "HEAD":
                        self.wfile.write(payload)
                    return
            req = Request(
                method=method,
                path=split.path,
                params={},
                query=parse_qs(split.query),
                body=body,
                headers={k.lower(): v for k, v in self.headers.items()},
            )
            parse_s += time.monotonic() - t_parse1
            ledger.add("parse", parse_s, start=t_parse0)
            req.ledger = ledger
            tr = get_tracer()
            span = None
            if tr.enabled:
                span = tr.start(
                    "http.request",
                    parent=parse_traceparent(req.headers.get("traceparent")),
                    method=method, target=self.path, frontend="threaded",
                )
                req.trace = span
                ledger.trace = span
                ledger.trace_id = span.trace_id
            status, payload, ctype = app.dispatch(req)
            if span is not None:
                tr.finish(span, status=status)
                tr.log_if_slow(span, log)
            t_write = time.monotonic()
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            if span is not None:
                # traced responses name their trace: the id to look up in
                # /debug/traces and to match against /metrics exemplars
                self.send_header(
                    "traceparent",
                    format_traceparent(span.trace_id, span.span_id),
                )
            # headers accumulated during dispatch (Retry-After on sheds,
            # Warning on stale-model responses)
            for k, v in req.response_headers:
                self.send_header(k, v)
            # compress sizable responses for clients that accept it (the
            # reference gzips csv/json via its Tomcat connector)
            accept_enc = self.headers.get("Accept-Encoding", "")
            self.send_header("Vary", "Accept-Encoding")
            if "gzip" in accept_enc.lower() and len(payload) >= 1024:
                payload = gzip.compress(payload, compresslevel=5)
                self.send_header("Content-Encoding", "gzip")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            if method != "HEAD":
                self.wfile.write(payload)
            # write covers headers + (gzip'd) payload hitting the socket;
            # the flush after it is the ledger's single exit point
            ledger.add("write", time.monotonic() - t_write, start=t_write)
            get_perfattr().observe_request(ledger)

        def do_GET(self):
            self._handle("GET")

        def do_HEAD(self):
            self._handle("HEAD")

        def do_POST(self):
            self._handle("POST")

        def do_DELETE(self):
            self._handle("DELETE")

    return Handler
