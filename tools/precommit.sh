#!/bin/sh
# Pre-commit gate: git-scoped oryxlint (grouped by rule, with severity
# and fix hints from the --json schema) plus the ruff lint/format gate
# when ruff is installed.
#
# Install:  ln -s ../../tools/precommit.sh .git/hooks/pre-commit
# Run ad hoc:  tools/precommit.sh
#
# Exit status: 0 clean, 1 findings (commit blocked), 2 internal error.
set -u

root="$(cd "$(dirname "$0")/.." && pwd)"
cd "$root" || exit 2

tmp="$(mktemp)"
errs="$(mktemp)"
trap 'rm -f "$tmp" "$errs"' EXIT
python -m tools.oryxlint --changed --json >"$tmp" 2>"$errs"
lint_rc=$?
if [ ! -s "$tmp" ] || [ "$lint_rc" -gt 1 ]; then
    echo "precommit: oryxlint internal error (rc=$lint_rc)" >&2
    cat "$errs" >&2
    exit 2
fi

ORYXLINT_JSON="$tmp" python - <<'PY'
import json
import os
import sys

try:
    with open(os.environ["ORYXLINT_JSON"], encoding="utf-8") as fh:
        doc = json.load(fh)
except (OSError, json.JSONDecodeError) as e:
    print(f"precommit: unparseable oryxlint --json output ({e})",
          file=sys.stderr)
    sys.exit(3)  # internal error, not findings
findings = doc.get("findings", [])
by_rule: dict = {}
for f in findings:
    by_rule.setdefault(f["rule"], []).append(f)
for rule in sorted(by_rule):
    fs = by_rule[rule]
    sev = fs[0].get("severity", "error")
    print(f"[{sev}] {rule} ({len(fs)} finding(s))")
    for f in fs:
        print(f"  {f['path']}:{f['line']}: {f['message']}")
    hint = fs[0].get("fix_hint")
    if hint:
        print(f"  fix: {hint}")
if findings:
    print(f"\nprecommit: {len(findings)} oryxlint finding(s); commit blocked")
    sys.exit(1)
print(f"precommit: oryxlint clean ({len(doc.get('suppressed', []))} suppressed)")
PY
group_rc=$?
if [ "$group_rc" -eq 3 ]; then
    cat "$errs" >&2
    exit 2
fi
[ "$group_rc" -ne 0 ] && exit 1

# ruff is optional in the minimal container; the gate runs wherever it
# exists (dev laptops, CI images with the full toolchain)
if python -m ruff --version >/dev/null 2>&1; then
    python -m ruff check oryx_tpu tools bench.py || exit 1
    python -m ruff format --check oryx_tpu tools bench.py || exit 1
else
    echo "precommit: ruff not installed; skipping lint/format gate"
fi

exit 0
