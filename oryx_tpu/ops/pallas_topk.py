"""Fused streaming dot+top-k Pallas TPU kernel for the serving hot path.

`topk_dot_batch` in ops/als.py is the whole serving request path (the
reference's ALSServingModel.topN LSH fan-out, app/oryx-app-serving
.../als/model/ALSServingModel.java:264-279, collapsed into one matmul +
top-k). Its XLA form materializes the [B, I] score matrix in HBM — at
reference scale (B=1024 requests x I=20M items) that is an 80 GB write +
read per dispatch, dwarfing the matmul itself. This kernel streams item
blocks HBM->VMEM, scores each block on the MXU, and folds it into a
running per-row top-k held in VMEM scratch, so Y is read exactly once and
the score matrix never exists.

Second generation (PR 8), three changes over the first kernel:

- Selection: the first kernel ran k sequential argmax+mask sweeps over a
  [Bb, k+Ib] candidate buffer — O(k·Ib) VPU work per block that exceeded
  the MXU's matmul FLOPs at k=32 and capped the fused path at k<=32.
  Now each block's scores are reduced by a BITONIC partial sort: the
  block splits into 128-lane chunks, each chunk is bitonic-sorted
  descending (28 compare-exchange stages), chunks pairwise-merge down a
  tree (8 stages per level), and the block's top-128 merges into the
  running top-128 (8 stages). ~36 vectorized stages per block total,
  independent of k, exact for any k <= 128 — the comparisons order by
  (value desc, index asc), the same total order as jax.lax.top_k, so
  duplicate scores tie-break identically.
- Streaming: the item matrix stays in HBM (`memory_space=ANY`) and the
  kernel issues its own double-buffered `pltpu.make_async_copy` DMAs
  into a 2-slot VMEM scratch, starting block i+1's copy before computing
  block i — the MXU never waits on the HBM stream.
- Blocks: `(block_b, block_i)` come from a per-(feature-pad, dtype)
  table (`tuned_blocks`) sized against the VMEM budget and cached for
  the process; `autotune_blocks` measures candidates on real hardware
  and locks the winner into the same table (bench uses it; serving
  inherits whatever the table holds at dispatch time).

The kernel also scores QUANTIZED item matrices (int8 rows + per-row f32
scales, ops/transfer.py QuantizedMatrix): the int8 stream halves the
bf16 HBM traffic that dominates the scan, queries are per-row
int8-quantized on device (quantize_queries) and the dot runs
int8 x int8 -> int32 on the MXU — the 2x-rate mode the int8 MFU peak
tables describe. Item scales multiply back before selection; query
scales (order-invariant per row) multiply the returned values after the
kernel. The serving tier re-ranks surviving candidates in f32 either
way (apps/als/serving.py _rerank_exact).

Layout: grid (B-blocks, I-blocks) with the item dimension innermost; the
running top-k scratch is (re)initialized at item-block 0 and written to the
output block on every step (the final step's write wins). k is padded to
the 128-lane tile internally and sliced by the wrapper.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128  # TPU lane tile; also the padded top-k slot width

# Scoped-VMEM working-set budget the block table sizes against (v5e
# exposes ~16 MB; leave headroom for the compiler's own temporaries).
_VMEM_BUDGET_BYTES = 12 << 20


# ---------------------------------------------------------------------------
# bitonic partial-sort selection (exact, index-carrying)
# ---------------------------------------------------------------------------

def _swap_xor(x, d):
    """Partner values at lane XOR d along the last axis (reshape + flip of
    the pair axis — lowers to lane shuffles, no gather)."""
    shp = x.shape
    l = shp[-1]
    xr = x.reshape(shp[:-1] + (l // (2 * d), 2, d))
    return jnp.flip(xr, axis=-2).reshape(shp)


def _cmp_exchange(v, i, d, desc):
    """One compare-exchange stage at XOR distance d, carrying indices.
    desc: bool array over the last axis — True where the run containing
    the lane sorts descending. Ordering is the strict total order
    (value desc, index asc), so equal values resolve exactly like
    jax.lax.top_k's stable lowest-index-first."""
    v_o = _swap_xor(v, d)
    i_o = _swap_xor(i, d)
    lane = jax.lax.broadcasted_iota(jnp.int32, v.shape, v.ndim - 1)
    is_lo = (lane & d) == 0
    greater = (v > v_o) | ((v == v_o) & (i < i_o))
    take_self = greater == (is_lo == desc)
    return jnp.where(take_self, v, v_o), jnp.where(take_self, i, i_o)


def _bitonic_sort_desc(v, i):
    """Full descending sort of the (pow2-length) last axis, carrying i."""
    l = v.shape[-1]
    lane = jax.lax.broadcasted_iota(jnp.int32, v.shape, v.ndim - 1)
    size = 2
    while size <= l:
        desc = (lane & size) == 0
        d = size // 2
        while d >= 1:
            v, i = _cmp_exchange(v, i, d, desc)
            d //= 2
        size *= 2
    return v, i


def _bitonic_merge_desc(v, i):
    """Sort a bitonic (pow2-length) last axis descending: log2(L) stages."""
    l = v.shape[-1]
    desc = jnp.ones(v.shape, dtype=bool)
    d = l // 2
    while d >= 1:
        v, i = _cmp_exchange(v, i, d, desc)
        d //= 2
    return v, i


def _merge_top(av, ai, bv, bi):
    """Exact top-L of two sorted-descending length-L lists: the bitonic
    split (elementwise a[j] vs b[L-1-j], keep the greater) leaves the L
    largest of the union as a bitonic sequence, then one log-merge sorts
    it descending. 1 + log2(L) stages total."""
    rv = jnp.flip(bv, axis=-1)
    ri = jnp.flip(bi, axis=-1)
    greater = (av > rv) | ((av == rv) & (ai < ri))
    return _bitonic_merge_desc(
        jnp.where(greater, av, rv), jnp.where(greater, ai, ri)
    )


def _block_topk(scores, col):
    """[Bb, block_i] scores + global column ids -> the block's exact
    top-128 (vals, idx), sorted descending. block_i must be a pow2
    multiple of 128: chunk sort once, then a pairwise merge tree."""
    bb, bi = scores.shape
    g = bi // _LANE
    v = scores.reshape(bb, g, _LANE)
    i = col.reshape(bb, g, _LANE)
    v, i = _bitonic_sort_desc(v, i)
    while g > 1:
        v = v.reshape(bb, g // 2, 2, _LANE)
        i = i.reshape(bb, g // 2, 2, _LANE)
        v, i = _merge_top(
            v[:, :, 0, :], i[:, :, 0, :], v[:, :, 1, :], i[:, :, 1, :]
        )
        g //= 2
    return v.reshape(bb, _LANE), i.reshape(bb, _LANE)


# ---------------------------------------------------------------------------
# the kernel: manual double-buffered Y stream + bitonic merge
# ---------------------------------------------------------------------------

def _topk_kernel(
    *refs, block_i, n_items, quantized,
):
    if quantized:
        (xs_ref, y_hbm, scale_ref, vals_ref, idx_ref,
         run_vals, run_idx, y_buf, sem) = refs
    else:
        (xs_ref, y_hbm, vals_ref, idx_ref,
         run_vals, run_idx, y_buf, sem) = refs
        scale_ref = None
    i = pl.program_id(1)
    ni = pl.num_programs(1)
    slot = jax.lax.rem(i, 2)

    def dma(s, chunk):
        return pltpu.make_async_copy(
            y_hbm.at[pl.ds(chunk * block_i, block_i)], y_buf.at[s], sem.at[s]
        )

    @pl.when(i == 0)
    def _init():
        dma(0, 0).start()
        run_vals[:] = jnp.full_like(run_vals, -jnp.inf)
        run_idx[:] = jnp.zeros_like(run_idx)

    # prefetch block i+1 while block i computes: the double buffer
    @pl.when(i + 1 < ni)
    def _prefetch():
        dma(jax.lax.rem(i + 1, 2), i + 1).start()

    dma(slot, i).wait()
    y_block = y_buf[slot]

    xs = xs_ref[:]
    if scale_ref is not None:
        # TRUE int8 path: queries arrive pre-quantized (wrapper, per-row
        # scales), so the dot runs int8 x int8 -> int32 on the MXU — the
        # 2x-rate mode the int8 MFU peak describes — exactly. Item scales
        # multiply back in before selection (they reorder across rows);
        # the QUERY scales do not: scaling a row by a positive constant
        # never changes that row's top-k order, so the wrapper applies
        # them to the returned values after the kernel.
        scores = jnp.dot(
            xs, y_block.T, preferred_element_type=jnp.int32
        ).astype(jnp.float32) * scale_ref[0, :][None, :]
    else:
        # [Bb, K] x [K, Ib] on the MXU, f32 accumulation
        scores = jnp.dot(xs, y_block.T, preferred_element_type=jnp.float32)
    col = i * block_i + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    scores = jnp.where(col < n_items, scores, -jnp.inf)  # mask tail padding

    bv, bidx = _block_topk(scores, col)
    nv, nidx = _merge_top(run_vals[:], run_idx[:], bv, bidx)
    run_vals[:] = nv
    run_idx[:] = nidx
    vals_ref[:] = nv
    idx_ref[:] = nidx


def _pad_to(x, size, axis, value=0.0):
    pad = size - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _pow2_ceil(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def _pow2_floor(n: int) -> int:
    return 1 << max(0, n.bit_length() - 1)


# ---------------------------------------------------------------------------
# block tuning: per-(feature-pad, dtype) table, autotunable on hardware
# ---------------------------------------------------------------------------

# (feat_pad, y-dtype itemsize) -> (block_b, block_i). Seeded lazily by the
# VMEM-budget solver; overwritten by autotune_blocks' measured winners and
# the ORYX_PALLAS_BLOCKS env override ("block_b,block_i"). Compile-time
# cache: every topk_dot_batch_pallas call with default blocks consults it,
# so one autotune pass retunes every later dispatch of that (f, dtype).
_BLOCK_TABLE: dict[tuple[int, int], tuple[int, int]] = {}

AUTOTUNE_BLOCK_I = (1024, 2048, 4096, 8192)


def _working_set_bytes(
    block_b: int, block_i: int, feat_pad: int, y_itemsize: int
) -> int:
    """Conservative scoped-VMEM estimate for one grid step: the 2-slot Y
    stream buffer, the query block, the f32 score block plus the sort
    network's value/index temporaries, and the running/output top-k."""
    return (
        2 * block_i * feat_pad * y_itemsize
        + block_b * feat_pad * 4
        + 3 * block_b * block_i * 4
        + 4 * block_b * _LANE * 8
    )


def tuned_blocks(feat_pad: int, y_itemsize: int) -> tuple[int, int]:
    """(block_b, block_i) for a feature pad + item-matrix itemsize: the
    cached table entry if one exists (env override, autotune winner, or a
    previous solve), else the largest pow2 block_i whose working set fits
    the VMEM budget at block_b=128. int8 matrices (itemsize 1) stream
    twice the rows of bf16 per byte, so their tuned block_i is larger."""
    key = (int(feat_pad), int(y_itemsize))
    hit = _BLOCK_TABLE.get(key)
    if hit is not None:
        return hit
    env = os.environ.get("ORYX_PALLAS_BLOCKS")
    if env:
        try:
            bb, bi = (int(t) for t in env.split(","))
            _BLOCK_TABLE[key] = (bb, bi)
            return bb, bi
        except ValueError:
            pass
    block_b = 128
    block_i = 8192
    while block_i > 256 and _working_set_bytes(
        block_b, block_i, feat_pad, y_itemsize
    ) > _VMEM_BUDGET_BYTES:
        block_i //= 2
    _BLOCK_TABLE[key] = (block_b, block_i)
    return block_b, block_i


def autotune_blocks(
    xs, y, *, k: int, scales=None, candidates=AUTOTUNE_BLOCK_I, iters: int = 5
) -> tuple[int, int]:
    """Measure candidate block_i values on the live backend and lock the
    winner into the block table (keyed by this matrix's feature pad +
    dtype, so every later default-block dispatch of the same shape class
    uses it). Compiles each candidate once before timing. Meant for bench
    and operator tooling — never called on a request path."""
    import time as _time

    import numpy as np

    feat_pad = max(_LANE, -(-xs.shape[1] // _LANE) * _LANE)
    itemsize = jnp.dtype(y.dtype).itemsize
    block_b = 128
    best, best_ms = None, None
    for bi in candidates:
        if _working_set_bytes(block_b, bi, feat_pad, itemsize) > _VMEM_BUDGET_BYTES:
            continue
        try:
            fn = lambda: topk_dot_batch_pallas(
                xs, y, k=k, scales=scales, block_b=block_b, block_i=bi
            )
            jax.block_until_ready(fn())  # compile
            t0 = _time.perf_counter()
            r = None
            for _ in range(iters):
                r = fn()
            np.asarray(r[0])
            ms = (_time.perf_counter() - t0) / iters * 1000
        except Exception:  # noqa: BLE001 - a candidate that fails just loses
            continue
        if best_ms is None or ms < best_ms:
            best, best_ms = bi, ms
    if best is not None:
        _BLOCK_TABLE[(feat_pad, itemsize)] = (block_b, best)
        return block_b, best
    return tuned_blocks(feat_pad, itemsize)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def quantize_queries(xs):
    """Per-row symmetric int8 quantization of a query block (device-side
    twin of transfer.quantize_rows_int8): (q int8, scale f32 [B]). The
    quantized kernels run the score dot int8 x int8 -> int32 on the MXU,
    which is what earns the int8 MFU denominator."""
    ax = jnp.max(jnp.abs(xs.astype(jnp.float32)), axis=1)
    sx = jnp.where(ax > 0, ax / 127.0, 1.0)
    q = jnp.clip(
        jnp.round(xs.astype(jnp.float32) / sx[:, None]), -127, 127
    ).astype(jnp.int8)
    return q, sx


@partial(
    jax.jit,
    static_argnames=("k", "block_b", "block_i", "quantized", "interpret"),
)
def _topk_pallas_jit(
    xs, y, scales, *, k, block_b, block_i, quantized, interpret
):
    n_b, n_feat = xs.shape
    if quantized:
        # int8 queries into the int8 kernel; per-row query scales apply
        # to the returned VALUES only (row-positive scaling is top-k
        # order-invariant, so they never need to enter the kernel)
        xs, sx = quantize_queries(xs)
    n_items = y.shape[0]
    # pad features to the lane tile (zeros leave dot products unchanged),
    # batch to the block size, items to the item block
    feat_pad = max(_LANE, -(-n_feat // _LANE) * _LANE)
    xs_p = _pad_to(_pad_to(xs, feat_pad, 1), -(-n_b // block_b) * block_b, 0)
    y_p = _pad_to(_pad_to(y, feat_pad, 1), -(-n_items // block_i) * block_i, 0)
    nb = xs_p.shape[0] // block_b
    ni = y_p.shape[0] // block_i

    kernel = partial(
        _topk_kernel, block_i=block_i, n_items=n_items, quantized=quantized
    )
    in_specs = [
        pl.BlockSpec((block_b, feat_pad), lambda b, i: (b, 0)),
        # the item matrix stays in HBM: the kernel streams its own
        # double-buffered DMA blocks out of it
        pl.BlockSpec(memory_space=pltpu.ANY),
    ]
    operands = [xs_p, y_p]
    if quantized:
        scale_p = _pad_to(
            jnp.asarray(scales, dtype=jnp.float32)[None, :], ni * block_i, 1
        )
        in_specs.append(pl.BlockSpec((1, block_i), lambda b, i: (0, i)))
        operands.append(scale_p)
    vals, idx = pl.pallas_call(
        kernel,
        grid=(nb, ni),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((block_b, _LANE), lambda b, i: (b, 0)),
            pl.BlockSpec((block_b, _LANE), lambda b, i: (b, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((xs_p.shape[0], _LANE), jnp.float32),
            jax.ShapeDtypeStruct((xs_p.shape[0], _LANE), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_b, _LANE), jnp.float32),
            pltpu.VMEM((block_b, _LANE), jnp.int32),
            pltpu.VMEM((2, block_i, feat_pad), y_p.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(*operands)
    vals, idx = vals[:n_b, :k], idx[:n_b, :k]
    if quantized:
        # scale the selected values back into score units (sx > 0, so
        # -inf padding slots stay -inf)
        vals = vals * sx[:n_b, None]
    return vals, idx


def topk_dot_batch_pallas(
    xs,
    y,
    *,
    k: int,
    scales=None,
    block_b: int | None = None,
    block_i: int | None = None,
    interpret: bool = False,
):
    """Top-k of xs @ y.T per row without materializing the score matrix.

    xs: [B, K] queries; y: [I, K] item factors; returns ([B, k] f32 scores,
    [B, k] int32 indices), identical ordering to jax.lax.top_k — including
    duplicate-score tie-breaks (lowest index first). k <= 128 (one lane
    tile of running top-k state). scales: per-row f32 dequantization
    scales for an int8 y (ops/transfer.py QuantizedMatrix) — scores become
    (xs @ y.T) * scale. interpret=True runs the kernel in the Pallas
    interpreter (CPU tests).

    block_b/block_i default to the tuned table (`tuned_blocks`): the
    largest pow2 item block whose double-buffered stream + score block +
    sort temporaries fit the scoped-VMEM budget. Measured on v5e at
    4096 x 1M x 50f bf16 k=10: the gen-1 argmax-round kernel ran 94 ms vs
    187 ms XLA (1.98x); the bitonic merge removes the O(k·Ib) selection
    sweeps that dominated that 94 ms.
    """
    if k > _LANE:
        raise ValueError(f"k must be <= {_LANE}, got {k}")
    n_b = xs.shape[0]
    n_items = y.shape[0]
    feat_pad = max(_LANE, -(-xs.shape[1] // _LANE) * _LANE)
    t_bb, t_bi = tuned_blocks(feat_pad, jnp.dtype(y.dtype).itemsize)
    if block_b is None:
        block_b = t_bb
    if block_i is None:
        block_i = t_bi
    block_b = min(block_b, max(8, n_b))
    # the merge tree needs a pow2 block_i >= one lane tile. Non-pow2
    # requests round DOWN — an operator shrinking the block to dodge a
    # VMEM overflow must get at most what they asked for, never a
    # silently larger block — and never past the next pow2 of the real
    # row count (no point padding the item axis beyond it)
    block_i = max(_LANE, min(_pow2_floor(block_i), _pow2_ceil(n_items)))
    return _topk_pallas_jit(
        xs, y, scales,
        k=k, block_b=block_b, block_i=block_i,
        quantized=scales is not None, interpret=interpret,
    )
