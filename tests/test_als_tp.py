"""Tensor-parallel ALS trainer: numerical equality with the replicated
trainer on an 8-device virtual mesh, plus compiled-HLO layout assertions
(the collectives must actually be there — "model axis exists" is not TP).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oryx_tpu.ops.als import (
    aggregate_interactions,
    train_als,
    train_als_tp,
    als_train_tp_jit,
    build_padded_lists,
    _row_pad,
)
from oryx_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, MeshSpec, make_mesh


def _synth(n_users=60, n_items=40, nnz=600, seed=5):
    rng = np.random.default_rng(seed)
    return aggregate_interactions(
        rng.integers(0, n_users, nnz).astype(str),
        rng.integers(0, n_items, nnz).astype(str),
        (rng.random(nnz) * 3 + 0.2).astype(np.float32),
        implicit=True,
    )


@pytest.fixture(scope="module")
def mesh42():
    return make_mesh(MeshSpec(data=4, model=2), jax.devices()[:8])


@pytest.mark.parametrize("implicit", [True, False])
def test_tp_matches_replicated_trainer(mesh42, implicit):
    data = _synth()
    key = jax.random.PRNGKey(3)
    kwargs = dict(
        features=6, lam=0.05, alpha=2.0, iterations=5, implicit=implicit,
        seed_key=key,
    )
    ref = train_als(data, **kwargs)
    tp = train_als_tp(data, mesh42, **kwargs)
    assert ref.user_ids == tp.user_ids and ref.item_ids == tp.item_ids
    # same math, reordered float accumulation: tight-but-not-exact match
    np.testing.assert_allclose(tp.x, ref.x, rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(tp.y, ref.y, rtol=2e-3, atol=2e-4)


def test_tp_uneven_shapes_and_small_blocks(mesh42):
    data = _synth(n_users=37, n_items=23, nnz=300, seed=9)
    key = jax.random.PRNGKey(1)
    ref = train_als(data, features=4, iterations=3, seed_key=key)
    tp = train_als_tp(data, mesh42, features=4, iterations=3, seed_key=key)
    np.testing.assert_allclose(tp.x, ref.x, rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(tp.y, ref.y, rtol=2e-3, atol=2e-4)


def test_tp_hlo_contains_cross_shard_collectives(mesh42):
    """The compiled program must psum the partial normal equations and the
    Grams — count all-reduces and check factor outputs stay sharded."""
    data = _synth(n_users=32, n_items=16, nnz=200, seed=2)
    dp, tp = mesh42.shape[DATA_AXIS], mesh42.shape[MODEL_AXIS]
    blk = 8
    n_u = -(-data.n_users // (dp * blk)) * (dp * blk)
    n_i = -(-data.n_items // (tp * blk)) * (tp * blk)
    u = build_padded_lists(data.users, data.items, data.values, n_u)
    i = build_padded_lists(data.items, data.users, data.values, n_i)
    y0 = jnp.zeros((n_i, 4), dtype=jnp.float32)
    step = als_train_tp_jit(mesh42, implicit=True, iterations=2, block=blk)
    from jax.sharding import NamedSharding, PartitionSpec as P

    row_d = NamedSharding(mesh42, P(DATA_AXIS, None))
    row_m = NamedSharding(mesh42, P(MODEL_AXIS, None))
    put = lambda a, s: jax.device_put(jnp.asarray(a), s)
    args = (
        put(u[0], row_d), put(u[1], row_d), put(u[2], row_d),
        put(i[0], row_m), put(i[1], row_m), put(i[2], row_m),
        put(y0, row_m), jnp.float32(0.01), jnp.float32(1.0),
    )
    compiled = step.lower(*args).compile()
    hlo = compiled.as_text()
    assert hlo.count("all-reduce") >= 2, "expected psums over both mesh axes"
    # outputs keep their shards: x over data (rows/dp), y over model (rows/tp)
    x, y = step(*args)
    # (trailing Nones are normalized away in specs)
    assert x.sharding.spec in (P(DATA_AXIS), P(DATA_AXIS, None))
    assert y.sharding.spec in (P(MODEL_AXIS), P(MODEL_AXIS, None))
    # per-device Y block is N_i/tp rows: the table is genuinely split
    db = y.addressable_shards[0].data
    assert db.shape[0] == n_i // tp


def test_row_pad_helper():
    a = np.ones((3, 2))
    assert _row_pad(a, 8).shape == (8, 2)
    assert _row_pad(a, 3) is a


def test_checkpointed_training_on_tp_mesh(tmp_path):
    """resume_y must thread through the tensor-parallel dispatch: a
    chunked checkpointed run on a (data, model) mesh equals the
    uninterrupted TP run."""
    import jax

    from oryx_tpu.ops.als import (
        aggregate_interactions,
        train_als,
        train_als_checkpointed,
    )
    from oryx_tpu.parallel.mesh import MeshSpec, make_mesh

    rng = np.random.default_rng(9)
    data = aggregate_interactions(
        rng.integers(0, 64, 4000).astype(str),
        rng.integers(0, 80, 4000).astype(str),
        rng.random(4000) + 0.1,
        implicit=True,
    )
    mesh = make_mesh(MeshSpec(data=2, model=2), jax.devices()[:4])
    key = jax.random.PRNGKey(13)
    base = train_als(
        data, features=8, iterations=4, implicit=True, mesh=mesh,
        block=8, seed_key=key,
    )
    chunked = train_als_checkpointed(
        data, tmp_path / "ck", checkpoint_every=2, features=8, iterations=4,
        implicit=True, mesh=mesh, block=8, seed_key=key,
    )
    np.testing.assert_allclose(chunked.x, base.x, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(chunked.y, base.y, rtol=1e-4, atol=1e-5)
