"""Fleet-scale chaos (ISSUE 7 satellite): the PR 5 chaos discipline
extended to multi-PROCESS topology. The scenario itself lives in
tools/chaos.py (`fleet-kill`) so the CLI chaos driver and this tier-1
smoke run the SAME code: two real serving replica processes behind the
in-process fleet front, an update storm on the bus, SIGKILL one replica
mid-storm — the front must keep answering (zero non-shed 5xx, zero
client-level errors), eject the corpse, and the survivor's
oryx_model_staleness_seconds must stay under the configured bound."""

from __future__ import annotations

import importlib.util
from pathlib import Path


def _chaos_module():
    root = Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "chaos", root / "tools" / "chaos.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fleet_kill_zero_non_shed_5xx_and_bounded_staleness(tmp_path):
    chaos = _chaos_module()
    doc, fn = chaos.SCENARIOS["fleet-kill"]
    problems = fn(str(tmp_path))
    assert problems == []


def test_flight_on_kill_harvests_corpse_last_words(tmp_path):
    """ISSUE 14 satellite: SIGKILL a replica mid update-storm behind the
    front — the supervisor must harvest a flight artifact containing the
    corpse's last lifecycle events (generation adoptions), and the
    front's ejection flight event must carry the same trace-joinable
    replica id."""
    chaos = _chaos_module()
    doc, fn = chaos.SCENARIOS["flight-on-kill"]
    problems = fn(str(tmp_path))
    assert problems == []


def test_fleet_canary_gates_bad_generation_and_rolls_back_pointer(tmp_path):
    """ISSUE 20 acceptance (degraded-model chaos, fleet edition): a
    corrupted generation reaches ONLY the canary replica, the quality
    gate refuses promotion, the rollback is a pure pointer swap (zero
    new distribution bytes), no client saw a non-shed 5xx, and the
    flight rings tell the story in causal order (canary-start ->
    quality-alarm -> canary-rollback)."""
    chaos = _chaos_module()
    doc, fn = chaos.SCENARIOS["fleet-canary"]
    problems = fn(str(tmp_path))
    assert problems == []
