"""In-memory seq model state shared by the speed and serving tiers.

Item embeddings live in the SAME FactorStore the ALS tiers use
(apps/als/state.py): a growing arena whose device copy resyncs by
dirty-row delta (PR 3's scatter_rows machinery), so the speed tier's
per-item UP writes reach the serving matrix as row scatters, never a
re-upload. The small recurrent weights (Wx/Wh/b) ride inline on the
MODEL message and swap atomically with the announced item-id set.
"""

from __future__ import annotations

import threading

import numpy as np

from oryx_tpu.apps.als.state import FactorStore
from oryx_tpu.apps.updates import parse_update_message
from oryx_tpu.ops.seq import GRU_PARAM_NAMES


class SeqState:
    """Embeddings + GRU weights + expected-id readiness bookkeeping."""

    def __init__(self, dim: int, window: int):
        self.dim = dim
        self.window = window
        self.items = FactorStore(dim)
        self.params: dict[str, np.ndarray] | None = None
        self.expected_items: set[str] | None = None
        self._have = 0
        self._frac_lock = threading.Lock()

    # -- writes (keep the readiness counter true) --------------------------

    def set_item(self, ident: str, vector: np.ndarray) -> None:
        present_before = ident in self.items
        self.items.set(ident, vector)
        if self.expected_items is not None:
            with self._frac_lock:
                if ident not in self.expected_items:
                    self.expected_items.add(ident)
                    self._have += 1
                elif not present_before:
                    self._have += 1

    def recount(self) -> None:
        with self._frac_lock:
            ex = self.expected_items
            self._have = len(ex & set(self.items.ids())) if ex is not None else 0

    def set_expected(self, item_ids) -> None:
        self.expected_items = set(item_ids)
        self.recount()

    def fraction_loaded(self) -> float:
        if self.expected_items is None or self.params is None:
            return 0.0
        total = len(self.expected_items)
        if total == 0:
            return 1.0
        with self._frac_lock:
            return self._have / total


def apply_seq_update(
    state: SeqState | None, key: str | None, message: str
) -> SeqState | None:
    """Apply one update-topic message — the single implementation behind
    both the speed and serving managers (the ALS apply_update_message
    pattern):

    MODEL / MODEL-REF -> a fresh state when the embedding width or the
    context window changed, else retain only the announced item ids;
    recurrent weights (inline tensors) swap in either way. The embedding
    matrix itself arrives as the UP row flood that follows (ALS's
    EnqueueFeatureVecsFn streaming pattern), or inline as an "E" tensor
    when the publisher chose to ship it whole.
    UP ["E", id, vec] -> set one item row (width-mismatched stale
    updates from an older-rank model are dropped).
    """
    from oryx_tpu.common.artifact import read_artifact_from_update

    if key in ("MODEL", "MODEL-REF"):
        art = read_artifact_from_update(key, message)
        dim = int(art.get_extension("dim"))
        window = int(art.get_extension("window", 8))
        params = {
            name: np.asarray(art.tensors[name], dtype=np.float32)
            for name in GRU_PARAM_NAMES
            if art.tensors and name in art.tensors
        }
        if len(params) != len(GRU_PARAM_NAMES):
            raise ValueError("seq MODEL message lacks recurrent weight tensors")
        if np.shape(params["Wh"]) != (dim, 3 * dim):
            raise ValueError(
                f"seq recurrent weights shaped {np.shape(params['Wh'])} "
                f"inconsistent with dim={dim}"
            )
        item_ids = art.get_extension_list("ItemIDs")
        if state is None or state.dim != dim:
            state = SeqState(dim, window)
        else:
            state.window = window
        state.params = params
        if item_ids:
            state.set_expected(item_ids)
            state.items.retain(set(item_ids))
            state.recount()
        else:
            state.set_expected(state.items.ids())
        from oryx_tpu.apps.als.state import _adopt_quality_profile

        _adopt_quality_profile(art, item_ids)
        e = art.tensors.get("E") if art.tensors else None
        if e is not None and item_ids and len(e) == len(item_ids):
            state.items.bulk_set(item_ids, np.asarray(e, dtype=np.float32))
            state.recount()
    elif key == "UP":
        if state is None:
            return None  # updates before any model: nothing to apply to
        kind, ident, vec, _known = parse_update_message(message)
        if kind != "E" or len(vec) != state.dim:
            return state
        state.set_item(ident, vec)
    return state
