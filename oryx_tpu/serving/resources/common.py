"""Resources every app shares: /ready health check and /ingest bulk input.

Mirrors the reference's Ready.java:33-46 (GET/HEAD 200-or-503 on model
load fraction) and Ingest.java (bulk lines -> input topic, gzip-aware via
the server's request decoding).
"""

from __future__ import annotations

from oryx_tpu.common.metrics import get_registry
from oryx_tpu.serving.app import OryxServingException, RawResponse, Request, ServingApp


def send_input_lines(
    app: ServingApp, text: str, what: str = "data points", required: bool = True
) -> int:
    """Bulk lines -> input topic; 400 when nothing usable was given (unless
    required=False — the wordcount /add treats an empty flush as a no-op).
    The one implementation behind /ingest, /add, and /train."""
    n = 0
    for line in text.splitlines():
        line = line.strip()
        if line:
            app.send_input(line)
            n += 1
    if n == 0 and required:
        raise OryxServingException(400, f"no {what} given")
    return n


def register(app: ServingApp) -> None:
    @app.route("GET", "/ready")
    def ready(a: ServingApp, req: Request):
        a.get_serving_model()  # raises 503 if not ready
        return 200, {"ready": True}

    @app.route("HEAD", "/ready")
    def ready_head(a: ServingApp, req: Request):
        a.get_serving_model()
        return 200, None

    @app.route("POST", "/ingest")
    def ingest(a: ServingApp, req: Request):
        n = send_input_lines(a, req.body_text(), "ingest body")
        return 200, {"ingested": n}

    if app.config.get_bool("oryx.monitoring.metrics", True):

        @app.route("GET", "/metrics")
        def metrics(a: ServingApp, req: Request):
            text = get_registry().render_prometheus()
            return RawResponse(200, text.encode("utf-8"), "text/plain; version=0.0.4")
