#!/usr/bin/env python3
"""Golden request/response transcripts for the Kafka wire client.

Why this exists (round-3 verdict #6): oryx_tpu/bus/kafka.py had only ever
spoken to the in-repo protocol fake (tests/kafka_testbroker.py) — a
shared-blind-spot risk, since the same author wrote both ends. This tool
produces byte-exact transcripts for the client's canonical exchanges, to
be replayed by tests/test_kafka_transcripts.py against the real client
with NO protocol logic in the middle (the replayer is a dumb byte pipe
that only patches correlation ids and recorded address fields).

Two provenances, recorded in the artifact:

- "live-broker": `python tools/kafka_transcripts.py record` captures the
  bytes from a REAL broker through a man-in-the-middle TCP proxy. Run it
  on any host with a broker (see the docker recipe below); commit the
  refreshed JSON.
- "spec-synthesized": `python tools/kafka_transcripts.py synth` builds
  the responses from an INDEPENDENT implementation of the Kafka protocol
  written directly from the public protocol specification (kafka.apache.
  org/protocol) — its own varint/zigzag, its own CRC-32C, its own
  RecordBatch v2 layout, importing nothing from oryx_tpu. Double-entry
  bookkeeping: a layout misunderstanding must now be made twice,
  independently, to cancel out.

Docker recipe for the live capture (any docker-capable host):

    docker run -d --name oryx-kafka -p 9092:9092 \
      -e KAFKA_CFG_NODE_ID=0 \
      -e KAFKA_CFG_PROCESS_ROLES=controller,broker \
      -e KAFKA_CFG_LISTENERS=PLAINTEXT://:9092,CONTROLLER://:9093 \
      -e KAFKA_CFG_ADVERTISED_LISTENERS=PLAINTEXT://127.0.0.1:19092 \
      -e KAFKA_CFG_CONTROLLER_LISTENER_NAMES=CONTROLLER \
      -e KAFKA_CFG_CONTROLLER_QUORUM_VOTERS=0@localhost:9093 \
      bitnami/kafka:3.6
    # advertised port 19092 = the recording proxy below, so every
    # follow-up (leader / coordinator) connection also flows through it
    ORYX_KAFKA_BROKER=127.0.0.1:9092 ORYX_KAFKA_PROXY_PORT=19092 \
      python tools/kafka_transcripts.py record

The transcript JSON is self-describing: each exchange carries the api
key/version, request/response hex, the byte offsets of address fields the
replayer must patch (broker host/port inside Metadata / FindCoordinator
responses), and the decoded values the client is expected to produce.
"""

from __future__ import annotations

import gzip
import json
import struct
import sys
from pathlib import Path

OUT = Path(__file__).resolve().parent.parent / "tests" / "data" / "kafka_transcripts.json"

# --------------------------------------------------------------------------
# independent wire primitives (from the spec; no oryx_tpu imports)
# --------------------------------------------------------------------------

_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (0x82F63B78 if _c & 1 else 0)
    _CRC_TABLE.append(_c)


def crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli), reflected polynomial 0x82F63B78."""
    c = 0xFFFFFFFF
    for b in data:
        c = (c >> 8) ^ _CRC_TABLE[(c ^ b) & 0xFF]
    return c ^ 0xFFFFFFFF


def zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def varint(v: int) -> bytes:
    u = zigzag(v) & 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def i8(v):  # noqa: E704 - tiny struct aliases
    return struct.pack(">b", v)
def i16(v):
    return struct.pack(">h", v)
def i32(v):
    return struct.pack(">i", v)
def i64(v):
    return struct.pack(">q", v)
def u32(v):
    return struct.pack(">I", v)
def string(s):
    if s is None:
        return i16(-1)
    b = s.encode("utf-8")
    return i16(len(b)) + b
def kbytes(b):
    if b is None:
        return i32(-1)
    return i32(len(b)) + b


def record(offset_delta: int, ts_delta: int, key: bytes | None, value: bytes) -> bytes:
    body = (
        i8(0)  # record attributes
        + varint(ts_delta)
        + varint(offset_delta)
        + (varint(-1) if key is None else varint(len(key)) + key)
        + varint(len(value)) + value
        + varint(0)  # headers
    )
    return varint(len(body)) + body


def snappy_compress_indep(data: bytes) -> bytes:
    """Raw snappy block written from the format description
    (github.com/google/snappy/blob/main/format_description.txt): unsigned
    LEB128 uncompressed length, then all-literal elements in <=60-byte
    chunks (tag (len-1)<<2). Valid, if uncompressive — the point is an
    independent byte stream the client must decode, not ratio."""
    out = bytearray()
    v = len(data)
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            break
    i = 0
    while i < len(data):
        chunk = data[i : i + 60]
        out.append((len(chunk) - 1) << 2)
        out += chunk
        i += len(chunk)
    return bytes(out)


def lz4f_compress_indep(data: bytes) -> bytes:
    """LZ4 frame via our OWN ctypes binding to the system liblz4 (not
    oryx_tpu.bus.compress — zero shared code with the client under test)."""
    import ctypes
    import ctypes.util

    lib = ctypes.CDLL(ctypes.util.find_library("lz4"))
    lib.LZ4F_compressFrameBound.restype = ctypes.c_size_t
    lib.LZ4F_compressFrameBound.argtypes = [ctypes.c_size_t, ctypes.c_void_p]
    lib.LZ4F_compressFrame.restype = ctypes.c_size_t
    lib.LZ4F_compressFrame.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_void_p,
    ]
    bound = lib.LZ4F_compressFrameBound(len(data), None)
    buf = ctypes.create_string_buffer(bound)
    n = lib.LZ4F_compressFrame(buf, bound, data, len(data), None)
    if n == 0 or n > bound:
        raise RuntimeError("LZ4F_compressFrame failed")
    return buf.raw[:n]


def zstd_compress_indep(data: bytes) -> bytes:
    """zstd via our OWN ctypes binding to the system libzstd."""
    import ctypes
    import ctypes.util

    lib = ctypes.CDLL(ctypes.util.find_library("zstd"))
    lib.ZSTD_compressBound.restype = ctypes.c_size_t
    lib.ZSTD_compressBound.argtypes = [ctypes.c_size_t]
    lib.ZSTD_compress.restype = ctypes.c_size_t
    lib.ZSTD_compress.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
    ]
    bound = lib.ZSTD_compressBound(len(data))
    buf = ctypes.create_string_buffer(bound)
    n = lib.ZSTD_compress(buf, bound, data, len(data), 3)
    if n == 0 or n > bound:
        raise RuntimeError("ZSTD_compress failed")
    return buf.raw[:n]


def record_batch(
    base_offset: int,
    records: list[tuple[bytes | None, bytes]],
    first_ts: int = 1_700_000_000_000,
    codec: int = 0,
) -> bytes:
    """RecordBatch v2 (magic 2): the fetch-response / produce-request
    payload format. codec (attributes bits 0-2): 0 none, 1 gzip,
    2 snappy, 3 lz4-frame, 4 zstd."""
    recs = b"".join(
        record(d, 0, k, v) for d, (k, v) in enumerate(records)
    )
    if codec == 1:
        recs = gzip.compress(recs, mtime=0)
    elif codec == 2:
        recs = snappy_compress_indep(recs)
    elif codec == 3:
        recs = lz4f_compress_indep(recs)
    elif codec == 4:
        recs = zstd_compress_indep(recs)
    after_crc = (
        i16(codec)                       # attributes
        + i32(len(records) - 1)          # lastOffsetDelta
        + i64(first_ts)                  # firstTimestamp
        + i64(first_ts)                  # maxTimestamp
        + i64(-1) + i16(-1) + i32(-1)    # producerId/Epoch, baseSequence
        + i32(len(records))
        + recs
    )
    after_length = i32(0) + i8(2) + u32(crc32c(after_crc)) + after_crc
    # partitionLeaderEpoch(0), magic(2), crc, then the covered bytes
    return i64(base_offset) + i32(len(after_length)) + after_length


def parse_request_header(body: bytes) -> tuple[int, int, int, str | None, bytes]:
    """(api_key, api_version, correlation_id, client_id, rest)."""
    key, ver, corr = struct.unpack_from(">hhi", body, 0)
    (clen,) = struct.unpack_from(">h", body, 8)
    pos = 10
    cid = None
    if clen >= 0:
        cid = body[pos : pos + clen].decode("utf-8")
        pos += clen
    return key, ver, corr, cid, body[pos:]


def decode_record_batches_indep(buf: bytes) -> list[tuple[int, bytes | None, bytes]]:
    """Independent RecordBatch v2 decoder (validates CRC-32C); used by the
    replay server to check the bytes the CLIENT produced."""
    out = []
    pos = 0
    while pos + 12 <= len(buf):
        (base,) = struct.unpack_from(">q", buf, pos)
        (blen,) = struct.unpack_from(">i", buf, pos + 8)
        start = pos + 12
        if start + blen > len(buf):
            break  # truncated trailing batch (legal on the wire)
        batch = buf[start : start + blen]
        magic = batch[4]
        assert magic == 2, f"magic {magic}"
        (crc,) = struct.unpack_from(">I", batch, 5)
        covered = batch[9:]
        assert crc == crc32c(covered), "RecordBatch CRC-32C mismatch"
        # within `covered`: attributes@0(2) lastOffsetDelta@2(4)
        # firstTs@6(8) maxTs@14(8) producerId@22(8) producerEpoch@30(2)
        # baseSequence@32(4) recordCount@36(4) records@40
        (attrs,) = struct.unpack_from(">h", covered, 0)
        (count,) = struct.unpack_from(">i", covered, 36)
        recs = covered[40:]
        codec = attrs & 0x7
        if codec == 1:
            recs = gzip.decompress(recs)
        elif codec != 0:
            raise AssertionError(f"unexpected codec {codec}")
        rp = 0

        def rd_varint():
            nonlocal rp
            shift = u = 0
            while True:
                b = recs[rp]
                rp += 1
                u |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            return (u >> 1) ^ -(u & 1)

        for _ in range(count):
            _ln = rd_varint()
            rp += 1  # attributes
            rd_varint()  # ts delta
            od = rd_varint()
            klen = rd_varint()
            key = None
            if klen >= 0:
                key = recs[rp : rp + klen]
                rp += klen
            vlen = rd_varint()
            val = recs[rp : rp + vlen]
            rp += vlen
            nh = rd_varint()
            assert nh == 0
            out.append((base + od, key, val))
        pos = start + blen
    return out


# --------------------------------------------------------------------------
# spec-synthesized responses at the exact api versions the client speaks
# --------------------------------------------------------------------------

TOPIC = "oryx-golden"
HOST = "127.0.0.1"  # patched to the replay server's address at replay time


def _metadata_v1() -> tuple[bytes, list[int]]:
    """Metadata v1 response: 1 broker, TOPIC with 2 partitions led by it.
    Returns (bytes, [port field offsets]) — the replayer patches the port
    i32s (and FindCoordinator's) to wherever the replay server listens."""
    out = bytearray()
    out += i32(1)  # brokers
    out += i32(0) + string(HOST)
    port_off = [len(out)]
    out += i32(0)  # port (patched)
    out += string(None)  # rack
    out += i32(0)  # controller id
    out += i32(1)  # topics
    out += i16(0) + string(TOPIC) + i8(0)  # error, name, is_internal
    out += i32(2)  # partitions
    for idx in range(2):
        out += i16(0) + i32(idx) + i32(0)  # err, index, leader=node 0
        out += i32(1) + i32(0)  # replicas [0]
        out += i32(1) + i32(0)  # isr [0]
    return bytes(out), port_off


def _find_coordinator_v0() -> tuple[bytes, list[int]]:
    out = bytearray()
    out += i16(0) + i32(0) + string(HOST)
    port_off = [len(out)]
    out += i32(0)
    return bytes(out), port_off


FETCH_RECORDS = [
    # batch at base offset 5, uncompressed: null key, keyed, longer value
    (5, None, b"v-five"),
    (6, b"k6", b"v-six"),
    (7, b"k7", b"v-seven has a somewhat longer value \xf0\x9f\x8c\x8a".decode(
        "utf-8", "ignore").encode()),
    # batch at base offset 8, gzip
    (8, None, b"v-eight"),
    (9, b"k9", b"v-nine"),
]


def _fetch_body(record_set: bytes, err: int = 0, hw: int = 10) -> bytes:
    """Fetch v4 response body around a raw record set (possibly empty, or
    deliberately truncated mid-batch)."""
    out = bytearray()
    out += i32(0)  # throttle
    out += i32(1)  # topics
    out += string(TOPIC)
    out += i32(1)  # partitions
    out += i32(0)  # partition index
    out += i16(err)
    out += i64(hw)  # high watermark
    out += i64(hw)  # last stable offset
    out += i32(0)  # aborted txns
    out += kbytes(record_set)
    return bytes(out)


def _fetch_v4() -> bytes:
    batch_a = record_batch(
        5, [(k, v) for _, k, v in FETCH_RECORDS[:3]], codec=0
    )
    batch_b = record_batch(
        8, [(k, v) for _, k, v in FETCH_RECORDS[3:]], codec=1
    )
    return _fetch_body(batch_a + batch_b)


def _api_versions_v0(ranges: list[tuple[int, int, int]] | None = None) -> bytes:
    """ApiVersions v0 response: error, then [api_key, min, max] triples —
    the negotiation the client runs on every fresh connection (KIP-35)."""
    if ranges is None:
        ranges = [(k, 0, 10) for k in (0, 1, 2, 3, 8, 9, 10, 18, 19, 20)]
    out = bytearray()
    out += i16(0)
    out += i32(len(ranges))
    for key, lo, hi in ranges:
        out += i16(key) + i16(lo) + i16(hi)
    return bytes(out)


def _metadata_v1_unknown_topic() -> tuple[bytes, list[int]]:
    """Metadata v1 where the topic comes back UNKNOWN_TOPIC_OR_PARTITION
    (error 3) with no partitions — what a broker without auto-create says
    for a missing topic."""
    out = bytearray()
    out += i32(1)  # brokers
    out += i32(0) + string(HOST)
    port_off = [len(out)]
    out += i32(0)
    out += string(None)  # rack
    out += i32(0)  # controller id
    out += i32(1)  # topics
    out += i16(3) + string(TOPIC) + i8(0)  # UNKNOWN_TOPIC_OR_PARTITION
    out += i32(0)  # no partitions
    return bytes(out), port_off


def _produce_v3() -> bytes:
    out = bytearray()
    out += i32(1)  # topics
    out += string(TOPIC)
    out += i32(1)
    out += i32(0) + i16(0) + i64(42) + i64(-1)  # partition, err, base, ts
    out += i32(0)  # throttle_time_ms (v1+; client must tolerate it)
    return bytes(out)


def _list_offsets_v1(offset: int = 10) -> bytes:
    out = bytearray()
    out += i32(1)
    out += string(TOPIC)
    out += i32(1)
    out += i32(0) + i16(0) + i64(-1) + i64(offset)  # ts, resolved offset
    return bytes(out)


def _create_topics_v0() -> bytes:
    return bytes(i32(1) + string(TOPIC) + i16(0))


def _delete_topics_v0() -> bytes:
    return bytes(i32(1) + string(TOPIC) + i16(0))


def _offset_commit_v2() -> bytes:
    out = bytearray()
    out += i32(1)
    out += string(TOPIC)
    out += i32(2)
    out += i32(0) + i16(0)
    out += i32(1) + i16(0)
    return bytes(out)


def _offset_fetch_v1() -> bytes:
    out = bytearray()
    out += i32(1)
    out += string(TOPIC)
    out += i32(2)
    out += i32(0) + i64(41) + string("") + i16(0)
    out += i32(1) + i64(7) + string(None) + i16(0)
    return bytes(out)


def _unknown_meta_exchange() -> dict:
    resp, port_offs = _metadata_v1_unknown_topic()
    return {
        "api_key": 3, "api_version": 1,
        "response_hex": resp.hex(), "port_offsets": port_offs,
    }


def synthesize() -> dict:
    meta, meta_ports = _metadata_v1()
    coord, coord_ports = _find_coordinator_v0()
    doc = {
        "source": "spec-synthesized",
        "note": "responses built by tools/kafka_transcripts.py from the "
        "public Kafka protocol spec, independently of oryx_tpu.bus "
        "(own varint/zigzag, CRC-32C, RecordBatch v2, own snappy "
        "encoder and lz4/zstd ctypes bindings); refresh from a real "
        "broker with `tools/kafka_transcripts.py record` (see module "
        "docstring for the docker recipe). Live capture attempted on "
        "the build host 2026-07-31: no docker/podman binary and no "
        "network egress, so record mode has not yet run against a "
        "real broker",
        "topic": TOPIC,
        "exchanges": {
            "metadata": {
                "api_key": 3, "api_version": 1,
                "response_hex": meta.hex(), "port_offsets": meta_ports,
            },
            "find_coordinator": {
                "api_key": 10, "api_version": 0,
                "response_hex": coord.hex(), "port_offsets": coord_ports,
            },
            "fetch": {
                "api_key": 1, "api_version": 4,
                "response_hex": _fetch_v4().hex(),
                "expect": [
                    [off, k.decode() if k else None, v.decode()]
                    for off, k, v in FETCH_RECORDS
                ],
            },
            "produce": {
                "api_key": 0, "api_version": 3,
                "response_hex": _produce_v3().hex(),
            },
            "list_offsets": {
                "api_key": 2, "api_version": 1,
                "response_hex": _list_offsets_v1().hex(),
                "expect_end_offset": 10,
            },
            "create_topics": {
                "api_key": 19, "api_version": 0,
                "response_hex": _create_topics_v0().hex(),
            },
            "delete_topics": {
                "api_key": 20, "api_version": 0,
                "response_hex": _delete_topics_v0().hex(),
            },
            "offset_commit": {
                "api_key": 8, "api_version": 2,
                "response_hex": _offset_commit_v2().hex(),
            },
            "offset_fetch": {
                "api_key": 9, "api_version": 1,
                "response_hex": _offset_fetch_v1().hex(),
                "expect": {"0": 41, "1": 7},
            },
            "api_versions": {
                "api_key": 18, "api_version": 0,
                "response_hex": _api_versions_v0().hex(),
            },
        },
    }

    # -- edge exchanges: error codes, truncation, codecs, failed
    # negotiation. Replayed as per-test OVERRIDES of the happy-path
    # exchanges above; response_seq_hex entries are served in order
    # (sticky last), modeling a broker whose state changes between
    # requests (leader movement, log truncation).
    batch5 = record_batch(5, [(k, v) for _, k, v in FETCH_RECORDS[:3]])
    batch8 = record_batch(8, [(k, v) for _, k, v in FETCH_RECORDS[3:]], codec=0)
    codec_batches = {
        "snappy": (2, 10, [(None, b"sn-ten"), (b"k11", b"sn-eleven")]),
        "lz4": (3, 12, [(b"k12", b"lz-twelve"), (None, b"lz-thirteen")]),
        "zstd": (4, 14, [(None, b"zs-fourteen"), (b"k15", b"zs-fifteen")]),
        "gzip": (1, 16, [(b"k16", b"gz-sixteen"), (None, b"gz-seventeen")]),
    }
    codec_set = b"".join(
        record_batch(base, recs, codec=c)
        for c, base, recs in codec_batches.values()
    )
    codec_expect = [
        [base + d, (k.decode() if k else None), v.decode()]
        for c, base, recs in codec_batches.values()
        for d, (k, v) in enumerate(recs)
    ]
    doc["edge_exchanges"] = {
        "fetch_offset_out_of_range": {
            # fetch@5 -> OFFSET_OUT_OF_RANGE (log truncated by retention);
            # the client must resolve the earliest retained offset and
            # resume there, like auto.offset.reset=earliest
            "api_key": 1, "api_version": 4,
            "response_seq_hex": [
                _fetch_body(b"", err=1).hex(),
                _fetch_body(batch8).hex(),
            ],
            "expect": [
                [off, k.decode() if k else None, v.decode()]
                for off, k, v in FETCH_RECORDS[3:]
            ],
        },
        "list_offsets_earliest_8": {
            "api_key": 2, "api_version": 1,
            "response_hex": _list_offsets_v1(8).hex(),
        },
        "fetch_not_leader": {
            # NOT_LEADER_OR_FOLLOWER: the client must refresh metadata and
            # poll again rather than raise (leader moved mid-consume)
            "api_key": 1, "api_version": 4,
            "response_seq_hex": [
                _fetch_body(b"", err=6).hex(),
                _fetch_body(batch5).hex(),
            ],
            "expect": [
                [off, k.decode() if k else None, v.decode()]
                for off, k, v in FETCH_RECORDS[:3]
            ],
        },
        "metadata_unknown_topic": _unknown_meta_exchange(),
        "fetch_truncated": {
            # brokers cut the record set at max_bytes, possibly mid-batch:
            # the complete first batch must decode, the partial tail must
            # be ignored (not crash, not corrupt)
            "api_key": 1, "api_version": 4,
            "response_hex": _fetch_body(batch5 + batch8[: len(batch8) // 2]).hex(),
            "expect": [
                [off, k.decode() if k else None, v.decode()]
                for off, k, v in FETCH_RECORDS[:3]
            ],
        },
        "fetch_codecs": {
            # one batch per codec the client claims: gzip + snappy written
            # by this tool's own encoders, lz4/zstd by its own ctypes
            # bindings to the system libraries
            "api_key": 1, "api_version": 4,
            "response_hex": _fetch_body(codec_set, hw=18).hex(),
            "expect": codec_expect,
        },
        "api_versions_no_fetch_v4": {
            # broker too old for the client's pinned Fetch v4: negotiation
            # must fail loudly at connect, not mid-consume with a garbled
            # response
            "api_key": 18, "api_version": 0,
            "response_hex": _api_versions_v0(
                [(0, 0, 10), (1, 0, 3), (2, 0, 10), (3, 0, 10), (8, 0, 10),
                 (9, 0, 10), (10, 0, 10), (18, 0, 10), (19, 0, 10),
                 (20, 0, 10)]
            ).hex(),
        },
    }
    return doc


# --------------------------------------------------------------------------
# independent response parsers — used by the live recorder to annotate
# captured bytes with the same port_offsets / expect fields the
# synthesizer writes, so `record` output replays identically
# --------------------------------------------------------------------------

def _rd_string(buf: bytes, pos: int) -> tuple[str | None, int]:
    (n,) = struct.unpack_from(">h", buf, pos)
    pos += 2
    if n < 0:
        return None, pos
    return buf[pos : pos + n].decode("utf-8"), pos + n


def metadata_v1_port_offsets(resp: bytes) -> list[int]:
    """Byte offsets of every broker port i32 in a Metadata v1 response."""
    offs = []
    (nb,) = struct.unpack_from(">i", resp, 0)
    pos = 4
    for _ in range(nb):
        pos += 4  # node id
        _, pos = _rd_string(resp, pos)  # host
        offs.append(pos)
        pos += 4  # port
        _, pos = _rd_string(resp, pos)  # rack
    return offs


def find_coordinator_v0_port_offsets(resp: bytes) -> list[int]:
    pos = 2 + 4  # error, node id
    _, pos = _rd_string(resp, pos)
    return [pos]


def fetch_v4_expect(resp: bytes) -> list[list]:
    """Decode a Fetch v4 response's first record set with the independent
    decoder; returns [[offset, key, value], ...]."""
    pos = 4  # throttle
    (nt,) = struct.unpack_from(">i", resp, pos)
    pos += 4
    assert nt >= 1
    _, pos = _rd_string(resp, pos)
    (np_,) = struct.unpack_from(">i", resp, pos)
    pos += 4
    assert np_ >= 1
    pos += 4 + 2 + 8 + 8  # partition, error, hw, lso
    (na,) = struct.unpack_from(">i", resp, pos)
    pos += 4 + max(0, na) * 16
    (blen,) = struct.unpack_from(">i", resp, pos)
    pos += 4
    batch = resp[pos : pos + blen]
    return [
        [off, k.decode() if k is not None else None, v.decode()]
        for off, k, v in decode_record_batches_indep(batch)
    ]


def list_offsets_v1_end_offset(resp: bytes) -> int:
    pos = 4  # topics count (>=1)
    _, pos = _rd_string(resp, pos)
    pos += 4  # partitions count
    pos += 4 + 2 + 8  # partition, error, timestamp
    (off,) = struct.unpack_from(">q", resp, pos)
    return off


def offset_fetch_v1_expect(resp: bytes) -> dict[str, int]:
    out = {}
    (nt,) = struct.unpack_from(">i", resp, 0)
    pos = 4
    for _ in range(nt):
        _, pos = _rd_string(resp, pos)
        (np_,) = struct.unpack_from(">i", resp, pos)
        pos += 4
        for _ in range(np_):
            pidx, off = struct.unpack_from(">iq", resp, pos)
            pos += 12
            _, pos = _rd_string(resp, pos)  # metadata
            pos += 2  # error
            out[str(pidx)] = off
    return out


_API_NAMES = {
    0: "produce", 1: "fetch", 2: "list_offsets", 3: "metadata",
    8: "offset_commit", 9: "offset_fetch", 10: "find_coordinator",
    18: "api_versions", 19: "create_topics", 20: "delete_topics",
}


def _annotate(ex: dict) -> dict:
    """Attach the replayer-required fields to one captured exchange."""
    resp = bytes.fromhex(ex["response_hex"])
    key = ex["api_key"]
    if key == 3:
        ex["port_offsets"] = metadata_v1_port_offsets(resp)
    elif key == 10:
        ex["port_offsets"] = find_coordinator_v0_port_offsets(resp)
    elif key == 1:
        ex["expect"] = fetch_v4_expect(resp)
    elif key == 2:
        ex["expect_end_offset"] = list_offsets_v1_end_offset(resp)
    elif key == 9:
        ex["expect"] = offset_fetch_v1_expect(resp)
    return ex


# --------------------------------------------------------------------------
# live capture: man-in-the-middle recorder against a real broker
# --------------------------------------------------------------------------

def record_live(broker: str, proxy_port: int) -> dict:
    """Record real-broker bytes: a TCP proxy logs every framed request/
    response while the oryx client performs the canonical scenario. The
    broker's advertised listener must point at the proxy (docker recipe
    in the module docstring) so leader/coordinator reconnects also flow
    through it."""
    import socket
    import threading

    host, port_s = broker.rsplit(":", 1)
    captured: dict[int, dict] = {}

    def pump(client_sock):
        up = socket.create_connection((host, int(port_s)), 10)

        def frames(sock):
            while True:
                head = b""
                while len(head) < 4:
                    chunk = sock.recv(4 - len(head))
                    if not chunk:
                        return
                    head += chunk
                (n,) = struct.unpack(">i", head)
                body = b""
                while len(body) < n:
                    chunk = sock.recv(n - len(body))
                    if not chunk:
                        return
                    body += chunk
                yield body

        pending: dict[int, tuple[int, int, str]] = {}

        def c2s():
            for body in frames(client_sock):
                key, ver, corr, _cid, _rest = parse_request_header(body)
                pending[corr] = (key, ver, body.hex())
                up.sendall(struct.pack(">i", len(body)) + body)
            up.close()

        def s2c():
            for body in frames(up):
                (corr,) = struct.unpack_from(">i", body, 0)
                if corr in pending:
                    key, ver, req_hex = pending.pop(corr)
                    # last COMPLETE request/response pair per api key wins
                    # (metadata runs several times across the scenario;
                    # the final one names the topic with its partitions)
                    captured[key] = {
                        "api_key": key,
                        "api_version": ver,
                        "request_hex": req_hex,
                        "response_hex": body[4:].hex(),
                    }
                client_sock.sendall(struct.pack(">i", len(body)) + body)
            client_sock.close()

        threading.Thread(target=c2s, daemon=True).start()
        s2c()

    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", proxy_port))
    srv.listen(16)

    def accept_loop():
        while True:
            c, _ = srv.accept()
            threading.Thread(target=pump, args=(c,), daemon=True).start()

    threading.Thread(target=accept_loop, daemon=True).start()

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from oryx_tpu.bus.kafka import KafkaBroker

    b = KafkaBroker([("127.0.0.1", proxy_port)])
    try:
        b.delete_topic(TOPIC)
    except Exception:
        pass
    b.create_topic(TOPIC, partitions=2)
    b.send_batch(TOPIC, [(None, "v-five"), ("k6", "v-six")], partition=0)
    b.read(TOPIC, 0, 0, 10)
    b.end_offsets(TOPIC)
    b.commit_offsets("oryx-golden-g", TOPIC, {0: 41, 1: 7})
    b.get_offsets("oryx-golden-g", TOPIC)
    b.close()
    srv.close()
    # NOTE the scenario deliberately leaves the topic in place and
    # captures the LAST metadata/fetch/list_offsets exchanges while it
    # exists, then annotates each captured exchange with the same
    # port_offsets/expect fields the synthesizer writes — the output
    # replays through tests/test_kafka_transcripts.py unchanged.
    return {
        "source": "live-broker",
        "broker": broker,
        "topic": TOPIC,
        "exchanges": {
            _API_NAMES.get(k, str(k)): _annotate(v)
            for k, v in sorted(captured.items())
        },
    }


def main() -> int:
    mode = sys.argv[1] if len(sys.argv) > 1 else "synth"
    if mode == "synth":
        doc = synthesize()
    elif mode == "record":
        import os

        broker = os.environ.get("ORYX_KAFKA_BROKER")
        if not broker:
            print("set ORYX_KAFKA_BROKER=host:port", file=sys.stderr)
            return 2
        doc = record_live(
            broker, int(os.environ.get("ORYX_KAFKA_PROXY_PORT", "19092"))
        )
    else:
        print(__doc__)
        return 2
    OUT.parent.mkdir(parents=True, exist_ok=True)
    OUT.write_text(json.dumps(doc, indent=1))
    print(f"wrote {OUT} ({doc['source']})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
