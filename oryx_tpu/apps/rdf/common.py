"""Shared RDF app pieces: config, artifact codec, the host-side model.

The reference spreads this across app/oryx-app-common .../rdf/ (pointer
trees, decisions, predictions) and .../rdf/RDFPMMLUtils.java (PMML
round-trip). Here a model is the dense array `Forest` (oryx_tpu/ops/rdf)
plus the bin edges and categorical value encodings needed to take a raw
CSV datum to binned predictor space; mutation (speed-tier "UP" messages)
edits leaf count/stat rows in place — the CategoricalPrediction.update /
NumericPrediction.update semantics (app/oryx-app-common .../classreg/
predict/{Categorical,Numeric}Prediction.java) without per-node objects.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np

from oryx_tpu.common.artifact import ModelArtifact
from oryx_tpu.common.config import Config
from oryx_tpu.common.text import parse_input_line
from oryx_tpu.ops.rdf import (
    Forest,
    bin_column,
    heap_to_node_id,
    node_id_to_heap,
    predict_class_probs,
    predict_regression,
    route_binned,
)
from oryx_tpu.apps.schema import CategoricalValueEncodings, InputSchema


@dataclass
class RDFConfig:
    num_trees: int
    max_split_candidates: object  # hyperparam range values
    max_depth: object
    impurity: object
    # featureSubsetStrategy (reference RDFUpdate.java:143-165): "auto",
    # "all", "sqrt", "log2", "onethird", or an explicit integer
    feature_subset: object

    @classmethod
    def from_config(cls, config: Config) -> "RDFConfig":
        g = lambda key, d=None: config.get(f"oryx.rdf.{key}", d)
        return cls(
            num_trees=int(g("num-trees", 20)),
            max_split_candidates=g("hyperparams.max-split-candidates", 100),
            max_depth=g("hyperparams.max-depth", 8),
            impurity=g("hyperparams.impurity", "entropy"),
            feature_subset=g("hyperparams.feature-subset", "auto"),
        )


# ---------------------------------------------------------------------------
# artifact codec
# ---------------------------------------------------------------------------

def forest_to_artifact(
    forest: Forest,
    edges: list[np.ndarray | None],
    n_bins: np.ndarray,
    encodings: CategoricalValueEncodings,
    schema: InputSchema,
    hyperparams: dict,
) -> ModelArtifact:
    """Forest + binning + encodings -> self-describing artifact (plays the
    role of the PMML MiningModel RDFUpdate.java:167-175 emits)."""
    p = len(n_bins)
    max_edges = max((len(e) for e in edges if e is not None), default=0)
    edge_mat = np.full((p, max_edges), np.nan, dtype=np.float32)
    for j, e in enumerate(edges):
        if e is not None and len(e):
            edge_mat[j, : len(e)] = e
    tensors = {
        "feature": forest.feature,
        "split_left": forest.split_left.astype(np.uint8),
        "edges": edge_mat,
        "n_bins": np.asarray(n_bins, dtype=np.int32),
    }
    if forest.is_classification:
        tensors["class_counts"] = forest.class_counts
    else:
        tensors["leaf_stats"] = forest.leaf_stats
    art = ModelArtifact(
        "rdf",
        extensions={k: str(v) for k, v in hyperparams.items()},
        tensors=tensors,
    )
    art.content["maxDepth"] = int(forest.max_depth)
    art.content["numTrees"] = int(forest.num_trees)
    art.content["categorical"] = [
        bool(schema.is_categorical(schema.predictor_to_feature_index(j)))
        for j in range(p)
    ]
    art.content["encodings"] = encodings.to_content()
    art.content["featureNames"] = schema.feature_names
    art.content["importances"] = [float(v) for v in forest.feature_importances]
    return art


def artifact_to_model(art: ModelArtifact, schema: InputSchema) -> "RDFModel":
    feature = np.asarray(art.tensors["feature"])
    split_left = np.asarray(art.tensors["split_left"]).astype(bool)
    n_bins = np.asarray(art.tensors["n_bins"])
    edge_mat = np.asarray(art.tensors["edges"])
    categorical = art.content["categorical"]
    edges: list[np.ndarray | None] = []
    for j in range(len(n_bins)):
        if categorical[j]:
            edges.append(None)
        else:
            e = edge_mat[j][: int(n_bins[j]) - 1] if edge_mat.size else np.empty(0)
            edges.append(np.asarray(e, dtype=np.float32))
    class_counts = art.tensors.get("class_counts")
    leaf_stats = art.tensors.get("leaf_stats")
    forest = Forest(
        feature=feature,
        split_left=split_left,
        class_counts=None if class_counts is None else np.asarray(class_counts),
        leaf_stats=None if leaf_stats is None else np.asarray(leaf_stats),
        feature_importances=np.asarray(art.content.get("importances", [])),
        max_depth=int(art.content["maxDepth"]),
    )
    encodings = CategoricalValueEncodings.from_content(art.content["encodings"])
    return RDFModel(forest, edges, n_bins, encodings, schema)


# ---------------------------------------------------------------------------
# host model
# ---------------------------------------------------------------------------

class RDFModel:
    """Forest + binning + encodings; thread-safe leaf mutation for the
    speed/serving consume path."""

    def __init__(
        self,
        forest: Forest,
        edges: list[np.ndarray | None],
        n_bins: np.ndarray,
        encodings: CategoricalValueEncodings,
        schema: InputSchema,
    ):
        self.forest = forest
        self.edges = edges
        self.n_bins = np.asarray(n_bins)
        self.encodings = encodings
        self.schema = schema
        self._lock = threading.Lock()

    # -- vectorization -----------------------------------------------------

    def rows_to_matrix(self, rows: list[list[str]]) -> tuple[np.ndarray, np.ndarray]:
        """Parsed rows -> (predictors [N,P] f32 with NaN missing, target)."""
        from oryx_tpu.apps.schema import encode_matrix

        return encode_matrix(self.schema, self.encodings, rows)

    def bin_matrix(self, x: np.ndarray) -> np.ndarray:
        binned = np.empty_like(x, dtype=np.int32)
        for j in range(x.shape[1]):
            binned[:, j] = bin_column(x[:, j], self.edges[j], int(self.n_bins[j]))
        return binned

    def datum_to_binned(self, datum: str) -> np.ndarray:
        # rows shorter than the schema (e.g. no target column) are fine:
        # encode_matrix NaN-fills any cell the row does not cover
        x, _ = self.rows_to_matrix([parse_input_line(datum)])
        return self.bin_matrix(x)

    # -- prediction --------------------------------------------------------

    def predict_datum(self, datum: str):
        """-> (predicted value/category string, probability dist or None)."""
        binned = self.datum_to_binned(datum)
        with self._lock:
            if self.forest.is_classification:
                probs = predict_class_probs(self.forest, binned)[0]
                code = int(np.argmax(probs))
                value = self.encodings.decode(self.schema.target_index, code)
                return value, probs
            value = float(predict_regression(self.forest, binned)[0])
            return value, None

    def terminal_nodes(self, binned: np.ndarray) -> np.ndarray:
        """[T, N] terminal heap slots."""
        with self._lock:
            return route_binned(
                self.forest.feature,
                self.forest.split_left,
                binned,
                self.forest.max_depth,
            )

    # -- speed/serving mutation (UP messages) ------------------------------

    def update_classification_leaf(
        self, tree: int, node_id: str, counts: dict[str, int]
    ) -> None:
        """Add per-class-encoding counts to a terminal node
        (CategoricalPrediction.update via RDFServingModelManager.java:69-76)."""
        slot = node_id_to_heap(node_id)
        with self._lock:
            for enc, count in counts.items():
                self.forest.class_counts[tree, slot, int(enc)] += int(count)

    def update_regression_leaf(
        self, tree: int, node_id: str, mean: float, count: int
    ) -> None:
        """Fold a (mean, count) summary into a terminal node's running mean
        (NumericPrediction.update via RDFServingModelManager.java:77-82)."""
        slot = node_id_to_heap(node_id)
        with self._lock:
            stats = self.forest.leaf_stats[tree, slot]
            stats[0] += count
            stats[1] += mean * count

    def feature_importance(self) -> list[float]:
        return [float(v) for v in self.forest.feature_importances]


def tokens_to_features(schema: InputSchema, tokens: list[str]) -> tuple[dict, str | None]:
    """CSV tokens -> ({feature name: raw token}, target token or None) for
    predicate-tree evaluation of imported PMML forests. Inactive/target/
    empty fields are omitted from the feature dict."""
    names = schema.feature_names
    features: dict = {}
    target: str | None = None
    for i, tok in enumerate(tokens):
        if i >= len(names):
            break
        if schema.is_target(i):
            target = tok if tok != "" else None
        elif schema.is_active(i) and tok != "":
            features[names[i]] = tok
    return features, target


def node_id(slot: int) -> str:
    return heap_to_node_id(slot)
