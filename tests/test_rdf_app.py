"""RDF application-tier tests: batch build + eval for classification and
regression (with categorical predictors), speed-tier terminal-node stats
on the reference wire format, serving-side live leaf updates, and the
classreg REST surface over a real HTTP server."""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from oryx_tpu.apps.rdf.batch import RDFUpdate
from oryx_tpu.apps.rdf.serving import RDFServingModelManager
from oryx_tpu.apps.rdf.speed import RDFSpeedModelManager
from oryx_tpu.bus.api import KeyMessage
from oryx_tpu.bus.broker import get_broker, topics
from oryx_tpu.bus.inproc import InProcBroker
from oryx_tpu.common.config import load_config
from oryx_tpu.common.ioutil import choose_free_port
from oryx_tpu.common.rng import RandomManager
from oryx_tpu.serving.server import ServingLayer


@pytest.fixture(autouse=True)
def _fresh():
    RandomManager.use_test_seed()
    InProcBroker.reset_all()
    yield
    InProcBroker.reset_all()


def _cls_cfg(port=0):
    return load_config(overlay={
        "oryx.id": "rdft",
        "oryx.input-topic.broker": "mem://rdft",
        "oryx.update-topic.broker": "mem://rdft",
        "oryx.serving.api.port": port,
        "oryx.serving.model-manager-class":
            "oryx_tpu.apps.rdf.serving.RDFServingModelManager",
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.classreg",
        ],
        "oryx.input-schema.feature-names": ["size", "color", "label"],
        "oryx.input-schema.numeric-features": ["size"],
        "oryx.input-schema.target-feature": "label",
        "oryx.rdf.num-trees": 8,
        "oryx.rdf.hyperparams.max-depth": 5,
        "oryx.ml.eval.test-fraction": 0.2,
    })


def _reg_cfg():
    return load_config(overlay={
        "oryx.id": "rdfr",
        "oryx.input-schema.feature-names": ["a", "b", "y"],
        "oryx.input-schema.numeric-features": ["a", "b", "y"],
        "oryx.input-schema.target-feature": "y",
        "oryx.rdf.num-trees": 8,
        "oryx.rdf.hyperparams.max-depth": 6,
    })


def _cls_lines(n=600, seed=0):
    """label = banana iff (size>0.5) xor (color==red)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        size = rng.random()
        color = rng.choice(["red", "green", "blue"])
        label = "banana" if (size > 0.5) ^ (color == "red") else "apple"
        out.append(KeyMessage(None, f"{size:.4f},{color},{label}"))
    return out


def _reg_lines(n=800, seed=1):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        a, b = rng.random(), rng.random()
        y = 3 * a - 2 * b
        out.append(KeyMessage(None, f"{a:.4f},{b:.4f},{y:.4f}"))
    return out


def _hp(cfg):
    return {
        "max-split-candidates": 32,
        "max-depth": cfg.get_int("oryx.rdf.hyperparams.max-depth", 8),
        "impurity": "entropy",
    }


def test_classification_build_and_evaluate():
    cfg = _cls_cfg()
    upd = RDFUpdate(cfg)
    data = _cls_lines()
    art = upd.build_model(data, _hp(cfg))
    assert art.content["numTrees"] == 8
    assert set(art.content["encodings"]["1"]) == {"red", "green", "blue"}
    acc = upd.evaluate(art, data, _cls_lines(200, seed=7))
    assert acc > 0.9


def test_regression_build_and_evaluate():
    cfg = _reg_cfg()
    upd = RDFUpdate(cfg)
    art = upd.build_model(_reg_lines(), _hp(cfg))
    neg_rmse = upd.evaluate(art, [], _reg_lines(200, seed=9))
    assert -neg_rmse < 0.5  # y spans roughly [-2, 3]


def test_speed_manager_emits_terminal_node_stats():
    cfg = _cls_cfg()
    art = RDFUpdate(cfg).build_model(_cls_lines(), _hp(cfg))
    mgr = RDFSpeedModelManager(cfg)
    assert mgr.build_updates([KeyMessage(None, "0.9,red,apple")]) == []  # no model
    mgr.consume_key_message("MODEL", art.to_string())
    ups = mgr.build_updates([KeyMessage(None, "0.9,red,apple")] * 5)
    assert len(ups) == 8  # one terminal node per tree
    for key, u in ups:
        assert key == "UP"  # SpeedLayer publishes (key, message) pairs
        tree, node_id, counts = json.loads(u)
        assert 0 <= tree < 8
        assert node_id.startswith("r") and set(node_id[1:]) <= {"-", "+"}
        assert sum(counts.values()) == 5
    mgr.consume_key_message("UP", ups[0][1])  # ignored, no error


def test_serving_applies_leaf_updates():
    cfg = _cls_cfg()
    art = RDFUpdate(cfg).build_model(_cls_lines(), _hp(cfg))
    mgr = RDFServingModelManager(cfg)
    mgr.consume_key_message("UP", json.dumps([0, "r", {"0": 1}]))  # pre-model noop
    mgr.consume_key_message("MODEL", art.to_string())
    model = mgr.get_model()
    value, probs = model.predict("0.9,red,")
    assert value == "apple" and probs is not None
    # flood one datum's terminal nodes with banana counts via speed messages
    banana_code = model.rdf.encodings.encode(2, "banana")
    speed = RDFSpeedModelManager(cfg)
    speed.consume_key_message("MODEL", art.to_string())
    for _, u in speed.build_updates(
        [KeyMessage(None, "0.9,red,banana")] * 500
    ):
        mgr.consume_key_message("UP", u)
    value_after, _ = model.predict("0.9,red,")
    assert value_after == "banana"
    dist = model.classification_distribution("0.9,red,")
    assert dist["banana"] > dist["apple"]
    assert banana_code in (0, 1)


def _http(method, url, body=None):
    req = urllib.request.Request(
        url, method=method, data=body, headers={"Accept": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_classreg_rest_surface():
    port = choose_free_port()
    cfg = _cls_cfg(port)
    topics.maybe_create("mem://rdft", cfg.get_string("oryx.input-topic.message.topic"), 1)
    topics.maybe_create("mem://rdft", cfg.get_string("oryx.update-topic.message.topic"), 1)
    broker = get_broker("mem://rdft")
    art = RDFUpdate(cfg).build_model(_cls_lines(), _hp(cfg))
    broker.send(
        cfg.get_string("oryx.update-topic.message.topic"), "MODEL", art.to_string()
    )
    with ServingLayer(cfg) as layer:
        base = f"http://127.0.0.1:{port}"
        for _ in range(100):
            try:
                if _http("GET", f"{base}/ready")[0] == 200:
                    break
            except Exception:
                pass
            time.sleep(0.1)
        s, body = _http("GET", f"{base}/predict/0.9,red,")
        assert s == 200 and json.loads(body) in ("apple", "banana")
        s, body = _http("POST", f"{base}/predict", b"0.9,red,\n0.1,red,\n")
        assert s == 200 and len(json.loads(body)) == 2
        s, body = _http("GET", f"{base}/classificationDistribution/0.9,red,")
        assert s == 200
        dist = dict((k, v) for k, v in json.loads(body))
        assert abs(sum(dist.values()) - 1.0) < 1e-5
        s, body = _http("GET", f"{base}/feature/importance")
        assert s == 200 and len(json.loads(body)) == 2
        s, body = _http("GET", f"{base}/feature/importance/0")
        assert s == 200
        s, body = _http("GET", f"{base}/feature/importance/9")
        assert s == 400
        s, _ = _http("POST", f"{base}/train/0.5,blue,apple")
        assert s == 200
        in_topic = cfg.get_string("oryx.input-topic.message.topic")
        recs = broker.read(in_topic, 0, 0, 10)
        assert any(m == "0.5,blue,apple" for _, _, m in recs)


def test_classreg_console_section():
    port = choose_free_port()
    cfg = _cls_cfg(port)
    topics.maybe_create("mem://rdft", cfg.get_string("oryx.input-topic.message.topic"), 1)
    topics.maybe_create("mem://rdft", cfg.get_string("oryx.update-topic.message.topic"), 1)
    broker = get_broker("mem://rdft")
    art = RDFUpdate(cfg).build_model(_cls_lines(), _hp(cfg))
    broker.send(cfg.get_string("oryx.update-topic.message.topic"), "MODEL", art.to_string())
    with ServingLayer(cfg):
        base = f"http://127.0.0.1:{port}"
        for _ in range(100):
            try:
                if _http("GET", f"{base}/ready")[0] == 200:
                    break
            except Exception:
                pass
            time.sleep(0.1)
        s, html = _http("GET", f"{base}/console")
        assert s == 200
        # section CONTENT, not just chrome: the target feature name, the
        # model type row, and at least one per-feature importance row
        assert "label" in html and "classification" in html
        assert "importance: " in html and "error" not in html
