"""Nightly 25M-scale quality gate (round-2 verdict #8).

The bf16 singularity guard (ops/als.py _half_step: jitter-retry on a
non-finite Cholesky, zero what still fails) fixed a real NaN poisoning
observed only at ML-25M scale — one marginal system rounded indefinite
by bf16 einsum inputs NaN'd gram() and with it the whole next half-sweep
(reference analogue: Solver.java's ill-conditioned check). A CI-sized
run can't reach the failure regime, so this gate runs the full 25M-shape
build at reduced sweeps on CPU, env-gated:

    ORYX_NIGHTLY=1 python -m pytest tests/test_quality_gate.py -q

Floors: AUC >= 0.87 — measured 0.9019 on this host (2026-07-30, full
25M shape, 3 sweeps, bf16, CPU, 108 s end-to-end, nan_rows 0), matching
the round-2 healthy-window ~0.90 at 10 sweeps; a NaN-poisoned or
guard-shredded build lands far below (a zeroed factor row scores 0
everywhere).
nan_rows == 0 always — the guard must REPAIR (jitter-retry), and any row
it zeroes re-enters the next half-sweep, so a persistent NaN/zeroed row
in the final factors means the guard regressed.
"""

import os

import pytest

nightly = pytest.mark.skipif(
    not os.environ.get("ORYX_NIGHTLY"),
    reason="25M-shape quality gate: minutes of CPU; set ORYX_NIGHTLY=1",
)

AUC_FLOOR = 0.87
ML25M_SHAPE = dict(n_users=162_000, n_items=59_000, nnz=25_000_000)


@nightly
def test_25m_shape_bf16_quality_floor():
    from oryx_tpu.ml.quality import build_and_evaluate

    rep = build_and_evaluate(
        **ML25M_SHAPE,
        features=50,
        iterations=3,  # reduced sweeps: enough to enter the bf16 failure
        # regime the guard exists for, without the full 10-sweep cost
        compute_dtype="bfloat16",
        seed=7,
    )
    assert rep.nan_rows == 0, (
        f"{rep.nan_rows} NaN factor rows — the _half_step singularity "
        f"guard regressed"
    )
    assert rep.auc >= AUC_FLOOR, (
        f"AUC {rep.auc:.4f} < floor {AUC_FLOOR} at 25M shape "
        f"(healthy ~0.90; NaN/zeroed rows or a trainer regression)"
    )


def test_quality_harness_smoke():
    """Always-on smoke at toy scale: the gate's harness itself must keep
    working between nightly runs (import path, report fields, AUC well
    above chance on structured data)."""
    from oryx_tpu.ml.quality import build_and_evaluate

    rep = build_and_evaluate(
        n_users=1200, n_items=800, nnz=60_000, features=16, iterations=4,
        compute_dtype="bfloat16", seed=3, sample_users=300,
    )
    assert rep.nan_rows == 0
    assert rep.auc > 0.70
    assert rep.build_s > 0 and rep.timings.get("train_flops", 0) > 0


# ---- RDF + k-means gates (round-3 verdict #5) ---------------------------
# Floors calibrated on this host (2026-07-30, CPU, seeds noted inline);
# each harness is the SAME code the bench's kmeans+rdf stage runs, so a
# trainer regression fails both the gate and the bench artifact.

RDF_ACC_FLOOR = 0.88  # raised round 5 with the feature_subset=14 default.
# Evidence: sqrt-auto measured 0.8813 at full covertype shape (2026-07-30,
# CPU, 905 s); subset 14 measured 0.8986 vs auto 0.8943 at 100k-example
# scale (round-5 sweep, ml/quality.py docstring). Each round's full-shape
# run lands in QUALITY_r{N}.json. Ceiling with 10% label noise is
# 1 - 0.1*(1 - 1/7) = 0.914
KMEANS_SSE_RATIO_CEIL = 1.05  # measured 1.000 across 5 seeds after the
# maximin reduction fix; the pre-fix k-means|| lost blobs at 1.7 - 4.2x
KMEANS_SIL_FLOOR = 0.5  # measured 0.74 at the toy shape


@nightly
def test_rdf_covertype_shape_accuracy_floor():
    """Planted-rule forest at UCI-covertype shape (581k x 54, 7 classes,
    BASELINE.json config #3; reference eval RDFUpdate.java:179-205). The
    rule is axis-aligned-representable, so accuracy near the noise
    ceiling measures the TRAINER (histogram splits, bootstrap, feature
    subsets), not concept difficulty."""
    from oryx_tpu.common.rng import RandomManager
    from oryx_tpu.ml.quality import build_and_evaluate_rdf

    RandomManager.use_test_seed(1)
    rep = build_and_evaluate_rdf(num_trees=10)
    assert rep.accuracy >= RDF_ACC_FLOOR, (
        f"accuracy {rep.accuracy:.4f} < floor {RDF_ACC_FLOOR} at covertype "
        f"shape (ceiling ~0.914 at 10% label noise)"
    )


@nightly
def test_kmeans_planted_blob_floors():
    """Planted Gaussian blobs at bench scale (reference eval strategies
    KMeansUpdate.java:137-173). SSE within 5% of the generating centers
    and a healthy silhouette — the k-means|| reduction bug this gate was
    built against cost 1.7-4.2x SSE by losing whole blobs."""
    from oryx_tpu.common.rng import RandomManager
    from oryx_tpu.ml.quality import build_and_evaluate_kmeans

    RandomManager.use_test_seed(1)
    rep = build_and_evaluate_kmeans(
        n_points=1_000_000, dims=20, k=50, iterations=10
    )
    assert rep.sse_ratio <= KMEANS_SSE_RATIO_CEIL, (
        f"SSE {rep.sse_ratio:.3f}x the planted centers "
        f"(> {KMEANS_SSE_RATIO_CEIL}): clusters lost or Lloyd regressed"
    )
    assert rep.silhouette >= KMEANS_SIL_FLOOR


def test_rdf_quality_harness_smoke():
    """Always-on toy-scale smoke of the RDF gate harness."""
    from oryx_tpu.common.rng import RandomManager
    from oryx_tpu.ml.quality import build_and_evaluate_rdf

    RandomManager.use_test_seed(1)
    rep = build_and_evaluate_rdf(
        n_examples=8_000, num_trees=4, max_depth=6, feature_subset="auto"
    )
    # 4 trees x mtry sqrt(54) only partially expresses the 4-feature rule
    # at toy scale (measured 0.52); chance is 1/7 = 0.143, so 0.4 still
    # catches a broken trainer while keeping the always-on smoke cheap
    assert rep.accuracy > 0.40
    assert rep.build_s > 0


def test_kmeans_quality_harness_smoke():
    """Always-on toy-scale smoke of the k-means gate harness — tight
    floors even at toy scale: blob recovery is exact when the init works."""
    from oryx_tpu.common.rng import RandomManager
    from oryx_tpu.ml.quality import build_and_evaluate_kmeans

    RandomManager.use_test_seed(1)
    rep = build_and_evaluate_kmeans(n_points=50_000, dims=20, k=12, iterations=8)
    assert rep.sse_ratio <= 1.05
    assert rep.silhouette >= 0.5


# ---- seq next-item gate (PR 10: the fourth packaged app) ----------------
# Planted-successor sessions (ml/quality.py synthesize_sessions): the
# walk follows a hidden permutation with p=0.85, so ~0.85 is the
# achievable ceiling and chance is k/V. Calibrated 2026-08-03 on this
# host: 0.819 at the full gate shape (2000 items, 3000 sessions, 12
# epochs, 27 s CPU), 0.885 at toy shape — a broken windowing, a
# mis-gathered embedding table, or a GRU cell regression lands near
# chance (0.005), far below the floor.

SEQ_HIT_RATE_FLOOR = 0.65


@nightly
def test_seq_next_item_hit_rate_floor():
    from oryx_tpu.common.rng import RandomManager
    from oryx_tpu.ml.quality import build_and_evaluate_seq

    RandomManager.use_test_seed(1)
    rep = build_and_evaluate_seq()
    assert rep.hit_rate >= SEQ_HIT_RATE_FLOOR, (
        f"hit-rate@{rep.k} {rep.hit_rate:.4f} < floor {SEQ_HIT_RATE_FLOOR} "
        f"(ceiling ~0.85 at follow_p=0.85, chance {rep.chance:.4f})"
    )


def test_seq_quality_harness_smoke():
    """Always-on toy-scale smoke of the seq gate harness (the same code
    path bench's seq stage and the nightly gate run)."""
    from oryx_tpu.common.rng import RandomManager
    from oryx_tpu.ml.quality import build_and_evaluate_seq

    RandomManager.use_test_seed(1)
    rep = build_and_evaluate_seq(
        n_items=200, n_sessions=300, session_len=8, dim=16, epochs=6
    )
    assert rep.hit_rate > 0.5, (
        f"toy hit-rate@{rep.k} {rep.hit_rate:.4f} near chance "
        f"({rep.chance:.3f}) — windowing or trainer regressed"
    )
    assert rep.examples > 0 and rep.build_s > 0


# ---- serving score-mode recall gate (PR 8) ------------------------------
# The quantized (int8 + exact rescore) and approx (partial-reduce) score
# modes must hold recall@10 >= 0.95 against the exact top-k on the
# standing corpus — speed can never silently buy wrong answers. Tier-1
# (always on): the gate is CPU-cheap, and the CPU run regression-guards
# the quantized claim everywhere even where approx_max_k computes exactly.


def test_score_mode_recall_gate():
    from oryx_tpu.ml.quality import (
        MIN_SCORE_MODE_RECALL,
        evaluate_score_mode_recall,
    )

    rep = evaluate_score_mode_recall(n_items=40_000, n_queries=128)
    assert rep.min_recall == MIN_SCORE_MODE_RECALL == 0.95
    assert rep.recall_quantized >= rep.min_recall, (
        f"quantized recall@{rep.k} {rep.recall_quantized:.4f} below the "
        f"{rep.min_recall} gate — int8 selection + exact rescore regressed"
    )
    assert rep.recall_approx >= rep.min_recall, (
        f"approx recall@{rep.k} {rep.recall_approx:.4f} below the "
        f"{rep.min_recall} gate"
    )
    assert rep.green


@nightly
def test_score_mode_recall_gate_full_corpus():
    """The nightly-scale corpus (the same configuration
    tools/quality_nightly.py records in the QUALITY artifact)."""
    from oryx_tpu.ml.quality import evaluate_score_mode_recall

    rep = evaluate_score_mode_recall()
    assert rep.green, (
        f"score-mode recall gate RED: quantized {rep.recall_quantized:.4f} "
        f"approx {rep.recall_approx:.4f} (floor {rep.min_recall})"
    )
