"""Honest-labeling and MFU-accounting contracts for the bench harness.

Round-2 verdict: a CPU-fallback artifact must never wear a TPU metric's
name (it reported a 100k-item cpu run as als_recommend_http_qps_1M_...
with vs_baseline computed against the 1M-item baseline), and no MFU
accounting existed anywhere. These pin the fixed behavior.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402  (repo-root module, no jax at import time)
from oryx_tpu.ops import flops  # noqa: E402


def test_items_label():
    assert bench._items_label(1_000_000) == "1M"
    assert bench._items_label(25_000_000) == "25M"
    assert bench._items_label(100_000) == "100k"
    assert bench._items_label(1234) == "1234"


def test_metric_name_carries_true_scale_and_platform():
    assert (
        bench._metric_name("als_recommend_http_qps", 1_000_000, 50, "tpu")
        == "als_recommend_http_qps_1M_items_50f"
    )
    # the degraded path must be visibly degraded
    assert (
        bench._metric_name("als_recommend_http_qps", 100_000, 50, "cpu")
        == "als_recommend_http_qps_100k_items_50f_cpu"
    )


def test_vs_baseline_null_on_config_mismatch():
    # matches the 1M x 50f row the 437-qps baseline was measured at
    assert bench._vs_baseline(874.0, 1_000_000, 50) == 2.0
    # any other scale: not like-for-like -> null
    assert bench._vs_baseline(703.0, 100_000, 50) is None
    assert bench._vs_baseline(160.0, 1_000_000, 250) is None


def test_bench_imports_no_jax():
    # the orchestration process must never import jax (a wedged tunnel
    # hangs jax.devices() forever in C code)
    assert "jax" not in sys.modules or not hasattr(
        sys.modules.get("bench"), "jax"
    )


def test_peak_flops_lookup():
    assert flops.peak_flops_for_kind("TPU v5 lite") == 394e12
    assert flops.peak_flops_for_kind("TPU v5e") == 394e12
    assert flops.peak_flops_for_kind("TPU v5p") == 459e12
    assert flops.peak_flops_for_kind("TPU v4") == 275e12
    assert flops.peak_flops_for_kind("TPU v6e") == 918e12
    assert flops.peak_flops_for_kind("TPU v5 lite", "float32") == 197e12
    assert flops.peak_flops_for_kind("Radical New Chip") is None


def test_analytic_flop_counts():
    # serving: one [B,F]x[F,I] matmul
    assert flops.topk_score_flops(1, 1_000_000, 50) == 2 * 1_000_000 * 50
    # ALS half-sweep: 2BPK^2 + 2BPK + fixed-side gram 2MK^2
    b, p, k, m = 1024, 128, 50, 4096
    assert flops.als_halfstep_flops(b, p, k, m) == (
        2 * b * p * k * k + 2 * b * p * k + 2 * m * k * k
    )
    assert flops.mfu(197e12, 394e12) == 0.5
    assert flops.mfu(1.0, None) is None


def test_train_als_reports_flops():
    import numpy as np

    from oryx_tpu.ops.als import aggregate_interactions, train_als

    rng = np.random.default_rng(0)
    users = rng.integers(0, 64, 2000)
    items = rng.integers(0, 48, 2000)
    vals = np.ones(2000)
    data = aggregate_interactions(users, items, vals, implicit=True)
    timings: dict = {}
    train_als(data, features=8, iterations=2, timings=timings)
    assert timings["train_flops"] > 0
    assert timings["train_s"] > 0
    # FLOPs scale linearly with iterations
    t2: dict = {}
    train_als(data, features=8, iterations=4, timings=t2)
    assert abs(t2["train_flops"] / timings["train_flops"] - 2.0) < 1e-9


def test_batcher_accumulates_flops():
    import numpy as np

    from oryx_tpu.serving.batcher import TopKBatcher

    class FakeY:
        shape = (100, 8)

    b = TopKBatcher(device_timeout=60)
    y = np.random.default_rng(1).standard_normal((100, 8)).astype(np.float32)

    # real dispatch through the batcher against a jax array
    import jax.numpy as jnp

    yj = jnp.asarray(y)
    vals, idx = b.submit(np.ones(8, dtype=np.float32), 3, yj, host_mat=y)
    assert len(idx) == 3
    assert b.flops_scored == 2.0 * 1 * 100 * 8
    b.close()


def test_bank_window_tool_extracts_and_guards(tmp_path):
    """tools/bank_window.py turns a window-bench capture into the
    BENCH_TPU_WINDOW artifact bench.py attaches: tpu-only, FINAL-line
    required, never replaced by a less complete capture."""
    import json
    import subprocess
    import sys
    from pathlib import Path

    tool = Path(__file__).resolve().parent.parent / "tools" / "bank_window.py"

    def run(capture_text, round_no="99"):
        cap = tmp_path / "cap.out"
        cap.write_text(capture_text)
        return subprocess.run(
            [sys.executable, str(tool), round_no, str(cap), str(tmp_path)],
            capture_output=True, text=True, timeout=60,
        )

    art = tmp_path / "BENCH_TPU_WINDOW_r99.json"
    good = (
        '{"detail": true, "metric": "m", "value": 2.0}\n'
        '{"final": true, "platform": "tpu", "metric": "m", '
        '"value": 2.0, "vs_baseline": 5.0, "stages_done": 3}\n'
    )
    assert run(good).returncode == 0
    banked = json.loads(art.read_text())
    assert banked["final"]["stages_done"] == 3

    # a WORSE capture (fewer stages) must not replace it
    worse = (
        '{"final": true, "platform": "tpu", "metric": "m", '
        '"value": 1.0, "stages_done": 1}\n'
    )
    assert run(worse).returncode == 0
    assert json.loads(art.read_text())["final"]["stages_done"] == 3

    # a forced-CPU final is not hardware evidence
    cpu = '{"final": true, "platform": "cpu", "value": 9}\n'
    assert run(cpu, "98").returncode == 1
    assert not (art.parent / "BENCH_TPU_WINDOW_r98.json").exists()

    # equal stages but a worse vs_baseline must not replace either
    same_stage_worse = (
        '{"final": true, "platform": "tpu", "metric": "m", '
        '"value": 1.0, "vs_baseline": 0.5, "stages_done": 3}\n'
    )
    assert run(same_stage_worse).returncode == 0
    assert json.loads(art.read_text())["final"]["value"] == 2.0

    # no FINAL line at all
    assert run('{"interim": true}\n', "97").returncode == 1

    # "auto" derives round from existing BENCH_r*.json in out_dir
    (tmp_path / "BENCH_r07.json").write_text("{}")
    assert run(good, "auto").returncode == 0
    assert (tmp_path / "BENCH_TPU_WINDOW_r08.json").exists()


def test_scale_body_chunked_path(monkeypatch, capsys):
    """With the chunking thresholds lowered, the CPU-scale sweep takes
    the chunked scoring path and reports chunk counts — the path the
    20M x 250 row needs on hardware (its one-shot compile crashed the
    remote-compile helper in round 5)."""
    import json as _json

    import bench

    monkeypatch.setattr(bench, "_CHUNK_OVER_BYTES", 64 * 1024)
    monkeypatch.setattr(bench, "_CHUNK_TARGET_BYTES", 32 * 1024)
    bench._bench_scale_body()
    out = capsys.readouterr().out
    last = [ln for ln in out.splitlines() if ln.strip().startswith("{")][-1]
    rows = _json.loads(last)["rows"]
    assert rows and all("error" not in r for r in rows), rows
    chunked_rows = [r for r in rows if r.get("chunked")]
    assert chunked_rows, rows  # 100k x 50f bf16 = 10MB > 64KB: chunked
    assert all(r["qps"] > 0 for r in rows)
