"""Unit tests for the robustness substrate: the fault-injection harness
(common/faults.py), the bounded-retry policy (common/retry.py), and the
dead-letter quarantine (common/quarantine.py)."""

import time

import pytest

from oryx_tpu.bus.api import KeyMessage
from oryx_tpu.common.config import load_config
from oryx_tpu.common.faults import (
    FaultSpec,
    InjectedFault,
    configure_faults,
    get_injector,
)
from oryx_tpu.common.metrics import get_registry
from oryx_tpu.common.quarantine import (
    Quarantine,
    load_quarantined,
    quarantine_files,
)
from oryx_tpu.common.retry import RetryPolicy, retry_call


@pytest.fixture(autouse=True)
def _disarm():
    get_injector().disarm()
    yield
    get_injector().disarm()


# ---- fault harness --------------------------------------------------------

def test_fire_noop_when_disarmed():
    get_injector().fire("bus.produce")  # nothing armed: no-op


def test_error_fault_fires_exactly_count_times():
    inj = get_injector()
    spec = inj.arm("site.a", kind="error", count=2)
    with pytest.raises(InjectedFault):
        inj.fire("site.a")
    with pytest.raises(InjectedFault):
        inj.fire("site.a")
    inj.fire("site.a")  # exhausted: clean pass
    assert spec.fired == 2


def test_injected_fault_is_oserror():
    # retry wrappers classify injected faults as the transient I/O they
    # simulate — the whole point of chaos exercising the REAL retry path
    assert issubclass(InjectedFault, OSError)


def test_after_skips_clean_passes_first():
    inj = get_injector()
    inj.arm("site.b", kind="error", count=1, after=2)
    inj.fire("site.b")
    inj.fire("site.b")
    with pytest.raises(InjectedFault):
        inj.fire("site.b")


def test_latency_fault_sleeps():
    inj = get_injector()
    inj.arm("site.c", kind="latency", count=1, latency_s=0.05)
    t0 = time.monotonic()
    inj.fire("site.c")
    assert time.monotonic() - t0 >= 0.05
    t0 = time.monotonic()
    inj.fire("site.c")  # count exhausted: no sleep
    assert time.monotonic() - t0 < 0.05


def test_probabilistic_fault_is_seeded_deterministic():
    def run(seed: int) -> list[bool]:
        inj = get_injector()
        inj.disarm()
        inj._seed = seed
        inj._rng = None
        inj.arm("site.p", kind="error", count=-1, probability=0.5)
        out = []
        for _ in range(32):
            try:
                inj.fire("site.p")
                out.append(False)
            except InjectedFault:
                out.append(True)
        inj.disarm()
        return out

    a, b = run(7), run(7)
    assert a == b  # same seed, same sequence
    assert any(a) and not all(a)  # actually probabilistic


def test_bad_kind_rejected():
    with pytest.raises(ValueError):
        FaultSpec(site="x", kind="explode")


def test_configure_from_config_plan():
    cfg = load_config(overlay={
        "oryx.monitoring.faults.enabled": True,
        "oryx.monitoring.faults.plan": [
            {"site": "bus.produce", "kind": "error", "count": 3},
        ],
    })
    configure_faults(cfg)
    spec = get_injector().spec("bus.produce")
    assert spec is not None and spec.count == 3 and spec.kind == "error"
    # a disabled config disarms everything armed before it
    configure_faults(load_config())
    assert get_injector().spec("bus.produce") is None
    assert not get_injector().enabled


def test_injection_metric_counts():
    inj = get_injector()
    c = get_registry().counter("oryx_fault_injections_total")
    before = c.value(site="site.m", kind="error")
    inj.arm("site.m", kind="error", count=1)
    with pytest.raises(InjectedFault):
        inj.fire("site.m")
    assert c.value(site="site.m", kind="error") == before + 1


# ---- retry ----------------------------------------------------------------

FAST = RetryPolicy(attempts=4, base_s=0.001, max_s=0.002, deadline_s=5.0)


def test_retry_recovers_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    c = get_registry().counter("oryx_retry_total")
    r0 = c.value(site="t.recover", outcome="retry")
    s0 = c.value(site="t.recover", outcome="recovered")
    assert retry_call("t.recover", flaky, policy=FAST) == "ok"
    assert len(calls) == 3
    assert c.value(site="t.recover", outcome="retry") == r0 + 2
    assert c.value(site="t.recover", outcome="recovered") == s0 + 1


def test_retry_exhausts_and_propagates_last_error():
    def always():
        raise OSError("forever")

    c = get_registry().counter("oryx_retry_total")
    e0 = c.value(site="t.exhaust", outcome="exhausted")
    with pytest.raises(OSError, match="forever"):
        retry_call("t.exhaust", always, policy=FAST)
    assert c.value(site="t.exhaust", outcome="exhausted") == e0 + 1


def test_retry_does_not_retry_deterministic_errors():
    calls = []

    def bad():
        calls.append(1)
        raise ValueError("deterministic")

    with pytest.raises(ValueError):
        retry_call("t.det", bad, policy=FAST)
    assert len(calls) == 1  # no retry for non-transient classes


def test_retry_deadline_bounds_total_time():
    tight = RetryPolicy(attempts=100, base_s=0.05, max_s=0.05, deadline_s=0.1)

    def always():
        raise OSError("x")

    t0 = time.monotonic()
    with pytest.raises(OSError):
        retry_call("t.deadline", always, policy=tight)
    assert time.monotonic() - t0 < 1.0


def test_policy_from_config():
    cfg = load_config(overlay={
        "oryx.monitoring.retry.attempts": 7,
        "oryx.monitoring.retry.base-ms": 10,
    })
    p = RetryPolicy.from_config(cfg)
    assert p.attempts == 7 and p.base_s == 0.01
    assert p.max_s == 2.0  # packaged default


def test_backoff_grows_and_caps():
    p = RetryPolicy(attempts=10, base_s=0.01, max_s=0.04, jitter=0.0)
    assert p.backoff_s(1) == pytest.approx(0.01)
    assert p.backoff_s(2) == pytest.approx(0.02)
    assert p.backoff_s(5) == pytest.approx(0.04)  # capped


# ---- quarantine -----------------------------------------------------------

def test_quarantine_divert_and_replay_roundtrip(tmp_path):
    q = Quarantine(str(tmp_path), "speed")
    recs = [KeyMessage("k1", "u1,i1,5"), KeyMessage(None, "poison{{{")]
    c = get_registry().counter("oryx_quarantined_records_total")
    before = c.value(layer="speed")
    path = q.divert(recs, reason="test")
    assert path is not None and path.exists()
    assert c.value(layer="speed") == before + 2
    # replayable, byte for byte, keys preserved
    back = load_quarantined(path)
    assert back == recs
    assert quarantine_files(str(tmp_path), "speed") == [path]
    assert quarantine_files(str(tmp_path)) == [path]


def test_quarantine_empty_divert_is_noop(tmp_path):
    q = Quarantine(str(tmp_path), "batch")
    assert q.divert([], reason="none") is None
    assert quarantine_files(str(tmp_path)) == []


def test_quarantine_no_partial_files_on_crash(tmp_path, monkeypatch):
    """A crash mid-divert must not leave a half-readable dead letter:
    the tmp file is renamed only after a full fsync'd write."""
    import oryx_tpu.common.quarantine as qmod

    q = Quarantine(str(tmp_path), "speed")

    def boom(src, dst):
        raise OSError("crash before rename")

    monkeypatch.setattr(qmod.os, "replace", boom)
    with pytest.raises(OSError):
        q.divert([KeyMessage(None, "x,y,1")], reason="r")
    # nothing readable landed
    assert quarantine_files(str(tmp_path)) == []
