"""ASan+UBSan build of native/oryxbus, exercised over the native test
corpus (slow; accel/nightly tier).

The native appender/scanner/parser is the one component where a memory
bug corrupts persisted history silently instead of raising — so its test
corpus (appends, batch appends, boundary scans over torn writes, the CSV
interaction parser's edge lines, CRC32C) runs under an
``-fsanitize=address,undefined -fno-sanitize-recover=all`` build
(``ORYX_NATIVE_SANITIZE=1`` in native/oryxbus/Makefile). Any sanitizer
finding aborts the child process and fails the test.

The instrumented .so loads into a stock python via LD_PRELOAD of the
asan runtime; leak detection is off (CPython itself "leaks" by ASan's
accounting), every other check is fatal.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
SRC_DIR = ROOT / "native" / "oryxbus"

pytestmark = pytest.mark.slow


def _toolchain():
    gxx = shutil.which("g++") or shutil.which("c++")
    make = shutil.which("make")
    if gxx is None or make is None:
        pytest.skip("no native toolchain")
    asan = subprocess.run(
        [gxx, "-print-file-name=libasan.so"], capture_output=True, text=True
    ).stdout.strip()
    if not asan or not os.path.isabs(asan) or not Path(asan).exists():
        pytest.skip("libasan runtime not available")
    return gxx, make, asan


# The corpus the sanitized library is driven through — the same surface
# tests/test_bus.py exercises, plus the parser edge lines that stress
# bounds (overlong float tokens, missing trailing newline, torn records).
_CORPUS = r"""
import ctypes, os, struct, sys

sys.path.insert(0, sys.argv[1])
log_path = sys.argv[2]

from oryx_tpu.bus.native import NativeAppender

nat = NativeAppender.load()

# -- append / append_batch -------------------------------------------------
nat.append(log_path, "key1", "native message")
nat.append(log_path, None, "null-key message")
nat.append(log_path, "k", "")  # empty message body

batch = b""
for i in range(64):
    k = f"bk{i}".encode(); m = (f"batch message {i}" * (i % 5 + 1)).encode()
    batch += struct.pack("<i", len(k)) + k + struct.pack("<I", len(m)) + m
nat.append_batch(log_path, batch)

# -- scan (complete log, then a torn trailing write) -----------------------
pos, scanned = nat.scan(log_path, 0)
assert len(pos) == 3 + 64, len(pos)
size = os.path.getsize(log_path)
assert scanned == size, (scanned, size)
with open(log_path, "ab") as f:
    f.write(struct.pack("<i", 4) + b"ke")  # torn record: stop cleanly
pos2, scanned2 = nat.scan(log_path, 0)
assert len(pos2) == len(pos) and scanned2 == size
pos3, _ = nat.scan(log_path, 0, max_records=5)
assert len(pos3) == 5

# -- interaction parser edge lines ----------------------------------------
lines = [
    b"1,2",                       # minimal
    b"3,4,5.5",                   # strength
    b"6,7,,",                     # empty strength = NaN delete marker
    b"8,9,1.0,1700000000.25",     # float ts
    b"07,9",                      # non-canonical id -> ok=0
    b"-0,9",                      # non-canonical -0 -> ok=0
    b'["json","line"]',           # JSON form -> ok=0
    b'"q",1',                     # quoted CSV -> ok=0
    b"10,11," + b"9" * 100,       # >63-char numeric token -> ok=0
    b"12,13," + b"1" * 63,        # 63-char token: exact tmp-buffer edge
    b"",                          # blank: no row
    b"  14,15,2.0  \r",           # trimmed whitespace + CR
    b"99999999999999999999,1",    # >18 digits: overflow guard -> ok=0
]
buf = b"\n".join(lines) + b"\n16,17"  # final line without newline
users, items, vals, tss, ok = nat.parse_interactions(buf)
rows = [ln for ln in lines if ln.strip()] + [b"16,17"]
assert len(users) == len(rows), (len(users), len(rows))
good = {(1, 2), (3, 4), (6, 7), (8, 9), (14, 15), (16, 17), (12, 13)}
parsed = {(int(u), int(it)) for u, it, o in zip(users, items, ok) if o}
assert good == parsed, parsed
assert vals[2] != vals[2]  # NaN delete marker survived

# -- crc32c (hw + sw paths share the dispatch entry) -----------------------
lib = ctypes.CDLL(os.environ["ORYXBUS_LIB"])
lib.oryxbus_crc32c.restype = ctypes.c_uint32
lib.oryxbus_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32]
data = bytes(range(256)) * 33 + b"tail"
crc = lib.oryxbus_crc32c(data, len(data), 0)
assert crc == lib.oryxbus_crc32c(data, len(data), 0)
assert lib.oryxbus_crc32c(b"", 0, 0) == 0

print("sanitized corpus ok")
"""


def test_sanitized_native_corpus(tmp_path):
    gxx, make, asan = _toolchain()
    so = tmp_path / "liboryxbus-san.so"
    build = subprocess.run(
        [make, "-C", str(SRC_DIR), "ORYX_NATIVE_SANITIZE=1", f"SO={so}"],
        capture_output=True, text=True, timeout=120,
    )
    assert build.returncode == 0, build.stdout + build.stderr
    assert so.exists() and so.stat().st_size > 0

    script = tmp_path / "corpus.py"
    script.write_text(_CORPUS, encoding="utf-8")
    env = dict(os.environ)
    env.update({
        "LD_PRELOAD": asan,
        "ORYXBUS_LIB": str(so),
        # leaks off: CPython interns/arenas read as leaks to ASan; every
        # other check stays fatal via -fno-sanitize-recover
        "ASAN_OPTIONS": "detect_leaks=0",
        "UBSAN_OPTIONS": "print_stacktrace=1",
        "JAX_PLATFORMS": "cpu",
    })
    proc = subprocess.run(
        [sys.executable, str(script), str(ROOT), str(tmp_path / "p0.log")],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "sanitized corpus ok" in proc.stdout


def test_default_build_is_warning_clean(tmp_path):
    """The default (unsanitized) build compiles clean under the Makefile's
    -Wall -Wextra -Werror default — warnings stop accumulating."""
    gxx, make, _asan = _toolchain()
    so = tmp_path / "liboryxbus.so"
    build = subprocess.run(
        [make, "-C", str(SRC_DIR), f"SO={so}"],
        capture_output=True, text=True, timeout=120,
    )
    assert build.returncode == 0, build.stdout + build.stderr
    assert "-Werror" in build.stdout
    assert so.exists() and so.stat().st_size > 0
