"""Incremental batch generations: aggregate-snapshot equivalence,
warm-start training, fallback discipline, ingest prefetch commit safety,
and the speed-layer failure counter.

The equivalence tests use dyadic-rational strengths (0.25/0.5/1/2...) and
decay 0.5 so every float operation is EXACT: the assertion is then
bit-identity between the incremental merge and a from-scratch
``aggregate_interactions`` over the concatenated history — semantic
equivalence proven without float-reordering noise.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from oryx_tpu.bus.api import KeyMessage, TopicProducer
from oryx_tpu.bus.broker import get_broker, topics
from oryx_tpu.bus.inproc import InProcBroker
from oryx_tpu.common.config import load_config
from oryx_tpu.common.metrics import get_registry
from oryx_tpu.common.rng import RandomManager
from oryx_tpu.layers.batch import BatchLayer
from oryx_tpu.layers.datastore import (
    LazyPastData,
    load_aggregate_snapshot,
    save_aggregate_snapshot,
    save_generation,
)
from oryx_tpu.ops.als import (
    AggregateState,
    agg_state_fingerprint,
    aggregate_interactions,
    align_factors,
    train_als,
    train_als_warm,
)

_DAY = 86_400_000


@pytest.fixture(autouse=True)
def _fresh_broker():
    InProcBroker.reset_all()
    yield
    InProcBroker.reset_all()


# ---- aggregate-snapshot equivalence ---------------------------------------

def _random_windows(seed, k=5, n=60, users=7, items=6, with_deletes=True):
    """K windows of raw events with dyadic strengths, out-of-order
    timestamps, and NaN delete markers."""
    r = np.random.default_rng(seed)
    windows = []
    for _ in range(k):
        u = np.array([f"u{r.integers(0, users)}" for _ in range(n)], dtype=object)
        i = np.array([f"i{r.integers(0, items)}" for _ in range(n)], dtype=object)
        p = [0.235, 0.235, 0.235, 0.235, 0.06] if with_deletes else [0.25] * 4 + [0.0]
        v = r.choice([0.25, 0.5, 1.0, 2.0, np.nan], size=n, p=p)
        # out-of-order, repeating, multi-day timestamps
        ts = r.integers(0, 20 * _DAY, size=n)
        windows.append((u, i, v, ts))
    return windows


def _merge_windows(windows, *, implicit, with_days, reload_at=None, tmp_path=None):
    """Fold windows through AggregateState, optionally round-tripping the
    state through a persisted snapshot mid-sequence."""
    state = AggregateState.empty(implicit=implicit, with_days=with_days)
    fp = agg_state_fingerprint(implicit=implicit, with_days=with_days)
    for j, (u, i, v, ts) in enumerate(windows):
        state = state.merge(
            AggregateState.from_window(
                u, i, v, ts, implicit=implicit, with_days=with_days
            )
        )
        if reload_at is not None and j == reload_at:
            save_aggregate_snapshot(str(tmp_path), 1000 + j, fp, state.to_arrays())
            loaded = load_aggregate_snapshot(str(tmp_path), fp)
            assert loaded is not None
            state = AggregateState.from_arrays(loaded[1])
    return state


@pytest.mark.parametrize("implicit", [True, False])
@pytest.mark.parametrize("decay", [1.0, 0.5])
@pytest.mark.parametrize("log_strength", [False, True])
def test_incremental_merge_bit_identical_to_from_scratch(
    tmp_path, implicit, decay, log_strength
):
    """The tentpole invariant: incremental merge over K windows — decay,
    deletes, out-of-order timestamps, a mid-sequence snapshot reload —
    materializes bit-identically to aggregate_interactions over the
    concatenated history."""
    with_days = implicit and decay < 1.0
    windows = _random_windows(seed=42)
    state = _merge_windows(
        windows, implicit=implicit, with_days=with_days,
        reload_at=2, tmp_path=tmp_path,
    )
    now_ms = 22 * _DAY + 54321
    view = dict(
        decay_factor=decay, zero_threshold=0.1, now_ms=now_ms,
        log_strength=log_strength, epsilon=0.5,
    )
    got = state.materialize(**view)
    cat = [np.concatenate([w[j] for w in windows]) for j in range(4)]
    want = aggregate_interactions(
        cat[0], cat[1], cat[2], cat[3], implicit=implicit, **view
    )
    assert got.user_ids == want.user_ids
    assert got.item_ids == want.item_ids
    assert np.array_equal(got.users, want.users)
    assert np.array_equal(got.items, want.items)
    assert np.array_equal(got.values, want.values)  # bitwise


def test_delete_marker_kills_pair_across_windows():
    """A NaN delete in window 2 must kill strengths from window 1 AND
    keep the pair dead when window 3 adds more strength — exactly the
    NaN-propagating full-history sum."""
    u = np.array(["a"], dtype=object)
    i = np.array(["x"], dtype=object)
    mk = lambda v: AggregateState.from_window(
        u, i, np.array([v]), np.array([0]), implicit=True
    )
    state = mk(1.0).merge(mk(np.nan)).merge(mk(2.0))
    assert len(state.materialize().values) == 0
    # and the id tables still carry the ids, like the from-scratch path
    assert state.materialize().user_ids == ["a"]


def test_explicit_last_wins_tie_goes_to_newer_window():
    u = np.array(["a"], dtype=object)
    i = np.array(["x"], dtype=object)
    mk = lambda v, ts: AggregateState.from_window(
        u, i, np.array([v]), np.array([ts]), implicit=False
    )
    merged = mk(3.0, 100).merge(mk(5.0, 100))  # same ts: newer window wins
    assert merged.materialize().values[0] == 5.0
    # matches from-scratch (later array position wins on a ts tie)
    ref = aggregate_interactions(
        np.array(["a", "a"], dtype=object), np.array(["x", "x"], dtype=object),
        np.array([3.0, 5.0]), np.array([100, 100]), implicit=False,
    )
    assert ref.values[0] == 5.0


def test_below_threshold_pair_can_come_back():
    """zero-threshold is a view-time filter: a pair filtered out this
    generation must reappear when later windows push it back up."""
    u = np.array(["a"], dtype=object)
    i = np.array(["x"], dtype=object)
    mk = lambda v: AggregateState.from_window(
        u, i, np.array([v]), np.array([0]), implicit=True
    )
    state = mk(0.25)
    assert len(state.materialize(zero_threshold=0.5).values) == 0
    state = state.merge(mk(1.0))
    assert state.materialize(zero_threshold=0.5).values[0] == 1.25


def test_staged_snapshot_invisible_until_finalized(tmp_path):
    """The double-fold crash guard: a snapshot staged during a build must
    not be loadable until the window it folded is persisted+committed
    (finalize). A crash in between re-delivers the window — merging it
    into an already-folded snapshot would double-count strengths."""
    from oryx_tpu.layers.datastore import finalize_aggregate_snapshot

    fp = agg_state_fingerprint(implicit=True, with_days=False)
    u = np.array(["a"], dtype=object)
    i = np.array(["x"], dtype=object)
    s1 = AggregateState.from_window(
        u, i, np.array([1.0]), np.array([0]), implicit=True
    )
    save_aggregate_snapshot(str(tmp_path), 1000, fp, s1.to_arrays())
    s2 = s1.merge(
        AggregateState.from_window(
            u, i, np.array([2.0]), np.array([0]), implicit=True
        )
    )
    save_aggregate_snapshot(str(tmp_path), 2000, fp, s2.to_arrays(), staged=True)
    # crash before finalize: the loadable state is still generation 1000
    ts, _ = load_aggregate_snapshot(str(tmp_path), fp)
    assert ts == 1000
    assert finalize_aggregate_snapshot(str(tmp_path), 2000) is True
    ts, arrays = load_aggregate_snapshot(str(tmp_path), fp)
    assert ts == 2000
    assert AggregateState.from_arrays(arrays).materialize().values[0] == 3.0
    # finalizing again is a no-op
    assert finalize_aggregate_snapshot(str(tmp_path), 2000) is False


def test_crashed_generation_does_not_double_fold(tmp_path):
    """Crash between snapshot stage and window persist: on restart the
    window re-delivers and must fold exactly once."""
    from oryx_tpu.apps.als.batch import ALSUpdate

    RandomManager.use_test_seed(12)
    cfg = _gen_cfg(tmp_path, "g6")
    broker = get_broker("mem://g6")
    rng = np.random.default_rng(6)
    layer = BatchLayer(cfg, update=ALSUpdate(cfg))
    layer.ensure_streams()
    _feed(broker, rng, 300, 1000, users=40, items=25)
    layer.run_generation(timestamp_ms=10_000)
    layer.close()

    # generation 2 "crashes" mid-build: the update stages its fold but
    # the batch layer never persists/commits/finalizes the window
    window = [KeyMessage(None, f"uX,iY,2,{20_000 + j}") for j in range(5)]
    upd_crash = ALSUpdate(cfg)

    class _Null:
        def send(self, *a):
            pass

        def send_batch(self, *a):
            pass

    assert upd_crash.incremental_update(20_000, window, str(tmp_path / "model"), _Null())
    # restart: the staged fold is invisible; re-delivering the window
    # merges it exactly once
    upd2 = ALSUpdate(cfg)
    layer2 = BatchLayer(cfg, update=upd2)
    layer2.ensure_streams()
    for km in window:
        broker.send("OryxInput", None, km.message)
    layer2.run_generation(timestamp_ms=30_000)
    layer2.close()
    state = upd2._agg_state
    mask = (np.asarray(state.user_ids)[state.users] == "uX") & (
        np.asarray(state.item_ids)[state.items] == "iY"
    )
    # 5 events of strength 2, summed once (the generation's 10% temporal
    # holdout keeps the newest event pending, not dropped)
    total = float(np.nansum(state.vals[mask]))
    pend_mask = upd2._agg_pending[0] == "uX"
    total += float(np.nansum(upd2._agg_pending[2][pend_mask]))
    assert total == 10.0


def test_snapshot_schema_mismatch_rejected(tmp_path):
    fp = agg_state_fingerprint(implicit=True, with_days=False)
    state = AggregateState.empty(implicit=True, with_days=False)
    save_aggregate_snapshot(str(tmp_path), 1, fp, state.to_arrays())
    assert load_aggregate_snapshot(str(tmp_path), fp) is not None
    other = agg_state_fingerprint(implicit=False, with_days=False)
    assert load_aggregate_snapshot(str(tmp_path), other) is None


# ---- warm-start training ---------------------------------------------------

def _synth_interactions(seed=1, n=2000, users=60, items=40):
    r = np.random.default_rng(seed)
    u = np.array([f"u{r.integers(0, users)}" for _ in range(n)], dtype=object)
    i = np.array([f"i{r.integers(0, items)}" for _ in range(n)], dtype=object)
    v = r.uniform(0.5, 3.0, n)
    return aggregate_interactions(u, i, v, implicit=True)


def test_align_factors_retains_rows_and_cold_starts_new():
    prev = np.arange(12, dtype=np.float32).reshape(4, 3)
    out = align_factors(["b", "a", "d", "c"], prev, ["a", "c", "e"], 3)
    assert np.array_equal(out[0], prev[1])  # "a"
    assert np.array_equal(out[1], prev[3])  # "c"
    assert out.shape == (3, 3)
    assert not np.allclose(out[2], 0.0)  # new id: cold init, not zeros
    # feature-width change cold-starts
    assert align_factors(["a"], prev, ["a"], 5) is None
    assert align_factors(None, None, ["a"], 3) is None


def test_warm_start_early_stops_and_matches_cold_quality():
    RandomManager.use_test_seed(7)
    data = _synth_interactions()
    cold, it_cold = train_als_warm(
        data, features=8, lam=0.01, alpha=10.0, iterations=10, tol=0.0
    )
    assert it_cold == 10
    warm, it_warm = train_als_warm(
        data, features=8, lam=0.01, alpha=10.0, iterations=10,
        resume_y=cold.y, tol=0.05, min_iterations=2, check_every=2,
    )
    assert it_warm < 10  # converged predictions stop the sweep loop
    # warm-started predictions agree with the cold model's
    p_cold = cold.x @ cold.y.T
    p_warm = warm.x @ warm.y.T
    denom = np.linalg.norm(p_cold) or 1.0
    assert np.linalg.norm(p_warm - p_cold) / denom < 0.2


def test_warm_tol_zero_disables_early_stop():
    data = _synth_interactions(seed=2, n=500)
    m, it = train_als_warm(
        data, features=4, iterations=6, tol=0.0, resume_y=None
    )
    assert it == 6 and m.x.shape[1] == 4


# ---- the wired incremental generation loop ---------------------------------

def _gen_cfg(tmp_path, name, **extra):
    overlay = {
        "oryx.id": name,
        "oryx.input-topic.broker": f"mem://{name}",
        "oryx.update-topic.broker": f"mem://{name}",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.als.hyperparams.features": 5,
        "oryx.als.hyperparams.iterations": 4,
        "oryx.ml.eval.test-fraction": 0.1,
    }
    overlay.update(extra)
    cfg = load_config(overlay=overlay)
    topics.maybe_create(f"mem://{name}", "OryxInput", 2)
    topics.maybe_create(f"mem://{name}", "OryxUpdate", 1)
    return cfg


def _feed(broker, rng, n, base_ts, users=25, items=15):
    for j in range(n):
        u, i = rng.integers(0, users), rng.integers(0, items)
        broker.send(
            "OryxInput", None,
            f"u{u},i{i},{1 + int(rng.poisson(1))},{base_ts + j}",
        )


def _counts():
    c = get_registry().counter("oryx_batch_incremental_total")
    return c.value(kind="full"), c.value(kind="delta")


def test_generation_cycle_full_then_deltas(tmp_path):
    from oryx_tpu.apps.als.batch import ALSUpdate

    RandomManager.use_test_seed(3)
    cfg = _gen_cfg(tmp_path, "g1")
    upd = ALSUpdate(cfg)
    layer = BatchLayer(cfg, update=upd)
    layer.ensure_streams()
    broker = get_broker("mem://g1")
    rng = np.random.default_rng(0)
    f0, d0 = _counts()

    # windows stay well under max-drift-fraction of the aggregate
    _feed(broker, rng, 500, 1000, users=40, items=25)
    layer.run_generation(timestamp_ms=10_000)
    _feed(broker, rng, 50, 20_000, users=40, items=25)
    layer.run_generation(timestamp_ms=30_000)
    _feed(broker, rng, 50, 40_000, users=40, items=25)
    layer.run_generation(timestamp_ms=50_000)
    f1, d1 = _counts()
    assert (f1 - f0, d1 - d0) == (1, 2)  # full only at generation 1

    # a model was published for every generation
    recs = broker.read("OryxUpdate", 0, 0, 100_000)
    assert sum(1 for _, k, _m in recs if k in ("MODEL", "MODEL-REF")) == 3
    assert get_registry().gauge("oryx_batch_aggregate_rows").value() > 0

    # incremental generations never read persisted history
    calls = []
    import oryx_tpu.layers.datastore as ds

    real = ds.load_all_data
    ds.load_all_data = lambda *a, **k: (calls.append(1), real(*a, **k))[1]
    try:
        _feed(broker, rng, 50, 60_000, users=40, items=25)
        layer.run_generation(timestamp_ms=70_000)
    finally:
        ds.load_all_data = real
    assert calls == []
    f2, d2 = _counts()
    assert (f2 - f0, d2 - d0) == (1, 3)
    layer.close()


def test_restart_resumes_incrementally_from_snapshot(tmp_path):
    from oryx_tpu.apps.als.batch import ALSUpdate

    RandomManager.use_test_seed(5)
    cfg = _gen_cfg(tmp_path, "g2")
    broker = get_broker("mem://g2")
    rng = np.random.default_rng(1)
    f0, d0 = _counts()
    layer1 = BatchLayer(cfg, update=ALSUpdate(cfg))
    layer1.ensure_streams()
    _feed(broker, rng, 200, 1000)
    layer1.run_generation(timestamp_ms=10_000)
    layer1.close()
    # fresh process: state reloads from the persisted snapshot
    layer2 = BatchLayer(cfg, update=ALSUpdate(cfg))
    _feed(broker, rng, 60, 20_000)
    layer2.run_generation(timestamp_ms=30_000)
    layer2.close()
    f1, d1 = _counts()
    assert (f1 - f0, d1 - d0) == (1, 1)


def test_stale_snapshot_forces_full_rebuild(tmp_path):
    """A persisted generation NEWER than the snapshot (crash between
    window persist and snapshot write) invalidates the state."""
    from oryx_tpu.apps.als.batch import ALSUpdate

    RandomManager.use_test_seed(6)
    cfg = _gen_cfg(tmp_path, "g3")
    broker = get_broker("mem://g3")
    rng = np.random.default_rng(2)
    f0, d0 = _counts()
    layer = BatchLayer(cfg, update=ALSUpdate(cfg))
    layer.ensure_streams()
    _feed(broker, rng, 200, 1000)
    layer.run_generation(timestamp_ms=10_000)
    # simulate the crash: a window persisted with no snapshot fold
    save_generation(
        str(tmp_path / "data"), 20_000, [KeyMessage(None, "u1,i1,1,19000")]
    )
    layer2 = BatchLayer(cfg, update=ALSUpdate(cfg))
    _feed(broker, rng, 60, 30_000)
    layer2.run_generation(timestamp_ms=40_000)
    f1, d1 = _counts()
    assert f1 - f0 == 2 and d1 - d0 == 0  # the stale state was rejected
    # ...and the full rebuild re-anchored: the next one is a delta
    _feed(broker, rng, 60, 50_000)
    layer2.run_generation(timestamp_ms=60_000)
    f2, d2 = _counts()
    assert f2 - f0 == 2 and d2 - d0 == 1
    layer.close()
    layer2.close()


def test_drift_past_fraction_forces_full_rebuild(tmp_path):
    from oryx_tpu.apps.als.batch import ALSUpdate

    RandomManager.use_test_seed(8)
    cfg = _gen_cfg(
        tmp_path, "g4",
        **{"oryx.batch.storage.incremental.max-drift-fraction": 0.05},
    )
    broker = get_broker("mem://g4")
    rng = np.random.default_rng(3)
    f0, d0 = _counts()
    layer = BatchLayer(cfg, update=ALSUpdate(cfg))
    layer.ensure_streams()
    _feed(broker, rng, 150, 1000)
    layer.run_generation(timestamp_ms=10_000)
    # a window as big as history: far beyond 5% drift
    _feed(broker, rng, 150, 20_000)
    layer.run_generation(timestamp_ms=30_000)
    f1, d1 = _counts()
    assert f1 - f0 == 2 and d1 - d0 == 0
    layer.close()


def test_failed_build_window_not_lost_from_memory_state(tmp_path, monkeypatch):
    """A generation whose training raises AFTER its window was polled
    still gets that window persisted by the batch layer. The next
    generation must NOT trust the in-memory state (which never folded
    it) — it must fall back to a full rebuild that re-reads the window."""
    import oryx_tpu.apps.als.batch as als_batch
    from oryx_tpu.apps.als.batch import ALSUpdate

    RandomManager.use_test_seed(13)
    cfg = _gen_cfg(tmp_path, "g8")
    broker = get_broker("mem://g8")
    rng = np.random.default_rng(7)
    f0, d0 = _counts()
    upd = ALSUpdate(cfg)
    layer = BatchLayer(cfg, update=upd)
    layer.ensure_streams()
    _feed(broker, rng, 400, 1000, users=40, items=25)
    layer.run_generation(timestamp_ms=10_000)

    real = als_batch.train_als_warm
    boom = {"armed": True}

    def flaky(*a, **k):
        if boom.pop("armed", False):
            raise RuntimeError("transient device failure")
        return real(*a, **k)

    monkeypatch.setattr(als_batch, "train_als_warm", flaky)
    # generation 2: the marker event's build fails mid-incremental; the
    # window persists and commits anyway (batch-layer contract)
    broker.send("OryxInput", None, "uLOST,iLOST,4,20000")
    layer.run_generation(timestamp_ms=30_000)
    # generation 3: in-memory state must be declared stale -> full rebuild
    _feed(broker, rng, 40, 40_000, users=40, items=25)
    layer.run_generation(timestamp_ms=50_000)
    f1, d1 = _counts()
    assert f1 - f0 == 2 and d1 - d0 == 0
    # and the re-read history includes the failed generation's event
    state = upd._agg_state
    mask = (np.asarray(state.user_ids)[state.users] == "uLOST") & (
        np.asarray(state.item_ids)[state.items] == "iLOST"
    )
    total = float(np.nansum(state.vals[mask]))
    pend = upd._agg_pending
    total += float(np.nansum(pend[2][pend[0] == "uLOST"]))
    assert total == 4.0
    layer.close()


def test_threshold_withheld_build_still_reanchors_snapshot(tmp_path):
    """An unpublishable (below-threshold) full build must still re-anchor
    the aggregate snapshot — otherwise every following generation repeats
    the O(history) rebuild until eval crosses the threshold."""
    from oryx_tpu.apps.als.batch import ALSUpdate

    RandomManager.use_test_seed(10)
    cfg = _gen_cfg(
        tmp_path, "g7", **{"oryx.ml.eval.threshold": 2.0}  # AUC can't reach
    )
    broker = get_broker("mem://g7")
    rng = np.random.default_rng(5)
    f0, d0 = _counts()
    layer = BatchLayer(cfg, update=ALSUpdate(cfg))
    layer.ensure_streams()
    _feed(broker, rng, 400, 1000, users=40, items=25)
    layer.run_generation(timestamp_ms=10_000)
    _feed(broker, rng, 40, 20_000, users=40, items=25)
    layer.run_generation(timestamp_ms=30_000)
    f1, d1 = _counts()
    assert (f1 - f0, d1 - d0) == (1, 1)  # gen 2 went incremental
    # and nothing was published either generation
    recs = broker.read("OryxUpdate", 0, 0, 100_000)
    assert not any(k in ("MODEL", "MODEL-REF") for _, k, _m in recs)
    layer.close()


def test_incremental_disabled_by_config(tmp_path):
    from oryx_tpu.apps.als.batch import ALSUpdate

    RandomManager.use_test_seed(9)
    cfg = _gen_cfg(
        tmp_path, "g5",
        **{"oryx.batch.storage.incremental.enabled": False},
    )
    broker = get_broker("mem://g5")
    rng = np.random.default_rng(4)
    f0, d0 = _counts()
    layer = BatchLayer(cfg, update=ALSUpdate(cfg))
    layer.ensure_streams()
    _feed(broker, rng, 100, 1000)
    layer.run_generation(timestamp_ms=10_000)
    _feed(broker, rng, 50, 20_000)
    layer.run_generation(timestamp_ms=30_000)
    f1, d1 = _counts()
    assert d1 - d0 == 0 and f1 - f0 == 2
    layer.close()


def test_full_rebuild_cli_flag(capsys):
    from oryx_tpu.cli import main as cli_main

    assert cli_main(["config", "--full-rebuild"]) == 0
    out = capsys.readouterr().out
    assert "oryx.batch.storage.incremental.enabled=false" in out


# ---- ingest prefetch: overlap without losing commit safety -----------------

class _GatedUpdate:
    """BatchLayerUpdate whose build blocks until released, so the test
    can interleave ingest with an in-flight generation."""

    def __init__(self):
        self.started = threading.Event()
        self.release = threading.Event()
        self.calls = []

    def run_update(self, ts, new_data, past_data, model_dir, producer):
        self.calls.append([km.message for km in new_data])
        self.started.set()
        assert self.release.wait(10)


def test_prefetch_drains_during_build_and_survives_crash(tmp_path):
    from oryx_tpu.api import BatchLayerUpdate

    class Gated(_GatedUpdate, BatchLayerUpdate):
        pass

    cfg = _gen_cfg(tmp_path, "pf")
    broker = get_broker("mem://pf")
    upd = Gated()
    layer = BatchLayer(cfg, update=upd)
    layer.ensure_streams()
    broker.send("OryxInput", None, "w1-a")
    broker.send("OryxInput", None, "w1-b")
    t = threading.Thread(
        target=layer.run_generation, kwargs={"timestamp_ms": 10_000}
    )
    t.start()
    assert upd.started.wait(10)
    # records arriving DURING the build: the prefetch thread drains them
    broker.send("OryxInput", None, "w2-a")
    broker.send("OryxInput", None, "w2-b")
    deadline = time.time() + 5
    while len(layer._prefetched) < 2 and time.time() < deadline:
        time.sleep(0.02)
    assert len(layer._prefetched) == 2
    upd.release.set()
    t.join(timeout=10)
    assert sorted(upd.calls[0]) == ["w1-a", "w1-b"]

    # crash before the next generation: a NEW layer (same group) must
    # re-see the prefetched-but-unpersisted records — the explicit
    # window-edge commit must not have covered them
    layer.close()
    upd2 = Gated()
    upd2.release.set()
    layer2 = BatchLayer(cfg, update=upd2)
    layer2.run_generation(timestamp_ms=20_000)
    assert sorted(upd2.calls[0]) == ["w2-a", "w2-b"]
    layer2.close()


def test_prefetched_records_feed_next_window_without_crash(tmp_path):
    from oryx_tpu.api import BatchLayerUpdate

    class Gated(_GatedUpdate, BatchLayerUpdate):
        pass

    cfg = _gen_cfg(tmp_path, "pf2")
    broker = get_broker("mem://pf2")
    upd = Gated()
    layer = BatchLayer(cfg, update=upd)
    layer.ensure_streams()
    broker.send("OryxInput", None, "a")
    t = threading.Thread(
        target=layer.run_generation, kwargs={"timestamp_ms": 10_000}
    )
    t.start()
    assert upd.started.wait(10)
    broker.send("OryxInput", None, "b")
    deadline = time.time() + 5
    while not layer._prefetched and time.time() < deadline:
        time.sleep(0.02)
    upd.release.set()
    t.join(timeout=10)
    layer.run_generation(timestamp_ms=20_000)
    assert upd.calls[0] == ["a"] and upd.calls[1] == ["b"]
    # both windows persisted exactly once
    persisted = LazyPastData(str(tmp_path / "data"))
    assert sorted(km.message for km in persisted) == ["a", "b"]
    layer.close()


# ---- speed-layer failure counter -------------------------------------------

def test_speed_failure_counter_increments_on_rewind(tmp_path):
    from oryx_tpu.api import AbstractSpeedModelManager
    from oryx_tpu.layers.speed import SpeedLayer

    class FailOnce(AbstractSpeedModelManager):
        def __init__(self):
            self.fail_next = True

        def consume_key_message(self, key, message):
            pass

        def build_updates(self, new_data):
            if self.fail_next:
                self.fail_next = False
                raise RuntimeError("transient")
            return []

    cfg = _gen_cfg(tmp_path, "spd")
    broker = get_broker("mem://spd")
    c = get_registry().counter("oryx_speed_failures_total")
    before = c.value()
    layer = SpeedLayer(cfg, manager=FailOnce())
    layer.ensure_streams()
    broker.send("OryxInput", None, "evt")
    layer.run_batch()  # fails inside, rewinds
    assert c.value() == before + 1
    layer.run_batch()  # reprocessed fine
    assert c.value() == before + 1
    layer.close()
