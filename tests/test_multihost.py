"""TRUE multi-host integration: two OS processes join a JAX process group
(jax.distributed over a localhost coordinator, Gloo CPU collectives) and
run the framework's distributed paths across the process boundary.

This executes what tests/test_distributed.py only shape-checks: the
reference scales with one Spark job spanning executor JVMs
(AbstractSparkLayer builds the cluster context; SURVEY.md §5 plane 3);
here the equivalent plane is a jax.distributed process group whose mesh
spans hosts — "data" over DCN, "model" inside a host. Each worker:

  1. joins via init_distributed(config) (the CLI/runtime entry path)
  2. builds the pod-wide hybrid mesh via global_mesh()
  3. computes a Gram matrix with rows sharded across BOTH processes —
     the XLA psum crosses the process boundary (ALS's core collective)
  4. runs ring attention with the sequence ring spanning both processes
     (ppermute over DCN) and checks it against the exact local result
  5. exercises barrier() and host_allgather()

Workers verify numerics locally and print a marker; the parent asserts
both exit clean. Requires no hardware: 2 processes x 2 virtual CPU
devices each = a 4-device pod on one machine.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent

_WORKER = r'''
import sys

sys.path.insert(0, sys.argv[4])
import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

from oryx_tpu.common.config import load_config
from oryx_tpu.parallel.distributed import (
    barrier,
    global_mesh,
    host_allgather,
    init_distributed,
)
from oryx_tpu.parallel.mesh import DATA_AXIS, MeshSpec

pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

cfg = load_config(overlay={
    "oryx.compute.distributed.coordinator-address": f"127.0.0.1:{port}",
    "oryx.compute.distributed.num-processes": nprocs,
    "oryx.compute.distributed.process-id": pid,
})
assert init_distributed(cfg) is True
assert jax.process_count() == nprocs
n_dev = jax.device_count()
assert n_dev == 4, f"expected 4 global devices, got {n_dev}"

# ---- pod-wide hybrid mesh: data spans hosts, model stays local --------
mesh = global_mesh(MeshSpec(data=2, model=2))
assert mesh.devices.size == 4

# ---- Gram psum across the process boundary ----------------------------
import jax.numpy as jnp
from jax.experimental import multihost_utils as mhu
from jax.sharding import NamedSharding, PartitionSpec as P

from oryx_tpu.ops.als import gram

rows, feat = 16, 8
host = np.arange(rows * feat, dtype=np.float32).reshape(rows, feat) / 7.0
sharding = NamedSharding(mesh, P((DATA_AXIS,), None))
garr = jax.make_array_from_callback(
    (rows, feat), sharding, lambda idx: host[idx]
)
g = jax.jit(gram, out_shardings=NamedSharding(mesh, P(None, None)))(garr)
expect = host.T @ host
np.testing.assert_allclose(
    np.asarray(mhu.process_allgather(g, tiled=True)), expect, rtol=1e-5
)

# ---- ring attention with the ring spanning both processes -------------
from oryx_tpu.ops.attention import attention, ring_attention

seq, d = 16, 8
rng = np.random.default_rng(0)
q = rng.standard_normal((seq, d)).astype(np.float32)
k = rng.standard_normal((seq, d)).astype(np.float32)
v = rng.standard_normal((seq, d)).astype(np.float32)
seq_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
mk = lambda a: jax.make_array_from_callback((seq, d), seq_sharding, lambda idx: a[idx])
out = ring_attention(mk(q), mk(k), mk(v), mesh, causal=True)
out_host = np.asarray(mhu.process_allgather(out, tiled=True))
ref = np.asarray(attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True))
np.testing.assert_allclose(out_host, ref, rtol=2e-4, atol=2e-5)

# ---- the FULL tensor-parallel ALS trainer across both hosts ----------
# same data + seed as the parent's single-process run; the result must be
# process-count-invariant (X/Y partials psum across the pod, factors
# allgathered back to every host)
import pickle

with open(sys.argv[5], "rb") as f:
    blob = pickle.load(f)
from oryx_tpu.ops.als import InteractionData, train_als_tp

tdata = InteractionData(*blob["data"])
model = train_als_tp(
    tdata, mesh, features=8, iterations=3, block=8,
    seed_key=jax.random.PRNGKey(7),
)
np.testing.assert_allclose(model.x, blob["x"], rtol=2e-4, atol=2e-5)
np.testing.assert_allclose(model.y, blob["y"], rtol=2e-4, atol=2e-5)

# default seed path: per-process urandom keys must be broadcast from
# process 0 so every host trains the identical model
m2 = train_als_tp(tdata, mesh, features=8, iterations=1, block=8)
digest = np.array([m2.x.sum(), m2.y.sum(), m2.x[0].sum()], dtype=np.float64)
all_digests = host_allgather(digest)
np.testing.assert_allclose(all_digests[0], all_digests[1], rtol=0, atol=0)

# ---- barrier + host gather -------------------------------------------
barrier("test")
got = host_allgather(np.int32(jax.process_index()))
assert sorted(int(x) for x in got.ravel()) == list(range(nprocs)), got

print(f"MULTIHOST_OK {pid}", flush=True)
'''


_POD_WINDOW_WORKER = r'''
import sys

sys.path.insert(0, sys.argv[4])
import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

from oryx_tpu.api import BatchLayerUpdate
from oryx_tpu.bus.broker import get_broker, topics
from oryx_tpu.common.config import load_config
from oryx_tpu.layers.batch import BatchLayer
from oryx_tpu.parallel.distributed import host_allgather, init_distributed

pid, nprocs, port, root, bus_dir = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4], sys.argv[5]
)
uri = f"file://{bus_dir}"
cfg = load_config(overlay={
    "oryx.id": "podwin",
    "oryx.input-topic.broker": uri,
    "oryx.input-topic.message.topic": "OryxInput",
    "oryx.update-topic.broker": uri,
    "oryx.update-topic.message.topic": "OryxUpdate",
    "oryx.batch.streaming.generation-interval-sec": 3600,
    "oryx.batch.storage.data-dir": f"{bus_dir}/data",
    "oryx.batch.storage.model-dir": f"{bus_dir}/model",
    "oryx.compute.distributed.coordinator-address": f"127.0.0.1:{port}",
    "oryx.compute.distributed.num-processes": nprocs,
    "oryx.compute.distributed.process-id": pid,
})
assert init_distributed(cfg) is True


class Captures(BatchLayerUpdate):
    def __init__(self, *a):
        self.windows = []

    def run_update(self, ts, new_data, past_data, model_dir, producer):
        self.windows.append([m.message for m in new_data])


up = Captures()
layer = BatchLayer(cfg, update=up)

if pid == 0:
    # the leader consumed records 0-1 in an earlier life: its group has a
    # durable commit at offset 2
    get_broker(uri).commit_offsets("OryxGroup-podwin-batch", "OryxInput", {0: 2})
layer.ensure_streams()
# the non-leader's fresh per-process group resolves start='committed' to
# its OWN log end (10) — WITHOUT the pod-agreed start seek it would see
# an empty window while the leader processes records 2..9
layer.run_generation(timestamp_ms=1234)

window = up.windows[0] if up.windows else []
assert len(window) == 8, f"pid {pid}: window has {len(window)} records"
assert window == [f"r{i}" for i in range(2, 10)], f"pid {pid}: {window}"
lens = host_allgather(np.int32(len(window)))
assert int(lens[0]) == int(lens[1]) == 8, lens
print(f"PODWINDOW_OK {pid}", flush=True)
'''


_POD_PARALLEL_WORKER = r'''
import sys

sys.path.insert(0, sys.argv[4])
import jax

jax.config.update("jax_platforms", "cpu")
import json

import numpy as np

from oryx_tpu.bus.api import KeyMessage, TopicProducer
from oryx_tpu.bus.broker import get_broker
from oryx_tpu.common.config import load_config
from oryx_tpu.parallel.distributed import global_mesh, init_distributed
from oryx_tpu.parallel.mesh import MeshSpec
from oryx_tpu.parallel.submesh import current_candidate_mesh

pid, nprocs, port, root, tmp = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4], sys.argv[5]
)

base = {
    "oryx.id": "podpar",
    "oryx.ml.eval.candidates": 2,
    "oryx.ml.eval.hyperparam-search": "grid",
    "oryx.ml.eval.test-fraction": 0.2,
    "oryx.als.hyperparams.features": 8,
    "oryx.als.hyperparams.iterations": 4,
    "oryx.als.hyperparams.alpha": 10.0,
    "oryx.als.hyperparams.lambda": [0.01, 500.0],
    "oryx.als.no-known-items": True,
    "oryx.compute.distributed.coordinator-address": f"127.0.0.1:{port}",
    "oryx.compute.distributed.num-processes": nprocs,
    "oryx.compute.distributed.process-id": pid,
}
assert init_distributed(load_config(overlay=base)) is True
mesh = global_mesh(MeshSpec(data=2, model=2))

# identical input on every member (the pod agrees the window in real runs)
rng = np.random.default_rng(17)
msgs = []
for j in range(1200):
    u = int(rng.integers(0, 40))
    i = (u % 3) * 10 + int(rng.integers(0, 10))
    msgs.append(KeyMessage(None, f"u{u},i{i},1,{j}"))

from oryx_tpu.apps.als.batch import ALSUpdate

built = []


class Spy(ALSUpdate):
    def build_model(self, train, hyperparams):
        built.append((float(hyperparams["lambda"]), current_candidate_mesh()))
        return super().build_model(train, hyperparams)


def run(parallelism):
    built.clear()
    over = dict(base)
    over["oryx.ml.eval.parallelism"] = parallelism
    broker = get_broker(f"mem://podpar-{pid}-{parallelism}")
    broker.create_topic("U", partitions=1)
    upd = Spy(load_config(overlay=over), mesh=mesh)
    upd.run_update(
        1000, msgs, [], f"{tmp}/p{pid}-model-{parallelism}",
        TopicProducer(broker, "U"),
    )
    recs = broker.read("U", 0, 0, 5)
    model_msgs = [m for _, k, m in recs if k == "MODEL"]
    assert model_msgs, recs
    return json.loads(model_msgs[0])["extensions"]["lambda"]


par = run(2)
# each member built exactly ONE candidate — its process group's — on its
# own 2-device (1 data x 2 model) slice of the pod
assert len(built) == 1, built
lam, sub = built[0]
assert sub is not None and sub.devices.size == 2, sub
assert sub.devices.shape == (1, 2), sub.devices.shape
assert {d.process_index for d in sub.devices.ravel()} == {pid}
assert lam == (0.01 if pid == 0 else 500.0), (pid, lam)

ser = run(1)
# serial lockstep: every member builds every candidate on the full mesh
assert [l for l, _ in built] == [0.01, 500.0], built
assert all(m is None for _, m in built), built

# winner identical across modes and members — and process 1 only has the
# winning artifact because _fetch_winner shipped it over the pod
assert par == ser == "0.01", (par, ser)
print(f"PODPAR_OK {pid}", flush=True)
'''


def _pod_env(device_count: int) -> dict:
    """CPU-only worker env with exactly device_count virtual devices —
    the one place the XLA flag surgery lives."""
    from oryx_tpu.common.executil import cpu_subprocess_env

    env = cpu_subprocess_env(dict(os.environ))
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(
        flags + [f"--xla_force_host_platform_device_count={device_count}"]
    )
    return env


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_pod_window_agrees_both_edges(tmp_path):
    """Round-3 advice (medium): _pod_window allgathered only END offsets,
    so a non-leader whose start position resolved independently (fresh
    group -> own log END at its own startup instant) consumed a DIFFERENT
    record set than the leader. Two real processes over a shared file://
    bus: the leader's group has a durable commit at offset 2, the
    non-leader starts fresh after 10 records exist — both must process
    exactly records 2..9."""
    from oryx_tpu.bus.broker import get_broker, topics

    bus_dir = tmp_path / "bus"
    bus_dir.mkdir()
    uri = f"file://{bus_dir}"
    topics.maybe_create(uri, "OryxInput", partitions=1)
    topics.maybe_create(uri, "OryxUpdate", partitions=1)
    broker = get_broker(uri)
    for i in range(10):
        broker.send("OryxInput", None, f"r{i}")

    port = _free_port()
    env = _pod_env(2)

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _POD_WINDOW_WORKER, str(i), "2", str(port),
             str(ROOT), str(bus_dir)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=180)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-2000:]}"
        assert f"PODWINDOW_OK {i}" in out, out[-2000:]


def test_two_process_pod_parallel_candidates(tmp_path):
    """Round-4 verdict #3: a REAL multi-process pod must search hyperparam
    candidates in parallel — one candidate per process group, each on its
    own slice of the pod mesh, scores gathered pod-wide, winner identical
    to the serial lockstep search (reference MLUpdate.java:253-258
    parallelizes across the Spark cluster). Two OS processes x 2 virtual
    CPU devices = a 4-device pod building 2 candidates concurrently."""
    port = _free_port()
    env = _pod_env(2)

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _POD_PARALLEL_WORKER, str(i), "2", str(port),
             str(ROOT), str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
        assert f"PODPAR_OK {i}" in out, out[-3000:]


_POD_UNEVEN_WORKER = r'''
import sys

sys.path.insert(0, sys.argv[4])
import jax

jax.config.update("jax_platforms", "cpu")
import json

import numpy as np

from oryx_tpu.bus.api import KeyMessage, TopicProducer
from oryx_tpu.bus.broker import get_broker
from oryx_tpu.common.config import load_config
from oryx_tpu.parallel.distributed import global_mesh, init_distributed
from oryx_tpu.parallel.mesh import MeshSpec
from oryx_tpu.parallel.submesh import current_candidate_mesh

pid, nprocs, port, root, tmp = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4], sys.argv[5]
)

base = {
    "oryx.id": "poduneven",
    "oryx.ml.eval.candidates": 2,
    "oryx.ml.eval.parallelism": 2,
    "oryx.ml.eval.hyperparam-search": "grid",
    "oryx.ml.eval.test-fraction": 0.2,
    "oryx.als.hyperparams.features": 8,
    "oryx.als.hyperparams.iterations": 3,
    "oryx.als.hyperparams.alpha": 10.0,
    "oryx.als.hyperparams.lambda": [0.01, 500.0],
    "oryx.als.no-known-items": True,
    "oryx.compute.distributed.coordinator-address": f"127.0.0.1:{port}",
    "oryx.compute.distributed.num-processes": nprocs,
    "oryx.compute.distributed.process-id": pid,
}
assert init_distributed(load_config(overlay=base)) is True
# 3 hosts x 2 local devices; model axis inside a host -> data axis = 3
mesh = global_mesh(MeshSpec(data=3, model=2))

rng = np.random.default_rng(17)
msgs = []
for j in range(900):
    u = int(rng.integers(0, 40))
    i = (u % 3) * 10 + int(rng.integers(0, 10))
    msgs.append(KeyMessage(None, f"u{u},i{i},1,{j}"))

from oryx_tpu.apps.als.batch import ALSUpdate

built = []


class Spy(ALSUpdate):
    def build_model(self, train, hyperparams):
        built.append((float(hyperparams["lambda"]), current_candidate_mesh()))
        return super().build_model(train, hyperparams)


broker = get_broker(f"mem://poduneven-{pid}")
broker.create_topic("U", partitions=1)
upd = Spy(load_config(overlay=base), mesh=mesh)
upd.run_update(
    2000, msgs, [], f"{tmp}/p{pid}-model", TopicProducer(broker, "U")
)
recs = broker.read("U", 0, 0, 5)
model_msgs = [m for _, k, m in recs if k == "MODEL"]
assert model_msgs, recs
winner = json.loads(model_msgs[0])["extensions"]["lambda"]

# groups over 3 processes at parallelism 2: [[0, 1], [2]] — candidate 0
# (lambda 0.01) trains on a sub-mesh SPANNING processes 0 and 1 (its
# psums/gathers cross the process boundary but stay inside the group),
# candidate 1 on process 2 alone
assert len(built) == 1, built
lam, sub = built[0]
expect_lam = 0.01 if pid in (0, 1) else 500.0
assert lam == expect_lam, (pid, lam)
owners = {d.process_index for d in sub.devices.ravel()}
assert owners == ({0, 1} if pid in (0, 1) else {2}), (pid, owners)
assert sub.devices.shape == ((2, 2) if pid in (0, 1) else (1, 2))

# winner agreed pod-wide; processes 2 got it via the broadcast
assert winner == "0.01", winner
print(f"PODUNEVEN_OK {pid}", flush=True)
'''


def test_three_process_pod_uneven_groups(tmp_path):
    """Groups that SPAN processes: 3 pod members at parallelism 2 split
    [[0,1],[2]] — candidate 0's collectives cross the process boundary
    inside its group while group 1 trains concurrently, and the winner
    ships to the group that didn't build it. This is the case that
    required train_als_tp's seed broadcast and factor gather to be
    mesh-scoped rather than pod-wide."""
    port = _free_port()
    env = _pod_env(2)

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _POD_UNEVEN_WORKER, str(i), "3", str(port),
             str(ROOT), str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(3)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
        assert f"PODUNEVEN_OK {i}" in out, out[-3000:]


def test_two_process_pod_collectives(tmp_path):
    # expected TP model from THIS (single-process, 8-device) interpreter,
    # same mesh shape and seed the workers will use across two processes
    import pickle

    import jax
    import numpy as np

    from oryx_tpu.ops.als import aggregate_interactions, train_als_tp
    from oryx_tpu.parallel.mesh import MeshSpec, make_mesh

    rng = np.random.default_rng(11)
    n = 400
    data = aggregate_interactions(
        rng.integers(0, 24, n).astype(str),
        rng.integers(0, 32, n).astype(str),
        rng.random(n).astype(np.float64) + 0.1,
        implicit=True,
    )
    mesh = make_mesh(MeshSpec(data=2, model=2), jax.devices("cpu")[:4])
    expect = train_als_tp(
        data, mesh, features=8, iterations=3, block=8,
        seed_key=jax.random.PRNGKey(7),
    )
    blob = tmp_path / "expected.pkl"
    with open(blob, "wb") as f:
        pickle.dump(
            {
                "data": (data.user_ids, data.item_ids, data.users, data.items, data.values),
                "x": expect.x,
                "y": expect.y,
            },
            f,
        )

    port = _free_port()
    env = _pod_env(2)

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(i), "2", str(port), str(ROOT), str(blob)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=180)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-2000:]}"
        assert f"MULTIHOST_OK {i}" in out, out[-2000:]
