"""End-to-end random-decision-forest lambda slice: ingest labeled examples
-> batch forest build -> update topic -> serving answers /predict +
/classificationDistribution -> speed layer folds /train examples into
terminal-node stats -> serving applies the leaf updates.

The classreg analogue of test_e2e_als.py (the reference's RDFUpdateIT +
serving ITs), over the in-process broker with a real HTTP server.
"""

import json
import time

import numpy as np
import pytest

from oryx_tpu.apps.rdf.batch import RDFUpdate
from oryx_tpu.apps.rdf.serving import RDFServingModelManager
from oryx_tpu.apps.rdf.speed import RDFSpeedModelManager
from oryx_tpu.bus.broker import get_broker, topics
from oryx_tpu.bus.inproc import InProcBroker
from oryx_tpu.common.config import load_config
from oryx_tpu.common.rng import RandomManager
from oryx_tpu.layers import BatchLayer, SpeedLayer
from oryx_tpu.serving.server import ServingLayer


@pytest.fixture(autouse=True)
def _fresh_registry():
    InProcBroker.reset_all()
    yield
    InProcBroker.reset_all()


from e2e_common import http_request as _http  # noqa: E402


def _cfg(tmp_path):
    return load_config(overlay={
        "oryx.id": "e2erdf",
        "oryx.input-topic.broker": "mem://e2erdf",
        "oryx.update-topic.broker": "mem://e2erdf",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.serving.api.port": 0,
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.classreg",
        ],
        "oryx.input-schema.feature-names": ["size", "color", "label"],
        "oryx.input-schema.numeric-features": ["size"],
        "oryx.input-schema.target-feature": "label",
        "oryx.rdf.num-trees": 8,
        "oryx.rdf.hyperparams.max-depth": 5,
        "oryx.ml.eval.test-fraction": 0.2,
        "oryx.serving.min-model-load-fraction": 1.0,
        "oryx.speed.min-model-load-fraction": 0.8,
    })


def _cls_lines(n=600, seed=0):
    """label = banana iff (size>0.5) xor (color==red)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        size = rng.random()
        color = rng.choice(["red", "green", "blue"])
        label = "banana" if (size > 0.5) ^ (color == "red") else "apple"
        out.append(f"{size:.4f},{color},{label}")
    return out


def test_full_rdf_slice(tmp_path):
    RandomManager.use_test_seed(5)
    cfg = _cfg(tmp_path)
    topics.maybe_create("mem://e2erdf", "OryxInput", partitions=2)
    topics.maybe_create("mem://e2erdf", "OryxUpdate", partitions=1)
    broker = get_broker("mem://e2erdf")

    serving = ServingLayer(cfg, model_manager=RDFServingModelManager(cfg))
    serving.start()
    base = f"http://127.0.0.1:{serving.port}"
    status, _ = _http("GET", f"{base}/ready")
    assert status == 503

    lines = _cls_lines()
    status, resp = _http("POST", f"{base}/ingest", body="\n".join(lines).encode())
    assert status == 200, resp

    batch = BatchLayer(cfg, update=RDFUpdate(cfg))
    batch.ensure_streams()
    batch._consumer._fetch_pos = {p: 0 for p in batch._consumer._fetch_pos}
    n = batch.run_generation(timestamp_ms=1_700_000_000_000)
    assert n == len(lines)
    batch.close()
    assert broker.read("OryxUpdate", 0, 0, 5)[0][1] == "MODEL"

    deadline = time.time() + 30
    while time.time() < deadline:
        status, _ = _http("GET", f"{base}/ready")
        if status == 200:
            break
        time.sleep(0.1)
    assert status == 200, "serving never became ready"

    # the forest learned the XOR rule on all four quadrants
    for datum, want in (
        ("0.9,green", "banana"),  # size>0.5, not red
        ("0.9,red", "apple"),
        ("0.1,red", "banana"),
        ("0.1,blue", "apple"),
    ):
        status, resp = _http("GET", f"{base}/predict/{datum}")
        assert status == 200, resp
        assert json.loads(resp) == want, (datum, resp)

    # distribution sums to ~1 and favors the predicted class
    status, resp = _http("GET", f"{base}/classificationDistribution/0.9,green")
    assert status == 200
    dist = dict(json.loads(resp))
    assert abs(sum(dist.values()) - 1.0) < 1e-6
    assert dist.get("banana", 0) > dist.get("apple", 0)

    # feature importances cover both predictors
    status, resp = _http("GET", f"{base}/feature/importance")
    assert status == 200 and len(json.loads(resp)) == 2

    # bad feature index -> 400, unknown route -> 404 (an unparseable
    # numeric feature is treated as MISSING and routed down the default
    # branch, like the reference forest's missing-value handling)
    status, _ = _http("GET", f"{base}/feature/importance/9")
    assert status == 400
    status, _ = _http("GET", f"{base}/nothere")
    assert status == 404

    # per-app console section
    status, resp = _http("GET", f"{base}/console")
    assert status == 200 and "importance" in resp.lower()

    # ---- speed tier: /train examples update terminal-node stats ----
    speed = SpeedLayer(cfg, manager=RDFSpeedModelManager(cfg))
    speed.start()
    deadline = time.time() + 30
    while time.time() < deadline:
        if speed.manager.model is not None:
            break
        time.sleep(0.1)
    assert speed.manager.model is not None

    # baseline BEFORE injecting: the micro-batch consumer is async
    before = speed.batch_count
    train_lines = "\n".join(_cls_lines(n=100, seed=9))
    status, _ = _http("POST", f"{base}/train", body=train_lines.encode())
    assert status == 200
    deadline = time.time() + 30
    while speed.batch_count == before and time.time() < deadline:
        time.sleep(0.1)
    assert speed.batch_count > before, "speed micro-batch never ran"

    # serving keeps answering correctly while leaf updates stream in
    deadline = time.time() + 10
    while time.time() < deadline:
        status, resp = _http("GET", f"{base}/predict/0.9,green")
        assert status == 200 and json.loads(resp) == "banana"
        time.sleep(0.2)

    speed.close()
    serving.close()
