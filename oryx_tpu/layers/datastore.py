"""Generation data store: append-only persistence of each batch window.

The reference appends every generation's input as Hadoop SequenceFiles
under dataDir/oryx-<timestamp>/ (SaveToHDFSFunction, skipping empty RDDs,
BatchLayer.java:122-130) and re-reads ALL past data each generation with a
glob (BatchUpdateFunction.java:103-130); TTL cleanup deletes aged dirs
(DeleteOldDataFn). Here each generation is one record-log file using the
bus wire format — so the native appender/scanner accelerate it too — under
<data-dir>/oryx-<timestamp>/data.log.
"""

from __future__ import annotations

from pathlib import Path
from typing import Sequence

from oryx_tpu.bus.api import KeyMessage
from oryx_tpu.bus.filelog import _PartitionIndex, encode_record, _maybe_native
from oryx_tpu.common.ioutil import list_generation_dirs, mkdirs, strip_scheme

_DATA_FILE = "data.log"


def save_generation(data_dir: str, timestamp_ms: int, records: Sequence[KeyMessage]) -> Path | None:
    """Persist one generation's window; empty windows write nothing
    (SaveToHDFSFunction skips empty RDDs)."""
    if not records:
        return None
    d = mkdirs(Path(strip_scheme(data_dir)) / f"oryx-{timestamp_ms}")
    path = d / _DATA_FILE
    blob = b"".join(encode_record(km.key, km.message) for km in records)
    native = _maybe_native()
    if native is not None:
        native.append_batch(str(path), blob)
    else:
        with open(path, "ab") as f:
            f.write(blob)
    return d


def load_all_data(data_dir: str) -> list[KeyMessage]:
    """All persisted generations, oldest first — the 'pastData' input to a
    batch model build."""
    out: list[KeyMessage] = []
    for gen_dir in list_generation_dirs(strip_scheme(data_dir)):
        path = gen_dir / _DATA_FILE
        if not path.exists():
            continue
        idx = _PartitionIndex(path, _maybe_native())
        recs = idx.read(0, 1 << 30)
        out.extend(KeyMessage(k, m) for _, k, m in recs)
    return out


