"""Sharded serving top-k: CPU multi-device proof of bit-identity.

The acceptance bar of PR 11's tentpole: a host_mesh(n)-style CPU
simulation (the conftest forces 8 virtual devices) must prove the
sharded top-k returns bit-identical (value, index) pairs to the
single-device exact kernel for n in {1, 2, 4} — including int8-quantized
shards and the duplicate-score tie-break — and that a dirty-row delta
scatters into its owning shard only.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oryx_tpu.ops.als import topk_dot_batch
from oryx_tpu.ops.shard_topk import merge_topk_partials, topk_dot_batch_sharded
from oryx_tpu.ops.transfer import (
    QuantizedMatrix,
    ShardedMatrix,
    scatter_rows,
    sharded_device_put,
    staged_device_put,
    quantize_rows_int8,
)


def _corpus(n_items=203, features=17, batch=5, seed=3):
    rng = np.random.default_rng(seed)
    y = rng.normal(size=(n_items, features)).astype(np.float32)
    xs = rng.normal(size=(batch, features)).astype(np.float32)
    return xs, y


@pytest.mark.parametrize("n_shards", [1, 2, 4])
def test_sharded_topk_bit_identical_bf16(n_shards):
    xs, y = _corpus()
    y_full = staged_device_put(y, dtype=jnp.bfloat16)
    y_sharded = sharded_device_put(y, n_shards, dtype=jnp.bfloat16)
    assert y_sharded.shape == y_full.shape
    v0, i0 = topk_dot_batch(jnp.asarray(xs), y_full, k=10)
    v1, i1 = topk_dot_batch(jnp.asarray(xs), y_sharded, k=10)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))


@pytest.mark.parametrize("n_shards", [1, 2, 4])
def test_sharded_topk_bit_identical_quantized(n_shards):
    xs, y = _corpus(seed=11)
    q, s = quantize_rows_int8(y)
    full = QuantizedMatrix(jnp.asarray(q), jnp.asarray(s))
    sharded = sharded_device_put(y, n_shards, quantize=True)
    # per-row scales are row-local: shard-local quantization must be
    # bit-identical to quantize-then-slice
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(sh.q) for sh in sharded.shards]), q
    )
    v0, i0 = topk_dot_batch(jnp.asarray(xs), full, k=10)
    v1, i1 = topk_dot_batch(jnp.asarray(xs), sharded, k=10)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))


@pytest.mark.parametrize("n_shards", [2, 4])
def test_sharded_topk_duplicate_score_tie_break(n_shards):
    # duplicate rows STRADDLING shard boundaries: every duplicate pair
    # scores identically, and the winner must be the LOWER global index
    # (lax.top_k's stable order), exactly as the single dispatch picks
    rng = np.random.default_rng(7)
    base = rng.normal(size=(40, 8)).astype(np.float32)
    y = np.concatenate([base, base, base])  # 120 rows, every score x3
    xs = rng.normal(size=(4, 8)).astype(np.float32)
    y_full = staged_device_put(y, dtype=jnp.bfloat16)
    y_sharded = sharded_device_put(y, n_shards, dtype=jnp.bfloat16)
    v0, i0 = topk_dot_batch(jnp.asarray(xs), y_full, k=12)
    v1, i1 = topk_dot_batch(jnp.asarray(xs), y_sharded, k=12)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))


def test_sharded_topk_uneven_rows_and_wide_k():
    # 7 rows over 4 shards (sizes 2,2,2,1) with k wider than any shard:
    # per-shard partials are narrower than k and the merge must still
    # produce the exact global ordering over every row
    xs, y = _corpus(n_items=7, features=5, batch=3, seed=23)
    y_full = staged_device_put(y, dtype=jnp.bfloat16)
    y_sharded = sharded_device_put(y, 4, dtype=jnp.bfloat16)
    v0, i0 = topk_dot_batch(jnp.asarray(xs), y_full, k=7)
    v1, i1 = topk_dot_batch(jnp.asarray(xs), y_sharded, k=7)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
    with pytest.raises(ValueError):
        topk_dot_batch_sharded(jnp.asarray(xs), y_sharded, k=8)


def test_sharded_placement_uses_distinct_devices():
    _, y = _corpus(n_items=64)
    sm = sharded_device_put(y, 4, dtype=jnp.bfloat16)
    devs = [next(iter(sh.devices())) for sh in sm.shards]
    assert len(set(devs)) == 4  # conftest forces 8 virtual CPU devices
    # placement must SURVIVE computation: shards are committed, so a
    # dirty-row scatter and the unit-view normalize both stay on the
    # owning shard's device (an uncommitted shard would silently migrate
    # to the default device on first touch — the multi-chip OOM)
    assert all(getattr(sh, "committed", True) for sh in sm.shards)
    after = scatter_rows(
        sm, np.array([17], dtype=np.int64),
        np.ones((1, y.shape[1]), dtype=np.float32),
    )
    assert [next(iter(sh.devices())) for sh in after.shards] == devs
    unit = sm.map(lambda s: (s.astype(jnp.float32) / 2).astype(s.dtype))
    assert [next(iter(sh.devices())) for sh in unit.shards] == devs
    smq = sharded_device_put(y, 4, quantize=True)
    qdevs = [next(iter(sh.devices())) for sh in smq.shards]
    assert len(set(qdevs)) == 4
    afterq = scatter_rows(
        smq, np.array([33], dtype=np.int64),
        np.ones((1, y.shape[1]), dtype=np.float32),
    )
    assert [next(iter(sh.devices())) for sh in afterq.shards] == qdevs
    # full view reassembles exactly across devices
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(sh, dtype=np.float32) for sh in sm.shards]),
        np.asarray(
            staged_device_put(y, dtype=jnp.bfloat16), dtype=np.float32
        ),
    )


def test_merge_topk_partials_direct():
    # hand-built partials with ties across shards and uneven widths
    v_a = np.array([[3.0, 1.0]], dtype=np.float32)
    i_a = np.array([[4, 9]], dtype=np.int32)
    v_b = np.array([[3.0, 2.0, 0.5]], dtype=np.float32)
    i_b = np.array([[2, 11, 20]], dtype=np.int32)
    v, i = merge_topk_partials([(v_a, i_a), (v_b, i_b)], k=4)
    np.testing.assert_array_equal(np.asarray(i), [[2, 4, 11, 9]])
    np.testing.assert_array_equal(np.asarray(v), [[3.0, 3.0, 2.0, 1.0]])
    with pytest.raises(ValueError):
        merge_topk_partials([], k=2)


def test_sharded_scatter_touches_owning_shard_only():
    _, y = _corpus(n_items=20, features=6)
    sm = sharded_device_put(y, 4, dtype=jnp.bfloat16)  # sizes [5,5,5,5]
    old_shards = list(sm.shards)
    rows = np.array([6, 8], dtype=np.int64)  # both owned by shard 1
    new_rows = np.full((2, 6), 2.5, dtype=np.float32)
    out = scatter_rows(sm, rows, new_rows)
    assert isinstance(out, ShardedMatrix)
    # untouched shards are the SAME buffers, not copies
    assert out.shards[0] is old_shards[0]
    assert out.shards[2] is old_shards[2]
    assert out.shards[3] is old_shards[3]
    assert out.shards[1] is not old_shards[1]
    got = np.asarray(out.shards[1], dtype=np.float32)
    np.testing.assert_allclose(got[[1, 3]], new_rows, rtol=0.01)
    # empty delta: the view object rides through unchanged
    same = scatter_rows(out, np.array([], dtype=np.int64), np.zeros((0, 6)))
    assert same is out


def test_sharded_scatter_quantized_requantizes_locally():
    _, y = _corpus(n_items=12, features=4)
    sm = sharded_device_put(y, 3, quantize=True)  # sizes [4,4,4]
    old = list(sm.shards)
    rows = np.array([5], dtype=np.int64)  # shard 1, local row 1
    fresh = np.array([[9.0, -3.0, 0.5, 1.0]], dtype=np.float32)
    out = scatter_rows(sm, rows, fresh)
    assert out.shards[0] is old[0] and out.shards[2] is old[2]
    q_exp, s_exp = quantize_rows_int8(fresh)
    np.testing.assert_array_equal(np.asarray(out.shards[1].q)[1], q_exp[0])
    np.testing.assert_allclose(
        np.asarray(out.shards[1].scale)[1], s_exp[0], rtol=1e-6
    )
    # the other rows of the touched shard kept their int8 bits exactly
    np.testing.assert_array_equal(
        np.asarray(out.shards[1].q)[[0, 2, 3]], np.asarray(old[1].q)[[0, 2, 3]]
    )


def test_bucketed_train_under_pjit_sharded_factors():
    """The bucketed (donated-carry) ALS scan runs under pjit with the
    item-factor table row-sharded over a model-axis mesh — and lands on
    the same model as the single-device scan (same seeded init; only
    collective summation order differs)."""
    from oryx_tpu.ops.als import aggregate_interactions, train_als, train_als_warm
    from oryx_tpu.parallel.mesh import model_mesh

    rng = np.random.default_rng(13)
    data = aggregate_interactions(
        rng.integers(0, 50, 800).astype(str),
        rng.integers(0, 30, 800).astype(str),
        (rng.random(800) * 2 + 0.2).astype(np.float32),
        implicit=True,
    )
    key = jax.random.PRNGKey(4)
    ref = train_als(data, features=6, iterations=4, seed_key=key)
    for n in (2, 4):
        sharded = train_als(
            data, features=6, iterations=4, seed_key=key,
            shard_mesh=model_mesh(n),
        )
        np.testing.assert_allclose(sharded.x, ref.x, rtol=2e-3, atol=2e-4)
        np.testing.assert_allclose(sharded.y, ref.y, rtol=2e-3, atol=2e-4)
    # the warm-start early-stop loop threads the shard mesh through its
    # donated re-entries unchanged
    warm, sweeps = train_als_warm(
        data, features=6, iterations=8, seed_key=key, resume_y=ref.y,
        tol=0.05, min_iterations=2, check_every=2,
        shard_mesh=model_mesh(2),
    )
    assert warm.y.shape == ref.y.shape
    assert 2 <= sweeps <= 8
    # combining an explicit mesh with shard_mesh is a loud error, never a
    # silently dropped shard layout
    from oryx_tpu.parallel.mesh import host_mesh

    with pytest.raises(ValueError):
        train_als(
            data, features=6, iterations=1, seed_key=key,
            mesh=host_mesh(2), shard_mesh=model_mesh(2),
        )


def test_checkpointed_train_threads_shard_mesh(tmp_path):
    """Review regression (PR 11): the checkpointed build path must keep
    the shard layout — dropping it silently trained single-device AND
    unsharded once ALSUpdate replaced the auto mesh with None."""
    from oryx_tpu.ops.als import (
        aggregate_interactions, train_als, train_als_checkpointed,
    )
    from oryx_tpu.parallel.mesh import model_mesh

    rng = np.random.default_rng(21)
    data = aggregate_interactions(
        rng.integers(0, 30, 400).astype(str),
        rng.integers(0, 20, 400).astype(str),
        (rng.random(400) + 0.2).astype(np.float32),
        implicit=True,
    )
    key = jax.random.PRNGKey(9)
    ref = train_als(data, features=4, iterations=4, seed_key=key)
    ck = train_als_checkpointed(
        data, tmp_path / "ck", 2, features=4, iterations=4, seed_key=key,
        shard_mesh=model_mesh(2),
    )
    np.testing.assert_allclose(ck.x, ref.x, rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(ck.y, ref.y, rtol=2e-3, atol=2e-4)


def test_sharded_matrix_through_the_batcher():
    """The shared TopKBatcher scores a ShardedMatrix view exactly like a
    plain device matrix — the serving integration point."""
    from oryx_tpu.serving.batcher import TopKBatcher

    xs, y = _corpus(n_items=96, features=8, batch=1)
    sm = sharded_device_put(y, 2, dtype=jnp.bfloat16)
    b = TopKBatcher()
    try:
        vals, idx = b.submit(xs[0], 5, sm, host_mat=y)
        v0, i0 = topk_dot_batch(
            jnp.asarray(xs[:1]), staged_device_put(y, dtype=jnp.bfloat16), k=5
        )
        np.testing.assert_array_equal(np.asarray(idx), np.asarray(i0)[0])
    finally:
        b.close()
