"""HTTP/2 (RFC 7540) for the asyncio serving frontend, from scratch.

Reference parity: the reference's Tomcat connector upgrades to h2
(framework/oryx-lambda-serving/.../ServingLayer.java:229
``addUpgradeProtocol(new Http2Protocol())``); this module is the asyncio
analogue. Three entry paths, matching Tomcat's:

- **prior knowledge** (``curl --http2-prior-knowledge``): the cleartext
  connection opens with the 24-byte client preface; aserver detects it
  and hands the socket here.
- **h2c upgrade**: an HTTP/1.1 request carrying ``Upgrade: h2c`` +
  ``HTTP2-Settings`` gets ``101 Switching Protocols`` and its response
  on stream 1.
- **ALPN over TLS**: server.py advertises ``("h2", "http/1.1")``; a
  client that negotiates h2 then sends the same preface, so the
  detection path is shared.

Streams multiplex onto the SAME deferred-dispatch path as HTTP/1.1
(AsyncHTTPServer._process): each stream's dispatch runs as its own task,
so one slow device-batched request never blocks other streams on the
connection. Flow control (connection + per-stream send windows,
WINDOW_UPDATE replenishment for request bodies), SETTINGS negotiation,
PING, RST_STREAM cancellation and GOAWAY are implemented; PRIORITY is
parsed and ignored (as most servers do); server push is never used.
"""

from __future__ import annotations

import asyncio
import gzip
import logging
import struct

from oryx_tpu.serving.aserver import MAX_BODY_BYTES
from oryx_tpu.serving.hpack import Decoder as HpackDecoder
from oryx_tpu.serving.hpack import HpackError, encode as hpack_encode

log = logging.getLogger(__name__)

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

# frame types (RFC 7540 §6)
DATA = 0x0
HEADERS = 0x1
PRIORITY = 0x2
RST_STREAM = 0x3
SETTINGS = 0x4
PUSH_PROMISE = 0x5
PING = 0x6
GOAWAY = 0x7
WINDOW_UPDATE = 0x8
CONTINUATION = 0x9

# flags
FLAG_END_STREAM = 0x1
FLAG_ACK = 0x1
FLAG_END_HEADERS = 0x4
FLAG_PADDED = 0x8
FLAG_PRIORITY = 0x20

# error codes (§7)
NO_ERROR = 0x0
PROTOCOL_ERROR = 0x1
FLOW_CONTROL_ERROR = 0x3
FRAME_SIZE_ERROR = 0x6
REFUSED_STREAM = 0x7
CANCEL = 0x8
COMPRESSION_ERROR = 0x9

# settings ids (§6.5.2)
S_HEADER_TABLE_SIZE = 0x1
S_ENABLE_PUSH = 0x2
S_MAX_CONCURRENT_STREAMS = 0x3
S_INITIAL_WINDOW_SIZE = 0x4
S_MAX_FRAME_SIZE = 0x5
S_MAX_HEADER_LIST_SIZE = 0x6

MAX_FRAME_SIZE = 16384  # we never raise it; peers must not send larger
DEFAULT_WINDOW = 65535
MAX_HEADER_BLOCK = 64 * 1024
MAX_STREAMS = 256
# read deadlines: ACTIVE_READ_TIMEOUT between frames while streams are
# open (covers slow uploads), IDLE_READ_TIMEOUT otherwise and for the
# CONTINUATION frames of an unfinished header block
ACTIVE_READ_TIMEOUT = 300.0
IDLE_READ_TIMEOUT = 75.0


def decode_h2c_settings(value: str) -> bytes | None:
    """base64url HTTP2-Settings payload -> raw SETTINGS bytes, or None
    when malformed (bad base64url, or a length that is not a multiple of
    6). RFC 7540 §3.2.1: a malformed HTTP2-Settings header means a
    malformed REQUEST — the h1 server must reject it (400) BEFORE sending
    101 Switching Protocols, so this helper runs in the upgrade gate.

    Strict on the alphabet: urlsafe_b64decode silently DISCARDS invalid
    characters, so garbage whose surviving length happened to be a
    multiple of 6 decoded to nonsense and was accepted. validate=True
    rejects characters outside the translated alphabet, and the explicit
    pre-check also rejects standard-alphabet '+'/'/' input (valid base64,
    but NOT the base64url encoding §3.2.1 requires)."""
    import base64
    import binascii
    import re

    if re.fullmatch(r"[A-Za-z0-9_-]*={0,2}", value) is None:
        return None
    unpadded = value.rstrip("=")
    try:
        raw = base64.b64decode(
            unpadded + "=" * (-len(unpadded) % 4),
            altchars=b"-_",
            validate=True,
        )
    except (ValueError, binascii.Error):
        return None
    return raw if len(raw) % 6 == 0 else None


class ConnectionError2(Exception):
    def __init__(self, code: int, msg: str = ""):
        super().__init__(msg)
        self.code = code


class _Stream:
    __slots__ = (
        "sid", "headers", "body", "remote_closed", "send_window", "task",
    )

    def __init__(self, sid: int, send_window: int):
        self.sid = sid
        self.headers: list[tuple[bytes, bytes]] = []
        self.body = bytearray()
        self.remote_closed = False
        self.send_window = send_window
        self.task: asyncio.Task | None = None


class Http2Connection:
    """One h2 connection: owns the frame loop, the connection-scoped
    HPACK decoder, flow-control windows, and the per-stream dispatch
    tasks."""

    def __init__(
        self,
        server,  # AsyncHTTPServer (duck-typed: _process)
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        upgraded_request: tuple[str, str, dict, bytes] | None = None,
        owner=None,  # the _LoopState owning this connection's event loop
    ):
        self.server = server
        # every stream task this connection spawns runs on the owning
        # loop; its conns registry and request counter are that loop's —
        # never another loop's — so loop-affine state stays loop-affine
        self.owner = owner
        self.reader = reader
        self.writer = writer
        self.upgraded_request = upgraded_request
        self.decoder = HpackDecoder()
        self.streams: dict[int, _Stream] = {}
        self.conn_send_window = DEFAULT_WINDOW
        self.peer_initial_window = DEFAULT_WINDOW
        self.peer_max_frame = MAX_FRAME_SIZE
        self.last_stream_id = 0
        self.goaway_sent = False
        self.peer_goaway = False
        self._write_lock = asyncio.Lock()
        self._window_cv = asyncio.Condition()

    # -- frame primitives --------------------------------------------------

    async def _send_frame(
        self, ftype: int, flags: int, sid: int, payload: bytes = b""
    ) -> None:
        async with self._write_lock:
            self.writer.write(
                struct.pack(">I", len(payload))[1:]
                + bytes([ftype, flags])
                + struct.pack(">I", sid & 0x7FFFFFFF)
                + payload
            )
            try:
                await self.writer.drain()
            except ConnectionError:
                pass

    async def _read_frame(self) -> tuple[int, int, int, bytes]:
        head = await self.reader.readexactly(9)
        length = int.from_bytes(head[:3], "big")
        ftype, flags = head[3], head[4]
        sid = int.from_bytes(head[5:9], "big") & 0x7FFFFFFF
        if length > MAX_FRAME_SIZE:
            raise ConnectionError2(FRAME_SIZE_ERROR, "frame too large")
        payload = await self.reader.readexactly(length) if length else b""
        return ftype, flags, sid, payload

    # -- lifecycle ---------------------------------------------------------

    async def run(self, preface_read: bool = False) -> None:
        """Serve the connection until the peer goes away. preface_read:
        the caller already consumed the 24-byte client preface."""
        try:
            if not preface_read:
                got = await asyncio.wait_for(
                    self.reader.readexactly(len(PREFACE)), timeout=30
                )
                if got != PREFACE:
                    return
            await self._send_frame(
                SETTINGS,
                0,
                0,
                struct.pack(">HI", S_MAX_CONCURRENT_STREAMS, MAX_STREAMS)
                + struct.pack(">HI", S_MAX_HEADER_LIST_SIZE, MAX_HEADER_BLOCK),
            )
            if self.upgraded_request is not None:
                # h2c upgrade: the original HTTP/1.1 request becomes
                # stream 1, half-closed (remote) — respond once the h2
                # layer is up (RFC 7540 §3.2). The HTTP2-Settings header
                # is the client's initial SETTINGS (§3.2.1): apply it
                # BEFORE opening stream 1 so e.g. a smaller
                # INITIAL_WINDOW_SIZE governs the stream-1 response
                # (strict clients treat an overrun as FLOW_CONTROL_ERROR)
                h2s = self.upgraded_request[2].get("http2-settings", "")
                if h2s:
                    raw = decode_h2c_settings(h2s)
                    if raw is None:
                        # defense in depth: aserver validates before the
                        # 101, but a malformed payload reaching here is a
                        # malformed REQUEST (RFC 7540 §3.2.1) —
                        # PROTOCOL_ERROR, not the FRAME_SIZE_ERROR that
                        # _on_settings would raise for a non-multiple-of-6
                        raise ConnectionError2(
                            PROTOCOL_ERROR, "bad HTTP2-Settings header"
                        )
                    await self._on_settings(0, raw, ack=False)
                st = _Stream(1, self.peer_initial_window)
                st.remote_closed = True
                self.streams[1] = st
                self.last_stream_id = 1
                method, target, headers, body = self.upgraded_request
                st.task = asyncio.ensure_future(
                    self._dispatch(st, method, target, headers, body)
                )
            await self._frame_loop()
        except (
            asyncio.IncompleteReadError,
            asyncio.TimeoutError,
            ConnectionError,
        ):
            pass
        except ConnectionError2 as e:
            await self._goaway(e.code)
        except HpackError:
            await self._goaway(COMPRESSION_ERROR)
        except Exception:  # pragma: no cover - defensive
            log.exception("h2 connection failed")
            await self._goaway(PROTOCOL_ERROR)
        finally:
            for st in list(self.streams.values()):
                if st.task is not None and not st.task.done():
                    st.task.cancel()

    async def _goaway(self, code: int) -> None:
        if self.goaway_sent:
            return
        self.goaway_sent = True
        try:
            await self._send_frame(
                GOAWAY, 0, 0,
                struct.pack(">II", self.last_stream_id, code),
            )
        except Exception:  # pragma: no cover
            pass

    def _mark_busy(self, busy: bool) -> None:
        # graceful-shutdown bookkeeping shared with the H1 path: idle
        # connections cancel immediately on drain, busy ones get grace.
        # The registry is the OWNING loop's — a multi-loop frontend drains
        # each loop's connections from that loop's own shutdown sweep.
        if self.owner is None:
            return
        task = asyncio.current_task()
        conns = self.owner.conns
        if task in conns:
            conns[task] = not busy

    # -- receive path ------------------------------------------------------

    async def _frame_loop(self) -> None:
        while True:
            self._mark_busy(bool(self.streams))
            ftype, flags, sid, payload = await asyncio.wait_for(
                self._read_frame(),
                timeout=(
                    ACTIVE_READ_TIMEOUT if self.streams else IDLE_READ_TIMEOUT
                ),
            )
            self._mark_busy(True)
            if ftype == HEADERS:
                await self._on_headers(flags, sid, payload)
            elif ftype == DATA:
                await self._on_data(flags, sid, payload)
            elif ftype == SETTINGS:
                await self._on_settings(flags, payload)
            elif ftype == PING:
                if not flags & FLAG_ACK:
                    await self._send_frame(PING, FLAG_ACK, 0, payload)
            elif ftype == WINDOW_UPDATE:
                await self._on_window_update(sid, payload)
            elif ftype == RST_STREAM:
                st = self.streams.pop(sid, None)
                if st is not None and st.task is not None:
                    st.task.cancel()
            elif ftype == GOAWAY:
                # a client GOAWAY forbids NEW streams; everything it
                # already opened — including streams mid-upload (task not
                # yet started) — must still complete (RFC 7540 §6.8)
                self.peer_goaway = True
                if not self.streams:
                    return
            elif ftype == PUSH_PROMISE:
                raise ConnectionError2(
                    PROTOCOL_ERROR, "client sent PUSH_PROMISE"
                )
            elif ftype in (PRIORITY, CONTINUATION):
                # PRIORITY: ignored. Bare CONTINUATION (outside the
                # HEADERS read in _on_headers) is a protocol error.
                if ftype == CONTINUATION:
                    raise ConnectionError2(
                        PROTOCOL_ERROR, "unexpected CONTINUATION"
                    )
            # unknown frame types are ignored (RFC 7540 §4.1)

    async def _on_settings(
        self, flags: int, payload: bytes, ack: bool = True
    ) -> None:
        """Apply a client SETTINGS payload. ack=False for the h2c
        HTTP2-Settings upgrade header (RFC 7540 §3.2.1: treated as the
        client's initial SETTINGS but never ACKed as a frame)."""
        if flags & FLAG_ACK:
            return
        if len(payload) % 6:
            raise ConnectionError2(FRAME_SIZE_ERROR, "bad SETTINGS length")
        for off in range(0, len(payload), 6):
            ident, value = struct.unpack_from(">HI", payload, off)
            if ident == S_INITIAL_WINDOW_SIZE:
                if value > 0x7FFFFFFF:
                    raise ConnectionError2(FLOW_CONTROL_ERROR, "window > 2^31-1")
                delta = value - self.peer_initial_window
                self.peer_initial_window = value
                async with self._window_cv:
                    for st in self.streams.values():
                        st.send_window += delta
                    self._window_cv.notify_all()
            elif ident == S_MAX_FRAME_SIZE:
                if not 16384 <= value <= 16777215:
                    raise ConnectionError2(PROTOCOL_ERROR, "bad MAX_FRAME_SIZE")
                self.peer_max_frame = min(value, MAX_FRAME_SIZE)
            elif ident == S_HEADER_TABLE_SIZE:
                # our stateless encoder never indexes, so any size is fine
                pass
        if ack:
            await self._send_frame(SETTINGS, FLAG_ACK, 0)

    async def _on_window_update(self, sid: int, payload: bytes) -> None:
        if len(payload) != 4:
            raise ConnectionError2(FRAME_SIZE_ERROR, "bad WINDOW_UPDATE")
        inc = int.from_bytes(payload, "big") & 0x7FFFFFFF
        if inc == 0:
            raise ConnectionError2(PROTOCOL_ERROR, "zero WINDOW_UPDATE")
        async with self._window_cv:
            if sid == 0:
                self.conn_send_window += inc
            else:
                st = self.streams.get(sid)
                if st is not None:
                    st.send_window += inc
            self._window_cv.notify_all()

    async def _on_headers(self, flags: int, sid: int, payload: bytes) -> None:
        if sid == 0 or sid % 2 == 0 or sid <= self.last_stream_id:
            raise ConnectionError2(PROTOCOL_ERROR, "bad HEADERS stream id")
        if flags & FLAG_PADDED:
            pad = payload[0]
            payload = payload[1:]
            if pad > len(payload):
                raise ConnectionError2(PROTOCOL_ERROR, "bad padding")
            payload = payload[: len(payload) - pad]
        if flags & FLAG_PRIORITY:
            payload = payload[5:]  # exclusive/dep (4) + weight (1), ignored
        fragment = bytearray(payload)
        end_headers = flags & FLAG_END_HEADERS
        while not end_headers:
            # bounded like the frame loop's reads: a client that sends
            # HEADERS without END_HEADERS then stalls must not pin the
            # connection (and its graceful-shutdown busy slot) forever
            ftype, cflags, csid, cpayload = await asyncio.wait_for(
                self._read_frame(), timeout=IDLE_READ_TIMEOUT
            )
            if ftype != CONTINUATION or csid != sid:
                raise ConnectionError2(
                    PROTOCOL_ERROR, "HEADERS not followed by CONTINUATION"
                )
            fragment += cpayload
            if len(fragment) > MAX_HEADER_BLOCK:
                raise ConnectionError2(PROTOCOL_ERROR, "header block too large")
            end_headers = cflags & FLAG_END_HEADERS
        self.last_stream_id = sid
        # the decoder is connection-scoped and MUST see every block in
        # wire order — including blocks for streams we refuse (RFC 7541
        # §2.2: skipping one desynchronizes the dynamic table and
        # corrupts every later block on the connection)
        decoded = self.decoder.decode(bytes(fragment))
        if len(self.streams) >= MAX_STREAMS or self.peer_goaway:
            await self._send_frame(
                RST_STREAM, 0, sid, struct.pack(">I", REFUSED_STREAM)
            )
            return
        st = _Stream(sid, self.peer_initial_window)
        st.headers = decoded
        self.streams[sid] = st
        if flags & FLAG_END_STREAM:
            st.remote_closed = True
            self._start_dispatch(st)

    async def _on_data(self, flags: int, sid: int, payload: bytes) -> None:
        st = self.streams.get(sid)
        if st is None or st.remote_closed:
            # stream already reset/closed: still account the connection
            # window so the peer doesn't stall
            if payload:
                await self._send_frame(
                    WINDOW_UPDATE, 0, 0,
                    struct.pack(">I", len(payload)),
                )
            return
        raw_len = len(payload)
        if flags & FLAG_PADDED:
            pad = payload[0]
            payload = payload[1:]
            if pad > len(payload):
                raise ConnectionError2(PROTOCOL_ERROR, "bad padding")
            payload = payload[: len(payload) - pad]
        st.body += payload
        if len(st.body) > MAX_BODY_BYTES:
            self.streams.pop(sid, None)
            await self._send_frame(
                RST_STREAM, 0, sid, struct.pack(">I", REFUSED_STREAM)
            )
            return
        if raw_len:
            # replenish both windows immediately: bodies are consumed into
            # memory, so there is no backpressure to express
            await self._send_frame(
                WINDOW_UPDATE, 0, 0, struct.pack(">I", raw_len)
            )
            if not flags & FLAG_END_STREAM:
                await self._send_frame(
                    WINDOW_UPDATE, 0, sid, struct.pack(">I", raw_len)
                )
        if flags & FLAG_END_STREAM:
            st.remote_closed = True
            self._start_dispatch(st)

    # -- dispatch + response ----------------------------------------------

    def _start_dispatch(self, st: _Stream) -> None:
        pseudo = {}
        headers: dict[str, str] = {}
        cookies: list[str] = []
        for name_b, value_b in st.headers:
            name = name_b.decode("latin-1")
            value = value_b.decode("latin-1")
            if name.startswith(":"):
                pseudo[name] = value
            elif name == "cookie":
                cookies.append(value)
            else:
                headers[name] = value
        if cookies:
            headers["cookie"] = "; ".join(cookies)
        if "host" not in headers and ":authority" in pseudo:
            headers["host"] = pseudo[":authority"]
        method = pseudo.get(":method", "GET")
        target = pseudo.get(":path", "/")
        st.task = asyncio.ensure_future(
            self._dispatch(st, method, target, headers, bytes(st.body))
        )

    async def _dispatch(
        self,
        st: _Stream,
        method: str,
        target: str,
        headers: dict[str, str],
        body: bytes,
    ) -> None:
        try:
            status, payload, ctype, extra = await self.server._process(
                method, target, headers, body
            )
            gzip_ok = "gzip" in headers.get("accept-encoding", "").lower()
            await self._respond(
                st, status, payload, ctype, method, gzip_ok, extra
            )
            if self.owner is not None:
                self.owner.requests += 1  # h2 streams count as requests
        except asyncio.CancelledError:
            raise
        except Exception:  # pragma: no cover - defensive
            log.exception("h2 stream dispatch failed")
            try:
                await self._send_frame(
                    RST_STREAM, 0, st.sid, struct.pack(">I", CANCEL)
                )
            except Exception:
                pass
        finally:
            self.streams.pop(st.sid, None)

    async def _respond(
        self,
        st: _Stream,
        status: int,
        payload: bytes,
        ctype: str,
        method: str,
        gzip_ok: bool,
        extra: tuple[tuple[str, str], ...] = (),
    ) -> None:
        hdrs: list[tuple[bytes, bytes]] = [
            (b":status", str(status).encode()),
            (b"content-type", ctype.encode("latin-1")),
            (b"vary", b"accept-encoding"),
        ]
        if gzip_ok and len(payload) >= 1024:
            payload = gzip.compress(payload, compresslevel=5)
            hdrs.append((b"content-encoding", b"gzip"))
        hdrs.append((b"content-length", str(len(payload)).encode()))
        for k, v in extra:
            hdrs.append((k.lower().encode("latin-1"), v.encode("latin-1")))
        block = hpack_encode(hdrs)
        if method == "HEAD" or not payload:
            await self._send_frame(
                HEADERS, FLAG_END_HEADERS | FLAG_END_STREAM, st.sid, block
            )
            return
        await self._send_frame(HEADERS, FLAG_END_HEADERS, st.sid, block)
        view = memoryview(payload)
        sent = 0
        while sent < len(payload):
            # flow control: both windows must be positive to send
            async with self._window_cv:
                await self._window_cv.wait_for(
                    lambda: (
                        min(self.conn_send_window, st.send_window) > 0
                        or st.sid not in self.streams
                    )
                )
                if st.sid not in self.streams:
                    return  # reset while waiting
                quota = min(
                    self.conn_send_window,
                    st.send_window,
                    self.peer_max_frame,
                    len(payload) - sent,
                )
                self.conn_send_window -= quota
                st.send_window -= quota
            chunk = view[sent:sent + quota]
            sent += quota
            await self._send_frame(
                DATA,
                FLAG_END_STREAM if sent == len(payload) else 0,
                st.sid,
                bytes(chunk),
            )
