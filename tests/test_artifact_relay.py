"""Cross-host MODEL-REF resolution over the bus (round-3 verdict #3).

The reference reads MODEL-REF paths through a shared Hadoop FileSystem
(app/oryx-app-common .../pmml/AppPMMLUtils.java:261-275, FileSystem.get),
so every host can fetch the model. Without HDFS, the framework ships the
oversized artifact's bytes as MODEL-CHUNK messages ahead of the MODEL-REF;
the consumer-side ArtifactRelay assembles them into a local cache that
read_artifact_from_update falls back to when the path isn't readable.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import oryx_tpu.common.artifact as artifact_mod
from oryx_tpu.common.artifact import (
    CHUNK_KEY,
    ArtifactRelay,
    ModelArtifact,
    publish_model_ref,
    read_artifact_from_update,
)

ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _fresh_relay(monkeypatch):
    monkeypatch.setattr(artifact_mod, "_RELAY", None)


class _CaptureProducer:
    def __init__(self):
        self.sent: list[tuple[str, str]] = []

    def send(self, key, message):
        self.sent.append((key, message))


def _sample_artifact() -> ModelArtifact:
    # big enough that to_string() far exceeds the 1024-byte max-size the
    # tests publish with (forcing the MODEL-REF + chunk path)
    rng = np.random.default_rng(5)
    return ModelArtifact(
        "kmeans",
        {"k": "3"},
        {"counts": [4, 5, 6]},
        {"centers": rng.standard_normal((3, 2048)).astype(np.float32)},
    )


def test_publish_chunks_then_ref_and_reassemble_out_of_order(tmp_path):
    art = _sample_artifact()
    serialized = art.to_string()
    prod = _CaptureProducer()
    ref = str(tmp_path / "model" / "12345")  # never written: no shared fs
    publish_model_ref(prod, serialized, ref, max_message_size=1024)
    keys = [k for k, _ in prod.sent]
    assert keys[-1] == "MODEL-REF"
    chunks = [m for k, m in prod.sent if k == CHUNK_KEY]
    assert len(chunks) > 1  # really chunked at this max size
    for k, m in prod.sent[:-1]:
        assert len(m.encode()) <= 1024  # every chunk respects max-size

    relay = artifact_mod.artifact_relay()
    # before any chunk: unresolvable, and as an OSError (retry class)
    with pytest.raises(OSError):
        relay.resolve(ref)
    # out-of-order arrival
    for m in reversed(chunks):
        relay.offer(m)
    loaded = ModelArtifact.read(relay.resolve(ref))
    assert loaded.app == "kmeans"
    assert loaded.content["counts"] == [4, 5, 6]
    np.testing.assert_array_equal(
        loaded.tensors["centers"], art.tensors["centers"]
    )
    # the full consumer path resolves through the relay too
    art2 = read_artifact_from_update("MODEL-REF", ref)
    assert art2.extensions["k"] == "3"


def test_local_path_wins_over_cache(tmp_path):
    art = _sample_artifact()
    local = tmp_path / "local-model"
    art.write(local)
    relay = ArtifactRelay()
    assert relay.resolve(str(local)) == str(local)


def test_sha_mismatch_rejected(tmp_path):
    art = _sample_artifact()
    prod = _CaptureProducer()
    ref = str(tmp_path / "m")
    publish_model_ref(prod, art.to_string(), ref, max_message_size=1024)
    chunks = [m for k, m in prod.sent if k == CHUNK_KEY]
    relay = artifact_mod.artifact_relay()
    for m in chunks[:-1]:
        relay.offer(m)
    last = json.loads(chunks[-1])
    last["data"] = last["data"][:-8] + "AAAAAAAA"  # corrupt the payload
    with pytest.raises(ValueError):
        relay.offer(json.dumps(last))
    with pytest.raises(OSError):
        relay.resolve(ref)


def test_transfer_flag_off_sends_bare_ref(tmp_path):
    prod = _CaptureProducer()
    publish_model_ref(
        prod, _sample_artifact().to_string(), str(tmp_path / "m"),
        max_message_size=1024, transfer=False,
    )
    assert [k for k, _ in prod.sent] == ["MODEL-REF"]


def test_serving_manager_loads_chunked_model_without_path(tmp_path):
    """In-process end-to-end: the k-means serving manager consumes the
    chunk stream + MODEL-REF through its normal dispatch loop and loads
    the model even though the referenced path never existed here."""
    from oryx_tpu.apps.kmeans.serving import KMeansServingModelManager
    from oryx_tpu.bus.api import KeyMessage
    from oryx_tpu.common.config import load_config

    art = _sample_artifact()
    prod = _CaptureProducer()
    ref = str(tmp_path / "never-written" / "999")
    publish_model_ref(prod, art.to_string(), ref, max_message_size=1024)

    cfg = load_config(
        overlay={
            "oryx.input-schema.num-features": 8,
            "oryx.input-schema.feature-names": [f"f{i}" for i in range(8)],
            "oryx.input-schema.numeric-features": [f"f{i}" for i in range(8)],
        }
    )
    mgr = KMeansServingModelManager(cfg)
    mgr.consume(iter([KeyMessage(k, m) for k, m in prod.sent]))
    assert mgr.model is not None
    assert mgr.model.centers.shape == (3, 2048)


def test_cross_process_model_ref(tmp_path):
    """The VERDICT's done-bar: a batch process publishes a >max-size model
    over a file:// bus from ITS data dir; a serving consumer with no
    access to that dir (deleted here — no shared mount) still loads it."""
    bus = tmp_path / "bus"
    model_root = tmp_path / "batch-host-models"
    pub = (
        "import sys; sys.path.insert(0, %r)\n"
        "import numpy as np\n"
        "from oryx_tpu.bus.broker import get_broker, topics\n"
        "from oryx_tpu.bus.api import TopicProducer\n"
        "from oryx_tpu.common.artifact import ModelArtifact\n"
        "from oryx_tpu.common.config import load_config\n"
        "from oryx_tpu.ml.update import MLUpdate\n"
        "uri = 'file://%s'\n"
        "topics.maybe_create(uri, 'OryxUpdate', partitions=1)\n"
        "rng = np.random.default_rng(5)\n"
        "art = ModelArtifact('kmeans', {'k': '3'}, {'counts': [4, 5, 6]},\n"
        "                    {'centers': rng.standard_normal((3, 2048)).astype(np.float32)})\n"
        "path = art.write(%r)\n"
        "cfg = load_config(overlay={'oryx.update-topic.message.max-size': 1024})\n"
        "class Pub(MLUpdate):\n"
        "    def build_model(self, *a, **k): raise NotImplementedError\n"
        "    def evaluate(self, *a, **k): raise NotImplementedError\n"
        "prod = TopicProducer(get_broker(uri), 'OryxUpdate')\n"
        "Pub(cfg).publish_model(art, str(path), prod)\n"
        "print('PUBLISHED')\n"
    ) % (str(ROOT), bus, str(model_root / "12345"))
    r = subprocess.run(
        [sys.executable, "-c", pub], capture_output=True, text=True, timeout=120
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "PUBLISHED" in r.stdout

    # simulate a different host: the batch host's model dir is unreachable
    import shutil

    shutil.rmtree(model_root)

    from oryx_tpu.apps.kmeans.serving import KMeansServingModelManager
    from oryx_tpu.bus.api import ConsumeDataIterator
    from oryx_tpu.bus.broker import get_broker
    from oryx_tpu.common.config import load_config

    cfg = load_config(
        overlay={
            "oryx.input-schema.num-features": 8,
            "oryx.input-schema.feature-names": [f"f{i}" for i in range(8)],
            "oryx.input-schema.numeric-features": [f"f{i}" for i in range(8)],
        }
    )
    mgr = KMeansServingModelManager(cfg)
    it = ConsumeDataIterator(
        get_broker(f"file://{bus}"), "OryxUpdate", group="s", start="earliest"
    )
    msgs = it.poll_available()
    assert any(k == "MODEL-REF" for k, _ in msgs)
    mgr.consume(iter(msgs))
    assert mgr.model is not None
    assert mgr.model.centers.shape == (3, 2048)
    np.testing.assert_array_equal(
        np.asarray(mgr.model.counts), np.array([4, 5, 6])
    )


def test_cache_stable_across_restarts_and_capped(tmp_path, monkeypatch):
    """Replay on restart must overwrite the same cache paths (no growth),
    and the per-process cache is LRU-capped so history can't accrete."""
    art = _sample_artifact()
    prod = _CaptureProducer()
    refs = [str(tmp_path / f"gen-{g}") for g in range(3)]
    for ref in refs:
        publish_model_ref(prod, art.to_string(), ref, max_message_size=4096)
    chunk_msgs = [(k, m) for k, m in prod.sent if k == CHUNK_KEY]

    croot = tmp_path / "cache-root"
    croot.mkdir()

    def fresh_relay():
        r = ArtifactRelay()
        r._cache_root = croot  # isolate from other tests' shared root
        return r

    r1 = fresh_relay()
    for _, m in chunk_msgs:
        r1.offer(m)
    dests1 = {ref: r1.resolve(ref) for ref in refs}

    # a "restarted" process replays the same history: same dests, nothing
    # new on disk
    r2 = fresh_relay()
    for _, m in chunk_msgs:
        r2.offer(m)
    for ref in refs:
        assert r2.resolve(ref) == dests1[ref]
    root = Path(dests1[refs[0]]).parent
    entries = [p for p in root.iterdir() if not p.name.startswith(".")]
    assert len(entries) == len(refs)

    # LRU cap: with MAX_CACHED=2, materializing 3 refs keeps only the
    # newest two on disk (in this relay's view)
    monkeypatch.setattr(ArtifactRelay, "MAX_CACHED", 2)
    r3 = fresh_relay()
    for _, m in chunk_msgs:
        r3.offer(m)
    with pytest.raises(OSError):
        r3.resolve(refs[0])  # evicted
    assert r3.resolve(refs[2])  # newest survives


def test_oversized_pending_is_never_self_evicted(monkeypatch):
    """An artifact bigger than the pending cap must still assemble — only
    OTHER refs' stale chunks are evicted (the in-flight transfer's memory
    floor is the artifact size, same as the publisher paid)."""
    monkeypatch.setattr(ArtifactRelay, "MAX_PENDING_BYTES", 1024)
    art = _sample_artifact()  # serialized ~30KB >> 1KB cap
    prod = _CaptureProducer()
    ref = "/nowhere/big-model"
    publish_model_ref(prod, art.to_string(), ref, max_message_size=4096)
    relay = ArtifactRelay()
    for k, m in prod.sent:
        if k == CHUNK_KEY:
            relay.offer(m)
    loaded = ModelArtifact.read(relay.resolve(ref))
    np.testing.assert_array_equal(
        loaded.tensors["centers"], art.tensors["centers"]
    )


def test_republish_with_new_bytes_restarts_assembly(tmp_path):
    """Same chunk count, new content (publisher rebuilt the model at the
    same path): the assembly must restart on the new sha, not verify the
    mixed stream against the stale one forever."""
    rng = np.random.default_rng(9)
    ref = str(tmp_path / "gen")
    old = ModelArtifact("kmeans", {}, {}, {"centers": rng.standard_normal((3, 2048)).astype(np.float32)})
    new = ModelArtifact("kmeans", {}, {}, {"centers": rng.standard_normal((3, 2048)).astype(np.float32)})
    p_old, p_new = _CaptureProducer(), _CaptureProducer()
    publish_model_ref(p_old, old.to_string(), ref, max_message_size=4096)
    publish_model_ref(p_new, new.to_string(), ref, max_message_size=4096)
    old_chunks = [m for k, m in p_old.sent if k == CHUNK_KEY]
    new_chunks = [m for k, m in p_new.sent if k == CHUNK_KEY]
    assert len(old_chunks) == len(new_chunks)  # same n: the nasty case
    relay = ArtifactRelay()
    for m in old_chunks[: len(old_chunks) // 2]:  # publisher died mid-send
        relay.offer(m)
    for m in new_chunks:  # republish, full stream
        relay.offer(m)
    loaded = ModelArtifact.read(relay.resolve(ref))
    np.testing.assert_array_equal(
        loaded.tensors["centers"], new.tensors["centers"]
    )


def test_unresolved_ref_parks_and_redispatches_on_late_arrival(tmp_path):
    """Round-4 advice: the dispatch loop's short OSError retries gave up
    ~1.2s after a MODEL-REF arrived, permanently dropping the model when
    its chunk stream simply hadn't finished (multi-partition lag,
    sha-mismatch republish). The relay now parks a re-dispatch that fires
    when the artifact materializes."""
    from oryx_tpu.api import _dispatch_update
    from oryx_tpu.bus.api import KeyMessage

    art = _sample_artifact()
    prod = _CaptureProducer()
    ref = str(tmp_path / "model" / "777")  # never written: no shared fs
    publish_model_ref(prod, art.to_string(), ref, max_message_size=1024)
    chunks = [m for k, m in prod.sent if k == CHUNK_KEY]

    loaded = []

    def handler(key, message):
        loaded.append(read_artifact_from_update(key, message))

    # the REF arrives BEFORE any chunk (out-of-order delivery): dispatch
    # exhausts its retries and parks
    _dispatch_update(handler, KeyMessage("MODEL-REF", ref))
    assert loaded == []
    # chunks finally land: materialization must fire the parked dispatch
    for m in chunks:
        _dispatch_update(handler, KeyMessage("MODEL-CHUNK", m))
    assert len(loaded) == 1
    assert loaded[0].extensions["k"] == "3"


def test_resolve_rechecks_existence_after_sibling_eviction(tmp_path):
    """Round-4 advice: with the cache root shared per-user across
    processes, a sibling's eviction could delete a dir this process still
    held in its in-memory map — resolve() must surface the retry class
    (FileNotFoundError), never a dead path."""
    import shutil

    art = _sample_artifact()
    prod = _CaptureProducer()
    ref = str(tmp_path / "m2")
    publish_model_ref(prod, art.to_string(), ref, max_message_size=4096)
    relay = ArtifactRelay()
    for k, m in prod.sent:
        if k == CHUNK_KEY:
            relay.offer(m)
    cached = Path(relay.resolve(ref))
    shutil.rmtree(cached)  # the sibling process's eviction
    with pytest.raises(FileNotFoundError):
        relay.resolve(ref)


def test_cache_eviction_is_cross_process_lru_by_mtime(tmp_path, monkeypatch):
    """Eviction ranks by shared directory mtimes (bumped on materialize
    and resolve), so every process sharing the root agrees on the LRU
    order; recently-touched dirs survive."""
    import os

    monkeypatch.setattr(ArtifactRelay, "MAX_CACHED", 3)
    relay = ArtifactRelay()
    relay._cache_root = tmp_path / "isolated-root"  # not the shared /tmp
    relay._cache_root.mkdir()
    paths = []
    for i in range(5):
        ref = str(tmp_path / f"gen-{i}")
        relay._materialize(ref, ModelArtifact("kmeans", {"i": str(i)}, {}, {}))
        p = relay._dest(ref)
        os.utime(p, (1000 + i, 1000 + i))  # deterministic LRU order
        paths.append(p)
    relay._evict_cache_dirs(keep=paths[-1])
    alive = [p.exists() for p in paths]
    # 5 dirs, cap 3: the two oldest stamps go
    assert alive == [False, False, True, True, True], alive


def test_pinned_refs_survive_eviction_until_unpinned(tmp_path, monkeypatch):
    """A pinned ref (a model-gate rollback target) is exempt from LRU
    eviction no matter how old its mtime; unpinning makes it ordinary
    again. Pins are refcounted so two holders must both release."""
    import os

    monkeypatch.setattr(ArtifactRelay, "MAX_CACHED", 2)
    relay = ArtifactRelay()
    relay._cache_root = tmp_path / "isolated-root"
    relay._cache_root.mkdir()
    pinned_ref = str(tmp_path / "gen-0")
    relay._materialize(pinned_ref, ModelArtifact("kmeans", {"i": "0"}, {}, {}))
    os.utime(relay._dest(pinned_ref), (1000, 1000))  # oldest = first victim
    relay.pin(pinned_ref)
    relay.pin(pinned_ref)  # a second holder (parked + history)
    newer = []
    for i in range(1, 5):
        ref = str(tmp_path / f"gen-{i}")
        relay._materialize(ref, ModelArtifact("kmeans", {"i": str(i)}, {}, {}))
        os.utime(relay._dest(ref), (1000 + i, 1000 + i))
        newer.append(ref)
    relay._evict_cache_dirs(keep=relay._dest(newer[-1]))
    # over cap and oldest by mtime — but pinned, so it resolves
    assert Path(relay.resolve(pinned_ref)).exists()

    def _pressure(start: int) -> None:
        # re-age gen-0 to the LRU victim slot FIRST (resolve() bumped
        # its mtime as a shared LRU touch), then refill above cap —
        # each materialize runs the evictor inline
        os.utime(relay._dest(pinned_ref), (1000, 1000))
        for i in range(start, start + 3):
            r = str(tmp_path / f"gen-{i}")
            relay._materialize(r, ModelArtifact("kmeans", {"i": str(i)}, {}, {}))
            newer.append(r)

    relay.unpin(pinned_ref)
    _pressure(5)
    assert Path(relay.resolve(pinned_ref)).exists()  # one holder remains
    relay.unpin(pinned_ref)
    _pressure(8)
    with pytest.raises(OSError):
        relay.resolve(pinned_ref)
