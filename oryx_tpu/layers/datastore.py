"""Generation data store: append-only persistence of each batch window,
plus the incremental-aggregate snapshot that lets generation N cost
O(new window) instead of O(all history).

The reference appends every generation's input as Hadoop SequenceFiles
under dataDir/oryx-<timestamp>/ (SaveToHDFSFunction, skipping empty RDDs,
BatchLayer.java:122-130) and re-reads ALL past data each generation with a
glob (BatchUpdateFunction.java:103-130); TTL cleanup deletes aged dirs
(DeleteOldDataFn). Here each generation is one record-log file using the
bus wire format — so the native appender/scanner accelerate it too — under
<data-dir>/oryx-<timestamp>/data.log.

History reads stream in bounded chunks (iter_all_data) so the from-scratch
rebuild path never materializes the whole log in one read call, and the
incremental path (LazyPastData + the aggregate snapshot under
<data-dir>/.agg-snapshot/) never reads history at all.
"""

from __future__ import annotations

import logging
import os
import tempfile
from pathlib import Path
from typing import Iterator, Sequence

import numpy as np

from oryx_tpu.bus.api import KeyMessage
from oryx_tpu.bus.filelog import _PartitionIndex, encode_record, _maybe_native
from oryx_tpu.common import faults
from oryx_tpu.common.retry import retry_call
from oryx_tpu.common.ioutil import (
    delete_recursively,
    list_generation_dirs,
    mkdirs,
    strip_scheme,
)

log = logging.getLogger(__name__)

_DATA_FILE = "data.log"

# Bounded read size for history streaming: one chunk of records is in
# memory per read call, never the whole multi-generation log.
_READ_CHUNK_RECORDS = 65_536

_SNAPSHOT_DIR = ".agg-snapshot"


def save_generation(data_dir: str, timestamp_ms: int, records: Sequence[KeyMessage]) -> Path | None:
    """Persist one generation's window; empty windows write nothing
    (SaveToHDFSFunction skips empty RDDs). The append runs under the
    bounded-retry contract (site "datastore.save"): losing a window to a
    transient disk hiccup is permanent input loss (the offsets commit
    right after), so this path absorbs what it can and fails loudly past
    the deadline — the caller then leaves offsets uncommitted and the
    window is re-delivered."""
    if not records:
        return None
    d = mkdirs(Path(strip_scheme(data_dir)) / f"oryx-{timestamp_ms}")
    path = d / _DATA_FILE
    blob = b"".join(encode_record(km.key, km.message) for km in records)
    native = _maybe_native()

    def _do() -> None:
        faults.fire("datastore.save_window")
        if native is not None:
            native.append_batch(str(path), blob)
        else:
            # single unbuffered append: a crash mid-write leaves a torn
            # TAIL, which the record scanner stops at (filelog
            # _PartitionIndex) — never a mid-log hole. A retried attempt
            # after a torn write would double-append, so roll back to the
            # pre-append size first.
            pre = path.stat().st_size if path.exists() else 0
            fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
            try:
                try:
                    wrote = os.write(fd, blob)
                except OSError:
                    os.ftruncate(fd, pre)
                    raise
                if wrote != len(blob):
                    os.ftruncate(fd, pre)
                    raise OSError(f"short append to {path}")
            finally:
                os.close(fd)

    retry_call("datastore.save", _do)
    return d


def iter_all_data(
    data_dir: str, chunk_records: int = _READ_CHUNK_RECORDS
) -> Iterator[KeyMessage]:
    """Stream every persisted generation, oldest first, in bounded read
    chunks — the fallback full-rebuild path must not OOM on long
    histories by pulling the entire log through one read call."""
    for gen_dir in list_generation_dirs(strip_scheme(data_dir)):
        path = gen_dir / _DATA_FILE
        if not path.exists():
            continue
        idx = _PartitionIndex(path, _maybe_native())
        offset = 0
        while True:
            recs = idx.read(offset, chunk_records)
            if not recs:
                break
            for _, k, m in recs:
                yield KeyMessage(k, m)
            offset += len(recs)


def load_all_data(data_dir: str) -> list[KeyMessage]:
    """All persisted generations, oldest first — the 'pastData' input to a
    batch model build."""
    return list(iter_all_data(data_dir))


def latest_generation_ts(data_dir: str) -> int | None:
    """Timestamp of the newest persisted generation with data, or None."""
    from oryx_tpu.common.ioutil import timestamp_from_dirname

    newest = None
    for gen_dir in list_generation_dirs(strip_scheme(data_dir)):
        if (gen_dir / _DATA_FILE).exists():
            ts = timestamp_from_dirname(gen_dir.name)
            if ts is not None and (newest is None or ts > newest):
                newest = ts
    return newest


class LazyPastData(Sequence):
    """Sequence view over persisted history that reads NOTHING until a
    consumer actually touches it. The incremental batch path merges only
    the new window into its aggregate snapshot and never materializes
    this; the from-scratch fallback (and any non-incremental update)
    list()s it and pays the streamed read then."""

    def __init__(self, data_dir: str):
        self._data_dir = data_dir
        self._records: list[KeyMessage] | None = None

    def _materialize(self) -> list[KeyMessage]:
        if self._records is None:
            self._records = load_all_data(self._data_dir)
        return self._records

    def known_len(self) -> int | None:
        """len() if already read, else None — trace attributes must not
        force the full history read the lazy path exists to avoid."""
        return None if self._records is None else len(self._records)

    def __len__(self) -> int:
        return len(self._materialize())

    def __getitem__(self, i):
        return self._materialize()[i]

    def __iter__(self):
        return iter(self._materialize())

    def __bool__(self) -> bool:
        # cheap existence probe: any generation dir with a data file
        if self._records is not None:
            return bool(self._records)
        for gen_dir in list_generation_dirs(strip_scheme(self._data_dir)):
            if (gen_dir / _DATA_FILE).exists():
                return True
        return False


# ---------------------------------------------------------------------------
# aggregate snapshots: the persistent state behind incremental generations
# ---------------------------------------------------------------------------

def save_aggregate_snapshot(
    data_dir: str,
    timestamp_ms: int,
    fingerprint: str,
    arrays: dict[str, np.ndarray],
    keep: int = 2,
    staged: bool = False,
) -> Path:
    """Persist one generation's aggregate state as a compact columnar npz
    alongside the generation logs. Atomic (tmp + rename), fingerprinted
    against the aggregation schema, pruned to the newest `keep` so disk
    cost stays O(aggregate), not O(generations).

    staged=True writes an ``.npz.staged`` file that load ignores until
    finalize_aggregate_snapshot renames it. The batch layer finalizes
    AFTER the window is persisted and its offsets committed: a snapshot
    that became durable first would double-fold the window when a crash
    in between re-delivers it (the fold is in the snapshot, the window is
    re-read as new data)."""
    d = mkdirs(Path(strip_scheme(data_dir)) / _SNAPSHOT_DIR)
    path = d / f"agg-{timestamp_ms}.npz{'.staged' if staged else ''}"
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp.npz")
    os.close(fd)
    try:
        faults.fire("datastore.snapshot_write")
        np.savez(
            tmp,
            fingerprint=np.asarray(fingerprint),
            through_ts=np.asarray(timestamp_ms, dtype=np.int64),
            **arrays,
        )
        # np.savez appends .npz to paths without the suffix; ours has it.
        # Retried (site "datastore.rename"): the tmp is complete, so only
        # the cheap rename replays.
        retry_call("datastore.rename", os.replace, tmp, path)
    except BaseException:
        Path(tmp).unlink(missing_ok=True)
        raise
    if not staged:
        _prune_snapshots(data_dir, keep, path)
    return path


def finalize_aggregate_snapshot(
    data_dir: str, timestamp_ms: int, keep: int = 2
) -> bool:
    """Promote a staged snapshot to loadable — called once the generation
    that folded it is persisted and committed. Returns False when nothing
    was staged (no-op)."""
    d = Path(strip_scheme(data_dir)) / _SNAPSHOT_DIR
    staged = d / f"agg-{timestamp_ms}.npz.staged"
    if not staged.exists():
        return False
    final = d / f"agg-{timestamp_ms}.npz"

    def _do() -> None:
        faults.fire("datastore.snapshot_rename")
        os.replace(staged, final)

    # a crash here (between the staged write and this promote) is SAFE by
    # construction: load ignores .staged files, so the next generation
    # sees a stale-or-missing snapshot and falls back to the from-scratch
    # rebuild that re-anchors it (pinned by tests/test_datastore_crash.py)
    retry_call("datastore.rename", _do)
    _prune_snapshots(data_dir, keep, final)
    return True


def _prune_snapshots(data_dir: str, keep: int, just_wrote: Path) -> None:
    if keep <= 0:
        return
    for old in _snapshot_paths(data_dir)[:-keep]:
        if old != just_wrote:
            delete_recursively(old)
    # staged leftovers from crashed generations are dead weight
    d = Path(strip_scheme(data_dir)) / _SNAPSHOT_DIR
    for p in d.iterdir():
        if p.name.endswith(".npz.staged") and p != just_wrote:
            try:
                if int(p.name[4:-11]) < int(just_wrote.name[4:-4]):
                    delete_recursively(p)
            except ValueError:
                continue


def _snapshot_paths(data_dir: str) -> list[Path]:
    d = Path(strip_scheme(data_dir)) / _SNAPSHOT_DIR
    if not d.is_dir():
        return []
    out = []
    for p in d.iterdir():
        if p.name.startswith("agg-") and p.name.endswith(".npz"):
            try:
                out.append((int(p.name[4:-4]), p))
            except ValueError:
                continue
    return [p for _, p in sorted(out)]


def load_aggregate_snapshot(
    data_dir: str, fingerprint: str
) -> tuple[int, dict[str, np.ndarray]] | None:
    """Newest snapshot whose fingerprint matches, as (through_ts, arrays).
    A missing, unreadable, or schema-mismatched snapshot returns None —
    the caller's cue for a from-scratch rebuild. Callers must ALSO check
    the through_ts against latest_generation_ts: a persisted window newer
    than the snapshot means a generation's merge was lost (e.g. a crash
    between persist and snapshot) and the state is stale."""
    for path in reversed(_snapshot_paths(data_dir)):
        try:
            with np.load(path, allow_pickle=False) as z:
                if str(z["fingerprint"]) != fingerprint:
                    log.info(
                        "aggregate snapshot %s has fingerprint %s, want %s; "
                        "ignoring", path.name, z["fingerprint"], fingerprint,
                    )
                    continue
                arrays = {
                    k: z[k]
                    for k in z.files
                    if k not in ("fingerprint", "through_ts")
                }
                return int(z["through_ts"]), arrays
        except Exception:  # noqa: BLE001 - torn/corrupt snapshot = rebuild
            log.warning("ignoring unreadable aggregate snapshot %s", path)
    return None
