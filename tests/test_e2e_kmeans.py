"""End-to-end k-means lambda slice: ingest -> batch build -> update topic
-> serving answers /assign + /distanceToNearest -> speed layer shifts
centroids from /add traffic -> serving applies the moves.

The clustering analogue of test_e2e_als.py (the reference's
KMeansUpdateIT + serving ITs), over the in-process broker with a real
HTTP server.
"""

import json
import time

import numpy as np
import pytest

from oryx_tpu.apps.kmeans import KMeansSpeedModelManager, KMeansUpdate
from oryx_tpu.apps.kmeans.serving import KMeansServingModelManager
from oryx_tpu.bus.broker import get_broker, topics
from oryx_tpu.bus.inproc import InProcBroker
from oryx_tpu.common.config import load_config
from oryx_tpu.common.rng import RandomManager
from oryx_tpu.layers import BatchLayer, SpeedLayer
from oryx_tpu.serving.server import ServingLayer


@pytest.fixture(autouse=True)
def _fresh_registry():
    InProcBroker.reset_all()
    yield
    InProcBroker.reset_all()


from e2e_common import http_request as _http  # noqa: E402


def _cfg(tmp_path):
    return load_config(overlay={
        "oryx.id": "e2ekm",
        "oryx.input-topic.broker": "mem://e2ekm",
        "oryx.update-topic.broker": "mem://e2ekm",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.serving.api.port": 0,
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.clustering",
        ],
        "oryx.input-schema.num-features": 2,
        "oryx.input-schema.numeric-features": ["0", "1"],
        "oryx.kmeans.hyperparams.k": 2,
        "oryx.kmeans.iterations": 10,
        "oryx.ml.eval.test-fraction": 0.2,
        "oryx.serving.min-model-load-fraction": 1.0,
        "oryx.speed.min-model-load-fraction": 0.8,
    })


def _blob_lines(seed=0):
    rng = np.random.default_rng(seed)
    lines = []
    for c in ((0.0, 0.0), (10.0, 10.0)):
        for _ in range(40):
            lines.append(f"{rng.normal(c[0], 0.2):.4f},{rng.normal(c[1], 0.2):.4f}")
    return lines


def test_full_kmeans_slice(tmp_path):
    RandomManager.use_test_seed(42)
    cfg = _cfg(tmp_path)
    topics.maybe_create("mem://e2ekm", "OryxInput", partitions=2)
    topics.maybe_create("mem://e2ekm", "OryxUpdate", partitions=1)
    broker = get_broker("mem://e2ekm")

    serving = ServingLayer(cfg, model_manager=KMeansServingModelManager(cfg))
    serving.start()
    base = f"http://127.0.0.1:{serving.port}"
    status, _ = _http("GET", f"{base}/ready")
    assert status == 503

    lines = _blob_lines()
    status, resp = _http("POST", f"{base}/ingest", body="\n".join(lines).encode())
    assert status == 200, resp

    batch = BatchLayer(cfg, update=KMeansUpdate(cfg))
    batch.ensure_streams()
    batch._consumer._fetch_pos = {p: 0 for p in batch._consumer._fetch_pos}
    n = batch.run_generation(timestamp_ms=1_700_000_000_000)
    assert n == len(lines)
    batch.close()
    assert broker.read("OryxUpdate", 0, 0, 5)[0][1] == "MODEL"

    deadline = time.time() + 30
    while time.time() < deadline:
        status, _ = _http("GET", f"{base}/ready")
        if status == 200:
            break
        time.sleep(0.1)
    assert status == 200, "serving never became ready"

    # the two blobs land in different clusters, near their centers
    status, a0 = _http("GET", f"{base}/assign/0.1,0.1")
    assert status == 200
    status, a1 = _http("GET", f"{base}/assign/9.9,10.1")
    assert status == 200
    assert json.loads(a0) != json.loads(a1)
    status, d = _http("GET", f"{base}/distanceToNearest/0.1,0.1")
    assert status == 200 and float(json.loads(d)) < 1.0

    # console section
    status, resp = _http("GET", f"{base}/console")
    assert status == 200 and "cluster" in resp.lower()

    # ---- speed tier: /add traffic drags a centroid toward (12,12) ----
    status, d_before = _http("GET", f"{base}/distanceToNearest/12.0,12.0")
    assert status == 200
    d_before = float(json.loads(d_before))

    speed = SpeedLayer(cfg, manager=KMeansSpeedModelManager(cfg))
    speed.start()
    deadline = time.time() + 30
    while time.time() < deadline:
        if speed.manager._model is not None:
            break
        time.sleep(0.1)
    assert speed.manager._model is not None

    # baseline BEFORE injecting: the micro-batch consumer is async and
    # could otherwise process everything before the baseline is read
    before = speed.batch_count
    for _ in range(30):
        status, _ = _http("POST", f"{base}/add/12.0,12.0")
        assert status == 200
    deadline = time.time() + 30
    while speed.batch_count == before and time.time() < deadline:
        time.sleep(0.1)

    deadline = time.time() + 30
    d_after = d_before
    while time.time() < deadline:
        status, resp = _http("GET", f"{base}/distanceToNearest/12.0,12.0")
        if status == 200:
            d_after = float(json.loads(resp))
            if d_after < d_before - 0.05:
                break
        time.sleep(0.2)
    assert d_after < d_before - 0.05, (d_before, d_after)

    speed.close()
    serving.close()
