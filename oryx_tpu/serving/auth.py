"""HTTP authentication for the serving layer.

Parity: the reference protects all endpoints with DIGEST auth against a
single-user in-memory realm (ServingLayer.java DIGEST constant +
InMemoryRealm; user/password from oryx.serving.api.user-name/password).
RFC 7616 MD5 digest with qop="auth"; nonces are HMAC-stamped timestamps so
validation is stateless (no nonce table to grow or lock), with a freshness
window and `stale=true` re-challenge semantics. Basic over TLS remains
available via oryx.serving.api.auth-scheme = "basic".
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import secrets
import time

REALM = "Oryx"
_NONCE_TTL_SEC = 300.0


def _md5(s: str) -> str:
    return hashlib.md5(s.encode("utf-8")).hexdigest()


def _parse_auth_params(header: str) -> dict[str, str]:
    """Parse the comma-separated (possibly quoted) k=v list of a Digest
    Authorization header."""
    out: dict[str, str] = {}
    rest = header
    while rest:
        rest = rest.lstrip(", ")
        if "=" not in rest:
            break
        key, rest = rest.split("=", 1)
        key = key.strip().lower()
        if rest.startswith('"'):
            end = rest.find('"', 1)
            if end < 0:
                break
            out[key] = rest[1:end]
            rest = rest[end + 1:]
        else:
            end = rest.find(",")
            if end < 0:
                out[key] = rest.strip()
                rest = ""
            else:
                out[key] = rest[:end].strip()
                rest = rest[end:]
    return out


class Authenticator:
    """Interface: check(method, uri, auth_header) -> True | challenge str.

    A str return is the WWW-Authenticate value to send with a 401.
    """

    def check(self, method: str, uri: str, header: str | None):  # pragma: no cover
        raise NotImplementedError


class BasicAuthenticator(Authenticator):
    def __init__(self, user: str, password: str):
        token = base64.b64encode(f"{user}:{password}".encode()).decode()
        self._expect = f"Basic {token}"

    def check(self, method: str, uri: str, header: str | None):
        if header is not None and hmac.compare_digest(header, self._expect):
            return True
        return f'Basic realm="{REALM}"'


class DigestAuthenticator(Authenticator):
    """Stateless RFC 7616 (MD5, qop=auth) verifier for one user."""

    def __init__(self, user: str, password: str, secret: bytes | None = None):
        self.user = user
        # HA1 precomputed: the realm never changes, and this mirrors the
        # reference's digest-ready credential storage in InMemoryRealm
        self._ha1 = _md5(f"{user}:{REALM}:{password}")
        self._secret = secret if secret is not None else os.urandom(32)

    # -- nonces ------------------------------------------------------------

    def _make_nonce(self) -> str:
        ts = f"{time.time():.3f}"
        mac = hmac.new(self._secret, ts.encode(), hashlib.sha256).hexdigest()[:24]
        return f"{ts}:{mac}"

    def _nonce_fresh(self, nonce: str) -> bool:
        ts, _, mac = nonce.partition(":")
        want = hmac.new(self._secret, ts.encode(), hashlib.sha256).hexdigest()[:24]
        if not hmac.compare_digest(mac, want):
            return False
        try:
            age = time.time() - float(ts)
        except ValueError:
            return False
        # small negative tolerance: the stamp is rounded to the nearest ms,
        # so a just-issued nonce can sit fractionally in the future
        return -1.0 <= age <= _NONCE_TTL_SEC

    def challenge(self, stale: bool = False) -> str:
        extra = ", stale=true" if stale else ""
        return (
            f'Digest realm="{REALM}", qop="auth", algorithm=MD5, '
            f'nonce="{self._make_nonce()}", opaque="{secrets.token_hex(8)}"{extra}'
        )

    # -- verification ------------------------------------------------------

    def check(self, method: str, uri: str, header: str | None):
        if not header or not header.startswith("Digest "):
            return self.challenge()
        p = _parse_auth_params(header[len("Digest "):])
        required = ("username", "nonce", "uri", "response")
        if any(k not in p for k in required):
            return self.challenge()
        if p["username"] != self.user:
            return self.challenge()
        # uri must match the request target (ignore authority-form quirks)
        if p["uri"] != uri:
            return self.challenge()
        ha2 = _md5(f"{method}:{p['uri']}")
        qop = p.get("qop")
        if qop == "auth":
            if "nc" not in p or "cnonce" not in p:
                return self.challenge()
            expect = _md5(
                f"{self._ha1}:{p['nonce']}:{p['nc']}:{p['cnonce']}:auth:{ha2}"
            )
        elif qop is None:  # RFC 2069 compatibility
            expect = _md5(f"{self._ha1}:{p['nonce']}:{ha2}")
        else:
            return self.challenge()
        if not hmac.compare_digest(p["response"], expect):
            return self.challenge()
        if not self._nonce_fresh(p["nonce"]):
            # correct credentials, expired nonce: re-challenge without
            # making the client re-prompt (RFC 7616 stale semantics)
            return self.challenge(stale=True)
        return True


def make_authenticator(config) -> Authenticator | None:
    """Build the configured authenticator, or None when auth is off
    (user-name/password unset, like the reference's optional realm)."""
    user = config.get_string("oryx.serving.api.user-name", None)
    password = config.get_string("oryx.serving.api.password", None)
    if not user or not password:
        return None
    scheme = (config.get_string("oryx.serving.api.auth-scheme", None) or "digest").lower()
    if scheme == "basic":
        return BasicAuthenticator(user, password)
    if scheme == "digest":
        return DigestAuthenticator(user, password)
    raise ValueError(f"unknown oryx.serving.api.auth-scheme: {scheme}")
