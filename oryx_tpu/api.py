"""User-facing SPI — the three interfaces an application implements.

Mirrors the reference's framework/oryx-api contract (SURVEY.md §2.2):
  - BatchLayerUpdate.run_update: invoked once per batch generation with the
    new-data window, all past data, the model dir, and an update-topic
    producer (reference .../api/batch/BatchLayerUpdate.java)
  - SpeedModelManager: consume() runs forever on the update-topic listener
    thread; build_updates() turns each micro-batch into update messages
    (reference .../api/speed/SpeedModelManager.java)
  - ServingModelManager / ServingModel: consume() likewise; get_model() is
    read by REST resources; fraction_loaded gates readiness
    (reference .../api/serving/ServingModelManager.java, ServingModel.java)

Data items are KeyMessage(key, message) pairs; "RDDs" are plain sequences —
the heavy lifting happens inside jitted ops, not in the carrier collection.
"""

from __future__ import annotations

import logging
import time
from abc import ABC, abstractmethod
from typing import Iterable, Iterator, Sequence

from oryx_tpu.bus.api import KeyMessage, TopicProducer
from oryx_tpu.common.config import Config

_log = logging.getLogger(__name__)


def _note_model_freshness(
    key: str | None,
    loaded: bool,
    parked: bool = False,
    message: str | None = None,
) -> None:
    """Feed the model-freshness tracker (common/freshness.py) after a
    MODEL/MODEL-REF dispatch — no-op for other keys, and NEVER lets its
    own failure (e.g. a metric registration collision at tracker
    construction) escape into the update-listener thread, which must
    survive anything per _dispatch_update's isolation contract. `parked`
    marks a MODEL-REF awaiting a late artifact: its stamp is held for the
    re-dispatched load instead of dropped."""
    if key not in ("MODEL", "MODEL-REF"):
        return
    try:
        from oryx_tpu.common.freshness import model_freshness

        if loaded:
            model_freshness().note_loaded(key, message=message)
        else:
            # the model did NOT load: its stamp must not claim an earlier
            # successful load (but a parked one may be claimed later)
            model_freshness().note_load_failed(parked=parked, message=message)
    except Exception:  # pragma: no cover - defensive
        _log.exception("model freshness hook failed")


def _dispatch_update(handler, km: KeyMessage) -> None:
    """Per-message dispatch with error isolation: a poison message must not
    kill the listener thread (it replays from earliest on restart and would
    hit the same message forever, freezing the model). MODEL/MODEL-REF I/O
    failures may be transient (MODEL-REF points at shared storage that can
    lag the publish), so only OSError retries — briefly, because replay
    also walks MODEL-REFs whose artifacts were TTL-pruned long ago, and
    every sleep here multiplies across that history. Parse/validation
    errors are deterministic and never retried."""
    if km.key == "MODEL-CHUNK":
        # framework-level artifact transfer (common/artifact.py
        # ArtifactRelay): assembled here so every app manager can resolve
        # a MODEL-REF without a shared filesystem; app handlers never see
        # the chunks
        from oryx_tpu.common.artifact import artifact_relay

        try:
            artifact_relay().offer(km.message)
        except Exception:
            _log.exception("ignoring bad MODEL-CHUNK message")
        return
    if km.key in ("MODEL", "MODEL-REF", "TRACE"):
        # staged-adoption gate (common/modelgate.py): on a canary or
        # held replica the gate buffers each model until its stamp names
        # a generation, then adopts/holds/refuses it. Off (the default)
        # this is one attribute read; a consumed message is the gate's
        # to deliver later through _dispatch_model below.
        from oryx_tpu.common.modelgate import get_model_gate

        gate = get_model_gate()
        if gate.active:
            try:
                if gate.offer(handler, km):
                    return
            except Exception:  # pragma: no cover - defensive
                _log.exception("model gate failed; dispatching ungated")
    if km.key == "TRACE":
        # framework-level publish stamp (common/freshness.py): follows its
        # MODEL/MODEL-REF on the update topic and feeds the
        # oryx_update_to_serve_seconds / oryx_model_staleness_seconds
        # metrics; app handlers never see it
        from oryx_tpu.common.freshness import model_freshness

        try:
            model_freshness().note_stamp(km.message)
        except Exception:
            _log.exception("ignoring bad TRACE publish stamp")
        return
    _dispatch_model(handler, km)


def _dispatch_model(handler, km: KeyMessage) -> None:
    """The retry/park/freshness leg of _dispatch_update, factored out so
    the model gate can deliver an adopted generation through the exact
    same machinery (and a parked MODEL-REF's late re-dispatch re-enters
    HERE, below the gate — the gate already decided to adopt it)."""
    retries = 3 if km.key in ("MODEL", "MODEL-REF") else 0
    for attempt in range(retries + 1):
        try:
            handler(km.key, km.message)
            _note_model_freshness(km.key, loaded=True, message=km.message)
            return
        except OSError:
            if attempt < retries:
                _log.warning(
                    "model load I/O failure (attempt %d/%d); retrying",
                    attempt + 1, retries,
                )
                time.sleep(0.2 * (attempt + 1))
            else:
                parked = False
                if km.key == "MODEL-REF":
                    # park ONLY when the artifact itself is unresolvable
                    # (chunk stream in flight, sha-mismatch republish) —
                    # a handler failing for its own reasons with a
                    # resolvable artifact must not re-fire immediately
                    # and loop (park re-checks resolvability on entry)
                    from oryx_tpu.common.artifact import artifact_relay

                    relay = artifact_relay()
                    try:
                        relay.resolve(km.message)
                    except OSError:
                        _log.warning(
                            "MODEL-REF %r unresolved after retries; parked "
                            "for re-dispatch on late artifact arrival",
                            km.message,
                        )
                        relay.park(
                            km.message, lambda: _dispatch_model(handler, km)
                        )
                        parked = True
                if not parked:
                    _log.exception(
                        "giving up on update message (key=%r)", km.key
                    )
                _note_model_freshness(
                    km.key, loaded=False, parked=parked, message=km.message,
                )
        except Exception:
            _note_model_freshness(km.key, loaded=False, message=km.message)
            _log.exception("ignoring bad update message (key=%r)", km.key)
            return


class BatchLayerUpdate(ABC):
    """Implemented by the batch tier of an app; config-named via
    oryx.batch.update-class."""

    @abstractmethod
    def run_update(
        self,
        timestamp_ms: int,
        new_data: Sequence[KeyMessage],
        past_data: Sequence[KeyMessage],
        model_dir: str,
        update_producer: TopicProducer,
    ) -> None: ...

    def finalize_generation(self, timestamp_ms: int) -> None:
        """Called by the batch layer AFTER the generation's window is
        persisted and its offsets committed. Updates that stage durable
        state during run_update (e.g. the incremental aggregate snapshot)
        promote it here — state made durable any earlier would double-fold
        the window if a crash in between re-delivered it."""

    def validate_record(self, km: KeyMessage) -> bool:
        """Cheap deserialize check, called once per record per generation
        BEFORE the window persists. Return False for a record that can
        never parse: the layer diverts it to the dead-letter store
        (common/quarantine.py) instead of letting it poison persisted
        history, where every later from-scratch rebuild would re-read it
        forever. Apps override with a parse-only check; the default
        accepts everything (the layer skips the sweep entirely when
        neither this nor validate_records is overridden)."""
        return True

    def validate_records(self, records: Sequence[KeyMessage]) -> Sequence[bool]:
        """Batch form of validate_record — override when a whole-window
        check is cheaper than per-record Python calls (the ALS apps run
        ONE native parse over the window and only Python-check the lines
        it flags). Default loops validate_record."""
        return [self.validate_record(km) for km in records]


class SpeedModelManager(ABC):
    """Implemented by the speed tier; config-named via
    oryx.speed.model-manager-class."""

    @abstractmethod
    def consume(self, updates: Iterator[KeyMessage]) -> None:
        """Read models/updates from the update topic forever."""

    @abstractmethod
    def build_updates(self, new_data: Sequence[KeyMessage]) -> Iterable[tuple[str, str]]:
        """Turn one micro-batch of input into (key, message) updates."""

    def validate_record(self, km: KeyMessage) -> bool:
        """Cheap deserialize check (see BatchLayerUpdate.validate_record):
        False diverts the record to the dead-letter store before
        build_updates ever sees it. Records that PARSE but break the
        fold-in are isolated separately by the speed layer's bisect pass
        after bounded window retries."""
        return True

    def validate_records(self, records: Sequence[KeyMessage]) -> Sequence[bool]:
        """Batch form of validate_record (see
        BatchLayerUpdate.validate_records). Default loops the per-record
        hook."""
        return [self.validate_record(km) for km in records]

    def close(self) -> None:
        pass


class AbstractSpeedModelManager(SpeedModelManager):
    """Dispatches consume() per message, the common pattern."""

    def consume(self, updates: Iterator[KeyMessage]) -> None:
        for km in updates:
            _dispatch_update(self.consume_key_message, km)

    @abstractmethod
    def consume_key_message(self, key: str | None, message: str) -> None: ...


class ServingModel(ABC):
    @abstractmethod
    def fraction_loaded(self) -> float:
        """1.0 when fully loaded; serving returns 503 below the configured
        min-model-load-fraction (reference ServingModel.getFractionLoaded)."""


class ServingModelManager(ABC):
    """Implemented by the serving tier; config-named via
    oryx.serving.model-manager-class."""

    def __init__(self, config: Config):
        self.config = config

    @abstractmethod
    def consume(self, updates: Iterator[KeyMessage]) -> None: ...

    @abstractmethod
    def get_model(self) -> ServingModel | None: ...

    def is_read_only(self) -> bool:
        return self.config.get_bool("oryx.serving.api.read-only", False)

    def close(self) -> None:
        pass


class AbstractServingModelManager(ServingModelManager):
    def consume(self, updates: Iterator[KeyMessage]) -> None:
        for km in updates:
            _dispatch_update(self.consume_key_message, km)

    @abstractmethod
    def consume_key_message(self, key: str | None, message: str) -> None: ...
