"""CLI tier tests (the oryx-run.sh surface): topic setup, stdin input
pump, config overlays via --set, and a real `python -m oryx_tpu.cli
serving` subprocess answering HTTP on a file:// broker."""

import io
import json
import pathlib
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from oryx_tpu import cli
from oryx_tpu.bus.broker import get_broker, topics
from oryx_tpu.bus.inproc import InProcBroker
from oryx_tpu.common.ioutil import choose_free_port


@pytest.fixture(autouse=True)
def _fresh():
    InProcBroker.reset_all()
    yield
    InProcBroker.reset_all()


def test_setup_creates_topics(capsys):
    rc = cli.main(
        [
            "setup",
            "--set", "oryx.input-topic.broker=mem://cli1",
            "--set", "oryx.update-topic.broker=mem://cli1",
        ]
    )
    assert rc == 0
    assert topics.exists("mem://cli1", "OryxInput")
    assert topics.exists("mem://cli1", "OryxUpdate")
    out = capsys.readouterr().out
    assert "OryxInput" in out and "OryxUpdate" in out


def test_set_overlay_parses_json_types():
    args = cli._parse_args(
        ["setup", "--set", "oryx.serving.api.port=123", "--set", "a.b=text"]
    )
    cfg = cli._build_config(args)
    assert cfg.get_int("oryx.serving.api.port") == 123
    assert cfg.get_string("a.b") == "text"
    with pytest.raises(SystemExit):
        cli._build_config(cli._parse_args(["setup", "--set", "novalue"]))


def test_input_pumps_stdin(monkeypatch):
    cli.main(
        ["setup", "--set", "oryx.input-topic.broker=mem://cli2",
         "--set", "oryx.update-topic.broker=mem://cli2"]
    )
    monkeypatch.setattr(sys, "stdin", io.StringIO("line one\nline two\n\n"))
    rc = cli.main(
        ["input", "--set", "oryx.input-topic.broker=mem://cli2",
         "--set", "oryx.update-topic.broker=mem://cli2"]
    )
    assert rc == 0
    broker = get_broker("mem://cli2")
    msgs: set[str] = set()
    for p in range(broker.num_partitions("OryxInput")):
        msgs |= {m for _, _, m in broker.read("OryxInput", p, 0, 10)}
    assert {"line one", "line two"} <= msgs


def _http(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_serving_subprocess_round_trip(tmp_path):
    port = choose_free_port()
    bus = f"file://{tmp_path}/bus"
    sets = [
        f"oryx.input-topic.broker={bus}",
        f"oryx.update-topic.broker={bus}",
        f"oryx.serving.api.port={port}",
        "oryx.serving.model-manager-class="
        "oryx_tpu.apps.example.serving.ExampleServingModelManager",
        'oryx.serving.application-resources='
        '["oryx_tpu.serving.resources.common","oryx_tpu.serving.resources.example"]',
    ]
    flags = [x for s in sets for x in ("--set", s)]
    assert cli.main(["setup", *flags]) == 0
    get_broker(bus).send("OryxUpdate", "MODEL", json.dumps({"cat": 2}))
    proc = subprocess.Popen(
        [sys.executable, "-m", "oryx_tpu.cli", "serving", *flags],
        cwd=str(pathlib.Path(__file__).resolve().parent.parent),
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
    )
    try:
        base = f"http://127.0.0.1:{port}"
        deadline = time.time() + 30
        status = None
        while time.time() < deadline:
            if proc.poll() is not None:
                raise AssertionError(proc.stderr.read().decode()[-2000:])
            try:
                status, body = _http(f"{base}/distinct/cat")
                if status == 200:
                    break
            except Exception:
                pass
            time.sleep(0.2)
        assert status == 200 and json.loads(body) == 2
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_loadtest_command(tmp_path):
    """The loadtest subcommand replays paths against a live serving layer
    and reports qps + latency percentiles as one JSON line."""
    import io
    import json as _json
    from contextlib import redirect_stdout

    from oryx_tpu.bus.broker import get_broker
    from oryx_tpu.cli import main as cli_main
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.server import ServingLayer

    bus = "mem://clilt"
    broker = get_broker(bus)
    for t in ("OryxInput", "OryxUpdate"):
        if not broker.topic_exists(t):
            broker.create_topic(t, 1)
    broker.send("OryxUpdate", "MODEL", _json.dumps({"word": 7}))
    cfg = load_config(overlay={
        "oryx.input-topic.broker": bus,
        "oryx.update-topic.broker": bus,
        "oryx.serving.api.port": 0,
        "oryx.serving.model-manager-class": "oryx_tpu.apps.example.serving.ExampleServingModelManager",
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.example",
        ],
    })
    paths = tmp_path / "paths.txt"
    paths.write_text("/distinct/word\n/ready\n")
    with ServingLayer(cfg) as sl:
        time.sleep(0.3)
        out = io.StringIO()
        with redirect_stdout(out):
            rc = cli_main([
                "loadtest",
                "--url", f"http://127.0.0.1:{sl.port}",
                "--paths", str(paths),
                "--rate", "200",
                "--duration", "2",
                "--workers", "4",
            ])
    assert rc == 0
    report = _json.loads(out.getvalue().strip().splitlines()[-1])
    assert report["errors"] == 0
    assert report["requests"] > 100  # ~400 scheduled at 200 rps x 2s
    assert report["latency_ms"]["p50"] > 0
    # pacing must not EXCEED the target (a loaded host may undershoot)
    assert report["qps"] <= 260


def test_loadtest_http2(tmp_path):
    """loadtest --http2 drives the serving layer over HTTP/2 prior
    knowledge using the in-repo HPACK/frame client."""
    import io
    import json as _json
    from contextlib import redirect_stdout

    from oryx_tpu.bus.broker import get_broker
    from oryx_tpu.cli import main as cli_main
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.server import ServingLayer

    bus = "mem://clilt2"
    broker = get_broker(bus)
    for t in ("OryxInput", "OryxUpdate"):
        if not broker.topic_exists(t):
            broker.create_topic(t, 1)
    broker.send("OryxUpdate", "MODEL", _json.dumps({"word": 7}))
    cfg = load_config(overlay={
        "oryx.input-topic.broker": bus,
        "oryx.update-topic.broker": bus,
        "oryx.serving.api.port": 0,
        "oryx.serving.model-manager-class": "oryx_tpu.apps.example.serving.ExampleServingModelManager",
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.example",
        ],
    })
    paths = tmp_path / "paths.txt"
    paths.write_text("/distinct\n/ready\n")
    with ServingLayer(cfg) as sl:
        time.sleep(0.3)
        out = io.StringIO()
        with redirect_stdout(out):
            rc = cli_main([
                "loadtest", "--http2",
                "--url", f"http://127.0.0.1:{sl.port}",
                "--paths", str(paths),
                "--duration", "2",
                "--workers", "4",
            ])
    assert rc == 0
    report = _json.loads(out.getvalue().strip().splitlines()[-1])
    assert report["errors"] == 0
    assert report["requests"] > 20
    assert report["latency_ms"]["p50"] > 0


def test_loadtest_multiloop_smoke(tmp_path):
    """Tier-1 frontend-throughput smoke: an unpaced ~2s loadtest against
    an in-process MULTI-LOOP server must push real traffic with zero
    errors, and the report's post-run /metrics scrape must show more than
    one event loop carrying it — a cheap canary so frontend-throughput
    regressions fail here instead of only in bench.py."""
    import io
    import json as _json
    from contextlib import redirect_stdout

    from oryx_tpu.bus.broker import get_broker
    from oryx_tpu.cli import main as cli_main
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.server import ServingLayer

    bus = "mem://cliltml"
    broker = get_broker(bus)
    for t in ("OryxInput", "OryxUpdate"):
        if not broker.topic_exists(t):
            broker.create_topic(t, 1)
    broker.send("OryxUpdate", "MODEL", _json.dumps({"word": 7}))
    cfg = load_config(overlay={
        "oryx.input-topic.broker": bus,
        "oryx.update-topic.broker": bus,
        "oryx.serving.api.port": 0,
        "oryx.serving.api.loops": 4,
        "oryx.serving.model-manager-class": "oryx_tpu.apps.example.serving.ExampleServingModelManager",
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.example",
        ],
    })
    paths = tmp_path / "paths.txt"
    paths.write_text("/distinct/word\n/ready\n")
    with ServingLayer(cfg) as sl:
        time.sleep(0.3)
        out = io.StringIO()
        with redirect_stdout(out):
            rc = cli_main([
                "loadtest",
                "--url", f"http://127.0.0.1:{sl.port}",
                "--paths", str(paths),
                "--duration", "2",
                "--workers", "8",
            ])
    assert rc == 0
    report = _json.loads(out.getvalue().strip().splitlines()[-1])
    assert report["errors"] == 0
    # unpaced on loopback: anything below this floor is a real frontend
    # regression, not CI noise (the in-process client shares the GIL with
    # the server, so the floor is far below the external-client ceiling)
    assert report["requests"] > 150, report
    srv = report.get("server")
    assert srv is not None, "loadtest never scraped the server's /metrics"
    assert srv["loops"] == 4
    assert srv["loops_serving"] >= 2, srv


def test_serving_replicas_share_port(tmp_path):
    """oryx.serving.api.processes=2: the CLI supervises two full serving
    replicas on ONE port via SO_REUSEPORT over a file:// broker; requests
    succeed under concurrency, and a killed replica is restarted."""
    import json as _json
    import os
    import signal as _signal
    import subprocess
    import urllib.request

    import pytest as _pytest

    if not hasattr(__import__("socket"), "SO_REUSEPORT"):
        _pytest.skip("no SO_REUSEPORT on this platform")

    from oryx_tpu.bus.broker import get_broker
    from oryx_tpu.common.ioutil import choose_free_port

    bus = f"file://{tmp_path}/bus"
    b = get_broker(bus)
    b.create_topic("OryxInput", 1)
    b.create_topic("OryxUpdate", 1)
    b.send("OryxUpdate", "MODEL", _json.dumps({"replica": 7}))
    port = choose_free_port()
    conf = tmp_path / "oryx.conf"
    conf.write_text(f'''
oryx.id = replicas
oryx.input-topic.broker = "{bus}"
oryx.update-topic.broker = "{bus}"
oryx.serving.api.port = {port}
oryx.serving.api.processes = 2
oryx.serving.model-manager-class = "oryx_tpu.apps.example.serving.ExampleServingModelManager"
oryx.serving.application-resources = ["oryx_tpu.serving.resources.common", "oryx_tpu.serving.resources.example"]
''')
    root = pathlib.Path(__file__).resolve().parent.parent
    from oryx_tpu.common.executil import cpu_subprocess_env

    env = cpu_subprocess_env(PYTHONPATH=str(root))
    sup = subprocess.Popen(
        [sys.executable, "-m", "oryx_tpu.cli", "serving", "--conf", str(conf)],
        cwd=str(root),
        env=env,
        # DEVNULL, not PIPE: three chatty processes share this fd and an
        # undrained pipe buffer would block them mid-test
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.time() + 60
        ok = False
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/distinct/replica", timeout=2
                ) as r:
                    if r.status == 200 and _json.loads(r.read()) == 7:
                        ok = True
                        break
            except Exception:
                pass
            time.sleep(0.3)
        assert ok, "replicas never became ready"

        def children():
            out = subprocess.run(
                ["pgrep", "-P", str(sup.pid)], capture_output=True, text=True
            ).stdout.split()
            return [int(x) for x in out]

        kids = children()
        assert len(kids) == 2, kids

        # kill one replica; requests keep succeeding and it is restarted.
        # The deadline must DOMINATE the supervisor's worst-case restart
        # backoff (30s cap) plus single-core starvation under full-suite
        # load — a 30s fixed window raced it and flaked (round-3 verdict)
        os.kill(kids[0], _signal.SIGKILL)
        deadline = time.time() + 120
        kids_now: list[int] = []
        while time.time() < deadline:
            kids_now = children()  # single snapshot per iteration: two
            # separate calls can straddle a respawn and disagree
            if len(kids_now) == 2 and kids[0] not in kids_now:
                break
            time.sleep(0.3)
        assert len(kids_now) == 2 and kids[0] not in kids_now, (
            f"dead replica was not restarted: {kids_now}"
        )
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/distinct/replica", timeout=5
        ) as r:
            assert r.status == 200
    finally:
        sup.terminate()
        try:
            sup.wait(timeout=30)
        except subprocess.TimeoutExpired:
            sup.kill()


def test_config_subcommand_flattens_effective_config(capsys):
    """`cli config` prints sorted key=value lines of the EFFECTIVE config
    (the reference's ConfigToProperties shell surface)."""
    from oryx_tpu.cli import cmd_config
    from oryx_tpu.common.config import load_config

    rc = cmd_config(load_config(overlay={"oryx.id": "cfgtest",
                                         "oryx.serving.api.port": 1234}))
    assert rc == 0
    out = capsys.readouterr().out
    lines = out.strip().splitlines()
    assert "oryx.id=cfgtest" in lines
    assert "oryx.serving.api.port=1234" in lines
    assert "oryx.monitoring.metrics=true" in lines  # booleans lowercase
    assert lines == sorted(lines)


def test_apply_platform_env_prefers_env_over_config(monkeypatch):
    """oryx.compute.platform steers jax when set (not "auto"); an explicit
    JAX_PLATFORMS env var wins as the operator override."""
    import jax

    from oryx_tpu.cli import _apply_platform_env
    from oryx_tpu.common.config import load_config

    before = jax.config.jax_platforms
    try:
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        _apply_platform_env(load_config(overlay={"oryx.compute.platform": "cpu"}))
        assert jax.config.jax_platforms == "cpu"
        # "auto" leaves whatever is configured alone
        jax.config.update("jax_platforms", "cpu")
        _apply_platform_env(load_config(overlay={"oryx.compute.platform": "auto"}))
        assert jax.config.jax_platforms == "cpu"
        # env var beats config
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        _apply_platform_env(load_config(overlay={"oryx.compute.platform": "tpu"}))
        assert jax.config.jax_platforms == "cpu"
    finally:
        jax.config.update("jax_platforms", before)


def test_app_flag_wires_registry_classes(capsys):
    """`--app seq` overlays the app registry's class/resource wiring
    (apps/spi.py) under the effective config — visible through the
    `config` subcommand like any other override."""
    rc = cli.main(["config", "--app", "seq"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "oryx.batch.update-class=oryx_tpu.apps.seq.batch.SeqUpdate" in out
    assert (
        "oryx.speed.model-manager-class="
        "oryx_tpu.apps.seq.speed.SeqSpeedModelManager" in out
    )
    assert (
        "oryx.serving.model-manager-class="
        "oryx_tpu.apps.seq.serving.SeqServingModelManager" in out
    )
    assert "oryx_tpu.serving.resources.seq" in out


def test_app_flag_explicit_set_still_wins(capsys):
    """An explicit --set outranks the app overlay (sugar must never
    shadow an operator's deliberate override)."""
    rc = cli.main([
        "config", "--app", "als",
        "--set", "oryx.batch.update-class=custom.Update",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "oryx.batch.update-class=custom.Update" in out
    # the rest of the app wiring still applies
    assert (
        "oryx.serving.model-manager-class="
        "oryx_tpu.apps.als.serving.ALSServingModelManager" in out
    )


def test_app_flag_unknown_app_fails_fast():
    with pytest.raises(SystemExit):
        cli.main(["config", "--app", "nosuchapp"])


def test_app_flag_survives_child_argv_rebuild():
    """fleet/pod child rebuilds keep --app (it is a value opt, so the
    subcommand detection must not eat its value either)."""
    raw = ["fleet", "--app", "seq", "--replicas", "2", "--conf", "x.conf"]
    child = cli._fleet_child_flags(raw)
    assert "--app" in child and child[child.index("--app") + 1] == "seq"
    assert "--replicas" not in child
    raw2 = ["--app", "seq", "pod", "--compute", "2"]
    child2 = cli._pod_child_flags(raw2)
    assert "--app" in child2 and child2[child2.index("--app") + 1] == "seq"
    assert "pod" not in child2
