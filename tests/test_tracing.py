"""End-to-end tracing: traceparent codec, the span ring buffer, exports,
model freshness, and the serving /debug/traces + /healthz lenses.

Covers the observability substrate (oryx_tpu/common/tracing.py +
freshness.py): stage-attributed spans are what make pipeline bottlenecks
actionable (tf.data, arXiv 2101.12127), so the smoke asserts an actual
loadtest request produces a span tree whose request span contains the
micro-batcher's queue-wait child — the exact attribution later perf PRs
report against.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from oryx_tpu.common.tracing import (
    Tracer,
    chrome_trace,
    format_traceparent,
    parse_traceparent,
    span_forest,
)


# ---- traceparent ----------------------------------------------------------

def test_traceparent_roundtrip():
    trace_id = "0af7651916cd43dd8448eb211c80319c"
    span_id = "b7ad6b7169203331"
    header = format_traceparent(trace_id, span_id)
    assert header == f"00-{trace_id}-{span_id}-01"
    ctx = parse_traceparent(header)
    assert ctx is not None
    assert ctx.trace_id == trace_id
    assert ctx.span_id == span_id


@pytest.mark.parametrize("bad", [
    None,
    "",
    "garbage",
    "00-abc-def-01",                                            # short ids
    "00-0af7651916cd43dd8448eb211c80319-b7ad6b7169203331-01",   # 31-char trace
    "00-0af7651916cd43dd8448eb211c80319c-b7ad6b716920333-01",   # 15-char span
    "ff-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01",  # version ff
    "00-" + "0" * 32 + "-b7ad6b7169203331-01",                  # zero trace id
    "00-0af7651916cd43dd8448eb211c80319c-" + "0" * 16 + "-01",  # zero span id
    "00-0af7651916cd43dd8448eb211c80319X-b7ad6b7169203331-01",  # non-hex
])
def test_traceparent_rejects_invalid(bad):
    assert parse_traceparent(bad) is None


def test_traceparent_case_and_whitespace_normalized():
    header = "  00-0AF7651916CD43DD8448EB211C80319C-B7AD6B7169203331-01  "
    ctx = parse_traceparent(header)
    assert ctx is not None and ctx.trace_id.islower()


# ---- ring buffer ----------------------------------------------------------

def test_disabled_tracer_records_nothing():
    tr = Tracer(capacity=32)
    assert tr.start("x") is None
    tr.finish(None)  # absorbing None is the contract
    assert tr.record_interval("y", time.monotonic()) is None
    assert tr.snapshot() == []


def test_span_parenting_and_attrs():
    tr = Tracer(capacity=32)
    tr.configure(enabled=True)
    root = tr.start("req", method="GET")
    child = tr.start("stage", parent=root, k=16)
    tr.finish(child)
    tr.finish(root, status=200)
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert child in root.children
    assert root.attrs == {"method": "GET", "status": 200}
    spans = tr.snapshot()
    assert [s.name for s in spans] == ["stage", "req"]  # finish order


def test_ring_wraparound_under_concurrent_writers():
    tr = Tracer(capacity=64)
    tr.configure(enabled=True)
    n_threads, per_thread = 8, 200

    def work(i: int) -> None:
        for j in range(per_thread):
            s = tr.start(f"w{i}", j=j)
            tr.finish(s)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = tr.snapshot()
    # bounded: never more than capacity, and the ring holds the newest
    assert 0 < len(spans) <= 64
    # every surviving span is finished and well-formed
    assert all(s.end is not None and s.end >= s.start for s in spans)
    assert all(len(s.trace_id) == 32 and len(s.span_id) == 16 for s in spans)
    # snapshot is ordered by record sequence
    seqs = [s.seq for s in spans]
    assert seqs == sorted(seqs)
    # 1600 spans were recorded through a 64-slot ring
    assert max(seqs) >= n_threads * per_thread - 64


def test_capacity_reconfigure_resets_ring():
    tr = Tracer(capacity=16)
    tr.configure(enabled=True)
    tr.finish(tr.start("a"))
    tr.configure(capacity=32)
    assert tr.snapshot() == []
    assert tr.capacity == 32


# ---- exports --------------------------------------------------------------

def _sample_spans():
    tr = Tracer(capacity=32)
    tr.configure(enabled=True)
    root = tr.start("http.request", method="GET", target="/x")
    child = tr.start("batcher.queue_wait", parent=root)
    tr.finish(child)
    tr.finish(root, status=200)
    return tr.snapshot()


def test_chrome_trace_event_schema():
    spans = _sample_spans()
    out = chrome_trace(spans)
    assert out["displayTimeUnit"] == "ms"
    events = out["traceEvents"]
    assert len(events) == len(spans)
    for ev in events:
        assert ev["ph"] == "X"
        assert ev["cat"] == "oryx"
        assert isinstance(ev["name"], str)
        assert isinstance(ev["ts"], float) and ev["ts"] > 0
        assert isinstance(ev["dur"], float) and ev["dur"] >= 0
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        assert len(ev["args"]["trace_id"]) == 32
    json.dumps(out)  # must be serializable as-is


def test_span_forest_nests_children():
    spans = _sample_spans()
    forest = span_forest(spans)
    assert len(forest) == 1
    root = forest[0]
    assert root["name"] == "http.request"
    assert root["attrs"]["status"] == 200
    assert [c["name"] for c in root["children"]] == ["batcher.queue_wait"]
    assert root["children"][0]["parent_id"] == root["span_id"]
    assert root["duration_ms"] >= root["children"][0]["duration_ms"] >= 0


def test_orphan_spans_surface_as_roots():
    tr = Tracer(capacity=2)
    tr.configure(enabled=True)
    root = tr.start("req")
    child = tr.start("stage", parent=root)
    tr.finish(child)
    tr.finish(root)
    # capacity 2 keeps both; drop the parent manually to simulate eviction
    spans = [s for s in tr.snapshot() if s.name == "stage"]
    forest = span_forest(spans)
    assert len(forest) == 1 and forest[0]["name"] == "stage"


# ---- slow-request log -----------------------------------------------------

def test_slow_request_log_breakdown(caplog):
    import logging

    tr = Tracer(capacity=32)
    tr.configure(enabled=True, slow_threshold=0.0)
    root = tr.start("http.request", method="GET", target="/slow")
    tr.finish(tr.start("batcher.queue_wait", parent=root))
    tr.finish(root, status=200)
    logger = logging.getLogger("test.slow")
    with caplog.at_level(logging.WARNING, logger="test.slow"):
        tr.log_if_slow(root, logger)
    assert any("slow request" in r.message and "batcher.queue_wait" in r.message
               for r in caplog.records)
    # below threshold: silent
    caplog.clear()
    tr.configure(slow_threshold=3600.0)
    with caplog.at_level(logging.WARNING, logger="test.slow"):
        tr.log_if_slow(root, logger)
    assert not caplog.records


# ---- model freshness ------------------------------------------------------

def test_publish_stamp_to_update_to_serve_metrics():
    """MODEL + its TRACE publish stamp through the standard update
    dispatcher -> oryx_update_to_serve_seconds observed, staleness and
    generation gauges live, and /metrics exports all three."""
    from oryx_tpu.apps.example.serving import ExampleServingModelManager
    from oryx_tpu.bus.api import KeyMessage
    from oryx_tpu.common.config import load_config
    from oryx_tpu.common.freshness import model_freshness, publish_stamp
    from oryx_tpu.common.metrics import get_registry

    mf = model_freshness()
    before = mf._h_lag.count()
    mgr = ExampleServingModelManager(load_config())
    stamp = json.loads(publish_stamp(generation=1234567))
    stamp["published_ms"] -= 2000  # published 2s ago
    mgr.consume(iter([
        KeyMessage("MODEL", json.dumps({"w": 1})),
        KeyMessage("TRACE", json.dumps(stamp)),
    ]))
    assert mf._h_lag.count() == before + 1
    assert mf.generation == 1234567
    assert 1.5 <= mf._staleness() < 60.0
    text = get_registry().render_prometheus()
    assert "oryx_update_to_serve_seconds_count" in text
    assert "oryx_model_staleness_seconds" in text
    assert "oryx_model_generation 1234567" in text


def test_publish_stamp_ignored_when_model_load_failed():
    from oryx_tpu.api import AbstractServingModelManager
    from oryx_tpu.bus.api import KeyMessage
    from oryx_tpu.common.config import load_config
    from oryx_tpu.common.freshness import model_freshness, publish_stamp

    class _Boom(AbstractServingModelManager):
        def get_model(self):
            return None

        def consume_key_message(self, key, message):
            raise ValueError("bad model")

    mf = model_freshness()
    before = mf._h_lag.count()
    mgr = _Boom(load_config())
    mgr.consume(iter([
        KeyMessage("MODEL", "junk"),
        KeyMessage("TRACE", publish_stamp(generation=99)),
    ]))
    # the stamped model never loaded: no lag observation, generation kept
    assert mf._h_lag.count() == before
    assert mf.generation != 99


def test_app_handlers_never_see_trace_stamps():
    """TRACE stamps are framework-level (like MODEL-CHUNK): the standard
    dispatcher must intercept them before the app handler."""
    from oryx_tpu.api import AbstractServingModelManager
    from oryx_tpu.bus.api import KeyMessage
    from oryx_tpu.common.config import load_config
    from oryx_tpu.common.freshness import publish_stamp

    class _Recorder(AbstractServingModelManager):
        seen: list = []

        def get_model(self):
            return None

        def consume_key_message(self, key, message):
            self.seen.append(key)

    mgr = _Recorder(load_config())
    mgr.consume(iter([
        KeyMessage("UP", "x,1"),
        KeyMessage("TRACE", publish_stamp()),
        KeyMessage("UP", "y,2"),
    ]))
    assert mgr.seen == ["UP", "UP"]


def test_parked_model_stamp_claimed_by_late_load():
    """A MODEL-REF parked for a lagging artifact loads AFTER its stamp:
    the held stamp must be claimed by the late re-dispatched load (every
    chunk-lagged publish would otherwise be invisible to freshness)."""
    from oryx_tpu.common.freshness import model_freshness, publish_stamp

    mf = model_freshness()
    before = mf._h_lag.count()
    # parked (not given up): the stamp that follows is held, keyed to the
    # parked message...
    mf.note_load_failed(parked=True, message="/models/4242")
    mf.note_stamp(publish_stamp(generation=4242))
    assert mf._h_lag.count() == before  # not observed yet
    # ...a DIFFERENT model loading meanwhile must not claim it (it takes
    # the normal pending path and its own stamp pairs with it)
    mf.note_loaded("MODEL", message="some-other-model")
    mf.note_stamp(publish_stamp(generation=5000))
    assert mf._h_lag.count() == before + 1
    assert mf.generation == 5000
    # ...and the parked model's late re-dispatch claims ITS held stamp
    mf.note_loaded("MODEL-REF", message="/models/4242")
    assert mf._h_lag.count() == before + 2
    assert mf.generation == 4242
    # a given-up load still drops its stamp
    mf.note_load_failed(parked=False)
    mf.note_stamp(publish_stamp(generation=5555))
    mf.note_loaded("MODEL")  # a LATER load must not claim the dropped stamp
    assert mf._h_lag.count() == before + 2
    assert mf.generation == 4242


def test_freshness_hook_failure_never_kills_listener(monkeypatch):
    """_dispatch_update's isolation contract: a freshness tracker that
    blows up (e.g. metric-name collision at construction) must not
    propagate out of the dispatcher in either the loaded or failed path."""
    import oryx_tpu.common.freshness as freshness_mod
    from oryx_tpu.api import _dispatch_update
    from oryx_tpu.bus.api import KeyMessage

    def boom():
        raise ValueError("registry collision")

    monkeypatch.setattr(freshness_mod, "model_freshness", boom)
    seen = []
    _dispatch_update(lambda k, m: seen.append(k), KeyMessage("MODEL", "{}"))
    _dispatch_update(
        lambda k, m: (_ for _ in ()).throw(ValueError("bad")),
        KeyMessage("MODEL", "junk"),
    )
    assert seen == ["MODEL"]


# ---- serving integration: /debug/traces + /healthz smoke ------------------

def _als_serving_config(bus: str, loops: int = 2):
    from oryx_tpu.bus.broker import get_broker
    from oryx_tpu.common.config import load_config

    broker = get_broker(bus)
    for t in ("OryxInput", "OryxUpdate"):
        if not broker.topic_exists(t):
            broker.create_topic(t, 1)
    return load_config(overlay={
        "oryx.input-topic.broker": bus,
        "oryx.update-topic.broker": bus,
        "oryx.serving.api.port": 0,
        "oryx.serving.api.loops": loops,
        "oryx.monitoring.tracing.enabled": True,
        "oryx.monitoring.tracing.buffer-size": 8192,
        "oryx.serving.model-manager-class":
            "oryx_tpu.apps.als.serving.ALSServingModelManager",
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.als",
        ],
    })


def _als_manager(cfg, n_users=32, n_items=64, features=8):
    import numpy as np

    from oryx_tpu.apps.als.serving import ALSServingModel, ALSServingModelManager
    from oryx_tpu.apps.als.state import ALSState
    from oryx_tpu.common.rng import RandomManager

    rng = RandomManager.get_random()
    state = ALSState(features, implicit=True)
    state.x.bulk_set(
        [f"u{i}" for i in range(n_users)],
        rng.standard_normal((n_users, features)).astype("float32"),
    )
    state.y.bulk_set(
        [f"i{i}" for i in range(n_items)],
        rng.standard_normal((n_items, features)).astype("float32"),
    )
    state.set_expected(state.x.ids(), state.y.ids())
    manager = ALSServingModelManager(cfg)
    manager.model = ALSServingModel(state)
    return manager


def test_loadtest_produces_span_tree_with_batcher_children(tmp_path):
    """Tier-1 smoke for the whole lens: a real loadtest against the async
    frontend with tracing on; /debug/traces must return a request span
    tree containing the batcher queue-wait (and device) children, the
    chrome export must be loadable, and /healthz must report liveness."""
    import io
    from contextlib import redirect_stdout

    from e2e_common import http_request

    from oryx_tpu.cli import main as cli_main
    from oryx_tpu.serving.server import ServingLayer

    cfg = _als_serving_config("mem://tracesmoke")
    manager = _als_manager(cfg)
    paths = tmp_path / "paths.txt"
    paths.write_text("/recommend/u0?howMany=4\n/recommend/u1?howMany=4\n")
    with ServingLayer(cfg, model_manager=manager) as sl:
        base = f"http://127.0.0.1:{sl.port}"
        out = io.StringIO()
        with redirect_stdout(out):
            rc = cli_main([
                "loadtest",
                "--url", base,
                "--paths", str(paths),
                "--duration", "1.5",
                "--workers", "4",
            ])
        assert rc == 0
        report = json.loads(out.getvalue().strip().splitlines()[-1])
        assert report["errors"] == 0 and report["requests"] > 10

        status, body = http_request("GET", f"{base}/debug/traces")
        assert status == 200
        payload = json.loads(body)
        assert payload["enabled"] is True
        roots = [
            t for t in payload["traces"]
            if t["name"] == "http.request"
            and "/recommend" in t["attrs"].get("target", "")
        ]
        assert roots, "no request span trees recorded"
        with_batcher = [
            t for t in roots
            if any(c["name"] == "batcher.queue_wait" for c in t["children"])
        ]
        assert with_batcher, (
            "no request span has a batcher.queue_wait child: "
            + json.dumps(roots[:2])[:800]
        )
        tree = with_batcher[-1]
        child_names = {c["name"] for c in tree["children"]}
        assert "batcher.device" in child_names or "batcher.host_score" in child_names
        assert "http.dispatch" in child_names
        assert tree["attrs"].get("status") == 200

        status, body = http_request("GET", f"{base}/debug/traces?format=chrome")
        assert status == 200
        chrome = json.loads(body)
        assert chrome["traceEvents"] and chrome["traceEvents"][0]["ph"] == "X"

        status, body = http_request("GET", f"{base}/healthz")
        assert status == 200
        health = json.loads(body)
        assert health["status"] == "up"
        assert health["uptime_seconds"] >= 0
        assert health["loops"] == 2

        # /metrics still renders with tracing on, and exposes freshness
        status, body = http_request("GET", f"{base}/metrics")
        assert status == 200
        assert "oryx_update_to_serve_seconds" in body
        assert "oryx_model_staleness_seconds" in body
    # restore the global tracer default for later tests in this process
    from oryx_tpu.common.tracing import get_tracer

    get_tracer().configure(enabled=False, capacity=2048)


def test_debug_traces_empty_when_disabled(tmp_path):
    """Default config: tracing off, /debug/traces reports enabled=false
    and records nothing for served requests."""
    from oryx_tpu.api import ServingModelManager
    from oryx_tpu.common.config import load_config
    from oryx_tpu.common.tracing import get_tracer
    from oryx_tpu.serving.app import Request, ServingApp

    class Manager(ServingModelManager):
        def __init__(self, config):
            self.config = config

        def consume(self, it):
            pass

        def get_model(self):
            return None

    get_tracer().clear()
    cfg = load_config(overlay={
        "oryx.serving.application-resources": ["oryx_tpu.serving.resources.common"],
    })
    app = ServingApp(cfg, Manager(cfg))
    status, body, _ = app.dispatch(
        Request("GET", "/debug/traces", {}, {}, b"", {"accept": "application/json"})
    )
    assert status == 200
    payload = json.loads(body)
    assert payload["enabled"] is False
    assert payload["traces"] == []


def test_healthz_via_dispatch_reports_generation():
    from oryx_tpu.api import ServingModelManager
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.app import Request, ServingApp

    class Manager(ServingModelManager):
        def __init__(self, config):
            self.config = config

        def consume(self, it):
            pass

        def get_model(self):
            return None

    cfg = load_config(overlay={
        "oryx.serving.application-resources": ["oryx_tpu.serving.resources.common"],
    })
    app = ServingApp(cfg, Manager(cfg))
    status, body, _ = app.dispatch(
        Request("GET", "/healthz", {}, {}, b"", {"accept": "application/json"})
    )
    assert status == 200
    health = json.loads(body)
    assert health["status"] == "up"
    assert health["loops"] == 1  # no async frontend attached
    assert "model_generation" in health
    # HEAD variant exists for probe tools
    status, body, _ = app.dispatch(
        Request("HEAD", "/healthz", {}, {}, b"", {})
    )
    assert status == 200
