#!/usr/bin/env python
"""Fleet load harness: drive a running fleet front, report both sides.

`cli loadtest` measures one serving process; this harness measures the
FLEET — it generates the recommend traffic itself (many distinct users,
so consistent-hash placement actually spreads), drives the front with
closed-loop workers, and then reads the front's own books: per-replica
request distribution, retries (shed / connect), ejections, generation
skew, and each replica's probe snapshot from ``/fleet/status``. A
deliberate shed (503 + Retry-After surfacing after every replica shed)
is counted separately from real errors, per the PR 5 contract.

    python -m oryx_tpu.cli fleet --conf oryx.conf --replicas 2 &
    python tools/fleetload.py --url http://localhost:8090 --duration 20

Prints ONE JSON report line. Exit status 1 when any non-shed error was
observed (the fleet contract: a healthy fleet behind the front serves
every request or sheds it honestly).
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import re
import sys
import threading
import time
from urllib.parse import urlsplit

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _scrape(host: str, port: int, path: str) -> tuple[int, str]:
    conn = http.client.HTTPConnection(host, port, timeout=10)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, r.read().decode("utf-8", "replace")
    finally:
        conn.close()


def _front_books(host: str, port: int) -> dict:
    """The front's own view of the run: /fleet/status + the
    oryx_fleet_* families off its /metrics."""
    out: dict = {}
    try:
        status, body = _scrape(host, port, "/fleet/status")
        if status == 200:
            out.update(json.loads(body))
    except Exception as e:  # noqa: BLE001 - report what we can
        out["status_error"] = f"{type(e).__name__}: {e}"
    try:
        _, text = _scrape(host, port, "/metrics")
        by_replica: dict[str, float] = {}
        retries: dict[str, float] = {}
        ejections: dict[str, float] = {}
        for line in text.splitlines():
            m = re.match(
                r'oryx_fleet_front_requests_total\{replica="([^"]+)"\} (\S+)',
                line,
            )
            if m:
                by_replica[m.group(1)] = float(m.group(2))
                continue
            m = re.match(
                r'oryx_fleet_front_retries_total\{reason="([^"]+)"\} (\S+)',
                line,
            )
            if m:
                retries[m.group(1)] = float(m.group(2))
                continue
            m = re.match(
                r'oryx_fleet_ejections_total\{replica="([^"]+)"\} (\S+)', line
            )
            if m:
                ejections[m.group(1)] = float(m.group(2))
                continue
            if line.startswith("oryx_fleet_generation_skew "):
                out["generation_skew"] = float(line.split()[1])
        if by_replica:
            out["requests_by_replica"] = {
                k: int(v) for k, v in sorted(by_replica.items())
            }
        if retries:
            out["retries"] = {k: int(v) for k, v in sorted(retries.items())}
        if ejections:
            out["ejections"] = {k: int(v) for k, v in sorted(ejections.items())}
    except Exception as e:  # noqa: BLE001
        out["metrics_error"] = f"{type(e).__name__}: {e}"
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--url", default="http://localhost:8090",
        help="base URL of a running fleet front (default the front's "
        "default port)",
    )
    ap.add_argument("--duration", type=float, default=20.0)
    ap.add_argument(
        "--workers", type=int, default=16,
        help="concurrent closed-loop client connections",
    )
    ap.add_argument(
        "--users", type=int, default=10_000,
        help="distinct user ids in the generated /recommend traffic "
        "(hash placement needs many to spread)",
    )
    ap.add_argument("--how-many", type=int, default=10)
    args = ap.parse_args()

    split = urlsplit(args.url if "//" in args.url else f"http://{args.url}")
    host, port = split.hostname or "localhost", split.port or 8090
    n_workers = max(1, args.workers)

    ok = [0] * n_workers
    shed = [0] * n_workers
    errors = [0] * n_workers
    lat_ms: list[list[float]] = [[] for _ in range(n_workers)]
    t_end = time.perf_counter() + args.duration

    def worker(w: int) -> None:
        conn: http.client.HTTPConnection | None = None
        j = w
        while time.perf_counter() < t_end:
            if conn is None:
                conn = http.client.HTTPConnection(host, port, timeout=60)
            path = f"/recommend/u{j % args.users}?howMany={args.how_many}"
            j += n_workers
            t0 = time.perf_counter()
            try:
                conn.request("GET", path)
                r = conn.getresponse()
                retry_after = r.getheader("Retry-After")
                r.read()
                if r.status == 200:
                    ok[w] += 1
                    lat_ms[w].append((time.perf_counter() - t0) * 1000)
                elif r.status == 503 and retry_after:
                    # the whole fleet shed: honest backpressure, honor it
                    shed[w] += 1
                    time.sleep(min(2.0, float(retry_after)))
                else:
                    errors[w] += 1
            except Exception:
                errors[w] += 1
                try:
                    conn.close()
                except Exception:
                    pass
                conn = None
        if conn is not None:
            conn.close()

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=worker, args=(w,)) for w in range(n_workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0

    lats = sorted(x for ws in lat_ms for x in ws)
    n_ok, n_shed, n_err = sum(ok), sum(shed), sum(errors)
    pct = lambda p: (
        round(lats[min(len(lats) - 1, int(p / 100 * len(lats)))], 2)
        if lats
        else None
    )
    report = {
        "requests": n_ok,
        "shed_503": n_shed,
        "errors": n_err,
        "seconds": round(dt, 2),
        "qps": round(n_ok / dt, 1) if dt else 0.0,
        "latency_ms": {"p50": pct(50), "p90": pct(90), "p99": pct(99)},
        "workers": n_workers,
        "users": args.users,
        "front": _front_books(host, port),
    }
    print(json.dumps(report))
    # contract: behind a healthy front every request is answered or
    # honestly shed — any residual error is a finding
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
