"""Metrics registry + Prometheus text exposition + JAX profiler hook.

The reference has no metrics subsystem at all — observability is delegated
to the Spark UI and rate-limited log lines (SURVEY.md §5 "no metrics
registry, no Prometheus — a deliberate gap to improve on"). This module
fills that gap natively: counters/gauges/histograms with labels, rendered
in Prometheus text exposition format at /metrics by the serving layer, plus
an optional per-generation JAX profiler trace (oryx.monitoring.profile-dir)
so TPU timelines of batch builds can be inspected in TensorBoard/Perfetto.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Iterator

# Latency-style default buckets (seconds), log-spaced 1ms..60s.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# Batch-generation-scale buckets: full model rebuilds run seconds to hours
# (the reference's default generation interval is 6h).
GENERATION_BUCKETS = (
    1.0, 5.0, 15.0, 60.0, 300.0, 900.0, 1800.0, 3600.0, 10800.0, 21600.0,
)

# Speed-micro-batch-scale buckets: 10ms up to well past the default 10s
# micro-batch interval.
MICROBATCH_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 120.0, 600.0,
)


def linear_buckets(start: float, width: float, count: int) -> tuple[float, ...]:
    """`count` bucket upper bounds starting at `start`, `width` apart —
    the right shape for bounded ratios (occupancy) and queue depths,
    where log spacing would waste resolution at the interesting end."""
    if count < 1:
        raise ValueError("count must be >= 1")
    return tuple(start + width * i for i in range(count))


def exponential_buckets(
    start: float, factor: float, count: int
) -> tuple[float, ...]:
    """`count` bucket upper bounds: start, start*factor, ... — the right
    shape for latencies and byte counts spanning orders of magnitude."""
    if count < 1:
        raise ValueError("count must be >= 1")
    if start <= 0 or factor <= 1:
        raise ValueError("start must be > 0 and factor > 1")
    return tuple(start * factor**i for i in range(count))


class GaugeSeriesGone(Exception):
    """Raised by a bound gauge/counter callable to permanently remove its
    series (e.g. the object it reports on was garbage-collected). Any
    other exception from a callable skips the series for this scrape
    only."""


def _label_key(labels: dict[str, str]) -> tuple[tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


def _fmt_labels(key: tuple[tuple[str, str], ...]) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
    return "{" + inner + "}"


def _escape(v: str) -> str:
    """Label-VALUE escaping: backslash, double quote, newline."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    """# HELP text escaping: the text format allows ONLY \\\\ and \\n here —
    escaping quotes (as label values must) would itself be an invalid
    escape sequence and corrupt the whole exposition."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if v == int(v):
        return str(int(v))
    return repr(v)


class Counter:
    """Monotonically increasing metric, per label set. A series may also
    be bound to a callable (set_function) evaluated at scrape time — for
    counters whose source of truth is owned by one thread (e.g. an event
    loop's request tally), so the hot path increments a plain int and
    only the scrape crosses threads. The callable must be monotonic to
    keep counter semantics."""

    kind = "counter"

    def __init__(self, name: str, help: str, labeled: bool = False):
        self.name = name
        self.help = help
        # labeled=True declares every series carries labels: with zero
        # series the metric then renders no sample at all instead of a
        # bogus unlabeled `name 0`
        self.labeled = labeled
        self._values: dict[tuple, float] = {}  # guarded-by: _lock
        self._fns: dict[tuple, object] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def set_function(self, fn, **labels: str) -> None:
        with self._lock:
            self._fns[_label_key(labels)] = fn

    def unbind_function(self, fn=None, **labels: str) -> None:
        """Drop a callback-bound series. When `fn` is given, only that
        exact binding is removed — a closed owner unbinding on shutdown
        cannot clobber a newer owner's binding under the same labels."""
        key = _label_key(labels)
        with self._lock:
            if fn is None or self._fns.get(key) is fn:
                self._fns.pop(key, None)

    def value(self, **labels: str) -> float:
        key = _label_key(labels)
        # snapshot under the lock (like render): an unlocked dict read can
        # race a concurrent first-insert resize and miss/see-torn state
        with self._lock:
            fn = self._fns.get(key)
            v = self._values.get(key, 0.0)
        if fn is not None:
            return float(fn())  # outside the lock: callables may be slow
        return v

    def series(self) -> dict[tuple, float]:
        """Snapshot of every series' value keyed by its sorted label
        tuple (callback-bound series evaluated outside the lock; a
        failing callback is skipped like a scrape would). The SLO
        trackers (common/slo.py) sum these to derive good/bad totals
        without new instrumentation on the request path."""
        with self._lock:
            snapshot = dict(self._values)
            fns = dict(self._fns)
        out = dict(snapshot)
        for key, fn in fns.items():
            try:
                out[key] = float(fn())
            except Exception:  # noqa: BLE001 - skip like render() does
                continue
        return out

    def render(self, openmetrics: bool = False) -> list[str]:
        # OpenMetrics counter contract: the METRIC FAMILY name carries no
        # _total suffix — samples are `<family>_total` — so the HELP/TYPE
        # lines must strip it or a strict parser (prometheus_client's
        # openmetrics decoder) rejects the whole page as a name clash.
        # Legacy counters that predate the suffix contract expose as
        # `unknown` under negotiation (their samples can't legally be
        # counter samples). Classic text keeps the full name everywhere.
        family, kind = self.name, "counter"
        if openmetrics:
            if self.name.endswith("_total"):
                family = self.name[: -len("_total")]
            else:
                kind = "unknown"
        lines = [
            f"# HELP {family} {_escape_help(self.help)}",
            f"# TYPE {family} {kind}",
        ]
        with self._lock:
            keys = sorted(set(self._values) | set(self._fns))
            snapshot = dict(self._values)
            fns = dict(self._fns)
        if not keys and not self.labeled:
            lines.append(f"{self.name} 0")
        for key in keys:
            fn = fns.get(key)
            if fn is not None:
                try:
                    v = float(fn())
                except GaugeSeriesGone:
                    with self._lock:
                        # identity-conditioned like unbind_function: a NEW
                        # owner may have re-bound these labels since the
                        # snapshot, and its fresh series must survive the
                        # dead reader's eviction
                        if self._fns.get(key) is fn:
                            self._fns.pop(key, None)
                    continue
                except Exception:
                    # transient callback failure: skip this scrape only
                    continue
            else:
                v = snapshot.get(key, 0.0)
            lines.append(f"{self.name}{_fmt_labels(key)} {_fmt_value(v)}")
        return lines


class Gauge:
    """Point-in-time value; set/inc/dec, or bind a callable for pull-time
    evaluation (e.g. model load fraction)."""

    kind = "gauge"

    def __init__(self, name: str, help: str, labeled: bool = False):
        self.name = name
        self.help = help
        self.labeled = labeled  # see Counter: suppress the zero-series sample
        self._values: dict[tuple, float] = {}  # guarded-by: _lock
        self._fns: dict[tuple, object] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def set_function(self, fn, **labels: str) -> None:
        with self._lock:
            self._fns[_label_key(labels)] = fn

    def clear_values(self) -> None:
        """Drop every set() series (callback-bound series stay) — for a
        gauge whose label sets enumerate state that was wholly replaced,
        e.g. the served generation's quality-scorecard metrics: a new
        generation without some metric must not keep exporting its
        predecessor's value under that label."""
        with self._lock:
            self._values.clear()

    def value(self, **labels: str) -> float:
        key = _label_key(labels)
        with self._lock:  # snapshot like render(); see Counter.value
            fn = self._fns.get(key)
            v = self._values.get(key, 0.0)
        if fn is not None:
            return float(fn())
        return v

    def render(self, openmetrics: bool = False) -> list[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} gauge",
        ]
        with self._lock:
            keys = sorted(set(self._values) | set(self._fns))
            snapshot = dict(self._values)
            fns = dict(self._fns)
        if not keys and not self.labeled:
            lines.append(f"{self.name} 0")
        for key in keys:
            fn = fns.get(key)
            if fn is not None:
                try:
                    v = float(fn())
                except GaugeSeriesGone:
                    with self._lock:
                        # identity-conditioned like unbind_function: a NEW
                        # owner may have re-bound these labels since the
                        # snapshot, and its fresh series must survive the
                        # dead reader's eviction
                        if self._fns.get(key) is fn:
                            self._fns.pop(key, None)
                    continue
                except Exception:
                    # transient callback failure: skip this scrape only
                    continue
            else:
                v = snapshot.get(key, 0.0)
            lines.append(f"{self.name}{_fmt_labels(key)} {_fmt_value(v)}")
        return lines


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each bucket counts
    observations <= its upper bound, +Inf bucket == count).

    Bucket boundaries are per-metric (see ``linear_buckets`` /
    ``exponential_buckets``): queue depths and occupancy ratios need
    linear spacing, latencies need exponential — one global scheme fits
    neither. Observations may carry a trace-id exemplar: the bucket the
    value lands in remembers the most recent (trace_id, value, wall-time)
    sample, rendered in OpenMetrics exemplar syntax so a "p99 got worse"
    bucket resolves to an actual traced request in /debug/traces.
    Exemplars only exist while tracing supplies ids, so the exposition
    stays plain Prometheus text when tracing is off."""

    kind = "histogram"

    def __init__(self, name: str, help: str, buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}  # guarded-by: _lock
        self._sums: dict[tuple, float] = {}  # guarded-by: _lock
        self._totals: dict[tuple, int] = {}  # guarded-by: _lock
        # label-key -> {bucket index (len(buckets) = +Inf): (trace_id,
        # value, unix ts)} — newest observation wins per bucket
        self._exemplars: dict[tuple, dict[int, tuple[str, float, float]]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def observe(
        self, value: float, trace_id: str | None = None, **labels: str
    ) -> None:
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            idx = len(self.buckets)  # +Inf
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
                    idx = min(idx, i)
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1
            if trace_id:
                self._exemplars.setdefault(key, {})[idx] = (
                    str(trace_id), value, time.time()
                )

    @contextmanager
    def time(self, **labels: str) -> Iterator[None]:
        start = time.monotonic()
        try:
            yield
        finally:
            self.observe(time.monotonic() - start, **labels)

    def count(self, **labels: str) -> int:
        with self._lock:  # snapshot like render(); see Counter.value
            return self._totals.get(_label_key(labels), 0)

    def sum(self, **labels: str) -> float:
        with self._lock:
            return self._sums.get(_label_key(labels), 0.0)

    def bucket_counts(self, **labels: str) -> list[tuple[float, int]]:
        """Cumulative (upper_bound, count) pairs including +Inf,
        snapshotted under the lock — an unlocked read can race an
        in-flight observe and see a bucket list mid-update (the same
        torn-read class PR 2 fixed for Counter.value)."""
        key = _label_key(labels)
        with self._lock:
            counts = list(self._counts.get(key, [0] * len(self.buckets)))
            total = self._totals.get(key, 0)
        out = [(ub, counts[i]) for i, ub in enumerate(self.buckets)]
        out.append((float("inf"), total))
        return out

    def totals_below(self, threshold: float) -> tuple[int, int]:
        """(observations at/under ``threshold``, total observations)
        summed across every label set — the latency-SLO numerator/
        denominator. Uses the largest bucket bound <= threshold (the
        conservative read when the threshold falls between bounds);
        a threshold under the first bound counts nothing as fast."""
        idx = -1
        for i, ub in enumerate(self.buckets):
            if ub <= threshold:
                idx = i
            else:
                break
        with self._lock:
            total = sum(self._totals.values())
            if idx < 0:
                below = 0
            else:
                below = sum(c[idx] for c in self._counts.values())
        return below, total

    def exemplar(self, bucket_index: int, **labels: str):
        """(trace_id, value, unix_ts) recorded for the bucket at
        ``bucket_index`` (len(buckets) = the +Inf bucket), or None."""
        with self._lock:
            return self._exemplars.get(_label_key(labels), {}).get(bucket_index)

    def render(self, openmetrics: bool = False) -> list[str]:
        """Exemplars render ONLY under openmetrics=True: the classic
        text exposition (text/plain; version=0.0.4) has no exemplar
        syntax, and a legacy parser hits the trailing `# {...}` and fails
        the whole scrape — exemplars are legal solely under
        application/openmetrics-text content negotiation."""
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} histogram",
        ]
        with self._lock:
            items = sorted(self._totals)
            counts = {k: list(v) for k, v in self._counts.items()}
            sums = dict(self._sums)
            totals = dict(self._totals)
            exemplars = (
                {k: dict(v) for k, v in self._exemplars.items()}
                if openmetrics else {}
            )

        def _ex(key: tuple, idx: int) -> str:
            ex = exemplars.get(key, {}).get(idx)
            if ex is None:
                return ""
            tid, val, ts = ex
            return (
                f' # {{trace_id="{_escape(tid)}"}} {_fmt_value(val)} {ts:.3f}'
            )

        for key in items:
            for i, ub in enumerate(self.buckets):
                bkey = key + (("le", _fmt_value(ub)),)
                lines.append(
                    f"{self.name}_bucket{_fmt_labels(bkey)} "
                    f"{counts[key][i]}{_ex(key, i)}"
                )
            inf_key = key + (("le", "+Inf"),)
            lines.append(
                f"{self.name}_bucket{_fmt_labels(inf_key)} "
                f"{totals[key]}{_ex(key, len(self.buckets))}"
            )
            lines.append(f"{self.name}_sum{_fmt_labels(key)} {_fmt_value(sums[key])}")
            lines.append(f"{self.name}_count{_fmt_labels(key)} {totals[key]}")
        return lines


class MetricsRegistry:
    """Thread-safe named-metric registry. Re-registering a name returns the
    existing metric (so layer + resource modules can share by name)."""

    def __init__(self):
        self._metrics: dict[str, object] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name} already registered as {existing.kind}"
                    )
                return existing
            m = cls(name, help, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "", labeled: bool = False) -> Counter:
        return self._get_or_create(Counter, name, help, labeled=labeled)

    def gauge(self, name: str, help: str = "", labeled: bool = False) -> Gauge:
        return self._get_or_create(Gauge, name, help, labeled=labeled)

    def histogram(
        self, name: str, help: str = "", buckets: tuple[float, ...] | None = None
    ) -> Histogram:
        """buckets=None adopts DEFAULT_BUCKETS on first registration and
        accepts whatever an existing metric was registered with.
        Explicitly-passed buckets that disagree with an existing metric's
        raise — two call sites silently observing into different bucket
        schemes under one name would corrupt every quantile read."""
        h = self._get_or_create(
            Histogram, name, help,
            buckets=DEFAULT_BUCKETS if buckets is None else buckets,
        )
        if buckets is not None and h.buckets != tuple(sorted(buckets)):
            raise ValueError(
                f"metric {name} already registered with buckets "
                f"{h.buckets}, conflicting with {tuple(sorted(buckets))}"
            )
        return h

    def render_prometheus(self, openmetrics: bool = False) -> str:
        """Text exposition. openmetrics=True renders the OpenMetrics
        dialect — exemplars on histogram buckets, non-`_total` counters
        as `unknown`, terminating `# EOF` — for scrapers that negotiated
        `application/openmetrics-text`; the default stays classic
        Prometheus text, which has no exemplar syntax."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render(openmetrics=openmetrics))
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()


_default = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default


@contextmanager
def maybe_profile(profile_dir: str | None, name: str) -> Iterator[None]:
    """JAX profiler trace around a block when a profile dir is configured
    (oryx.monitoring.profile-dir); no-op otherwise. Traces land in
    <dir>/<name>-<ts> for TensorBoard/Perfetto. Never lets profiler errors
    (e.g. a trace already active) break the traced computation."""
    if not profile_dir:
        yield
        return
    import jax

    path = f"{profile_dir}/{name}-{int(time.time() * 1000)}"
    started = False
    try:
        jax.profiler.start_trace(path)
        started = True
    except Exception:
        pass
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
