"""Multi-host distributed backend: config parsing, hybrid mesh shape math,
global mesh on the virtual 8-device CPU mesh, and single-process no-ops.
True multi-process joins can't run in one test process; the shape logic
that decides the pod layout is pure and covered directly."""

from __future__ import annotations

import jax
import numpy as np
import pytest

from oryx_tpu.common.config import load_config
from oryx_tpu.parallel.distributed import (
    DistributedConfig,
    barrier,
    global_mesh,
    host_allgather,
    hybrid_shape,
    init_distributed,
    mesh_from_config,
)
from oryx_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, MeshSpec


def test_distributed_config_defaults_disabled():
    cfg = load_config()
    dc = DistributedConfig.from_config(cfg)
    assert dc.num_processes == 1 and dc.coordinator_address is None
    assert not dc.enabled


def test_distributed_config_enabled():
    cfg = load_config(overlay={
        "oryx.compute.distributed.coordinator-address": "10.0.0.1:8476",
        "oryx.compute.distributed.num-processes": 4,
        "oryx.compute.distributed.process-id": 2,
    })
    dc = DistributedConfig.from_config(cfg)
    assert dc.enabled and dc.num_processes == 4 and dc.process_id == 2


def test_init_noop_single_process():
    assert init_distributed(load_config()) is False


def test_init_requires_coordinator():
    cfg = load_config(overlay={"oryx.compute.distributed.num-processes": 2})
    with pytest.raises(ValueError):
        init_distributed(cfg)


def test_hybrid_shape_model_within_host():
    # 4 hosts x 8 local devices, model=4: model stays inside a host
    assert hybrid_shape(4, 8, MeshSpec(data=-1, model=4)) == (2, 4, 4)
    # pure data parallel
    assert hybrid_shape(2, 8, MeshSpec()) == (8, 1, 2)


def test_hybrid_shape_rejects_cross_host_model_axis():
    with pytest.raises(ValueError):
        hybrid_shape(2, 4, MeshSpec(data=1, model=8))


def test_hybrid_shape_rejects_nondividing():
    with pytest.raises(ValueError):
        hybrid_shape(3, 8, MeshSpec(data=4, model=2))


def test_global_mesh_single_process_spans_devices():
    mesh = global_mesh(MeshSpec(data=4, model=2))
    assert mesh.shape[DATA_AXIS] == 4 and mesh.shape[MODEL_AXIS] == 2


def test_mesh_from_config_uses_all_devices():
    mesh = mesh_from_config(load_config())
    assert mesh is not None  # conftest forces 8 virtual CPU devices
    assert mesh.shape[DATA_AXIS] * mesh.shape[MODEL_AXIS] == len(jax.devices())


def test_barrier_and_allgather_single_process():
    barrier("test")  # no-op, must not raise
    out = host_allgather(np.asarray([1, 2, 3]))
    assert out.shape == (1, 3)
    assert list(out[0]) == [1, 2, 3]


def test_trainer_picks_up_mesh_automatically():
    from oryx_tpu.apps.als.batch import ALSUpdate

    upd = ALSUpdate(load_config())
    assert upd.mesh is not None
    assert upd.mesh.shape[DATA_AXIS] * upd.mesh.shape[MODEL_AXIS] == len(jax.devices())


def test_configure_compilation_cache(tmp_path):
    """oryx.compute.compilation-cache-dir points JAX's persistent compile
    cache at the given dir (created if absent); unset/null is a no-op."""
    import jax

    from oryx_tpu.common.config import load_config
    from oryx_tpu.parallel.distributed import configure_compilation_cache

    assert configure_compilation_cache(load_config()) is False
    d = tmp_path / "xla-cache"
    cfg = load_config(
        overlay={"oryx.compute.compilation-cache-dir": str(d)}
    )
    try:
        assert configure_compilation_cache(cfg) is True
        assert d.is_dir()
        import jax.numpy as jnp

        # unique shape so this compile isn't served from an in-memory cache
        x = jnp.ones((173, 61))
        jax.block_until_ready(jax.jit(lambda a: (a @ a.T).sum())(x))
        assert any(d.iterdir()), "no cache entry written"
        # remote URIs pass through verbatim (no local 'gs:/...' dir)
        assert configure_compilation_cache(
            load_config(
                overlay={"oryx.compute.compilation-cache-dir": "gs://b/c"}
            )
        ) is True
        assert jax.config.jax_compilation_cache_dir == "gs://b/c"
        import os

        assert not os.path.exists("gs:")
    finally:
        jax.config.update("jax_compilation_cache_dir", None)
        # restore the threshold knobs configure_compilation_cache zeroed,
        # or later tests in this process see order-dependent caching
        for flag, default in (
            ("jax_persistent_cache_min_compile_time_secs", 1.0),
            ("jax_persistent_cache_min_entry_size_bytes", 0),
        ):
            try:
                jax.config.update(flag, default)
            except AttributeError:
                pass


def test_host_broadcast_bytes_single_process():
    """Single-process degenerate forms: payload passes through, None and
    empty become b"" (the multi-process paths run in test_multihost.py
    via the pod winner shipping)."""
    from oryx_tpu.parallel.distributed import host_broadcast_bytes

    assert host_broadcast_bytes(b"abc", 0) == b"abc"
    assert host_broadcast_bytes(None, 0) == b""
    assert host_broadcast_bytes(b"", 0) == b""


def test_window_quality_key_ordering():
    """bench._window_quality_key is the ONE ordering of banked TPU
    windows (shared with tools/bank_window.py): stages first, then
    vs_baseline, malformed fields rank lowest instead of raising."""
    from bench import _window_quality_key as key  # repo root on sys.path
    # via tests/conftest.py

    assert key({"stages_done": 3, "vs_baseline": 1.0}) > key(
        {"stages_done": 2, "vs_baseline": 99.0}
    )
    assert key({"stages_done": 2, "vs_baseline": 5.0}) > key(
        {"stages_done": 2, "vs_baseline": 4.0}
    )
    # numeric strings coerce and order correctly; junk ranks lowest
    assert key({"stages_done": "3", "vs_baseline": None}) == (3.0, 0.0)
    assert key({"stages_done": "wedged", "vs_baseline": [1]}) == (0.0, 0.0)
    assert key({}) == (0.0, 0.0)
