"""Event-loop blocking-call detector (rule ``blocking-call-on-loop``).

The serving tier multiplexes thousands of connections over a handful of
asyncio event loops; ONE blocking call on a loop stalls every connection
that loop owns. This repo has already shipped (and hand-fixed) the bug
class twice: broker I/O on the /healthz probe path (PR 7 moved
``ConsumeDataIterator.lag()`` to a dedicated sampling thread) and the
general rule that handlers may run inline on the loop only when declared
``nonblocking=True``.

The checker walks the call graph from every event-loop root:

- ``async def`` functions (coroutines execute on a loop by definition)
- route handlers registered ``nonblocking=True`` (``ServingApp``
  dispatches these inline on the loop)

following confident edges only (module-local, ``self``/typed-receiver
methods, imported symbols — tools/oryxlint/callgraph.py), and flags
blocking sinks: ``time.sleep``, ``subprocess.*``, ``os.fsync``, raw
``socket``/``http.client`` exchanges, broker I/O
(``ConsumeDataIterator`` reads/commits, ``TopicProducer.send*``), and
blocking ``Future.result`` waits.

A function that provably runs on a worker thread (a ``threading.Thread``
target, an executor task) breaks the walk with an ``oryxlint: offloop``
annotation on its ``def`` line.
"""

from __future__ import annotations

import ast

from tools.oryxlint.callgraph import (
    FunctionInfo, ProjectIndex, body_calls, shared_index,
)
from tools.oryxlint.core import Checker, Finding, Project

# fully-qualified callables that block the calling thread
BLOCKING_DOTTED = {
    "time.sleep": "time.sleep",
    "os.fsync": "os.fsync",
    "os.fdatasync": "os.fdatasync",
    "socket.create_connection": "socket.create_connection",
}
# any attribute under these module prefixes blocks (process spawns, raw
# HTTP exchanges)
BLOCKING_PREFIXES = ("subprocess.", "http.client.")

# method names whose only project definition is broker/consumer I/O —
# specific enough that a bare receiver is still a confident match
BLOCKING_METHOD_NAMES = {
    "lag": "ConsumeDataIterator.lag (broker I/O)",
    "poll_available": "ConsumeDataIterator.poll_available (broker I/O)",
    "send_batch": "TopicProducer.send_batch (broker I/O)",
    "getresponse": "http.client getresponse (blocking socket read)",
}
# generic method names that block only on particular receivers: matched
# when the receiver's source text carries one of the hint substrings
BLOCKING_METHOD_HINTS = {
    "send": ("producer", "broker"),
    "commit": ("consumer", "iterator"),
    "request": ("conn",),
    "result": ("fut", "future"),
}

MAX_DEPTH = 24


def _sink_description(idx: ProjectIndex, fi: FunctionInfo, call: ast.Call) -> str | None:
    func = call.func
    dotted = idx.dotted_name(fi.module, func)
    if dotted is not None:
        if dotted in BLOCKING_DOTTED:
            return BLOCKING_DOTTED[dotted]
        for p in BLOCKING_PREFIXES:
            if dotted.startswith(p) or dotted + "." == p:
                return dotted
    if isinstance(func, ast.Attribute):
        if func.attr in BLOCKING_METHOD_NAMES:
            return BLOCKING_METHOD_NAMES[func.attr]
        hints = BLOCKING_METHOD_HINTS.get(func.attr)
        if hints:
            try:
                recv = ast.unparse(func.value).lower()
            except Exception:  # pragma: no cover - unparse is total on 3.9+
                recv = ""
            if any(h in recv for h in hints):
                return f"{recv}.{func.attr} (blocking call)"
    return None


class EventLoopChecker(Checker):
    name = "eventloop"
    rules = {
        "blocking-call-on-loop": (
            "blocking I/O (sleep, subprocess, broker, socket, Future.result) "
            "reachable from an event-loop root; prove worker-thread "
            "execution with an offloop annotation"
        ),
    }
    fix_hints = {
        "blocking-call-on-loop": (
            "offload the call to a worker thread (and mark that function "
            "`# oryxlint: offloop`), or drop nonblocking=True"
        ),
    }

    def check(self, project: Project) -> list[Finding]:
        idx = shared_index(project)
        roots = [
            fi for fi in idx.functions
            if (fi.is_async or fi.nonblocking_route) and not fi.offloop
        ]
        findings: list[Finding] = []
        seen_sites: set[tuple[str, int, str]] = set()
        for root in roots:
            self._walk(idx, root, root, [], set(), findings, seen_sites, 0)
        return findings

    def _walk(
        self,
        idx: ProjectIndex,
        root: FunctionInfo,
        fi: FunctionInfo,
        chain: list[str],
        visited: set[int],
        findings: list[Finding],
        seen_sites: set[tuple[str, int, str]],
        depth: int,
    ) -> None:
        if depth > MAX_DEPTH or id(fi) in visited:
            return
        visited.add(id(fi))
        chain = chain + [fi.qualname]
        for call in body_calls(fi.node):
            desc = _sink_description(idx, fi, call)
            if desc is not None:
                site = (fi.module.relpath, call.lineno, desc)
                if site not in seen_sites:
                    seen_sites.add(site)
                    via = " -> ".join(chain)
                    findings.append(Finding(
                        fi.module.relpath, call.lineno,
                        "blocking-call-on-loop",
                        f"{desc} runs on an event loop: reachable from "
                        f"loop root {root.qualname} ({root.where}) via "
                        f"{via}; offload it or annotate the worker-thread "
                        "function with `oryxlint: offloop`",
                    ))
                continue
            for tgt in idx.resolve_call(fi, call):
                if tgt.offloop:
                    continue
                self._walk(
                    idx, root, tgt, chain, visited, findings, seen_sites,
                    depth + 1,
                )
