"""Lightweight end-to-end tracing: spans, ring buffer, W3C propagation.

The reference delegates all observability to the Spark UI and rate-limited
log lines (SURVEY.md §5); PR 1's Prometheus registry added aggregate
counters, but counters cannot answer the question a lambda architecture
lives or dies by: *where did this request's latency go* — header parse vs.
route vs. batcher queue-wait vs. device dispatch. tf.data (arXiv
2101.12127) and TensorFlow (arXiv 1605.08695) both attribute pipeline time
to stages for exactly this reason. This module is the substrate:

- ``Span``: name + attrs + parent + monotonic start/end, grouped by a
  128-bit trace id. Spans form trees: an HTTP request span parents the
  auth/dispatch/respond stages and the micro-batcher's queue-wait and
  device spans, even across the worker-pool thread hop.
- A bounded per-process ring buffer of finished spans. Writers claim slots
  through an ``itertools.count`` (atomic under the GIL) — no lock on the
  record path, the oldest span is simply overwritten.
- W3C ``traceparent`` parse/format, so external callers can stitch serving
  spans into their own traces and bus publish stamps can carry the batch
  generation's context to the serving tier (common/freshness.py).
- Export as a span forest (``/debug/traces``) or Chrome trace-event JSON
  (``?format=chrome``) that opens directly in Perfetto next to the
  ``maybe_profile`` TPU traces (common/metrics.py).

Tracing is OFF by default (``oryx.monitoring.tracing.enabled``); every
instrumentation site guards on ``tracer.enabled``, so the disabled cost is
one attribute read per request.

Span-name families emitted by the serving hot path (the /fleet/traces
waterfall groups on these): ``http.request`` roots with ``http.parse`` /
``http.auth`` / ``http.dispatch`` / ``http.respond`` stages, the
batcher's ``batcher.queue_wait`` / ``batcher.device`` /
``batcher.host_score``, ``batcher.compile_stall`` (the first dispatch of
a new shape signature — XLA trace+compile blocking the dispatcher; see
common/perfattr.py), and ``phase.<name>`` children replayed from each
request's phase ledger (``phase.parse`` … ``phase.write``) so the
latency-budget phases line up under the request root even when a phase
ran on another thread.
"""

from __future__ import annotations

import itertools
import logging
import os
import re
import threading
import time
from typing import NamedTuple

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)

# Anchor for converting monotonic span times to wall-clock microseconds in
# exports (Chrome trace events want an absolute-ish timebase so separate
# dumps — e.g. a serving trace and a maybe_profile device trace — line up).
_WALL_ANCHOR = time.time()
_MONO_ANCHOR = time.monotonic()


def wall_time_us(monotonic_t: float) -> float:
    """Monotonic timestamp -> wall-clock microseconds since the epoch."""
    return (_WALL_ANCHOR + (monotonic_t - _MONO_ANCHOR)) * 1e6


class SpanContext(NamedTuple):
    """Just the ids — what propagation headers carry."""

    trace_id: str  # 32 lowercase hex chars
    span_id: str   # 16 lowercase hex chars


def parse_traceparent(value: str | None) -> SpanContext | None:
    """W3C trace-context ``traceparent`` -> SpanContext, or None when the
    header is absent/malformed (per spec, invalid headers are ignored and
    a new trace starts)."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, _flags = m.groups()
    if version == "ff":  # forbidden by the spec
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:  # all-zero ids invalid
        return None
    return SpanContext(trace_id, span_id)


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


class Span:
    """One timed operation. Finished child spans append themselves to
    ``children`` (bounded) so a slow-request log can print the breakdown
    without scanning the ring."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "parent",
        "start", "end", "attrs", "tid", "seq", "children",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        parent_id: str | None,
        start: float,
        attrs: dict,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id(8)
        self.parent_id = parent_id
        self.parent: "Span | None" = None
        self.start = start
        self.end: float | None = None
        self.attrs = attrs
        self.tid = threading.get_ident()
        self.seq = -1
        self.children: list["Span"] = []

    @property
    def duration(self) -> float:
        return (self.end - self.start) if self.end is not None else 0.0

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"Span({self.name!r}, {self.duration * 1000:.2f}ms, "
            f"trace={self.trace_id[:8]}..)"
        )


_MAX_CHILDREN = 128  # per-span bound: a runaway handler can't grow a tree


class Tracer:
    """Span factory + bounded ring buffer of finished spans.

    The record path is lock-free-ish: slot indices come from an
    ``itertools.count`` (its ``next`` is a single C call, atomic under the
    GIL) and list item assignment is likewise atomic, so concurrent
    writers — event loops, worker threads, the batcher dispatcher — never
    block each other; at worst two spans race for the same wrapped slot
    and one overwrites the other, which a *bounded* buffer accepts by
    design.
    """

    def __init__(self, capacity: int = 2048):
        self.enabled = False
        self.slow_threshold: float | None = None
        # the ring and its slot counter are REBOUND together (configure's
        # capacity change, clear) under _cfg_lock so a concurrent
        # reconfigure can't pair a fresh counter with the old buffer.
        # Writes-only guarding: slot writes in _record and snapshot reads
        # bind the list locally and are seq-claimed lock-free by design.
        self._cfg_lock = threading.Lock()
        self._buf: list[Span | None] = [None] * max(16, capacity)  # guarded-by: _cfg_lock (writes)
        self._seq = itertools.count()  # guarded-by: _cfg_lock (writes)

    @property
    def capacity(self) -> int:
        return len(self._buf)

    def configure(
        self,
        enabled: bool | None = None,
        capacity: int | None = None,
        slow_threshold: float | None | type(...) = ...,
    ) -> None:
        if capacity is not None and capacity != len(self._buf):
            with self._cfg_lock:
                self._buf = [None] * max(16, capacity)
                self._seq = itertools.count()
        if enabled is not None:
            self.enabled = bool(enabled)
        if slow_threshold is not ...:
            self.slow_threshold = (
                float(slow_threshold) if slow_threshold is not None else None
            )

    # -- span lifecycle ----------------------------------------------------

    def start(
        self,
        name: str,
        parent: "Span | SpanContext | None" = None,
        start: float | None = None,
        **attrs,
    ) -> Span | None:
        """New span, or None when tracing is disabled (call sites pass the
        None straight back into finish()/record_interval(), which absorb
        it — no branching needed beyond the hot-path ``enabled`` guard).
        ``start`` backdates the span to an already-captured monotonic
        time."""
        if not self.enabled:
            return None
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = _new_id(16), None
        s = Span(
            name, trace_id, parent_id,
            start if start is not None else time.monotonic(), attrs,
        )
        if isinstance(parent, Span):
            s.parent = parent
        return s

    def finish(self, span: Span | None, **attrs) -> None:
        if span is None:
            return
        if attrs:
            span.attrs.update(attrs)
        if span.end is None:
            span.end = time.monotonic()
        self._record(span)

    def record_interval(
        self,
        name: str,
        start: float,
        end: float | None = None,
        parent: "Span | SpanContext | None" = None,
        **attrs,
    ) -> Span | None:
        """Create-and-finish in one call, for stages whose edges were
        captured as plain monotonic floats (queue-wait, header parse)."""
        if not self.enabled:
            return None
        s = self.start(name, parent=parent, start=start, **attrs)
        if s is not None:
            s.end = end if end is not None else time.monotonic()
            self._record(s)
        return s

    def _record(self, span: Span) -> None:
        span.seq = next(self._seq)
        buf = self._buf
        buf[span.seq % len(buf)] = span
        p = span.parent
        if p is not None and len(p.children) < _MAX_CHILDREN:
            p.children.append(span)

    # -- reading -----------------------------------------------------------

    def snapshot(self) -> list[Span]:
        """Finished spans currently in the ring, oldest first."""
        spans = [s for s in list(self._buf) if s is not None and s.end is not None]
        spans.sort(key=lambda s: s.seq)
        return spans

    def clear(self) -> None:
        with self._cfg_lock:
            self._buf = [None] * len(self._buf)

    # -- slow-request log --------------------------------------------------

    def log_if_slow(self, span: Span | None, logger: logging.Logger) -> None:
        """WARN with the full per-stage breakdown when a finished request
        span exceeds ``oryx.monitoring.slow-request-threshold``."""
        th = self.slow_threshold
        if th is None or span is None or span.end is None:
            return
        total = span.duration
        if total < th:
            return
        stages = ", ".join(
            f"{c.name}={c.duration * 1000.0:.1f}ms"
            for c in span.children
            if c.end is not None
        )
        logger.warning(
            "slow request %s %s: %.1f ms total (threshold %.0f ms)%s",
            span.attrs.get("method", "?"),
            span.attrs.get("target", span.name),
            total * 1000.0,
            th * 1000.0,
            f" — {stages}" if stages else "",
        )


# -- current-span propagation (thread-scoped) -------------------------------
#
# The serving dispatch path is synchronous within one thread (event loop for
# nonblocking routes, a worker-pool thread otherwise): ServingApp sets the
# request span as "current" around _dispatch, and everything the handler
# calls synchronously — notably TopKBatcher.submit_nowait — picks it up as
# the parent without every signature in between carrying a span argument.

_tls = threading.local()


def current_span() -> Span | None:
    return getattr(_tls, "span", None)


def swap_current(span: Span | None) -> Span | None:
    """Install ``span`` as the thread's current span; returns the previous
    one for restoration (always restore in a finally)."""
    prev = getattr(_tls, "span", None)
    _tls.span = span
    return prev


# -- export -----------------------------------------------------------------


def chrome_trace(spans: list[Span]) -> dict:
    """Chrome trace-event JSON (`ph: "X"` complete events) — open the dump
    directly in Perfetto/chrome://tracing, alongside maybe_profile's TPU
    traces (the shared wall-clock timebase lines the two up)."""
    pid = os.getpid()
    events = []
    for s in spans:
        events.append({
            "name": s.name,
            "cat": "oryx",
            "ph": "X",
            "ts": wall_time_us(s.start),
            "dur": max(0.0, s.duration) * 1e6,
            "pid": pid,
            "tid": s.tid,
            "args": {
                "trace_id": s.trace_id,
                "span_id": s.span_id,
                "parent_id": s.parent_id or "",
                **s.attrs,
            },
        })
    return {"displayTimeUnit": "ms", "traceEvents": events}


def span_forest(spans: list[Span]) -> list[dict]:
    """Spans -> list of nested trees (roots = spans whose parent is not in
    the snapshot, e.g. evicted from the ring or remote)."""
    nodes: dict[str, dict] = {}
    for s in spans:
        nodes[s.span_id] = {
            "name": s.name,
            "trace_id": s.trace_id,
            "span_id": s.span_id,
            "parent_id": s.parent_id,
            "start_ms": round(wall_time_us(s.start) / 1000.0, 3),
            "duration_ms": round(s.duration * 1000.0, 3),
            "attrs": dict(s.attrs),
            "children": [],
        }
    roots: list[dict] = []
    for s in spans:
        node = nodes[s.span_id]
        parent = nodes.get(s.parent_id) if s.parent_id else None
        if parent is None or parent is node:
            roots.append(node)
        else:
            parent["children"].append(node)
    return roots


# -- cross-process stitching ------------------------------------------------
#
# One process's ring answers "where did this request's latency go HERE";
# a fleet answers it only when the front's span tree and every replica's
# can be laid side by side under one trace id. The helpers below take
# span FORESTS (the /debug/traces JSON shape, which crosses process
# boundaries as plain dicts) from N processes and stitch them: grouped
# by trace id, or exported as one Chrome trace with a LANE PER PROCESS
# (Perfetto renders each pid as its own track, so front queueing vs
# replica dispatch vs device time line up on the shared wall clock).


def flatten_forest(roots: list[dict]) -> list[dict]:
    """Forest (nested ``children``) -> flat span list, children stripped.
    Tolerant of foreign dicts: nodes without a trace_id are dropped."""
    out: list[dict] = []
    stack = [r for r in roots if isinstance(r, dict)]
    while stack:
        node = stack.pop()
        kids = node.get("children") or []
        stack.extend(k for k in kids if isinstance(k, dict))
        if node.get("trace_id"):
            flat = {k: v for k, v in node.items() if k != "children"}
            out.append(flat)
    return out


def stitch_traces(
    processes: list[tuple[str, list[dict]]]
) -> list[dict]:
    """[(process label, span forest)] -> one entry per trace id, spans
    labeled with their owning process, ordered by earliest span start.
    Duplicate span ids across sources (co-resident processes sharing a
    ring in tests) keep the first occurrence only."""
    by_trace: dict[str, list[dict]] = {}
    seen: set[tuple[str, str]] = set()
    for label, forest in processes:
        for span in flatten_forest(forest):
            key = (span["trace_id"], span.get("span_id", ""))
            if key in seen:
                continue
            seen.add(key)
            by_trace.setdefault(span["trace_id"], []).append(
                {"process": label, **span}
            )
    out = []
    for trace_id, spans in by_trace.items():
        spans.sort(key=lambda s: s.get("start_ms", 0.0))
        out.append({
            "trace_id": trace_id,
            "processes": sorted({s["process"] for s in spans}),
            "spans": spans,
        })
    out.sort(key=lambda t: t["spans"][0].get("start_ms", 0.0))
    return out


def stitched_chrome(processes: list[tuple[str, list[dict]]]) -> dict:
    """[(process label, span forest)] -> Chrome trace-event JSON with one
    pid lane per process (``process_name`` metadata names the lanes), so
    the stitched artifact opens in Perfetto with the front and each
    replica as separate tracks on the shared wall-clock timebase."""
    events: list[dict] = []
    seen: set[tuple[str, str]] = set()
    for pid, (label, forest) in enumerate(processes, start=1):
        events.append({
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": label},
        })
        for span in flatten_forest(forest):
            key = (span["trace_id"], span.get("span_id", ""))
            if key in seen:
                continue
            seen.add(key)
            events.append({
                "name": span.get("name", "?"),
                "cat": "oryx-fleet",
                "ph": "X",
                "ts": float(span.get("start_ms", 0.0)) * 1000.0,
                "dur": max(0.0, float(span.get("duration_ms", 0.0))) * 1000.0,
                "pid": pid,
                "tid": 1,
                "args": {
                    "process": label,
                    "trace_id": span["trace_id"],
                    "span_id": span.get("span_id", ""),
                    "parent_id": span.get("parent_id") or "",
                    **(span.get("attrs") or {}),
                },
            })
    return {"displayTimeUnit": "ms", "traceEvents": events}


# -- process-global tracer --------------------------------------------------

_default = Tracer()


def get_tracer() -> Tracer:
    return _default


def configure_tracing(config) -> Tracer:
    """Apply the oryx.monitoring.* tracing keys to the global tracer (each
    layer runtime calls this at construction; last writer wins, which is
    what one config per process means)."""
    tr = _default
    tr.configure(
        enabled=config.get_bool("oryx.monitoring.tracing.enabled", False),
        capacity=config.get_int("oryx.monitoring.tracing.buffer-size", 2048),
        slow_threshold=config.get("oryx.monitoring.slow-request-threshold", None),
    )
    return tr
