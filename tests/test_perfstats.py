"""Runtime performance observability: perfstats records and live MFU,
per-metric histogram buckets + exemplars, /debug/profile, the metric→
trace exemplar path on both frontends, and the bench ratchet
(tools/check_bench.py).

Includes the tier-1 acceptance smoke: under a traced load window,
/metrics must report a non-null oryx_device_mfu and an
oryx_dispatch_batch_occupancy consistent with the batcher's valid_rows
accounting (and <= 1.0), and /debug/profile must return a
Perfetto-loadable artifact.
"""

import http.client
import json
import math
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- histogram buckets + exemplars ----------------------------------------


def test_bucket_helpers():
    from oryx_tpu.common.metrics import exponential_buckets, linear_buckets

    assert linear_buckets(1.0, 2.0, 3) == (1.0, 3.0, 5.0)
    assert exponential_buckets(1.0, 10.0, 3) == (1.0, 10.0, 100.0)
    with pytest.raises(ValueError):
        linear_buckets(0.0, 1.0, 0)
    with pytest.raises(ValueError):
        exponential_buckets(0.0, 2.0, 3)
    with pytest.raises(ValueError):
        exponential_buckets(1.0, 1.0, 3)


def test_registry_histogram_per_metric_buckets_and_mismatch():
    from oryx_tpu.common.metrics import (
        DEFAULT_BUCKETS,
        MetricsRegistry,
        linear_buckets,
    )

    reg = MetricsRegistry()
    h = reg.histogram("t_occ", "occupancy", buckets=linear_buckets(0.25, 0.25, 4))
    assert h.buckets == (0.25, 0.5, 0.75, 1.0)
    # buckets=None accepts whatever the metric was registered with
    assert reg.histogram("t_occ") is h
    # same explicit buckets: fine
    assert reg.histogram("t_occ", buckets=(0.25, 0.5, 0.75, 1.0)) is h
    # conflicting explicit buckets: loud failure, not silent corruption
    with pytest.raises(ValueError):
        reg.histogram("t_occ", buckets=(1.0, 2.0))
    # default registration still gets DEFAULT_BUCKETS
    assert reg.histogram("t_lat").buckets == DEFAULT_BUCKETS


def test_histogram_bucket_counts_snapshot_and_exemplars():
    from oryx_tpu.common.metrics import Histogram

    h = Histogram("t_h", "help", buckets=(0.1, 1.0, 10.0))
    h.observe(0.05, method="GET")
    h.observe(0.5, trace_id="aaaa1111", method="GET")
    h.observe(100.0, trace_id="bbbb2222", method="GET")
    counts = h.bucket_counts(method="GET")
    assert counts == [(0.1, 1), (1.0, 2), (10.0, 2), (float("inf"), 3)]
    # exemplar sits on the exact bucket the value landed in
    assert h.exemplar(1, method="GET")[0] == "aaaa1111"
    assert h.exemplar(3, method="GET")[0] == "bbbb2222"  # +Inf bucket
    assert h.exemplar(0, method="GET") is None  # untraced observation
    # newest traced sample wins the bucket
    h.observe(0.7, trace_id="cccc3333", method="GET")
    assert h.exemplar(1, method="GET")[0] == "cccc3333"
    lines = h.render(openmetrics=True)
    ex_lines = [l for l in lines if " # {" in l]
    assert any('le="1"' in l and 'trace_id="cccc3333"' in l for l in ex_lines)
    assert any('le="+Inf"' in l and 'trace_id="bbbb2222"' in l for l in ex_lines)
    # OpenMetrics exemplar shape: `count # {labels} value timestamp`
    bucket_1 = next(l for l in ex_lines if 'le="1"' in l)
    tail = bucket_1.split(" # ", 1)[1]
    assert tail.startswith('{trace_id="cccc3333"} 0.7 ')
    # the CLASSIC exposition has no exemplar syntax — emitting it would
    # fail legacy scrape parsers on the whole page
    assert not any(" # {" in l for l in h.render())


def test_openmetrics_dialect_counter_suffix_and_eof():
    from oryx_tpu.common.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.counter("t_good_total", "conformant").inc()
    reg.counter("t_legacy", "no _total suffix").inc()
    plain = reg.render_prometheus()
    om = reg.render_prometheus(openmetrics=True)
    assert "# TYPE t_legacy counter" in plain
    assert "# TYPE t_good_total counter" in plain
    # strict OpenMetrics parsers reject counter samples without _total:
    # legacy-named counters expose as `unknown` under negotiation
    assert "# TYPE t_legacy unknown" in om
    # ...and the counter FAMILY name strips _total (samples keep it)
    assert "# TYPE t_good counter" in om
    assert "# TYPE t_good_total" not in om
    assert "\nt_good_total 1" in om
    assert om.rstrip().endswith("# EOF") and "# EOF" not in plain


def test_openmetrics_exposition_accepted_by_reference_parser():
    """The negotiated dialect must parse under the strict OpenMetrics
    reference parser — the whole point of negotiating is that a strict
    scraper ingests the page (exemplars included) instead of failing it."""
    parser = pytest.importorskip("prometheus_client.openmetrics.parser")
    from oryx_tpu.common.metrics import get_registry
    from oryx_tpu.common.perfstats import get_perfstats

    ps = get_perfstats()
    ps.ensure_metrics()
    ps.record_dispatch(
        "serving", flops=100.0, bytes_moved=10.0, wall_s=0.001,
        rows=1, padded_rows=1, valid_rows=1, capacity_rows=2,
        trace_id="feedbeef" * 4,
    )
    om = get_registry().render_prometheus(openmetrics=True)
    families = list(parser.text_string_to_metric_families(om))
    assert families, "reference parser ingested nothing"
    by_name = {f.name: f for f in families}
    assert by_name["oryx_device_fallback_dispatches"].type == "counter"
    hist = by_name["oryx_dispatch_batch_occupancy"]
    exemplars = [
        s.exemplar for s in hist.samples
        if s.name.endswith("_bucket") and s.exemplar
    ]
    assert any(
        e.labels.get("trace_id") == "feedbeef" * 4 for e in exemplars
    ), "exemplar did not survive the reference parser"


# ---- perfstats core --------------------------------------------------------


def _fresh_perfstats(window_s=10.0):
    from oryx_tpu.common.perfstats import PerfStats

    ps = PerfStats(capacity=256, window_s=window_s)
    ps.ensure_metrics()
    return ps


def test_record_dispatch_occupancy_and_mfu():
    ps = _fresh_perfstats()
    ps.assumed_peak_flops = 1e6
    ps.record_dispatch(
        "serving", flops=1e5, bytes_moved=4096, wall_s=0.01,
        rows=3, padded_rows=4, valid_rows=50, capacity_rows=128,
    )
    ps.record_dispatch(
        "serving", flops=1e5, bytes_moved=4096, wall_s=0.01,
        rows=3, padded_rows=4, valid_rows=50, capacity_rows=128,
    )
    recs = ps.records_since(0)
    assert len(recs) == 2
    assert recs[0].occupancy == pytest.approx(50 / 128)
    # 2e5 FLOPs over a 10s window against a 1e6 assumed peak
    assert ps.achieved_flops_per_sec("serving") == pytest.approx(2e4)
    assert ps.mfu("serving") == pytest.approx(0.02)
    # occupancy can never exceed 1.0, even on inconsistent inputs
    over = ps.record_dispatch(
        "train", flops=1.0, bytes_moved=0, wall_s=0.001,
        rows=10, padded_rows=10, valid_rows=20, capacity_rows=10,
    )
    assert over.occupancy == 1.0


def test_record_dispatch_occupancy_degenerate_inputs():
    """Regression (ISSUE 17): a zero-capacity or empty dispatch must
    never observe a >1.0 or NaN occupancy sample — degenerate inputs
    read as 0.0 (no real data streamed), not as a perfect batch."""
    ps = _fresh_perfstats()
    cases = [
        dict(valid_rows=5, capacity_rows=0),    # zero capacity
        dict(valid_rows=0, capacity_rows=128),  # empty dispatch
        dict(valid_rows=0, capacity_rows=0),    # both degenerate
        dict(valid_rows=-3, capacity_rows=64),  # nonsense negative
    ]
    for kw in cases:
        r = ps.record_dispatch(
            "serving", flops=1.0, bytes_moved=0, wall_s=0.001,
            rows=1, padded_rows=1, **kw,
        )
        assert r.occupancy == 0.0, kw
        assert not math.isnan(r.occupancy)
        assert r.occupancy <= 1.0


def test_mfu_nan_without_peak_and_zero_during_fallback():
    ps = _fresh_perfstats(window_s=0.2)
    ps.record_dispatch(
        "serving", flops=1e5, bytes_moved=0, wall_s=0.001,
        rows=1, padded_rows=1, valid_rows=1, capacity_rows=1,
    )
    # no chip peak, no assumed peak: NaN, not a confident 0
    assert math.isnan(ps.mfu("serving"))
    ps.assumed_peak_flops = 1e6
    assert ps.mfu("serving") > 0
    # a fallback zeroes the gauge for one window...
    ps.note_fallback(2)
    assert ps.mfu("serving") == 0.0
    # ...then it recovers (fresh work after the window: the old record
    # has also rolled out of the 0.2s window by now)
    time.sleep(0.25)
    ps.record_dispatch(
        "serving", flops=1e5, bytes_moved=0, wall_s=0.001,
        rows=1, padded_rows=1, valid_rows=1, capacity_rows=1,
    )
    assert ps.mfu("serving") > 0
    # real chip peak, once noted, wins over the assumed override
    ps.note_peak("serving", 1e7)
    assert ps.peak_for("serving") == 1e7


def test_capture_profile_artifact_and_concurrency_guard():
    ps = _fresh_perfstats()
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            ps.record_dispatch(
                "serving", flops=100.0, bytes_moved=10.0, wall_s=0.001,
                rows=1, padded_rows=1, valid_rows=64, capacity_rows=128,
            )
            time.sleep(0.01)

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    try:
        art = ps.capture_profile(0.3)
    finally:
        stop.set()
        t.join()
    assert art["displayTimeUnit"] == "ms"
    assert art["traceEvents"], "no dispatch slices captured in the window"
    ev = art["traceEvents"][0]
    assert ev["ph"] == "X" and ev["name"] == "device.dispatch.serving"
    assert ev["args"]["occupancy"] == pytest.approx(0.5)
    summary = art["oryx"]["by_kind"]["serving"]
    assert summary["dispatches"] >= 1
    assert summary["mean_occupancy"] == pytest.approx(0.5)
    # the capture lock refuses concurrent jax-profiler windows
    assert ps._capture_lock.acquire(blocking=False)
    try:
        with pytest.raises(RuntimeError):
            ps.capture_profile(0.01)
    finally:
        ps._capture_lock.release()


def test_batcher_records_dispatch_costs():
    import jax.numpy as jnp

    from oryx_tpu.common.perfstats import get_perfstats
    from oryx_tpu.serving.batcher import TopKBatcher

    ps = get_perfstats()
    t_mark = time.monotonic()
    host = np.random.default_rng(0).standard_normal((64, 8)).astype(np.float32)
    y = jnp.asarray(host)
    b = TopKBatcher()
    try:
        b.submit(host[0], 3, y, host_mat=host, valid_rows=50)
    finally:
        b.close()
    recs = [
        r for r in ps.records_since(t_mark) if r.kind == "serving"
    ]
    assert recs, "batcher dispatch did not record into perfstats"
    r = recs[-1]
    assert r.flops == pytest.approx(2.0 * 1 * 50 * 8)
    assert r.occupancy == pytest.approx(50 / 64)
    assert r.bytes_moved > 0 and r.wall_s > 0


def test_train_scan_records_dispatch_costs():
    from oryx_tpu.common.perfstats import get_perfstats
    from oryx_tpu.ops.als import InteractionData, train_als

    ps = get_perfstats()
    t_mark = time.monotonic()
    rng = np.random.default_rng(0)
    n = 300
    data = InteractionData(
        [f"u{i}" for i in range(40)], [f"i{i}" for i in range(30)],
        rng.integers(0, 40, n).astype(np.int32),
        rng.integers(0, 30, n).astype(np.int32),
        (rng.random(n) + 0.1).astype(np.float32),
    )
    train_als(data, features=4, iterations=2)
    recs = [r for r in ps.records_since(t_mark) if r.kind == "train"]
    assert recs, "train scan did not record into perfstats"
    r = recs[-1]
    assert r.flops > 0 and r.bytes_moved > 0 and r.wall_s > 0
    # 70 real rows over the two 1024-unit padded tables
    assert r.occupancy == pytest.approx(70 / 2048)


# ---- serving integration ---------------------------------------------------


def _als_serving_config(bus: str, frontend: str = "async", extra=None):
    from oryx_tpu.bus.broker import get_broker
    from oryx_tpu.common.config import load_config

    broker = get_broker(bus)
    for t in ("OryxInput", "OryxUpdate"):
        if not broker.topic_exists(t):
            broker.create_topic(t, 1)
    overlay = {
        "oryx.input-topic.broker": bus,
        "oryx.update-topic.broker": bus,
        "oryx.serving.api.port": 0,
        "oryx.serving.api.server": frontend,
        "oryx.serving.api.loops": 2,
        "oryx.monitoring.tracing.enabled": True,
        "oryx.monitoring.tracing.buffer-size": 8192,
        "oryx.serving.model-manager-class":
            "oryx_tpu.apps.als.serving.ALSServingModelManager",
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.als",
        ],
    }
    overlay.update(extra or {})
    return load_config(overlay=overlay)


def _als_manager(cfg, n_users=32, n_items=64, features=8):
    from oryx_tpu.apps.als.serving import ALSServingModel, ALSServingModelManager
    from oryx_tpu.apps.als.state import ALSState
    from oryx_tpu.common.rng import RandomManager

    rng = RandomManager.get_random()
    state = ALSState(features, implicit=True)
    state.x.bulk_set(
        [f"u{i}" for i in range(n_users)],
        rng.standard_normal((n_users, features)).astype("float32"),
    )
    state.y.bulk_set(
        [f"i{i}" for i in range(n_items)],
        rng.standard_normal((n_items, features)).astype("float32"),
    )
    state.set_expected(state.x.ids(), state.y.ids())
    manager = ALSServingModelManager(cfg)
    manager.model = ALSServingModel(state)
    return manager


def _http_get(
    port: int, path: str, accept: str | None = None
) -> tuple[int, dict, bytes]:
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", path, headers={"Accept": accept} if accept else {})
        resp = conn.getresponse()
        body = resp.read()
        return resp.status, {k.lower(): v for k, v in resp.getheaders()}, body
    finally:
        conn.close()


def _restore_tracer():
    from oryx_tpu.common.tracing import get_tracer

    get_tracer().configure(enabled=False, capacity=2048)


@pytest.mark.parametrize("frontend", ["async", "threaded"])
def test_exemplar_joins_traced_request_to_metrics(frontend, tmp_path):
    """Satellite contract: a traced request's trace id must appear in the
    /metrics exemplar of the latency bucket it landed in — on BOTH
    frontends — and exemplar rendering must coexist with `labeled=`
    zero-series suppression."""
    from oryx_tpu.common.metrics import get_registry
    from oryx_tpu.serving.server import ServingLayer

    cfg = _als_serving_config(f"mem://exemplar-{frontend}", frontend=frontend)
    manager = _als_manager(cfg)
    # a labeled metric with zero series: its suppression must survive the
    # exemplar-rendering path (HELP/TYPE render, no bogus `name 0` sample)
    get_registry().counter(
        "oryx_test_labeled_empty", "suppression canary", labeled=True
    )
    try:
        with ServingLayer(cfg, model_manager=manager) as sl:
            trace_ids = []
            for i in range(6):
                status, headers, _ = _http_get(
                    sl.port, f"/recommend/u{i % 4}?howMany=4"
                )
                assert status == 200
                # traced responses echo their trace context
                tp = headers.get("traceparent", "")
                assert tp.startswith("00-"), headers
                trace_ids.append(tp.split("-")[1])
            # exemplars ride ONLY the negotiated OpenMetrics dialect
            status, headers, body = _http_get(
                sl.port, "/metrics",
                accept="application/openmetrics-text",
            )
            assert status == 200
            assert headers["content-type"].startswith(
                "application/openmetrics-text"
            )
            text = body.decode()
            ex_lines = [
                l for l in text.splitlines()
                if l.startswith("oryx_serving_request_seconds_bucket")
                and " # {" in l
            ]
            assert ex_lines, "no exemplars on the request-latency histogram"
            assert any(
                tid in l for tid in trace_ids for l in ex_lines
            ), f"none of {trace_ids} in exemplars: {ex_lines}"
            # labeled= suppression survived: declaration, but no sample
            assert "# TYPE oryx_test_labeled_empty unknown" in text
            assert "\noryx_test_labeled_empty 0" not in text
            # a classic scrape stays exemplar-free (legacy parsers would
            # fail the whole page on exemplar syntax) and plain-typed
            status, headers, body = _http_get(sl.port, "/metrics")
            assert headers["content-type"].startswith("text/plain")
            plain = body.decode()
            assert " # {" not in plain and "# EOF" not in plain
            assert "# TYPE oryx_test_labeled_empty counter" in plain
    finally:
        _restore_tracer()


def test_perf_smoke_mfu_occupancy_profile(tmp_path):
    """Tier-1 acceptance smoke: under a traced load window, /metrics
    reports non-null oryx_device_mfu and oryx_dispatch_batch_occupancy
    consistent with the batcher's valid_rows accounting (<= 1.0), and
    /debug/profile?seconds=1 returns a Perfetto-loadable artifact."""
    from oryx_tpu.common.perfstats import get_perfstats
    from oryx_tpu.serving.server import ServingLayer

    cfg = _als_serving_config(
        "mem://perfsmoke",
        extra={
            # CPU host: no honest chip peak — the configured assumed peak
            # makes the MFU gauge a real (non-null, non-NaN) ratio
            "oryx.monitoring.perf.assumed-peak-flops": 1.0e12,
            "oryx.monitoring.perf.window-sec": 120,
            "oryx.monitoring.profile.enabled": True,
            "oryx.monitoring.profile.max-seconds": 5,
        },
    )
    manager = _als_manager(cfg)
    ps = get_perfstats()
    t_mark = time.monotonic()
    # the process-global occupancy histogram is cumulative across tests:
    # the load window's contribution is measured as a sum/count DELTA
    from oryx_tpu.common.metrics import get_registry

    h_occ = get_registry().histogram("oryx_dispatch_batch_occupancy")
    occ_count0 = h_occ.count(kind="serving")
    occ_sum0 = h_occ.sum(kind="serving")
    try:
        with ServingLayer(cfg, model_manager=manager) as sl:
            stop = threading.Event()
            errors = []

            def drive(worker: int):
                while not stop.is_set():
                    try:
                        status, _, _ = _http_get(
                            sl.port, f"/recommend/u{worker}?howMany=4"
                        )
                        if status != 200:
                            errors.append(status)
                    except Exception as e:  # noqa: BLE001
                        errors.append(repr(e))

            threads = [
                threading.Thread(target=drive, args=(i,), daemon=True)
                for i in range(4)
            ]
            for t in threads:
                t.start()
            try:
                time.sleep(1.0)
                # /debug/profile captures a window WHILE load is flowing
                status, headers, body = _http_get(
                    sl.port, "/debug/profile?seconds=1"
                )
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=10)
            assert not errors, errors[:5]
            assert status == 200
            assert "attachment" in headers.get("content-disposition", "")
            artifact = json.loads(body)
            # Perfetto-loadable: trace-event JSON with complete events
            assert artifact["displayTimeUnit"] == "ms"
            assert artifact["traceEvents"], "empty profile window"
            assert any(
                e["ph"] == "X" and e["name"] == "device.dispatch.serving"
                for e in artifact["traceEvents"]
            )
            assert artifact["oryx"]["by_kind"]["serving"]["dispatches"] >= 1

            status, _, body = _http_get(sl.port, "/metrics")
            assert status == 200
            metrics = body.decode()

            def metric_value(line_prefix: str) -> float:
                for line in metrics.splitlines():
                    if line.startswith(line_prefix):
                        return float(line.rsplit(" ", 1)[1])
                raise AssertionError(f"{line_prefix} not in /metrics")

            mfu = metric_value('oryx_device_mfu{kind="serving"}')
            assert not math.isnan(mfu) and mfu > 0.0
            assert metric_value(
                'oryx_device_flops_per_sec{kind="serving"}'
            ) > 0.0

            # occupancy: every observation <= 1.0 (the le="1" bucket holds
            # the full count) and the mean matches the batcher's
            # valid_rows / capacity accounting exactly
            occ_count = metric_value(
                'oryx_dispatch_batch_occupancy_count{kind="serving"}'
            )
            occ_sum = metric_value(
                'oryx_dispatch_batch_occupancy_sum{kind="serving"}'
            )
            occ_le_1 = metric_value(
                'oryx_dispatch_batch_occupancy_bucket{kind="serving",le="1"}'
            )
            assert occ_count >= 1
            assert occ_le_1 == occ_count  # nothing ever exceeded 1.0
            mean_occ = occ_sum / occ_count
            assert 0.0 < mean_occ <= 1.0
            recs = [
                r for r in ps.records_since(t_mark) if r.kind == "serving"
            ]
            assert recs
            expected = recs[-1].valid_rows / recs[-1].capacity_rows
            # this window's observations (the /metrics figures are
            # process-cumulative; earlier tests contributed other ratios)
            window_mean = (occ_sum - occ_sum0) / (occ_count - occ_count0)
            assert window_mean == pytest.approx(expected, rel=1e-6)
            # and the record's valid_rows is the model's real row count
            y_rows = manager.model._y_view_full()[0].shape[0]
            assert recs[-1].valid_rows == 64
            assert recs[-1].capacity_rows == y_rows

            # fallback accounting: /metrics exposes the counter family
            assert "oryx_device_fallback_dispatches_total" in metrics
    finally:
        _restore_tracer()


def test_debug_profile_gated_when_disabled(tmp_path):
    from oryx_tpu.serving.server import ServingLayer

    cfg = _als_serving_config("mem://profilegate")
    manager = _als_manager(cfg)
    try:
        with ServingLayer(cfg, model_manager=manager) as sl:
            status, _, body = _http_get(sl.port, "/debug/profile?seconds=1")
            assert status == 403, body
    finally:
        _restore_tracer()


# ---- bench ratchet (tools/check_bench.py) ---------------------------------


def _run_check_bench(tmp_path, baseline: dict, current: dict):
    bpath = tmp_path / "baseline.json"
    cpath = tmp_path / "current.json"
    bpath.write_text(json.dumps(baseline))
    cpath.write_text(json.dumps(current))
    return subprocess.run(
        [
            sys.executable, os.path.join(ROOT, "tools", "check_bench.py"),
            "--baseline", str(bpath), "--current", str(cpath),
        ],
        capture_output=True, text=True, timeout=120,
    )


_RATCHET = {
    "metrics": [
        {"name": "kernel_mfu", "platform": "tpu", "baseline": 0.01,
         "direction": "up", "tolerance": 0.1},
        {"name": "latency_ms_p99", "platform": "tpu", "baseline": 100.0,
         "direction": "down", "tolerance": 0.2},
        {"name": "value", "platform": "cpu", "baseline": 100.0,
         "direction": "up", "tolerance": 0.3},
    ]
}


def test_check_bench_passes_within_tolerance(tmp_path):
    proc = _run_check_bench(tmp_path, _RATCHET, {
        "platform": "tpu", "kernel_mfu": 0.0095, "latency_ms_p99": 110.0,
    })
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ratchet ok" in proc.stdout
    # the cpu-locked metric was skipped, not failed
    assert "SKIP" in proc.stdout


def test_check_bench_fails_on_regression(tmp_path):
    proc = _run_check_bench(tmp_path, _RATCHET, {
        "platform": "tpu", "kernel_mfu": 0.005, "latency_ms_p99": 50.0,
    })
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "kernel_mfu" in proc.stdout and "FAIL" in proc.stdout
    assert "RATCHET FAILED" in proc.stderr


def test_check_bench_fails_on_missing_metric(tmp_path):
    proc = _run_check_bench(tmp_path, _RATCHET, {
        "platform": "tpu", "kernel_mfu": 0.02,
    })
    assert proc.returncode == 1
    assert "MISSING" in proc.stdout


def test_check_bench_latency_ratchets_down(tmp_path):
    proc = _run_check_bench(tmp_path, _RATCHET, {
        "platform": "tpu", "kernel_mfu": 0.02, "latency_ms_p99": 130.0,
    })
    assert proc.returncode == 1
    assert "latency_ms_p99" in proc.stdout


def test_check_bench_pending_rows_report_but_never_fail(tmp_path):
    """A "pending": true row (baseline declared ahead of its first banked
    measurement — PR 8's retightened pallas_speedup and the new
    score-mode metrics) must render loudly but fail nothing, whether the
    metric is absent from the run or present below the future floor."""
    ratchet = {
        "metrics": [
            {"name": "kernel_mfu", "platform": "tpu", "baseline": 0.01,
             "direction": "up", "tolerance": 0.1},
            {"name": "qps_quantized", "platform": "tpu", "baseline": 36000,
             "direction": "up", "tolerance": 0.25, "pending": True},
            {"name": "pallas_speedup", "platform": "tpu", "baseline": 3.0,
             "direction": "up", "tolerance": 0.15, "pending": True},
        ]
    }
    proc = _run_check_bench(tmp_path, ratchet, {
        "platform": "tpu", "kernel_mfu": 0.02, "pallas_speedup": 1.94,
    })
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert proc.stdout.count("PENDING") == 2
    assert "ratchet ok" in proc.stdout


def test_committed_ratchet_accepts_its_own_sources():
    """The committed BASELINE_RATCHET.json must accept the very artifacts
    its baselines were read from — a ratchet that fails its own source
    data would block every future bench run."""
    proc = subprocess.run(
        [
            sys.executable, os.path.join(ROOT, "tools", "check_bench.py"),
            "--current", os.path.join(ROOT, "BENCH_TPU_WINDOW_r05.json"),
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
