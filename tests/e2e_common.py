"""Shared helpers for the per-app end-to-end lambda-slice suites."""

import urllib.error
import urllib.request


def http_request(method, url, body=None, accept="application/json"):
    req = urllib.request.Request(
        url, method=method, data=body, headers={"Accept": accept}
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class WedgeHook:
    """Monkeypatch target simulating a wedged device transport: blocks
    topk_dot_batch until released, then delegates to the real kernel.

    block_first_only=True blocks just the first call (a transient wedge);
    False blocks every call until release (a dead transport)."""

    def __init__(self, real_fn, block_first_only=True, timeout=30):
        import threading

        self.release = threading.Event()
        self.calls = 0
        self._real = real_fn
        self._first_only = block_first_only
        self._timeout = timeout

    def __call__(self, xs, y, k, **kwargs):
        self.calls += 1
        if (self.calls == 1 or not self._first_only) and not self.release.is_set():
            self.release.wait(timeout=self._timeout)
        return self._real(xs, y, k=k, **kwargs)
