"""ALS serving tier: in-device factor store + query methods + manager.

Mirrors ALSServingModel/ALSServingModelManager (app/oryx-app-serving
.../als/model/ALSServingModel.java:96-409, ALSServingModelManager.java:
69-182). The reference partitions Y by LSH bucket and fans requests over a
thread pool with bounded heaps; here the whole Y store is one device matrix
and top-N is a single matmul + lax.top_k (so LSH becomes an optional
approximation, not a necessity — sample-rate < 1 subsamples rows instead).
knownItems ingestion rides the X update flood like the reference.
"""

from __future__ import annotations

import logging
import threading
from concurrent.futures import Future

import numpy as np

import jax.numpy as jnp

from oryx_tpu.api import AbstractServingModelManager, ServingModel
from oryx_tpu.common.config import Config
from oryx_tpu.ops.als import compute_updated_xu
from oryx_tpu.apps.als.common import ALSConfig
from oryx_tpu.serving.app import chain_future
from oryx_tpu.serving.batcher import TopKBatcher, cosine_scale, select_topk
from oryx_tpu.apps.als.state import ALSState, apply_update_message

log = logging.getLogger(__name__)

# Max LSH partition-rebuild frequency under live update ingestion.
_LSH_REFRESH_SEC = 1.0

_POST_POOL = None
_POST_POOL_LOCK = threading.Lock()
_POST_POOL_WORKERS = 8  # overridden from config by the serving manager


def configure_post_pool(workers: int) -> None:
    """Size the post-processing pool (oryx.serving.api.post-workers) —
    takes effect at first use; an already-created pool keeps its size."""
    global _POST_POOL_WORKERS
    _POST_POOL_WORKERS = max(1, int(workers))


def _post_pool():
    """Shared pool for per-request post-processing chained off batcher
    futures (sized for trim/render work; a rescorer that blocks holds one
    of these threads, never the batcher dispatcher — and blocking top_n()
    callers post-process on their own thread, so nested rescorer queries
    cannot exhaust this pool into a deadlock)."""
    global _POST_POOL
    if _POST_POOL is None:
        with _POST_POOL_LOCK:
            if _POST_POOL is None:
                from concurrent.futures import ThreadPoolExecutor

                _POST_POOL = ThreadPoolExecutor(
                    max_workers=_POST_POOL_WORKERS,
                    thread_name_prefix="oryx-topn-post",
                )
    return _POST_POOL


class _LshPartitions:
    """Per-partition contiguous scoring blocks for the LSH host path:
    rows[p] maps block rows back to store rows, mats[p] is the contiguous
    factor block, norms[p] its row norms (for cosine queries). One matched
    snapshot, rebuilt with the partition view."""

    __slots__ = ("rows", "mats", "norms")

    def __init__(self, rows, mats, norms):
        self.rows = rows
        self.mats = mats
        self.norms = norms


class ALSServingModel(ServingModel):
    def __init__(
        self,
        state: ALSState,
        sample_rate: float = 1.0,
        num_cores: int | None = None,
        approx_recall: float = 1.0,
        lsh_max_bits_differing: int | None = None,
    ):
        self.state = state
        # < 1.0: serve via the on-device approximate top-k (the TPU
        # replacement for the reference's LSH sampling); the exact f32
        # re-rank still runs over the returned candidates
        self.approx_recall = approx_recall
        # (device matrix, ids, version) swapped as ONE tuple: readers always
        # see a matched pair, no lock on the read path
        self._device_view: tuple | None = None
        self._unit_view: tuple | None = None  # row-normalized Y, same keying
        self._sync_lock = threading.Lock()
        # LSH candidate subsampling (CPU-parity approximation; the TPU path
        # scores everything exactly): built lazily at first query
        self.sample_rate = sample_rate
        self._num_cores = num_cores
        self._lsh_max_bits = lsh_max_bits_differing
        self._lsh = None
        # (ids, parts, version, _LshPartitions) — no flat matrix copy: the
        # partition blocks inside _LshPartitions are the snapshot
        self._partition_view: tuple | None = None
        self._partition_built_at = 0.0
        # Host LSH scoring gates on a core-sized semaphore: each request
        # gathers an O(sample_rate·N·F) candidate matrix, and unbounded
        # dispatch-pool concurrency multiplies that working set by the
        # thread count — measured as a 14x collapse (64 threads on one
        # core thrashing ~3GB of concurrent gathers). Cores-many scorers
        # keep the CPUs busy with bounded memory; the rest queue.
        import os as _os

        self._host_score_sem = threading.Semaphore(
            max(1, num_cores if num_cores else (_os.cpu_count() or 1))
        )

    def _lsh_index(self):
        """(lsh, ids, partitions-per-row, partition index) — ONE matched
        snapshot: id list, partition assignment and partition blocks all
        from the same store version (concurrent UP ingestion bumps the
        version; rows from a fresher partitioning must never index an
        older matrix), the partitioning done once per version. The
        partition index stores each partition's rows as a CONTIGUOUS
        matrix block (the reference's partitioned-store layout,
        ALSServingModel.java candidate partitions): per-query scoring dots
        the candidate blocks directly instead of gathering an
        O(sample_rate·N·F) candidate copy per request — the gather was
        ~40% of per-request cost at 1M x 50f. The blocks ARE the snapshot
        (the flat arena copy is not retained alongside them), so the LSH
        path holds one grouped copy of Y, rebuilt at most once per
        refresh window."""
        from oryx_tpu.apps.als.lsh import LocalitySensitiveHash

        if self._lsh is None:
            with self._sync_lock:
                if self._lsh is None:
                    self._lsh = LocalitySensitiveHash(
                        self.sample_rate, self.state.features, self._num_cores,
                        max_bits_differing=self._lsh_max_bits,
                    )
        view = self._partition_view
        version = self.state.y.get_version()
        # Every single UP write bumps the store version; rebuilding the
        # O(N.F) snapshot + O(N.H.F) partitioning per write would dwarf the
        # subsampled scoring LSH exists for. Refresh at most once a second —
        # queries in between serve the previous consistent snapshot (the
        # whole read path is snapshot-based anyway).
        import time as _time

        now = _time.monotonic()
        if view is None or (
            view[2] != version and now - self._partition_built_at >= _LSH_REFRESH_SEC
        ):
            with self._sync_lock:
                view = self._partition_view
                if view is None or (
                    view[2] != self.state.y.get_version()
                    and _time.monotonic() - self._partition_built_at >= _LSH_REFRESH_SEC
                ):
                    mat, ids, version = self.state.y.snapshot()
                    mat = np.asarray(mat, dtype=np.float32)
                    parts = self._lsh.indices_for(mat)
                    # partition -> (row indices, contiguous block, norms),
                    # grouped once per snapshot: the query path touches
                    # only candidate partitions — no O(N) isin scan and
                    # no per-request gather
                    order = np.argsort(parts, kind="stable")
                    sorted_parts = parts[order]
                    bounds = np.searchsorted(
                        sorted_parts, np.arange(self._lsh.num_partitions + 1)
                    )
                    rows_by_part = [
                        order[bounds[p]:bounds[p + 1]]
                        for p in range(self._lsh.num_partitions)
                    ]
                    mats = [np.ascontiguousarray(mat[r]) for r in rows_by_part]
                    pindex = _LshPartitions(
                        rows=rows_by_part,
                        mats=mats,
                        norms=[np.linalg.norm(m, axis=1) for m in mats],
                    )
                    # the flat arena copy is NOT kept in the view — the
                    # partition blocks are a complete copy already, and
                    # retaining both would double the LSH host footprint
                    view = (ids, parts, version, pindex)
                    self._partition_view = view
                    self._partition_built_at = _time.monotonic()
        return self._lsh, view[0], view[1], view[3]

    def fraction_loaded(self) -> float:
        return self.state.fraction_loaded()

    # -- device scoring view ----------------------------------------------

    def _y_view_full(self) -> tuple:
        """(device Y matrix, row ids, version, host Y matrix) resynced
        lazily on version drift — a double-buffered atomic tuple swap
        instead of the reference's fine-grained read locks on the hot path.
        Staleness probe is a cheap version read; the full arena copies only
        on drift."""
        view = self._device_view
        version = self.state.y.get_version()
        if view is not None and view[2] == version:
            return view
        with self._sync_lock:
            view = self._device_view
            if view is not None and view[2] == self.state.y.get_version():
                return view
            mat, ids, version = self.state.y.snapshot()
            # bf16 scoring view: halves the HBM traffic of the memory-bound
            # top-k scan. Scores accumulate in f32 on the MXU; at 1M x 50f
            # the bf16 ranking matched f32 index-for-index (pallas_topk.py).
            # The f32 host matrix rides along for the exact candidate
            # re-rank — row-aligned with the device view by construction,
            # read lock-free on the request path.
            mat = np.asarray(mat, dtype=np.float32)
            # oversized models come back as a ChunkedMatrix: a single
            # (20M, 250)-class operand's program is too large to compile
            # (ops/transfer.py); the batcher scores it chunk-and-merge
            from oryx_tpu.ops.transfer import device_put_maybe_chunked

            view = (
                device_put_maybe_chunked(mat, dtype=jnp.bfloat16),
                ids, version, mat,
            )
            self._device_view = view
        return view

    def _y_view(self):
        view = self._y_view_full()
        return view[0], view[1]

    def _y_unit_view(self):
        """Row-normalized Y for cosine queries, cached per store version so
        the O(N.K) normalization runs once per model drift, not per request.
        y/ids/version/host matrix come from ONE view tuple — re-reading the
        version separately could cache a stale matrix under a newer stamp."""
        y, ids, version, host_mat = self._y_view_full()
        view = self._unit_view
        if view is not None and view[2] == version:
            return view[0], view[1], view[3], view[4]
        with self._sync_lock:
            view = self._unit_view
            if view is not None and view[2] == version:
                return view[0], view[1], view[3], view[4]
            from oryx_tpu.ops.transfer import ChunkedMatrix

            def normalize(a):
                af = a.astype(jnp.float32)
                n = jnp.maximum(jnp.linalg.norm(af, axis=1, keepdims=True), 1e-12)
                return (af / n).astype(a.dtype)

            # row normalization is row-local, so a chunked view normalizes
            # per chunk and stays chunked
            unit = y.map(normalize) if isinstance(y, ChunkedMatrix) else normalize(y)
            # host row norms cached per version too: the wedged-device
            # cosine fallback must not pay an O(N.K) norm pass per request
            host_norms = np.linalg.norm(host_mat, axis=1)
            view = (unit, ids, version, host_mat, host_norms)
            self._unit_view = view
        return view[0], view[1], view[3], view[4]

    # -- queries -----------------------------------------------------------

    def _top_n_plan(self, user_vector, how_many, exclude, rescorer, cosine):
        """Shared front half of top_n/top_n_async: either ("done", pairs)
        for paths resolved synchronously on the host, or
        ("fut", batcher_future, post_fn) for the device path."""
        if self.sample_rate < 1.0:
            # LSH candidate subsampling: score only items whose partition is
            # within the Hamming ball of the query's (the reference's
            # candidate-partition fan-out, ALSServingModel.java:264-279).
            # Matrix/ids/partitions are one matched snapshot from _lsh_index.
            # Pure host work — completes on this thread, gated by the
            # core-sized scoring semaphore (bounded memory under load).
            lsh, ids, _parts, pindex = self._lsh_index()
            if not ids:
                return "done", []
            k = min(len(ids), how_many + len(exclude) + 8)
            cand_parts = [
                int(p) for p in lsh.candidate_indices(user_vector)
                if pindex.rows[int(p)].size
            ]
            if not cand_parts:
                return "done", []
            q = np.asarray(user_vector, dtype=np.float32)
            with self._host_score_sem:
                # dot each candidate partition's contiguous block; the
                # per-partition scores and row maps concatenate into one
                # ranking problem
                score_parts = [pindex.mats[p] @ q for p in cand_parts]
                scores = (
                    score_parts[0] if len(score_parts) == 1
                    else np.concatenate(score_parts)
                )
                rows = (
                    pindex.rows[cand_parts[0]] if len(cand_parts) == 1
                    else np.concatenate([pindex.rows[p] for p in cand_parts])
                )
                if cosine:
                    norms = (
                        pindex.norms[cand_parts[0]] if len(cand_parts) == 1
                        else np.concatenate([pindex.norms[p] for p in cand_parts])
                    )
                    scores = cosine_scale(scores, norms)
                vals, top = select_topk(scores, min(k, rows.size))
                idx = rows[top]
            return "done", _trim_pairs(vals, idx, ids, how_many, exclude, rescorer)

        host_norms = None
        if cosine:
            y, ids, host_mat, host_norms = self._y_unit_view()
        else:
            y, ids, _v, host_mat = self._y_view_full()
        n = len(ids)
        if n == 0:
            return "done", []
        # over-fetch to survive exclusions/filters, then trim.
        # Concurrent requests coalesce into one bucketed-shape device
        # dispatch (serving/batcher.py) — B=1 matmuls waste the MXU and
        # a data-dependent k would recompile per exclusion-set size.
        k = min(n, how_many + len(exclude) + 8)
        # host_mat doubles as the wedged-device fallback: the batcher
        # scores on the host if the accelerator transport hangs
        fut = TopKBatcher.shared().submit_nowait(
            user_vector, k, y, host_mat=host_mat, cosine=cosine,
            host_norms=host_norms, recall=self.approx_recall,
        )

        def _post(result):
            vals, idx = result
            # The device scan selects candidates in bf16 (half the HBM
            # traffic of the memory-bound sweep); near-ties inside the
            # candidate set are then re-ranked EXACTLY by one vectorized
            # f32 gather against the row-aligned host matrix — k*features
            # flops, noise next to the scan it corrects.
            vals, idx = _rerank_exact(user_vector, vals, idx, host_mat, cosine)
            return _trim_pairs(vals, idx, ids, how_many, exclude, rescorer)

        return "fut", fut, _post

    def top_n(
        self,
        user_vector: np.ndarray,
        how_many: int,
        exclude: set[str] = frozenset(),
        rescorer=None,
        cosine: bool = False,
    ) -> list[tuple[str, float]]:
        """Blocking top-N. Post-processing runs on the CALLER's thread —
        never the post pool — so rescorers issuing nested blocking queries
        cannot exhaust the pool into a deadlock."""
        plan = self._top_n_plan(user_vector, how_many, exclude, rescorer, cosine)
        if plan[0] == "done":
            return plan[1]
        _, fut, post = plan
        return post(fut.result())

    def top_n_async(
        self,
        user_vector: np.ndarray,
        how_many: int,
        exclude: set[str] = frozenset(),
        rescorer=None,
        cosine: bool = False,
    ) -> Future:
        """top_n as a Future: the device path chains its host-side
        post-processing (exact re-rank, exclusion/rescorer trim) onto the
        batcher future, so a deferred endpoint holds no thread while the
        coalesced dispatch is in flight."""
        out: Future = Future()
        try:
            plan = self._top_n_plan(
                user_vector, how_many, exclude, rescorer, cosine
            )
        except BaseException as e:  # noqa: BLE001 - carried to caller
            out.set_exception(e)
            return out
        if plan[0] == "done":
            out.set_result(plan[1])
            return out
        _, fut, post = plan
        # post-processing (and everything chained after it: pagination,
        # render, metrics) bounces onto a pool — run inline it would
        # serialize on the batcher dispatcher thread inside the watchdog
        # window, stalling the device pipeline and deadlocking any
        # rescorer that submits its own query
        return chain_future(fut, post, executor=_post_pool())

    def get_user_vector(self, user: str) -> np.ndarray | None:
        return self.state.x.get(user)

    def get_item_vector(self, item: str) -> np.ndarray | None:
        return self.state.y.get(item)

    def dot(self, user: str, item: str) -> float | None:
        xu = self.state.x.get(user)
        yi = self.state.y.get(item)
        if xu is None or yi is None:
            return None
        return float(xu @ yi)

    def fold_in_user_vector(
        self, item_strengths: list[tuple[str, float]], implicit: bool | None = None
    ) -> np.ndarray | None:
        """Anonymous-user vector from (item, strength) prefs: iterated
        fold-in against the cached Y solver (EstimateForAnonymous.java:
        47-85 / RecommendToAnonymous pattern)."""
        chol = self.state.yty.get()
        if chol is None:
            return None
        implicit = self.state.implicit if implicit is None else implicit
        xu = np.zeros(self.state.features, dtype=np.float32)
        folded = False
        for item, strength in item_strengths:
            yi = self.state.y.get(item)
            if yi is None:
                continue
            xu = np.asarray(
                compute_updated_xu(
                    jnp.asarray(chol), jnp.float32(strength),
                    jnp.asarray(xu), jnp.asarray(yi), implicit=implicit,
                )
            )
            folded = True
        return xu if folded else None

    def cosine_to_items(self, items: list[str]) -> np.ndarray | None:
        """Mean unit-vector of the given items (similarity queries)."""
        vecs = [self.state.y.get(i) for i in items]
        vecs = [v for v in vecs if v is not None]
        if not vecs:
            return None
        m = np.stack(vecs)
        norms = np.linalg.norm(m, axis=1, keepdims=True)
        norms[norms == 0] = 1
        return (m / norms).mean(axis=0)

    def most_popular_items(self, how_many: int, rescorer=None) -> list[tuple[str, int]]:
        counts: dict[str, int] = {}
        for items in self.state.known_items_snapshot().values():
            for i in items:
                counts[i] = counts.get(i, 0) + 1
        out = [
            (i, c) for i, c in counts.items()
            if rescorer is None or not rescorer.is_filtered(i)
        ]
        out.sort(key=lambda t: (-t[1], t[0]))
        return out[:how_many]

    def representative_items(self, how_many: int) -> list[str]:
        """A spread of items across the factor space. With LSH enabled this
        is the reference's one-item-per-partition sample
        (PopularRepresentativeItems); otherwise an even stride over the
        store serves the same diverse-sample purpose. The LSH branch stays
        entirely on host — no device view is materialized for it."""
        if self.sample_rate < 1.0:
            lsh, ids, parts, _pindex = self._lsh_index()
            if not ids:
                return []
            _, first_rows = np.unique(parts, return_index=True)
            return [ids[int(r)] for r in first_rows[:how_many]]
        _, ids = self._y_view()
        if not ids:
            return []
        stride = max(1, len(ids) // how_many)
        return list(ids[::stride][:how_many])

    def most_active_users(self, how_many: int) -> list[tuple[str, int]]:
        out = [(u, len(s)) for u, s in self.state.known_items_snapshot().items()]
        out.sort(key=lambda t: (-t[1], t[0]))
        return out[:how_many]


def _trim_pairs(
    vals, idx, ids, how_many: int, exclude: set[str], rescorer
) -> list[tuple[str, float]]:
    """Ranked (id, score) pairs after exclusion filtering and optional
    rescoring (the reference's per-request filter/rescore pass)."""
    out: list[tuple[str, float]] = []
    for v, j in zip(np.asarray(vals), np.asarray(idx)):
        ident = ids[int(j)]
        if ident in exclude:
            continue
        score = float(v)
        if rescorer is not None:
            if rescorer.is_filtered(ident):
                continue
            score = rescorer.rescore(ident, score)
            if score is None or np.isnan(score):
                continue
        out.append((ident, score))
        if len(out) == how_many and rescorer is None:
            break
    if rescorer is not None:
        out.sort(key=lambda t: -t[1])
        out = out[:how_many]
    return out


def _rerank_exact(user_vector, vals, idx, host_mat: np.ndarray, cosine: bool):
    """Recompute candidate scores with one vectorized f32 gather against
    the host matrix row-aligned with the device view, and re-sort. Lock-free
    and O(k*features) — no per-row store reads on the request path."""
    idx = np.asarray(idx)
    uv = np.asarray(user_vector, dtype=np.float32)
    rows = host_mat[idx]
    vals = rows @ uv
    if cosine:
        vals = vals / np.maximum(np.linalg.norm(rows, axis=1), 1e-12)
    order = np.argsort(-vals, kind="stable")
    return vals[order], idx[order]


class ALSServingModelManager(AbstractServingModelManager):
    def __init__(self, config: Config):
        super().__init__(config)
        self.als = ALSConfig.from_config(config)
        self.model: ALSServingModel | None = None
        self._rescorer_provider = _load_rescorer_provider(config)
        configure_post_pool(
            config.get_int("oryx.serving.api.post-workers", 8)
        )

    def get_model(self) -> ALSServingModel | None:
        return self.model

    def rescorer_provider(self):
        return self._rescorer_provider

    def consume_key_message(self, key: str | None, message: str) -> None:
        prev = self.model.state if self.model is not None else None
        state = apply_update_message(prev, key, message, with_known_items=True)
        if state is not None and state is not prev:
            self.model = ALSServingModel(
                state, sample_rate=self.als.sample_rate,
                approx_recall=self.als.approx_recall,
                num_cores=(self.als.candidate_partitions or None),
                lsh_max_bits_differing=self.als.lsh_max_bits_differing,
            )


def _load_rescorer_provider(config: Config):
    """Optional result-rescoring plugin, config-named like the reference's
    oryx.als.rescorer-provider-class (ALSServingModelManager.java:147-180)."""
    name = config.get_string("oryx.als.rescorer-provider-class", None)
    if not name:
        return None
    from oryx_tpu.common.classutil import load_instance_of

    return load_instance_of(name)
