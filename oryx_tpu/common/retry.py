"""Shared bounded-retry policy: exponential backoff + jitter + deadline.

The reference outsourced transient-failure absorption to its substrates —
Kafka client retries, Spark task re-execution. This reproduction replaced
both, so the equivalent contract lives here: one policy object, one
``retry_call`` wrapper, threaded around the bus produce/consume and
datastore write/rename paths. Every wrapped site reports
``oryx_retry_total{site,outcome}``:

    outcome="retry"      an attempt failed and will be retried
    outcome="recovered"  the call eventually succeeded after >= 1 retry
    outcome="exhausted"  attempts/deadline ran out; the error propagates

so a scrape distinguishes "the disk hiccuped and we absorbed it" from
"we are paying retries constantly" — the second is a pager signal long
before the first exhausted error surfaces.

Only *transient* error classes retry (default: OSError family — which
includes the fault harness's InjectedFault — plus ConnectionError and
TimeoutError). Deterministic failures (parse errors, bad config) propagate
on the first attempt: retrying them only delays the loud failure.
"""

from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass

from oryx_tpu.common.config import Config

log = logging.getLogger(__name__)

# Error classes worth retrying by default: transient I/O. InjectedFault
# (common/faults.py) subclasses OSError so chaos-injected failures take
# exactly this path.
TRANSIENT = (OSError, ConnectionError, TimeoutError)


@dataclass(frozen=True)
class RetryPolicy:
    """attempts = total tries (1 = no retry); backoff doubles from base_s
    to max_s with multiplicative jitter; deadline_s bounds the whole call
    including sleeps, so a retry storm cannot stall a generation loop
    past its interval."""

    attempts: int = 4
    base_s: float = 0.025
    max_s: float = 2.0
    deadline_s: float = 15.0
    jitter: float = 0.25

    @staticmethod
    def from_config(config: Config) -> "RetryPolicy":
        return RetryPolicy(
            attempts=config.get_int("oryx.monitoring.retry.attempts", 4),
            base_s=config.get_int("oryx.monitoring.retry.base-ms", 25) / 1000.0,
            max_s=config.get_int("oryx.monitoring.retry.max-ms", 2000) / 1000.0,
            deadline_s=config.get_int("oryx.monitoring.retry.deadline-ms", 15000)
            / 1000.0,
            jitter=config.get_float("oryx.monitoring.retry.jitter", 0.25),
        )

    def backoff_s(self, attempt: int) -> float:
        """Sleep before retry `attempt` (1-based), jittered UP only so the
        base remains a floor (coordinated thundering retries decorrelate,
        but a tightened test policy keeps its configured pacing)."""
        d = min(self.max_s, self.base_s * (2.0 ** (attempt - 1)))
        return d * (1.0 + self.jitter * random.random())


_default_policy = RetryPolicy()


def configure_retry(config: Config) -> None:
    """Adopt the config's policy as the process default (layers call this
    at construction, like configure_tracing)."""
    global _default_policy
    _default_policy = RetryPolicy.from_config(config)


def default_policy() -> RetryPolicy:
    return _default_policy


_m_retries = None


def _metric():
    global _m_retries
    if _m_retries is None:
        from oryx_tpu.common.metrics import get_registry

        _m_retries = get_registry().counter(
            "oryx_retry_total",
            "Bounded-retry events by site and outcome (retry = attempt "
            "failed and will be retried, recovered = succeeded after "
            "retries, exhausted = gave up and propagated)",
            labeled=True,
        )
    return _m_retries


def ensure_metrics() -> None:
    """Register oryx_retry_total now (empty, HELP/TYPE only) so scrapes
    see the series family from process start instead of after the first
    retry event — alerts need the zero baseline."""
    _metric()


def retry_call(
    site: str,
    fn,
    *args,
    policy: RetryPolicy | None = None,
    retry_on: tuple = TRANSIENT,
    **kwargs,
):
    """Call fn(*args, **kwargs) under the bounded-retry contract. Errors
    outside `retry_on` propagate immediately; errors inside it retry with
    backoff until attempts or the deadline run out, then the LAST error
    propagates (outcome="exhausted")."""
    p = policy or _default_policy
    deadline = time.monotonic() + p.deadline_s
    attempt = 0
    while True:
        try:
            result = fn(*args, **kwargs)
        except retry_on as e:
            attempt += 1
            sleep_s = p.backoff_s(attempt)
            if attempt >= p.attempts or time.monotonic() + sleep_s > deadline:
                _metric().inc(site=site, outcome="exhausted")
                log.error(
                    "%s failed permanently after %d attempt(s): %s",
                    site, attempt, e,
                )
                raise
            _metric().inc(site=site, outcome="retry")
            log.warning(
                "%s failed (attempt %d/%d): %s — retrying in %.0fms",
                site, attempt, p.attempts, e, sleep_s * 1000,
            )
            time.sleep(sleep_s)
        else:
            if attempt:
                _metric().inc(site=site, outcome="recovered")
            return result
