"""CSV/JSON line codecs for the wire format on bus topics.

Mirrors the reference's TextUtils (framework/oryx-common .../text/TextUtils.java):
input lines are CSV (RFC-4180-ish, with quoting) or JSON arrays; update-topic
payloads are JSON with typed decoding (`convertViaJSON`).
"""

from __future__ import annotations

import csv
import io
import json
from typing import Any, Sequence


def parse_delimited(line: str, delimiter: str = ",") -> list[str]:
    """Parse one delimited line honoring quotes (TextUtils.parseDelimited)."""
    reader = csv.reader(io.StringIO(line), delimiter=delimiter)
    row = next(reader, [])
    return row


def parse_csv(line: str) -> list[str]:
    return parse_delimited(line, ",")


def join_delimited(values: Sequence[Any], delimiter: str = ",") -> str:
    """Join values into one delimited line with quoting (TextUtils.joinDelimited)."""
    buf = io.StringIO()
    writer = csv.writer(buf, delimiter=delimiter, quoting=csv.QUOTE_MINIMAL, lineterminator="")
    writer.writerow(["" if v is None else v for v in values])
    return buf.getvalue()


def join_csv(values: Sequence[Any]) -> str:
    return join_delimited(values, ",")


def parse_json_array(line: str) -> list:
    v = json.loads(line)
    if not isinstance(v, list):
        raise ValueError(f"not a JSON array: {line[:100]}")
    return v


def parse_input_line(line: str) -> list[str]:
    """Auto-detect JSON-array vs CSV input lines, the behavior of the
    reference's shared PARSE_FN (app/oryx-app-common .../fn/MLFunctions.java)."""
    s = line.strip()
    if s.startswith("["):
        return [str(x) if x is not None else "" for x in parse_json_array(s)]
    return parse_csv(s)


def to_json(value: Any) -> str:
    return json.dumps(value, separators=(",", ":"))


def from_json(s: str) -> Any:
    return json.loads(s)


def convert_via_json(value: Any, target: type) -> Any:
    """Round-trip a value through JSON to coerce it into `target`
    (TextUtils.convertViaJSON) — used to decode typed update payloads.
    String forms parse like JSON scalars would, so "false" -> False and
    "3" -> 3, never Python truthiness coercion."""
    v = json.loads(json.dumps(value))
    if target is bool:
        if isinstance(v, bool):
            return v
        if isinstance(v, str) and v.lower() in ("true", "false"):
            return v.lower() == "true"
        raise ValueError(f"cannot convert {v!r} to bool")
    if target is int:
        if isinstance(v, bool) or not isinstance(v, (int, float, str)):
            raise ValueError(f"cannot convert {v!r} to int")
        return int(float(v)) if isinstance(v, str) else int(v)
    if target is float:
        if isinstance(v, bool) or not isinstance(v, (int, float, str)):
            raise ValueError(f"cannot convert {v!r} to float")
        return float(v)
    if target is str:
        return v if isinstance(v, str) else json.dumps(v)
    if target in (list, dict):
        if not isinstance(v, target):
            raise ValueError(f"cannot convert {type(v)} to {target}")
        return v
    return v
