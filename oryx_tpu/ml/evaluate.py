"""Evaluation metrics for the batch harness.

Parity targets: the reference's implicit-ALS mean per-user AUC with sampled
negatives (app/oryx-app-mllib .../als/Evaluation.areaUnderCurve, :70-130),
explicit RMSE (Evaluation.rmse:49-55), and classification accuracy. The
clustering indices (Davies-Bouldin, Dunn, Silhouette, SSE) live with the
k-means ops (oryx_tpu/ops/kmeans.py) since they share its distance kernels.
Scoring is device matmuls; per-user bookkeeping stays on host.
"""

from __future__ import annotations

import numpy as np

from oryx_tpu.common.rng import RandomManager


def rmse(x: np.ndarray, y: np.ndarray, users: np.ndarray, items: np.ndarray, values: np.ndarray) -> float:
    """Root-mean-square error of x_u . y_i vs held-out values; negated by
    callers that need bigger-is-better."""
    if len(values) == 0:
        return float("nan")
    preds = np.einsum("ik,ik->i", x[users], y[items])
    return float(np.sqrt(np.mean((preds - values) ** 2)))


def auc_mean_per_user(
    x: np.ndarray,
    y: np.ndarray,
    test_users: np.ndarray,
    test_items: np.ndarray,
    known_by_user: dict[int, set[int]] | None = None,
    negatives_per_positive: int = 1,
) -> float:
    """Mean per-user AUC: for each test user, P(score(held-out positive) >
    score(sampled negative)), negatives drawn from items the user has not
    interacted with. Same statistic as the reference's custom AUC."""
    if len(test_users) == 0:
        return float("nan")
    rng = RandomManager.get_random()
    n_items = y.shape[0]
    known_by_user = known_by_user or {}
    aucs = []
    for u in np.unique(test_users):
        pos = test_items[test_users == u]
        known = known_by_user.get(int(u), set()) | set(int(i) for i in pos)
        if len(known) >= n_items or len(pos) == 0:
            continue
        n_neg = len(pos) * negatives_per_positive
        negs = []
        # rejection-sample negatives; bounded tries keeps it honest on
        # dense users
        tries = 0
        while len(negs) < n_neg and tries < 20 * n_neg:
            c = int(rng.integers(n_items))
            tries += 1
            if c not in known:
                negs.append(c)
        if not negs:
            continue
        user_scores = y @ x[int(u)]
        pos_s = user_scores[pos]
        neg_s = user_scores[np.asarray(negs)]
        # all-pairs comparison, ties count half
        wins = (pos_s[:, None] > neg_s[None, :]).mean()
        ties = (pos_s[:, None] == neg_s[None, :]).mean()
        aucs.append(wins + 0.5 * ties)
    return float(np.mean(aucs)) if aucs else float("nan")


def accuracy(predicted: np.ndarray, actual: np.ndarray) -> float:
    if len(actual) == 0:
        return float("nan")
    return float(np.mean(np.asarray(predicted) == np.asarray(actual)))
