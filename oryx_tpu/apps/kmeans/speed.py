"""k-means speed tier: per-micro-batch centroid shifts.

Mirrors KMeansSpeedModelManager (app/oryx-app .../speed/kmeans/
KMeansSpeedModelManager.java:55-125): "UP" messages are ignored (hearing
our own updates — the serving tier applies them); MODEL(-REF) replaces the
local model; build_updates assigns each datum to its closest cluster, one
batched device call for the whole window, reduces per-cluster (mean, count),
applies ClusterInfo.update to the local copy, and emits
[clusterID, newCenter, newCount] messages.
"""

from __future__ import annotations

import logging

import numpy as np

from oryx_tpu.api import AbstractSpeedModelManager
from oryx_tpu.common.artifact import read_artifact_from_update
from oryx_tpu.common.config import Config
from oryx_tpu.ops.kmeans import assign_clusters, online_update
from oryx_tpu.apps.kmeans.common import cluster_update_message, vectorize_rows
from oryx_tpu.apps.schema import InputSchema

log = logging.getLogger(__name__)


class KMeansSpeedModelManager(AbstractSpeedModelManager):
    def __init__(self, config: Config):
        self.config = config
        self.schema = InputSchema(config)
        self.centers: np.ndarray | None = None  # [K,D] f64
        self.counts: np.ndarray | None = None  # [K] i64

    def consume_key_message(self, key: str | None, message: str) -> None:
        if key == "UP":
            return  # hearing our own updates
        if key in ("MODEL", "MODEL-REF"):
            art = read_artifact_from_update(key, message)
            self.centers = np.asarray(art.tensors["centers"], dtype=np.float64)
            counts = art.content.get("counts")
            self.counts = (
                np.asarray(counts, dtype=np.int64)
                if counts is not None
                else np.ones(len(self.centers), dtype=np.int64)
            )
            log.info("new model loaded: %d clusters", len(self.centers))
        else:
            raise ValueError(f"bad key: {key}")

    def build_updates(self, new_data):
        if self.centers is None:
            return []
        points = vectorize_rows(self.schema, (km.message for km in new_data))
        if len(points) == 0:
            return []
        ids, _ = assign_clusters(
            np.asarray(points, dtype=np.float32),
            np.asarray(self.centers, dtype=np.float32),
        )
        ids = np.asarray(ids)
        out = []
        for c in np.unique(ids):
            members = points[ids == c]
            new_center, new_total = online_update(
                self.centers[c], int(self.counts[c]), members.mean(axis=0), len(members)
            )
            self.centers[c] = new_center
            self.counts[c] = new_total
            out.append(cluster_update_message(int(c), new_center, new_total))
        return out
