"""RDF batch tier: full forest rebuild per generation.

Replaces RDFUpdate (app/oryx-app-mllib .../batch/mllib/rdf/RDFUpdate.java):
build categorical value encodings from all training data (:205-231),
encode + quantile-bin predictors, grow the histogram forest on device
(ops.rdf), and evaluate accuracy (classification) or -RMSE (regression)
on the held-out split (:179-205). Hyperparameters match the reference's
tuned set: max-split-candidates, max-depth, impurity (:100-105).
"""

from __future__ import annotations

import logging
from typing import Any, Sequence

import numpy as np

from oryx_tpu.bus.api import KeyMessage
from oryx_tpu.common.artifact import ModelArtifact
from oryx_tpu.common.config import Config
from oryx_tpu.common.text import parse_input_line
from oryx_tpu.ml.update import MLUpdate
from oryx_tpu.ops.rdf import bin_dataset, grow_forest
from oryx_tpu.apps.rdf.common import RDFConfig, artifact_to_model, forest_to_artifact
from oryx_tpu.apps.schema import CategoricalValueEncodings, InputSchema, encode_matrix

log = logging.getLogger(__name__)


def _parse_rows(data: Sequence[KeyMessage]) -> list[list[str]]:
    rows = []
    for km in data:
        try:
            rows.append(parse_input_line(km.message))
        except ValueError:
            continue
    return rows


class RDFUpdate(MLUpdate):
    def __init__(self, config: Config, mesh=None):
        super().__init__(config)
        self.rdf = RDFConfig.from_config(config)
        self.schema = InputSchema(config)
        if not self.schema.has_target():
            raise ValueError("RDF requires a target feature")
        if mesh is None:
            from oryx_tpu.parallel.distributed import mesh_from_config

            mesh = mesh_from_config(config)
        self.mesh = mesh

    def hyperparam_ranges(self) -> dict[str, Any]:
        return {
            "max-split-candidates": self.rdf.max_split_candidates,
            "max-depth": self.rdf.max_depth,
            "impurity": self.rdf.impurity,
        }

    def build_model(
        self, train: Sequence[KeyMessage], hyperparams: dict[str, Any]
    ) -> ModelArtifact:
        rows = _parse_rows(train)
        if not rows:
            raise ValueError("no parseable training rows")
        encodings = CategoricalValueEncodings.from_data(self.schema, rows)
        x, y = encode_matrix(self.schema, encodings, rows)
        keep = ~np.isnan(y)
        x, y = x[keep], y[keep]
        if len(y) == 0:
            raise ValueError("no rows with a target value")

        is_cat = np.array(
            [
                self.schema.is_categorical(self.schema.predictor_to_feature_index(j))
                for j in range(self.schema.num_predictors)
            ]
        )
        cat_counts = np.array(
            [
                encodings.get_value_count(self.schema.predictor_to_feature_index(j))
                for j in range(self.schema.num_predictors)
            ]
        )
        data = bin_dataset(
            x, is_cat, cat_counts, int(hyperparams["max-split-candidates"])
        )
        classification = self.schema.is_classification()
        n_classes = (
            encodings.get_value_count(self.schema.target_index) if classification else 0
        )
        impurity = str(hyperparams["impurity"]).lower()
        if not classification:
            impurity = "variance"
        forest = grow_forest(
            data,
            y,
            num_trees=self.rdf.num_trees,
            max_depth=int(hyperparams["max-depth"]),
            impurity=impurity,
            n_classes=n_classes,
            feature_subset=self.rdf.feature_subset,
            mesh=self._build_mesh(),
        )
        return forest_to_artifact(
            forest, data.edges, data.n_bins, encodings, self.schema, hyperparams
        )

    def evaluate(self, model: ModelArtifact, train, test) -> float:
        rows = _parse_rows(test)
        if not rows:
            return float("nan")
        rdf_model = artifact_to_model(model, self.schema)
        x, y = rdf_model.rows_to_matrix(rows)
        keep = ~np.isnan(y)
        x, y = x[keep], y[keep]
        if len(y) == 0:
            return float("nan")
        binned = rdf_model.bin_matrix(x)
        if self.schema.is_classification():
            from oryx_tpu.ops.rdf import predict_class_probs

            probs = predict_class_probs(rdf_model.forest, binned)
            acc = float(np.mean(np.argmax(probs, axis=1) == y.astype(np.int64)))
            log.info("accuracy: %.5f", acc)
            return acc
        from oryx_tpu.ops.rdf import predict_regression

        preds = predict_regression(rdf_model.forest, binned)
        rmse = float(np.sqrt(np.mean((preds - y) ** 2)))
        log.info("RMSE: %.5f", rmse)
        return -rmse
