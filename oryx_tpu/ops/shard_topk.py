"""Cross-shard top-k: per-shard fused partials, exactly merged.

Pod-scale serving (ROADMAP item 1) splits the item matrix by row across
shards (ops/transfer.ShardedMatrix, one device per shard when the host
has them): each shard runs the EXISTING fused score+top-k over its own
row slice — the gen-2 Pallas kernel on TPU, XLA elsewhere, quantized or
bf16 per shard — producing per-shard (values, global-index) top-k
partials. The cross-shard merge below is the gen-2 kernel's bitonic
merge tree (ops/pallas_topk._merge_top) one level up: the same
(value desc, index asc) total order that makes the in-kernel merge
bit-identical to jax.lax.top_k makes the cross-shard merge bit-identical
to scoring the unsharded matrix — duplicate-score tie-breaks included —
which is what lets a CPU host_mesh(n) simulation PROVE the sharded path
correct before a pod ever runs it.

The merge runs as a host-side reduce (partials are fetched and merged on
the default device). At k <= 128 a partial is ~1 KB per shard per row —
three orders of magnitude below the per-shard HBM scan it concludes —
so the reduce is not worth a collective until shard counts reach the
hundreds; the merge tree itself is shard-count-agnostic either way.
"""

from __future__ import annotations

import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from oryx_tpu.ops.pallas_topk import _merge_top

# index value carried by merge padding slots: loses every (value desc,
# index asc) comparison against any real candidate at equal value
_PAD_IDX = np.iinfo(np.int32).max

_MERGE_METRICS = None
_MERGE_METRICS_LOCK = threading.Lock()


def _merge_metrics():
    """(merge-seconds histogram,) — process-wide, lazily registered so
    importing this module never touches the registry."""
    global _MERGE_METRICS
    if _MERGE_METRICS is None:
        with _MERGE_METRICS_LOCK:
            if _MERGE_METRICS is None:
                from oryx_tpu.common.metrics import (
                    MICROBATCH_BUCKETS, get_registry,
                )

                _MERGE_METRICS = (
                    get_registry().histogram(
                        "oryx_shard_merge_seconds",
                        "wall-clock of one cross-shard top-k merge (the "
                        "host-side reduce over per-shard partials; the "
                        "per-shard scans it concludes ride "
                        "oryx_device_dispatch_seconds)",
                        buckets=MICROBATCH_BUCKETS,
                    ),
                )
    return _MERGE_METRICS


def _pow2_ceil(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def _pad_tail(a, width: int, value):
    pad = width - a.shape[-1]
    if pad <= 0:
        return a
    return jnp.pad(
        a, [(0, 0)] * (a.ndim - 1) + [(0, pad)], constant_values=value
    )


def _merge_stacked(vals, idx, *, k: int):
    """Merge tree over stacked sorted-descending partials: vals/idx
    [S, B, L] (L pow2) -> exact top-k of the union per row, ordered by
    (value desc, index asc). Pairwise _merge_top halvings — the gen-2
    kernel's block merge applied across shards."""
    s = vals.shape[0]
    while s > 1:
        half = s // 2
        mv, mi = _merge_top(
            vals[:half], idx[:half], vals[half : 2 * half], idx[half : 2 * half]
        )
        if s % 2:
            vals = jnp.concatenate([mv, vals[-1:]], axis=0)
            idx = jnp.concatenate([mi, idx[-1:]], axis=0)
        else:
            vals, idx = mv, mi
        s = vals.shape[0]
    return vals[0, :, :k], idx[0, :, :k]


_merge_stacked_jit = jax.jit(_merge_stacked, static_argnames=("k",))


def merge_topk_partials(partials, k: int):
    """Exact top-k of the union of per-shard top-k partials.

    partials: [(vals [B, k_s], idx [B, k_s])] per shard, each row sorted
    descending with GLOBAL indices (ties already index-ascending — what
    lax.top_k and the fused kernel both emit after index rebasing).
    Returns ([B, k] f32, [B, k] int32) in the same total order the
    single-matrix kernel produces, bit-identical tie-breaks included.
    Padding slots carry (-inf, int32 max) so they lose every comparison
    against real candidates.
    """
    if not partials:
        raise ValueError("merge_topk_partials needs at least one partial")
    width = _pow2_ceil(max(k, max(int(v.shape[-1]) for v, _ in partials)))
    vals = jnp.stack([
        _pad_tail(jnp.asarray(v, dtype=jnp.float32), width, -jnp.inf)
        for v, _ in partials
    ])
    idx = jnp.stack([
        _pad_tail(jnp.asarray(i, dtype=jnp.int32), width, _PAD_IDX)
        for _, i in partials
    ])
    return _merge_stacked_jit(vals, idx, k=k)


def topk_dot_batch_sharded(xs, sm, *, k: int, recall: float = 1.0):
    """Batched top-k over a ShardedMatrix: each shard scores its row
    slice with the normal kernel-selection path (ops.als.topk_dot_batch
    — fused Pallas on TPU, quantized/bf16 per the shard's dtype), with
    the query block placed on the shard's device, then the per-shard
    partials merge exactly with indices rebased to global rows.

    Top-k is associative over row partitions, so the merge is exact;
    with recall < 1 each shard's partial reduce carries the same
    per-shard recall target (the chunked kernel's convention)."""
    from oryx_tpu.ops.als import topk_dot_batch

    total = sm.plan.total
    if k > total:
        # contract parity with the single-dispatch kernel (lax.top_k
        # raises there); padded merge slots would otherwise fabricate
        # (-inf, pad-index) results
        raise ValueError(f"k={k} exceeds total rows {total}")
    partials = []
    for s, shard in enumerate(sm.shards):
        n_s = int(shard.shape[0])
        if n_s == 0:
            continue  # an empty shard contributes no candidates
        dev = next(iter(shard.devices()), None)
        xs_s = xs if dev is None else jax.device_put(xs, dev)
        v, i = topk_dot_batch(xs_s, shard, k=min(k, n_s), recall=recall)
        partials.append((v, i + sm.plan.lo(s)))
    t0 = time.monotonic()
    # host-side reduce: partials come back to the default device and the
    # bitonic merge tree runs once over the stack
    merged = merge_topk_partials(
        [(np.asarray(v), np.asarray(i)) for v, i in partials], k
    )
    _merge_metrics()[0].observe(time.monotonic() - t0)
    return merged
