"""Fleet controller: the closed loop over supervisor + front.

The pieces below it are deliberately dumb: the model gate
(``common/modelgate.py``) holds or adopts generations per replica, the
front (``fleet/front.py``) splits a stable traffic cohort and
drains/joins replicas, the supervisor (``fleet/supervisor.py``) spawns
and stops processes. This module is the policy that composes them into
a staged rollout and self-healing capacity:

Canary rollout (``oryx.fleet.canary.*``)
  1. **Arm**: every hold-mode replica's gate starts unarmed (watermark
     ``None`` — bootstrap safety). The controller pins each watermark to
     the replica's CURRENT generation via ``POST /control/model/approve``,
     so the next published generation parks fleet-wide except on the
     canary replica, whose gate adopts immediately.
  2. **Start**: when the canary's adopted generation pulls ahead of the
     incumbent, the front splits ``traffic-fraction`` of the placement
     keys to it (stable hash cohort — sessions stick to one generation)
     and a ``canary-start`` flight event opens the story.
  3. **Judge**: promotion is gated on the canary's quality-SLO fast
     burn, its serving-latency fast burn, and its live recall vs the
     incumbent fleet's — all only after ``min-samples`` shadow-rescored
     samples landed on the new generation (PR 14's generation-scoped
     windows mean those samples are the new generation's alone).
  4. **Promote**: approvals raise every hold replica's watermark; the
     held generation adopts fleet-wide, the split clears once the fleet
     reports the new generation, ``canary-promote`` closes the story.
  5. **Rollback**: a burn/recall breach, an ejected canary, or the
     fail-closed ``hold-timeout-sec`` instead re-pins the previous
     generation via ``POST /control/model/rollback`` — a pure pointer
     swap out of the artifact relay's pinned cache, zero re-download
     bytes — clears the split, and records ``canary-rollback`` with the
     evidence that forced it. The generation is vetoed: topic replay
     cannot re-adopt it.

Autoscaling (``oryx.fleet.autoscale.*``)
  Scale UP on availability fast-burn at the front or a shed storm
  (retries/sec over ``scale-up-shed-rate``); scale DOWN when mean
  dispatch-batch occupancy across the fleet stays under
  ``scale-down-occupancy`` for ``scale-down-after-sec``. Scale-down is
  graceful: the victim drains (no new requests, in-flight ones finish)
  before its process stops and its ring keys remap — and only its keys
  (``fleet/ring.py`` removes one node's points). Every decision records
  an ``autoscale`` flight event with the evidence that drove it.

The controller runs in the fleet front's process (``cli fleet`` wires
it between front start and the supervisor loop), so the front's SLO
trackers and metric registry are direct reads; replica state arrives
through the prober's /healthz parses on ``front.replicas``.
"""

from __future__ import annotations

import json
import logging
import threading
import time

from oryx_tpu.common import slo
from oryx_tpu.common.config import Config
from oryx_tpu.common.flightrec import get_flightrec
from oryx_tpu.common.metrics import get_registry

log = logging.getLogger(__name__)


def _mean(values: list[float]) -> float | None:
    return sum(values) / len(values) if values else None


class _Rollout:
    """One in-flight canary evaluation (created at canary-start,
    destroyed at promote/rollback)."""

    __slots__ = (
        "generation", "incumbent", "started", "baseline_samples",
        "promoting", "promote_evidence",
    )

    def __init__(self, generation, incumbent, baseline_samples):
        self.generation = generation
        self.incumbent = incumbent
        self.started = time.monotonic()
        self.baseline_samples = baseline_samples
        # promote decided; approvals re-sent each tick until every hold
        # replica's watermark caught up, then the split clears
        self.promoting = False
        self.promote_evidence: dict = {}


class FleetController:
    def __init__(self, config: Config, supervisor, front):
        self.config = config
        self.supervisor = supervisor
        self.front = front
        self.canary_enabled = config.get_bool("oryx.fleet.canary.enabled", False)
        self.canary_rid = config.get_string("oryx.fleet.canary.replica", "r0")
        self.traffic_fraction = config.get_float(
            "oryx.fleet.canary.traffic-fraction", 0.1
        )
        self.min_samples = config.get_int("oryx.fleet.canary.min-samples", 25)
        self.max_quality_burn = config.get_float(
            "oryx.fleet.canary.max-quality-burn", 2.0
        )
        self.max_latency_burn = config.get_float(
            "oryx.fleet.canary.max-latency-burn", 6.0
        )
        self.recall_slack = config.get_float(
            "oryx.fleet.canary.recall-slack", 0.05
        )
        self.hold_timeout = config.get_float(
            "oryx.fleet.canary.hold-timeout-sec", 300.0
        )
        self.autoscale_enabled = config.get_bool(
            "oryx.fleet.autoscale.enabled", False
        )
        self.min_replicas = max(
            1, config.get_int("oryx.fleet.autoscale.min-replicas", 2)
        )
        self.max_replicas = config.get_int(
            "oryx.fleet.autoscale.max-replicas", 4
        )
        self.scale_up_burn = config.get_float(
            "oryx.fleet.autoscale.scale-up-burn", 6.0
        )
        self.scale_up_shed_rate = config.get_float(
            "oryx.fleet.autoscale.scale-up-shed-rate", 5.0
        )
        self.scale_down_occupancy = config.get_float(
            "oryx.fleet.autoscale.scale-down-occupancy", 0.15
        )
        self.scale_down_after = config.get_float(
            "oryx.fleet.autoscale.scale-down-after-sec", 120.0
        )
        self.cooldown = config.get_float(
            "oryx.fleet.autoscale.cooldown-sec", 60.0
        )
        self.drain_timeout = config.get_float(
            "oryx.fleet.autoscale.drain-timeout-sec", 30.0
        )
        self.tick_interval = config.get_float("oryx.fleet.control.tick-sec", 1.0)
        self._rollout: _Rollout | None = None
        # generations this controller already rolled back: a canary gate
        # restart (fresh veto set) must not re-trigger the same rollout
        self._vetoed: set[int] = set()
        self._gave_up_seen: set[str] = set()
        # autoscaler state
        self._cooldown_until = 0.0
        self._low_occ_since: float | None = None
        self._draining: tuple[str, float] | None = None  # (rid, deadline)
        self._last_shed: tuple[float, float] | None = None  # (t, total)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        reg = get_registry()
        self._g_replicas = reg.gauge(
            "oryx_fleet_autoscale_replicas",
            "Live replicas the controller counts toward fleet capacity "
            "(draining and gave-up replicas excluded) — the autoscaler's "
            "current size, bounded by oryx.fleet.autoscale.min-replicas/"
            "max-replicas",
        )
        self._m_autoscale = reg.counter(
            "oryx_fleet_autoscale_events_total",
            "Autoscaling decisions the fleet controller executed, by "
            "direction (up = replica spawned and joined to routing, "
            "down = replica drained, stopped, and removed from the ring)",
            labeled=True,
        )
        self._m_canary = reg.counter(
            "oryx_fleet_canary_decisions_total",
            "Canary rollout decisions the fleet controller took, by "
            "outcome (start, promote, rollback)",
            labeled=True,
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="oryx-fleet-controller", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _loop(self) -> None:  # oryxlint: offloop (controller thread)
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - one bad tick never kills the loop
                log.exception("fleet controller tick failed")
            self._stop.wait(self.tick_interval)

    # -- one control pass (public so chaos/tests can drive it directly) ------

    def tick(self) -> None:
        self._mirror_gave_up()
        if self.canary_enabled:
            self._canary_tick()
        if self.autoscale_enabled:
            self._autoscale_tick()
        self._g_replicas.set(float(len(self._live_replicas())))

    def _live_replicas(self):
        return [
            r
            for r in self.front.replicas
            if r.state not in ("gave_up", "draining")
        ]

    def _mirror_gave_up(self) -> None:
        """Reflect the supervisor's crash-loop give-ups in the front's
        routing table (satellite: /fleet/status shows state=gave_up
        instead of a probe-flapping hole)."""
        for rid in list(self.supervisor.gave_up):
            if rid not in self._gave_up_seen:
                self._gave_up_seen.add(rid)
                self.front.mark_gave_up(rid)

    # -- canary rollout -------------------------------------------------------

    def _canary_tick(self) -> None:
        canary = next(
            (r for r in self.front.replicas if r.id == self.canary_rid), None
        )
        if canary is None:
            return
        holds = [
            r
            for r in self.front.replicas
            if r.id != self.canary_rid
            and isinstance(r.model_gate, dict)
            and r.model_gate.get("mode") == "hold"
        ]
        self._arm_holds(holds)
        if self._rollout is None:
            self._maybe_start(canary, holds)
            return
        if self._rollout.promoting:
            self._finish_promotion(canary, holds)
            return
        self._judge(canary, holds)

    def _arm_holds(self, holds) -> None:
        """Pin every UNARMED hold gate's watermark to the generation it
        already serves: from then on anything newer parks until this
        controller promotes it."""
        for r in holds:
            if r.model_gate.get("watermark") is None and r.generation:
                res = self._post(
                    r, "/control/model/approve", {"generation": r.generation}
                )
                if res is not None:
                    log.info(
                        "fleet controller: armed %s at generation %s",
                        r.id, r.generation,
                    )

    def _canary_generation(self, canary) -> int | None:
        mg = canary.model_gate if isinstance(canary.model_gate, dict) else {}
        gens = mg.get("generations") or []
        g = gens[-1] if gens else canary.generation
        return int(g) if isinstance(g, (int, float)) else None

    def _maybe_start(self, canary, holds) -> None:
        gen = self._canary_generation(canary)
        incumbents = [r.generation for r in holds if r.generation]
        incumbent = max(incumbents) if incumbents else None
        if (
            gen is None
            or incumbent is None
            or gen <= incumbent
            or gen in self._vetoed
        ):
            return
        baseline = 0
        if isinstance(canary.quality, dict) and isinstance(
            canary.quality.get("samples"), int
        ):
            baseline = canary.quality["samples"]
        self.front.set_canary(self.canary_rid, self.traffic_fraction)
        self._rollout = _Rollout(gen, incumbent, baseline)
        self._m_canary.inc(outcome="start")
        get_flightrec().record(
            kind="canary-start",
            replica=self.canary_rid,
            generation=gen,
            incumbent=incumbent,
            fraction=self.traffic_fraction,
        )
        log.info(
            "fleet controller: canary rollout of generation %s started on "
            "%s (incumbent %s, %.0f%% of traffic)",
            gen, self.canary_rid, incumbent, self.traffic_fraction * 100,
        )

    def _judge(self, canary, holds) -> None:
        ro = self._rollout
        sb = canary.slo_burn if isinstance(canary.slo_burn, dict) else {}
        q_burn = (sb.get("quality") or {}).get("fast")
        l_burn = (sb.get("serving-latency") or {}).get("fast")
        samples = None
        recall = None
        if isinstance(canary.quality, dict):
            s = canary.quality.get("samples")
            if isinstance(s, int):
                samples = max(0, s - ro.baseline_samples)
            recall = canary.quality.get("live_recall_at_10")
        incumbent_recall = _mean(
            [
                r.quality["live_recall_at_10"]
                for r in holds
                if isinstance(r.quality, dict)
                and isinstance(r.quality.get("live_recall_at_10"), (int, float))
            ]
        )
        evidence = {
            "generation": ro.generation,
            "incumbent": ro.incumbent,
            "samples": samples,
            "quality_burn": q_burn,
            "latency_burn": l_burn,
            "canary_recall": recall,
            "incumbent_recall": incumbent_recall,
        }
        if not canary.routable:
            self._rollback(canary, "canary-ejected", evidence)
            return
        if samples is not None and samples >= self.min_samples:
            breaches = []
            if isinstance(q_burn, (int, float)) and q_burn > self.max_quality_burn:
                breaches.append(f"quality-burn {q_burn} > {self.max_quality_burn}")
            if isinstance(l_burn, (int, float)) and l_burn > self.max_latency_burn:
                breaches.append(f"latency-burn {l_burn} > {self.max_latency_burn}")
            if (
                isinstance(recall, (int, float))
                and incumbent_recall is not None
                and recall < incumbent_recall - self.recall_slack
            ):
                breaches.append(
                    f"recall {recall} < incumbent {round(incumbent_recall, 4)}"
                    f" - {self.recall_slack}"
                )
            if breaches:
                self._rollback(canary, "; ".join(breaches), evidence)
                return
            # every gate leg green over enough samples: promote
            ro.promoting = True
            ro.promote_evidence = evidence
            log.info(
                "fleet controller: promoting generation %s (%s)",
                ro.generation, evidence,
            )
            self._finish_promotion(canary, holds)
            return
        if time.monotonic() - ro.started > self.hold_timeout:
            # fail closed: a canary that cannot accumulate evidence
            # inside the window never promotes
            self._rollback(canary, "hold-timeout", evidence)
            return
        # insufficient evidence yet: say so (episode-limited) so the
        # flight ring shows the gate WAITING, not silent
        get_flightrec().record(
            kind="canary-hold",
            episode_s=30.0,
            replica=self.canary_rid,
            generation=ro.generation,
            samples=samples,
            min_samples=self.min_samples,
        )

    def _finish_promotion(self, canary, holds) -> None:
        """Re-send approvals until every hold replica's watermark covers
        the promoted generation, then clear the split and close the
        story. Idempotent per tick: an unreachable replica just gets the
        approval again next pass."""
        ro = self._rollout
        behind = []
        for r in holds:
            wm = r.model_gate.get("watermark")
            if not isinstance(wm, (int, float)) or wm < ro.generation:
                behind.append(r)
        for r in behind:
            self._post(
                r, "/control/model/approve", {"generation": ro.generation}
            )
        # the prober refreshes model_gate between ticks; once nothing is
        # behind, the fleet serves the promoted generation
        if behind:
            return
        self.front.clear_canary()
        self._m_canary.inc(outcome="promote")
        get_flightrec().record(
            kind="canary-promote",
            replica=self.canary_rid,
            **{k: v for k, v in ro.promote_evidence.items() if v is not None},
        )
        log.info(
            "fleet controller: generation %s promoted fleet-wide",
            ro.generation,
        )
        self._rollout = None

    def _rollback(self, canary, reason: str, evidence: dict) -> None:
        ro = self._rollout
        res = self._post(canary, "/control/model/rollback", {"reason": reason})
        if res is None:
            # the pointer swap did not happen (gate has no prior adoption
            # in history, or the replica is unreachable): the canary is
            # still serving the vetoed generation, so clearing the split
            # would hash real users back onto it. A zero-fraction split
            # quarantines it — no cohort routes there, everyone else
            # avoids it — until the next rollout's verdict replaces the
            # split or a promote clears it.
            self.front.set_canary(self.canary_rid, 0.0)
        else:
            self.front.clear_canary()
        self._vetoed.add(ro.generation)
        self._m_canary.inc(outcome="rollback")
        get_flightrec().record(
            kind="canary-rollback",
            replica=self.canary_rid,
            reason=reason,
            rolled_back_to=(res or {}).get("rolled_back_to"),
            quarantined=res is None,
            **{k: v for k, v in evidence.items() if v is not None},
        )
        if res is None:
            log.warning(
                "fleet controller: rollback of generation %s on %s FAILED "
                "(%s); replica quarantined at zero traffic",
                ro.generation, self.canary_rid, reason,
            )
        else:
            log.warning(
                "fleet controller: rolled back generation %s on %s: %s",
                ro.generation, self.canary_rid, reason,
            )
        self._rollout = None

    # -- autoscaling -----------------------------------------------------------

    def _autoscale_tick(self) -> None:
        now = time.monotonic()
        if self._draining is not None:
            self._finish_drain(now)
            return
        if now < self._cooldown_until:
            return
        live = self._live_replicas()
        up_reason = self._up_signal(now)
        if up_reason is not None and len(live) < self.max_replicas:
            self._scale_up(up_reason)
            return
        self._maybe_scale_down(now, live)

    def _up_signal(self, now: float) -> str | None:
        """Scale-up wants FAST signals: the front's own availability
        burn (requests the client already lost) and the shed rate (work
        the fleet is actively refusing)."""
        burn = slo.current_burn("front-availability")
        if burn is not None and burn > self.scale_up_burn:
            return f"front-availability fast burn {round(burn, 2)} > {self.scale_up_burn}"
        shed = 0.0
        try:
            c = get_registry().counter("oryx_fleet_front_retries_total")
            shed = sum(
                v for k, v in c.series().items() if dict(k).get("reason") == "shed"
            )
        except Exception:  # noqa: BLE001 - registry families vary in tests
            return None
        last = self._last_shed
        self._last_shed = (now, shed)
        if last is None or now <= last[0]:
            return None
        rate = (shed - last[1]) / (now - last[0])
        if rate > self.scale_up_shed_rate:
            return f"shed rate {round(rate, 1)}/s > {self.scale_up_shed_rate}/s"
        return None

    def _scale_up(self, reason: str) -> None:
        rid, port = self.supervisor.scale_up()
        self.front.add_replica(rid, "127.0.0.1", port)
        self._cooldown_until = time.monotonic() + self.cooldown
        self._m_autoscale.inc(direction="up")
        get_flightrec().record(
            kind="autoscale", direction="up", replica=rid, port=port,
            reason=reason, replicas=len(self._live_replicas()),
        )
        log.warning("fleet controller: scaled up (%s): spawned %s", reason, rid)

    def _maybe_scale_down(self, now: float, live) -> None:
        occs = [
            float(r.occupancy["mean"])
            for r in live
            if r.routable
            and isinstance(r.occupancy, dict)
            and isinstance(r.occupancy.get("mean"), (int, float))
        ]
        occ = _mean(occs)
        if occ is None or occ >= self.scale_down_occupancy:
            self._low_occ_since = None
            return
        if self._low_occ_since is None:
            self._low_occ_since = now
            return
        if now - self._low_occ_since < self.scale_down_after:
            return
        if len(live) <= self.min_replicas:
            return
        victim = self._pick_victim(live)
        if victim is None:
            return
        self.front.begin_drain(victim.id)
        self._draining = (victim.id, now + self.drain_timeout)
        self._low_occ_since = None
        get_flightrec().record(
            kind="autoscale", direction="down", replica=victim.id,
            phase="drain", occupancy=round(occ, 4),
            threshold=self.scale_down_occupancy,
            replicas=len(live),
        )
        log.warning(
            "fleet controller: scaling down %s (mean occupancy %.3f < %.3f "
            "for %.0fs); draining",
            victim.id, occ, self.scale_down_occupancy, self.scale_down_after,
        )

    def _pick_victim(self, live):
        """Highest-index routable replica that is not the canary: the
        supervisor refills the highest slots first, and the canary
        replica's gate history is the fleet's rollback path."""
        for r in reversed(live):
            if r.routable and r.id != self.canary_rid:
                return r
        return None

    def _finish_drain(self, now: float) -> None:
        rid, deadline = self._draining
        inflight = self.front.inflight(rid)
        if inflight > 0 and now < deadline:
            return  # in-flight requests get their answers first
        self.supervisor.stop_replica(rid)
        self.front.remove_replica(rid)
        self._draining = None
        self._cooldown_until = now + self.cooldown
        self._m_autoscale.inc(direction="down")
        get_flightrec().record(
            kind="autoscale", direction="down", replica=rid, phase="stopped",
            forced=inflight > 0, replicas=len(self._live_replicas()),
        )
        log.warning(
            "fleet controller: scale-down of %s complete (%s)",
            rid, "drain deadline forced" if inflight > 0 else "drained clean",
        )

    # -- replica control endpoint ---------------------------------------------

    # blocking http.client is legal here: the controller is a dedicated
    # thread, never one of the front's event loops
    def _post(self, r, path: str, body: dict) -> dict | None:  # oryxlint: offloop (controller thread)
        import http.client

        try:
            conn = http.client.HTTPConnection(r.host, r.port, timeout=5)
            try:
                conn.request(
                    "POST", path, json.dumps(body),
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                data = resp.read().decode("utf-8", "replace")
                if resp.status != 200:
                    log.warning(
                        "fleet controller: POST %s to %s -> %d %s",
                        path, r.id, resp.status, data[:200],
                    )
                    return None
                return json.loads(data)
            finally:
                conn.close()
        except Exception:  # noqa: BLE001 - replica may be mid-restart
            log.warning(
                "fleet controller: POST %s to %s failed", path, r.id,
                exc_info=True,
            )
            return None
