"""ALS REST endpoint surface — full parity with the reference's 19 ALS
resources (SURVEY.md §2.11, app/oryx-app-serving .../als/*.java), re-based
on the single-matmul serving model:

  /recommend/{user}                /recommendToMany/{users...}
  /recommendToAnonymous/{prefs..}  /recommendWithContext/{user}/{prefs..}
  /similarity/{items...}           /similarityToItem/{to}/{items...}
  /estimate/{user}/{items...}      /estimateForAnonymous/{to}/{prefs..}
  /because/{user}/{item}           /mostSurprising/{user}
  /knownItems/{user}               /mostActiveUsers
  /mostPopularItems                /popularRepresentativeItems
  /user/allIDs                     /item/allIDs
  /pref/{user}/{item} POST/DELETE  (+ /ready and /ingest in common.py)

Query params: howMany (clamped), offset, considerKnownItems, rescorerParams.
"""

from __future__ import annotations

import numpy as np

from oryx_tpu.common.text import join_csv
from oryx_tpu.serving.app import (
    OryxServingException, Request, ServingApp, deferred_map,
)


def _model(a: ServingApp):
    return a.get_serving_model()


def _how_many(req: Request, default: int = 10) -> tuple[int, int]:
    try:
        how_many = int(req.q1("howMany", str(default)))
        offset = int(req.q1("offset", "0"))
    except ValueError as e:
        raise OryxServingException(400, f"bad howMany/offset: {e}") from None
    # separate checks so the 400 names the parameter that's actually
    # wrong — a negative offset used to be blamed on howMany
    if how_many <= 0:
        raise OryxServingException(400, "howMany must be positive")
    if offset < 0:
        raise OryxServingException(400, "offset must not be negative")
    return how_many, offset

def _page(pairs, how_many, offset):
    return [[i, float(s)] for i, s in pairs[offset : offset + how_many]]


def _parse_prefs(rest: str) -> list[tuple[str, float]]:
    """Path-tail item prefs: itemID(=strength)? segments."""
    out = []
    for seg in rest.split("/"):
        if not seg:
            continue
        if "=" in seg:
            ident, s = seg.split("=", 1)
            try:
                out.append((ident, float(s)))
            except ValueError:
                raise OryxServingException(400, f"bad strength in {seg!r}") from None
        else:
            out.append((seg, 1.0))
    if not out:
        raise OryxServingException(400, "no items given")
    return out


def _rescorer(a: ServingApp, method: str, req: Request, *args):
    provider = getattr(a.model_manager, "rescorer_provider", lambda: None)()
    if provider is None:
        return None
    params = req.q_list("rescorerParams")
    return getattr(provider, method)(*args, *params)


def _user_vector_or_404(model, user: str) -> np.ndarray:
    xu = model.get_user_vector(user)
    if xu is None:
        raise OryxServingException(404, f"unknown user: {user}")
    return xu


def register(app: ServingApp) -> None:
    # -- recommend family --------------------------------------------------

    # NOT nonblocking: the plan path can rebuild the device view (full Y
    # copy + staged upload under _sync_lock after a model update) or run
    # host LSH scoring — both far too heavy for inline event-loop
    # dispatch. The worker-pool hop stays.
    @app.route("GET", "/recommend/{userID}")
    def recommend(a: ServingApp, req: Request):
        model = _model(a)
        user = req.params["userID"]
        xu = _user_vector_or_404(model, user)
        how_many, offset = _how_many(req)
        consider_known = req.q1("considerKnownItems", "false") == "true"
        exclude = set() if consider_known else model.state.get_known_items(user)
        rescorer = _rescorer(a, "get_recommend_rescorer", req, [user], model)
        return deferred_map(
            model.top_n_async(xu, how_many + offset, exclude, rescorer),
            lambda pairs: _page(pairs, how_many, offset),
        )

    @app.route("GET", "/recommendToMany/{userIDs:rest}")
    def recommend_to_many(a: ServingApp, req: Request):
        model = _model(a)
        users = [u for u in req.params["userIDs"].split("/") if u]
        vecs, known = [], set()
        for u in users:
            xu = model.get_user_vector(u)
            if xu is not None:
                vecs.append(xu)
                known |= model.state.get_known_items(u)
        if not vecs:
            raise OryxServingException(404, "no known users")
        how_many, offset = _how_many(req)
        consider_known = req.q1("considerKnownItems", "false") == "true"
        rescorer = _rescorer(a, "get_recommend_rescorer", req, users, model)
        mean_vec = np.mean(vecs, axis=0)
        return deferred_map(
            model.top_n_async(mean_vec, how_many + offset,
                              set() if consider_known else known, rescorer),
            lambda pairs: _page(pairs, how_many, offset),
        )

    @app.route("GET", "/recommendToAnonymous/{itemPrefs:rest}")
    def recommend_to_anonymous(a: ServingApp, req: Request):
        model = _model(a)
        prefs = _parse_prefs(req.params["itemPrefs"])
        xu = model.fold_in_user_vector(prefs)
        if xu is None:
            raise OryxServingException(404, "no known items")
        how_many, offset = _how_many(req)
        rescorer = _rescorer(a, "get_recommend_to_anonymous_rescorer", req,
                             [i for i, _ in prefs], model)
        return deferred_map(
            model.top_n_async(xu, how_many + offset, {i for i, _ in prefs}, rescorer),
            lambda pairs: _page(pairs, how_many, offset),
        )

    @app.route("GET", "/recommendWithContext/{userID}/{itemPrefs:rest}")
    def recommend_with_context(a: ServingApp, req: Request):
        """User's vector nudged by session-context prefs before top-N."""
        model = _model(a)
        user = req.params["userID"]
        xu = _user_vector_or_404(model, user).copy()
        prefs = _parse_prefs(req.params["itemPrefs"])
        ctx = model.fold_in_user_vector(prefs)
        if ctx is not None:
            xu = xu + ctx
        how_many, offset = _how_many(req)
        exclude = model.state.get_known_items(user) | {i for i, _ in prefs}
        rescorer = _rescorer(a, "get_recommend_rescorer", req, [user], model)
        return deferred_map(
            model.top_n_async(xu, how_many + offset, exclude, rescorer),
            lambda pairs: _page(pairs, how_many, offset),
        )

    # -- similarity family -------------------------------------------------

    @app.route("GET", "/similarity/{itemIDs:rest}")
    def similarity(a: ServingApp, req: Request):
        model = _model(a)
        items = [i for i in req.params["itemIDs"].split("/") if i]
        mean_vec = model.cosine_to_items(items)
        if mean_vec is None:
            raise OryxServingException(404, "no known items")
        how_many, offset = _how_many(req)
        rescorer = _rescorer(a, "get_most_similar_items_rescorer", req, model)
        return deferred_map(
            model.top_n_async(
                mean_vec, how_many + offset, set(items), rescorer, cosine=True
            ),
            lambda pairs: _page(pairs, how_many, offset),
        )

    @app.route("GET", "/similarityToItem/{toItemID}/{itemIDs:rest}")
    def similarity_to_item(a: ServingApp, req: Request):
        model = _model(a)
        to_vec = model.get_item_vector(req.params["toItemID"])
        if to_vec is None:
            raise OryxServingException(404, "unknown item")
        out = []
        for item in req.params["itemIDs"].split("/"):
            if not item:
                continue
            yi = model.get_item_vector(item)
            if yi is None:
                raise OryxServingException(404, f"unknown item: {item}")
            denom = float(np.linalg.norm(to_vec) * np.linalg.norm(yi))
            out.append([item, float(to_vec @ yi) / denom if denom else 0.0])
        return out

    # -- estimate family ---------------------------------------------------

    @app.route("GET", "/estimate/{userID}/{itemIDs:rest}")
    def estimate(a: ServingApp, req: Request):
        model = _model(a)
        xu = _user_vector_or_404(model, req.params["userID"])
        out = []
        for item in req.params["itemIDs"].split("/"):
            if not item:
                continue
            yi = model.get_item_vector(item)
            out.append([item, float(xu @ yi) if yi is not None else 0.0])
        return out

    @app.route("GET", "/estimateForAnonymous/{toItemID}/{itemPrefs:rest}")
    def estimate_for_anonymous(a: ServingApp, req: Request):
        model = _model(a)
        to_vec = model.get_item_vector(req.params["toItemID"])
        if to_vec is None:
            raise OryxServingException(404, "unknown item")
        xu = model.fold_in_user_vector(_parse_prefs(req.params["itemPrefs"]))
        if xu is None:
            raise OryxServingException(404, "no known items")
        return [[req.params["toItemID"], float(xu @ to_vec)]]

    # -- explain family ----------------------------------------------------

    @app.route("GET", "/because/{userID}/{itemID}")
    def because(a: ServingApp, req: Request):
        """Known items most similar to the recommended item — 'because you
        interacted with these' (Because.java cosine ranking)."""
        model = _model(a)
        yi = model.get_item_vector(req.params["itemID"])
        if yi is None:
            raise OryxServingException(404, "unknown item")
        known = model.state.get_known_items(req.params["userID"])
        if not known:
            raise OryxServingException(404, "no known items for user")
        how_many, offset = _how_many(req)
        ni = float(np.linalg.norm(yi))
        scored = []
        for item in known:
            yk = model.get_item_vector(item)
            if yk is None:
                continue
            denom = ni * float(np.linalg.norm(yk))
            scored.append((item, float(yi @ yk) / denom if denom else 0.0))
        scored.sort(key=lambda t: -t[1])
        return _page(scored, how_many, offset)

    @app.route("GET", "/mostSurprising/{userID}")
    def most_surprising(a: ServingApp, req: Request):
        """Known items with the LOWEST predicted strength — interactions the
        model least expects (MostSurprising.java)."""
        model = _model(a)
        user = req.params["userID"]
        xu = _user_vector_or_404(model, user)
        known = model.state.get_known_items(user)
        if not known:
            raise OryxServingException(404, "no known items for user")
        how_many, offset = _how_many(req)
        scored = []
        for item in known:
            yk = model.get_item_vector(item)
            if yk is not None:
                scored.append((item, float(xu @ yk)))
        scored.sort(key=lambda t: t[1])
        return _page(scored, how_many, offset)

    # -- introspection -----------------------------------------------------

    @app.route("GET", "/knownItems/{userID}")
    def known_items(a: ServingApp, req: Request):
        model = _model(a)
        known = model.state.get_known_items(req.params["userID"])
        if not known:
            raise OryxServingException(404, "no known items for user")
        return sorted(known)

    @app.route("GET", "/mostActiveUsers")
    def most_active_users(a: ServingApp, req: Request):
        model = _model(a)
        how_many, offset = _how_many(req)
        return _page(model.most_active_users(how_many + offset), how_many, offset)

    @app.route("GET", "/mostPopularItems")
    def most_popular_items(a: ServingApp, req: Request):
        model = _model(a)
        how_many, offset = _how_many(req)
        rescorer = _rescorer(a, "get_most_popular_items_rescorer", req, model)
        return _page(model.most_popular_items(how_many + offset, rescorer), how_many, offset)

    @app.route("GET", "/popularRepresentativeItems")
    def popular_representative_items(a: ServingApp, req: Request):
        """One item per LSH partition when LSH is on (reference
        PopularRepresentativeItems), else an even stride over the store."""
        model = _model(a)
        how_many, _ = _how_many(req)
        return model.representative_items(how_many)

    @app.route("GET", "/user/allIDs")
    def user_all_ids(a: ServingApp, req: Request):
        return _model(a).state.x.ids()

    @app.route("GET", "/item/allIDs")
    def item_all_ids(a: ServingApp, req: Request):
        return _model(a).state.y.ids()

    # -- writes ------------------------------------------------------------

    @app.route("POST", "/pref/{userID}/{itemID}")
    def set_pref(a: ServingApp, req: Request):
        model = _model(a)
        user, item = req.params["userID"], req.params["itemID"]
        body = req.body_text().strip()
        try:
            strength = float(body) if body else 1.0
        except ValueError:
            raise OryxServingException(400, f"bad strength: {body!r}") from None
        a.send_input(join_csv([user, item, strength]))
        # read-your-write: apply locally right away (Preference.java:44-66)
        model.state.add_known_items(user, [item])
        return 200, None

    @app.route("DELETE", "/pref/{userID}/{itemID}")
    def delete_pref(a: ServingApp, req: Request):
        model = _model(a)
        user, item = req.params["userID"], req.params["itemID"]
        # empty strength = delete marker (NaN downstream)
        a.send_input(join_csv([user, item, ""]))
        model.state.remove_known_item(user, item)
        return 200, None

    def _als_console(a: ServingApp) -> list[tuple[str, object]]:
        model = _model(a)  # 503s before the model is queryable
        st = model.state
        known = st.known_items_snapshot()
        mb = (st.x.nbytes() + st.y.nbytes()) / 1e6
        # MEASURED live recall beside the configured sample rate: the
        # knob says what was asked for, the shadow-rescore window says
        # what the traffic actually got (n/a before the first sample)
        from oryx_tpu.common.qualitystats import get_qualitystats

        live = get_qualitystats().live_recall()
        return [
            ("users (X rows)", len(st.x)),
            ("items (Y rows)", len(st.y)),
            ("features", st.features),
            ("feedback", "implicit" if st.implicit else "explicit"),
            ("users with known items", len(known)),
            ("known-item pairs", sum(len(s) for s in known.values())),
            ("LSH sample rate", model.sample_rate),
            ("live recall@10 (measured)", f"{live:.4f}" if live == live else "n/a"),
            ("host factor arenas", f"{mb:.1f} MB"),
        ]

    app.console_sections.append(("ALS model", _als_console))

    # memory parity metric: the reference's performance page tracks heap MB
    # per (users+items) x features; this is the equivalent host-side figure
    import weakref

    from oryx_tpu.common.metrics import GaugeSeriesGone, get_registry

    ref = weakref.ref(app)

    def _model_bytes() -> float:
        a = ref()
        if a is None:
            raise GaugeSeriesGone("app gone")
        model = a.model_manager.get_model()
        if model is None:
            return 0.0
        return float(model.state.x.nbytes() + model.state.y.nbytes())

    get_registry().gauge(
        "oryx_als_model_bytes", "Host factor-arena bytes (X + Y)"
    ).set_function(_model_bytes, manager=type(app.model_manager).__name__)
